"""Pure JAX/XLA classification path.

Implements the XDP hot path (/root/reference/bpf/ingress_node_firewall_kernel.c:
189-457) as batched tensor ops with bit-identical verdict semantics:

- LPM lookup over the (ifindex:32 || srcIP:128) key space, packet-side
  prefix caps (64 for v4, 160 for v6) included;
- the ordered 100-entry rule scan with half-open port ranges, end==0
  single-port encoding, family-gated ICMP matching, protocol==0 catch-all
  and ruleId==0 slot skipping;
- result packing (action | ruleId<<8), final XDP verdict mapping, and
  per-ruleId statistics (stats recorded only for ALLOW/DENY with
  ruleId < MAX_TARGETS, mirroring the per-CPU stats map).

Two LPM strategies, selected by table size:
- dense: compare the packet key against every entry (vector-friendly,
  reference-capacity MAX_TARGETS=1024 scale);
- trie:  the poptrie walk (build_poptrie / trie_walk) — a DIR-16 root
  gather plus one compressed-node-row gather per 8-bit level with
  popcount-rank child indexing, statically unrolled; scales to 100K-1M
  CIDRs at ~140MB device memory per million entries.
"""
from __future__ import annotations

import functools
import hashlib
import os
import threading
import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler import CompiledTables, trie_level_strides
from ..contracts import must_precede
from ..constants import (
    ALLOW,
    DENY,
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    KIND_IPV4,
    KIND_IPV6,
    KIND_MALFORMED,
    MAX_TARGETS,
    XDP_DROP,
    XDP_PASS,
)
from ..packets import PacketBatch

# Device-side stats tensor layout: (MAX_TARGETS, 6) int32 columns
# [allow_pkts, allow_bytes_hi, allow_bytes_lo, deny_pkts, deny_bytes_hi,
# deny_bytes_lo] where bytes_hi/lo are sums of (len>>8) and (len&0xFF);
# the host accumulator recombines into int64 packets/bytes.
STATS_COLS = 6


class DeviceTables(NamedTuple):
    """Compiled tables resident on device.

    ``trie_levels`` holds the device LPM walk structure (the poptrie
    transform of the compiler's variable-stride slot trie, see
    build_poptrie): element 0 is the DIR-16 root level as a direct-
    indexed (n_0*65536, 2) int32 slot array (tiny, direct index beats
    any compression); elements 1.. are (n_l, 18) uint32 poptrie node
    rows [child_base, target_base, child_bitmap x8, target_bitmap x8].
    ``trie_targets`` is the per-level target-compact arrays concatenated
    (leading 0 sentinel).  The tuple length is part of the pytree
    structure, so jit specializes per level count — the static level
    bound the walk unrolls over."""

    key_words: jax.Array    # (T, 5) uint32
    mask_words: jax.Array   # (T, 5) uint32
    mask_len: jax.Array     # (T,) int32
    #: (T, R*5) uint16 FLATTENED packed rule rows [rid|act<<8,
    #: proto|icmpType<<8, icmpCode, portStart, portEnd] per rule when
    #: every field fits (syncer tables always) — (T, R*7) int32 otherwise
    #: (adversarial direct content with wide values).  Flattened 2D on
    #: purpose: XLA's row gather from a 2D (T, W) layout measures ~2.4x
    #: faster than the same bytes as (T, R, C) 3D (tools/profile_trie.py
    #: variants B vs G on v5e); classify reshapes to (B, R, C) after the
    #: gather, which fuses into the scan.  The mesh rules-sharded path
    #: keeps its own 3D layout (parallel/mesh.py).
    rules: jax.Array
    trie_levels: Tuple[jax.Array, ...]
    trie_targets: jax.Array  # (1 + total present targets,) int32
    #: Joined target rows (build_joined): row p = [tidx+1 lo, tidx+1 hi,
    #: mask_len, packed rules R*5] uint16 (or [tidx+1, mask_len, rules
    #: R*7] int32 for wide tables), indexed by the SAME positions the
    #: walk's win tracking produces — so the trie path's final gather
    #: returns the rules in ONE fat row (row width is free up to ~512B,
    #: tools/profile_gather.py) instead of a separate trie_targets
    #: resolve + rules gather (two diverse ~8-11ns gathers -> one).
    #: Shape (1, 1) uint16 when inactive (duplication-gated; dense path;
    #: mesh shards) — the static shape selects the walk at trace time.
    joined: jax.Array
    root_lut: jax.Array     # (max_if+1,) int32
    num_entries: jax.Array  # () int32


class DeviceBatch(NamedTuple):
    kind: jax.Array       # (B,) int32
    l4_ok: jax.Array      # (B,) int32
    ifindex: jax.Array    # (B,) int32
    ip_words: jax.Array   # (B, 4) uint32
    proto: jax.Array      # (B,) int32
    dst_port: jax.Array   # (B,) int32
    icmp_type: jax.Array  # (B,) int32
    icmp_code: jax.Array  # (B,) int32
    pkt_len: jax.Array    # (B,) int32


class DeviceTableInvariantError(AssertionError):
    """A device-table mutation violated the bucket/placeholder contract
    (see assert_patched_tables) — raised at the mutation site so a bad
    patch never installs, instead of surfacing later as a parity mystery
    (the PR-4 joined-placeholder bucket-padding bug was exactly this
    class: caught by accident, downstream, via the mesh parity suite)."""


#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_JOINED_PAD_BUG env var), patch_device_tables re-introduces
#: the PR-4 bug — bucket-padding the inactive (1, 1) joined placeholder
#: to (8, 1) on structural patches, which flips classify into a
#: zero-width joined walk.  The state-checker acceptance gate
#: (tools/infw_lint.py state --inject-defect) proves the model checker
#: catches this with a shrunk reproducer; never set it in production.
_INJECT_JOINED_PAD_BUG = False


def _inject_joined_pad_bug() -> bool:
    if _INJECT_JOINED_PAD_BUG:
        return True
    env = os.environ.get("INFW_INJECT_JOINED_PAD_BUG", "")
    return env not in ("", "0", "false", "no")


def _row_bucket(n: int) -> int:
    """Bucketed device row count: small tables round to the next power of
    two, large ones to 4096-row chunks — so a few appended entries keep
    every device array shape (and thus the jit cache AND the incremental
    patch path) stable."""
    if n <= 0:
        return 8
    if n <= 4096:
        return max(8, 1 << (n - 1).bit_length())
    return -(-n // 4096) * 4096


def _pad_rows(a: np.ndarray, n_rows: int, fill=0) -> np.ndarray:
    if a.shape[0] >= n_rows:
        return a
    if fill == 0:
        # np.zeros is calloc — lazily mapped zero pages, no write pass
        # (np.full memsets the whole multi-GB buffer; measured 20+s across
        # a large table's padded layouts)
        out = np.zeros((n_rows,) + a.shape[1:], a.dtype)
    else:
        out = np.empty((n_rows,) + a.shape[1:], a.dtype)
        out[a.shape[0] :] = fill
    out[: a.shape[0]] = a
    return out


def build_poptrie(tables: CompiledTables):
    """Host transform: the compiler's slot-indexed variable-stride trie
    (per level (n_l*slots, 2) int32 — ~1% occupied at scale, 3.4GB at 1M
    entries) -> a poptrie-style compressed representation (Asai &
    Ohara's poptrie, adapted: bitmap + popcount-rank node rows with
    IMPLICIT child numbering) that the device walk gathers from:

    - level 0 (DIR-16 root) stays a direct-indexed slot array — it is
      small (n_0*65536 rows only for live ifindexes) and direct indexing
      beats any compression; its child column is remapped to renumbered
      level-1 ids PLUS ONE (0 = no child, since renumbered ids are
      0-based).
    - level l>=1 nodes renumber to the order their parent slots appear
      (row-major (parent, slot) scan), so a node's children occupy the
      contiguous range [child_base, child_base + popcount(bitmap)) at
      the next level and the walk needs NO child-pointer gather: the
      child id is child_base + rank(nib).
    - per-level target values compact the same way; the walk tracks only
      a winning global index into ``targets`` (leading 0 sentinel, so
      index 0 reads 0 == no target) and gathers ONCE after the walk.

    Returns (levels, targets): levels[0] (n_0*65536, 2) int32,
    levels[1:] (n_l, 18) uint32 rows
    [child_base, target_base, child_bm x8, target_bm x8]; targets int32.

    Memoized on the CompiledTables instance — the transform scans the
    full slot arrays (seconds at the 1M tier) and both the upload and
    the patch diff consume it."""
    cached = getattr(tables, "_poptrie_cache", None)
    if cached is not None:
        return cached
    from ..compiler import record_build_phase

    _t0 = time.perf_counter()
    slot_levels = tables.trie_levels
    strides = trie_level_strides(len(slot_levels))
    out_levels = []
    targets_parts = [np.zeros(1, np.int32)]  # index-0 sentinel
    t_off = 1  # global target index of the current level's first target
    perm = None  # new-id -> old-id for the CURRENT level (None: identity)
    for l, (tbl, stride) in enumerate(zip(slot_levels, strides)):
        slots = 1 << stride
        R = tbl.reshape(tbl.shape[0] // slots, slots, 2)
        if perm is not None:
            # renumbered order; unreachable (orphaned) nodes drop out
            R = R[perm] if len(perm) else R[:0]
        n_nodes = R.shape[0]
        child = R[:, :, 0]
        tgt = R[:, :, 1]
        present = child != 0
        # next level's renumbering: present children in (node, slot) order
        perm = child[present]
        if l == 0:
            # The walk computes e0 = root * 65536 + nib0 in int32; keep
            # the root level small enough that the product cannot wrap
            # (>= 32768 root nodes would need a ~17GB host slot array
            # long before this fires, but wrap would silently turn deny
            # entries into UNDEF/PASS via the OOB mask).
            if n_nodes * 65536 > np.iinfo(np.int32).max:
                raise ValueError(
                    f"poptrie root level has {n_nodes} nodes; int32 "
                    "DIR-16 indexing supports at most 32767"
                )
            # remap child ids to renumbered-level-1 ids + 1 (0 = none)
            if len(slot_levels) > 1:
                n_next = slot_levels[1].shape[0] // (1 << strides[1])
                inv = np.zeros(max(n_next, 1), np.int32)
                inv[perm] = np.arange(1, len(perm) + 1, dtype=np.int32)
                remapped = np.where(present, inv[child], 0)
            else:
                remapped = np.zeros_like(child)
            lvl0 = np.stack([remapped, tgt], axis=2).reshape(-1, 2)
            out_levels.append(np.ascontiguousarray(lvl0, np.int32))
            continue
        tpres = tgt > 0
        # LSB-first bit packing: slot s -> word s>>5, bit s&31
        cb = np.packbits(present, axis=1, bitorder="little")
        cb = np.ascontiguousarray(cb).view("<u4").astype(np.uint32)
        tb = np.packbits(tpres, axis=1, bitorder="little")
        tb = np.ascontiguousarray(tb).view("<u4").astype(np.uint32)
        counts = present.sum(axis=1, dtype=np.int64)
        tcounts = tpres.sum(axis=1, dtype=np.int64)
        cbase = np.zeros(n_nodes, np.int64)
        tbase = np.zeros(n_nodes, np.int64)
        if n_nodes:
            np.cumsum(counts[:-1], out=cbase[1:])
            np.cumsum(tcounts[:-1], out=tbase[1:])
        rows = np.empty((max(n_nodes, 1), 18), np.uint32)
        rows[:] = 0
        if n_nodes:
            rows[:n_nodes, 0] = cbase.astype(np.uint32)
            # target_base carries the GLOBAL concat offset, so the walk
            # derives the final targets index with no per-level offset
            # bookkeeping (padding rows keep 0; their bitmap is 0 so the
            # sentinel slot is never selected)
            rows[:n_nodes, 1] = (tbase + t_off).astype(np.uint32)
            rows[:n_nodes, 2:10] = cb.reshape(n_nodes, -1)[:, :8]
            rows[:n_nodes, 10:18] = tb.reshape(n_nodes, -1)[:, :8]
        lvl_targets = tgt[tpres].astype(np.int32)
        t_off += len(lvl_targets)
        out_levels.append(rows)
        targets_parts.append(lvl_targets)
    result = (out_levels, np.concatenate(targets_parts))
    record_build_phase(tables, "build_poptrie", time.perf_counter() - _t0)
    try:
        object.__setattr__(tables, "_poptrie_cache", result)
    except (AttributeError, TypeError):
        pass
    return result


#: joined-targets duplication gate: a trie whose leaf-pushed slot
#: expansion duplicates targets more than this (positions per entry)
#: falls back to the two-gather walk rather than paying the rule-row
#: duplication in device memory
JOINED_DUP_LIMIT = 2.5


def _packed_rules_flat(tables: CompiledTables):
    """(T, R*5) uint16 flattened packed rules, or (T, R*7) int32 for
    wide tables — memoized (shared with _host_device_layout)."""
    rules = getattr(tables, "_packed_rules_cache", None)
    if rules is None:
        rules = pack_rules_u16(tables.rules)
        if rules is None:
            rules = tables.rules
        rules = np.ascontiguousarray(rules).reshape(rules.shape[0], -1)
        try:
            object.__setattr__(tables, "_packed_rules_cache", rules)
        except (AttributeError, TypeError):
            pass
    return rules


def joined_layout(tables: CompiledTables):
    """UNGATED joined-targets layout: (joined, l0_joined, t_vals).

    - ``joined`` row p (p < len(targets)) corresponds to targets
      position p: [tidx+1 (2 x u16), mask_len, packed rules] — so the
      walk's win position indexes it DIRECTLY; rows for the root level's
      targets are appended once per unique root tidx.
    - ``l0_joined`` is levels[0] with the target column rewritten from
      tidx+1 to the appended joined index.
    - ``t_vals`` maps joined row -> tidx+1 (0 = sentinel/padding).

    build_joined applies the device-memory duplication gate on top; the
    fused Pallas walk (kernels.pallas_walk) consumes this directly — its
    gate is the VMEM budget after deep-tail extraction, not HBM
    duplication.  Memoized per tables instance (both consumers run on
    every full load)."""
    cached = getattr(tables, "_joined_layout_cache", None)
    if cached is not None:
        return cached
    levels, targets = build_poptrie(tables)
    rules_flat = _packed_rules_flat(tables)
    l0 = levels[0]
    rt = l0[:, 1]
    uniq = np.unique(rt[rt > 0])  # root target values (tidx+1)
    t_vals = np.concatenate([targets.astype(np.int64), uniq.astype(np.int64)])
    total = len(t_vals)
    tidx = np.maximum(t_vals - 1, 0)
    ml = np.maximum(tables.mask_len, 0)
    valid = (t_vals > 0)[:, None]
    if rules_flat.dtype == np.uint16:
        joined = np.empty((total, 3 + rules_flat.shape[1]), np.uint16)
        joined[:, 0] = t_vals & 0xFFFF
        joined[:, 1] = (t_vals >> 16) & 0xFFFF
        joined[:, 2] = np.minimum(ml[tidx], 0xFFFF)
        joined[:, 3:] = rules_flat[tidx]
    else:
        joined = np.empty((total, 2 + rules_flat.shape[1]), np.int32)
        joined[:, 0] = t_vals
        joined[:, 1] = ml[tidx]
        joined[:, 2:] = rules_flat[tidx]
    joined *= valid.astype(joined.dtype)  # sentinel/zero rows stay zero
    l0j = l0.copy()
    nz = rt > 0
    l0j[nz, 1] = (
        len(targets) + np.searchsorted(uniq, rt[nz])
    ).astype(np.int32)
    result = (joined, l0j, t_vals)
    try:
        object.__setattr__(tables, "_joined_layout_cache", result)
    except (AttributeError, TypeError):
        pass
    return result


def build_joined(tables: CompiledTables):
    """Joined target rows for the one-gather trie tail (see
    DeviceTables.joined): returns (joined, l0_joined, sorted_t, order)
    or None when the duplication gate trips.

    ``(sorted_t, order)``: positions grouped by tidx+1 (argsort of the
    row->tidx+1 map) so a rules-only edit can find and patch exactly
    the joined rows of the dirty entries (searchsorted, no scan).

    Memoized on the tables instance alongside the poptrie cache."""
    cached = getattr(tables, "_joined_cache", None)
    if cached is not None:
        return None if cached == "none" else cached
    _levels, targets = build_poptrie(tables)
    T = _packed_rules_flat(tables).shape[0]
    rt = _levels[0][:, 1]
    uniq = np.unique(rt[rt > 0])
    total = len(targets) + len(uniq)
    result = None
    if total <= max(4096, JOINED_DUP_LIMIT * (T + 1)):
        joined, l0j, t_vals = joined_layout(tables)
        order = np.argsort(t_vals, kind="stable").astype(np.int64)
        result = (joined, l0j, t_vals[order], order)
    try:
        object.__setattr__(
            tables, "_joined_cache", result if result is not None else "none"
        )
    except (AttributeError, TypeError):
        pass
    return result


# --- path/level-compressed poptrie (the "cpoptrie" layout) ------------------
#
# The per-level poptrie walk pays one HBM gather per 8-bit level — a /128
# table is 14 deep gathers even when most of the trie is single-child
# chains (clean /48+/24 distributions at the 10M tier are ~all chain).
# The compressed layout merges every deep level into ONE global node
# array and collapses single-child no-target chains into SKIP nodes
# ("path compression": each step consumes skip_len <= 24 chain bits plus
# its own 8-bit stride, so the effective per-step stride is 8..32 bits,
# selected by subtree occupancy — the level-compression dual).  Node row
# (20 x u32, 80 B — inside the flat-gather cost window):
#
#   [child_base, target_base, skip_len, skip_bits,
#    child_bitmap x8, target_bitmap x8]
#
# Children keep poptrie's implicit contiguous numbering (BFS order), so
# the child id is child_base + rank(nib) with no pointer gather.  Target
# hits record a position into a flat ``targets`` array of tidx+1 values;
# the rules tail indexes a per-TARGET joined row matrix (row t+1 =
# [tidx+1, mask_len, packed rules] — no leaf-push duplication, so the
# JOINED_DUP_LIMIT gate never applies to this layout).
#
# Only chains with NO targets compress (leaf-pushed targets pin their
# nodes), preserving bit-exact LPM semantics: the walk is verified
# bit-identical to trie_walk/the CPU oracle by tests/test_pallas_walk.py
# and the statecheck equivalence engine (compressed configs).

#: max chain bits absorbed into one skip node: skip + the node's own
#: 8-bit stride stays within a 32-bit extraction window (2 ip words)
CPOP_MAX_SKIP = 24


class CTrieTables(NamedTuple):
    """Compressed-poptrie device operands (see module comment above).

    ``d_max`` is NOT carried here — it is a static walk-unroll bound and
    travels through the jitted-factory cache key instead (NamedTuple
    fields are pytree leaves)."""

    l0: jax.Array        # (n0*65536, 2) int32 [cnode_id+1, tidx+1]
    nodes: jax.Array     # (N, 20) uint32 merged skip-node rows
    targets: jax.Array   # (1 + n_tgt,) int32 tidx+1 values (0 sentinel)
    joined: jax.Array    # (T+1, 3+R*5) uint16 per-tidx joined rows
    root_lut: jax.Array  # (max_if+1,) int32


#: TEST-ONLY defect injection for the skip-node path: zero out every
#: skip_bits word so a packet whose skipped chain bits are nonzero
#: wrongly fails (or passes) the skip compare — the statecheck
#: acceptance gate (tools/infw_lint.py state --inject-defect=cskip)
#: proves the model checker catches a compressed-walk defect via oracle
#: divergence.  Never set in production.
_INJECT_CSKIP_BUG = False


def _inject_cskip_bug() -> bool:
    if _INJECT_CSKIP_BUG:
        return True
    env = os.environ.get("INFW_INJECT_CSKIP_BUG", "")
    return env not in ("", "0", "false", "no")


def _single_child_nib(rows: np.ndarray) -> np.ndarray:
    """Slot index of the single set child-bitmap bit per node (valid
    only where the child count is exactly 1)."""
    cbm = rows[:, 2:10].astype(np.uint32)
    nz = cbm != 0
    w = np.argmax(nz, axis=1)
    wv = cbm[np.arange(len(rows)), w].astype(np.float64)
    # log2 is exact for single-bit values up to 2^31
    bit = np.zeros(len(rows), np.int64)
    pos = wv > 0
    bit[pos] = np.log2(wv[pos]).astype(np.int64)
    return w.astype(np.int64) * 32 + bit


def _pc_rows(words: np.ndarray) -> np.ndarray:
    return _popcount32(words.astype(np.uint32)).sum(axis=1).astype(np.int64)


def _crange_concat(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenate [s, s+c) ranges, vectorized (int64)."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    ends = np.cumsum(counts)
    offs = np.repeat(starts - np.concatenate([[0], ends[:-1]]), counts)
    return offs + np.arange(total, dtype=np.int64)


def build_cpoptrie(tables: CompiledTables):
    """Host transform: poptrie levels -> the merged path-compressed node
    array.  Fully vectorized (per-level scans + a d_max-bounded BFS of
    array concatenations — no per-node Python), so it rides the same
    build-time budget as build_poptrie whose output it consumes.

    Returns (l0, nodes, targets, d_max):
      l0      (n0*65536, 2) int32 [cnode_id+1, tidx+1]
      nodes   (max(N,1), 20) uint32 skip-node rows
      targets (1 + n_tgt,) int32 tidx+1 per target position (0 sentinel)
      d_max   int — compressed walk depth (static unroll bound)

    Memoized on the tables instance (keyed with the defect-injection
    flag so the acceptance gate cannot serve a stale clean build)."""
    inject = _inject_cskip_bug()
    cached = getattr(tables, "_cpoptrie_cache", None)
    if cached is not None and cached[0] == inject:
        return cached[1]
    from ..compiler import record_build_phase

    _t0 = time.perf_counter()
    levels, targets = build_poptrie(tables)
    deep = [np.asarray(l, np.uint32) for l in levels[1:]]
    L = len(deep)
    n_l = [d.shape[0] for d in deep]
    cc = [_pc_rows(d[:, 2:10]) if d.size else np.zeros(0, np.int64)
          for d in deep]
    tc = [_pc_rows(d[:, 10:18]) if d.size else np.zeros(0, np.int64)
          for d in deep]
    cb_base = [d[:, 0].astype(np.int64) if d.size else np.zeros(0, np.int64)
               for d in deep]
    tb_base = [d[:, 1].astype(np.int64) if d.size else np.zeros(0, np.int64)
               for d in deep]
    nib1 = [_single_child_nib(d) if d.size else np.zeros(0, np.int64)
            for d in deep]

    # -- top-down: pending skip accumulation + the skip/emit decision ----
    pend_len = [np.zeros(n, np.int64) for n in n_l]
    pend_bits = [np.zeros(n, np.int64) for n in n_l]
    skipped = []
    for l in range(L):
        chain = (cc[l] == 1) & (tc[l] == 0) & (l + 1 < L)
        sk = chain & (pend_len[l] + 8 <= CPOP_MAX_SKIP)
        skipped.append(sk)
        if l + 1 < L and sk.any():
            idx = np.nonzero(sk)[0]
            ch = cb_base[l][idx]  # the single child's id at level l+1
            ok = ch < n_l[l + 1]
            idx, ch = idx[ok], ch[ok]
            pend_len[l + 1][ch] = pend_len[l][idx] + 8
            pend_bits[l + 1][ch] = (pend_bits[l][idx] << 8) | nib1[l][idx]

    # -- bottom-up: resolve every node to the emitted node absorbing it --
    res_lvl = [None] * L
    res_id = [None] * L
    for l in range(L - 1, -1, -1):
        lv = np.full(n_l[l], l, np.int64)
        ids = np.arange(n_l[l], dtype=np.int64)
        if l + 1 < L and n_l[l + 1]:
            ch = np.clip(cb_base[l], 0, n_l[l + 1] - 1)
            lv = np.where(skipped[l], res_lvl[l + 1][ch], lv)
            ids = np.where(skipped[l], res_id[l + 1][ch], ids)
        res_lvl[l], res_id[l] = lv, ids

    # -- BFS numbering: emitted nodes in (parent, slot) order so every
    # node's children stay contiguous (the implicit-numbering contract) --
    l0 = np.asarray(levels[0], np.int32)
    c0 = l0[:, 0].astype(np.int64)
    has0 = c0 > 0
    if L and has0.any() and n_l[0]:
        ch0 = np.clip(c0[has0] - 1, 0, n_l[0] - 1)
        f_lvl = res_lvl[0][ch0]
        f_id = res_id[0][ch0]
    else:
        f_lvl = np.zeros(0, np.int64)
        f_id = np.zeros(0, np.int64)

    rows_out: list = []
    tgt_out: list = []
    total = 0
    t_total = 1  # targets[0] sentinel
    l0_child_new = np.zeros(len(c0), np.int64)
    first_ids = None
    d_max = 0
    while len(f_lvl):
        d_max += 1
        n_f = len(f_lvl)
        gids = total + np.arange(n_f, dtype=np.int64)
        total += n_f
        if first_ids is None:
            first_ids = gids
        # gather per-node data (grouped by source level)
        cc_f = np.empty(n_f, np.int64)
        tc_f = np.empty(n_f, np.int64)
        cb_f = np.empty(n_f, np.int64)
        tb_f = np.empty(n_f, np.int64)
        pl_f = np.empty(n_f, np.int64)
        pb_f = np.empty(n_f, np.int64)
        bm_f = np.zeros((n_f, 16), np.uint32)
        lvl_next = np.empty(n_f, np.int64)
        for l in np.unique(f_lvl):
            m = f_lvl == l
            sel = f_id[m]
            cc_f[m] = cc[l][sel]
            tc_f[m] = tc[l][sel]
            cb_f[m] = cb_base[l][sel]
            tb_f[m] = tb_base[l][sel]
            pl_f[m] = pend_len[l][sel]
            pb_f[m] = pend_bits[l][sel]
            bm_f[m] = deep[l][sel, 2:18]
            lvl_next[m] = l + 1
        # next frontier: resolved children, whole contiguous ranges
        child_old = _crange_concat(cb_f, cc_f)
        child_lvl_src = np.repeat(lvl_next, cc_f)
        nf_lvl = np.empty(len(child_old), np.int64)
        nf_id = np.empty(len(child_old), np.int64)
        for l in np.unique(child_lvl_src):
            m = child_lvl_src == l
            if l >= L or n_l[l] == 0:
                # dead pointers below the last level: resolve to self;
                # their bitmaps are zero so the walk never descends
                nf_lvl[m] = l - 1
                nf_id[m] = 0
                continue
            sel = np.clip(child_old[m], 0, n_l[l] - 1)
            nf_lvl[m] = res_lvl[l][sel]
            nf_id[m] = res_id[l][sel]
        # rows for this step
        excl_c = np.concatenate([[0], np.cumsum(cc_f)[:-1]]) if n_f else []
        excl_t = np.concatenate([[0], np.cumsum(tc_f)[:-1]]) if n_f else []
        rows = np.zeros((n_f, 20), np.uint32)
        rows[:, 0] = (total + excl_c).astype(np.uint32)
        rows[:, 1] = (t_total + excl_t).astype(np.uint32)
        rows[:, 2] = pl_f.astype(np.uint32)
        rows[:, 3] = (
            np.zeros(n_f, np.uint32) if inject else pb_f.astype(np.uint32)
        )
        rows[:, 4:20] = bm_f
        rows_out.append(rows)
        # flat targets in node order (values are global tidx+1)
        tgt_out.append(targets[_crange_concat(tb_f, tc_f)].astype(np.int64))
        t_total += int(tc_f.sum())
        f_lvl, f_id = nf_lvl, nf_id

    nodes = (
        np.concatenate(rows_out) if rows_out else np.zeros((1, 20), np.uint32)
    )
    new_targets = np.concatenate(
        [np.zeros(1, np.int64)] + tgt_out
    ).astype(np.int32)
    l0_new = l0.copy()
    if first_ids is not None:
        l0_child_new[:] = 0
        l0_child_new[np.nonzero(has0)[0]] = first_ids + 1
        l0_new[:, 0] = l0_child_new.astype(np.int32)
    else:
        l0_new[:, 0] = 0
    result = (l0_new, nodes, new_targets, d_max)
    record_build_phase(tables, "build_cpoptrie", time.perf_counter() - _t0)
    try:
        object.__setattr__(tables, "_cpoptrie_cache", (inject, result))
    except (AttributeError, TypeError):
        pass
    return result


def joined_by_tidx(tables: CompiledTables):
    """(T+1, 3 + R*5) uint16 joined rows indexed DIRECTLY by tidx+1
    (row 0 = the UNDEF sentinel): [tidx+1 lo, tidx+1 hi, mask_len,
    packed rules].  One row per dense entry — no leaf-push duplication,
    so the compressed walk's rules tail never trips the joined
    duplication gate and a rules-only edit is a scatter at positions
    dirty_tidx + 1.  Returns None for wide (int32) rule tables.
    Memoized on the tables instance."""
    cached = getattr(tables, "_joined_tidx_cache", None)
    if cached is not None:
        return None if isinstance(cached, str) else cached
    rules_flat = _packed_rules_flat(tables)
    if rules_flat.dtype != np.uint16:
        try:
            object.__setattr__(tables, "_joined_tidx_cache", "none")
        except (AttributeError, TypeError):
            pass
        return None
    T = rules_flat.shape[0]
    rows = np.zeros((T + 1, 3 + rules_flat.shape[1]), np.uint16)
    tvals = np.arange(1, T + 1, dtype=np.int64)
    rows[1:, 0] = (tvals & 0xFFFF).astype(np.uint16)
    rows[1:, 1] = (tvals >> 16).astype(np.uint16)
    rows[1:, 2] = np.minimum(
        np.maximum(tables.mask_len, 0), 0xFFFF
    ).astype(np.uint16)
    rows[1:, 3:] = rules_flat
    try:
        object.__setattr__(tables, "_joined_tidx_cache", rows)
    except (AttributeError, TypeError):
        pass
    return rows


def _joined_tidx_patch_rows(
    tables: CompiledTables, dirty: np.ndarray, rules_flat=None
):
    """(pos, rows) scatter payload for the per-tidx joined matrix at
    the dirty dense rows — the ONE place the patch-side joined row
    format [tidx+1 lo, tidx+1 hi, mask_len, packed rules] is spelled
    out (joined_by_tidx builds the full matrix with the same layout;
    patch_ctrie, pallas_walk.patch_cwalk_joined and the host-cache
    seeding all scatter through here).  Returns None for wide rule
    tables."""
    if rules_flat is None:
        rules_flat = _packed_rules_flat(tables)
    if rules_flat.dtype != np.uint16:
        return None
    dirty = dirty[(dirty >= 0) & (dirty < rules_flat.shape[0])]
    pos = dirty + 1
    rows = np.zeros((len(pos), 3 + rules_flat.shape[1]), np.uint16)
    rows[:, 0] = (pos & 0xFFFF).astype(np.uint16)
    rows[:, 1] = (pos >> 16).astype(np.uint16)
    rows[:, 2] = np.minimum(
        np.maximum(np.asarray(tables.mask_len)[dirty], 0), 0xFFFF
    ).astype(np.uint16)
    rows[:, 3:] = rules_flat[dirty]
    return pos, rows


def _seed_ctrie_caches_forward(
    old: CompiledTables, new: CompiledTables, dirty: np.ndarray
) -> None:
    """Carry the compressed-layout host caches from ``old`` to ``new``
    across a RULES-ONLY edit (caller guarantees the trie is untouched):
    the packed-rules cache is patched at the dirty rows, the
    structural transforms (_poptrie_cache/_cpoptrie_cache/
    _depth_lut_cache — they read trie levels and targets, never rules)
    are shared by reference, and the per-tidx joined cache is patched
    in place.  Without this every 1-key ctrie edit repacks the full
    rules tensor and rebuilds the joined matrix — seconds of host work
    at the 10M tier for a kilobyte-sized device scatter.  Best-effort:
    any mismatch leaves a cache unset and the slow rebuild runs."""
    if old.rules.shape != new.rules.shape:
        return
    try:
        old_packed = getattr(old, "_packed_rules_cache", None)
        if old_packed is not None and getattr(
            new, "_packed_rules_cache", None
        ) is None:
            if len(dirty) == 0:
                # nothing changed: the arrays are immutable once handed
                # out — share by reference
                new_packed = old_packed
            elif old_packed.dtype == np.uint16:
                sub = pack_rules_u16(new.rules[dirty])
                if sub is None:
                    return  # edit introduced wide values: full path
                new_packed = old_packed.copy()
                new_packed[dirty] = sub.reshape(len(dirty), -1)
            else:
                new_packed = old_packed.copy()
                new_packed[dirty] = new.rules[dirty].reshape(len(dirty), -1)
            object.__setattr__(new, "_packed_rules_cache", new_packed)
        for name in ("_poptrie_cache", "_cpoptrie_cache",
                     "_depth_lut_cache", "_depth_classes_cache"):
            c = getattr(old, name, None)
            if c is not None and getattr(new, name, None) is None:
                object.__setattr__(new, name, c)
        jt = getattr(old, "_joined_tidx_cache", None)
        if jt is not None and getattr(
            new, "_joined_tidx_cache", None
        ) is None:
            if isinstance(jt, str) or len(dirty) == 0:
                object.__setattr__(new, "_joined_tidx_cache", jt)
            else:
                pr = _joined_tidx_patch_rows(new, dirty)
                if pr is not None:
                    pos, rows = pr
                    if len(pos) and rows.shape[1] == jt.shape[1] and (
                        int(pos.max()) < jt.shape[0]
                    ):
                        jn = jt.copy()
                        jn[pos] = rows
                        object.__setattr__(new, "_joined_tidx_cache", jn)
    except (AttributeError, TypeError, ValueError, IndexError):
        return


def hint_trie_unchanged(hint) -> bool:
    """True when the dirty hint proves the edit was rules-only (no trie
    level rows touched) — the condition for cache seeding, the joined
    fast path, and for a no-hint patch retry to behave differently from
    the hinted attempt."""
    return hint is not None and all(
        len(h) == 0 for h in hint.get("levels", [np.zeros(1)])
    )


def seed_ctrie_caches_forward(
    old: CompiledTables, new: CompiledTables, hint
) -> None:
    """Backend-facing wrapper: seed the compressed-layout host caches
    when the dirty hint proves the trie untouched.  Must run BEFORE
    any eligibility probe touches ``new`` — joined_by_tidx and
    check_wire_ruleids memoize on first touch, so seeding after the
    fact is too late."""
    if not hint_trie_unchanged(hint):
        return
    dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
    dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
    _seed_ctrie_caches_forward(old, new, dirty)


def device_ctrie(
    tables: CompiledTables, device=None, pad: bool = False
) -> Optional[Tuple[CTrieTables, int]]:
    """Upload the compressed-poptrie layout; returns (CTrieTables,
    d_max) or None when the layout cannot serve this table (wide int32
    rules).  ``pad=True`` buckets the node/target/joined row counts so
    small structural edits can diff-scatter (patch_ctrie) instead of
    re-uploading; padding rows are all-zero and unreachable (bitmaps 0,
    tidx+1 bounds)."""
    joined = joined_by_tidx(tables)
    if joined is None:
        return None
    l0, nodes, targets, d_max = build_cpoptrie(tables)
    root_lut = np.asarray(tables.root_lut, np.int32)
    if pad:
        nodes = _pad_rows(nodes, _row_bucket(nodes.shape[0]))
        targets = _pad_rows(targets, _row_bucket(targets.shape[0]))
        joined = _pad_rows(joined, _row_bucket(joined.shape[0]))
        root_lut = _pad_rows(root_lut, _row_bucket(root_lut.shape[0]))
    put = lambda a: jax.device_put(jnp.asarray(a), device)
    return CTrieTables(
        l0=put(l0),
        nodes=put(nodes),
        targets=put(targets),
        joined=put(joined),
        root_lut=put(root_lut),
    ), d_max


def _ctrie_host_layout(tables: CompiledTables):
    """Unpadded host arrays in device_ctrie order (the patch diff
    source), or None for wide tables."""
    joined = joined_by_tidx(tables)
    if joined is None:
        return None
    l0, nodes, targets, d_max = build_cpoptrie(tables)
    return (l0, nodes, targets, joined,
            np.asarray(tables.root_lut, np.int32)), d_max


def patch_ctrie(
    cdev: CTrieTables,
    old: CompiledTables,
    new: CompiledTables,
    device=None,
    hint=None,
) -> Optional[Tuple[CTrieTables, int]]:
    """Incremental device update of the compressed layout.

    Rules-only edits (dirty hint proves the trie untouched) scatter
    exactly the dirty tidx rows of the per-target joined matrix —
    kilobytes, positions are dirty_tidx + 1 by construction.  Structural
    edits diff the old/new host cpoptrie arrays row-wise (same
    _patch_array machinery as the poptrie path).  Returns
    (patched, rows_changed) or None when the layout shifted beyond the
    row buckets (caller re-uploads)."""
    if hint_trie_unchanged(hint):
        dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
        dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
        # seed the host caches FIRST so the payload below patches the
        # carried packed-rules cache instead of repacking the full
        # tensor (the level walk's _seed_caches_forward contract)
        _seed_ctrie_caches_forward(old, new, dirty)
        pr = _joined_tidx_patch_rows(new, dirty)
        if pr is None:
            return None
        pos, rows = pr
        if len(pos) == 0:
            return cdev, 0
        if int(pos.max()) >= cdev.joined.shape[0]:
            return None
        if rows.shape[1] != cdev.joined.shape[1]:
            return None
        joined = _capped_scatter(cdev.joined, pos, rows, device)
        if joined is None:
            return None
        return cdev._replace(joined=joined), len(pos)
    o = _ctrie_host_layout(old)
    nw = _ctrie_host_layout(new)
    if o is None or nw is None:
        return None
    (o_arrs, _od), (n_arrs, _nd) = o, nw
    if _od != _nd:
        return None  # static unroll depth changed: re-jit + re-upload
    # Transaction discipline, ctrie structural half: compute every
    # array's host diff first, then stage all payloads' H2D copies in
    # one pass, then launch the warmed scatters — unlike the level-walk
    # path there is no per-array re-upload fallback (the merged layout's
    # arrays are interdependent), so any oversized/bucket-shifted delta
    # fails the whole patch and the caller re-uploads.
    payloads = []
    for dl, ol, nl in zip(cdev, o_arrs, n_arrs):
        if dl.shape[0] % 65536 == 0 and ol.shape[1:] == (2,):
            # l0 is not bucket-shaped; diff it with an exact-shape check
            if ol.shape != nl.shape or dl.shape != ol.shape:
                return None
            changed = np.nonzero((ol != nl).any(axis=1))[0]
            if len(changed) > max(dl.shape[0] // 4, 1):
                return None
            payloads.append((changed, nl[changed]))
            continue
        pay = _patch_diff_payload(dl, ol, nl)
        if pay is None:
            return None
        payloads.append(pay)
    staged = []
    total = 0
    for dl, (pos, vals) in zip(cdev, payloads):
        if len(pos) == 0:
            staged.append(lambda dl=dl: dl)
            continue
        th = _stage_capped(dl, pos, vals, device)
        if th is None:
            return None
        staged.append(th)
        total += len(pos)
    return CTrieTables(*(th() for th in staged)), total


def extract_ip_bits(ip_words: jax.Array, pos: jax.Array, n: jax.Array):
    """(B,) values of the ``n`` bits at absolute bit offset ``pos``
    (both dynamic per lane, n <= 32, window spans <= 2 words) of the
    128-bit address (4 big-endian u32 words, bit 0 = MSB of word 0).
    Pure u32 VPU math — the word pick is selects, not a gather (a
    take_along_axis here lowers to a per-lane gather per step, measured
    ~10x slower in the cpoptrie prototype)."""
    w = jnp.clip(pos >> 5, 0, 4).astype(jnp.int32)
    zeros = jnp.zeros_like(ip_words[:, 0])

    def pick(widx):
        out = zeros
        for k in range(4):
            out = jnp.where(widx == k, ip_words[:, k], out)
        return out

    lo = pick(w).astype(jnp.uint32)
    hi = pick(w + 1).astype(jnp.uint32)
    off = (pos & 31).astype(jnp.uint32)
    n = n.astype(jnp.uint32)
    hi_part = jnp.where(off == 0, jnp.uint32(0), hi >> (jnp.uint32(32) - off))
    top32 = (lo << off) | hi_part
    return jnp.where(n == 0, jnp.uint32(0), top32 >> (jnp.uint32(32) - n))


def _ctrie_descend(
    nodes: jax.Array, batch: DeviceBatch, node: jax.Array,
    alive: jax.Array, d_max: int,
) -> jax.Array:
    """The shared skip-node descent body: ``d_max`` steps over ONE
    merged node array from a caller-resolved entry (node id + alive
    mask) — each step checks the node's skip chain, consumes its 8-bit
    stride, and rank-indexes the contiguous children.  Returns the
    winning flat target position per lane (0 = sentinel / no hit).

    The single-table walk (ctrie_walk_rows) and the multi-tenant paged
    arena walk (arena_ctrie_rows) run EXACTLY this loop: arena slabs
    bake page-global node/target ids at slab-write time, so paging is
    entirely an entry-steering concern and the descent stays one code
    path."""
    pos = jnp.full_like(node, 16)
    cap_bits = jnp.where(batch.kind == KIND_IPV4, 32, 128)
    widx8 = jnp.arange(8, dtype=jnp.int32)[None, :]
    win = jnp.zeros_like(node)  # flat target position (0 = sentinel)

    for _step in range(d_max):
        in_n = (node >= 0) & (node < nodes.shape[0])
        alive = alive & in_n
        r = jnp.take(nodes, node, axis=0, mode="clip")
        skip_len = r[:, 2].astype(jnp.int32)
        skip_ok = jnp.where(
            skip_len > 0,
            extract_ip_bits(batch.ip_words, pos, skip_len) == r[:, 3],
            True,
        )
        alive = alive & skip_ok
        pos = pos + skip_len
        nib = extract_ip_bits(
            batch.ip_words, pos, jnp.full_like(pos, 8)
        ).astype(jnp.int32)
        pos = pos + 8
        w = (nib >> 5)[:, None]
        below = (np.uint32(1) << (nib & 31).astype(jnp.uint32)) - 1
        cb = r[:, 4:12]
        tb = r[:, 12:20]
        pc_cb = _popcount32(cb)
        pc_tb = _popcount32(tb)
        prefix = jnp.sum(jnp.where(widx8 < w, pc_cb, 0), axis=1)
        tprefix = jnp.sum(jnp.where(widx8 < w, pc_tb, 0), axis=1)
        cw = jnp.sum(jnp.where(widx8 == w, cb, 0), axis=1)
        tw = jnp.sum(jnp.where(widx8 == w, tb, 0), axis=1)
        bit = (nib & 31).astype(jnp.uint32)
        ok_t = alive & (((tw >> bit) & 1) > 0) & (pos <= cap_bits)
        win = jnp.where(
            ok_t,
            (r[:, 1] + tprefix + _popcount32(tw & below)).astype(jnp.int32),
            win,
        )
        alive = alive & (((cw >> bit) & 1) > 0)
        node = jnp.where(
            alive,
            (r[:, 0] + prefix + _popcount32(cw & below)).astype(jnp.int32),
            0,
        )
    return win


def ctrie_walk_rows(
    cdev: CTrieTables, batch: DeviceBatch, d_max: int
) -> jax.Array:
    """The compressed walk: DIR-16 root gather, then the shared
    skip-node descent (_ctrie_descend) over the ONE merged node array.
    Returns the (B, 3 + R*5) per-tidx joined rows (row 0 / dead lanes
    all-zero -> UNDEF), bit-identical in verdict semantics to
    trie_walk_joined."""
    l0, nodes, targets, joined, root_lut = cdev
    lut_size = root_lut.shape[0]
    if_ok = (batch.ifindex >= 0) & (batch.ifindex < lut_size)
    root = jnp.where(
        if_ok, jnp.take(root_lut, jnp.clip(batch.ifindex, 0, lut_size - 1)), 0
    )
    nib0 = (batch.ip_words[:, 0] >> np.uint32(16)).astype(jnp.int32)
    e0 = root * 65536 + nib0
    in0 = (e0 >= 0) & (e0 < l0.shape[0])
    rows0 = jnp.take(l0, e0, axis=0, mode="clip")
    best0 = jnp.where(in0 & (rows0[:, 1] > 0), rows0[:, 1], 0)  # tidx+1
    alive = in0 & (rows0[:, 0] > 0)
    node = jnp.where(alive, rows0[:, 0] - 1, 0)
    win = _ctrie_descend(nodes, batch, node, alive, d_max)

    in_w = (win >= 0) & (win < targets.shape[0])
    tval = jnp.where(in_w, jnp.take(targets, jnp.clip(win, 0), mode="clip"), 0)
    sel = jnp.where(tval > 0, tval, best0)  # tidx+1 (0 = no match)
    in_j = (sel > 0) & (sel < joined.shape[0])
    rows = jnp.take(
        joined, jnp.clip(sel, 0, joined.shape[0] - 1), axis=0, mode="clip"
    )
    return jnp.where(in_j[:, None], rows, 0)


def _ctrie_result_and_score(cdev: CTrieTables, batch: DeviceBatch, d_max: int):
    rows = ctrie_walk_rows(cdev, batch, d_max)
    matched = (
        rows[:, 0].astype(jnp.int32) | (rows[:, 1].astype(jnp.int32) << 16)
    ) > 0
    score = jnp.where(matched, rows[:, 2].astype(jnp.int32) + 1, 0)
    return rule_scan(joined_rule_rows(rows), batch), score


def classify_ctrie(
    cdev: CTrieTables, batch: DeviceBatch, *, d_max: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full forward pass via the compressed walk; verdict-identical to
    classify(use_trie=True) on the same tables."""
    raw, _score = _ctrie_result_and_score(cdev, batch, d_max)
    return finalize(raw, batch)


def classify_ctrie_with_overlay(
    cdev: CTrieTables,
    overlay: DeviceTables,
    batch: DeviceBatch,
    *,
    d_max: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compressed-walk classify combined with the dense overlay
    side-table (same longest-prefix combine as classify_with_overlay)."""
    raw_m, score_m = _ctrie_result_and_score(cdev, batch, d_max)
    raw_o, score_o = _raw_result_and_score(overlay, batch, use_trie=False)
    result = jnp.where(score_o > score_m, raw_o, raw_m)
    return finalize(result, batch)


@functools.lru_cache(maxsize=None)
def jitted_classify_ctrie(d_max: int):
    return jax.jit(functools.partial(classify_ctrie, d_max=d_max))


def classify_ctrie_wire(
    cdev: CTrieTables, wire: jax.Array, *, d_max: int
) -> Tuple[jax.Array, jax.Array]:
    res, _xdp, stats = classify_ctrie(cdev, unpack_wire(wire), d_max=d_max)
    return res.astype(jnp.uint16), stats


@functools.lru_cache(maxsize=None)
def jitted_classify_ctrie_wire_fused(d_max: int):
    def f(cdev: CTrieTables, wire: jax.Array) -> jax.Array:
        return fuse_wire_outputs(*classify_ctrie_wire(cdev, wire, d_max=d_max))

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def jitted_classify_ctrie_wire_overlay_fused(d_max: int):
    def f(cdev: CTrieTables, overlay: DeviceTables, wire: jax.Array):
        res, _xdp, stats = classify_ctrie_with_overlay(
            cdev, overlay, unpack_wire(wire), d_max=d_max
        )
        return fuse_wire_outputs(res.astype(jnp.uint16), stats)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def jitted_classify_ctrie_wire8_fused(d_max: int, overlay: bool):
    """wire8 (8 B/packet) launch over the compressed layout: same
    res16-only packed D2H contract as jitted_classify_wire8_fused.  The
    compressed walk needs no v4 depth truncation — the per-lane cap_bits
    gate bounds v4 descent inside the one merged node array."""
    if overlay:
        def f(cdev, ov, wire, ifmap):
            res, _x, _s = classify_ctrie_with_overlay(
                cdev, ov, unpack_wire8(wire, ifmap), d_max=d_max
            )
            return _pack_res16(res.astype(jnp.uint16))
    else:
        def f(cdev, wire, ifmap):
            res, _x, _s = classify_ctrie(
                cdev, unpack_wire8(wire, ifmap), d_max=d_max
            )
            return _pack_res16(res.astype(jnp.uint16))

    return jax.jit(f)


def warm_ctrie_patch_scatters(cdev: CTrieTables, device=None) -> None:
    """Pre-compile the compressed layout's patch scatters (the
    warm_patch_scatters analogue): nodes/targets/joined/root_lut are the
    bucket-padded patchable arrays; l0 patches through its own
    exact-shape diff, which shares the same capped executables.  The
    dirty-row ladder (scatter_cap_ladder) keeps multi-edit transaction
    flushes compile-free up to TXN_WARM_MAX_ROWS dirty rows."""
    warm_scatters(
        (cdev.nodes, cdev.targets, cdev.joined, cdev.root_lut, cdev.l0),
        device, max_rows=TXN_WARM_MAX_ROWS,
    )


def _seed_caches_forward(
    old: CompiledTables, new: CompiledTables, dirty_tidx
) -> None:
    """Carry the poptrie/packed/joined host caches from ``old`` to
    ``new`` across a RULES-ONLY edit (caller guarantees the trie is
    untouched), patching only the dirty rows.  Best-effort: any shape or
    mode mismatch silently leaves the caches unset and the slow rebuild
    paths take over.  Returns the (positions, rows) joined scatter
    payload when one was computed, so the caller's device patch does not
    recompute it."""
    if dirty_tidx is None:
        return None
    pop = getattr(old, "_poptrie_cache", None)
    old_packed = getattr(old, "_packed_rules_cache", None)
    if pop is None or old_packed is None:
        return None
    if old.rules.shape != new.rules.shape:
        return None
    pr = None
    try:
        dirty = np.unique(np.asarray(dirty_tidx, np.int64))
        dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
        if len(dirty) == 0:
            # nothing changed (overlay-only sync): share every cache by
            # reference — the arrays are immutable once handed out
            new_packed = old_packed
        elif old_packed.dtype == np.uint16:
            sub = pack_rules_u16(new.rules[dirty])
            if sub is None:
                return None  # edit introduced wide values: full path
            new_packed = old_packed.copy()
            new_packed[dirty] = sub.reshape(len(dirty), -1)
        else:
            new_packed = old_packed.copy()
            new_packed[dirty] = new.rules[dirty].reshape(len(dirty), -1)
        object.__setattr__(new, "_packed_rules_cache", new_packed)
        # trie untouched: the poptrie transform is identical — share it
        object.__setattr__(new, "_poptrie_cache", pop)
        # ...and so are the depth-steering caches (they read trie
        # levels, never rules): without this every rules-only flush in
        # an update storm re-derived the LUT + class thresholds per
        # generation — O(root slots) host work per transaction
        for name in ("_depth_lut_cache", "_depth_classes_cache"):
            c = getattr(old, name, None)
            if c is not None:
                object.__setattr__(new, name, c)
        built = getattr(old, "_joined_cache", None)
        if built is not None and built != "none":
            joined_old, l0j, sorted_t, order = built
            pr = joined_patch_rows(old, new, dirty)
            if pr is not None:
                pos, rows = pr
                if len(pos):
                    joined_new = joined_old.copy()
                    joined_new[pos] = rows
                else:
                    joined_new = joined_old
                object.__setattr__(
                    new, "_joined_cache", (joined_new, l0j, sorted_t, order)
                )
        elif built == "none":
            object.__setattr__(new, "_joined_cache", "none")
    except (AttributeError, TypeError, ValueError, IndexError):
        return None
    return pr


def joined_patch_rows(
    old: CompiledTables, new: CompiledTables, dirty_tidx: np.ndarray
):
    """(positions, rows) scatter payload updating the joined array for a
    RULES-ONLY edit: positions come from the OLD generation's cached
    position map (the trie — and therefore the position layout — is
    unchanged, which is exactly what the caller's dirty hint proves),
    row contents from the NEW tables' packed rules.  Never triggers a
    poptrie/joined rebuild of the new snapshot.  Returns None when the
    packed-rule layout changed (caller falls back to full upload)."""
    built = build_joined(old)
    if built is None:
        return None
    joined_old, _l0j, sorted_t, order = built
    new_flat = _packed_rules_flat(new)
    if new_flat.dtype != _packed_rules_flat(old).dtype or (
        new_flat.shape[1] != _packed_rules_flat(old).shape[1]
    ):
        return None
    vals = np.unique(np.asarray(dirty_tidx, np.int64)) + 1
    vals = vals[vals > 0]
    lo = np.searchsorted(sorted_t, vals, side="left")
    hi = np.searchsorted(sorted_t, vals, side="right")
    parts = [order[a:b] for a, b in zip(lo, hi)]
    pos = np.concatenate(parts) if parts else np.zeros(0, np.int64)
    if len(pos) == 0:
        return pos, joined_old[:0]
    t = np.repeat(vals, hi - lo)
    tidx = np.minimum(t - 1, new_flat.shape[0] - 1)
    ml = np.maximum(new.mask_len, 0)
    if new_flat.dtype == np.uint16:
        rows = np.empty((len(pos), 3 + new_flat.shape[1]), np.uint16)
        rows[:, 0] = t & 0xFFFF
        rows[:, 1] = (t >> 16) & 0xFFFF
        rows[:, 2] = np.minimum(ml[tidx], 0xFFFF)
        rows[:, 3:] = new_flat[tidx]
    else:
        rows = np.empty((len(pos), 2 + new_flat.shape[1]), np.int32)
        rows[:, 0] = t
        rows[:, 1] = ml[tidx]
        rows[:, 2:] = new_flat[tidx]
    return pos, rows


def _host_device_layout(tables: CompiledTables, pad: bool, with_trie: bool = True):
    """Host-side arrays in the exact layout device_tables uploads:
    mask_len sentinel applied, trie levels in the poptrie device form,
    rows bucket-padded when ``pad``.  Shared by device_tables and
    patch_device_tables so a patched device state is bit-identical to a
    fresh upload.  ``with_trie=False`` skips the (seconds-at-scale)
    poptrie transform and returns empty levels/targets — for patch calls
    whose dirty hint proves the trie is untouched."""
    mask_len = tables.mask_len.copy()
    mask_len[tables.num_entries :] = -1
    # copy=False: the compiler already stores these as uint32; a blind
    # astype would copy the full arrays on every patch diff
    key_words = tables.key_words.astype(np.uint32, copy=False)
    mask_words = tables.mask_words.astype(np.uint32, copy=False)
    # memoized per tables instance (same pattern as _poptrie_cache): the
    # patch path calls this for BOTH generations on every edit, and a
    # full repack is O(table) host work the hint fast path must not pay
    rules = getattr(tables, "_packed_rules_cache", None)
    if rules is None:
        rules = pack_rules_u16(tables.rules)
        if rules is None:
            rules = tables.rules  # wide values: int32 layout
        # flattened 2D device layout (see DeviceTables.rules)
        rules = np.ascontiguousarray(rules).reshape(rules.shape[0], -1)
        try:
            object.__setattr__(tables, "_packed_rules_cache", rules)
        except (AttributeError, TypeError):
            pass
    joined = np.zeros((1, 1), np.uint16)  # inactive placeholder
    if with_trie:
        trie_levels, trie_targets = build_poptrie(tables)
        built = build_joined(tables)
        if built is not None:
            joined, l0j, _st, _o = built
            trie_levels = [l0j] + list(trie_levels[1:])
    else:
        trie_levels, trie_targets = [], np.zeros(1, np.int32)
    root_lut = tables.root_lut
    if pad:
        n = _row_bucket(mask_len.shape[0])
        key_words = _pad_rows(key_words, n)
        mask_words = _pad_rows(mask_words, n)
        mask_len = _pad_rows(mask_len, n, fill=-1)  # padding rows are inert
        rules = _pad_rows(rules, n)
        # level padding rows are unreachable (child ranks only reach
        # allocated nodes) and zero = empty bitmaps anyway
        trie_levels = [_pad_rows(l, _row_bucket(l.shape[0])) for l in trie_levels]
        trie_targets = _pad_rows(trie_targets, _row_bucket(trie_targets.shape[0]))
        root_lut = _pad_rows(root_lut, _row_bucket(root_lut.shape[0]))
        if joined.shape[0] > 1:
            joined = _pad_rows(joined, _row_bucket(joined.shape[0]))
    return (key_words, mask_words, mask_len, rules, trie_levels,
            trie_targets, root_lut, joined)


@functools.lru_cache(maxsize=None)
def _sparse_expand_jit(n_rows: int, n_cols: int, dtype: str):
    """zeros scattered from (idx, vals) — the device side of the sparse
    trie-level transfer.  One jit per level shape; retraces per nnz shape
    are cheap and the persistent compile cache carries them across
    processes."""
    def f(idx, vals):
        return jnp.zeros((n_rows, n_cols), dtype).at[idx].set(vals)

    return jax.jit(f)


def pack_rules_u16(rules: np.ndarray):
    """(T, R, 7) int32 -> (T, R, 5) uint16 packed rule rows, or None when
    any field exceeds its packed width (ruleId/proto/icmp/action 8 bits,
    ports 16).  The scan gathers one row per packet, so the row is the
    HBM cost that matters: 10B/rule vs 28B."""
    if rules.size == 0:
        return np.zeros(rules.shape[:2] + (5,), np.uint16)
    mx = rules.max(axis=(0, 1))
    mn = int(rules.min())
    if mn < 0 or (mx[[0, 1, 4, 5, 6]] > 0xFF).any() or (mx[[2, 3]] > 0xFFFF).any():
        return None
    out = np.empty(rules.shape[:2] + (5,), np.uint16)
    out[..., 0] = rules[..., 0] | (rules[..., 6] << 8)   # rid | act
    out[..., 1] = rules[..., 1] | (rules[..., 4] << 8)   # proto | icmpType
    out[..., 2] = rules[..., 5]                          # icmpCode
    out[..., 3] = rules[..., 2]                          # portStart
    out[..., 4] = rules[..., 3]                          # portEnd
    return out


@functools.lru_cache(maxsize=None)
def _mask_words_dev_jit():
    """Reconstruct (T, 5) uint32 mask_words from mask_len on device —
    mask words are pure prefix masks (compiler.py:789-792: ifindex word
    fully masked on live rows, IP words from _mask_words_vec; dead and
    padding rows are all-zero with the mask_len == -1 sentinel), so
    shipping the 4-byte mask_len column reconstructs the 20-byte mask row
    exactly."""
    def f(mask_len):
        valid = mask_len >= 0
        w = jnp.arange(4, dtype=jnp.int32)[None, :]
        bits = jnp.clip(mask_len[:, None] - 32 * w, 0, 32).astype(jnp.uint32)
        full = jnp.uint32(0xFFFFFFFF)
        ip = jnp.where(
            bits > 0, (full << (jnp.uint32(32) - bits)) & full, 0
        ).astype(jnp.uint32)
        if0 = jnp.where(valid, full, 0).astype(jnp.uint32)[:, None]
        return jnp.concatenate([if0, jnp.where(valid[:, None], ip, 0)], axis=1)

    return jax.jit(f)


#: ship a trie level sparse when its nonzero-row fraction is below this
#: (sparse costs 12B/row shipped vs 8B/row dense, so the byte win starts
#: at 2/3 — 0.5 keeps slack for the extra dispatch)
_SPARSE_DENSITY_LIMIT = 0.5


def device_tables(
    tables: CompiledTables, device=None, pad: bool = False
) -> DeviceTables:
    """Upload to device.  ``pad=True`` buckets row counts (see
    _row_bucket) — used by the long-lived classifier so incremental table
    edits keep array shapes, enabling patch_device_tables and avoiding
    per-size jit recompiles.  Padding rows carry the mask_len == -1
    sentinel so the dense match excludes them without a separate entry
    count (and every array stays shardable along the target axis).

    The TRANSFER layout is compacted — the restart-to-enforcement path
    (the analogue of pinned-map re-adoption, loader.go:381-407) is
    link-bandwidth bound at the 1M-entry tier (3.5GB of trie levels took
    ~13 min through a ~5MB/s tunnel), so:
      - trie levels ship sparse (index + nonzero rows; levels measure
        ~1% dense at scale) and expand via on-device scatter;
      - mask_words never ship (reconstructed on device from mask_len);
      - rules ship as uint16 when their values fit (ports are the widest
        field) and upcast on device.
    The resident DeviceTables is bit-identical to a direct upload — the
    patch path diffs against it with no knowledge of how it traveled."""
    from ..compiler import record_build_phase

    _t0 = time.perf_counter()
    (key_words, mask_words, mask_len, rules, trie_levels, trie_targets,
     root_lut, joined) = _host_device_layout(tables, pad)
    record_build_phase(tables, "upload/host-layout", time.perf_counter() - _t0)
    _t0 = time.perf_counter()
    put = lambda a: jax.device_put(jnp.asarray(a), device)

    # -- trie levels: sparse scatter below the density limit (the DIR-16
    # root level is ~0-60% dense; poptrie node rows are mostly dense by
    # construction, so they usually ship whole — and are ~30x smaller
    # than the slot arrays they replaced) --------------------------------
    levels_dev = []
    for tbl in trie_levels:
        n = tbl.shape[0]
        if n == 0:
            levels_dev.append(put(tbl))
            continue
        flat = np.ascontiguousarray(tbl).reshape(n, -1)
        nnz = np.nonzero(flat.any(axis=1))[0]
        # sparse ships (4 + rowbytes) per nnz row vs rowbytes per row
        row_b = flat.shape[1] * tbl.dtype.itemsize
        if len(nnz) * (4 + row_b) <= _SPARSE_DENSITY_LIMIT * n * row_b:
            levels_dev.append(
                _sparse_expand_jit(n, tbl.shape[1], str(tbl.dtype))(
                    put(nnz.astype(np.int32)), put(tbl[nnz])
                )
            )
        else:
            levels_dev.append(put(tbl))

    result = DeviceTables(
        key_words=put(key_words),
        mask_words=_mask_words_dev_jit()(put(mask_len)),
        mask_len=put(mask_len),
        rules=put(rules),
        trie_levels=tuple(levels_dev),
        trie_targets=put(trie_targets),
        joined=put(joined),
        root_lut=put(root_lut),
        num_entries=put(np.int32(tables.num_entries)),
    )
    record_build_phase(tables, "upload/device-put", time.perf_counter() - _t0)
    if pad:
        # same permanent contract the patch path enforces: a padded
        # upload IS the layout every later patch diffs against
        assert_patched_tables(result)
    return result


@functools.lru_cache(maxsize=None)
def _scatter_rows_jit():
    # NOT donated on purpose: in-flight classify dispatches hold the old
    # table handles, and the double-buffer contract says they finish on
    # the old generation.  XLA materializes copy-then-scatter on device —
    # a full-table HBM copy is milliseconds; what the patch saves is the
    # host->device transfer of the unchanged gigabytes.
    return jax.jit(lambda a, idx, rows: a.at[idx].set(rows))


def _patch_diff_payload(dev_arr, old_np: np.ndarray, new_np: np.ndarray,
                        fill=0):
    """The host-diff half of _patch_array: validate the bucket/dtype
    contract and compute the (pos, rows) scatter payload (possibly
    empty) from the UNPADDED old/new sources — no padded copies are
    materialized (np.full of multi-GB pad layouts was 20+s per patch).
    Appended rows scatter their new values; rows the table shrank away
    from reset to the pad fill, keeping the device state bit-identical
    to a fresh ``pad=True`` upload.  Returns (pos, rows) or None when
    the dtype/trailing dims/row bucket changed or the delta exceeds the
    capped-scatter budget (caller re-uploads)."""
    if old_np.dtype != new_np.dtype or old_np.shape[1:] != new_np.shape[1:]:
        return None
    nb = dev_arr.shape[0]
    if nb != _row_bucket(nb):
        # The resident array is not bucket-shaped — e.g. the inactive
        # (1, 1) joined placeholder, whose static shape SELECTS the
        # classify walk and must never be scatter-patched or padded
        # (the PR-4 bug class).  Refusing here is the permanent half of
        # the bucket contract; assert_patched_tables is the other.
        return None
    if (
        tuple(dev_arr.shape[1:]) != new_np.shape[1:]
        or _row_bucket(new_np.shape[0]) != nb
        or _row_bucket(old_np.shape[0]) != nb
    ):
        return None
    no, nn = old_np.shape[0], new_np.shape[0]
    common = min(no, nn)
    changed = np.nonzero(
        (
            old_np[:common].reshape(common, -1)
            != new_np[:common].reshape(common, -1)
        ).any(axis=1)
    )[0]
    parts_idx = [changed]
    parts_rows = [new_np[changed]]
    if nn > no:
        parts_idx.append(np.arange(no, nn))
        parts_rows.append(new_np[no:])
    elif no > nn:
        parts_idx.append(np.arange(nn, no))
        parts_rows.append(
            np.full((no - nn,) + new_np.shape[1:], fill, new_np.dtype)
        )
    idx = np.concatenate(parts_idx)
    rows = np.concatenate(parts_rows)
    if len(idx) > nb // 4:
        # Large delta: a bucketed scatter would ship close to the full
        # array AND pay the device-side copy — the full upload wins.
        return None
    return idx, rows


def _patch_array(dev_arr, old_np: np.ndarray, new_np: np.ndarray, device, fill=0):
    """Scatter-patch one bucket-padded device array from the host diff
    of its UNPADDED old/new sources (payload via _patch_diff_payload,
    launch via the shared capped executable).  Returns
    (patched_or_original_array, rows_changed) or None when the
    dtype/trailing dims/row bucket changed or the delta is oversized
    (caller re-uploads)."""
    pay = _patch_diff_payload(dev_arr, old_np, new_np, fill=fill)
    if pay is None:
        return None
    idx, rows = pay
    if len(idx) == 0:
        return dev_arr, 0
    patched = _capped_scatter(dev_arr, idx, rows, device)
    if patched is None:
        return None
    return patched, len(idx)


#: every patch of <= this many rows shares ONE scatter executable per
#: array shape — precompiled by warm_patch_scatters at load time; the
#: padding transfer cost (256 rows of the widest row layout) is a few KB
_PATCH_CAP = 256


def _scatter_cap(k: int, nb: int) -> int:
    """Padded scatter size for a k-row patch into an nb-row array: the
    fixed _PATCH_CAP for every small patch (one warmable executable),
    pow2 buckets only for rare large deltas."""
    if nb <= _PATCH_CAP:
        return nb
    if k <= _PATCH_CAP:
        return _PATCH_CAP
    return min(1 << (k - 1).bit_length(), nb)


def _scatter(dev_arr, pidx: np.ndarray, prows: np.ndarray, device):
    return _scatter_rows_jit()(
        dev_arr, jax.device_put(pidx, device), jax.device_put(prows, device)
    )


def _capped_payload(pos: np.ndarray, rows: np.ndarray, nb: int):
    """Pad a (pos, rows) scatter payload to its shared capped size
    (_scatter_cap) by repeating the last row — duplicate indices with
    identical values are a deterministic no-op — so every small patch of
    one array shape reuses one warmed executable.  Returns
    (pidx, prows) or None when the delta exceeds the capped-scatter
    budget (callers escalate to a re-upload/rebuild)."""
    k = len(pos)
    if k > nb // 4:
        return None
    cap = _scatter_cap(k, nb)
    pidx = np.empty(cap, np.int64)
    pidx[:k] = pos
    pidx[k:] = pos[-1]
    prows = np.empty((cap,) + rows.shape[1:], rows.dtype)
    prows[:k] = rows
    prows[k:] = rows[-1]
    return pidx, prows


def _capped_scatter(dev_arr, pos: np.ndarray, rows: np.ndarray, device):
    """Scatter ``rows`` at ``pos`` into ``dev_arr`` through the shared
    capped executable (see _scatter_cap): every small patch of one array
    shape reuses ONE warmed scatter compile.  Returns the patched array,
    or None when the delta is too large to win over a re-upload/rebuild
    (callers fall back).  Shared by the joined-row patch and the fused
    walk's byte-plane patch (pallas_walk.patch_walk_joined)."""
    k = len(pos)
    if k == 0:
        return dev_arr
    pay = _capped_payload(pos, rows, dev_arr.shape[0])
    if pay is None:
        return None
    return _scatter(dev_arr, pay[0], pay[1], device)


def _stage_capped(dev_arr, pos: np.ndarray, rows: np.ndarray, device):
    """The two-phase form of _capped_scatter: pad the payload and START
    its H2D copies now (jax.device_put is async), returning a zero-arg
    thunk that launches the warmed scatter.  A transaction patch stages
    EVERY tensor family's payload first — one H2D staging pass whose
    transfers overlap each other and whatever the device is running —
    then launches.  None when the payload exceeds the capped budget."""
    if len(pos) == 0:
        return lambda: dev_arr
    pay = _capped_payload(pos, rows, dev_arr.shape[0])
    if pay is None:
        return None
    didx = jax.device_put(pay[0], device)
    drows = jax.device_put(pay[1], device)
    return lambda: _scatter_rows_jit()(dev_arr, didx, drows)


# --- fused transaction scatter ----------------------------------------------
#
# A flushed edit transaction produces one merged dirty-row set per
# tensor family; the hot (rules-only) flush updates the whole dense
# group + the joined plane in ONE fused executable below, and every
# payload is pre-padded to its capped size so the executable cache stays
# bounded across transaction sizes (the dirty-row-count ladder prewarm
# in warm_txn_scatters keeps the serving path compile-free).

#: dirty-row-count prewarm bound: caps for transactions of up to this
#: many dirty rows are compiled at load time (larger transactions are
#: close to the nb//4 budget where the patch falls back to a re-upload
#: anyway)
TXN_WARM_MAX_ROWS = 512


@functools.lru_cache(maxsize=None)
def jitted_txn_scatter(n: int):
    """ONE fused executable scattering ``n`` (array, idx, rows) payloads
    in a single dispatch — the transaction patch launch.  NOT donated
    for the same double-buffer reason as _scatter_rows_jit: in-flight
    classifies finish on the old generation's handles."""
    def f(arrays, idxs, rows):
        return tuple(a.at[i].set(r) for a, i, r in zip(arrays, idxs, rows))

    return jax.jit(f)


def txn_scatter(entries, device):
    """Fused multi-array transaction scatter: ``entries`` is a sequence
    of ``(dev_arr, pos, rows)`` — one merged dirty-row payload per
    tensor family.  Every payload's H2D copy is staged before any
    launch (one staging pass), then ALL arrays update in one
    jitted_txn_scatter dispatch.  Zero-row payloads pass their array
    through untouched (and stay out of the launch — an identity scatter
    would still pay a device-side full-array copy).  Returns the list
    of patched arrays in entry order, or None when any payload exceeds
    the capped-scatter budget (the caller escalates to a full
    re-upload/rebuild)."""
    payloads = []
    for dev_arr, pos, rows in entries:
        if len(pos) == 0:
            payloads.append(None)
            continue
        pay = _capped_payload(pos, rows, dev_arr.shape[0])
        if pay is None:
            return None
        payloads.append(pay)
    live = [i for i, p in enumerate(payloads) if p is not None]
    out = [a for a, _pos, _rows in entries]
    if not live:
        return out
    # ONE staging pass: every payload's async copy is in flight before
    # the fused launch below
    staged = [
        (
            entries[i][0],
            jax.device_put(payloads[i][0], device),
            jax.device_put(payloads[i][1], device),
        )
        for i in live
    ]
    patched = jitted_txn_scatter(len(staged))(
        tuple(a for a, _i, _r in staged),
        tuple(i for _a, i, _r in staged),
        tuple(r for _a, _i, r in staged),
    )
    for j, i in enumerate(live):
        out[i] = patched[j]
    return out


def scatter_cap_ladder(nb: int, max_rows: int = TXN_WARM_MAX_ROWS):
    """The distinct dirty-row counts whose capped payloads exercise
    every executable shape a 1..max_rows-row patch of an nb-row array
    can emit — the dirty-row-count prewarm ladder (one representative k
    per distinct _scatter_cap)."""
    hi = min(max_rows, nb // 4)
    ks = []
    k = 1
    while k <= hi:
        ks.append(k)
        k = _scatter_cap(k, nb) + 1
    return ks


def warm_txn_scatters(dev: "DeviceTables", device=None,
                      max_rows: int = TXN_WARM_MAX_ROWS) -> None:
    """Pre-compile the fused transaction executable (jitted_txn_scatter)
    for the rules-only flush combo — the dense group plus, when active,
    the joined plane — across the dirty-row-count cap ladder, so a
    flushed edit transaction of any size up to ``max_rows`` launches
    compile-free.  Mixed-cap combos (families whose dirty counts land in
    different >256-row buckets) compile once on first use; uniform
    combos — every transaction below _PATCH_CAP rows, i.e. the churn
    regime — are fully covered here.  Same discard-the-result contract
    as warm_patch_scatters: the resident arrays are never mutated."""
    arrays = [dev.key_words, dev.mask_words, dev.mask_len, dev.rules]
    nb = arrays[0].shape[0]
    if nb <= 1 or nb != _row_bucket(nb):
        return
    if dev.joined.shape[0] > 1:
        arrays.append(dev.joined)
    for k in scatter_cap_ladder(nb, max_rows):
        txn_scatter(
            [
                (
                    a,
                    np.zeros(min(k, max(a.shape[0] // 4, 1)), np.int64),
                    np.zeros(
                        (min(k, max(a.shape[0] // 4, 1)),) + a.shape[1:],
                        a.dtype,
                    ),
                )
                for a in arrays
            ],
            device,
        )


def warm_patch_scatters(dev: DeviceTables, device=None) -> None:
    """Pre-compile the patch path's scatter executables so the FIRST
    incremental update after a (re)load does not pay the scatter-jit
    compile (~10s measured at the 1M tier).  The executable cache is
    keyed on abstract shapes/dtypes, and every <= _PATCH_CAP-row patch
    uses the SAME capped scatter shape (_scatter_cap), so one warm per
    array shape covers all small edits; the dirty-row-count ladder
    (scatter_cap_ladder) extends the coverage to multi-edit transaction
    flushes up to TXN_WARM_MAX_ROWS dirty rows, and warm_txn_scatters
    covers the FUSED rules-only combo the transaction patch launches.
    Each warm scatters against the RESIDENT array — _scatter is
    non-donating, so the live table is never mutated (XLA materializes
    copy-then-scatter) and the discarded result is the only transient
    allocation; scattering into a separate zeros scratch would double
    the transient HBM right after a full load, when the double-buffer
    contract may still hold the previous generation live."""
    warm_scatters(
        (dev.key_words, dev.mask_words, dev.mask_len, dev.rules,
         *dev.trie_levels, dev.trie_targets, dev.joined, dev.root_lut),
        device, max_rows=TXN_WARM_MAX_ROWS,
    )
    warm_txn_scatters(dev, device)


def warm_scatters(arrays, device=None, max_rows: int = 1) -> None:
    """Pre-compile the capped scatter executable for each distinct
    (shape, dtype) among ``arrays`` (the shared body of
    warm_patch_scatters, also used for the fused walk's patchable joined
    planes), across the dirty-row-count cap ladder up to ``max_rows``
    (default 1 = the single-edit cap only).  Arrays with <= 1 rows are
    skipped: a non-bucket resident (the (1, 1) placeholders) is never
    patchable by contract."""
    seen = set()
    for arr in arrays:
        for k in scatter_cap_ladder(arr.shape[0], max(max_rows, 1)):
            cap = _scatter_cap(k, arr.shape[0])
            key = (cap, tuple(arr.shape), str(arr.dtype))
            if arr.shape[0] <= 1 or key in seen:
                continue
            seen.add(key)
            pidx = np.zeros(cap, np.int64)
            # index 0 rewritten with... whatever value row 0 holds is NOT
            # needed: the scatter result is discarded, so writing zeros
            # into the COPY is harmless — the resident buffer is
            # untouched.
            prows = np.zeros((cap,) + arr.shape[1:], arr.dtype)
            _scatter(arr, pidx, prows, device)


def _patch_rows_payload(dev_arr, new_np: np.ndarray, rows: np.ndarray):
    """Hint-mode payload: ``new_np[rows]`` with no host diff.  ``rows``
    must be a SUPERSET of the rows whose values changed (the compiler's
    dirty tracking guarantees this); unchanged hinted rows rewrite their
    identical value.  Returns (pos, row values) — possibly empty — or
    None when the bucket/dtype no longer matches or the hint is too
    large to win."""
    nb = dev_arr.shape[0]
    if nb != _row_bucket(nb):
        return None  # non-bucket resident (placeholder): never patchable
    if (
        dev_arr.dtype != new_np.dtype
        or tuple(dev_arr.shape[1:]) != new_np.shape[1:]
        or _row_bucket(new_np.shape[0]) != nb
    ):
        return None
    rows = rows[rows < new_np.shape[0]]
    if len(rows) > nb // 4:
        return None
    return rows, new_np[rows]


def _patch_array_rows(dev_arr, new_np: np.ndarray, rows: np.ndarray, device):
    """Hint-mode patch: scatter ``new_np[rows]`` without any host diff
    (payload via _patch_rows_payload, launch via the shared capped
    executable).  Returns (array, k) or None when the bucket/dtype no
    longer matches or the hint is too large to win."""
    pay = _patch_rows_payload(dev_arr, new_np, rows)
    if pay is None:
        return None
    pos, vals = pay
    if len(pos) == 0:
        return dev_arr, 0
    patched = _capped_scatter(dev_arr, pos, vals, device)
    if patched is None:
        return None
    return patched, len(pos)


def patch_device_tables(
    dev: DeviceTables,
    old: CompiledTables,
    new: CompiledTables,
    device=None,
    hint=None,
) -> Tuple[DeviceTables, int] | None:
    """Incremental device update — the TPU-native Map.Update
    (/root/reference/pkg/ebpf/ingress_node_firewall_loader.go:200-218,
    where a one-CIDR edit touches one kernel map key): diff the old/new
    host tables row-wise (in the padded device layout, so the patched
    state is bit-identical to a fresh ``device_tables(new, pad=True)``
    upload) and ship ONLY the changed rows, scattering them into a
    device-side copy of the resident arrays.  A one-key edit at 1M
    entries uploads kilobytes instead of the ~3.4GB full table.

    ``dev`` must have been built with ``pad=True``.  Returns
    (new DeviceTables, total_rows_changed), or None when the structure
    changed beyond the row buckets (level count, bucket growth,
    compaction shrink past a bucket) and the caller re-uploads in full.

    Trie levels live on device in the poptrie form (build_poptrie), and
    a CIDR edit renumbers/ranks nodes, so per-level changes are diffed
    on the poptrie HOST arrays — the level hint (slot-space row numbers)
    does not apply to them and only accelerates the dense arrays; a
    rules-only edit (the common Map.Update case) leaves every level's
    poptrie bytes identical and the diff is a cheap vectorized compare."""
    if len(dev.trie_levels) != len(new.trie_levels) or len(
        old.trie_levels
    ) != len(new.trie_levels):
        return None
    # Rules-only edits (the common Map.Update) leave the trie untouched —
    # the dirty hint proves it (its level lists track slot-space repush
    # writes), so the poptrie transform AND the per-level diffs are
    # skipped entirely and the resident level arrays carry over.
    trie_unchanged = hint_trie_unchanged(hint)
    if trie_unchanged:
        # Seed the NEW generation's host caches from the old one BEFORE
        # any layout call: without this, every patched generation rebuilt
        # the packed-rules array (O(table) repack) and — worse — the next
        # edit's joined_patch_rows(old=this generation) re-ran the FULL
        # poptrie transform because no cache existed (measured: 1-key
        # rule edits at 1M cost ~6s instead of ~1s).  Rules-only edits
        # keep the trie and the position layout identical, so the poptrie
        # cache is shared by reference and packed/joined caches are
        # copied + dirty-row-patched.
        seeded_pr = _seed_caches_forward(old, new, hint.get("dense"))
    else:
        seeded_pr = None
    o = _host_device_layout(old, pad=False, with_trie=not trie_unchanged)
    nw = _host_device_layout(new, pad=False, with_trie=not trie_unchanged)
    # only trie levels / targets / root_lut go through put: pad fill is 0
    put = lambda a: jax.device_put(
        jnp.asarray(_pad_rows(a, _row_bucket(a.shape[0]))), device
    )
    total = 0

    fused_joined = None  # resident joined patched by the fused launch
    if hint is not None:
        # Transaction fast path (the update-storm flush): ONE merged
        # dirty-row payload per dense array — plus the joined plane on
        # rules-only flushes — staged in one H2D pass and launched as
        # ONE fused scatter executable (jitted_txn_scatter, pre-warmed
        # across the dirty-row ladder by warm_txn_scatters), so a
        # 64-edit folded transaction costs one dispatch, not 5 x 64.
        entries = []
        for dl, nl in zip(
            (dev.key_words, dev.mask_words, dev.mask_len, dev.rules),
            nw[:4],
        ):
            pay = _patch_rows_payload(dl, nl, hint["dense"])
            if pay is None:
                return None
            entries.append((dl,) + pay)
        if trie_unchanged and dev.joined.shape[0] > 1:
            # the joined array carries RULE BYTES, so a rules-only edit
            # must patch its rows too (positions from the old
            # generation's cached map; trie unchanged = positions valid)
            pr = (seeded_pr if seeded_pr is not None
                  else joined_patch_rows(old, new, hint["dense"]))
            if pr is None:
                return None
            pos, rows = pr
            if len(pos):
                if (
                    rows.dtype != dev.joined.dtype
                    or rows.shape[1:] != tuple(dev.joined.shape[1:])
                    or int(pos.max()) >= dev.joined.shape[0]
                ):
                    return None
            entries.append((dev.joined, pos, rows))
        patched = txn_scatter(entries, device)
        if patched is None:
            return None
        dense = patched[:4]
        total += sum(len(e[1]) for e in entries)
        if len(entries) > 4:
            fused_joined = patched[4]
    else:
        dense = []
        for dl, ol, nl, fill in zip(
            (dev.key_words, dev.mask_words, dev.mask_len, dev.rules),
            o[:4],
            nw[:4],
            (0, 0, -1, 0),
        ):
            p = _patch_array(dl, ol, nl, device, fill=fill)
            if p is None:
                return None
            dense.append(p[0])
            total += p[1]
    if trie_unchanged:
        levels = list(dev.trie_levels)
        trie_targets = dev.trie_targets
        joined = fused_joined if fused_joined is not None else dev.joined
    else:
        # Structural flush: compute every family's host diff FIRST, then
        # start every payload's (and fallback re-upload's) H2D copy in
        # one staging pass — the transfers overlap each other and
        # whatever the device is running — then launch the per-family
        # warmed scatters.  A family whose bucket changed (or whose
        # delta is oversized) re-uploads just itself.
        specs = []  # (tag, dev_arr, new host array, payload | None)
        for dl, ol, nl in zip(dev.trie_levels, o[4], nw[4]):
            specs.append(("level", dl, nl, _patch_diff_payload(dl, ol, nl)))
        specs.append((
            "targets", dev.trie_targets, nw[5],
            _patch_diff_payload(dev.trie_targets, o[5], nw[5]),
        ))
        if nw[7].shape[0] <= 1:
            specs.append(("joined-inactive", dev.joined, nw[7], None))
        else:
            specs.append((
                "joined", dev.joined, nw[7],
                _patch_diff_payload(dev.joined, o[7], nw[7]),
            ))
        staged = []  # ("ready", array, rows) | ("launch", thunk, rows)
        for tag, dl, nl, pay in specs:
            if tag == "joined-inactive":
                # Inactive joined row ((1, 1) placeholder or single-
                # sentinel layout): it must keep its exact single-row
                # shape — classify selects the joined walk on
                # joined.shape[0] > 1, so a bucket-padded put() here
                # would flip a non-joined table into walking a
                # zero/garbage-width rules tail (and the payload helpers
                # always refuse it: _row_bucket(1) == 8 != 1).
                # assert_patched_tables below enforces this as a
                # permanent contract at the mutation site.
                if _inject_joined_pad_bug():
                    arr = put(nl)  # the PR-4 defect, re-introduced
                else:
                    arr = jax.device_put(jnp.asarray(nl), device)
                staged.append(
                    ("ready", arr, 0 if dev.joined.shape[0] <= 1 else 1)
                )
                continue
            if pay is None:
                staged.append(("ready", put(nl), len(nl)))
                continue
            pos, vals = pay
            if len(pos) == 0:
                staged.append(("ready", dl, 0))
                continue
            th = _stage_capped(dl, pos, vals, device)
            if th is None:
                staged.append(("ready", put(nl), len(nl)))
            else:
                staged.append(("launch", th, len(pos)))
        outs = []
        for mode, x, k in staged:
            outs.append(x if mode == "ready" else x())
            total += k
        n_lv = len(dev.trie_levels)
        levels = outs[:n_lv]
        trie_targets = outs[n_lv]
        joined = outs[n_lv + 1]
    p = _patch_array(dev.root_lut, o[6], nw[6], device)
    if p is None:
        root_lut = put(nw[6])
        total += len(nw[6])
    else:
        root_lut, k = p
        total += k
    result = DeviceTables(
        key_words=dense[0],
        mask_words=dense[1],
        mask_len=dense[2],
        rules=dense[3],
        trie_levels=tuple(levels),
        trie_targets=trie_targets,
        joined=joined,
        root_lut=root_lut,
        num_entries=jax.device_put(
            jnp.asarray(np.int32(new.num_entries)), device
        ),
    )
    # Permanent post-patch contract (shape-only, negligible cost): the
    # PR-4 bug class — a placeholder that stopped being exactly (1, 1),
    # a de-bucketed row count — raises HERE, at the mutation site.
    assert_patched_tables(result)
    return result, total


def assert_patched_tables(dev: DeviceTables) -> None:
    """Cheap permanent shape contract on a padded/patched DeviceTables;
    raises DeviceTableInvariantError on violation.  Checks only static
    shapes/dtypes (no device reads), so it is always on — the deep
    data-level pass lives in infw.analysis.statecheck.check_device_tables
    and runs under INFW_CHECK_INVARIANTS / the model checker."""
    nb = dev.key_words.shape[0]
    for name, arr in (
        ("key_words", dev.key_words), ("mask_words", dev.mask_words),
        ("mask_len", dev.mask_len), ("rules", dev.rules),
    ):
        if arr.shape[0] != nb:
            raise DeviceTableInvariantError(
                f"dense row-count mismatch: {name} has {arr.shape[0]} rows, "
                f"key_words has {nb}"
            )
    if nb != _row_bucket(nb):
        raise DeviceTableInvariantError(
            f"dense arrays have {nb} rows — not a valid row bucket "
            f"(_row_bucket({nb}) == {_row_bucket(nb)})"
        )
    j = dev.joined
    meta_w = 3 if j.dtype == jnp.uint16 else 2
    if j.shape[0] <= 1:
        # Inactive for classify (the walk selects on shape[0] > 1): the
        # (1, 1) placeholder, or a single-sentinel-row joined layout
        # from a tiny/empty table.  Any other width means something
        # padded or truncated the placeholder.
        if j.shape[1] != 1 and j.shape[1] != meta_w + dev.rules.shape[1]:
            raise DeviceTableInvariantError(
                f"inactive joined row has width {j.shape[1]} — neither "
                "the (1, 1) placeholder nor the sentinel joined layout "
                f"({meta_w} + rules width {dev.rules.shape[1]})"
            )
    else:
        # ACTIVE for classify: the row must really carry
        # [tidx, mask_len, rules] in the resident rules layout — a
        # bucket-padded placeholder ((8, 1), the PR-4 bug) or a stale
        # width would make classify walk a zero/garbage-width rules tail.
        if j.dtype != dev.rules.dtype:
            raise DeviceTableInvariantError(
                f"active joined dtype {j.dtype} != rules dtype "
                f"{dev.rules.dtype}"
            )
        if j.shape[1] != meta_w + dev.rules.shape[1]:
            raise DeviceTableInvariantError(
                f"active joined row width {j.shape[1]} != {meta_w} + rules "
                f"width {dev.rules.shape[1]} (a bucket-padded placeholder "
                "masquerading as an active joined plane — the PR-4 bug "
                "class)"
            )
        if j.shape[0] != _row_bucket(j.shape[0]):
            raise DeviceTableInvariantError(
                f"active joined array has {j.shape[0]} rows — not a valid "
                "row bucket"
            )
    for i, lvl in enumerate(dev.trie_levels):
        n = lvl.shape[0]
        if i == 0:
            if n % 65536:
                raise DeviceTableInvariantError(
                    f"trie level 0 has {n} rows — not a whole number of "
                    "DIR-16 root nodes (65536 slots each)"
                )
        elif n != _row_bucket(n):
            raise DeviceTableInvariantError(
                f"trie level {i} has {n} rows — not a valid row bucket"
            )


def device_batch(batch: PacketBatch, device=None) -> DeviceBatch:
    put = lambda a: jax.device_put(jnp.asarray(a), device)
    return DeviceBatch(
        kind=put(batch.kind),
        l4_ok=put(batch.l4_ok),
        ifindex=put(batch.ifindex),
        ip_words=put(batch.ip_words.astype(np.uint32)),
        proto=put(batch.proto),
        dst_port=put(batch.dst_port),
        icmp_type=put(batch.icmp_type),
        icmp_code=put(batch.icmp_code),
        pkt_len=put(batch.pkt_len),
    )


def unpack_wire(wire: jax.Array) -> DeviceBatch:
    """Device-side inverse of PacketBatch.pack_wire / pack_wire_v4 /
    packets.narrow_wire, discriminated by the (static) wire width:
    (B, 7) full layout, (B, 4) v4-compact (IP word 0 only, high words
    reconstructed as zeros — the v4 key invariant), (B, 3) / (B, 6) the
    NARROW layouts (ifindex folded into w0, dst_port overlaid with the
    ICMP fields in one l4 word — lossless for classification, see
    narrow_wire).  Pure elementwise bit ops, fused by XLA into whatever
    consumes the fields — the packed descriptor never round-trips HBM."""
    w0 = wire[:, 0]
    w1 = wire[:, 1]
    narrow = wire.shape[1] in (3, 6)
    ip_off = 2 if narrow else 3
    if wire.shape[1] in (3, 4):
        ip_words = jnp.concatenate(
            [
                wire[:, ip_off : ip_off + 1],
                jnp.zeros((wire.shape[0], 3), wire.dtype),
            ],
            axis=1,
        )
    else:
        ip_words = wire[:, ip_off : ip_off + 4]
    proto = ((w0 >> 3) & 0xFF).astype(jnp.int32)
    if narrow:
        is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
        l4w = (w1 & 0xFFFF).astype(jnp.int32)
        ifindex = ((w0 >> 11) & 0xFFFF).astype(jnp.int32)
        dst_port = jnp.where(is_icmp, 0, l4w)
        icmp_type = jnp.where(is_icmp, l4w >> 8, 0)
        icmp_code = jnp.where(is_icmp, l4w & 0xFF, 0)
        pkt_len = ((w1 >> 16) & 0xFFFF).astype(jnp.int32)
    else:
        ifindex = wire[:, 2].astype(jnp.int32)
        dst_port = (w1 & 0xFFFF).astype(jnp.int32)
        icmp_type = ((w0 >> 11) & 0xFF).astype(jnp.int32)
        icmp_code = ((w0 >> 19) & 0xFF).astype(jnp.int32)
        pkt_len = (((w1 >> 16) & 0xFFFF) | ((w0 >> 27) << 16)).astype(jnp.int32)
    return DeviceBatch(
        kind=(w0 & 3).astype(jnp.int32),
        l4_ok=((w0 >> 2) & 1).astype(jnp.int32),
        ifindex=ifindex,
        ip_words=ip_words,
        proto=proto,
        dst_port=dst_port,
        icmp_type=icmp_type,
        icmp_code=icmp_code,
        pkt_len=pkt_len,
    )


def unpack_wire8(wire: jax.Array, ifmap: jax.Array) -> DeviceBatch:
    """Device-side inverse of packets.wire8: (B, 2) uint32 rows + the
    (16,) int32 ifindex dictionary.  pkt_len is reconstructed as ZERO —
    this format never carries lengths; byte statistics are computed
    host-side from the returned verdicts (daemon.stats_from_results), so
    callers must NOT consume the device stats of a wire8 classify."""
    w0 = wire[:, 0]
    proto = ((w0 >> 3) & 0xFF).astype(jnp.int32)
    is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
    l4w = ((w0 >> 15) & 0xFFFF).astype(jnp.int32)
    ifd = ((w0 >> 11) & 0xF).astype(jnp.int32)
    ifindex = jnp.take(ifmap, ifd, mode="clip").astype(jnp.int32)
    zeros = jnp.zeros_like(proto)
    return DeviceBatch(
        kind=(w0 & 3).astype(jnp.int32),
        l4_ok=((w0 >> 2) & 1).astype(jnp.int32),
        ifindex=ifindex,
        ip_words=jnp.concatenate(
            [wire[:, 1:2], jnp.zeros((wire.shape[0], 3), wire.dtype)], axis=1
        ),
        proto=proto,
        dst_port=jnp.where(is_icmp, 0, l4w),
        icmp_type=jnp.where(is_icmp, l4w >> 8, 0),
        icmp_code=jnp.where(is_icmp, l4w & 0xFF, 0),
        pkt_len=zeros,
    )


def _pack_res16(res16: jax.Array) -> jax.Array:
    """(B,) u16 -> ceil(B/2) int32 single-buffer D2H payload.  The
    (nw, 2) u16 -> u32 bitcast is a pure reinterpretation, no
    lane-crossing shuffles (the strided r[0::2] | r[1::2] << 16 form
    measures ~40% slower on the chip)."""
    r = res16
    if r.shape[0] % 2:
        r = jnp.concatenate([r, jnp.zeros(1, jnp.uint16)])
    packed = jax.lax.bitcast_convert_type(r.reshape(-1, 2), jnp.uint32)
    return jax.lax.bitcast_convert_type(packed, jnp.int32)


def unpack_res16_host(arr: np.ndarray, b: int) -> np.ndarray:
    u = arr.view(np.uint32)
    res16 = np.empty(len(u) * 2, np.uint16)
    res16[0::2] = u & 0xFFFF
    res16[1::2] = u >> 16
    return res16[:b]


def classify_wire8(
    tables: DeviceTables, wire: jax.Array, ifmap: jax.Array,
    overlay: "Optional[DeviceTables]" = None, *, v4_only: bool = True
) -> jax.Array:
    """wire8 classify: res16-only packed D2H (stats are host-derived for
    this format; the wire is v4-compact by construction, so the walk
    truncates like classify_wire's v4_only path)."""
    if v4_only:
        depth = v4_trie_depth(len(tables.trie_levels))
        tables = tables._replace(trie_levels=tables.trie_levels[:depth])
    batch = unpack_wire8(wire, ifmap)
    if overlay is not None:
        res, _x, _s = classify_with_overlay(
            tables, overlay, batch, use_trie=True
        )
    else:
        res, _x, _s = classify(tables, batch, use_trie=True)
    return _pack_res16(res.astype(jnp.uint16))


@functools.lru_cache(maxsize=None)
def jitted_classify_wire8_fused(overlay: bool, v4_only: bool = True):
    if overlay:
        def f(tables, ov, wire, ifmap):
            return classify_wire8(tables, wire, ifmap, ov, v4_only=v4_only)
    else:
        def f(tables, wire, ifmap):
            return classify_wire8(tables, wire, ifmap, v4_only=v4_only)

    return jax.jit(f)


def build_depth_lut(tables: CompiledTables) -> np.ndarray:
    """(n0*65536,) int8 per-root-slot DEEP-LEVEL requirement: the number
    of trie levels BELOW the DIR-16 root reachable under each root slot
    — i.e. packets whose (root, top-16-bits) slot maps to value d are
    fully classified by trie_levels[:1+d].

    This is the depth-steering analogue of the v4 family split: measured
    on the 100K bench table, 52% of v6 packets need <=3 deep levels (26%
    need none at all) while the static walk pays all 14 — and the walk
    cost is linear in levels (~2.45 ns/level on v5e).  The LUT is a
    TABLE-SHAPE property: conservative under deletes (targets only
    disappear, depth never grows), recomputed on any structural load
    (the host-cache carry-forward only survives provably rules-only
    edits, see _seed_caches_forward).

    Memoized on the tables instance."""
    cached = getattr(tables, "_depth_lut_cache", None)
    if cached is not None:
        return cached
    levels = tables.trie_levels
    strides = trie_level_strides(len(levels))
    depth_next = None  # per-node depth of the NEXT level
    for l in range(len(levels) - 1, 0, -1):
        slots = 1 << strides[l]
        child = levels[l].reshape(-1, slots, 2)[:, :, 0]
        if depth_next is None:
            d = np.ones(child.shape[0], np.int8)
        else:
            cd = np.where(
                child > 0,
                depth_next[np.clip(child, 0, len(depth_next) - 1)],
                0,
            )
            d = (1 + cd.max(axis=1, initial=0)).astype(np.int8)
        depth_next = d
    l0 = levels[0].reshape(-1, 2)
    if depth_next is None:
        lut = np.zeros(l0.shape[0], np.int8)
    else:
        lut = np.where(
            l0[:, 0] > 0,
            depth_next[np.clip(l0[:, 0], 0, len(depth_next) - 1)],
            0,
        ).astype(np.int8)
    try:
        object.__setattr__(tables, "_depth_lut_cache", lut)
    except (AttributeError, TypeError):
        pass
    return lut


#: deep-level class thresholds for depth steering: each v6 chunk walks
#: the smallest class >= its packets' LUT depth.  A fixed small set
#: bounds the number of compiled executables.
DEPTH_CLASS_THRESHOLDS = (0, 3, 7)


def depth_group_indices(root_lut_np, lut, classes, ifindex, ip_words, idx):
    """Host-side depth-class binning shared by the classifier's
    v6_depth_groups and the bench's steered split: returns
    [(class_or_None, positions)] partitioning ``idx``; the last class is
    reported as None (full depth — untruncated executable).  Out-of-range
    ifindexes bin to class 0 (they resolve to the reserved null root
    whose subtree is empty)."""
    ifx = np.asarray(ifindex)[idx].astype(np.int64)
    ok = (ifx >= 0) & (ifx < len(root_lut_np))
    root = np.where(ok, root_lut_np[np.clip(ifx, 0, len(root_lut_np) - 1)], 0)
    nib0 = (
        np.asarray(ip_words)[idx, 0].astype(np.uint32) >> 16
    ).astype(np.int64)
    e0 = root * 65536 + nib0
    in0 = ok & (e0 >= 0) & (e0 < len(lut))
    pd = np.where(in0, lut[np.clip(e0, 0, len(lut) - 1)], 0)
    out = []
    prev = -1
    for c in classes:
        sub = idx[np.nonzero((pd > prev) & (pd <= c))[0]]
        prev = c
        if len(sub):
            out.append((None if c == classes[-1] else int(c), sub))
    return out


def depth_classes(n_levels: int):
    """The usable class list for a table of ``n_levels`` trie levels:
    thresholds below the full deep depth, plus the full depth."""
    full = n_levels - 1
    return tuple(t for t in DEPTH_CLASS_THRESHOLDS if t < full) + (full,)


def depth_class_histogram(tables: CompiledTables) -> np.ndarray:
    """(full_depth + 1,) root-slot counts per deep-level requirement —
    the depth histogram the steering thresholds are tuned against.
    Index d = number of DIR-16 slots whose subtree needs exactly d deep
    levels (build_depth_lut); slot mass is the available proxy for
    packet mass (the bench logs the per-class packet split so the
    recorded run shows both)."""
    lut = build_depth_lut(tables)
    full = max(len(tables.trie_levels) - 1, 0)
    return np.bincount(
        np.asarray(lut, np.int64), minlength=full + 1
    )[: full + 1]


def tune_depth_classes(tables: CompiledTables, max_classes: int = 4):
    """Depth-class thresholds tuned to THIS table's depth histogram
    instead of the static DEPTH_CLASS_THRESHOLDS (which were picked
    against the 100K bench table and under-split the 1M adversarial
    histogram — round-5 verdict ask #3): up to ``max_classes - 1``
    thresholds at equal-mass quantiles of the sub-full-depth slot mass,
    deduped, always ending with the full depth.  Degenerate histograms
    (no sub-full mass, single level) fall back to the static classes.
    Memoized on the tables instance (rides the build_depth_lut cache
    plus its own) — the classifier asks on every load."""
    cached = getattr(tables, "_depth_classes_cache", None)
    if cached is not None:
        return cached
    full = len(tables.trie_levels) - 1
    if full <= 0:
        return (max(full, 0),)
    hist = depth_class_histogram(tables).astype(np.float64)
    below = hist[:full]
    # depth 0 ("no deep levels") always gets its own class: it is the
    # cheapest executable AND the dominant slot mass on real tables, so
    # quantiles are computed over the REMAINING (depth >= 1) mass — a
    # depth-0-dominated histogram would otherwise collapse every
    # threshold to 0 and leave the full class covering depths 1..full.
    mass = below[1:].sum()
    if mass <= 0:
        result = depth_classes(len(tables.trie_levels))
    else:
        cum = np.cumsum(below[1:]) / mass  # cum[i] = mass at depth <= i+1
        picks = {0}
        n_thresh = max(max_classes - 2, 1)
        for k in range(1, n_thresh + 1):
            q = k / (n_thresh + 1)
            d = 1 + int(np.searchsorted(cum, q))
            if 0 < d < full:
                picks.add(d)
        result = tuple(sorted(picks)) + (full,)
    try:
        object.__setattr__(tables, "_depth_classes_cache", result)
    except (AttributeError, TypeError):
        pass
    return result


def v4_trie_depth(n_levels: int) -> int:
    """Number of leading trie levels whose bit boundary is within the IPv4
    packet-side cap (32 bits): entries longer than /32 can never match a
    v4 packet (kernel.c:207), so a v4-only batch walks only these levels.
    With 16-8-8-... strides that is min(3, n_levels)."""
    strides = trie_level_strides(n_levels)
    depth, bit_end = 0, 0
    for s in strides:
        bit_end += s
        if bit_end > 32:
            break
        depth += 1
    return max(1, depth)


def classify_wire(
    tables: DeviceTables, wire: jax.Array, *, use_trie: bool,
    v4_only: bool = False, depth: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Wire-format forward pass: packed descriptors in, (results_u16,
    stats) out.  The D2H payload is 2B/packet — ruleId ≤ 255 always holds
    (MAX_RULES_PER_TARGET=100), and the XDP verdict is host-derivable from
    (results, kind), so neither the u32 results nor the xdp array crosses
    the link.

    ``v4_only`` is the depth-specialization fast path: when the caller
    guarantees the batch holds no IPv6 packets, the trie walk is truncated
    to the levels reachable under the 32-bit cap — a /128-deep table walks
    3 gathers instead of 15.  ``depth`` is the v6 analogue (depth-class
    steering, build_depth_lut): the caller guarantees every packet's root
    slot needs at most ``depth`` DEEP levels, so the walk keeps
    trie_levels[:1+depth].  The truncated level tuple changes the pytree
    structure, so jit compiles a separate (cheaper) executable."""
    if use_trie and v4_only:
        d = v4_trie_depth(len(tables.trie_levels))
        tables = tables._replace(trie_levels=tables.trie_levels[:d])
    elif use_trie and depth is not None:
        tables = tables._replace(
            trie_levels=tables.trie_levels[: 1 + depth])
    res, _xdp, stats = classify(tables, unpack_wire(wire), use_trie=use_trie)
    return res.astype(jnp.uint16), stats


def check_wire_ruleids(tables: CompiledTables) -> None:
    """The wire result is (ruleId<<8 | action) cast to uint16, so ruleIds
    must fit in 8 bits.  Syncer-compiled tables always satisfy this
    (ruleId == order < MAX_RULES_PER_TARGET), but
    compile_tables_from_content accepts adversarial direct content where
    rid goes up to 2^24 — fail loudly at load time instead of silently
    corrupting reported ruleIds (the analogue of the pallas rule_width
    guard in build_pallas_tables)."""
    max_rid = int(tables.rules[..., 0].max()) if tables.rules.size else 0
    if max_rid > 0xFF:
        raise ValueError(
            f"max ruleId {max_rid} > 255 does not fit the uint16 wire "
            "result; use the u32 (non-wire) classify path"
        )


@functools.lru_cache(maxsize=None)
def jitted_classify_wire(use_trie: bool, v4_only: bool = False,
                         depth: Optional[int] = None):
    return jax.jit(
        functools.partial(classify_wire, use_trie=use_trie,
                          v4_only=v4_only, depth=depth)
    )


def fuse_wire_outputs(res16: jax.Array, stats: jax.Array) -> jax.Array:
    """Pack (results_u16, stats_i32) into ONE int32 device buffer.

    Each D2H materialization is a separate RPC that pays the link's sync
    floor — ~90 ms per array on a tunneled deployment (measured) —
    so reading results and stats separately doubles the per-chunk latency
    for 24KB of stats.  Layout: ceil(B/2) words of u16-pair-packed
    results, then stats flattened; bitcast (not convert) so the high
    result's top bit survives the int32 view."""
    return jnp.concatenate([_pack_res16(res16), stats.reshape(-1)])


def split_wire_outputs(arr: np.ndarray, b: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host inverse of fuse_wire_outputs -> (results_u16[b], stats_i32)."""
    nw = (b + 1) // 2
    res16 = unpack_res16_host(arr[:nw], b)
    stats = arr[nw:].reshape(MAX_TARGETS, 6)
    return res16[:b], stats


@functools.lru_cache(maxsize=None)
def jitted_classify_wire_fused(use_trie: bool, v4_only: bool = False,
                               depth: Optional[int] = None):
    def f(tables: DeviceTables, wire: jax.Array) -> jax.Array:
        return fuse_wire_outputs(
            *classify_wire(tables, wire, use_trie=use_trie,
                           v4_only=v4_only, depth=depth)
        )

    return jax.jit(f)


def classify_wire_overlay(
    tables: DeviceTables,
    overlay: DeviceTables,
    wire: jax.Array,
    *,
    use_trie: bool,
    v4_only: bool = False,
    depth: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """classify_wire with the overlay combine (see classify_with_overlay);
    the v4/depth truncation applies to the main trie only."""
    if use_trie and v4_only:
        d = v4_trie_depth(len(tables.trie_levels))
        tables = tables._replace(trie_levels=tables.trie_levels[:d])
    elif use_trie and depth is not None:
        tables = tables._replace(
            trie_levels=tables.trie_levels[: 1 + depth])
    res, _xdp, stats = classify_with_overlay(
        tables, overlay, unpack_wire(wire), use_trie=use_trie
    )
    return res.astype(jnp.uint16), stats


@functools.lru_cache(maxsize=None)
def jitted_classify_wire_overlay_fused(use_trie: bool, v4_only: bool = False,
                                       depth: Optional[int] = None):
    def f(tables: DeviceTables, overlay: DeviceTables, wire: jax.Array):
        return fuse_wire_outputs(
            *classify_wire_overlay(
                tables, overlay, wire, use_trie=use_trie, v4_only=v4_only,
                depth=depth,
            )
        )

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def jitted_classify_with_overlay(use_trie: bool):
    return jax.jit(
        functools.partial(classify_with_overlay, use_trie=use_trie)
    )


def host_finalize_wire(res16: np.ndarray, kind: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side completion of the wire path: widen results to u32 and
    rebuild the XDP verdict exactly as finalize() does on device
    (kernel.c:423-455 — malformed DROP, deny DROP, else PASS)."""
    results = res16.astype(np.uint32)
    action = results & 0xFF
    xdp = np.where(
        kind == KIND_MALFORMED,
        XDP_DROP,
        np.where(action == DENY, XDP_DROP, XDP_PASS),
    ).astype(np.int32)
    return results, xdp


def packet_key_words(batch: DeviceBatch) -> jax.Array:
    """(B, 5) uint32 — [ifindex, ip word0..3]: the LPM key the kernel
    builds at kernel.c:206-212 / 292-295."""
    return jnp.concatenate(
        [batch.ifindex.astype(jnp.uint32)[:, None], batch.ip_words], axis=1
    )


def lpm_dense_scores(tables: DeviceTables, batch: DeviceBatch) -> jax.Array:
    """(B, T) compare-all LPM match scores: mask_len + 1 for matching
    non-padding entries within the packet-side cap (32 for v4, 128 for
    v6 — kernel.c:206-219), else 0.  The ONE dense-match implementation:
    both the single-chip path (lpm_dense) and the mesh rules-sharded
    partial (parallel.mesh._local_dense_partial) consume these scores, so
    a semantics change lands everywhere at once."""
    pkt = packet_key_words(batch)  # (B,5)
    diff = (pkt[:, None, :] ^ tables.key_words[None]) & tables.mask_words[None]
    match = jnp.all(diff == 0, axis=-1)  # (B,T)
    cap = jnp.where(batch.kind == KIND_IPV4, 32, 128)  # packet-side mask cap
    ok = match & (tables.mask_len[None] >= 0) & (tables.mask_len[None] <= cap[:, None])
    return jnp.where(ok, tables.mask_len[None] + 1, 0)  # (B,T)


def lpm_dense(tables: DeviceTables, batch: DeviceBatch) -> jax.Array:
    """Compare-all LPM: returns per-packet target index or -1."""
    score = lpm_dense_scores(tables, batch)
    tidx = jnp.argmax(score, axis=1).astype(jnp.int32)
    return jnp.where(jnp.max(score, axis=1) > 0, tidx, -1)


def _popcount32(x: jax.Array) -> jax.Array:
    """SWAR popcount on uint32 lanes (no native popcount in jnp) — 5
    vector ops, fused by XLA into the walk's per-level arithmetic."""
    x = x - ((x >> 1) & np.uint32(0x55555555))
    x = (x & np.uint32(0x33333333)) + ((x >> 2) & np.uint32(0x33333333))
    x = (x + (x >> 4)) & np.uint32(0x0F0F0F0F)
    return (x * np.uint32(0x01010101)) >> 24


def trie_walk(
    trie_levels, trie_targets: jax.Array, root_lut: jax.Array,
    batch: DeviceBatch
) -> jax.Array:
    """Poptrie walk (build_poptrie layout): the DIR-16 root level is one
    direct-indexed slot-row gather; every deeper level is ONE (18-word)
    node-row gather + bitmap-rank arithmetic — the child id is
    child_base + rank(nib) (implicit numbering, no pointer gather), and
    target hits record only a global index into ``trie_targets``,
    resolved with a single gather AFTER the walk.  Statically unrolled
    over the level count; no data-dependent control flow.  Returns the
    target index or -1.

    vs the previous slot-array walk: each deep level's gather now lands
    in an array ~30x smaller (nodes, not nodes x 256 slots), which is
    what the gather-bound walk's throughput follows; the rank math is
    ~60 cheap VPU ops per level.

    Targets at a level cover prefixes with mask_len in (prev_boundary,
    boundary]; the IPv4 packet-side cap (entries longer than /32 cannot
    match a v4 packet, kernel.c:207) is the boundary test
    ``bit_end <= cap_bits`` — boundaries are 16, 24, 32, 40, ... so 32
    always lands exactly on one."""
    strides = trie_level_strides(len(trie_levels))
    lut_size = root_lut.shape[0]
    if_ok = (batch.ifindex >= 0) & (batch.ifindex < lut_size)
    root = jnp.where(
        if_ok, jnp.take(root_lut, jnp.clip(batch.ifindex, 0, lut_size - 1)), 0
    )

    # -- level 0: direct-indexed DIR-16 root --------------------------------
    # OOB policy for every gather in the walk: indices are in-range by
    # construction (child ranks only reach allocated nodes; dead lanes
    # pin to 0; build_poptrie bounds the root level so e0 below cannot
    # wrap int32), and should a future build bug break that, the lane is
    # INVALIDATED — an explicit range mask forces it to UNDEF, i.e. XDP
    # PASS (deterministic, never a wrong-verdict read; note this default
    # is allow, matching the kernel's no-match semantics, kernel.c:453).
    nib0 = (batch.ip_words[:, 0] >> np.uint32(16)).astype(jnp.int32)
    e0 = root * 65536 + nib0
    in0 = (e0 >= 0) & (e0 < trie_levels[0].shape[0])
    rows0 = jnp.take(trie_levels[0], e0, axis=0, mode="clip")
    best0 = jnp.where(in0 & (rows0[:, 1] > 0), rows0[:, 1] - 1, -1)
    alive = in0 & (rows0[:, 0] > 0)  # child ids are stored +1 (0 = none)
    node = jnp.where(alive, rows0[:, 0] - 1, 0)

    cap_bits = jnp.where(batch.kind == KIND_IPV4, 32, 128)
    win = jnp.zeros_like(node, dtype=jnp.uint32)  # targets[0] sentinel
    widx8 = jnp.arange(8, dtype=jnp.int32)[None, :]

    bit_end = strides[0]
    for stride, tbl in zip(strides[1:], trie_levels[1:]):
        bit_start, bit_end = bit_end, bit_end + stride
        w32 = bit_start // 32
        shift = 32 - stride - (bit_start % 32)
        nib = (
            (batch.ip_words[:, w32] >> np.uint32(shift))
            & np.uint32((1 << stride) - 1)
        ).astype(jnp.int32)
        in_l = (node >= 0) & (node < tbl.shape[0])
        alive = alive & in_l
        r = jnp.take(tbl, node, axis=0, mode="clip")
        w = (nib >> 5)[:, None]          # bitmap word 0..7
        below = (np.uint32(1) << (nib & 31).astype(jnp.uint32)) - 1
        cb = r[:, 2:10]
        tb = r[:, 10:18]
        pc_cb = _popcount32(cb)
        pc_tb = _popcount32(tb)
        prefix = jnp.sum(jnp.where(widx8 < w, pc_cb, 0), axis=1)
        tprefix = jnp.sum(jnp.where(widx8 < w, pc_tb, 0), axis=1)
        cw = jnp.sum(jnp.where(widx8 == w, cb, 0), axis=1)
        tw = jnp.sum(jnp.where(widx8 == w, tb, 0), axis=1)
        bit = (nib & 31).astype(jnp.uint32)
        ok_t = (
            alive
            & (((tw >> bit) & 1) > 0)
            & (bit_end <= cap_bits)
        )
        win = jnp.where(
            ok_t, r[:, 1] + tprefix + _popcount32(tw & below), win
        )
        alive = alive & (((cw >> bit) & 1) > 0)
        node = jnp.where(
            alive, (r[:, 0] + prefix + _popcount32(cw & below)).astype(jnp.int32), 0
        )
    win = win.astype(jnp.int32)
    in_w = (win >= 0) & (win < trie_targets.shape[0])
    tval = jnp.take(trie_targets, win, mode="clip")
    return jnp.where(in_w & (tval > 0), tval - 1, best0)


def lpm_trie(tables: DeviceTables, batch: DeviceBatch) -> jax.Array:
    return trie_walk(
        tables.trie_levels, tables.trie_targets, tables.root_lut, batch
    )


def trie_walk_joined(
    trie_levels, joined: jax.Array, root_lut: jax.Array, batch: DeviceBatch
) -> jax.Array:
    """The poptrie walk with the joined-targets tail: identical level
    loop to trie_walk, but the win is a POSITION that indexes ``joined``
    directly (level-0's target column was rewritten to appended joined
    rows by build_joined), so target resolve + rules fetch collapse into
    ONE fat-row gather.  Returns the (B, W) joined rows; row 0 / invalid
    lanes read all-zero (-> ruleId 0 -> UNDEF)."""
    strides = trie_level_strides(len(trie_levels))
    lut_size = root_lut.shape[0]
    if_ok = (batch.ifindex >= 0) & (batch.ifindex < lut_size)
    root = jnp.where(
        if_ok, jnp.take(root_lut, jnp.clip(batch.ifindex, 0, lut_size - 1)), 0
    )
    nib0 = (batch.ip_words[:, 0] >> np.uint32(16)).astype(jnp.int32)
    e0 = root * 65536 + nib0
    in0 = (e0 >= 0) & (e0 < trie_levels[0].shape[0])
    rows0 = jnp.take(trie_levels[0], e0, axis=0, mode="clip")
    best0 = jnp.where(in0 & (rows0[:, 1] > 0), rows0[:, 1], 0)
    alive = in0 & (rows0[:, 0] > 0)
    node = jnp.where(alive, rows0[:, 0] - 1, 0)

    cap_bits = jnp.where(batch.kind == KIND_IPV4, 32, 128)
    win = jnp.zeros_like(node, dtype=jnp.uint32)
    widx8 = jnp.arange(8, dtype=jnp.int32)[None, :]

    bit_end = strides[0]
    for stride, tbl in zip(strides[1:], trie_levels[1:]):
        bit_start, bit_end = bit_end, bit_end + stride
        w32 = bit_start // 32
        shift = 32 - stride - (bit_start % 32)
        nib = (
            (batch.ip_words[:, w32] >> np.uint32(shift))
            & np.uint32((1 << stride) - 1)
        ).astype(jnp.int32)
        in_l = (node >= 0) & (node < tbl.shape[0])
        alive = alive & in_l
        r = jnp.take(tbl, node, axis=0, mode="clip")
        w = (nib >> 5)[:, None]
        below = (np.uint32(1) << (nib & 31).astype(jnp.uint32)) - 1
        cb = r[:, 2:10]
        tb = r[:, 10:18]
        pc_cb = _popcount32(cb)
        pc_tb = _popcount32(tb)
        prefix = jnp.sum(jnp.where(widx8 < w, pc_cb, 0), axis=1)
        tprefix = jnp.sum(jnp.where(widx8 < w, pc_tb, 0), axis=1)
        cw = jnp.sum(jnp.where(widx8 == w, cb, 0), axis=1)
        tw = jnp.sum(jnp.where(widx8 == w, tb, 0), axis=1)
        bit = (nib & 31).astype(jnp.uint32)
        ok_t = (
            alive
            & (((tw >> bit) & 1) > 0)
            & (bit_end <= cap_bits)
        )
        win = jnp.where(
            ok_t, r[:, 1] + tprefix + _popcount32(tw & below), win
        )
        alive = alive & (((cw >> bit) & 1) > 0)
        node = jnp.where(
            alive, (r[:, 0] + prefix + _popcount32(cw & below)).astype(jnp.int32), 0
        )
    win = win.astype(jnp.int32)
    pos = jnp.where(win > 0, win, best0)
    in_p = (pos > 0) & (pos < joined.shape[0])
    rows = jnp.take(joined, jnp.clip(pos, 0, joined.shape[0] - 1), axis=0,
                    mode="clip")
    return jnp.where(in_p[:, None], rows, 0)


def joined_rule_rows(rows: jax.Array) -> jax.Array:
    """(B, W) joined rows -> (B, R, C) scan operand."""
    if rows.dtype == jnp.uint16:
        return rows[:, 3:].reshape(rows.shape[0], -1, 5)
    return rows[:, 2:].reshape(rows.shape[0], -1, 7)


def rule_scan(rows: jax.Array, batch: DeviceBatch) -> jax.Array:
    """Vectorized ordered first-match scan (kernel.c:222-258).

    rows: (B, R, 5) uint16 packed (pack_rules_u16 — the resident form
    for in-range tables, halving the gather bytes that dominate this
    path) or (B, R, 7) int32 — already gathered (zeroed for
    no-LPM-match packets, which then yield ruleId==0 everywhere ->
    UNDEF).

    Perf note (the single biggest lever on this path): the first-match
    select is a min-index + masked-sum, NOT take_along_axis.  On TPU the
    composed classify with a take_along_axis select runs at ~34 M pkts/s
    at 100K CIDRs; the gather-free formulation of the exact same scan
    runs at ~311 M/s (measured on v5e, 628K-packet shard) — XLA fuses
    the masked reduction into the hit computation, while the per-lane
    gather forces a separate materialize-and-gather pass.  The scan also
    runs in (R, B) orientation so packets ride the 128-wide vector lanes;
    the transpose folds into the preceding rules gather."""
    if rows.shape[-1] == 5:
        s = jnp.transpose(rows.astype(jnp.int32), (2, 1, 0))  # (5, R, B)
        rid = s[0] & 0xFF
        act = s[0] >> 8
        rproto = s[1] & 0xFF
        it = s[1] >> 8
        ic = s[2]
        ps = s[3]
        pe = s[4]
    else:
        s = jnp.transpose(rows, (2, 1, 0))  # (7, R, B): field, rule, packet
        rid, rproto, ps, pe, it, ic, act = (s[i] for i in range(7))

    proto = batch.proto[None, :]
    dport = batch.dst_port[None, :]
    valid = rid != 0
    proto_eq = (rproto != 0) & (rproto == proto)
    is_transport = (
        (rproto == IPPROTO_TCP) | (rproto == IPPROTO_UDP) | (rproto == IPPROTO_SCTP)
    )
    port_hit = jnp.where(
        pe == 0, dport == ps, (dport >= ps) & (dport < pe)
    )
    fam = jnp.where(batch.kind == KIND_IPV4, IPPROTO_ICMP, IPPROTO_ICMPV6)[None, :]
    icmp_hit = (
        (rproto == fam)
        & (it == batch.icmp_type[None, :])
        & (ic == batch.icmp_code[None, :])
    )
    catch_all = rproto == 0
    hit = valid & ((proto_eq & ((is_transport & port_hit) | icmp_hit)) | catch_all)

    R = rid.shape[0]
    idx = jnp.arange(R, dtype=jnp.int32)[:, None]
    first = jnp.min(jnp.where(hit, idx, R), axis=0)
    any_hit = first < R
    sel = hit & (idx == first[None, :])
    rid_f = jnp.sum(jnp.where(sel, rid, 0), axis=0)
    act_f = jnp.sum(jnp.where(sel, act, 0), axis=0)
    result = jnp.where(
        any_hit,
        ((rid_f.astype(jnp.uint32) & 0xFFFFFF) << 8) | (act_f.astype(jnp.uint32) & 0xFF),
        0,
    )
    return result.astype(jnp.uint32)


def result_stats(result: jax.Array, batch: DeviceBatch) -> jax.Array:
    """(MAX_TARGETS, STATS_COLS) int32 per-batch statistics from PACKED
    results (kernel.c:361-400: allow/deny only, ruleId < MAX_TARGETS) —
    the stats half of finalize, exposed so the resident fused step can
    derive statistics from the MERGED flow-hit/stateless verdict vector
    on device (the in-program twin of daemon.stats_from_results; the
    host merge is jaxpath.merge_stats_host either way)."""
    is_ip = (batch.kind == KIND_IPV4) | (batch.kind == KIND_IPV6)
    action = (result & 0xFF).astype(jnp.int32)
    rule_id = ((result >> 8) & 0xFFFFFF).astype(jnp.int32)
    allow = (action == ALLOW) & is_ip
    deny = (action == DENY) & is_ip
    recorded = (allow | deny) & (rule_id < MAX_TARGETS)
    sid = jnp.where(recorded, rule_id, MAX_TARGETS)
    ln = batch.pkt_len
    hi = (ln >> 8) & 0xFFFFFF
    lo = ln & 0xFF
    a = allow.astype(jnp.int32)
    d = deny.astype(jnp.int32)
    data = jnp.stack([a, a * hi, a * lo, d, d * hi, d * lo], axis=1)  # (B,6)
    stats = jax.ops.segment_sum(data, sid, num_segments=MAX_TARGETS + 1)[:MAX_TARGETS]
    return stats.astype(jnp.int32)


def finalize(result: jax.Array, batch: DeviceBatch) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Ethertype/kind dispatch and stats (kernel.c:412-457, 361-400).

    Returns (results, xdp, stats) where stats is (MAX_TARGETS, STATS_COLS)
    int32 per-batch sums."""
    is_ip = (batch.kind == KIND_IPV4) | (batch.kind == KIND_IPV6)
    looked_up = is_ip & (batch.l4_ok != 0)
    result = jnp.where(looked_up, result, 0).astype(jnp.uint32)

    action = (result & 0xFF).astype(jnp.int32)

    xdp = jnp.where(
        batch.kind == KIND_MALFORMED,
        XDP_DROP,
        jnp.where(is_ip & (action == DENY), XDP_DROP, XDP_PASS),
    ).astype(jnp.int32)

    return result, xdp, result_stats(result, batch)


def gather_rule_rows(rules: jax.Array, tidx: jax.Array) -> jax.Array:
    """Per-packet rule rows for the scan: (B, R, C) from either the
    flattened 2D device layout (fast-gather form, see DeviceTables.rules)
    or a 3D (T, R, C) layout (mesh shards).  No-LPM-match packets get
    all-zero rows -> ruleId 0 everywhere -> UNDEF."""
    rows = jnp.take(rules, jnp.clip(tidx, 0), axis=0)
    if rows.ndim == 2:
        c = 5 if rows.dtype == jnp.uint16 else 7
        rows = rows.reshape(rows.shape[0], -1, c)
    return jnp.where((tidx >= 0)[:, None, None], rows, 0)


def _raw_result_and_score(
    tables: DeviceTables, batch: DeviceBatch, *, use_trie: bool
) -> Tuple[jax.Array, jax.Array]:
    """(raw scan result, LPM score) where score = mask_len + 1 of the
    winning entry (0 = no match) — the combine key for the overlay path
    (equal scores are impossible across disjoint tables: same mask_len
    matching one packet implies the same masked prefix, and identities
    are deduplicated at compile/routing time)."""
    if use_trie and tables.joined.shape[0] > 1:
        rows = trie_walk_joined(
            tables.trie_levels, tables.joined, tables.root_lut, batch
        )
        if rows.dtype == jnp.uint16:
            matched = (rows[:, 0].astype(jnp.int32)
                       | (rows[:, 1].astype(jnp.int32) << 16)) > 0
            ml = rows[:, 2].astype(jnp.int32)
        else:
            matched = rows[:, 0] > 0
            ml = rows[:, 1]
        score = jnp.where(matched, ml + 1, 0)
        return rule_scan(joined_rule_rows(rows), batch), score
    if use_trie:
        tidx = lpm_trie(tables, batch)
    else:
        tidx = lpm_dense(tables, batch)
    ml = jnp.take(tables.mask_len, jnp.clip(tidx, 0), mode="clip")
    score = jnp.where(tidx >= 0, ml + 1, 0)
    return rule_scan(gather_rule_rows(tables.rules, tidx), batch), score


def classify(
    tables: DeviceTables, batch: DeviceBatch, *, use_trie: bool
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full forward pass: LPM -> gather rules -> scan -> finalize."""
    if use_trie and tables.joined.shape[0] > 1:
        # one-gather tail: the walk's win position returns the rules row
        rows = trie_walk_joined(
            tables.trie_levels, tables.joined, tables.root_lut, batch
        )
        result = rule_scan(joined_rule_rows(rows), batch)
        return finalize(result, batch)
    if use_trie:
        tidx = lpm_trie(tables, batch)
    else:
        tidx = lpm_dense(tables, batch)
    result = rule_scan(gather_rule_rows(tables.rules, tidx), batch)
    return finalize(result, batch)


def classify_with_overlay(
    tables: DeviceTables,
    overlay: DeviceTables,
    batch: DeviceBatch,
    *,
    use_trie: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Main-table classify combined with a SMALL dense overlay table —
    the structural-update fast path (the Map.Update analogue for CIDR
    ADDS, loader.go:200-218): new keys land in the overlay (a dense
    compare over <= a few hundred entries, uploaded in kilobytes) so the
    main trie's device form is untouched; the longest-prefix winner
    across both tables is selected by mask_len score.  Equal scores
    cannot occur (the router keeps identities disjoint), so strict
    greater-than gives the overlay exactly kernel-LPM semantics."""
    raw_m, score_m = _raw_result_and_score(tables, batch, use_trie=use_trie)
    raw_o, score_o = _raw_result_and_score(overlay, batch, use_trie=False)
    result = jnp.where(score_o > score_m, raw_o, raw_m)
    return finalize(result, batch)


@functools.lru_cache(maxsize=None)
def jitted_classify(use_trie: bool):
    """Compiled classify entry point; cache keyed on the static config
    (the trie level count is part of the DeviceTables pytree structure,
    so jit re-specializes per table depth automatically).  Always use
    this (never eager) — op-by-op dispatch is orders of magnitude slower
    than the fused XLA program."""
    return jax.jit(functools.partial(classify, use_trie=use_trie))


def merge_stats_host(stats: np.ndarray) -> np.ndarray:
    """Device (MAX_TARGETS, 6) int32 -> host (MAX_TARGETS, 4) int64
    [allow_pkts, allow_bytes, deny_pkts, deny_bytes]."""
    s = stats.astype(np.int64)
    out = np.zeros((stats.shape[0], 4), np.int64)
    out[:, 0] = s[:, 0]
    out[:, 1] = s[:, 1] * 256 + s[:, 2]
    out[:, 2] = s[:, 3]
    out[:, 3] = s[:, 4] * 256 + s[:, 5]
    return out


# === multi-tenant paged table arena ==========================================
#
# The capacity-scaling layer (ISSUE-10): thousands of tenant rulesets
# share ONE preallocated HBM pool per layout family instead of one
# DeviceTables instance each.  Every family pool is divided into
# fixed-size SLABS (pages); a tenant's compiled table is baked into its
# slab with PAGE-GLOBAL indices (child/target pointers, joined
# positions, root ids all offset by the slab base at write time), so
# the classify kernels index one flat pool and the per-packet tenant
# column steers only the ENTRY — the same way ingress_ifindex steers
# the LPM root today.  Consequences:
#
# - one classify batch carries mixed-tenant traffic (the tenant column
#   picks each packet's slab base through the device-resident
#   tenant -> page table);
# - tenant activation / hot-swap is a page-table ROW FLIP (one 1-row
#   scatter, pre-warmed like the txn ladder) instead of a full table
#   re-upload;
# - the incremental patch machinery applies PER SLAB unchanged: a
#   rules-only tenant edit is the usual joined/dense row scatter with
#   positions offset by the slab base, through the same capped/fused
#   executables (_capped_scatter / txn_scatter) the single-table path
#   warms.
#
# Two families: "dense" (compare-all slabs — also the overlay side-pool)
# and "ctrie" (the path/level-compressed poptrie, whose ONE merged node
# array is what makes slab paging natural: the descent loop
# (_ctrie_descend) is shared verbatim with the single-table walk).

#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_PAGEFLIP_BUG env var), ArenaAllocator.activate skips the
#: device page-table row flip after a tenant swap — the host-side
#: registry believes the swap landed while the device keeps serving the
#: STALE slab.  The statecheck acceptance gate (tools/infw_lint.py
#: state --inject-defect pageflip) proves the model checker catches
#: this via oracle divergence with a shrunk reproducer.  Never set in
#: production.
_INJECT_PAGEFLIP_BUG = False


def _inject_pageflip_bug() -> bool:
    if _INJECT_PAGEFLIP_BUG:
        return True
    env = os.environ.get("INFW_INJECT_PAGEFLIP_BUG", "")
    return env not in ("", "0", "false", "no")


#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_COWLEAK_BUG env var), the copy-on-write clone path of
#: ArenaAllocator.load_tenant "forgets" the donor page's refcount
#: decrement after flipping the editing tenant onto its private clone —
#: the classic CoW leak (the donor page can never drop to zero and be
#: reclaimed).  The statecheck acceptance gate (tools/infw_lint.py
#: state --inject-defect cowleak, on the shared-then-edited-biased
#: "arena-cow" config) proves check_arena's refcount/aliasing
#: invariants catch it with a shrunk reproducer.  Never set in
#: production.
_INJECT_COWLEAK_BUG = False


def _inject_cowleak_bug() -> bool:
    if _INJECT_COWLEAK_BUG:
        return True
    env = os.environ.get("INFW_INJECT_COWLEAK_BUG", "")
    return env not in ("", "0", "false", "no")


#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_SPLICELEAK_BUG env var), the unsplice path of
#: ArenaAllocator._splice_edit "forgets" the old subtree plane's
#: refcount decrement after repointing the editing tenant's splice row
#: at its private (or re-merged) plane — the subtree-granular CoW leak
#: (the shared plane can never drop to zero and be reclaimed).  The
#: statecheck acceptance gate (tools/infw_lint.py state --inject-defect
#: spliceleak, on the near-copy-biased "arena-splice" config) proves
#: check_arena's splice refcount invariants catch it with a shrunk
#: reproducer.  Never set in production.
_INJECT_SPLICELEAK_BUG = False


def _inject_spliceleak_bug() -> bool:
    if _INJECT_SPLICELEAK_BUG:
        return True
    env = os.environ.get("INFW_INJECT_SPLICELEAK_BUG", "")
    return env not in ("", "0", "false", "no")


#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_COWRACE_BUG env var), ArenaAllocator._cow_install defers
#: the CoW donor's refcount decrement past the allocator lock release —
#: load_tenant then lands it as an UNLOCKED read-modify-write (with an
#: explicit sched_point in the window), so a concurrently locked
#: decrement (destroy_tenant / dedup_sweep) can interleave between the
#: read and the write-back and be lost.  The schedcheck acceptance gate
#: (tools/infw_lint.py sched --inject-defect cowrace) proves the
#: deterministic interleaving explorer finds the race, ddmin-shrinks
#: the schedule, and check_arena's cowleak refcount invariant names the
#: stale page.  Never set in production.
_INJECT_COWRACE_BUG = False


def _inject_cowrace_bug() -> bool:
    if _INJECT_COWRACE_BUG:
        return True
    env = os.environ.get("INFW_INJECT_COWRACE_BUG", "")
    return env not in ("", "0", "false", "no")


#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_CLAMPGATHER_BUG env var), arena_ctrie_rows skips the
#: ``& _SPLICE_PAGE_MASK`` decode of spliced page-table rows — the
#: bank bit (bit 30) leaks into the page id, so ``pg0 * SL + ifindex``
#: indexes the root lut out of bounds for any spliced tenant.  The
#: static bounds verifier's acceptance gate (tools/infw_lint.py bounds
#: --inject-defect clampgather) proves abstract interpretation flags
#: the unclamped gather and concretizes a diverging boundary witness.
#: TRACE-time flag: must be set before the entrypoint is first traced
#: (the acceptance gate runs it in a fresh process).  Never set in
#: production.
_INJECT_CLAMPGATHER_BUG = False


def _inject_clampgather_bug() -> bool:
    if _INJECT_CLAMPGATHER_BUG:
        return True
    env = os.environ.get("INFW_INJECT_CLAMPGATHER_BUG", "")
    return env not in ("", "0", "false", "no")


class ArenaCapacityError(ValueError):
    """A tenant table does not fit the arena's slab geometry (entries,
    node rows, trie depth, rule width, lut span) or the pool is out of
    free pages.  Callers either re-size the arena (a new pool
    generation) or refuse the tenant — never silently truncate."""


class _PlaneCapacityError(ArenaCapacityError):
    """The subtree plane pool is exhausted.  Internal: the decomposed
    install/stage paths catch it and fall back to the whole-slab path
    (degrade to flat slabs, never refuse the tenant); page exhaustion
    keeps raising plain ArenaCapacityError."""


#: Splice-indirect walk encoding (ISSUE-17).  A spliced l0 slot stores
#: SPLICE_TAG + slot_id in column 0 (instead of root-node-id + 1): the
#: walk entry resolves the slot through the tenant's splice-table rows
#: to a shared plane id, and descends from that plane's root row in the
#: appended plane pool region.  The tag bit doubles as the page-table
#: BANK bit: a spliced arena's page-table rows encode
#: ``page | bank << 30`` so one 1-row flip switches a tenant's page AND
#: its (double-buffered) splice-table bank atomically.
SPLICE_TAG = np.int32(1 << 30)
_SPLICE_BANK_SHIFT = 30
_SPLICE_PAGE_MASK = (1 << 30) - 1


class ArenaSpec(NamedTuple):
    """Geometry of one paged arena (a layout family's pool).  All row
    counts are PER SLAB; device pools are ``pages`` slabs, flat along
    rows.  Constructed via make_arena_spec (which buckets/validates) —
    the raw constructor is for tests."""

    family: str        # "dense" | "ctrie"
    pages: int
    max_tenants: int
    entries: int       # dense-entry capacity per slab (T)
    rule_slots: int    # packed rules per entry (row width = rule_slots*5)
    lut_rows: int      # root_lut rows per slab (max ifindex + 1 bound)
    root_nodes: int    # ctrie DIR-16 root nodes per slab (R0)
    node_rows: int     # ctrie merged skip-node rows per slab (SN)
    target_rows: int   # ctrie flat target rows per slab (ST)
    d_max: int         # static descent unroll bound (pool-wide)
    # -- structural (subtree-splice) compression geometry (ISSUE-17) ---------
    # zero everywhere -> a plain (unspliced) arena; ctrie-only.  The
    # plane pool is APPENDED to the slab pools (rows pages*SN .. on),
    # so every resident index stays pool-global and the walk kernels
    # never branch on "slab vs plane".
    plane_slots: int = 0        # shared subtree planes in the pool (PP)
    plane_node_rows: int = 0    # skip-node rows per plane (SNP)
    plane_target_rows: int = 0  # target rows per plane (STP)
    plane_joined_rows: int = 0  # joined rows per plane (SJP, row 0 unused)
    splice_slots: int = 0       # splice-table rows per tenant slab (K)

    @property
    def joined_rows(self) -> int:
        """Per-slab joined rows: tidx+1 indexing plus the slab's own
        zero sentinel row."""
        return self.entries + 1

    @property
    def l0_rows(self) -> int:
        return self.root_nodes * 65536

    @property
    def spliced(self) -> bool:
        """True when the arena factors shared subtrees into the
        refcounted plane pool and reads them through the per-tenant
        splice table."""
        return self.plane_slots > 0 and self.splice_slots > 0

    @property
    def splice_rows(self) -> int:
        """Device splice-table rows: two banks (double-buffered per
        tenant so a splice-map update lands atomically with the
        page-table flip) of max_tenants * splice_slots."""
        if not self.spliced:
            return 1  # degenerate placeholder array
        return 2 * self.max_tenants * self.splice_slots


def make_arena_spec(
    family: str,
    pages: int,
    max_tenants: int,
    entries: int,
    rule_slots: int,
    lut_rows: int = 8,
    root_nodes: int = 1,
    node_rows: int = 128,
    target_rows: int = 64,
    d_max: int = 6,
    plane_slots: int = 0,
    plane_node_rows: int = 0,
    plane_target_rows: int = 0,
    plane_joined_rows: int = 0,
    splice_slots: int = 0,
) -> ArenaSpec:
    """Normalize + validate an arena geometry: row counts bucket to the
    shared scatter-ladder shapes (node rows additionally to 128-row
    tiles for the Pallas byte planes), and the pool must satisfy the
    capped-scatter budget (a full-slab write is <= pool/4 rows, i.e.
    pages >= 4) and the int32 DIR-16 indexing bound.  Non-zero splice
    geometry (ctrie-only) appends a ``plane_slots``-deep shared subtree
    plane pool and a two-bank per-tenant splice table."""
    if family not in ("dense", "ctrie"):
        raise ValueError(f"unknown arena family {family!r}")
    if pages < 4:
        raise ValueError(
            f"arena needs >= 4 pages (full-slab writes ride the capped "
            f"scatter budget of pool/4 rows); got {pages}"
        )
    if max_tenants < 1 or entries < 1 or rule_slots < 1:
        raise ValueError("max_tenants, entries and rule_slots must be >= 1")
    entries = _row_bucket(entries)
    lut_rows = _row_bucket(lut_rows)
    target_rows = _row_bucket(target_rows)
    node_rows = -(-max(node_rows, 128) // 128) * 128
    if family == "ctrie" and pages * root_nodes * 65536 > np.iinfo(np.int32).max:
        raise ValueError(
            f"arena l0 pool {pages}x{root_nodes} root nodes exceeds int32 "
            "DIR-16 indexing"
        )
    splicey = (plane_slots, plane_node_rows, plane_target_rows,
               plane_joined_rows, splice_slots)
    if any(v < 0 for v in splicey):
        raise ValueError("splice geometry fields must be >= 0")
    if any(splicey):
        if family != "ctrie":
            raise ValueError("subtree-splice compression is ctrie-only")
        if not all(splicey):
            raise ValueError(
                "splice geometry is all-or-nothing: plane_slots, "
                "plane_node_rows, plane_target_rows, plane_joined_rows "
                "and splice_slots must all be > 0"
            )
        # plane rows bucket to small multiples of 8 (they ride the same
        # warmed fused scatter; no 128-row tiling needed — the Pallas
        # byte planes pad the POOL TOTAL to 128 rows internally)
        r8 = lambda x: -(-int(x) // 8) * 8
        plane_node_rows = r8(plane_node_rows)
        plane_target_rows = r8(plane_target_rows)
        plane_joined_rows = r8(plane_joined_rows)
        total_nodes = pages * node_rows + plane_slots * plane_node_rows
        if total_nodes + 1 >= int(SPLICE_TAG):
            raise ValueError(
                f"node pool {total_nodes} rows collides with the splice "
                f"tag space (< {int(SPLICE_TAG)})"
            )
        if splice_slots >= int(SPLICE_TAG):
            raise ValueError("splice_slots exceeds the splice tag space")
    return ArenaSpec(
        family=family, pages=pages, max_tenants=max_tenants,
        entries=entries, rule_slots=rule_slots, lut_rows=lut_rows,
        root_nodes=root_nodes, node_rows=node_rows,
        target_rows=target_rows, d_max=d_max,
        plane_slots=plane_slots, plane_node_rows=plane_node_rows,
        plane_target_rows=plane_target_rows,
        plane_joined_rows=plane_joined_rows, splice_slots=splice_slots,
    )


def arena_spec_for(
    family: str,
    tables_iter,
    pages: int,
    max_tenants: int,
    headroom: float = 1.0,
    d_max: Optional[int] = None,
    **splice_kwargs,
) -> ArenaSpec:
    """Size an ArenaSpec from sample tenant tables: take per-family
    maxima over the samples, scaled by ``headroom``, then bucket via
    make_arena_spec.  The samples must be u16-packable (the arena's
    resident rule layout).  ``splice_kwargs`` (plane_slots,
    plane_node_rows, ...) pass through to make_arena_spec for spliced
    geometries."""
    ent = 1
    rs = 1
    lut = 1
    r0 = 1
    nn = 1
    tt = 1
    dm = 1
    for t in tables_iter:
        rules = _packed_rules_flat(t)
        if rules.dtype != np.uint16:
            raise ArenaCapacityError(
                "arena slabs hold u16-packed rules; a sample table has "
                "wide int32 values"
            )
        ent = max(ent, t.rules.shape[0])
        rs = max(rs, rules.shape[1] // 5)
        lut = max(lut, np.asarray(t.root_lut).shape[0])
        if family == "ctrie":
            l0, nodes, targets, d = build_cpoptrie(t)
            r0 = max(r0, l0.shape[0] // 65536)
            nn = max(nn, nodes.shape[0])
            tt = max(tt, targets.shape[0])
            dm = max(dm, d)
    h = lambda x: int(-(-x * headroom // 1))
    return make_arena_spec(
        family, pages, max_tenants,
        entries=h(ent), rule_slots=rs, lut_rows=h(lut), root_nodes=r0,
        node_rows=h(nn), target_rows=h(tt),
        d_max=d_max if d_max is not None else dm,
        **splice_kwargs,
    )


class DenseArena(NamedTuple):
    """Dense-family device pool: ``pages`` compare-all slabs flat along
    rows, plus the tenant -> page table.  Unassigned rows carry the
    mask_len == -1 sentinel (inert exactly like single-table padding);
    page_table rows are -1 for absent tenants."""

    key_words: jax.Array   # (P*S, 5) uint32
    mask_words: jax.Array  # (P*S, 5) uint32
    mask_len: jax.Array    # (P*S,) int32
    rules: jax.Array       # (P*S, R*5) uint16
    page_table: jax.Array  # (max_tenants,) int32


class CtrieArena(NamedTuple):
    """Ctrie-family device pool: per-slab compressed-poptrie layouts
    with PAGE-GLOBAL indices baked at slab-write time (node ids, target
    positions, joined positions, root ids), so the shared descent
    (_ctrie_descend) and the tail gathers run on the flat pools
    untouched.  Pool row 0 of ``targets``/``joined`` doubles as the
    global sentinel (page 0's slab sentinel — all slabs keep their
    local row 0 zero).

    Spliced geometries (spec.spliced) APPEND the shared subtree plane
    pool to ``nodes``/``targets``/``joined`` (plane_slots slabs of
    plane_*_rows each, starting at row pages*SN / pages*ST / pages*SJ)
    and carry the two-bank per-tenant ``splice`` table: row
    (bank*max_tenants + tenant)*K + slot holds the plane id serving
    that tenant's spliced l0 slot (-1 = unused).  Plane-internal
    indices are baked pool-global exactly like slab indices, so the
    descent and tail gathers stay splice-oblivious; only the l0 entry
    resolves through the indirection."""

    l0: jax.Array          # (P*R0*65536, 2) int32
    nodes: jax.Array       # (P*SN [+ PP*SNP], 20) uint32
    targets: jax.Array     # (P*ST [+ PP*STP],) int32 global joined positions
    joined: jax.Array      # (P*(S+1) [+ PP*SJP], 3+R*5) uint16
    root_lut: jax.Array    # (P*SL,) int32 global root ids
    splice: jax.Array      # (2*max_tenants*K,) int32 plane ids, -1 unused
    page_table: jax.Array  # (max_tenants,) int32 (spliced: page|bank<<30)


# -- slab baking (host) ------------------------------------------------------


def _dense_slab_arrays(spec: ArenaSpec, tables: CompiledTables):
    """Full-slab host arrays for the dense family (page-offset-free:
    dense slabs carry no cross-row indices).  Raises ArenaCapacityError
    when the table exceeds the slab geometry."""
    kw, mw, ml, rules, _lv, _tg, _lut, _j = _host_device_layout(
        tables, pad=False, with_trie=False
    )
    S = spec.entries
    if kw.shape[0] > S:
        raise ArenaCapacityError(
            f"tenant has {kw.shape[0]} entries > slab capacity {S}"
        )
    if rules.dtype != np.uint16:
        raise ArenaCapacityError("arena slabs hold u16-packed rules")
    if rules.shape[1] != spec.rule_slots * 5:
        raise ArenaCapacityError(
            f"rule row width {rules.shape[1]} != slab width "
            f"{spec.rule_slots * 5} (compile tenants with rule_width="
            f"{spec.rule_slots})"
        )
    return (
        _pad_rows(kw, S),
        _pad_rows(mw, S),
        _pad_rows(ml, S, fill=-1),
        _pad_rows(rules, S),
    )


def _ctrie_canonical_slab(spec: ArenaSpec, tables: CompiledTables):
    """Page-independent ("canonical") full-slab host arrays for the
    ctrie family: slab-local indices, zero padding — the form the
    content hash is computed over (identical rulesets bake to identical
    bytes regardless of which physical page they land on).  Returns
    (arrays, n_nodes); ``n_nodes`` is the real skip-node row count,
    needed because node-row offsets apply unconditionally to real rows
    (offsetting/un-offsetting is row-count-dependent).  Raises
    ArenaCapacityError when any per-slab bound is exceeded."""
    host = _ctrie_host_layout(tables)
    if host is None:
        raise ArenaCapacityError(
            "tenant table is not ctrie-eligible (wide int32 rules)"
        )
    (l0, nodes, targets, joined, root_lut), d_max = host
    if d_max > spec.d_max:
        raise ArenaCapacityError(
            f"tenant trie depth d_max={d_max} > arena unroll bound "
            f"{spec.d_max}"
        )
    n0 = l0.shape[0] // 65536
    if n0 > spec.root_nodes:
        raise ArenaCapacityError(
            f"{n0} root nodes > slab bound {spec.root_nodes}"
        )
    if nodes.shape[0] > spec.node_rows:
        raise ArenaCapacityError(
            f"{nodes.shape[0]} skip nodes > slab bound {spec.node_rows}"
        )
    if targets.shape[0] > spec.target_rows:
        raise ArenaCapacityError(
            f"{targets.shape[0]} targets > slab bound {spec.target_rows}"
        )
    if joined.shape[0] > spec.joined_rows:
        raise ArenaCapacityError(
            f"{joined.shape[0]} joined rows > slab bound "
            f"{spec.joined_rows}"
        )
    if joined.shape[1] != 3 + spec.rule_slots * 5:
        raise ArenaCapacityError(
            f"joined row width {joined.shape[1]} != slab width "
            f"{3 + spec.rule_slots * 5}"
        )
    if root_lut.shape[0] > spec.lut_rows:
        raise ArenaCapacityError(
            f"root_lut spans {root_lut.shape[0]} ifindexes > slab bound "
            f"{spec.lut_rows}"
        )
    l0b = np.zeros((spec.l0_rows, 2), np.int32)
    l0b[: l0.shape[0]] = l0
    nodesb = np.zeros((spec.node_rows, 20), np.uint32)
    nodesb[: nodes.shape[0]] = nodes.astype(np.uint32)
    tgtb = np.zeros(spec.target_rows, np.int32)
    tgtb[: targets.shape[0]] = targets.astype(np.int32)
    joinb = np.zeros((spec.joined_rows, joined.shape[1]), np.uint16)
    joinb[: joined.shape[0]] = joined
    lutb = np.zeros(spec.lut_rows, np.int32)
    lutb[: root_lut.shape[0]] = root_lut.astype(np.int32)
    return (l0b, nodesb, tgtb, joinb, lutb), int(nodes.shape[0])


def _offset_ctrie_slab(spec: ArenaSpec, arrays, n_nodes: int, page: int):
    """Canonical ctrie slab arrays -> the page's resident form: node
    ids += page*SN, target positions += page*ST, joined positions +=
    page*SJ, root ids += page*R0 (zero entries stay zero; real node
    rows offset unconditionally — hence ``n_nodes``).  Never mutates
    the canonical arrays."""
    l0, nodes, targets, joined, root_lut = arrays
    if page == 0:
        return l0, nodes, targets, joined, root_lut
    nb = page * spec.node_rows
    tb = page * spec.target_rows
    jb = page * spec.joined_rows
    rb = page * spec.root_nodes
    l0o = np.zeros_like(l0)
    # spliced l0 slots (SPLICE_TAG + slot) are slab-local slot ids
    # resolved through the tenant splice table — never page-offset
    tag = l0[:, 0] >= SPLICE_TAG
    l0o[:, 0] = np.where(
        tag, l0[:, 0], np.where(l0[:, 0] > 0, l0[:, 0] + nb, 0)
    )
    l0o[:, 1] = np.where(l0[:, 1] > 0, l0[:, 1] + jb, 0)
    nodeso = nodes.copy()
    nodeso[:n_nodes, 0] += np.uint32(nb)
    nodeso[:n_nodes, 1] += np.uint32(tb)
    tgto = np.where(targets > 0, targets + jb, 0).astype(np.int32)
    luto = (root_lut.astype(np.int64) + rb).astype(np.int32)
    return l0o, nodeso, tgto, joined, luto


def _unoffset_ctrie_slab(spec: ArenaSpec, arrays, n_nodes: int, page: int):
    """Inverse of _offset_ctrie_slab: a page's resident slab rows back
    to the canonical (page-independent) form — what the content hash
    and the CoW clone read from the host mirror."""
    l0, nodes, targets, joined, root_lut = arrays
    if page == 0:
        return l0, nodes, targets, joined, root_lut
    nb = page * spec.node_rows
    tb = page * spec.target_rows
    jb = page * spec.joined_rows
    rb = page * spec.root_nodes
    l0c = np.zeros_like(l0)
    tag = l0[:, 0] >= SPLICE_TAG
    l0c[:, 0] = np.where(
        tag, l0[:, 0], np.where(l0[:, 0] > 0, l0[:, 0] - nb, 0)
    )
    l0c[:, 1] = np.where(l0[:, 1] > 0, l0[:, 1] - jb, 0)
    nodesc = nodes.copy()
    nodesc[:n_nodes, 0] -= np.uint32(nb)
    nodesc[:n_nodes, 1] -= np.uint32(tb)
    tgtc = np.where(targets > 0, targets - jb, 0).astype(np.int32)
    lutc = (root_lut.astype(np.int64) - rb).astype(np.int32)
    return l0c, nodesc, tgtc, joined, lutc


def _ctrie_slab_arrays(spec: ArenaSpec, page: int, tables: CompiledTables):
    """Full-slab host arrays for the ctrie family with the page's
    GLOBAL offsets baked in (the canonical bake + the page offset pass).
    Raises ArenaCapacityError when any per-slab bound is exceeded."""
    arrays, n_nodes = _ctrie_canonical_slab(spec, tables)
    return _offset_ctrie_slab(spec, arrays, n_nodes, page)


def slab_content_hash(arrays, n_nodes: int = 0) -> bytes:
    """Canonical content hash of one baked slab: sha256 over the
    page-independent slab arrays' bytes (shape/dtype-framed) plus the
    real node-row count.  Hashing the BAKED arrays (not the spec) means
    two rulesets that compile to the same forwarding state dedup even
    when their specs differ cosmetically."""
    h = hashlib.sha256()
    h.update(str(int(n_nodes)).encode())
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(repr((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    return h.digest()


# -- structural (subtree) decomposition (host) --------------------------------


def _np_popcount_rows(bitmaps: np.ndarray) -> np.ndarray:
    """(n, 8) uint32 bitmap rows -> (n,) per-row set-bit counts."""
    b = np.ascontiguousarray(bitmaps.astype(np.uint32)).view(np.uint8)
    return np.unpackbits(b, axis=-1).sum(axis=1).astype(np.int64)


class _SpliceSub(NamedTuple):
    """One factored subtree of a decomposed ctrie slab: the mapping
    between the subtree's canonical PLANE form (plane-local indices,
    content-canonical bytes shared across tenants) and its footprint in
    the tenant's whole-slab canonical form.  ``node_rows``/``tpos`` are
    the ORIGINAL (ascending) slab row positions the subtree occupied;
    plane-local row i is node_rows[i] / tpos[i] (BFS emission order is
    monotone in node id, so the sorted restriction IS the subtree's own
    BFS order).  ``tidx`` is the sorted list of tidx+1 joined positions
    the subtree owns; plane-local joined row 1+j carries the original
    row bytes of tidx[j] (the self-indexed bytes are identical across
    rules-only-variant tenants, which is what makes planes shareable).
    ``dead_cb``/``dead_tb`` preserve the original base values of rows
    with zero children/targets (dead pointers are never descended but
    must round-trip bit-exactly for the whole-slab hash invariant)."""

    slot: int               # splice slot id (l0 slot order)
    e: int                  # l0 row of the subtree root's DIR-16 slot
    root: int               # original root node id
    node_rows: np.ndarray   # (n_local,) int64 ascending original node ids
    dead_cb: np.ndarray     # (n_local,) int64, -1 where child_base live
    dead_tb: np.ndarray     # (n_local,) int64, -1 where target_base live
    tpos: np.ndarray        # (n_t,) int64 ascending original target rows
    tidx: np.ndarray        # (n_j,) int64 ascending original tidx+1 values
    n_local: int            # real plane node rows
    plane: tuple            # (pnodes, ptargets, pjoined) canonical arrays
    phash: bytes            # slab_content_hash(plane, n_local)


def _decompose_one_subtree(spec, arrays, n_nodes, cc, tc, e, slot,
                           claimed_nodes, claimed_tidx, best0_tidx):
    """Try to factor the subtree rooted at l0 row ``e`` into one plane.
    Returns a _SpliceSub or None (doesn't fit the plane geometry, or
    overlaps an already-claimed/trunk-owned row — the subtree then
    stays resident in the trunk slab)."""
    l0, nodes, targets, joined, _root_lut = arrays
    snp = spec.plane_node_rows
    root = int(l0[e, 0]) - 1
    if root < 0 or root >= n_nodes:
        return None
    cb = nodes[:n_nodes, 0].astype(np.int64)
    tb = nodes[:n_nodes, 1].astype(np.int64)
    # BFS-collect the subtree's node rows (bounded by the plane size)
    rows: list = []
    seen: set = set()
    frontier = [root]
    while frontier:
        nxt: list = []
        for nid in frontier:
            if nid in seen or len(rows) >= snp:
                return None
            seen.add(nid)
            rows.append(nid)
            c = int(cc[nid])
            if c:
                b = int(cb[nid])
                if b < 0 or b + c > n_nodes:
                    return None
                nxt.extend(range(b, b + c))
        frontier = nxt
    if not rows:
        return None
    nr = np.array(sorted(rows), np.int64)
    if claimed_nodes[nr].any():
        return None
    # target rows owned by the subtree (contiguous per node)
    tl: list = []
    for nid in nr:
        t = int(tc[nid])
        if t:
            b = int(tb[nid])
            if b < 1 or b + t > targets.shape[0]:
                return None
            tl.extend(range(b, b + t))
    if len(tl) != len(set(tl)) or len(tl) > spec.plane_target_rows:
        return None
    tpos = np.array(sorted(tl), np.int64)
    tvals = targets[tpos].astype(np.int64) if len(tpos) else np.zeros(0, np.int64)
    live = tvals[tvals > 0]
    if len(set(live.tolist())) != len(live):
        return None
    tidx = np.unique(live)
    if len(tidx) + 1 > spec.plane_joined_rows:
        return None
    if len(tidx) and int(tidx.max()) >= joined.shape[0]:
        return None
    for v in tidx.tolist():
        if v in claimed_tidx or v in best0_tidx:
            return None
    # bake the canonical plane (plane-local indices)
    n_local = len(nr)
    pn = np.zeros((snp, 20), np.uint32)
    pn[:n_local] = nodes[nr]
    dead_cb = np.full(n_local, -1, np.int64)
    dead_tb = np.full(n_local, -1, np.int64)
    pos_of_node = {int(v): i for i, v in enumerate(nr)}
    pos_of_t = {int(v): i for i, v in enumerate(tpos)}
    for i, nid in enumerate(nr.tolist()):
        if int(cc[nid]):
            lb = pos_of_node.get(int(cb[nid]))
            if lb is None:
                return None
            pn[i, 0] = np.uint32(lb)
        else:
            dead_cb[i] = int(cb[nid])
            pn[i, 0] = 0
        if int(tc[nid]):
            lt = pos_of_t.get(int(tb[nid]))
            if lt is None:
                return None
            pn[i, 1] = np.uint32(lt)
        else:
            dead_tb[i] = int(tb[nid])
            pn[i, 1] = 0
    pt = np.zeros(spec.plane_target_rows, np.int32)
    for j, v in enumerate(tvals.tolist()):
        pt[j] = 0 if v <= 0 else 1 + int(np.searchsorted(tidx, v))
    pj = np.zeros((spec.plane_joined_rows, joined.shape[1]), np.uint16)
    for j, v in enumerate(tidx.tolist()):
        pj[1 + j] = joined[v]
    plane = (pn, pt, pj)
    return _SpliceSub(
        slot=slot, e=int(e), root=root, node_rows=nr, dead_cb=dead_cb,
        dead_tb=dead_tb, tpos=tpos, tidx=tidx, n_local=n_local,
        plane=plane, phash=slab_content_hash(plane, n_local),
    )


def _decompose_ctrie_slab(spec: ArenaSpec, arrays, n_nodes: int):
    """Factor a canonical ctrie slab into (trunk arrays, subtree metas):
    each factorable l0 subtree (fits the plane geometry, disjoint from
    every other factored subtree, owns none of the <=16-bit best0
    joined rows) moves to a canonical plane; its l0 slot becomes
    SPLICE_TAG + slot and its node/target/joined rows ZERO in the
    trunk (no renumbering — trunk bytes stay content-canonical, and
    structurally-identical tenants produce bit-identical trunks).
    Returns None when nothing factors (caller installs whole-slab)."""
    if not spec.spliced or n_nodes <= 0:
        return None
    l0, nodes, targets, joined, root_lut = arrays
    cc = _np_popcount_rows(nodes[:n_nodes, 4:12])
    tc = _np_popcount_rows(nodes[:n_nodes, 12:20])
    best0 = l0[:, 1]
    best0_tidx = set(int(v) for v in best0[best0 > 0].tolist())
    claimed_nodes = np.zeros(n_nodes, bool)
    claimed_tidx: set = set()
    metas: list = []
    for e in np.nonzero(l0[:, 0] > 0)[0].tolist():
        if len(metas) >= spec.splice_slots:
            break
        m = _decompose_one_subtree(
            spec, arrays, n_nodes, cc, tc, e, len(metas),
            claimed_nodes, claimed_tidx, best0_tidx,
        )
        if m is None:
            continue
        claimed_nodes[m.node_rows] = True
        claimed_tidx.update(m.tidx.tolist())
        metas.append(m)
    if not metas:
        return None
    tl0 = l0.copy()
    tn = nodes.copy()
    tt = targets.copy()
    tj = joined.copy()
    for m in metas:
        tl0[m.e, 0] = np.int32(int(SPLICE_TAG) + m.slot)
        tn[m.node_rows] = 0
        tt[m.tpos] = 0
        tj[m.tidx] = 0
    return (tl0, tn, tt, tj, root_lut.copy()), tuple(metas)


def _recompose_ctrie_slab(spec: ArenaSpec, trunk_arrays, metas, planes):
    """Inverse of _decompose_ctrie_slab: trunk + canonical planes back
    to the tenant's whole-slab canonical arrays — the invariant teeth
    of check_arena (residual slab + spliced planes must reproduce the
    whole-slab canonical bytes/hash bit-exactly).  ``planes`` aligns
    with ``metas``: (pnodes, ptargets, pjoined, n_local) each."""
    l0, nodes, targets, joined, root_lut = (
        np.array(a, copy=True) for a in trunk_arrays
    )
    for m, (pn, pt, pj, n_local) in zip(metas, planes):
        l0[m.e, 0] = np.int32(m.root + 1)
        out = np.array(pn[:n_local], copy=True)
        ccp = _np_popcount_rows(out[:, 4:12])
        tcp = _np_popcount_rows(out[:, 12:20])
        local_cb = np.clip(out[:, 0].astype(np.int64), 0, max(n_local - 1, 0))
        glob_cb = np.where(ccp > 0, m.node_rows[local_cb], m.dead_cb)
        if len(m.tpos):
            local_tb = np.clip(
                out[:, 1].astype(np.int64), 0, len(m.tpos) - 1
            )
            glob_tb = np.where(tcp > 0, m.tpos[local_tb], m.dead_tb)
        else:
            glob_tb = m.dead_tb
        out[:, 0] = glob_cb.astype(np.uint32)
        out[:, 1] = glob_tb.astype(np.uint32)
        nodes[m.node_rows] = out
        for j, p in enumerate(m.tpos.tolist()):
            v = int(pt[j])
            targets[p] = 0 if v <= 0 else np.int32(m.tidx[v - 1])
        for j, v in enumerate(m.tidx.tolist()):
            joined[v] = pj[1 + j]
    return l0, nodes, targets, joined, root_lut


def _offset_plane_slab(spec: ArenaSpec, plane_arrays, n_local: int, ps: int):
    """Canonical plane arrays -> the plane slot's resident (pool-
    global) form: node rows += plane-pool base + ps*SNP, target bases
    += target base + ps*STP, target values += joined base + ps*SJP —
    after which the shared descent walks the plane exactly like slab
    rows.  Never mutates the canonical arrays."""
    pn, pt, pj = plane_arrays
    nb = spec.pages * spec.node_rows + ps * spec.plane_node_rows
    tb = spec.pages * spec.target_rows + ps * spec.plane_target_rows
    jb = spec.pages * spec.joined_rows + ps * spec.plane_joined_rows
    pno = pn.copy()
    pno[:n_local, 0] += np.uint32(nb)
    pno[:n_local, 1] += np.uint32(tb)
    pto = np.where(pt > 0, pt + jb, 0).astype(np.int32)
    return pno, pto, pj


def _unoffset_plane_slab(spec: ArenaSpec, plane_arrays, n_local: int,
                         ps: int):
    """Inverse of _offset_plane_slab: resident plane rows back to the
    canonical plane form (the dedup-rehash / recompose source)."""
    pn, pt, pj = plane_arrays
    nb = spec.pages * spec.node_rows + ps * spec.plane_node_rows
    tb = spec.pages * spec.target_rows + ps * spec.plane_target_rows
    jb = spec.pages * spec.joined_rows + ps * spec.plane_joined_rows
    pnc = pn.copy()
    pnc[:n_local, 0] -= np.uint32(nb)
    pnc[:n_local, 1] -= np.uint32(tb)
    ptc = np.where(pt > 0, pt - jb, 0).astype(np.int32)
    return pnc, ptc, np.array(pj, copy=True)


# -- arena classify kernels --------------------------------------------------


def _arena_pages(page_table: jax.Array, tenant: jax.Array) -> jax.Array:
    """(B,) page index per packet from the device page table; -1 for
    out-of-range tenant ids and absent tenants (their lanes classify to
    UNDEF — the deterministic no-table verdict, never a read from
    another tenant's slab)."""
    mt = page_table.shape[0]
    t_ok = (tenant >= 0) & (tenant < mt)
    pg = jnp.take(
        page_table, jnp.clip(tenant, 0, mt - 1), mode="clip"
    ).astype(jnp.int32)
    return jnp.where(t_ok, pg, -1)


def arena_dense_result_and_score(
    arena: DenseArena, batch: DeviceBatch, tenant: jax.Array, *, pages: int
) -> Tuple[jax.Array, jax.Array]:
    """(raw scan result, LPM score) over the dense pool: each packet
    compares against ITS OWN slab's rows (a (B, S)-shaped gather-
    compare — same arithmetic as lpm_dense_scores, slab-local).  Also
    the overlay side of the arena combine."""
    S = arena.mask_len.shape[0] // pages
    pg = _arena_pages(arena.page_table, tenant)
    valid = pg >= 0
    base = jnp.clip(pg, 0) * S
    ridx = base[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    kw = jnp.take(arena.key_words, ridx, axis=0, mode="clip")   # (B,S,5)
    mw = jnp.take(arena.mask_words, ridx, axis=0, mode="clip")
    ml = jnp.take(arena.mask_len, ridx, axis=0, mode="clip")    # (B,S)
    pkt = packet_key_words(batch)
    diff = (pkt[:, None, :] ^ kw) & mw
    match = jnp.all(diff == 0, axis=-1)
    cap = jnp.where(batch.kind == KIND_IPV4, 32, 128)
    ok = valid[:, None] & match & (ml >= 0) & (ml <= cap[:, None])
    score_all = jnp.where(ok, ml + 1, 0)
    loc = jnp.argmax(score_all, axis=1).astype(jnp.int32)
    score = jnp.max(score_all, axis=1)
    rows = jnp.take(arena.rules, base + loc, axis=0, mode="clip")
    rows = jnp.where((score > 0)[:, None], rows, 0)
    rows = rows.reshape(rows.shape[0], -1, 5)
    return rule_scan(rows, batch), score


def classify_arena_dense(
    arena: DenseArena, batch: DeviceBatch, tenant: jax.Array, *, pages: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    raw, _score = arena_dense_result_and_score(
        arena, batch, tenant, pages=pages
    )
    return finalize(raw, batch)


def _arena_ctrie_entry(
    ca: CtrieArena, batch: DeviceBatch, tenant: jax.Array, *,
    pages: int, spec: Optional[ArenaSpec] = None,
):
    """Tenant-steered entry of the paged compressed walk: tenant ->
    page (device page table) -> slab root_lut row -> GLOBAL root node
    -> DIR-16 slot.  Returns (node, alive, best0) in pool-global terms
    — everything past here is the shared _ctrie_descend.

    On a spliced arena (``spec.spliced``), page-table rows decode to
    (page, bank), and a SPLICE_TAG-tagged l0 slot resolves through the
    tenant's active splice-table bank to a shared plane id: the walk
    enters at that plane's root row in the appended plane pool (local
    row 0 — BFS emission makes the subtree root the minimum node id)
    with NO host round-trip.  best0 (<=16-bit prefixes) is always
    trunk-owned, so the leaf-push fallback is splice-oblivious."""
    SL = ca.root_lut.shape[0] // pages
    R0 = ca.l0.shape[0] // (pages * 65536)
    pg_raw = _arena_pages(ca.page_table, tenant)
    valid = pg_raw >= 0
    spliced = spec is not None and spec.spliced
    if spliced:
        bank = jnp.where(valid, pg_raw >> _SPLICE_BANK_SHIFT, 0)
        if _inject_clampgather_bug():
            pg = jnp.where(valid, pg_raw, -1)
        else:
            pg = jnp.where(valid, pg_raw & _SPLICE_PAGE_MASK, -1)
    else:
        pg = pg_raw
    pg0 = jnp.clip(pg, 0)
    if_ok = (batch.ifindex >= 0) & (batch.ifindex < SL)
    lidx = pg0 * SL + jnp.clip(batch.ifindex, 0, SL - 1)
    # out-of-lut ifindexes resolve to the page's OWN null root (the
    # single-table if_ok -> root 0 semantics, slab-local)
    root = jnp.where(
        if_ok, jnp.take(ca.root_lut, lidx, mode="clip"), pg0 * R0
    ).astype(jnp.int32)
    nib0 = (batch.ip_words[:, 0] >> np.uint32(16)).astype(jnp.int32)
    e0 = root * 65536 + nib0
    in0 = valid & (e0 >= 0) & (e0 < ca.l0.shape[0])
    rows0 = jnp.take(ca.l0, e0, axis=0, mode="clip")
    best0 = jnp.where(in0 & (rows0[:, 1] > 0), rows0[:, 1], 0)
    v = rows0[:, 0]
    if spliced:
        K = spec.splice_slots
        mt = ca.page_table.shape[0]
        is_sp = v >= jnp.int32(SPLICE_TAG)
        slot = jnp.clip(v - jnp.int32(SPLICE_TAG), 0, K - 1)
        t0 = jnp.clip(tenant, 0, mt - 1).astype(jnp.int32)
        srow = (bank * mt + t0) * K + slot
        ps = jnp.take(ca.splice, srow, mode="clip").astype(jnp.int32)
        plane_root = (
            pages * spec.node_rows
            + jnp.clip(ps, 0) * spec.plane_node_rows
        )
        alive = in0 & jnp.where(is_sp, ps >= 0, v > 0)
        node = jnp.where(
            is_sp, plane_root, jnp.maximum(v, 1) - 1
        ).astype(jnp.int32)
        node = jnp.where(alive, node, 0)
    else:
        alive = in0 & (v > 0)
        node = jnp.where(alive, v - 1, 0)
    return node, alive, best0


def arena_ctrie_rows(
    ca: CtrieArena, batch: DeviceBatch, tenant: jax.Array, *,
    pages: int, d_max: int, spec: Optional[ArenaSpec] = None,
) -> jax.Array:
    """(B, 3 + R*5) joined rows from the paged compressed walk —
    per-tenant verdicts bit-identical to ctrie_walk_rows over that
    tenant's standalone CTrieTables."""
    node, alive, best0 = _arena_ctrie_entry(
        ca, batch, tenant, pages=pages, spec=spec
    )
    win = _ctrie_descend(ca.nodes, batch, node, alive, d_max)
    in_w = (win >= 0) & (win < ca.targets.shape[0])
    tval = jnp.where(
        in_w, jnp.take(ca.targets, jnp.clip(win, 0), mode="clip"), 0
    )
    sel = jnp.where(tval > 0, tval, best0)  # global joined position
    in_j = (sel > 0) & (sel < ca.joined.shape[0])
    rows = jnp.take(
        ca.joined, jnp.clip(sel, 0, ca.joined.shape[0] - 1), axis=0,
        mode="clip",
    )
    return jnp.where(in_j[:, None], rows, 0)


def arena_ctrie_result_and_score(
    ca: CtrieArena, batch: DeviceBatch, tenant: jax.Array, *,
    pages: int, d_max: int, spec: Optional[ArenaSpec] = None,
) -> Tuple[jax.Array, jax.Array]:
    rows = arena_ctrie_rows(
        ca, batch, tenant, pages=pages, d_max=d_max, spec=spec
    )
    matched = (
        rows[:, 0].astype(jnp.int32) | (rows[:, 1].astype(jnp.int32) << 16)
    ) > 0
    score = jnp.where(matched, rows[:, 2].astype(jnp.int32) + 1, 0)
    return rule_scan(joined_rule_rows(rows), batch), score


def classify_arena_ctrie(
    ca: CtrieArena, batch: DeviceBatch, tenant: jax.Array, *,
    pages: int, d_max: int, spec: Optional[ArenaSpec] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    raw, _s = arena_ctrie_result_and_score(
        ca, batch, tenant, pages=pages, d_max=d_max, spec=spec
    )
    return finalize(raw, batch)


def classify_arena_with_overlay(
    main, overlay: DenseArena, batch: DeviceBatch, tenant: jax.Array, *,
    pages: int, ov_pages: int, d_max: int = 0,
    spec: Optional[ArenaSpec] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Arena classify with the per-tenant dense overlay side-pool: the
    longest-prefix combine of classify_with_overlay, both sides
    tenant-steered.  ``main`` is a CtrieArena (d_max > 0) or a
    DenseArena."""
    if isinstance(main, CtrieArena):
        raw_m, score_m = arena_ctrie_result_and_score(
            main, batch, tenant, pages=pages, d_max=d_max, spec=spec
        )
    else:
        raw_m, score_m = arena_dense_result_and_score(
            main, batch, tenant, pages=pages
        )
    raw_o, score_o = arena_dense_result_and_score(
        overlay, batch, tenant, pages=ov_pages
    )
    result = jnp.where(score_o > score_m, raw_o, raw_m)
    return finalize(result, batch)


@functools.lru_cache(maxsize=None)
def jitted_classify_arena_wire_fused(
    family: str, pages: int, d_max: int = 0, ov_pages: int = 0,
    spec: Optional[ArenaSpec] = None,
):
    """The arena wire launch: (arena[, overlay], wire, tenant) ->
    fused (res16, stats) single-buffer output — the production
    mixed-tenant dispatch.  Cache keyed on the pool geometry statics
    (family, pages, d_max, overlay pages, and — spliced arenas only —
    the full spec), which are FIXED per arena: tenant count, swaps and
    patches never re-specialize.  Callers pass ``spec`` only when
    spec.spliced (legacy cache arity preserved)."""
    if family == "dense":
        if ov_pages:
            def f(arena, ov, wire, tenant):
                res, _x, stats = classify_arena_with_overlay(
                    arena, ov, unpack_wire(wire), tenant,
                    pages=pages, ov_pages=ov_pages,
                )
                return fuse_wire_outputs(res.astype(jnp.uint16), stats)
        else:
            def f(arena, wire, tenant):
                res, _x, stats = classify_arena_dense(
                    arena, unpack_wire(wire), tenant, pages=pages
                )
                return fuse_wire_outputs(res.astype(jnp.uint16), stats)
    elif family == "ctrie":
        if ov_pages:
            def f(arena, ov, wire, tenant):
                res, _x, stats = classify_arena_with_overlay(
                    arena, ov, unpack_wire(wire), tenant,
                    pages=pages, ov_pages=ov_pages, d_max=d_max,
                    spec=spec,
                )
                return fuse_wire_outputs(res.astype(jnp.uint16), stats)
        else:
            def f(arena, wire, tenant):
                res, _x, stats = classify_arena_ctrie(
                    arena, unpack_wire(wire), tenant,
                    pages=pages, d_max=d_max, spec=spec,
                )
                return fuse_wire_outputs(res.astype(jnp.uint16), stats)
    else:
        raise ValueError(f"unknown arena family {family!r}")
    return jax.jit(f)


# -- the allocator -----------------------------------------------------------


class ArenaAllocator:
    """Host-side slab allocator over one family pool: page alloc/free,
    full-slab bakes, per-slab incremental patches, page-table flips and
    compaction — every device mutation through the SAME warmed capped/
    fused scatter executables as the single-table patch path, so a
    warm arena performs zero jit compiles across tenant create / swap /
    patch / destroy (test-pinned by the recompile-lint suite).

    Slabs are CONTENT-ADDRESSED and shared COPY-ON-WRITE (ISSUE-15):
    a canonical sha256 over the baked (page-independent) slab arrays
    maps identical rulesets to ONE physical page with refcounted
    page-table rows — N tenants on the same baseline cost one slab,
    and installing a ruleset whose content is already resident is a
    page-table row flip (no bake, no device write).  A tenant EDIT on
    a shared page triggers clone-then-patch: the donor's canonical
    arrays copy host-side, the dirty rows patch the copy, and the
    result lands in a free page through the warmed full-slab fused
    scatter before the editing tenant's page-table row flips — the
    donor's refcount decrements (free at zero) and every OTHER sharer
    keeps serving the untouched donor slab, gap-free.  In-place
    patches of a private (refcount-1) page stay O(dirty rows); they
    mark the page's content hash stale, and a background
    ``dedup_sweep`` re-hashes stale pages and re-merges pages whose
    content re-converged.

    Thread-safety: all mutating entry points take the internal lock
    (re-entrant: the ``pre_flip`` plane-refresh callback reads
    allocator state back); ``arena`` snapshots the current device
    tuple (classify dispatches finish on the tuple they captured — the
    double-buffer contract, per-row granular here because a page-table
    flip only redirects lanes of the flipped tenant)."""

    def __init__(self, spec: ArenaSpec, device=None, shardings=None):
        """``device`` is a jax device OR a Sharding (scatter payloads
        and flips are placed with it — on a mesh, pass the REPLICATED
        sharding); ``shardings`` optionally overrides the initial
        placement PER POOL ARRAY name (the mesh backend passes the
        slab-family partition rules here, pages sharded along the
        "rules" axis)."""
        self.spec = spec
        self._device = device
        self._shardings = shardings or {}
        self._lock = threading.RLock()
        P = spec.pages
        if spec.family == "dense":
            S = P * spec.entries
            host = {
                "key_words": np.zeros((S, 5), np.uint32),
                "mask_words": np.zeros((S, 5), np.uint32),
                "mask_len": np.full(S, -1, np.int32),
                "rules": np.zeros((S, spec.rule_slots * 5), np.uint16),
            }
        else:
            pp = spec.plane_slots
            host = {
                "l0": np.zeros((P * spec.l0_rows, 2), np.int32),
                "nodes": np.zeros(
                    (P * spec.node_rows + pp * spec.plane_node_rows, 20),
                    np.uint32,
                ),
                "targets": np.zeros(
                    P * spec.target_rows + pp * spec.plane_target_rows,
                    np.int32,
                ),
                "joined": np.zeros(
                    (P * spec.joined_rows + pp * spec.plane_joined_rows,
                     3 + spec.rule_slots * 5),
                    np.uint16,
                ),
                "root_lut": np.zeros(P * spec.lut_rows, np.int32),
                "splice": np.full(spec.splice_rows, -1, np.int32),
            }
        host["page_table"] = np.full(spec.max_tenants, -1, np.int32)
        self._host = host
        dev = {
            k: jax.device_put(
                jnp.asarray(v), self._shardings.get(k, device)
            )
            for k, v in host.items()
        }
        if spec.family == "dense":
            self._dev = DenseArena(**dev)
        else:
            self._dev = CtrieArena(**dev)
        self._free = list(range(P))
        self._tenant_page: dict = {}
        self._tenant_tables: dict = {}
        #: CoW bookkeeping -------------------------------------------------
        #: page -> count of page-table rows referencing it (the tenant
        #: references; the check_arena invariant is that this equals
        #: the recount from _tenant_page at every boundary)
        self._page_refs: dict = {}
        #: page -> count of stage() reservations not yet activated /
        #: released (holds keep a page alive independent of refs and
        #: pin its page id against compaction/dedup moves)
        self._page_holds: dict = {}
        #: page -> real skip-node row count of the resident slab (what
        #: makes page-offset stripping well-defined; persists across a
        #: free so a standby claim-back stays canonicalizable)
        self._page_nnodes: dict = {}
        #: content hash -> page and inverse, for pages whose hash is
        #: KNOWN-current; pages go _hash_dirty on in-place patch /
        #: CoW clone / free-list claim-back and dedup_sweep re-indexes
        self._hash_page: dict = {}
        self._page_hash: dict = {}
        self._hash_dirty: set = set()
        self.counters = {
            "assigns": 0, "patches": 0, "swaps": 0, "flips": 0,
            "destroys": 0, "compactions": 0, "slab_writes": 0,
            "shared_hits": 0, "cow_clones": 0, "dedup_merges": 0,
            "plane_writes": 0, "plane_hits": 0, "splice_unsplices": 0,
            "splice_merges": 0,
        }
        #: structural (subtree-splice) compression state (ISSUE-17) ---------
        self._spliced = spec.spliced
        #: plane id free list / refcounts / stage holds (plane analogue
        #: of the page bookkeeping; a plane frees at zero refs + holds)
        self._plane_free = list(range(spec.plane_slots))
        self._plane_refs: dict = {}
        self._plane_holds: dict = {}
        self._plane_nnodes: dict = {}
        #: plane content hash -> plane id and inverse (plane-granular
        #: content addressing; planes go hash-dirty on in-place joined
        #: patches and dedup_sweep re-merges re-converged planes)
        self._hash_plane: dict = {}
        self._plane_hash: dict = {}
        self._plane_hash_dirty: set = set()
        #: tenant -> {splice slot -> plane id} (the host truth of the
        #: active splice-table bank) and tenant -> _SpliceSub metas
        #: (slot ownership maps for recompose / edit routing)
        self._tenant_splices: dict = {}
        self._tenant_splice_meta: dict = {}
        #: tenant -> active splice bank (0/1); the page-table row
        #: encodes page | bank << 30 so both flip in ONE scatter
        self._tenant_bank: dict = {}
        #: pages whose resident slab is a decomposed TRUNK (hash-index
        #: keys domain-tagged b"T"+hash so a trunk never dedups against
        #: a whole slab of coincidentally-equal bytes)
        self._page_decomposed: set = set()
        #: page -> stack of staged splice plans [((slot, plane), ...)]
        #: consumed LIFO by activate()/release()
        self._stage_plans: dict = {}
        #: planes whose node rows changed since the last
        #: consume_dirty_plane_rows() (the Pallas byte-plane refresh)
        self._dirty_plane_rows: set = set()
        #: bumps on every structural slab write — consumers that derive
        #: secondary layouts from the node pool (the paged Pallas walk's
        #: byte planes) rebuild when this moves; rules-only patches
        #: never touch it
        self.node_gen = 0
        #: pages whose node slab changed since the last
        #: consume_dirty_node_pages() — lets plane consumers re-derive
        #: ONLY the written slabs' rows instead of the whole pool
        self._dirty_node_pages: set = set()
        self._warm()

    # -- introspection -------------------------------------------------------

    @property
    def arena(self):
        """Snapshot of the current device pool tuple."""
        with self._lock:
            return self._dev

    @property
    def family(self) -> str:
        return self.spec.family

    def page_of(self, tenant: int):
        with self._lock:
            return self._tenant_page.get(tenant)

    def tables_of(self, tenant: int):
        with self._lock:
            return self._tenant_tables.get(tenant)

    def tenants(self):
        with self._lock:
            return sorted(self._tenant_page)

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def page_refcount(self, page: int) -> int:
        """Page-table references on one physical page (0 for free /
        hold-only pages)."""
        with self._lock:
            return self._page_refs.get(page, 0)

    def page_holds(self, page: int) -> int:
        with self._lock:
            return self._page_holds.get(page, 0)

    def tenant_shares_page(self, tenant: int) -> bool:
        """True when the tenant's slab is shared (another tenant's
        page-table row or a stage hold references the same physical
        page) — the condition under which an edit must CoW instead of
        patching in place."""
        with self._lock:
            page = self._tenant_page.get(tenant)
            return page is not None and self._is_shared(page)

    def distinct_slabs(self) -> int:
        """Live physical pages (referenced or held) — the real HBM
        occupancy denominator under sharing."""
        with self._lock:
            live = set(self._page_refs) | {
                p for p, h in self._page_holds.items() if h > 0
            }
            return len(live)

    def pool_bytes(self) -> int:
        """Resident HBM footprint of the pools (the denominator of the
        arena-vs-N-tables bench line)."""
        with self._lock:
            return sum(int(np.asarray(a).nbytes) for a in self._dev)

    def host_nodes(self) -> Optional[np.ndarray]:
        """Host mirror of the merged skip-node pool (ctrie family) —
        the paged Pallas walk derives its byte planes from this; pair
        reads with ``node_gen`` to know when to rebuild."""
        with self._lock:
            arr = self._host.get("nodes")
            return None if arr is None else arr.copy()

    def consume_dirty_node_pages(self):
        """(node_gen, pages, node-slab host rows per page) of every
        slab whose node rows changed since the last call — the
        incremental feed for plane consumers (a full-pool re-derive on
        every tenant mutation would put O(pool) work on the O(1) swap
        path)."""
        with self._lock:
            pages = sorted(self._dirty_node_pages)
            self._dirty_node_pages = set()
            sn = self.spec.node_rows
            rows = {
                p: self._host["nodes"][p * sn : (p + 1) * sn].copy()
                for p in pages
            } if "nodes" in self._host else {}
            return self.node_gen, pages, rows

    def consume_dirty_plane_rows(self):
        """(node_gen, [(pool row base, plane node rows), ...]) of every
        subtree plane whose node rows changed since the last call — the
        plane-region analogue of consume_dirty_node_pages (plane writes
        bump node_gen but touch no page slab, so the Pallas byte-plane
        consumer refreshes O(touched subtrees), never the pool)."""
        with self._lock:
            planes = sorted(self._dirty_plane_rows)
            self._dirty_plane_rows = set()
            blocks: list = []
            if planes and "nodes" in self._host:
                snp = self.spec.plane_node_rows
                for ps in planes:
                    b = self._plane_base(ps)[0]
                    blocks.append(
                        (b, self._host["nodes"][b: b + snp].copy())
                    )
            return self.node_gen, blocks

    def plane_refcount(self, ps: int) -> int:
        """Splice-row references on one subtree plane (0 for free /
        hold-only planes)."""
        with self._lock:
            return self._plane_refs.get(ps, 0)

    def tenant_splices(self, tenant: int) -> dict:
        """{splice slot -> plane id} of the tenant's active splice
        bank (empty for unspliced tenants)."""
        with self._lock:
            return dict(self._tenant_splices.get(tenant) or {})

    def distinct_planes(self) -> int:
        """Live subtree planes (referenced or held) — the plane-pool
        half of the HBM occupancy numerator."""
        with self._lock:
            live = set(self._plane_refs) | {
                p for p, h in self._plane_holds.items() if h > 0
            }
            return len(live)

    def counter_values(self) -> dict:
        """tenant_* counters for /metrics (the obs satellite): gauges
        for slab occupancy plus monotonic mutation counts."""
        with self._lock:
            live = set(self._page_refs) | {
                p for p, h in self._page_holds.items() if h > 0
            }
            out = {
                "tenant_active_slabs": len(self._tenant_page),
                "tenant_free_slabs": len(self._free),
                "tenant_distinct_slabs": len(live),
                "tenant_shared_pages": sum(
                    1 for n in self._page_refs.values() if n > 1
                ),
                "tenant_hash_index": len(self._hash_page),
                "tenant_hash_dirty": len(self._hash_dirty),
            }
            if self._spliced:
                live_planes = set(self._plane_refs) | {
                    p for p, h in self._plane_holds.items() if h > 0
                }
                out["arena_subtree_planes"] = len(live_planes)
                out["arena_shared_subtrees"] = sum(
                    1 for n in self._plane_refs.values() if n > 1
                )
                out["arena_splice_rows"] = sum(
                    len(m) for m in self._tenant_splices.values()
                )
                out["splice_unsplices"] = self.counters[
                    "splice_unsplices"
                ]
                out["splice_merges"] = self.counters["splice_merges"]
            for k, v in self.counters.items():
                out[f"tenant_{k}_total"] = v
            return out

    # -- device write plumbing ----------------------------------------------

    def _warm(self) -> None:
        """Pre-compile every scatter shape the allocator can emit: the
        small-edit cap ladder per pool array, the FULL-SLAB row counts
        (tenant create/swap/compact), the fused multi-family slab
        combo, and the 1-row page-table flip — so a warm arena's whole
        tenant lifecycle is compile-free."""
        dev = self._dev
        arrays = [a for a in dev[:-1]]
        warm_scatters(arrays, self._device, max_rows=TXN_WARM_MAX_ROWS)
        # the page-table flip executable (1-row direct scatter)
        _scatter(dev.page_table, np.zeros(1, np.int64),
                 np.zeros(1, np.int32), self._device)
        # full-slab writes: one fused txn_scatter over every family
        # array at its slab row count
        entries = []
        for arr, rows in zip(arrays, self._slab_rows()):
            entries.append((
                arr, np.zeros(rows, np.int64),
                np.zeros((rows,) + tuple(arr.shape[1:]), arr.dtype),
            ))
        txn_scatter(entries, self._device)
        if self._spliced:
            # plane writes: one fused txn_scatter over the three plane
            # arrays at their per-plane row counts, plus the K-row
            # splice-bank scatter — the whole splice lifecycle (plane
            # share/unsplice/merge, splice-map update, bank flip) then
            # rides warmed executables only
            txn_scatter(
                [
                    (
                        getattr(dev, name),
                        np.zeros(rows, np.int64),
                        np.zeros(
                            (rows,) + tuple(getattr(dev, name).shape[1:]),
                            getattr(dev, name).dtype,
                        ),
                    )
                    for name, rows in zip(
                        ("nodes", "targets", "joined"), self._plane_rows()
                    )
                ],
                self._device,
            )
            K = self.spec.splice_slots
            _scatter(dev.splice, np.arange(K, dtype=np.int64),
                     np.full(K, -1, np.int32), self._device)
        # rules-only patch combo (ladder) for the hint fast path
        patchable = [self._patch_arrays(dev)]
        for group in patchable:
            nb = group[0].shape[0]
            for k in scatter_cap_ladder(nb, TXN_WARM_MAX_ROWS):
                txn_scatter(
                    [
                        (
                            a,
                            np.zeros(min(k, max(a.shape[0] // 4, 1)), np.int64),
                            np.zeros(
                                (min(k, max(a.shape[0] // 4, 1)),)
                                + tuple(a.shape[1:]),
                                a.dtype,
                            ),
                        )
                        for a in group
                    ],
                    self._device,
                )

    def _slab_rows(self):
        s = self.spec
        if s.family == "dense":
            return (s.entries, s.entries, s.entries, s.entries)
        return (s.l0_rows, s.node_rows, s.target_rows, s.joined_rows,
                s.lut_rows)

    def _array_names(self):
        if self.spec.family == "dense":
            return ("key_words", "mask_words", "mask_len", "rules")
        return ("l0", "nodes", "targets", "joined", "root_lut")

    # -- subtree plane plumbing (spliced arenas) ------------------------------

    def _plane_rows(self):
        s = self.spec
        return (s.plane_node_rows, s.plane_target_rows,
                s.plane_joined_rows)

    def _plane_base(self, ps: int):
        """(nodes, targets, joined) pool row bases of plane ``ps`` —
        the plane pool region starts where the page slabs end."""
        s = self.spec
        return (s.pages * s.node_rows + ps * s.plane_node_rows,
                s.pages * s.target_rows + ps * s.plane_target_rows,
                s.pages * s.joined_rows + ps * s.plane_joined_rows)

    def _decode_page_table(self, vals):
        """Strip the splice bank bit off page-table values (identity on
        unspliced arenas); -1 absent rows pass through."""
        vals = np.asarray(vals)
        if not self._spliced:
            return vals
        return np.where(
            vals >= 0, vals & _SPLICE_PAGE_MASK, vals
        ).astype(vals.dtype)

    def _canonical_of_plane(self, ps: int):
        """(pnodes, ptargets, pjoined, n_local) canonical form of one
        resident plane, derived from the host mirror by stripping the
        plane-slot offsets."""
        names = ("nodes", "targets", "joined")
        arrs = tuple(
            self._host[name][b: b + r]
            for name, r, b in zip(names, self._plane_rows(),
                                  self._plane_base(ps))
        )
        n_local = self._plane_nnodes.get(ps, 0)
        return _unoffset_plane_slab(self.spec, arrs, n_local, ps) + (
            n_local,
        )

    def _plane_is_shared(self, ps: int) -> bool:
        return (
            self._plane_refs.get(ps, 0) > 1
            or self._plane_holds.get(ps, 0) > 0
        )

    def _alloc_plane(self) -> int:
        if not self._plane_free:
            raise _PlaneCapacityError(
                f"arena out of subtree planes ({self.spec.plane_slots} "
                "total) — the decomposed install falls back to the "
                "whole-slab path"
            )
        return self._plane_free.pop(0)

    def _write_plane(self, ps: int, plane_arrays, n_local: int) -> None:
        """Bake one canonical plane into the pool region: mirror first,
        then ONE fused txn_scatter across nodes/targets/joined at the
        plane row counts (warmed in _warm)."""
        resident = _offset_plane_slab(self.spec, plane_arrays, n_local, ps)
        names = ("nodes", "targets", "joined")
        entries = []
        for name, rows, base, arr in zip(
            names, self._plane_rows(), self._plane_base(ps), resident
        ):
            self._host[name][base: base + rows] = arr
            entries.append((
                getattr(self._dev, name),
                base + np.arange(rows, dtype=np.int64),
                arr,
            ))
        patched = txn_scatter(entries, self._device)
        if patched is None:
            raise ArenaCapacityError(
                "plane write exceeded the scatter budget"
            )
        self._dev = self._dev._replace(**dict(zip(names, patched)))
        self._plane_nnodes[ps] = int(n_local)
        self.counters["plane_writes"] += 1
        self.node_gen += 1
        self._dirty_plane_rows.add(ps)

    def _unindex_plane(self, ps: int) -> None:
        old = self._plane_hash.pop(ps, None)
        if old is not None and self._hash_plane.get(old) == ps:
            del self._hash_plane[old]

    def _index_plane(self, ps: int, phash: bytes) -> bool:
        self._unindex_plane(ps)
        self._plane_hash_dirty.discard(ps)
        cur = self._hash_plane.get(phash)
        if cur is not None and cur != ps:
            self._plane_hash_dirty.add(ps)
            return False
        self._hash_plane[phash] = ps
        self._plane_hash[ps] = phash
        return True

    def _plane_incref(self, ps: int) -> None:
        self._plane_refs[ps] = self._plane_refs.get(ps, 0) + 1

    def _plane_decref(self, ps: int, from_unsplice: bool = False) -> None:
        """Drop one splice-row reference on a plane; the plane frees at
        zero (with no holds).  ``from_unsplice`` marks the unsplice
        path's old-plane decrement — the exact statement the injected
        spliceleak defect forgets."""
        if from_unsplice and _inject_spliceleak_bug():
            return
        n = self._plane_refs.get(ps, 0) - 1
        if n > 0:
            self._plane_refs[ps] = n
            return
        self._plane_refs.pop(ps, None)
        if self._plane_holds.get(ps, 0) == 0:
            self._release_plane(ps)

    def _release_plane(self, ps: int) -> None:
        self._unindex_plane(ps)
        self._plane_hash_dirty.discard(ps)
        if ps not in self._plane_free:
            self._plane_free.append(ps)

    def _release_plane_hold(self, ps: int) -> None:
        h = self._plane_holds.get(ps, 0)
        if h <= 0:
            return
        if h == 1:
            self._plane_holds.pop(ps, None)
        else:
            self._plane_holds[ps] = h - 1
        if (
            self._plane_refs.get(ps, 0) == 0
            and self._plane_holds.get(ps, 0) == 0
        ):
            self._release_plane(ps)

    def _acquire_plane(self, m: "_SpliceSub") -> int:
        """Content-addressed plane acquisition for one subtree meta:
        hash HIT -> refcount bump on the already-resident plane (N
        near-copy tenants cost ONE plane); miss -> alloc + warmed
        write + index.  Returns the plane id with one reference
        taken."""
        ps = self._hash_plane.get(m.phash)
        if ps is not None:
            self._plane_incref(ps)
            self.counters["plane_hits"] += 1
            return ps
        ps = self._alloc_plane()
        try:
            self._write_plane(ps, m.plane, m.n_local)
        except Exception:
            if ps not in self._plane_free:
                self._plane_free.insert(0, ps)
            raise
        self._index_plane(ps, m.phash)
        self._plane_refs[ps] = 1
        return ps

    def _write_splice_rows(self, tenant: int, slot_map: dict) -> None:
        """Write the tenant's FULL splice row block (all K slots; -1
        for unused) to the INACTIVE bank and switch the tenant's bank
        variable — the very next _flip() publishes page + bank in one
        1-row page-table scatter, so classify never pairs a new splice
        map with the old page (or vice versa)."""
        K = self.spec.splice_slots
        mt = self.spec.max_tenants
        bank = 1 - self._tenant_bank.get(tenant, 0)
        vals = np.full(K, -1, np.int32)
        for slot, ps in slot_map.items():
            vals[slot] = ps
        base = (bank * mt + tenant) * K
        self._host["splice"][base: base + K] = vals
        sp = _scatter(
            self._dev.splice,
            base + np.arange(K, dtype=np.int64),
            vals, self._device,
        )
        self._dev = self._dev._replace(splice=sp)
        self._tenant_bank[tenant] = bank

    def _clear_splice_rows(self, tenant: int) -> None:
        """Blank the tenant's ACTIVE splice bank (no bank switch) —
        used after the tenant stopped serving spliced content (whole-
        slab activate / destroy), purely for mirror hygiene: an
        untagged l0 never reads the splice table."""
        K = self.spec.splice_slots
        mt = self.spec.max_tenants
        bank = self._tenant_bank.get(tenant, 0)
        base = (bank * mt + tenant) * K
        vals = np.full(K, -1, np.int32)
        self._host["splice"][base: base + K] = vals
        sp = _scatter(
            self._dev.splice,
            base + np.arange(K, dtype=np.int64),
            vals, self._device,
        )
        self._dev = self._dev._replace(splice=sp)

    def _drop_tenant_planes(self, tenant: int) -> None:
        """Release every plane the tenant's splice rows reference and
        clear its splice state (the tenant leaves decomposed serving)."""
        smap = self._tenant_splices.pop(tenant, None)
        self._tenant_splice_meta.pop(tenant, None)
        if smap:
            self._clear_splice_rows(tenant)
            for ps in smap.values():
                self._plane_decref(ps)

    def _bake_decomposed(self, tables: CompiledTables):
        """(trunk arrays, n_nodes, trunk hash key, metas) of one tenant
        table's subtree decomposition, or None when nothing factors.
        Memoized on the tables object like _bake_canonical, so repeated
        installs of a known near-copy pay the decompose ONCE.  The
        trunk key is domain-tagged (b"T" + hash) — a trunk page never
        hash-collides with a whole slab."""
        if not self._spliced:
            return None
        cached = getattr(tables, "_arena_splice_cache", None)
        if cached is not None and cached[0] == self.spec:
            return cached[1]
        arrays, n_nodes, _chash = self._bake_canonical(tables)
        dec = _decompose_ctrie_slab(self.spec, arrays, n_nodes)
        if dec is None:
            result = None
        else:
            trunk, metas = dec
            result = (
                trunk, n_nodes,
                b"T" + slab_content_hash(trunk, n_nodes), metas,
            )
        try:
            object.__setattr__(
                tables, "_arena_splice_cache", (self.spec, result)
            )
        except Exception:
            pass
        return result

    # -- content addressing / CoW plumbing ------------------------------------

    def _is_shared(self, page: int) -> bool:
        """A page is shared when >1 page-table row references it OR a
        stage hold reserves it — either way an in-place write would
        mutate state some OTHER consumer is serving/holding, so edits
        must copy-on-write."""
        return (
            self._page_refs.get(page, 0) > 1
            or self._page_holds.get(page, 0) > 0
        )

    def _bake_canonical(self, tables: CompiledTables):
        """(canonical arrays, n_nodes, content hash) for one tenant
        table — the page-independent bake the hash index keys on.
        Memoized on the tables object (same trick as the cpoptrie host
        caches), so repeated installs of a known baseline pay the bake
        and the hash ONCE and every later tenant-create-from-content
        is a dict probe + page-table flip."""
        cached = getattr(tables, "_arena_slab_cache", None)
        if cached is not None and cached[0] == self.spec:
            return cached[1], cached[2], cached[3]
        if self.spec.family == "dense":
            arrays = _dense_slab_arrays(self.spec, tables)
            n_nodes = 0
        else:
            arrays, n_nodes = _ctrie_canonical_slab(self.spec, tables)
        chash = slab_content_hash(arrays, n_nodes)
        try:
            object.__setattr__(
                tables, "_arena_slab_cache",
                (self.spec, arrays, n_nodes, chash),
            )
        except Exception:
            pass
        return arrays, n_nodes, chash

    def _offset(self, arrays, n_nodes: int, page: int):
        """Canonical slab arrays -> the page's resident form (identity
        for the dense family: dense slabs carry no cross-row indices)."""
        if self.spec.family == "dense":
            return arrays
        return _offset_ctrie_slab(self.spec, arrays, n_nodes, page)

    def _canonical_of_page(self, page: int):
        """Canonical (page-independent) arrays of one resident page,
        derived from the host mirror by stripping the page offsets —
        the CoW clone / compaction / dedup-rehash source.  Returns
        mirror VIEWS for the dense family and page 0; callers that
        mutate must copy."""
        arrays = tuple(
            self._host[name][page * r : (page + 1) * r]
            for name, r in zip(self._array_names(), self._slab_rows())
        )
        if self.spec.family == "dense":
            return arrays
        return _unoffset_ctrie_slab(
            self.spec, arrays, self._page_nnodes.get(page, 0), page
        )

    def _unindex(self, page: int) -> None:
        """Drop a page's hash-index entry (and its inverse) if present."""
        old = self._page_hash.pop(page, None)
        if old is not None and self._hash_page.get(old) == page:
            del self._hash_page[old]

    def _index_page(self, page: int, chash: bytes) -> bool:
        """Register a page's known-current content hash.  When another
        live page already owns the hash, the index keeps pointing at it
        and this page stays hash-dirty (dedup_sweep merges the
        duplicates); returns whether the page was indexed."""
        self._unindex(page)
        self._hash_dirty.discard(page)
        cur = self._hash_page.get(chash)
        if cur is not None and cur != page:
            self._hash_dirty.add(page)
            return False
        self._hash_page[chash] = page
        self._page_hash[page] = chash
        return True

    def _mark_hash_dirty(self, page: int) -> None:
        """The page's content diverged from its registered hash (an
        in-place patch): unindex now, re-hash lazily in dedup_sweep —
        keeping the patch fast path O(dirty rows), not O(slab hash)."""
        self._unindex(page)
        self._hash_dirty.add(page)

    def _incref(self, page: int) -> None:
        self._page_refs[page] = self._page_refs.get(page, 0) + 1

    def _decref(self, page: int, from_clone: bool = False) -> None:
        """Drop one page-table reference; the page frees at zero (with
        no holds).  ``from_clone`` marks the CoW donor decrement — the
        exact statement the injected cowleak defect forgets."""
        if from_clone and _inject_cowleak_bug():
            return
        n = self._page_refs.get(page, 0) - 1
        if n > 0:
            self._page_refs[page] = n
            return
        self._page_refs.pop(page, None)
        if self._page_holds.get(page, 0) == 0:
            self._release_page(page)

    def _release_page(self, page: int) -> None:
        """Return a page to the free list: unindex its hash (a free
        page must never be a dedup hit — _alloc_page may rebake it) but
        keep the slab bytes/mirror/n_nodes, so the standby claim-back
        pattern (activate straight off the free list) keeps serving
        valid content."""
        self._unindex(page)
        self._hash_dirty.discard(page)
        if page not in self._free:
            self._free.append(page)

    def _clone_patched_canonical(self, donor_page: int, old, new, hint):
        """The CoW clone-then-patch bake: copy the DONOR page's
        canonical arrays (no table recompile — the point of the clone)
        and apply the rules-only dirty rows of ``new`` on the copy.
        Returns (arrays, n_nodes) or None when the hinted patch cannot
        express the edit (caller falls back to a full canonical bake)."""
        dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
        dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
        arrays = [np.array(a, copy=True)
                  for a in self._canonical_of_page(donor_page)]
        n_nodes = self._page_nnodes.get(donor_page, 0)
        if self.spec.family == "dense":
            kw, mw, ml, rules, _lv, _tg, _lut, _j = _host_device_layout(
                new, pad=False, with_trie=False
            )
            if rules.dtype != np.uint16 or (
                rules.shape[1] != self.spec.rule_slots * 5
                or kw.shape[0] > self.spec.entries
            ):
                return None
            rows = dirty[dirty < kw.shape[0]]
            for arr, src in zip(arrays, (kw, mw, ml, rules)):
                arr[rows] = src[rows]
            return tuple(arrays), n_nodes
        # ctrie: structure untouched by contract (rules-only hint) —
        # only the joined plane's dirty tidx rows change
        _seed_ctrie_caches_forward(old, new, dirty)
        pr = _joined_tidx_patch_rows(new, dirty)
        if pr is None:
            return None
        pos, rows = pr
        if len(pos) and (
            int(pos.max()) >= self.spec.joined_rows
            or rows.shape[1] != arrays[3].shape[1]
        ):
            return None
        arrays[3][pos] = rows
        return tuple(arrays), n_nodes

    def _patch_arrays(self, dev):
        """The arrays a rules-only tenant edit scatters (the hint fast
        path): the dense group, or the ctrie joined plane."""
        if self.spec.family == "dense":
            return (dev.key_words, dev.mask_words, dev.mask_len, dev.rules)
        return (dev.joined,)

    def _write_slab(self, page: int, slab_arrays, n_nodes: int = 0) -> None:
        """Bake one tenant's full slab into the pools: ONE fused
        txn_scatter across every family array (whole slab rows, so a
        reused page carries no stale bytes).  Mirrors update first —
        they are the diff/bench/equivalence source of truth.
        ``n_nodes`` records the slab's real skip-node row count (what
        keeps the page's canonical form derivable from the mirror)."""
        names = self._array_names()
        entries = []
        for name, rows, arr in zip(names, self._slab_rows(), slab_arrays):
            base = page * rows
            self._host[name][base : base + rows] = arr
            entries.append((
                getattr(self._dev, name),
                base + np.arange(rows, dtype=np.int64),
                arr,
            ))
        patched = txn_scatter(entries, self._device)
        if patched is None:  # pages >= 4 makes this unreachable
            raise ArenaCapacityError("slab write exceeded the scatter budget")
        self._dev = self._dev._replace(**dict(zip(names, patched)))
        self._page_nnodes[page] = int(n_nodes)
        # a full-slab write is whole-slab content by default; the trunk
        # writer re-marks decomposed pages right after
        self._page_decomposed.discard(page)
        self.counters["slab_writes"] += 1
        self.node_gen += 1
        self._dirty_node_pages.add(page)

    def _flip(self, tenant: int, page: int, _inject: bool = False) -> None:
        """The page-table row flip — the O(1) activation that replaces
        a full re-upload.  On a spliced arena the row encodes
        ``page | bank << 30``: the tenant's splice-table bank publishes
        in the SAME scatter as its page, which is what makes a splice-
        map change atomic with the page move.  Injected defect
        (pageflip, activate-only): the device row keeps its STALE value
        while the host mirror moves on — the arena keeps serving the
        OLD slab after a swap."""
        enc = page
        if self._spliced and page >= 0:
            enc = page | (
                self._tenant_bank.get(tenant, 0) << _SPLICE_BANK_SHIFT
            )
        self._host["page_table"][tenant] = enc
        if _inject:
            self.counters["flips"] += 1
            return
        # direct 1-row scatter, NOT the capped helper: the flip is
        # always exactly one row and must not ride the nb//4 delta
        # budget (a tiny page table would refuse its own flip)
        pt = _scatter(
            self._dev.page_table,
            np.array([tenant], np.int64),
            np.array([enc], np.int32),
            self._device,
        )
        self._dev = self._dev._replace(page_table=pt)
        self.counters["flips"] += 1

    # -- tenant lifecycle ----------------------------------------------------

    def _alloc_page(self) -> int:
        if not self._free:
            raise ArenaCapacityError(
                f"arena out of pages ({self.spec.pages} total, "
                f"{len(self._page_refs)} distinct slabs live for "
                f"{len(self._tenant_page)} tenants; an edit of a SHARED "
                "slab needs a free page to copy-on-write into — size the "
                "pool with spare pages beyond the distinct-content count)"
            )
        return self._free.pop(0)

    def _check_tenant(self, tenant: int) -> None:
        if not (0 <= tenant < self.spec.max_tenants):
            raise ArenaCapacityError(
                f"tenant id {tenant} outside [0, {self.spec.max_tenants})"
            )

    def load_tenant(self, tenant: int, tables: CompiledTables,
                    hint=None, pre_flip=None) -> str:
        """Install/refresh one tenant's table.  Returns the device path
        taken:

        - "patch":   rules-only row scatter into the tenant's PRIVATE
                     resident slab (refcount 1, no holds);
        - "share":   the baked content is already resident on some page
                     (hash hit) — refcount bump + page-table flip, no
                     bake, no slab write;
        - "cow":     the tenant's page is shared and the edit forced a
                     private copy: clone-then-patch (or a full bake for
                     structural edits) into a free page, flip, donor
                     refcount decremented;
        - "rewrite": in-place full slab bake of a private page
                     (structural edit, no page change);
        - "assign":  fresh page + page-table flip.

        Spliced arenas add subtree-granular paths:

        - "patch":    additionally covers rules-only edits that land
                      inside PRIVATE planes / the private trunk;
        - "unsplice": a rules-only edit inside a SHARED subtree plane
                      repointed just that slot at a private (or
                      re-converged) plane — K splice rows + one bank
                      flip, trunk untouched;
        - "share":    trunk hash hit AND every plane hash hit (the
                      create-from-near-copy case costs the changed
                      planes only).

        ``pre_flip`` (optional callable) runs after any slab write and
        strictly BEFORE the page-table flip of paths that redirect the
        tenant to a new page — the fused-walk classifier passes its
        plane refresh here so classify never pairs a new page table
        with stale planes (new-planes/old-table is the safe pairing)."""
        self._check_tenant(tenant)
        with self._lock:
            if self._spliced:
                path = self._load_tenant_spliced(
                    tenant, tables, hint, pre_flip
                )
            else:
                path = self._load_tenant_whole(tenant, tables, hint,
                                               pre_flip)
        if _inject_cowrace_bug():
            self._finish_cowrace_pending()
        return path

    def _finish_cowrace_pending(self) -> None:
        """TEST-ONLY (cowrace defect): land the donor decref
        _cow_install deferred — OUTSIDE the allocator lock, as a plain
        read-modify-write on _page_refs with a sched_point in the
        window, so schedcheck can interleave a locked decrement
        (destroy_tenant / dedup_sweep merge) in between and demonstrate
        the lost update.  Semantics match _decref(donor,
        from_clone=True) when run serially."""
        from .. import _threads

        donor = getattr(self, "_cowrace_pending", None)
        if donor is None:
            return
        self._cowrace_pending = None
        n = self._page_refs.get(donor, 0)
        _threads.sched_point("cowrace-rmw")
        n -= 1
        if n > 0:
            self._page_refs[donor] = n
            return
        self._page_refs.pop(donor, None)
        if self._page_holds.get(donor, 0) == 0:
            self._release_page(donor)

    def _load_tenant_whole(self, tenant: int, tables: CompiledTables,
                           hint=None, pre_flip=None) -> str:
        """Whole-slab install — the pre-splice lifecycle, and the
        spliced arena's degrade-never-refuse fallback (tables that
        don't decompose, plane-pool exhaustion)."""
        with self._lock:
            page = self._tenant_page.get(tenant)
            old = self._tenant_tables.get(tenant)
            shared = page is not None and self._is_shared(page)
            if (page is not None and not shared and old is not None
                    and hint is not None):
                if self._try_patch(tenant, page, old, tables, hint):
                    self._tenant_tables[tenant] = tables
                    self.counters["patches"] += 1
                    # content diverged from the registered hash; the
                    # dedup sweep re-hashes lazily
                    self._mark_hash_dirty(page)
                    return "patch"
            if shared and old is not None and hint_trie_unchanged(hint):
                # CoW clone-then-patch: bake-free (donor canonical copy
                # + dirty rows) — skips the hash-index probe on purpose
                # (hashing would force the full bake the clone avoids;
                # re-convergence is dedup_sweep's job)
                can = self._clone_patched_canonical(page, old, tables, hint)
                if can is not None:
                    return self._cow_install(
                        tenant, page, can[0], can[1], None, tables,
                        pre_flip,
                    )
            arrays, n_nodes, chash = self._bake_canonical(tables)
            hit = self._hash_page.get(chash)
            if hit is not None:
                if hit == page:
                    # content unchanged (or a no-op edit): nothing to do
                    self._tenant_tables[tenant] = tables
                    return "share"
                self._tenant_page[tenant] = hit
                self._incref(hit)
                self._tenant_tables[tenant] = tables
                if pre_flip is not None:
                    pre_flip()
                self._flip(tenant, hit)
                if page is not None:
                    self._decref(page)
                self.counters["shared_hits"] += 1
                return "share"
            if page is None:
                new_page = self._alloc_page()
                try:
                    self._write_slab(
                        new_page, self._offset(arrays, n_nodes, new_page),
                        n_nodes=n_nodes,
                    )
                except Exception:
                    self._free.insert(0, new_page)  # never leak the page
                    raise
                self._index_page(new_page, chash)
                self._tenant_page[tenant] = new_page
                self._page_refs[new_page] = 1
                self._tenant_tables[tenant] = tables
                if pre_flip is not None:
                    pre_flip()
                self._flip(tenant, new_page)
                self.counters["assigns"] += 1
                return "assign"
            if not shared:
                self._write_slab(
                    page, self._offset(arrays, n_nodes, page),
                    n_nodes=n_nodes,
                )
                self._index_page(page, chash)
                self._tenant_tables[tenant] = tables
                self.counters["assigns"] += 1
                return "rewrite"
            # shared page + structural edit: full bake into a private
            # page (the CoW slow path)
            return self._cow_install(
                tenant, page, arrays, n_nodes, chash, tables, pre_flip,
            )

    def _write_trunk(self, page: int, trunk_arrays, n_nodes: int,
                     tkey: bytes) -> None:
        """Bake a decomposed trunk slab into ``page`` and index it
        under its domain-tagged key (b"T" + hash): trunk bytes are
        content-canonical across structurally-identical tenants, so N
        near-copies share ONE trunk page."""
        self._write_slab(
            page, self._offset(trunk_arrays, n_nodes, page),
            n_nodes=n_nodes,
        )
        self._page_decomposed.add(page)
        self._index_page(page, tkey)

    def _load_tenant_spliced(self, tenant: int, tables: CompiledTables,
                             hint, pre_flip) -> str:
        """The decomposed install: rules-only edits route through
        _splice_edit (touched subtrees only); otherwise decompose,
        acquire planes content-addressed, land/share the trunk, write
        the splice rows to the inactive bank and publish page + bank in
        one flip.  Tables that don't decompose (or plane-pool
        exhaustion) degrade to the whole-slab path."""
        page = self._tenant_page.get(tenant)
        old = self._tenant_tables.get(tenant)
        if (page is not None and old is not None and hint is not None
                and self._tenant_splices.get(tenant)
                and hint_trie_unchanged(hint)):
            r = self._splice_edit(tenant, page, old, tables, hint,
                                  pre_flip)
            if r is not None:
                return r
        dec = self._bake_decomposed(tables)
        if dec is None:
            # a previously-spliced tenant's hint describes an edit
            # against DECOMPOSED residency — never let the whole-slab
            # fast paths patch a trunk as if it were a flat slab
            if self._tenant_splices.get(tenant):
                hint = None
            r = self._load_tenant_whole(tenant, tables, hint, pre_flip)
            self._drop_tenant_planes(tenant)
            return r
        trunk_arrays, n_nodes, tkey, metas = dec
        hits0 = self.counters["plane_hits"]
        got: list = []
        try:
            for m in metas:
                got.append(self._acquire_plane(m))
        except _PlaneCapacityError:
            for ps in got:
                self._plane_decref(ps)
            r = self._load_tenant_whole(tenant, tables, None, pre_flip)
            self._drop_tenant_planes(tenant)
            return r
        all_hit = (self.counters["plane_hits"] - hits0) == len(metas)
        shared_trunk = self._hash_page.get(tkey)
        wrote = False
        try:
            if shared_trunk is not None:
                target = shared_trunk
            elif page is not None and not self._is_shared(page):
                self._write_trunk(page, trunk_arrays, n_nodes, tkey)
                target = page
                wrote = True
            else:
                target = self._alloc_page()
                try:
                    self._write_trunk(target, trunk_arrays, n_nodes, tkey)
                except Exception:
                    self._free.insert(0, target)
                    raise
                wrote = True
        except ArenaCapacityError:
            for ps in got:
                self._plane_decref(ps)
            raise
        old_map = dict(self._tenant_splices.get(tenant) or {})
        slot_map = {m.slot: ps for m, ps in zip(metas, got)}
        self._write_splice_rows(tenant, slot_map)
        self._tenant_splices[tenant] = slot_map
        self._tenant_splice_meta[tenant] = metas
        if target != page:
            self._tenant_page[tenant] = target
            self._incref(target)
        self._tenant_tables[tenant] = tables
        if pre_flip is not None:
            pre_flip()
        self._flip(tenant, target)
        if page is not None and target != page:
            self._decref(page)
        for ps in old_map.values():
            self._plane_decref(ps)
        if shared_trunk is not None and all_hit:
            self.counters["shared_hits"] += 1
            return "share"
        if page is None:
            self.counters["assigns"] += 1
            return "assign"
        if wrote and target == page:
            self.counters["assigns"] += 1
            return "rewrite"
        if target != page:
            if wrote:
                self.counters["cow_clones"] += 1
                return "cow"
            self.counters["shared_hits"] += 1
            return "share"
        # same trunk page; "unsplice" when the plane set changed
        return "unsplice" if slot_map != old_map else "share"

    def _splice_edit(self, tenant: int, page: int, old, new, hint,
                     pre_flip):
        """Rules-only edit of a spliced tenant, routed per dirty joined
        row to its owning subtree: trunk-owned rows patch the (private)
        trunk in place; plane-owned rows patch a private plane in place
        (lazy re-hash) or UNSPLICE a shared plane — repoint just that
        slot at a freshly-written private plane (or re-share an
        already-resident identical one), publish the new splice map via
        bank write + flip, and decrement the old plane's refcount (the
        spliceleak injection site).  Returns None when the edit can't
        be expressed this way (caller falls back to the decomposed full
        install)."""
        metas = self._tenant_splice_meta.get(tenant)
        cur = self._tenant_splices.get(tenant)
        if not metas or not cur:
            return None
        dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
        dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
        _seed_ctrie_caches_forward(old, new, dirty)
        pr = _joined_tidx_patch_rows(new, dirty)
        if pr is None:
            return None
        pos, rows = pr
        if len(pos) and (
            int(pos.max()) >= self.spec.joined_rows
            or rows.shape[1] != self._dev.joined.shape[1]
        ):
            return None
        if len(pos) == 0:
            self._tenant_tables[tenant] = new
            self.counters["patches"] += 1
            return "patch"
        rowmap = {int(p): rows[j] for j, p in enumerate(pos.tolist())}
        own: dict = {}
        for i, m in enumerate(metas):
            for v in m.tidx.tolist():
                own[v] = i
        trunk_pos: list = []
        by_slot: dict = {}
        for p in pos.tolist():
            i = own.get(int(p))
            if i is None:
                trunk_pos.append(int(p))
            else:
                by_slot.setdefault(i, []).append(int(p))
        if trunk_pos and self._is_shared(page):
            # trunk CoW: route through the full decomposed install
            return None
        # plan plane actions BEFORE mutating anything, so a plane-pool
        # shortage (or a merge-target hazard) bails cleanly
        plans: list = []
        allocs = 0
        dropping: set = set()
        for i in sorted(by_slot):
            plist = by_slot[i]
            m = metas[i]
            ps = cur.get(m.slot)
            if ps is None:
                return None
            pn, pt, pj, n_local = self._canonical_of_plane(ps)
            if n_local != m.n_local:
                return None
            pj2 = np.array(pj, copy=True)
            for p in plist:
                j = int(np.searchsorted(m.tidx, p))
                if j >= len(m.tidx) or int(m.tidx[j]) != p:
                    return None
                pj2[1 + j] = rowmap[p]
            if not self._plane_is_shared(ps):
                plans.append(("patch", m, ps, plist, None, None))
                continue
            plane = (pn, pt, pj2)
            h = slab_content_hash(plane, n_local)
            tgt = self._hash_plane.get(h)
            if tgt is not None and tgt != ps:
                plans.append(("merge", m, ps, plist, tgt, None))
            else:
                plans.append(("unsplice", m, ps, plist, None, (plane, h)))
                allocs += 1
            dropping.add(ps)
        if allocs > len(self._plane_free):
            return None
        for kind, _m, _ps, _pl, tgt, _b in plans:
            if kind == "merge" and tgt in dropping:
                # the merge target is itself being dropped this edit —
                # ordering hazard; take the full-install path instead
                return None
        if trunk_pos:
            gpos = (
                page * self.spec.joined_rows
                + np.array(trunk_pos, np.int64)
            )
            vals = np.stack([rowmap[p] for p in trunk_pos])
            self._host["joined"][gpos] = vals
            joined = _capped_scatter(
                self._dev.joined, gpos, vals, self._device
            )
            if joined is None:
                return None
            self._dev = self._dev._replace(joined=joined)
            self._mark_hash_dirty(page)
        changed: dict = {}
        for kind, m, ps, plist, tgt, built in plans:
            if kind == "patch":
                jb = self._plane_base(ps)[2]
                lpos = np.array(
                    [jb + 1 + int(np.searchsorted(m.tidx, p))
                     for p in plist],
                    np.int64,
                )
                vals = np.stack([rowmap[p] for p in plist])
                self._host["joined"][lpos] = vals
                joined = _capped_scatter(
                    self._dev.joined, lpos, vals, self._device
                )
                if joined is None:
                    return None
                self._dev = self._dev._replace(joined=joined)
                self._unindex_plane(ps)
                self._plane_hash_dirty.add(ps)
            elif kind == "merge":
                self._plane_incref(tgt)
                changed[m.slot] = tgt
                self.counters["splice_merges"] += 1
                self._plane_decref(ps, from_unsplice=True)
            else:
                plane, h = built
                nps = self._alloc_plane()
                try:
                    self._write_plane(nps, plane, m.n_local)
                except Exception:
                    if nps not in self._plane_free:
                        self._plane_free.insert(0, nps)
                    raise
                self._index_plane(nps, h)
                self._plane_refs[nps] = 1
                changed[m.slot] = nps
                self.counters["splice_unsplices"] += 1
                self._plane_decref(ps, from_unsplice=True)
        if changed:
            newmap = dict(cur)
            newmap.update(changed)
            self._write_splice_rows(tenant, newmap)
            self._tenant_splices[tenant] = newmap
            if pre_flip is not None:
                pre_flip()
            self._flip(tenant, page)
        self._tenant_tables[tenant] = new
        self.counters["patches"] += 1
        return "unsplice" if changed else "patch"

    @must_precede("pre_flip", "_flip")
    def _cow_install(self, tenant, donor, arrays, n_nodes, chash,
                     tables, pre_flip) -> str:
        """The CoW landing sequence: write the private copy into a free
        page (ONE warmed full-slab fused scatter — the clone and the
        patch land together), refresh planes (pre_flip), flip the
        editing tenant's page-table row, and only then decrement the
        donor's refcount — every other sharer serves the untouched
        donor slab throughout (no serving gap)."""
        new_page = self._alloc_page()
        try:
            self._write_slab(
                new_page, self._offset(arrays, n_nodes, new_page),
                n_nodes=n_nodes,
            )
        except Exception:
            self._free.insert(0, new_page)
            raise
        if chash is not None:
            self._index_page(new_page, chash)
        else:
            # clone-then-patch: content hash unknown (computing it
            # would cost the O(slab) pass the clone skipped) — the
            # dedup sweep re-hashes in the background
            self._hash_dirty.add(new_page)
        self._tenant_page[tenant] = new_page
        self._page_refs[new_page] = 1
        self._tenant_tables[tenant] = tables
        if pre_flip is not None:
            pre_flip()
        self._flip(tenant, new_page)
        if _inject_cowrace_bug():
            # TEST-ONLY (cowrace defect): defer the donor decref past
            # the lock release — load_tenant lands it unlocked
            self._cowrace_pending = donor
        else:
            self._decref(donor, from_clone=True)
        self.counters["cow_clones"] += 1
        return "cow"

    def _try_patch(self, tenant, page, old, new, hint) -> bool:
        """Rules-only per-slab patch (the Map.Update analogue inside
        one slab): hinted dense rows / dirty joined tidx rows scatter
        at slab-base-offset positions through the shared fused
        executable.  False -> caller falls back to the slab rewrite."""
        if not hint_trie_unchanged(hint):
            return False
        dirty = np.unique(np.asarray(hint.get("dense", ()), np.int64))
        dirty = dirty[(dirty >= 0) & (dirty < new.rules.shape[0])]
        if self.spec.family == "dense":
            kw, mw, ml, rules, _lv, _tg, _lut, _j = _host_device_layout(
                new, pad=False, with_trie=False
            )
            if rules.dtype != np.uint16 or (
                rules.shape[1] != self.spec.rule_slots * 5
                or kw.shape[0] > self.spec.entries
            ):
                return False
            base = page * self.spec.entries
            rows = dirty[dirty < kw.shape[0]]
            entries = []
            for name, src in zip(
                ("key_words", "mask_words", "mask_len", "rules"),
                (kw, mw, ml, rules),
            ):
                vals = src[rows]
                self._host[name][base + rows] = vals
                entries.append((getattr(self._dev, name), base + rows, vals))
            patched = txn_scatter(entries, self._device)
            if patched is None:
                return False
            self._dev = self._dev._replace(
                **dict(zip(("key_words", "mask_words", "mask_len", "rules"),
                           patched))
            )
            return True
        # ctrie family: seed caches forward, then scatter the dirty
        # joined rows at the slab base
        _seed_ctrie_caches_forward(old, new, dirty)
        pr = _joined_tidx_patch_rows(new, dirty)
        if pr is None:
            return False
        pos, rows = pr
        if len(pos) and (
            int(pos.max()) >= self.spec.joined_rows
            or rows.shape[1] != self._dev.joined.shape[1]
        ):
            return False
        if len(pos) == 0:
            return True
        gpos = page * self.spec.joined_rows + pos
        self._host["joined"][gpos] = rows
        joined = _capped_scatter(self._dev.joined, gpos, rows, self._device)
        if joined is None:
            return False
        self._dev = self._dev._replace(joined=joined)
        return True

    def stage(self, tables: CompiledTables) -> int:
        """Content-addressed staging: hash the canonical bake and, on
        an index HIT, reserve the ALREADY-RESIDENT page (a hold — no
        bake, no device write; N stages of the same baseline cost one
        slab).  On a miss, bake into a free page and index it.  Returns
        the staged page id (reserved until activate/release)."""
        with self._lock:
            if self._spliced:
                dec = self._bake_decomposed(tables)
                if dec is not None:
                    try:
                        return self._stage_spliced(dec)
                    except _PlaneCapacityError:
                        pass  # degrade to whole-slab staging
            arrays, n_nodes, chash = self._bake_canonical(tables)
            hit = self._hash_page.get(chash)
            if hit is not None:
                self._page_holds[hit] = self._page_holds.get(hit, 0) + 1
                self.counters["shared_hits"] += 1
                return hit
            page = self._alloc_page()
            try:
                self._write_slab(
                    page, self._offset(arrays, n_nodes, page),
                    n_nodes=n_nodes,
                )
            except Exception:
                self._free.insert(0, page)
                raise
            self._index_page(page, chash)
            self._page_holds[page] = self._page_holds.get(page, 0) + 1
            return page

    def _stage_spliced(self, dec) -> int:
        """Decomposed staging: hold the shared planes (writing the
        missing ones) plus the trunk page, and record the splice PLAN
        on the page's stack — activate() rederives the plan from the
        tables and consumes it (holds become refs), release() pops it.
        Raises _PlaneCapacityError (rolled back) for the whole-slab
        fallback."""
        trunk_arrays, n_nodes, tkey, metas = dec
        got: list = []
        try:
            for m in metas:
                ps = self._hash_plane.get(m.phash)
                if ps is None:
                    ps = self._alloc_plane()
                    try:
                        self._write_plane(ps, m.plane, m.n_local)
                    except Exception:
                        if ps not in self._plane_free:
                            self._plane_free.insert(0, ps)
                        raise
                    self._index_plane(ps, m.phash)
                else:
                    self.counters["plane_hits"] += 1
                self._plane_holds[ps] = self._plane_holds.get(ps, 0) + 1
                got.append(ps)
        except _PlaneCapacityError:
            for ps in got:
                self._release_plane_hold(ps)
            raise
        hit = self._hash_page.get(tkey)
        if hit is not None:
            self._page_holds[hit] = self._page_holds.get(hit, 0) + 1
            self.counters["shared_hits"] += 1
            page = hit
        else:
            page = self._alloc_page()
            try:
                self._write_trunk(page, trunk_arrays, n_nodes, tkey)
            except Exception:
                self._free.insert(0, page)
                for ps in got:
                    self._release_plane_hold(ps)
                raise
            self._page_holds[page] = self._page_holds.get(page, 0) + 1
        self._stage_plans.setdefault(page, []).append(
            tuple((m.slot, ps) for m, ps in zip(metas, got))
        )
        return page

    def _take_stage_plan(self, page: int, tables):
        """Match + pop the staged splice plan for (page, tables):
        (plan, metas) when this page was splice-staged for these
        tables, else None (whole-slab activate).  The memoized
        decompose plus the held planes' stable hash index make the
        rederivation exact."""
        if not self._spliced or tables is None:
            return None
        plans = self._stage_plans.get(page)
        if not plans:
            return None
        dec = self._bake_decomposed(tables)
        if dec is None:
            return None
        _trunk, _nn, _tkey, metas = dec
        want = tuple(
            (m.slot, self._hash_plane.get(m.phash)) for m in metas
        )
        if any(ps is None for _slot, ps in want) or want not in plans:
            return None
        plans.remove(want)
        if not plans:
            self._stage_plans.pop(page, None)
        return want, metas

    def _activate_spliced(self, tenant: int, page: int, tables,
                          plan, metas) -> None:
        """Activate a splice-staged page: consume the plane holds into
        splice-row references, write the tenant's splice rows to the
        inactive bank, and publish page + bank in ONE flip — the
        spliced hot-swap stays O(1) page-table scatter + K splice
        rows."""
        if page in self._free:
            self._free.remove(page)
            self._hash_dirty.add(page)
        h = self._page_holds.get(page, 0)
        if h:  # consume one stage reservation
            if h == 1:
                self._page_holds.pop(page, None)
            else:
                self._page_holds[page] = h - 1
        old_page = self._tenant_page.get(tenant)
        old_map = dict(self._tenant_splices.get(tenant) or {})
        slot_map: dict = {}
        for slot, ps in plan:
            self._plane_incref(ps)
            self._release_plane_hold(ps)
            slot_map[slot] = ps
        self._write_splice_rows(tenant, slot_map)
        self._tenant_splices[tenant] = slot_map
        self._tenant_splice_meta[tenant] = metas
        self._tenant_page[tenant] = page
        self._tenant_tables[tenant] = tables
        if old_page != page:
            self._incref(page)
        self._flip(
            tenant, page,
            _inject=_inject_pageflip_bug() and old_page is not None,
        )
        if old_page is not None and old_page != page:
            self._decref(old_page)
        for ps in old_map.values():
            self._plane_decref(ps)
        self.counters["swaps"] += 1

    def release(self, page: int) -> None:
        """Drop one staged-but-never-activated reservation; the page
        frees when no references and no other holds remain.  On a
        spliced arena a splice-staged reservation also releases its
        plan's plane holds (plans pop LIFO per page)."""
        with self._lock:
            plans = self._stage_plans.get(page)
            if plans:
                plan = plans.pop()
                if not plans:
                    self._stage_plans.pop(page, None)
                for _slot, ps in plan:
                    self._release_plane_hold(ps)
            h = self._page_holds.get(page, 0)
            if h <= 0:
                return
            if h == 1:
                self._page_holds.pop(page, None)
            else:
                self._page_holds[page] = h - 1
            if (
                self._page_refs.get(page, 0) == 0
                and self._page_holds.get(page, 0) == 0
            ):
                self._release_page(page)

    def activate(self, tenant: int, page: int,
                 tables: Optional[CompiledTables] = None) -> None:
        """Hot-swap: flip the tenant's page-table row to a staged (or
        shared) page — O(1) scatter — bump its refcount, and decrement
        the previous slab's.  THE measured swap path of bench_tenant.
        Activating a page live for ANOTHER tenant is sharing, not an
        error: both tenants' rows reference one refcounted slab."""
        self._check_tenant(tenant)
        with self._lock:
            taken = self._take_stage_plan(page, tables)
            if taken is not None:
                return self._activate_spliced(
                    tenant, page, tables, taken[0], taken[1]
                )
            # a re-activated page may sit on the free list (the
            # ping-pong standby pattern drops the previous page to
            # refcount 0 on each flip): claim it back — the slab bytes
            # persisted — and mark it for a dedup re-hash
            if page in self._free:
                self._free.remove(page)
                self._hash_dirty.add(page)
            h = self._page_holds.get(page, 0)
            if h:  # consume one stage reservation
                if h == 1:
                    self._page_holds.pop(page, None)
                else:
                    self._page_holds[page] = h - 1
            old_page = self._tenant_page.get(tenant)
            self._tenant_page[tenant] = page
            if tables is not None:
                self._tenant_tables[tenant] = tables
            else:
                # the previous table no longer describes the slab now
                # serving; a stale record would let a later CoW patch
                # apply against the PRE-swap ruleset — drop it (the
                # canonical mirror keeps the page movable regardless)
                self._tenant_tables.pop(tenant, None)
            if old_page != page:
                self._incref(page)
            # the injected pageflip defect fires ONLY on the swap of an
            # already-resident tenant — the exact transition the
            # statecheck acceptance gate must prove is covered
            self._flip(
                tenant, page,
                _inject=_inject_pageflip_bug() and old_page is not None,
            )
            if old_page is not None and old_page != page:
                self._decref(old_page)
            if self._spliced and self._tenant_splices.get(tenant):
                # the tenant now serves whole-slab content; its splice
                # rows are unread (untagged l0) — release the planes
                self._drop_tenant_planes(tenant)
            self.counters["swaps"] += 1

    def swap_tenant(self, tenant: int, tables: CompiledTables) -> None:
        """stage + activate in one call (the non-prestaged swap)."""
        page = self.stage(tables)
        self.activate(tenant, page, tables)

    def destroy_tenant(self, tenant: int) -> None:
        """Flip the tenant's row to -1 and drop its reference: a page
        SHARED with other tenants survives (they keep serving it); a
        private page frees."""
        self._check_tenant(tenant)
        with self._lock:
            page = self._tenant_page.pop(tenant, None)
            self._tenant_tables.pop(tenant, None)
            self._flip(tenant, -1)
            if page is not None:
                self._decref(page)
            if self._spliced:
                self._drop_tenant_planes(tenant)
                self._tenant_bank.pop(tenant, None)
            self.counters["destroys"] += 1

    def compact(self) -> int:
        """Repack live slabs into the lowest-numbered pages so a long
        create/destroy churn leaves the occupied region contiguous.
        Each move rebakes the page from its CANONICAL mirror (no tenant
        tables needed — shared and tables-less pages move too), then
        flips EVERY sharer's page-table row; the donor page is
        reclaimed only after the last row has flipped, so there is no
        serving gap (rows flip one warmed scatter at a time, but both
        pages hold identical content throughout the window).  Staged
        pages (live holds) are pinned: their page id is a reservation
        some caller will activate.  Returns tenant rows moved."""
        moved = 0
        with self._lock:
            while True:
                live = sorted(
                    p for p in self._page_refs
                    if self._page_holds.get(p, 0) == 0
                )
                src = tgt = None
                for p in reversed(live):
                    lower = [f for f in self._free if f < p]
                    if lower:
                        src, tgt = p, min(lower)
                        break
                if src is None:
                    break
                arrays = tuple(
                    np.array(a, copy=True)
                    for a in self._canonical_of_page(src)
                )
                n_nodes = self._page_nnodes.get(src, 0)
                self._free.remove(tgt)
                self._write_slab(
                    tgt, self._offset(arrays, n_nodes, tgt),
                    n_nodes=n_nodes,
                )
                # transfer refcount + hash-index identity to the new
                # page BEFORE the flips (bookkeeping must never lag the
                # device rows)
                self._page_refs[tgt] = self._page_refs.pop(src)
                if src in self._page_decomposed:
                    # the moved slab is a trunk; the flag (like
                    # _page_nnodes) persists on src for claim-back
                    self._page_decomposed.add(tgt)
                chash = self._page_hash.pop(src, None)
                if chash is not None and self._hash_page.get(chash) == src:
                    self._hash_page[chash] = tgt
                    self._page_hash[tgt] = chash
                elif src in self._hash_dirty:
                    self._hash_dirty.discard(src)
                    self._hash_dirty.add(tgt)
                sharers = sorted(
                    t for t, p in self._tenant_page.items() if p == src
                )
                for t in sharers:
                    self._tenant_page[t] = tgt
                    self._flip(t, tgt)
                    moved += 1
                # every sharer's row has flipped; only now reclaim
                if src not in self._free:
                    self._free.append(src)
            self._free.sort()
            if moved:
                self.counters["compactions"] += 1
        return moved

    def dedup_sweep(self, limit: Optional[int] = None) -> dict:
        """Background re-merge (the lazy half of content addressing):
        re-hash pages whose content hash went stale (in-place patch,
        CoW clone, free-list claim-back), re-index them, and MERGE
        pages whose content re-converged with an already-indexed page —
        every tenant of the duplicate flips onto the canonical page
        (warmed 1-row scatters, old slab serves until its row flips),
        then the duplicate frees.  Staged pages re-index but never
        merge away (their page id is a live reservation).  Compile-free
        by construction.  Returns {"hashed", "merged", "moved"} —
        ``moved`` lists tenant ids whose physical page changed, so the
        classifier wrapper can re-steer flow slabs."""
        hashed = 0
        moved: list = []
        with self._lock:
            dirty = sorted(self._hash_dirty)
            if limit is not None:
                dirty = dirty[: max(int(limit), 0)]
            for page in dirty:
                if (
                    self._page_refs.get(page, 0) == 0
                    and self._page_holds.get(page, 0) == 0
                ):
                    self._hash_dirty.discard(page)
                    continue
                chash = slab_content_hash(
                    self._canonical_of_page(page),
                    self._page_nnodes.get(page, 0),
                )
                if page in self._page_decomposed:
                    # trunk slabs hash in their own domain: a trunk
                    # must never dedup against a whole slab of
                    # coincidentally-equal bytes (their l0 tags mean
                    # different things)
                    chash = b"T" + chash
                hashed += 1
                cur = self._hash_page.get(chash)
                if cur is None or cur == page:
                    self._index_page(page, chash)
                    continue
                if self._page_holds.get(page, 0):
                    self._hash_dirty.discard(page)
                    continue
                sharers = sorted(
                    t for t, p in self._tenant_page.items() if p == page
                )
                for t in sharers:
                    self._tenant_page[t] = cur
                    self._incref(cur)
                    self._flip(t, cur)
                    self._decref(page)
                    moved.append(t)
                self._hash_dirty.discard(page)
                if sharers:
                    self.counters["dedup_merges"] += 1
            plane_merged = 0
            if self._spliced:
                plane_merged = self._dedup_planes(limit)
        rep = {"hashed": hashed, "merged": len(moved), "moved": moved}
        if self._spliced:
            rep["plane_merged"] = plane_merged
        return rep

    def _dedup_planes(self, limit: Optional[int] = None) -> int:
        """The plane half of dedup_sweep: re-hash hash-dirty planes
        (in-place plane patches), re-index them, and MERGE planes whose
        content re-converged — every splice row of the duplicate
        repoints at the canonical plane (K-row bank write + 1-row flip
        per affected tenant, old plane serves until its rows flip),
        then the duplicate frees.  Held planes re-index but never merge
        away.  Returns planes merged."""
        merged = 0
        pdirty = sorted(self._plane_hash_dirty)
        if limit is not None:
            pdirty = pdirty[: max(int(limit), 0)]
        for ps in pdirty:
            if (
                self._plane_refs.get(ps, 0) == 0
                and self._plane_holds.get(ps, 0) == 0
            ):
                self._plane_hash_dirty.discard(ps)
                continue
            pn, pt, pj, n_local = self._canonical_of_plane(ps)
            h = slab_content_hash((pn, pt, pj), n_local)
            cur = self._hash_plane.get(h)
            if cur is None or cur == ps:
                self._index_plane(ps, h)
                continue
            if self._plane_holds.get(ps, 0):
                self._plane_hash_dirty.discard(ps)
                continue
            affected = sorted(
                t for t, smap in self._tenant_splices.items()
                if ps in smap.values()
            )
            for t in affected:
                smap = self._tenant_splices[t]
                newmap: dict = {}
                for slot, v in smap.items():
                    if v == ps:
                        self._plane_incref(cur)
                        newmap[slot] = cur
                    else:
                        newmap[slot] = v
                self._write_splice_rows(t, newmap)
                self._tenant_splices[t] = newmap
                self._flip(t, self._tenant_page[t])
                for v in smap.values():
                    if v == ps:
                        self._plane_decref(ps)
            self._plane_hash_dirty.discard(ps)
            if affected:
                merged += 1
                self.counters["splice_merges"] += 1
        return merged


# === stateful flow tier (device-resident connection tracking) ================
#
# The exact-match verdict cache in front of the LPM + rule scan (ISSUE-11,
# the SDN flow-table pattern): a W-way set-associative hash table in fixed
# -shape JAX tensors, keyed by the FULL set of verdict-relevant packet
# fields (tenant, ifindex, source IP words, proto, dst_port, icmp
# type/code, kind, l4_ok), so a hit can serve the cached res16 verdict
# with bit-identical semantics to the stateless path — the invariant the
# flow statecheck configs and bench_flow gate on.  Layout is columnar
# (one tensor per field, C = pages * slab_entries rows) with per-tenant
# SLABS: the per-packet tenant column steers the slot range exactly the
# way the arena page table steers classification, and the key embeds the
# tenant id so a paging bug can never serve one tenant's verdict to
# another (isolation is key-level, not just slab-level).
#
# Mutations are all deterministic scatter forms (add / max / min / set at
# per-slot-unique winner lanes), so the numpy host model
# (infw.flow.HostFlowModel) replays them bit-exactly — the model-checker
# compares device columns against the model after every settled op.
#
# Invalidation is GENERATIONAL: every entry records the per-tenant
# ruleset generation at insert time and a hit requires it to still match
# ``gens[tenant]`` — a patch transaction, tenant swap or full reload
# bumps the generation (backend/tpu.py load_tables / tenant lifecycle)
# and every resident flow verdict of that tenant goes stale at once,
# with no O(table) flush on the mutation path.

#: TCP flag bits of the optional per-packet flags column (PacketBatch
#: .tcp_flags); 0 (the default when the ingest source carries no flags)
#: degrades the TCP model to established-on-first-packet.
TCP_FIN = 0x01
TCP_SYN = 0x02
TCP_RST = 0x04
TCP_ACK = 0x10

#: flow entry states (the TCP-state bitmap column): EMPTY slots are
#: free; NEW = TCP flow that has only shown a pure SYN (tracked but NOT
#: serve-eligible — SYN floods never graduate into the fast path); EST
#: and FIN short-circuit classification.
FLOW_EMPTY = 0
FLOW_NEW = 1
FLOW_EST = 2
FLOW_FIN = 3

FLOW_KEY_WORDS = 8


class FlowTable(NamedTuple):
    """Device-resident flow columns (C = pages * slab_entries rows).
    Mutable state is packed into THREE narrow matrices so the probe's
    in-kernel updates are 3 scatter ops, not 6 — scatter op count is
    what the probe's cost scales with."""

    keys: jax.Array  # (C, 8) uint32 [tenant, ifindex, ip0..3, m0, m1]
    vg: jax.Array    # (C, 2) int32 [cached res16 verdict, tenant gen]
    se: jax.Array    # (C, 2) int32 [FLOW_* state, last-seen epoch]
    cnt: jax.Array   # (C, 3) int32 [pkts, sum(len>>8), sum(len&0xFF)]


def flow_key_words(batch: DeviceBatch, tenant: jax.Array) -> jax.Array:
    """(B, 8) uint32 exact-match key covering every field the verdict
    depends on (pkt_len only feeds statistics, never the verdict)."""
    m0 = (
        (batch.proto.astype(jnp.uint32) & 0xFF)
        | ((batch.dst_port.astype(jnp.uint32) & 0xFFFF) << 8)
        | ((batch.kind.astype(jnp.uint32) & 3) << 24)
        | ((batch.l4_ok.astype(jnp.uint32) & 1) << 26)
    )
    m1 = (batch.icmp_type.astype(jnp.uint32) & 0xFF) | (
        (batch.icmp_code.astype(jnp.uint32) & 0xFF) << 8
    )
    return jnp.stack(
        [
            tenant.astype(jnp.uint32),
            batch.ifindex.astype(jnp.uint32),
            batch.ip_words[:, 0].astype(jnp.uint32),
            batch.ip_words[:, 1].astype(jnp.uint32),
            batch.ip_words[:, 2].astype(jnp.uint32),
            batch.ip_words[:, 3].astype(jnp.uint32),
            m0,
            m1,
        ],
        axis=1,
    )


def _flow_hash(keys: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """FNV-1a over the 8 key words -> (h1, h2) uint32; h2 is forced odd
    so the double-hash probe sequence visits distinct slots in a pow2
    slab.  Pure wrapping u32 arithmetic — the numpy model computes the
    identical values."""
    h = jnp.full(keys.shape[:1], 0x811C9DC5, jnp.uint32)
    for w in range(FLOW_KEY_WORDS):
        h = (h ^ keys[:, w].astype(jnp.uint32)) * jnp.uint32(0x01000193)
    return h, (h >> 16) | jnp.uint32(1)


def _flow_slots(
    keys: jax.Array, page: jax.Array, *, slab_entries: int, ways: int
) -> jax.Array:
    """(B, W) int32 global candidate slot ids (page-slab-local double
    hashing); ``slab_entries`` must be a power of two."""
    h1, h2 = _flow_hash(keys)
    w = jnp.arange(ways, dtype=jnp.uint32)[None, :]
    local = (h1[:, None] + w * h2[:, None]) & jnp.uint32(slab_entries - 1)
    return (
        jnp.clip(page, 0)[:, None] * slab_entries + local.astype(jnp.int32)
    )


def _pack_bits32(mask: jax.Array) -> jax.Array:
    """(B,) bool -> (ceil(B/32),) int32 LSB-first bitmap words."""
    b = mask.shape[0]
    nw = -(-b // 32)
    m = jnp.zeros(nw * 32, jnp.uint32).at[: b].set(mask.astype(jnp.uint32))
    words = jnp.sum(
        m.reshape(nw, 32) << jnp.arange(32, dtype=jnp.uint32)[None, :],
        axis=1, dtype=jnp.uint32,
    )
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def unpack_bits32_host(words: np.ndarray, b: int) -> np.ndarray:
    """Host inverse of _pack_bits32 -> (b,) bool."""
    u = np.asarray(words).view(np.uint32)
    bits = (u[:, None] >> np.arange(32, dtype=np.uint32)[None, :]) & 1
    return bits.reshape(-1)[:b].astype(bool)


def _flow_probe_parts(
    flow: FlowTable, gens: jax.Array, page_table: jax.Array,
    batch: DeviceBatch, tenant: jax.Array, tflags: jax.Array,
    epoch_now: jax.Array, max_age: jax.Array,
    *, slab_entries: int, ways: int,
):
    """The shared probe body -> (served u32 verdicts, hit mask, stale
    mask, updated mutable columns) — un-fused so the resident serving
    step (jitted_resident_step) can compose the probe with the stateless
    classify and the miss insert inside ONE device program; the classic
    probe dispatch (_flow_probe_core) packs these parts into its fused
    readback buffer bit-identically.

    A hit requires: eligible lane (real IP, l4 parsed, tenant mapped to
    a flow slab), exact 8-word key match, serve-eligible state (>= EST),
    matching tenant generation, and a last-seen epoch within ``max_age``
    of ``epoch_now``.  Hits update per-flow counters/epoch in-kernel and
    apply the RST/FIN teardown transitions; a key match failing ONLY the
    generation check counts as a stale reject (the invalidation metric).
    Per-ruleId statistics for the served lanes derive HOST-side from the
    returned res16 + pkt_len (the wire8 readback contract), so the probe
    ships no stats tensor."""
    C = flow.se.shape[0]
    page = _arena_pages(page_table, tenant)
    keyw = flow_key_words(batch, tenant)
    is_ip = (batch.kind == KIND_IPV4) | (batch.kind == KIND_IPV6)
    elig = is_ip & (batch.l4_ok != 0) & (page >= 0)
    cand = _flow_slots(keyw, page, slab_entries=slab_entries, ways=ways)
    ek = jnp.take(flow.keys, cand, axis=0, mode="clip")     # (B, W, 8)
    ese = jnp.take(flow.se, cand, axis=0, mode="clip")      # (B, W, 2)
    evg = jnp.take(flow.vg, cand, axis=0, mode="clip")
    match = jnp.all(ek == keyw[:, None, :], axis=2) & elig[:, None]
    live = ese[:, :, 0] >= FLOW_EST
    mygen = jnp.take(gens, jnp.clip(tenant, 0, gens.shape[0] - 1),
                     mode="clip")
    gen_ok = evg[:, :, 1] == mygen[:, None]
    fresh = (epoch_now - ese[:, :, 1]) <= max_age
    hit_w = match & live & gen_ok & fresh
    stale_w = match & live & fresh & ~gen_ok
    W = ways
    widx = jnp.arange(W, dtype=jnp.int32)[None, :]
    first = jnp.min(jnp.where(hit_w, widx, W), axis=1)
    hit = first < W
    sel = jnp.sum(jnp.where(widx == first[:, None], cand, 0), axis=1)
    stale = jnp.any(stale_w, axis=1) & ~hit
    slot = jnp.where(hit, sel, C)  # C = dropped by scatter mode="drop"

    served = jnp.where(
        hit,
        jnp.sum(jnp.where(widx == first[:, None], evg[:, :, 0], 0), axis=1),
        0,
    ).astype(jnp.uint32)

    ln = batch.pkt_len
    cnt = flow.cnt.at[slot].add(
        jnp.stack(
            [jnp.ones_like(ln), (ln >> 8) & 0xFFFFFF, ln & 0xFF], axis=1
        ),
        mode="drop",
    )
    is_tcp = batch.proto == IPPROTO_TCP
    fin = is_tcp & ((tflags & TCP_FIN) != 0)
    rst = is_tcp & ((tflags & TCP_RST) != 0)
    # ONE max-scatter carries both the FIN half-close transition and the
    # last-seen refresh (epoch_now >= any stored epoch by monotonicity);
    # one min-scatter applies RST teardown
    big = jnp.int32(np.iinfo(np.int32).max)
    se = flow.se.at[slot].max(
        jnp.stack(
            [
                jnp.where(hit & fin, FLOW_FIN, -1).astype(jnp.int32),
                jnp.broadcast_to(epoch_now, slot.shape).astype(jnp.int32),
            ],
            axis=1,
        ),
        mode="drop",
    )
    se = se.at[jnp.where(hit & rst, slot, C)].min(
        jnp.stack(
            [jnp.full_like(slot, FLOW_EMPTY), jnp.full_like(slot, big)],
            axis=1,
        ),
        mode="drop",
    )
    return served, hit, stale, flow._replace(se=se, cnt=cnt)


def _flow_probe_core(
    flow: FlowTable, gens: jax.Array, page_table: jax.Array,
    batch: DeviceBatch, tenant: jax.Array, tflags: jax.Array,
    epoch_now: jax.Array, max_age: jax.Array,
    *, slab_entries: int, ways: int,
):
    """The classic probe dispatch: _flow_probe_parts packed into the
    fused readback buffer -> (fused output, updated mutable columns)."""
    served, hit, stale, updated = _flow_probe_parts(
        flow, gens, page_table, batch, tenant, tflags, epoch_now,
        max_age, slab_entries=slab_entries, ways=ways,
    )
    fused = jnp.concatenate([
        _pack_res16(served.astype(jnp.uint16)),
        _pack_bits32(hit),
        jnp.stack([
            jnp.sum(hit.astype(jnp.int32)),
            jnp.sum(stale.astype(jnp.int32)),
        ]),
    ])
    return fused, updated


def split_flow_probe_outputs(
    arr: np.ndarray, b: int
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Host inverse of the probe's fused buffer -> (res16[b], hit mask
    (b,) bool, hits, stale)."""
    nw = (b + 1) // 2
    res16 = unpack_res16_host(arr[:nw], b)
    nh = -(-b // 32)
    hit = unpack_bits32_host(arr[nw : nw + nh], b)
    hits, stale = int(arr[nw + nh]), int(arr[nw + nh + 1])
    return res16, hit, hits, stale


@functools.lru_cache(maxsize=None)
def jitted_flow_probe(slab_entries: int, ways: int):
    """The fused flow-probe dispatch: serve cached verdicts + update
    per-flow state in ONE launch.  Cache keyed on the pool geometry
    statics only — batch shape, occupancy, tenant count and generation
    churn never re-specialize (the zero-recompile flow lifecycle)."""
    def f(flow, gens, page_table, wire, tenant, tflags, epoch_now, max_age):
        return _flow_probe_core(
            flow, gens, page_table, unpack_wire(wire), tenant, tflags,
            epoch_now, max_age, slab_entries=slab_entries, ways=ways,
        )

    return jax.jit(f)


def _flow_insert_core(
    flow: FlowTable, gens: jax.Array, page_table: jax.Array,
    batch: DeviceBatch, tenant: jax.Array, tflags: jax.Array,
    verdict16: jax.Array, epoch_now: jax.Array,
    *, slab_entries: int, ways: int, lane_ok: Optional[jax.Array] = None,
):
    """Batch insert of miss-lane verdicts -> (updated FlowTable, (4,)
    int32 [inserts, evictions, promotes, 0]).

    Way choice per lane: an existing slot holding the SAME key (any
    state/generation — re-insert refreshes verdict+generation), else the
    first EMPTY way, else the way with the OLDEST last-seen epoch (LRU
    eviction, counted when it overwrites a different live key).  One
    WINNER lane per slot (the last eligible lane in batch order) does
    the .set() writes, so duplicate-slot scatters stay deterministic;
    per-flow counters initialize from segment sums over ALL eligible
    lanes that chose the slot.

    ``lane_ok`` (the resident fused step) restricts eligibility to a
    caller-provided lane mask — the in-program form of the host-side
    miss compaction: the classic multi-dispatch path compacts the miss
    lanes into a pow2 bucket before this kernel sees them, the fused
    step instead masks the hit lanes out.  Eligible-lane identity and
    relative order are the same either way, so winner selection and the
    counter segment sums stay bit-identical."""
    C = flow.se.shape[0]
    page = _arena_pages(page_table, tenant)
    keyw = flow_key_words(batch, tenant)
    is_ip = (batch.kind == KIND_IPV4) | (batch.kind == KIND_IPV6)
    is_tcp = batch.proto == IPPROTO_TCP
    syn = is_tcp & ((tflags & TCP_SYN) != 0)
    ack = is_tcp & ((tflags & TCP_ACK) != 0)
    fin = is_tcp & ((tflags & TCP_FIN) != 0)
    rst = is_tcp & ((tflags & TCP_RST) != 0)
    elig = is_ip & (batch.l4_ok != 0) & (page >= 0) & ~rst
    if lane_ok is not None:
        elig = elig & lane_ok
    cand = _flow_slots(keyw, page, slab_entries=slab_entries, ways=ways)
    ek = jnp.take(flow.keys, cand, axis=0, mode="clip")
    ese = jnp.take(flow.se, cand, axis=0, mode="clip")
    est = ese[:, :, 0]
    eep = ese[:, :, 1]
    match_w = jnp.all(ek == keyw[:, None, :], axis=2) & (est > 0)
    empty_w = est == 0
    W = ways
    widx = jnp.arange(W, dtype=jnp.int32)[None, :]
    m_first = jnp.min(jnp.where(match_w, widx, W), axis=1)
    e_first = jnp.min(jnp.where(empty_w, widx, W), axis=1)
    oldest = jnp.argmin(eep, axis=1).astype(jnp.int32)
    way = jnp.where(
        m_first < W, m_first, jnp.where(e_first < W, e_first, oldest)
    )
    slot = jnp.sum(jnp.where(widx == way[:, None], cand, 0), axis=1)
    matched = m_first < W
    old_state = jnp.sum(jnp.where(widx == way[:, None], est, 0), axis=1)

    # last eligible lane per slot wins the .set() writes
    lane = jnp.arange(slot.shape[0], dtype=jnp.int32)
    idx_e = jnp.where(elig, slot, C)
    winner = jnp.full(C + 1, -1, jnp.int32).at[idx_e].max(lane, mode="drop")
    win = elig & (
        jnp.take(winner, jnp.clip(slot, 0, C), mode="clip") == lane
    )
    idx_w = jnp.where(win, slot, C)

    # per-slot batch contributions (counter seeds) over ALL eligible lanes
    ln = batch.pkt_len
    seeds = jnp.zeros((C, 3), jnp.int32).at[idx_e].add(
        jnp.stack(
            [jnp.ones_like(ln), (ln >> 8) & 0xFFFFFF, ln & 0xFF], axis=1
        ),
        mode="drop",
    )

    state_val = jnp.where(
        fin, FLOW_FIN,
        jnp.where(is_tcp & syn & ~ack, FLOW_NEW, FLOW_EST),
    ).astype(jnp.int32)
    mygen = jnp.take(gens, jnp.clip(tenant, 0, gens.shape[0] - 1),
                     mode="clip")
    keys = flow.keys.at[idx_w].set(keyw, mode="drop")
    vg = flow.vg.at[idx_w].set(
        jnp.stack(
            [(verdict16.astype(jnp.int32)) & 0xFFFF, mygen], axis=1
        ),
        mode="drop",
    )
    se = flow.se.at[idx_w].set(
        jnp.stack(
            [state_val,
             jnp.broadcast_to(epoch_now, slot.shape).astype(jnp.int32)],
            axis=1,
        ),
        mode="drop",
    )
    cnt = flow.cnt.at[idx_w].set(
        jnp.take(seeds, jnp.clip(slot, 0, C - 1), axis=0, mode="clip"),
        mode="drop",
    )

    evict = win & ~matched & (old_state > 0)
    promote = win & matched & (old_state == FLOW_NEW) & (
        state_val == FLOW_EST
    )
    counts = jnp.stack([
        jnp.sum(win.astype(jnp.int32)),
        jnp.sum(evict.astype(jnp.int32)),
        jnp.sum(promote.astype(jnp.int32)),
        jnp.int32(0),
    ])
    return FlowTable(keys=keys, vg=vg, se=se, cnt=cnt), counts


@functools.lru_cache(maxsize=None)
def jitted_flow_insert(slab_entries: int, ways: int):
    def f(flow, gens, page_table, wire, tenant, tflags, verdict16,
          epoch_now):
        return _flow_insert_core(
            flow, gens, page_table, unpack_wire(wire), tenant, tflags,
            verdict16, epoch_now, slab_entries=slab_entries, ways=ways,
        )

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def jitted_flow_age():
    """Epoch-based age sweep over the (state, epoch) matrix: entries
    last seen strictly before ``cutoff`` free their slot.  Returns
    (new se, aged count)."""
    def f(se, cutoff):
        expire = (se[:, 0] > 0) & (se[:, 1] < cutoff)
        return (
            jnp.where(expire[:, None], jnp.stack(
                [jnp.zeros_like(se[:, 0]), se[:, 1]], axis=1
            ), se),
            jnp.sum(expire.astype(jnp.int32)),
        )

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def jitted_flow_occupancy():
    return jax.jit(lambda se: jnp.sum((se[:, 0] > 0).astype(jnp.int32)))


# === resident serving step (zero-copy donated-buffer loop, ISSUE-12) =========
#
# ONE fused device program per admission: wire decode + flow probe +
# stateless classify + verdict merge + device stats + miss insert — the
# in-program composition of the probe-then-classify multi-dispatch plan
# (backend/tpu.py _launch_flow), which pays three launches and two
# blocking host round-trips per admission.  The mutable flow columns and
# the epoch scalar are DONATED (jax.jit donate_argnums input-output
# aliasing): XLA writes the updated columns back into the very buffers
# the previous dispatch produced, so the steady-state loop performs zero
# flow-state device allocations and the epoch never crosses the link —
# the program increments it on device and hands the aliased buffer to
# the next dispatch.
#
# Bit-identity contract (gated by statecheck's `resident` config, the
# bench_resident oracle gate and tests/test_resident.py): the merged
# verdict vector, the statistics and the post-dispatch flow columns are
# bit-identical to what the multi-dispatch plan produces for the same
# wire chunk — the probe/insert bodies are the SAME functions
# (_flow_probe_parts / _flow_insert_core), the stateless classify is the
# same forward pass over every lane (the hit lanes' results fall out of
# the merge instead of being skipped by host compaction), and the insert
# masks hit lanes via lane_ok instead of host-compacting the misses
# (same eligible-lane set and order -> same winner scatters).


def _resident_step_core(
    flow: FlowTable, gens: jax.Array, page_table: jax.Array,
    epoch: jax.Array, tdev, wire: jax.Array, tenant: jax.Array,
    tflags: jax.Array, max_age: jax.Array, ov=None, sk=None, sc=None,
    model=None, tparams=None, pay=None, plen=None, ptrans=None,
    pmatch=None, pmode=None,
    *, slab_entries: int, ways: int, path: str, v4_only: bool,
    depth: Optional[int], d_max: int, sketch=None, score=None,
    payload=None,
):
    batch = unpack_wire(wire)
    e1 = (epoch + jnp.int32(1)).astype(jnp.int32)
    served, hit, stale, flow1 = _flow_probe_parts(
        flow, gens, page_table, batch, tenant, tflags, e1, max_age,
        slab_entries=slab_entries, ways=ways,
    )
    # stateless classify of EVERY lane against the SAME table snapshot:
    # the hit lanes' stateless results are discarded by the merge below
    # (at the small-batch rungs the extra lanes are far cheaper than a
    # second launch + host compaction round-trip)
    if path == "ctrie":
        if ov is not None:
            res, _x, _s = classify_ctrie_with_overlay(
                tdev, ov, batch, d_max=d_max
            )
        else:
            res, _x, _s = classify_ctrie(tdev, batch, d_max=d_max)
    else:
        t = tdev
        use_trie = path == "trie"
        if use_trie and v4_only:
            t = t._replace(
                trie_levels=t.trie_levels[: v4_trie_depth(len(t.trie_levels))]
            )
        elif use_trie and depth is not None:
            t = t._replace(trie_levels=t.trie_levels[: 1 + depth])
        if ov is not None:
            res, _x, _s = classify_with_overlay(t, ov, batch,
                                                use_trie=use_trie)
        else:
            res, _x, _s = classify(t, batch, use_trie=use_trie)
    # the wire contract (check_wire_ruleids at plan time) guarantees the
    # stateless result fits 16 bits, exactly like the fused wire path
    merged = jnp.where(hit, served, res & 0xFFFF).astype(jnp.uint32)
    sc2 = score_out = anom = None
    if score is not None:
        # MXU anomaly scoring (ISSUE-14): the feature update + forest/
        # MLP inference + per-tenant policy ride the SAME device
        # program, on the merged RULE verdicts (pre-policy — features
        # never read their own rewrites).  In enforce mode ``merged2``
        # carries the rewritten verdicts, and it is what the miss
        # insert below caches — mitigation sticks to the flow, and a
        # model swap invalidates it through the very generation stamps
        # a rule patch uses.
        from . import mxu_score as mxu_score_mod

        sc2, score_out, anom, merged2 = mxu_score_mod._score_update_core(
            sc, batch, tenant, tflags, merged, model, tparams, spec=score,
        )
        merged2 = merged2.astype(jnp.uint32)
    else:
        merged2 = merged
    pay_hit = pay_rw = None
    if payload is not None:
        # payload-matching tier (ISSUE-19): the Aho-Corasick DFA walk
        # over the ring-sliced payload-prefix column rides the SAME
        # device program, as the FOURTH verdict-merge tier — after the
        # score rewrite, with the same guardrails (failsafe lanes and
        # existing rule Denies are never overridden).  The automaton
        # operands (ptrans/pmatch) and the shadow/enforce scalar are
        # persistent VALUE operands — a pattern hot-swap replaces them
        # whole with spec-fixed shapes, so swapping never recompiles
        # and never disturbs the donation aliasing of the state that
        # precedes them in the operand order.
        from . import acmatch as acmatch_mod

        bitmap = acmatch_mod._acmatch_core(
            ptrans, pmatch, pay, plen, spec=payload
        )
        merged3, pay_hit, pay_rw = acmatch_mod._payload_merge_core(
            merged2, bitmap, pmode, batch.proto, batch.dst_port
        )
        merged3 = merged3.astype(jnp.uint32)
    else:
        merged3 = merged2
    flow2, counts = _flow_insert_core(
        flow1, gens, page_table, batch, tenant, tflags, merged3, e1,
        slab_entries=slab_entries, ways=ways, lane_ok=~hit,
    )
    # res16-only readback (the wire8 contract): per-ruleId statistics
    # derive HOST-side from the merged verdicts + the pkt_len column
    # that never left the host — shipping the (1024, 6) stats tensor
    # would cost ~24 KB per admission, dwarfing the ~100 B the resident
    # loop actually needs back
    parts = [
        _pack_res16(merged3.astype(jnp.uint16)),
        _pack_bits32(hit),
        jnp.stack([
            jnp.sum(hit.astype(jnp.int32)),
            jnp.sum(stale.astype(jnp.int32)),
        ]),
        counts,
    ]
    if score is not None:
        # scoring extension of the fused readback: the anomaly bitmap
        # (b/32 words) and the int16-saturated per-lane scores (b/2
        # words) — what shadow records, the precision/recall legs and
        # the cross-path identity gate read; internal state stays exact
        # int32 on device
        s16 = jnp.clip(score_out, -32768, 32767).astype(jnp.int16)
        parts.append(_pack_bits32(anom))
        parts.append(_pack_res16(s16.astype(jnp.uint16)))
    if payload is not None:
        # payload extension of the fused readback: the matched-lane and
        # rewritten-lane bitmaps (b/32 words each) — the counters and
        # the classic-path identity gate read these; the FULL (b, PW)
        # match bitmap never crosses the link on the resident path (the
        # standalone jitted_acmatch launch serves statecheck's
        # bit-identity compare instead)
        parts.append(_pack_bits32(pay_hit))
        parts.append(_pack_bits32(pay_rw))
    fused = jnp.concatenate(parts)
    if sketch is not None:
        # device-resident telemetry (ISSUE-13): the sketch update rides
        # the SAME device program as the verdicts — count-min + top-K +
        # tenant-counter scatters over the SERVED res16 (post-policy,
        # so telemetry counts what the dataplane actually did), donated
        # like the flow columns, nothing read back (the decimated drain
        # is the only D2H the telemetry plane ever pays)
        from . import sketch as sketch_mod

        sk2 = sketch_mod._sketch_update_core(
            sk, batch, tenant, tflags, merged3, spec=sketch,
        )
        if score is not None:
            return flow2, e1, sk2, sc2, fused
        return flow2, e1, sk2, fused
    if score is not None:
        return flow2, e1, sc2, fused
    return flow2, e1, fused


def split_resident_outputs(arr: np.ndarray, b: int):
    """Host inverse of the resident step's fused buffer -> (res16[b],
    hit mask, hits, stale, (inserts, evictions, promotes)).  ~100 B per
    admission — statistics derive host-side (the wire8 contract)."""
    nw = (b + 1) // 2
    nh = -(-b // 32)
    res16 = unpack_res16_host(arr[:nw], b)
    hit = unpack_bits32_host(arr[nw : nw + nh], b)
    hits, stale = int(arr[nw + nh]), int(arr[nw + nh + 1])
    counts = tuple(int(x) for x in arr[nw + nh + 2 : nw + nh + 5])
    return res16, hit, hits, stale, counts


def split_resident_score_outputs(arr: np.ndarray, b: int):
    """Host inverse of the SCORING resident step's fused buffer ->
    (res16[b] — policy-rewritten in enforce mode, hit mask, hits,
    stale, (inserts, evictions, promotes), anom mask[b], scores[b]
    int32 from the int16-saturated readback)."""
    nw = (b + 1) // 2
    nh = -(-b // 32)
    res16, hit, hits, stale, counts = split_resident_outputs(
        arr[: nw + nh + 6], b
    )
    base = nw + nh + 6
    anom = unpack_bits32_host(arr[base : base + nh], b)
    s16 = unpack_res16_host(arr[base + nh : base + nh + nw], b)
    scores = s16.astype(np.uint16).astype(np.int16).astype(np.int32)
    return res16, hit, hits, stale, counts, anom, scores


def split_resident_payload_outputs(arr: np.ndarray, b: int,
                                   score: bool = False):
    """Host inverse of the PAYLOAD resident step's fused buffer: the
    base (or scoring) tuple with the matched-lane and rewritten-lane
    bitmaps appended -> (..., pay_hit[b], pay_rewrote[b]).  The payload
    extension is the LAST 2*ceil(b/32) words regardless of which other
    tiers ride the program, so the slice anchors from the end."""
    arr = np.asarray(arr)
    nh = -(-b // 32)
    base, tail = arr[: arr.shape[0] - 2 * nh], arr[arr.shape[0] - 2 * nh:]
    head = (
        split_resident_score_outputs(base, b) if score
        else split_resident_outputs(base, b)
    )
    pay_hit = unpack_bits32_host(tail[:nh], b)
    pay_rw = unpack_bits32_host(tail[nh:], b)
    return head + (pay_hit, pay_rw)


#: donated operand positions of the resident step — the flow column
#: pytree and the device epoch scalar; declared here so the entrypoint
#: registry and the jaxcheck donation lint share one source of truth
RESIDENT_DONATE_ARGNUMS = (0, 3)

#: the telemetry variant additionally donates the sketch tensors
#: (operand 4, right after the epoch) — telemetry state is rewritten in
#: place every admission exactly like the flow columns
RESIDENT_SKETCH_DONATE_ARGNUMS = (0, 3, 4)

#: the anomaly-scoring variant donates the score state at position 4
#: (or 5 when the sketch tensors are present too); the model value and
#: tparams operands that follow it are persistent, NOT donated
RESIDENT_SCORE_DONATE_ARGNUMS = (0, 3, 4)
RESIDENT_SKETCH_SCORE_DONATE_ARGNUMS = (0, 3, 4, 5)


def resident_donate_argnums(sketch: bool, score: bool) -> tuple:
    """The donated positions for a (sketch?, score?) resident variant —
    one source of truth for the factory below, the entrypoint registry
    and the jaxcheck donation lint."""
    donate = [0, 3]
    pos = 4
    if sketch:
        donate.append(pos)
        pos += 1
    if score:
        donate.append(pos)
    return tuple(donate)


@functools.lru_cache(maxsize=None)
def jitted_resident_step(
    slab_entries: int, ways: int, path: str, v4_only: bool = False,
    depth: Optional[int] = None, d_max: int = 0, overlay: bool = False,
    sketch=None, score=None, payload=None,
):
    """The resident fused executable, cache-keyed on (flow geometry,
    layout path, wire format specialization, sketch/score geometry) —
    batch shape and the trie level count specialize through jit's
    shape/pytree keying, so a warmed ladder serves every admission with
    zero recompiles (the same contract as every other serving factory,
    test-pinned).

    Operand order: f(flow, gens, page_table, epoch, [sk], [sc, model,
    tparams], tables[, overlay], wire, tenant, tflags, max_age) ->
    (flow', epoch', [sk'], [sc'], fused).  ``flow``, ``epoch`` and the
    optional sketch/score states are DONATED: the returned tensors
    alias the input buffers in place (XLA input_output_alias; the
    jaxcheck donation lint fails if a donated buffer is silently
    copied), so the caller must treat the inputs as consumed and chain
    the returned arrays into the next dispatch.  The score model/
    tparams operands are persistent device arrays — a model hot swap
    replaces them whole with spec-fixed shapes, so swapping never
    recompiles.

    The payload variant (``payload`` = an acmatch.AcSpec) extends the
    order to f(flow, gens, page_table, epoch, [sk], [sc, model,
    tparams], [ptrans, pmatch, pmode], tables[, overlay], wire, pay,
    plen, tenant, tflags, max_age): the automaton operands sit AFTER
    every donated position, so the fourth tier never perturbs the
    aliasing contract, and a pattern hot-swap is a value-operand
    replacement exactly like a score-model swap."""
    kw = dict(slab_entries=slab_entries, ways=ways, path=path,
              v4_only=v4_only, depth=depth, d_max=d_max, sketch=sketch,
              score=score, payload=payload)
    has_sk = sketch is not None
    has_sc = score is not None
    has_pay = payload is not None

    def f(*args):
        flow, gens, page_table, epoch = args[:4]
        i = 4
        sk = sc = model = tparams = None
        ptrans = pmatch = pmode = None
        if has_sk:
            sk = args[i]
            i += 1
        if has_sc:
            sc, model, tparams = args[i], args[i + 1], args[i + 2]
            i += 3
        if has_pay:
            ptrans, pmatch, pmode = args[i], args[i + 1], args[i + 2]
            i += 3
        tdev = args[i]
        i += 1
        ov = None
        if overlay:
            ov = args[i]
            i += 1
        if has_pay:
            wire, pay, plen, tenant, tflags, max_age = args[i : i + 6]
        else:
            wire, tenant, tflags, max_age = args[i : i + 4]
            pay = plen = None
        return _resident_step_core(
            flow, gens, page_table, epoch, tdev, wire, tenant, tflags,
            max_age, ov=ov, sk=sk, sc=sc, model=model, tparams=tparams,
            pay=pay, plen=plen, ptrans=ptrans, pmatch=pmatch,
            pmode=pmode, **kw,
        )

    return jax.jit(f, donate_argnums=resident_donate_argnums(has_sk,
                                                             has_sc))


# === resident superbatch: the device-side epoch loop (ISSUE-16) ==============
#
# K stacked admissions chewed through in ONE device program: the fused
# step's body runs under a lax.scan (an XLA while loop with stacked
# outs), the donated flow columns / epoch scalar / sketch / score state
# chained through the loop CARRY — no intermediate host round-trips, no
# per-admission Python dispatch.  Bit-identity is by construction: the
# scan body IS _resident_step_core, the same integer-deterministic
# function K sequential jitted_resident_step dispatches run, applied to
# the same carry chain in the same order — verdicts, statistics, flow
# columns and sketch/score state all land bit-identical (pinned by the
# statecheck `pipeline` config and the bench_pipeline identity gate).
# The fused readbacks stack to one (K, L) buffer: the host splits rows
# with resident_fused_host and drains the model mirrors per admission
# in device-epoch order exactly as on the single-step path.


def resident_fused_host(fused) -> np.ndarray:
    """Host view of ONE admission's fused readback: either a bare
    fused buffer (single-step dispatch) or a ``(stack, row)`` pair
    referencing one row of a superbatch's stacked (K, L) readback.
    np.asarray blocks until the dispatch lands — the mirror-queue
    drain's ordering contract."""
    if isinstance(fused, tuple):
        stack, row = fused
        return np.asarray(stack)[int(row)]
    return np.asarray(fused)


@functools.lru_cache(maxsize=None)
def jitted_resident_superbatch(
    slab_entries: int, ways: int, path: str, v4_only: bool = False,
    depth: Optional[int] = None, d_max: int = 0, overlay: bool = False,
    sketch=None, score=None, payload=None,
):
    """The K-admission device epoch program, cache-keyed exactly like
    jitted_resident_step (K and the batch shape specialize through
    jit's shape keying — a warmed (K, B, W) shape recompiles never).

    Operand order matches the single-step factory with the wire/tenant/
    tflags operands STACKED along a leading K axis: f(flow, gens,
    page_table, epoch, [sk], [sc, model, tparams], tables[, overlay],
    wire (K, B, W), tenant (K, B), tflags (K, B), max_age) -> (flow',
    epoch', [sk'], [sc'], fused (K, L)).  Donation is identical to the
    single step (flow, epoch, sketch, score) — XLA aliases the carry
    in place through the while loop, verified against the compiled
    HLO by the jaxcheck donation lint.

    The payload variant stacks the pay/plen columns with the wire:
    f(..., [ptrans, pmatch, pmode], tables[, overlay], wire (K, B, W),
    pay (K, B, L), plen (K, B), tenant, tflags, max_age) — the
    automaton operands stay loop-INVARIANT (closed over by the scan
    body), so K admissions walk one resident copy of the transition
    tensors."""
    kw = dict(slab_entries=slab_entries, ways=ways, path=path,
              v4_only=v4_only, depth=depth, d_max=d_max, sketch=sketch,
              score=score, payload=payload)
    has_sk = sketch is not None
    has_sc = score is not None
    has_pay = payload is not None

    def f(*args):
        flow, gens, page_table, epoch = args[:4]
        i = 4
        sk = sc = model = tparams = None
        ptrans = pmatch = pmode = None
        if has_sk:
            sk = args[i]
            i += 1
        if has_sc:
            sc, model, tparams = args[i], args[i + 1], args[i + 2]
            i += 3
        if has_pay:
            ptrans, pmatch, pmode = args[i], args[i + 1], args[i + 2]
            i += 3
        tdev = args[i]
        i += 1
        ov = None
        if overlay:
            ov = args[i]
            i += 1
        if has_pay:
            wire, pay, plen, tenant, tflags, max_age = args[i : i + 6]
            xs = (wire, pay, plen, tenant, tflags)
        else:
            wire, tenant, tflags, max_age = args[i : i + 4]
            xs = (wire, tenant, tflags)

        def body(carry, xs_row):
            fl, ep, skc, scc = carry
            if has_pay:
                w, py, pl, tn, tf = xs_row
            else:
                w, tn, tf = xs_row
                py = pl = None
            out = _resident_step_core(
                fl, gens, page_table, ep, tdev, w, tn, tf, max_age,
                ov=ov, sk=skc, sc=scc, model=model, tparams=tparams,
                pay=py, plen=pl, ptrans=ptrans, pmatch=pmatch,
                pmode=pmode, **kw,
            )
            fl2, ep2 = out[0], out[1]
            j = 2
            sk2 = sc2 = None
            if has_sk:
                sk2 = out[j]
                j += 1
            if has_sc:
                sc2 = out[j]
                j += 1
            return (fl2, ep2, sk2, sc2), out[-1]

        (flow2, e2, sk2, sc2), fused = jax.lax.scan(
            body, (flow, epoch, sk, sc), xs
        )
        outs = [flow2, e2]
        if has_sk:
            outs.append(sk2)
        if has_sc:
            outs.append(sc2)
        outs.append(fused)
        return tuple(outs)

    return jax.jit(f, donate_argnums=resident_donate_argnums(has_sk,
                                                             has_sc))
