"""Dataplane ABI constants.

These mirror the reference dataplane contract in
/root/reference/bpf/ingress_node_firewall.h:4-23 (constants, action values and
the action/ruleId bit-packing macros).  They are the conformance contract that
every classifier backend (Pallas TPU kernel, XLA trie path, C++ CPU reference,
NumPy oracle) must implement bit-exactly.
"""

# Capacity constants (ingress_node_firewall.h:13-16).
MAX_TARGETS = 1024
MAX_RULES_PER_TARGET = 100
MAX_EVENT_DATA = 256
INVALID_RULE_ID = 0

# XDP verdicts.  The reference aliases firewall actions onto XDP actions
# (ingress_node_firewall.h:10-12): UNDEF=XDP_ABORTED, DENY=XDP_DROP,
# ALLOW=XDP_PASS.
XDP_ABORTED = 0
XDP_DROP = 1
XDP_PASS = 2

UNDEF = XDP_ABORTED
DENY = XDP_DROP
ALLOW = XDP_PASS

# Ethertypes (ingress_node_firewall.h:5-7).
ETH_P_IP = 0x0800
ETH_P_IPV6 = 0x86DD
ETH_P_ARP = 0x0806

# L4 protocol numbers used by the rule scan
# (bpf/ingress_node_firewall_kernel.c:231-233,247,329).
IPPROTO_ICMP = 1
IPPROTO_TCP = 6
IPPROTO_UDP = 17
IPPROTO_ICMPV6 = 58
IPPROTO_SCTP = 132

# LPM key geometry: the match data is (ingress_ifindex:32bits || ip_data:128bits)
# and entry prefixLen counts the ifindex bits too
# (pkg/ebpf/ingress_node_firewall_loader.go:35,543).
IFINDEX_KEY_LENGTH = 32
# Packet-side key prefix lengths (kernel.c:207,293): entries with a longer
# prefixLen than the packet key cannot match.
V4_KEY_PREFIX_LEN = 64   # 32 ifindex bits + 32 IPv4 bits
V6_KEY_PREFIX_LEN = 160  # 32 ifindex bits + 128 IPv6 bits

# Packet "kind" codes used by this framework's batched representation of the
# ethertype switch in ingress_node_firewall_main (kernel.c:423-439).
KIND_MALFORMED = 0  # short/invalid ethernet header   -> XDP_DROP
KIND_IPV4 = 1       # ETH_P_IP                        -> ipv4_firewall_lookup
KIND_IPV6 = 2       # ETH_P_IPV6                      -> ipv6_firewall_lookup
KIND_OTHER = 3      # any other ethertype             -> XDP_PASS


def get_action(result: int) -> int:
    """GET_ACTION macro (ingress_node_firewall.h:18)."""
    return result & 0xFF


def set_action(action: int) -> int:
    """SET_ACTION macro (ingress_node_firewall.h:19)."""
    return action & 0xFF


def get_rule_id(result: int) -> int:
    """GET_RULE_ID macro (ingress_node_firewall.h:20)."""
    return (result >> 8) & 0xFFFFFF


def set_actionrule_response(action: int, rule_id: int) -> int:
    """SET_ACTIONRULE_RESPONSE macro (ingress_node_firewall.h:22-23)."""
    return ((rule_id & 0xFFFFFF) << 8) | (action & 0xFF)
