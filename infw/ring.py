"""Persistent pinned host ingest ring (ISSUE-12).

A preallocated shared-memory SPSC ring through which producers
(``tools/loadgen.py --ring``) and the daemon's ingest loop exchange
PACKED WIRE chunks: the producer writes each record IN PLACE into a
mapped slot (no per-chunk file create/rename/unlink syscalls, no
per-chunk numpy reallocation), publishes it with one commit-word store,
and the consumer's view IS the H2D staging buffer — ``jax.device_put``
reads straight out of the mapping (zero-copy on the CPU backend for
aligned slots).  The scheduler admits by ring cursor: one record is one
admission-sized chunk, already in the 4/7-word wire layout the packed
dispatch consumes.

Layout (one file, mapped by both sides):

- 4096-byte header page: magic ``INFWRNG1``, version, slots,
  slot_bytes, then the producer ``head`` and consumer ``tail`` cursors
  (uint64, monotonically increasing sequence numbers, each written by
  exactly one side).
- ``slots`` fixed-size slots of ``slot_bytes`` each, 64-byte aligned.
  Slot layout: commit (u64, = sequence + 1 once the payload below it is
  fully written — the publish barrier), n (u32 packets), width (u32, 4
  or 7), flags (u32: bit0 v4_only, bit1 tcp_flags present, bit2 payload
  column present), payload prefix width L (u32, the formerly-reserved
  word; 0 unless bit2), then ``n*width`` uint32 wire words, then ``n``
  int32 TCP flags when present, then the OPTIONAL payload-prefix column
  (ISSUE-19): ``n*L`` uint8 payload bytes + ``n`` int32 payload lengths.
  L is one of kernels.wire_decode.PAYLOAD_PREFIX_WIDTHS (64/128) so the
  column lands in the fixed jit geometry buckets the Aho-Corasick match
  compiles against.

Single-producer / single-consumer by design (the deployment shape: one
loadgen or NIC-facing shim per daemon); the commit word gives the
consumer a torn-read-free publish point without locks.  Overrun policy
is PRODUCER BLOCKS (bounded by ``timeout``): an ingest ring must apply
backpressure, not drop — dropping belongs to the NIC edge, where the
reference XDP program already counts it.
"""
from __future__ import annotations

import mmap
import os
import time
from typing import Optional

import numpy as np

from ._threads import sched_point

_MAGIC = b"INFWRNG1"
_VERSION = 1
_HEADER_BYTES = 4096
_SLOT_HEADER_BYTES = 64

#: record flag bits
FLAG_V4_ONLY = 1
FLAG_TCP_FLAGS = 2
FLAG_PAYLOAD = 4

DEFAULT_SLOTS = 64
DEFAULT_SLOT_PACKETS = 4096


def slot_bytes_for(max_packets: int, width: int = 7,
                   with_flags: bool = True,
                   payload_width: int = 0) -> int:
    """Slot size fitting ``max_packets`` of the widest record shape.
    ``payload_width`` > 0 reserves the per-packet payload-prefix column
    (L uint8 bytes + one int32 length)."""
    n = _SLOT_HEADER_BYTES + max_packets * width * 4
    if with_flags:
        n += max_packets * 4
    if payload_width:
        n += max_packets * (int(payload_width) + 4)
    return (n + 63) & ~63


class RingChunk:
    """One popped record: zero-copy numpy views into the mapped slot.

    The views stay valid until ``release()`` advances the consumer
    cursor — hold the chunk until the dispatch that read it has
    materialized (the daemon keeps it in the in-flight job), or copy.
    """

    __slots__ = ("wire", "tcp_flags", "payload", "payload_len",
                 "v4_only", "seq", "_ring")

    def __init__(self, ring, seq, wire, tcp_flags, v4_only,
                 payload=None, payload_len=None):
        self._ring = ring
        self.seq = seq
        self.wire = wire
        self.tcp_flags = tcp_flags
        self.v4_only = v4_only
        #: optional ring-sliced payload-prefix column (ISSUE-19):
        #: (n, L) uint8 view + (n,) int32 lengths, or None
        self.payload = payload
        self.payload_len = payload_len

    def release(self) -> None:
        """Return the slot to the producer (advance tail past seq).
        Records release in order — releasing out of order is a
        programming error the ring refuses."""
        if self._ring is not None:
            ring, self._ring = self._ring, None
            ring._advance_tail(self.seq)


class IngestRing:
    """The mapped ring.  ``create`` truncates/initializes the file
    (consumer side — it owns sizing); ``attach`` maps an existing ring
    (producer side) and validates the header."""

    def __init__(self, path: str, mm: mmap.mmap, create: bool,
                 slots: int, slot_bytes: int) -> None:
        self.path = path
        self._mm = mm
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._u64 = np.frombuffer(mm, np.uint64, 6, 0)
        #: per-process (producer and consumer each attach their own
        #: instance): depth_hwm is the occupancy high-watermark this
        #: side observed; blocked_us is cumulative wall time reserve()
        #: spent waiting on a full ring — the PRODUCER-side backpressure
        #: signal, distinct from falling behind an open-loop schedule
        #: (tools/loadgen.py --ring splits the two in its manifest)
        #: Counter write discipline (ISSUE-18): every key is written by
        #: exactly ONE side of the SPSC pair — pushed/blocked_waits/
        #: blocked_us/depth_hwm_prod by the producer, popped/
        #: depth_hwm_cons by the consumer — because a plain-dict
        #: read-modify-write shared across both threads loses updates
        #: (the depth_hwm check-then-store raced before the split; the
        #: schedcheck test pins the fix).  counter_values() merges the
        #: two watermarks.
        self._stats = {"pushed": 0, "popped": 0, "blocked_waits": 0,
                       "depth_hwm_prod": 0, "depth_hwm_cons": 0,
                       "blocked_us": 0}
        #: consumer-side read cursor: records between tail and here are
        #: popped but not yet released (their slot views may be in
        #: flight as H2D staging buffers) — the producer only reuses
        #: slots behind TAIL, so in-flight views are never overwritten
        self._read_seq = int(self._u64[4])
        #: corrupt records skipped by pop(): their slots free only when
        #: the release protocol reaches them IN ORDER (_advance_tail
        #: drains through this set), so a poison record can never bump
        #: the tail past earlier in-flight slot views
        self._skipped: set = set()

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, path: str, slots: int = DEFAULT_SLOTS,
               slot_packets: int = DEFAULT_SLOT_PACKETS,
               payload_width: int = 0) -> "IngestRing":
        # build the ring under a temp name and rename into place: a
        # producer's attach() (which retries until the path exists) can
        # then never map a half-initialized file — the header, cursors
        # and zeroed commit words are all durable before visibility
        slot_b = slot_bytes_for(slot_packets, payload_width=payload_width)
        total = _HEADER_BYTES + slots * slot_b
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        hdr = np.frombuffer(mm, np.uint64, 6, 0)
        hdr[1] = (_VERSION << 32) | slots
        hdr[2] = slot_b
        hdr[3] = 0  # head
        hdr[4] = 0  # tail
        # zero every commit word so attach never reads a stale publish
        for i in range(slots):
            np.frombuffer(mm, np.uint64, 1,
                          _HEADER_BYTES + i * slot_b)[0] = 0
        mm[0:8] = _MAGIC  # magic last: a torn tmp file never validates
        mm.flush()
        os.replace(tmp, path)
        return cls(path, mm, True, slots, slot_b)

    @classmethod
    def attach(cls, path: str, timeout: float = 5.0) -> "IngestRing":
        deadline = time.monotonic() + timeout
        while True:
            try:
                fd = os.open(path, os.O_RDWR)
            except FileNotFoundError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.01)
                continue
            try:
                size = os.fstat(fd).st_size
                if size < _HEADER_BYTES:
                    # defensive: create() publishes atomically via
                    # rename, but a foreign/partial file should retry
                    # within the deadline instead of crashing mmap
                    raise ValueError(f"{path}: ring file too small")
                mm = mmap.mmap(fd, size)
            except ValueError:
                os.close(fd)
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.01)
                continue
            os.close(fd)
            break
        if mm[0:8] != _MAGIC:
            mm.close()
            raise ValueError(f"{path}: not an infw ingest ring")
        hdr = np.frombuffer(mm, np.uint64, 6, 0)
        version = int(hdr[1]) >> 32
        slots = int(hdr[1]) & 0xFFFFFFFF
        if version != _VERSION:
            raise ValueError(
                f"{path}: ring version {version} != {_VERSION}"
            )
        return cls(path, mm, False, slots, int(hdr[2]))

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass  # live numpy views pin the mapping; the OS reclaims

    # -- cursors -------------------------------------------------------------

    @property
    def head(self) -> int:
        return int(self._u64[3])

    @property
    def tail(self) -> int:
        return int(self._u64[4])

    def __len__(self) -> int:
        """Committed, unconsumed records."""
        return max(0, self.head - self.tail)

    def _slot_off(self, seq: int) -> int:
        return _HEADER_BYTES + (seq % self.slots) * self.slot_bytes

    def _advance_tail(self, seq: int) -> None:
        if int(self._u64[4]) != seq:
            raise RuntimeError(
                f"out-of-order ring release: tail={int(self._u64[4])}, "
                f"released seq={seq}"
            )
        self._u64[4] = seq + 1
        self._drain_skipped()

    def _drain_skipped(self) -> None:
        """Free poison (corrupt, skipped-by-pop) slots once the release
        order reaches them — never before, so the producer cannot
        overwrite earlier popped-but-unreleased slot views."""
        while int(self._u64[4]) in self._skipped:
            t = int(self._u64[4])
            self._skipped.discard(t)
            self._u64[4] = t + 1

    # -- producer ------------------------------------------------------------

    def max_packets(self, width: int = 7, with_flags: bool = True,
                    payload_width: int = 0) -> int:
        avail = self.slot_bytes - _SLOT_HEADER_BYTES
        per = width * 4 + (4 if with_flags else 0)
        if payload_width:
            per += int(payload_width) + 4
        return avail // per

    def reserve(self, n: int, width: int,
                with_flags: bool = False,
                payload_width: int = 0,
                timeout: Optional[float] = None):
        """Producer half 1: claim the next slot and return in-place
        views -> (wire (n, width) uint32 view, flags (n,) int32 view or
        None, token) — or, with ``payload_width`` L > 0, (wire, flags,
        payload (n, L) uint8 view, payload_len (n,) int32 view, token).
        The producer packs straight into the views (no intermediate
        chunk array), then ``commit(token)`` publishes.  Blocks while
        the ring is full (backpressure); raises TimeoutError past
        ``timeout`` seconds."""
        if n < 1 or width not in (4, 7):
            raise ValueError(f"bad record shape n={n} width={width}")
        if payload_width:
            from .kernels.wire_decode import PAYLOAD_PREFIX_WIDTHS

            if payload_width not in PAYLOAD_PREFIX_WIDTHS:
                raise ValueError(
                    f"payload prefix width {payload_width} not in "
                    f"{PAYLOAD_PREFIX_WIDTHS}"
                )
        if n > self.max_packets(width, with_flags, payload_width):
            raise ValueError(
                f"record of {n} packets exceeds the slot capacity "
                f"{self.max_packets(width, with_flags, payload_width)}"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        seq = self.head
        t_block = None
        while seq - self.tail >= self.slots:
            if t_block is None:
                t_block = time.monotonic()
            self._stats["blocked_waits"] += 1
            if deadline is not None and time.monotonic() > deadline:
                self._stats["blocked_us"] += int(
                    (time.monotonic() - t_block) * 1e6
                )
                raise TimeoutError("ingest ring full (consumer stalled)")
            time.sleep(0.0005)
        if t_block is not None:
            self._stats["blocked_us"] += int(
                (time.monotonic() - t_block) * 1e6
            )
        off = self._slot_off(seq)
        hdr32 = np.frombuffer(self._mm, np.uint32, 4, off + 8)
        flags = (FLAG_TCP_FLAGS if with_flags else 0)
        if payload_width:
            flags |= FLAG_PAYLOAD
        hdr32[0] = n
        hdr32[1] = width
        hdr32[2] = flags
        hdr32[3] = int(payload_width)
        wire = np.frombuffer(
            self._mm, np.uint32, n * width, off + _SLOT_HEADER_BYTES
        ).reshape(n, width)
        cursor = off + _SLOT_HEADER_BYTES + n * width * 4
        fl = None
        if with_flags:
            fl = np.frombuffer(self._mm, np.int32, n, cursor)
            cursor += n * 4
        if not payload_width:
            return wire, fl, (seq, off)
        pay = np.frombuffer(
            self._mm, np.uint8, n * payload_width, cursor
        ).reshape(n, payload_width)
        plen = np.frombuffer(
            self._mm, np.int32, n, cursor + n * payload_width
        )
        return wire, fl, pay, plen, (seq, off)

    def commit(self, token, v4_only: bool = False) -> int:
        """Producer half 2: publish the reserved record (commit-word
        store, then the head bump)."""
        seq, off = token
        hdr32 = np.frombuffer(self._mm, np.uint32, 4, off + 8)
        if v4_only:
            hdr32[2] |= FLAG_V4_ONLY
        np.frombuffer(self._mm, np.uint64, 1, off)[0] = seq + 1
        self._u64[3] = seq + 1
        self._stats["pushed"] += 1
        depth = len(self)
        sched_point("ring-hwm-prod")
        if depth > self._stats["depth_hwm_prod"]:
            self._stats["depth_hwm_prod"] = depth
        return seq

    def push(self, wire: np.ndarray, v4_only: bool = False,
             tcp_flags: Optional[np.ndarray] = None,
             payload: Optional[np.ndarray] = None,
             payload_len: Optional[np.ndarray] = None,
             timeout: Optional[float] = None) -> int:
        """One-call producer convenience: reserve + in-place copy +
        commit.  ``payload`` must already be bucketed to a
        PAYLOAD_PREFIX_WIDTHS column (kernels.wire_decode.
        pad_payload_prefix)."""
        n, width = wire.shape
        if payload is None:
            wv, fv, token = self.reserve(
                n, width, with_flags=tcp_flags is not None,
                timeout=timeout,
            )
        else:
            wv, fv, pv, lv, token = self.reserve(
                n, width, with_flags=tcp_flags is not None,
                payload_width=payload.shape[1], timeout=timeout,
            )
            np.copyto(pv, np.asarray(payload, np.uint8))
            np.copyto(lv, (
                np.asarray(payload_len, np.int32)
                if payload_len is not None
                else np.full(n, payload.shape[1], np.int32)
            ))
        np.copyto(wv, wire)
        if tcp_flags is not None:
            np.copyto(fv, np.asarray(tcp_flags, np.int32))
        return self.commit(token, v4_only=v4_only)

    # -- consumer ------------------------------------------------------------

    def pop(self, timeout: float = 0.0) -> Optional[RingChunk]:
        """Next committed record as zero-copy views, or None when the
        ring is empty past ``timeout``.  The slot is NOT reclaimed until
        the chunk's ``release()`` — the views double as the H2D staging
        buffer, so the producer must not overwrite them mid-copy."""
        deadline = time.monotonic() + timeout
        seq = self._read_seq
        while True:
            if self.head > seq:
                off = self._slot_off(seq)
                commit = int(np.frombuffer(self._mm, np.uint64, 1, off)[0])
                if commit == seq + 1:
                    break
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.0005)
        off = self._slot_off(seq)
        hdr32 = np.frombuffer(self._mm, np.uint32, 4, off + 8)
        n, width, flags = int(hdr32[0]), int(hdr32[1]), int(hdr32[2])
        pw = int(hdr32[3]) if flags & FLAG_PAYLOAD else 0
        # the sanity bound must use the RECORD's own layout: a
        # flag-less record legally holds more packets than a flagged
        # one of the same slot size
        cap = self.max_packets(width, bool(flags & FLAG_TCP_FLAGS), pw)
        bad_pw = bool(flags & FLAG_PAYLOAD) and pw not in (64, 128)
        if width not in (4, 7) or n < 1 or bad_pw or n > cap:
            # fail closed on a torn/corrupt record: skip the READ
            # cursor only — the slot frees when the release order
            # reaches it (_drain_skipped), never by bumping the tail
            # past earlier in-flight slot views
            self._read_seq = seq + 1
            self._skipped.add(seq)
            self._drain_skipped()
            raise ValueError(
                f"corrupt ring record at seq {seq}: n={n} width={width}"
                f" payload_width={pw}"
            )
        wire = np.frombuffer(
            self._mm, np.uint32, n * width, off + _SLOT_HEADER_BYTES
        ).reshape(n, width)
        cursor = off + _SLOT_HEADER_BYTES + n * width * 4
        fl = None
        if flags & FLAG_TCP_FLAGS:
            fl = np.frombuffer(self._mm, np.int32, n, cursor)
            cursor += n * 4
        pay = plen = None
        if pw:
            pay = np.frombuffer(
                self._mm, np.uint8, n * pw, cursor
            ).reshape(n, pw)
            plen = np.frombuffer(self._mm, np.int32, n, cursor + n * pw)
        self._stats["popped"] += 1
        depth = self.head - seq
        sched_point("ring-hwm-cons")
        if depth > self._stats["depth_hwm_cons"]:
            self._stats["depth_hwm_cons"] = depth
        self._read_seq = seq + 1
        return RingChunk(self, seq, wire, fl, bool(flags & FLAG_V4_ONLY),
                         payload=pay, payload_len=plen)

    # -- observability -------------------------------------------------------

    def counter_values(self) -> dict:
        """ring_* gauges for /metrics."""
        return {
            "ring_pushed_total": self._stats["pushed"],
            "ring_popped_total": self._stats["popped"],
            "ring_blocked_waits_total": self._stats["blocked_waits"],
            "ring_blocked_us_total": self._stats["blocked_us"],
            "ring_depth": len(self),
            "ring_depth_hwm": max(self._stats["depth_hwm_prod"],
                                  self._stats["depth_hwm_cons"]),
            "ring_slots": self.slots,
        }


def ring_path(state_dir: str) -> str:
    """The daemon's default ring location under its state dir."""
    return os.path.join(state_dir, "ingest.ring")
