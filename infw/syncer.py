"""The dataplane sync boundary.

TPU-native equivalent of the reference's ebpfsyncer
(/root/reference/pkg/ebpfsyncer/ebpfsyncer.go) — the architecture's key
seam: the single point of contact between declarative desired state and the
running classifier.  One method, ``sync_interface_ingress_rules(rules,
is_delete)`` (ebpfsyncer.go:32-34), hides the backend (TPU Pallas / XLA
trie / native C++ CPU reference).

Lifecycle semantics preserved from the reference:

- **singleton, mutex-serialized** (:38-67, :72-73): one syncer per daemon
  process; concurrent syncs serialize.  ``reset_singleton_for_test()``
  replaces the test suite's ``once = sync.Once{}`` restart simulation
  (ebpfsyncer_test.go:1232-1234).
- **lazy manager creation + restart re-adoption** (:100-104 →
  loader.go:381-407): the classifier is created on first sync; if a
  checkpoint ("pinned" compiled tables + attach manifest) exists it is
  re-adopted, so a daemon restart resumes enforcing without recompiling.
- **stats poller paused around sync** (:81-88) so metrics never read a
  table mid-rewrite.
- **is_delete ⇒ resetAll** (:90-97, :160-181): detach everything, close the
  classifier, remove the checkpoint (unpin).
- **detach-unmanaged → attach-new → load rules** order (:106-125); attach
  retries on busy interfaces (XDP_EBUSY, :193-207).
- **idempotent rule load**: desired vs stale key diff
  (loader.go:177-194,551-631) — unchanged content causes no device reload.
- ``get_classifier_map_content_for_test`` mirrors
  ``GetBPFMapContentForTest`` (ebpfsyncer.go:128-133).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Protocol, Set, Tuple

import numpy as np

from . import interfaces as interfaces_mod
from .backend.base import Classifier
from .compiler import (
    CompiledTables,
    CompileError,
    IncrementalTables,
    LpmKey,
    build_table_content,
    compile_tables_from_content,
    min_rule_width,
)
from .constants import MAX_RULES_PER_TARGET
from .contracts import must_precede
from .interfaces import InterfaceRegistry
from .spec import IngressNodeFirewallRules

log = logging.getLogger("infw.syncer")

# XDP_EBUSY retry policy (ebpfsyncer.go:28-30,193-207).
XDP_EBUSY_MAX_RETRIES = 10
XDP_EBUSY_RETRY_INTERVAL_S = 0.1


def merge_rebuild_content(content, ups, dels, extra=None):
    """The columnar-rebuild escalation's content merge — live content
    minus the deleted masked identities, plus the upserts (plus an
    optional absorbed side dict, e.g. the overlay).  ONE shared recipe
    for the single-tenant flush path and the tenant registry, so the
    escalation semantics cannot drift between them."""
    del_idents = {k.masked_identity() for k in dels}
    out = {
        k: v for k, v in dict(content).items()
        if k.masked_identity() not in del_idents
    }
    out.update(ups)
    if extra:
        out.update(extra)
    return out


class SyncError(RuntimeError):
    pass


def _rules_equal(a: Optional[np.ndarray], b: Optional[np.ndarray]) -> bool:
    """Width-insensitive rule-matrix equality: the reference compares
    fixed-width (100) packed structs (loader.go:580 DeepEqual); our compiled
    widths shrink to the ruleset, so matrices are equal when they agree on
    the common prefix and are zero beyond it."""
    if a is None or b is None:
        return False
    if a.shape[0] < b.shape[0]:
        a, b = b, a
    w = b.shape[0]
    return np.array_equal(a[:w], b) and not a[w:].any()


class AttachBusyError(SyncError):
    """The interface is held by another program (unix.EBUSY analogue)."""


class StatsPoller(Protocol):
    """The pause/resume surface of the metrics poller
    (pkg/metrics/statistics.go:88-110)."""

    def start_poll(self, classifier: Classifier) -> None: ...
    def stop_poll(self) -> None: ...


class Syncer(Protocol):
    """EbpfSyncer interface (ebpfsyncer.go:32-34) — the mock boundary used
    by the node-state controller tests."""

    def sync_interface_ingress_rules(
        self,
        iface_ingress_rules: Dict[str, List[IngressNodeFirewallRules]],
        is_delete: bool,
    ) -> None: ...


class DataplaneSyncer:
    """Production syncer driving a Classifier backend.

    ``classifier_factory`` plays the role of ``createNewManager``
    (ebpfsyncer.go:100 → NewIngNodeFwController); ``attach_fn`` /
    ``detach_fn`` are the XDP attach/detach seams (tests inject failures to
    exercise the EBUSY retry path).
    """

    def __init__(
        self,
        classifier_factory: Callable[[], Classifier],
        registry: Optional[InterfaceRegistry] = None,
        stats_poller: Optional[StatsPoller] = None,
        checkpoint_dir: Optional[str] = None,
        rule_width: Optional[int] = None,
        attach_fn: Optional[Callable[[str], None]] = None,
        detach_fn: Optional[Callable[[str], None]] = None,
        is_valid_interface: Optional[Callable[[str], bool]] = None,
        ebusy_retry_interval_s: float = XDP_EBUSY_RETRY_INTERVAL_S,
        analysis_mode: Optional[str] = None,
        analysis_ring=None,
    ) -> None:
        self._factory = classifier_factory
        self._registry = registry if registry is not None else interfaces_mod.default_registry
        self._stats_poller = stats_poller
        self._checkpoint_dir = checkpoint_dir
        self._rule_width = rule_width
        self._attach_fn = attach_fn
        self._detach_fn = detach_fn
        # Injectable like the package-level isValidInterfaceNameAndState var
        # (ebpfsyncer.go:26, mocked at ebpfsyncer_test.go:1249-1251).
        self._is_valid_interface = is_valid_interface
        self._ebusy_interval = ebusy_retry_interval_s
        # Opt-in pre-sync semantic analysis of the desired table
        # (infw.analysis.rules).  "off" (default) skips it; "events"
        # downgrades findings to AnalysisEventRecords on analysis_ring
        # (never blocks); "block" additionally fails the sync on
        # error-severity findings BEFORE any interface is touched.
        # Constructor arg beats the INFW_SYNC_ANALYSIS env var.
        if analysis_mode is None:
            analysis_mode = os.environ.get("INFW_SYNC_ANALYSIS") or "off"
        if analysis_mode not in ("off", "events", "block"):
            raise ValueError(
                f"unknown analysis mode {analysis_mode!r} "
                "(expected off|events|block)"
            )
        self._analysis_mode = analysis_mode
        self._analysis_ring = analysis_ring
        self.last_analysis_findings: List = []

        self._lock = threading.Lock()
        self._classifier: Optional[Classifier] = None
        self._attached: Set[str] = set()
        self._content: Dict[LpmKey, np.ndarray] = {}
        # Incremental compile state: kept across syncs so a small rule edit
        # patches per-key (addOrUpdateRules/purgeKeys granularity,
        # loader.go:200-218,633) instead of recompiling the whole table.
        self._updater: Optional[IncrementalTables] = None
        # Incremental deltas applied to the updater but not yet persisted
        # to any checkpoint (journal or base); survives failed loads.
        self._pending_deltas: List[Tuple[Dict[LpmKey, np.ndarray], List[LpmKey]]] = []
        # Structural-add overlay (the CIDR-add Map.Update analogue,
        # loader.go:200-218): NEW keys route into this small side dict —
        # classified as a dense side-table combined by longest prefix
        # (jaxpath.classify_with_overlay) — so a 1-key CIDR add never
        # pays the main trie's poptrie re-transform.  Merged into the
        # main table when it outgrows OVERLAY_CAP.  Deletes of MAIN keys
        # remain structural (node repush + re-transform).
        self._overlay: Dict[LpmKey, np.ndarray] = {}
        self._overlay_compiled = None  # (rule_width, CompiledTables) memo

    #: overlay size bound: the combine costs ~9-10 ns/packet FIXED while
    #: any overlay is active (measured v5e, size-independent 64->1024
    #: entries — tools/profile_overlay.py), so the cap bounds memory and
    #: compile variety, not marginal cost; overflow merges into the main
    #: trie (paying one re-transform)
    OVERLAY_CAP = 1024
    #: only route to the overlay when the main table is trie-path scale
    #: (a dense-path main table rebuilds in milliseconds anyway)
    OVERLAY_MIN_MAIN = 4096

    # -- public surface ------------------------------------------------------

    def _valid_fn(self) -> Callable[[str], bool]:
        """Resolve the validity seam (the injectable
        isValidInterfaceNameAndState package var, ebpfsyncer.go:26)."""
        return self._is_valid_interface or self._registry.is_valid_interface_name_and_state

    def sync_interface_ingress_rules(
        self,
        iface_ingress_rules: Dict[str, List[IngressNodeFirewallRules]],
        is_delete: bool,
    ) -> None:
        """SyncInterfaceIngressRules (ebpfsyncer.go:70-126)."""
        with self._lock:
            log.info("syncing ingress firewall rules for %d interfaces (delete=%s)",
                     len(iface_ingress_rules), is_delete)
            if self._stats_poller is not None:
                self._stats_poller.stop_poll()
            try:
                self._create_manager_if_not_exists()
                if is_delete:
                    self._reset_all()
                    return
                # Build the desired table content BEFORE touching the attach
                # set: compilation is pure, so a CompileError (bad port
                # string, out-of-range order...) leaves the dataplane exactly
                # as it was — no interfaces detached, last-good rules intact.
                desired, width = self._build_desired_content(iface_ingress_rules)
                self._pre_sync_analysis(desired)
                self._detach_unmanaged_interfaces(iface_ingress_rules)
                self._attach_new_interfaces(iface_ingress_rules)
                self._load_ingress_node_firewall_rules(desired, width)
                # The attach/detach set may change even when rule content
                # does not; the manifest must always reflect it or a restart
                # re-adopts stale attachments.
                self._save_manifest()
            finally:
                if self._stats_poller is not None and self._classifier is not None:
                    self._stats_poller.start_poll(self._classifier)

    def apply_edit_transaction(self, ops, reason: str = "manual",
                               enqueue_ts=None, stats=None, ring=None):
        """Apply one batched edit transaction (infw.txn fold semantics)
        as ONE device patch generation — the update-storm counterpart of
        ``sync_interface_ingress_rules``: where a sync reconciles a full
        desired state, this folds N queued single-key edits
        (``infw.txn.EditOp``) into their net effect and lands them with
        one ``IncrementalTables.apply``, one ``load_tables`` (one H2D
        staging pass + one fused scatter launch) and the same overlay /
        journal / checkpoint discipline as the sync path.  The old
        generation serves until the swap; a transaction the updater
        cannot absorb escalates to the columnar rebuild.

        Requires a live dataplane (a prior sync created the classifier
        and updater).  ``enqueue_ts``/``stats``/``ring`` feed the
        per-op staleness histogram, TxnStats counters and the
        PatchTxnRecord obs event."""
        from . import txn as txn_mod

        with self._lock:
            if self._classifier is None or self._updater is None:
                raise SyncError(
                    "no dataplane to edit (sync rules before queuing edits)"
                )
            t0 = time.monotonic()
            if self._stats_poller is not None:
                self._stats_poller.stop_poll()
            try:
                report = self._apply_edit_txn_locked(ops, reason, txn_mod)
            finally:
                if self._stats_poller is not None and self._classifier is not None:
                    self._stats_poller.start_poll(self._classifier)
            report.apply_s = time.monotonic() - t0
            staleness = []
            if enqueue_ts:
                staleness = [max(0.0, t0 - ts) for ts in enqueue_ts]
                report.worst_staleness_s = max(staleness, default=0.0)
            if stats is not None:
                stats.note_flush(
                    report.n_ops, report.n_folded, report.dirty_rows,
                    reason, report.escalated, staleness_s=staleness,
                )
            if ring is not None:
                from .obs.events import PatchTxnRecord

                ring.push(PatchTxnRecord(
                    ops=report.n_ops, folded=report.n_folded,
                    dirty_rows=report.dirty_rows, reason=reason,
                    escalated=report.escalated,
                    staleness_us=report.worst_staleness_s * 1e6,
                ))
            return report

    def _apply_edit_txn_locked(self, ops, reason, txn_mod):
        """The routing half, under the lock: fold, route (overlay vs
        main vs escalation, mirroring _load_ingress_node_firewall_rules),
        one updater apply, one device load, journal + checkpoint."""
        ov_idents_before = {k.masked_identity() for k in self._overlay}
        existing = set(self._updater._ident_to_t) | ov_idents_before
        folded = txn_mod.fold_ops(ops, existing)
        # same post-delete size gate as the sync path: a shrunken main
        # table may land on the dense path, which cannot honor overlays
        # (folded.deletes over-counts by the overlay's own deletes —
        # conservative toward merging, never wrong)
        overlay_ok = (
            getattr(self._classifier, "supports_overlay", False)
            and len(self._updater._ident_to_t) - len(folded.deletes)
            > self.OVERLAY_MIN_MAIN
        )
        ups, deletes, ov_dirty = txn_mod.route_folded(
            folded, self._overlay, overlay_ok, self.OVERLAY_CAP
        )
        if ov_dirty:
            self._overlay_compiled = None
        escalated = False
        try:
            if ups and not self._updater.fits(ups):
                raise CompileError("trie depth exceeded; rebuild")
            self._updater.apply(ups, deletes)
            if self._updater.maybe_compact():
                log.info("txn flush: compacted table, tombstones reclaimed")
                escalated = True
        except CompileError:
            # columnar-rebuild escalation: fresh updater absorbs the
            # overlay too; the OLD generation keeps serving until the
            # load below swaps
            content = merge_rebuild_content(
                self._updater.content, ups, deletes, extra=self._overlay
            )
            self._overlay = {}
            self._overlay_compiled = None
            self._updater = IncrementalTables.from_content(
                content, rule_width=self._updater.rule_width
            )
            escalated = True
        # journal records reflect the folded net effect regardless of
        # routing, so restart replay reconstructs everything (same
        # discipline as the sync path's desired diff)
        journal_ups = dict(ups)
        journal_ups.update(
            {k: r for k, (r, _kind) in folded.new_keys.items()}
        )
        journal_ups.update(
            {k: r for k, r in folded.upserts.items()
             if k.masked_identity() in ov_idents_before}
        )
        journal_dels = list(folded.deletes)
        if journal_ups or journal_dels:
            self._pending_deltas.append((journal_ups, journal_dels))
        tables = self._updater.snapshot()
        if os.environ.get("INFW_CHECK_INVARIANTS", "") not in (
            "", "0", "false", "no"
        ):
            self._check_overlay_contract()
        width = self._updater.rule_width
        if getattr(self._classifier, "supports_overlay", False):
            self._classifier.load_tables(
                tables, dirty_hint=self._updater.peek_dirty(),
                overlay=self._compile_overlay(width),
            )
        else:
            if self._overlay:
                raise SyncError("overlay routed to a non-overlay backend")
            self._classifier.load_tables(
                tables, dirty_hint=self._updater.peek_dirty()
            )
        self._updater.clear_dirty()
        self._save_overlay()
        self._content = dict(self._updater.content)
        self._content.update(self._overlay)
        if escalated or not self._journal_pending():
            self._save_checkpoint(tables)
        mode, dirty_rows = getattr(
            self._classifier, "_last_load", ("full", 0)
        )
        log.info(
            "edit txn (%s): %d op(s), %d folded, mode=%s, %d dirty "
            "row(s)%s", reason, folded.n_ops, folded.n_folded, mode,
            dirty_rows, ", escalated" if escalated else "",
        )
        return txn_mod.TxnReport(
            n_ops=folded.n_ops, n_folded=folded.n_folded,
            dirty_rows=int(dirty_rows), mode=mode, reason=reason,
            escalated=escalated,
        )

    @property
    def classifier(self) -> Optional[Classifier]:
        return self._classifier

    def attached_interfaces(self) -> Set[str]:
        with self._lock:
            return set(self._attached)

    def get_classifier_map_content_for_test(self) -> Dict[LpmKey, np.ndarray]:
        """GetBPFMapContentForTest (ebpfsyncer.go:128-133,
        loader.go:286-303): the live table content of the running
        classifier."""
        with self._lock:
            if self._classifier is None:
                raise SyncError("Failed to get BPF map content: no manager")
            return {k: v.copy() for k, v in self._content.items()}

    def shutdown(self) -> None:
        """SIGTERM handler path (ebpfsyncer.go:90-97): full reset, keeping
        the checkpoint so a restart re-adopts (the kernel analogue: pinned
        links keep enforcing after daemon death)."""
        with self._lock:
            if self._classifier is None:
                return
            if self._stats_poller is not None:
                self._stats_poller.stop_poll()
            for name in list(self._attached):
                self._detach(name)
            self._classifier.close()
            self._classifier = None
            self._attached.clear()
            self._content = {}
            self._updater = None
            self._overlay = {}  # restored from the sidecar on restart
            self._overlay_compiled = None

    # -- lifecycle internals -------------------------------------------------

    def _create_manager_if_not_exists(self) -> None:
        """createNewManagerIfNotExists (ebpfsyncer.go:100-104 → loader
        NewIngNodeFwController), incl. pinned-state re-adoption
        (loader.go:99-104,381-407)."""
        if self._classifier is not None:
            return
        self._classifier = self._factory()
        ck = self._load_checkpoint()
        if ck is not None:
            tables, attached = ck
            self._load_overlay({k.masked_identity() for k in tables.content})
            self._overlay_compiled = None
            if self._overlay and getattr(
                self._classifier, "supports_overlay", False
            ) and tables.num_entries > self.OVERLAY_MIN_MAIN:
                self._classifier.load_tables(
                    tables,
                    overlay=self._compile_overlay(tables.rule_width),
                )
            else:
                # overlay unsupported by this backend: fold it into the
                # restored content through one compile
                if self._overlay:
                    merged = dict(tables.content)
                    merged.update(self._overlay)
                    self._overlay = {}
                    tables = compile_tables_from_content(
                        merged, rule_width=tables.rule_width
                    )
                self._classifier.load_tables(tables)
            self._content = dict(tables.content)
            self._content.update(self._overlay)
            valid = self._valid_fn()
            for name in attached:
                if not valid(name):
                    log.warning("re-adopt: interface %s no longer valid", name)
                    continue
                try:
                    self._attach(name)
                except (SyncError, interfaces_mod.InterfaceError):
                    log.warning("re-adopt: interface %s no longer attachable", name)
            log.info("re-adopted checkpoint: %d entries, %d interfaces",
                     tables.num_entries, len(self._attached))

    def _reset_all(self) -> None:
        """resetAll (ebpfsyncer.go:160-181): detach + close + unpin."""
        for name in list(self._attached):
            self._detach(name)
        self._attached.clear()
        if self._classifier is not None:
            self._classifier.close()
        self._classifier = None
        self._content = {}
        self._updater = None
        self._overlay = {}
        self._overlay_compiled = None
        self._remove_checkpoint()
        p = self._overlay_path()
        if p is not None:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    def _detach_unmanaged_interfaces(
        self, iface_ingress_rules: Dict[str, List[IngressNodeFirewallRules]]
    ) -> None:
        """detachUnmanagedInterfaces (ebpfsyncer.go:218-232): anything
        currently attached but absent from the desired set is detached."""
        for name in list(self._attached):
            if name not in iface_ingress_rules:
                log.info("detaching unmanaged interface %s", name)
                self._detach(name)

    def _attach_new_interfaces(
        self, iface_ingress_rules: Dict[str, List[IngressNodeFirewallRules]]
    ) -> None:
        """attachNewInterfaces (ebpfsyncer.go:183-215): invalid interfaces
        are skipped without error; busy interfaces retry."""
        valid = self._valid_fn()
        for name in iface_ingress_rules:
            if name in self._attached:
                continue
            if not valid(name):
                log.error("fail to attach ingress firewall prog to interface %s: invalid state", name)
                continue
            last: Optional[Exception] = None
            for attempt in range(XDP_EBUSY_MAX_RETRIES):
                try:
                    self._attach(name)
                    last = None
                    break
                except AttachBusyError as e:
                    last = e
                    if attempt < XDP_EBUSY_MAX_RETRIES - 1:
                        time.sleep(self._ebusy_interval)
            if last is not None:
                raise SyncError(f"failed to attach interface {name}: {last}")

    def _pre_sync_analysis(self, desired: Dict[LpmKey, np.ndarray]) -> None:
        """Opt-in semantic gate over the desired content (pure — runs
        before any interface or device mutation).  Findings downgrade to
        emitted events by default; only mode="block" turns error-severity
        findings into a SyncError."""
        if self._analysis_mode == "off":
            return
        from .analysis import rules as analysis_rules

        findings = analysis_rules.analyze_content(desired)
        self.last_analysis_findings = findings
        if not findings:
            return
        for f in findings:
            log.log(
                logging.ERROR if f.severity == "error" else logging.WARNING,
                "pre-sync analysis: %s [%s] %s: %s",
                f.severity, f.check, f.entry, f.message,
            )
        if self._analysis_ring is not None:
            from .obs.events import emit_analysis_findings

            emit_analysis_findings(self._analysis_ring, findings)
        errors = [f for f in findings if f.severity == "error"]
        if self._analysis_mode == "block" and errors:
            raise SyncError(
                f"pre-sync analysis found {len(errors)} error finding(s): "
                + "; ".join(f"[{f.check}] {f.entry}" for f in errors[:5])
            )

    def _build_desired_content(
        self, iface_ingress_rules: Dict[str, List[IngressNodeFirewallRules]]
    ) -> Tuple[Dict[LpmKey, np.ndarray], int]:
        """Pure compile step: CRD rules → packed map content.  Raises
        CompileError/InterfaceError without mutating any syncer state."""
        valid = self._valid_fn()
        width = self._desired_width(iface_ingress_rules)
        raw = build_table_content(
            iface_ingress_rules, self._registry, width, is_valid_interface=valid
        )
        # Collapse keys that alias after masking (last writer wins), exactly
        # like successive Map.Update calls on the kernel LPM trie — the diff
        # below and the test-content API must see what the device enforces.
        dedup = {}
        for k, v in raw.items():
            dedup[k.masked_identity()] = (k, v)
        return {k: v for k, v in dedup.values()}, width

    def _load_ingress_node_firewall_rules(
        self, desired: Dict[LpmKey, np.ndarray], width: int
    ) -> None:
        """loadIngressNodeFirewallRules → IngressNodeFwRulesLoader
        (loader.go:130-194): diff desired against current, reload the
        device tables only when the content changed, then pin."""
        stale = self._get_stale_keys(desired)
        current = {k.masked_identity(): v for k, v in self._content.items()}
        changed = bool(stale) or any(
            not _rules_equal(current.get(k.masked_identity()), v)
            for k, v in desired.items()
        )
        if not changed and self._classifier.tables is not None:
            log.info("rules unchanged; skipping device reload")
            return
        if (
            self._updater is not None
            and self._updater.rule_width == width
            and self._updater.fits(desired)
        ):
            # Per-key patch: purge stale identities, upsert changed/new
            # ones (addOrUpdateRules/purgeKeys granularity) — a one-CIDR
            # edit touches one dense row + one trie node.  Diff against the
            # UPDATER's content, not self._content: a failed load/checkpoint
            # leaves _content stale while the updater already mutated, and
            # the next sync must reconcile from what the updater holds.
            base = self._updater.content
            base_by_ident = {k.masked_identity(): v for k, v in base.items()}
            ov_by_ident = {k.masked_identity(): k for k in self._overlay}
            desired_idents = {k.masked_identity() for k in desired}
            deletes = [
                k for k in base
                if k.masked_identity() not in desired_idents
            ]
            ov_deletes = [
                k for k in self._overlay
                if k.masked_identity() not in desired_idents
            ]
            upserts = {}
            ov_upserts = {}
            new_keys = {}
            for k, v in desired.items():
                ident = k.masked_identity()
                if ident in base_by_ident:
                    if not _rules_equal(base_by_ident[ident], v):
                        upserts[k] = v
                elif ident in ov_by_ident:
                    if not _rules_equal(
                        self._overlay.get(ov_by_ident[ident]), v
                    ):
                        ov_upserts[k] = v
                else:
                    new_keys[k] = v
            # journal records reflect the DESIRED diff regardless of how
            # it was routed, so restart replay reconstructs everything
            journal_upserts = {**upserts, **ov_upserts, **new_keys}
            journal_deletes = deletes + ov_deletes
            if ov_deletes or ov_upserts:
                self._overlay_compiled = None
            for k in ov_deletes:
                self._overlay.pop(k, None)
            for k, v in ov_upserts.items():
                self._overlay.pop(ov_by_ident[k.masked_identity()], None)
                self._overlay[k] = v
            # gate on the POST-delete size: a delete-heavy sync can
            # shrink the main table onto the dense path, where the
            # classifier cannot honor an overlay (it raises rather than
            # silently dropping rules) — merge instead
            overlay_ok = (
                getattr(self._classifier, "supports_overlay", False)
                and len(base) - len(deletes) > self.OVERLAY_MIN_MAIN
            )
            if overlay_ok and (
                len(self._overlay) + len(new_keys) <= self.OVERLAY_CAP
            ):
                # structural ADD fast path: new keys go to the dense
                # side-table; the main trie's device form is untouched
                if new_keys:
                    self._overlay_compiled = None
                self._overlay.update(new_keys)
            else:
                # overflow (or no overlay support): merge everything into
                # the main table — the amortized structural slow path
                if self._overlay or new_keys:
                    upserts = {**upserts, **self._overlay, **new_keys}
                    self._overlay = {}
                    self._overlay_compiled = None
            self._updater.apply(upserts, deletes)
            log.info(
                "incremental table update: %d main upserts, %d main "
                "deletes, %d overlay adds/updates (%d overlay total)",
                len(upserts), len(deletes),
                len(ov_upserts) + len(new_keys), len(self._overlay),
            )
            # Deltas accumulate until a checkpoint (journal or base)
            # actually persists them: a failed device load leaves the
            # delta pending, so the NEXT successful sync still journals
            # it instead of silently dropping it from the checkpoint.
            if journal_upserts or journal_deletes:
                self._pending_deltas.append((journal_upserts, journal_deletes))
            incremental = True
            if self._updater.maybe_compact():
                log.info("compacted table: tombstones reclaimed")
                incremental = False  # checkpoint needs the full state
        else:
            self._updater = IncrementalTables.from_content(
                desired, rule_width=width
            )
            self._overlay = {}  # full rebuild absorbs everything
            self._overlay_compiled = None
            incremental = False
        tables = self._updater.snapshot()
        if os.environ.get("INFW_CHECK_INVARIANTS", "") not in (
            "", "0", "false", "no"
        ):
            self._check_overlay_contract()
        # Dirty rows accumulated since the last SUCCESSFUL load: the
        # device backend patches exactly those rows instead of diffing or
        # re-uploading the table.  Cleared only after load_tables returns
        # (a failed load keeps accumulating, so the next attempt's hint
        # still covers this generation's changes).
        if getattr(self._classifier, "supports_overlay", False):
            self._classifier.load_tables(
                tables, dirty_hint=self._updater.peek_dirty(),
                overlay=self._compile_overlay(width),
            )
        else:
            self._classifier.load_tables(
                tables, dirty_hint=self._updater.peek_dirty()
            )
        self._updater.clear_dirty()
        self._save_overlay()
        self._content = dict(desired)
        # Checkpointing follows the same O(delta) discipline as the device
        # path: an incremental sync appends small journal records (one per
        # pending delta); the full (compression-bound, ~14s at 300K
        # entries) base rewrite only happens on rebuilds or when the
        # journal grows past its cap.
        if incremental and self._journal_pending():
            return
        self._save_checkpoint(tables)

    def _check_overlay_contract(self) -> None:
        """Opt-in (INFW_CHECK_INVARIANTS=1) overlay accounting contract,
        checked at the sync boundary BEFORE the device load: the overlay
        must respect its capacity bound and stay identity-disjoint from
        the main table — the classify combine resolves ties by strict
        mask-len score, which is only collision-free while no LPM
        identity lives in both tables.  A violation here is a routing bug
        in _load_ingress_node_firewall_rules, surfaced at the mutation
        site instead of as a wrong-verdict mystery."""
        if len(self._overlay) > self.OVERLAY_CAP:
            raise SyncError(
                f"overlay holds {len(self._overlay)} keys — exceeds "
                f"OVERLAY_CAP={self.OVERLAY_CAP} (spill-to-merge routing "
                "failed)"
            )
        if self._updater is None or not self._overlay:
            return
        main = {k.masked_identity() for k in self._updater.content}
        dup = [
            k for k in self._overlay if k.masked_identity() in main
        ]
        if dup:
            raise SyncError(
                f"{len(dup)} overlay key(s) alias main-table identities "
                f"(first: {dup[0]}); the longest-prefix combine requires "
                "disjoint identities"
            )

    def _compile_overlay(self, width: int) -> Optional[CompiledTables]:
        """Small dense CompiledTables from the overlay dict, or None when
        empty.  Memoized until the overlay mutates — a rules-only edit to
        the MAIN table must not pay an overlay recompile + re-upload (the
        classifier also reuses its device copy for the same instance)."""
        if not self._overlay:
            self._overlay_compiled = None
            return None
        cached = getattr(self, "_overlay_compiled", None)
        if cached is not None and cached[0] == width:
            return cached[1]
        ct = compile_tables_from_content(
            dict(self._overlay), rule_width=width
        )
        self._overlay_compiled = (width, ct)
        return ct

    def _overlay_path(self) -> Optional[str]:
        if not self._checkpoint_dir:
            return None
        return os.path.join(self._checkpoint_dir, "overlay.json")

    def _save_overlay(self) -> None:
        """Sidecar checkpoint for the overlay: the journal carries its
        deltas too, but a journal-overflow base rewrite saves only the
        main updater's snapshot — this tiny file keeps overlay keys
        restorable across that."""
        path = self._overlay_path()
        if path is None:
            return
        if not self._overlay:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            return
        os.makedirs(self._checkpoint_dir, exist_ok=True)
        rec = [
            [k.prefix_len, k.ingress_ifindex, k.ip_data.hex(),
             np.asarray(v, np.int32).tolist()]
            for k, v in self._overlay.items()
        ]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)

    def _load_overlay(self, content_idents) -> None:
        """Restore the overlay sidecar, dropping entries the restored
        main content already covers (journal replay may have landed them
        in the main table)."""
        path = self._overlay_path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                rec = json.load(f)
            self._overlay = {
                key: np.asarray(rows, np.int32)
                for p, i, h, rows in rec
                if (key := LpmKey(p, i, bytes.fromhex(h))).masked_identity()
                not in content_idents
            }
        except (ValueError, KeyError, TypeError) as e:
            log.warning("overlay sidecar unreadable (%s); dropping", e)
            self._overlay = {}

    def _desired_width(self, iface_ingress_rules) -> int:
        if self._rule_width is not None:
            return self._rule_width
        return min(min_rule_width(iface_ingress_rules), MAX_RULES_PER_TARGET)

    def _get_stale_keys(self, desired: Dict[LpmKey, np.ndarray]) -> List[LpmKey]:
        """getStaleKeys (loader.go:551-631): current keys that are absent
        from — or whose rules differ from — the desired content."""
        want = {k.masked_identity(): v for k, v in desired.items()}
        return [
            k
            for k, v in self._content.items()
            if not _rules_equal(want.get(k.masked_identity()), v)
        ]

    # -- attach/detach seams -------------------------------------------------

    def _attach(self, name: str) -> None:
        if self._attach_fn is not None:
            self._attach_fn(name)
        else:
            self._registry.set_xdp(name, True)
        self._attached.add(name)

    def _detach(self, name: str) -> None:
        try:
            if self._detach_fn is not None:
                self._detach_fn(name)
            else:
                self._registry.set_xdp(name, False)
        except interfaces_mod.InterfaceError:
            pass  # interface vanished; treat as detached (loader.go:268-283)
        self._attached.discard(name)

    # -- checkpoint ("pinning") ---------------------------------------------

    def _ck_paths(self) -> Optional[Tuple[str, str]]:
        if not self._checkpoint_dir:
            return None
        return (
            os.path.join(self._checkpoint_dir, "tables.npz"),
            os.path.join(self._checkpoint_dir, "manifest.json"),
        )

    def _save_checkpoint(self, tables: CompiledTables) -> None:
        paths = self._ck_paths()
        if paths is None:
            return
        tables_path, _ = paths
        os.makedirs(self._checkpoint_dir, exist_ok=True)
        # Clear the journal BEFORE swapping the base: a crash in between
        # leaves old-base + empty-journal (consistent, merely stale —
        # the controller's next sync converges it), never new-base +
        # stale-journal, whose replay would resurrect deleted rules.
        self._clear_journal()
        # Atomic swap: never leave a torn checkpoint (the bpffs pin is
        # similarly all-or-nothing).
        tmp = tables_path + ".tmp.npz"
        tables.save(tmp)
        os.replace(tmp, tables_path)
        self._pending_deltas = []
        # manifest is written by the sync-level _save_manifest() call

    # -- delta-journal checkpointing ----------------------------------------
    #
    # A 1-key sync must not pay a full-table compression pass: the delta
    # is appended as journal/<seq>.json next to the base npz, and restart
    # replays base.content + journal (same last-writer-wins masked-identity
    # semantics as successive Map.Update calls) through one compile.  The
    # journal is capped (JOURNAL_MAX records) — overflow rewrites the base.

    JOURNAL_MAX = 64

    def _journal_dir(self) -> Optional[str]:
        if not self._checkpoint_dir:
            return None
        return os.path.join(self._checkpoint_dir, "journal")

    def _journal_files(self) -> List[str]:
        d = self._journal_dir()
        if d is None or not os.path.isdir(d):
            return []
        # tmp files are '<seq>.json.tmp' — excluded by the suffix check
        return sorted(f for f in os.listdir(d) if f.endswith(".json"))

    def _journal_pending(self) -> bool:
        """Append every pending delta as a journal record; returns False
        when the caller must do a full base save instead (no checkpoint
        dir, no base yet, or the journal would exceed its cap)."""
        d = self._journal_dir()
        paths = self._ck_paths()
        if d is None or paths is None or not os.path.exists(paths[0]):
            return False
        if not self._pending_deltas:
            return True  # nothing new to persist; checkpoint already current
        existing = self._journal_files()
        if len(existing) + len(self._pending_deltas) > self.JOURNAL_MAX:
            log.info("checkpoint journal full (%d records); compacting to base",
                     len(existing))
            return False
        os.makedirs(d, exist_ok=True)
        seq = int(existing[-1].split(".")[0]) + 1 if existing else 0
        for upserts, deletes in self._pending_deltas:
            rec = {
                "upserts": [
                    [k.prefix_len, k.ingress_ifindex, k.ip_data.hex(),
                     np.asarray(v, np.int32).tolist()]
                    for k, v in upserts.items()
                ],
                "deletes": [
                    [k.prefix_len, k.ingress_ifindex, k.ip_data.hex()]
                    for k in deletes
                ],
            }
            path = os.path.join(d, f"{seq:08d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, path)
            seq += 1
        self._pending_deltas = []
        return True

    def _clear_journal(self) -> None:
        d = self._journal_dir()
        if d is None or not os.path.isdir(d):
            return
        for f in os.listdir(d):  # records AND orphaned tmp files
            try:
                os.remove(os.path.join(d, f))
            except FileNotFoundError:
                pass

    def _replay_journal(self, tables: CompiledTables) -> CompiledTables:
        """Apply journal records to the base checkpoint's content and
        recompile once.  A corrupt record stops replay at that point
        (prefix semantics — everything before it is still applied)."""
        files = self._journal_files()
        if not files:
            return tables
        content = dict(tables.content)
        by_ident = {k.masked_identity(): k for k in content}
        d = self._journal_dir()
        applied = 0
        for fn in files:
            try:
                with open(os.path.join(d, fn)) as f:
                    rec = json.load(f)
                ups = [
                    (LpmKey(p, i, bytes.fromhex(h)), np.asarray(rows, np.int32))
                    for p, i, h, rows in rec["upserts"]
                ]
                dels = [LpmKey(p, i, bytes.fromhex(h))
                        for p, i, h in rec["deletes"]]
            except (OSError, ValueError, KeyError, TypeError) as e:
                log.warning("corrupt journal record %s: %s; replay stops here",
                            fn, e)
                break
            for k in dels:
                old = by_ident.pop(k.masked_identity(), None)
                if old is not None:
                    content.pop(old, None)
            for k, rows in ups:
                ident = k.masked_identity()
                old = by_ident.get(ident)
                if old is not None and old != k:
                    content.pop(old, None)
                by_ident[ident] = k
                content[k] = rows
            applied += 1
        if applied == 0:
            return tables  # nothing usable: skip the pointless recompile
        log.info("checkpoint journal: replayed %d/%d records", applied, len(files))
        return compile_tables_from_content(content, rule_width=tables.rule_width)

    def _save_manifest(self) -> None:
        paths = self._ck_paths()
        if paths is None:
            return
        _, manifest_path = paths
        os.makedirs(self._checkpoint_dir, exist_ok=True)
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"attached": sorted(self._attached)}, f)
        os.replace(tmp, manifest_path)

    def _load_checkpoint(self) -> Optional[Tuple[CompiledTables, List[str]]]:
        paths = self._ck_paths()
        if paths is None:
            return None
        tables_path, manifest_path = paths
        if not (os.path.exists(tables_path) and os.path.exists(manifest_path)):
            return None
        try:
            tables = CompiledTables.load(tables_path)
            tables = self._replay_journal(tables)
            with open(manifest_path) as f:
                manifest = json.load(f)
            return tables, list(manifest.get("attached", []))
        except Exception as e:  # torn/corrupt checkpoint: start fresh
            log.warning("failed to load checkpoint: %s", e)
            return None

    def _remove_checkpoint(self) -> None:
        paths = self._ck_paths()
        if paths is None:
            return
        self._clear_journal()
        for p in paths:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass


# -- process singleton (GetEbpfSyncer, ebpfsyncer.go:38-67) ------------------

_singleton_lock = threading.Lock()
_singleton: Optional[DataplaneSyncer] = None


def get_syncer(**kwargs) -> DataplaneSyncer:
    """First call constructs the singleton with the given kwargs; later
    calls return it unchanged (sync.Once semantics, ebpfsyncer.go:56-63)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = DataplaneSyncer(**kwargs)
        return _singleton


def reset_singleton_for_test() -> None:
    """once = sync.Once{} (ebpfsyncer_test.go:1232-1234): simulates daemon
    process restart."""
    global _singleton
    with _singleton_lock:
        _singleton = None


class TenantError(SyncError):
    """Tenant registry misuse: unknown name, duplicate create, or a
    table the arena geometry cannot hold."""


class TenantRegistry:
    """Multi-tenant control plane over an arena-backed classifier
    (backend.tpu.ArenaClassifier / backend.mesh.MeshArenaClassifier):
    names tenants, owns one IncrementalTables per tenant (the same
    per-key incremental compile state the single-tenant syncer keeps),
    and drives the tenant lifecycle —

    - ``create_tenant``: compile + slab assign + page-table flip;
    - ``update_tenant`` / ``apply_edit_transaction``: per-tenant
      incremental edits through the SAME fold + dirty-hint machinery as
      the single-tenant path (infw.txn.fold_ops), landing as per-slab
      row scatters;
    - ``swap_tenant``: full ruleset replacement as stage (background
      slab bake into a free page) + activate (the O(1) page-table row
      flip) — the re-upload killer the bench_tenant tier measures;
    - ``destroy_tenant``: row flip to -1 + page free.

    Every transition emits a TenantSwapRecord on the obs event ring
    (when given one) and the tenant_* counters surface through
    ``counter_values`` for /metrics."""

    def __init__(self, classifier, rule_width: int,
                 event_ring=None) -> None:
        self._clf = classifier
        self._rule_width = rule_width
        self._ring = event_ring
        self._lock = threading.Lock()
        #: serializes whole lifecycle operations per registry: the
        #: per-tenant IncrementalTables is not thread-safe, and an
        #: update racing a swap's updater replacement could scatter a
        #: stale snapshot over the freshly swapped slab — lifecycle ops
        #: are control-plane-rate, so one coarse lock is the honest
        #: contract (classify never takes it)
        self._op_lock = threading.RLock()
        self._names: Dict[str, int] = {}
        self._updaters: Dict[int, IncrementalTables] = {}
        #: per-tenant shared-delta overlay content (ISSUE-15): small
        #: deltas of a tenant sitting on a SHARED (content-addressed)
        #: page ride the dense overlay side-pool instead of forcing a
        #: CoW clone — only brand-new prefixes (and edits/deletes of
        #: overlay-resident ones) are overlay-eligible, because the
        #: longest-prefix combine is strict (an overlay entry with the
        #: same prefix as a main-slab entry would lose the tie).  Any
        #: non-eligible edit folds the overlay back into the main
        #: updater and lands as the clone it was deferring.
        self._overlays: Dict[int, Dict[LpmKey, np.ndarray]] = {}
        #: creates in flight: name -> reserved id.  The name/id become
        #: visible in _names/_updaters only once the compile + slab
        #: load SUCCEEDS, so concurrent edits on a half-created tenant
        #: get a clean TenantError("unknown"), never a None updater.
        self._creating: Dict[str, int] = {}
        self._next_id = 0
        self._max = classifier.spec.max_tenants

    # -- introspection -------------------------------------------------------

    @property
    def classifier(self):
        return self._clf

    def tenant_id(self, name: str) -> int:
        with self._lock:
            if name not in self._names:
                raise TenantError(f"unknown tenant {name!r}")
            return self._names[name]

    def tenant_names(self):
        with self._lock:
            return sorted(self._names)

    def tenant_ids_by_name(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._names)

    def counter_values(self) -> Dict[str, int]:
        out = {"tenant_registered": len(self._names)}
        getter = getattr(self._clf, "tenant_counters", None)
        if getter is not None:
            out.update(getter())
        return out

    def _emit(self, record) -> None:
        if self._ring is not None:
            try:
                self._ring.push(record)
            except Exception:
                pass

    def _alloc_id(self) -> int:
        busy = set(self._updaters) | set(self._creating.values())
        for _ in range(self._max):
            tid = self._next_id % self._max
            self._next_id += 1
            if tid not in busy:
                return tid
        raise TenantError(f"tenant registry full ({self._max} ids)")

    # -- lifecycle -----------------------------------------------------------

    def create_tenant(self, name: str, content: Dict[LpmKey, np.ndarray]) -> int:
        from .obs.events import TenantSwapRecord

        with self._op_lock:
            return self._create_tenant_locked(name, content)

    @must_precede("load_tenant", "store:_names")
    def _create_tenant_locked(self, name, content) -> int:
        from .obs.events import TenantSwapRecord

        with self._lock:
            if name in self._names or name in self._creating:
                raise TenantError(f"tenant {name!r} already exists")
            tid = self._alloc_id()
            self._creating[name] = tid
        try:
            upd = IncrementalTables.from_content(
                dict(content), rule_width=self._rule_width
            )
            snap = upd.snapshot()
            t0 = time.perf_counter()
            self._clf.load_tenant(tid, snap)
            dt = (time.perf_counter() - t0) * 1e6
            upd.start_dirty_tracking()
        except Exception:
            with self._lock:
                self._creating.pop(name, None)
            raise
        with self._lock:
            self._creating.pop(name, None)
            self._names[name] = tid
            self._updaters[tid] = upd
        self._emit(TenantSwapRecord(
            tenant=name, tenant_id=tid,
            page=self._clf.allocator.page_of(tid) or 0,
            entries=snap.num_entries, kind="create", stage_us=dt,
        ))
        return tid

    def update_tenant(self, name: str,
                      ups: Dict[LpmKey, np.ndarray], dels) -> str:
        """Incremental per-tenant edit: one updater apply + one per-slab
        device patch (dirty-hinted).  When the tenant sits on a SHARED
        content-addressed page and the delta is overlay-eligible (only
        brand-new prefixes added, or overlay-resident ones edited/
        deleted), the delta rides the dense overlay side-pool and the
        shared slab stays untouched — no CoW clone (returns "overlay").
        Otherwise the edit lands in the main slab: the allocator
        patches a private page in place or CoW-clones a shared one —
        or, for a subtree-SPLICED tenant (ISSUE-17), patches a private
        plane / unsplices just the edited subtree, which is why spliced
        tenants skip the overlay detour entirely — and any deferred
        overlay content folds back in first.  Escalates to a rebuild
        exactly like the single-tenant syncer (CompileError /
        capacity)."""
        with self._op_lock:
            tid = self.tenant_id(name)
            with self._lock:
                upd = self._updaters[tid]
            if self._try_overlay_delta(tid, upd, ups, dels):
                return "overlay"
            merge_ov = self._overlays.get(tid)
            if merge_ov:
                # the deferred shared-page delta folds back into the
                # main updater before the edit that forced the clone
                # (the dict clears only after the load succeeds).
                # Overlay keys THIS edit deletes must not fold back in:
                # apply() runs deletes before upserts, so a folded-in
                # copy would resurrect the key the caller just removed
                del_idents = {k.masked_identity() for k in dels}
                ups = {
                    **{k: v for k, v in merge_ov.items()
                       if k.masked_identity() not in del_idents},
                    **dict(ups),
                }
            try:
                if ups and not upd.fits(ups):
                    raise CompileError("trie depth exceeded; rebuild")
                upd.apply(ups, list(dels))
                upd.maybe_compact()
            except CompileError:
                # the SAME escalation recipe as the single-tenant flush
                # path (merge_rebuild_content) — no drift between them
                upd = IncrementalTables.from_content(
                    merge_rebuild_content(upd.content, ups, dels),
                    rule_width=self._rule_width,
                )
                with self._lock:
                    self._updaters[tid] = upd
            hint = upd.peek_dirty()
            snap = upd.snapshot()
            path = self._clf.load_tenant(tid, snap, hint=hint)
            upd.clear_dirty()
            if merge_ov:
                self._clear_overlay(tid)
            return path

    def _try_overlay_delta(self, tid: int, upd, ups, dels) -> bool:
        """Route a small delta of a shared-page tenant into the dense
        overlay side-pool.  Eligible iff the classifier HAS an overlay
        pool, the tenant's main page is shared (a main-slab write would
        CoW-clone), every delete targets an overlay-resident identity,
        and every upsert is either overlay-resident or a brand-new
        identity (same-prefix-as-main entries would lose the strict
        longest-prefix tie and must clone instead).  Commits the
        overlay dict only after the device load succeeds; an overlay
        capacity overflow falls back to the clone path."""
        ov_alloc = getattr(self._clf, "overlay_allocator", None)
        if ov_alloc is None:
            return False
        alloc = getattr(self._clf, "allocator", None)
        if alloc is None or not alloc.tenant_shares_page(tid):
            return False
        if getattr(alloc, "tenant_splices", None) and alloc.tenant_splices(tid):
            # overlay-vs-unsplice-vs-clone routing (ISSUE-17): a
            # subtree-SPLICED tenant never needs the overlay detour — a
            # deep edit patches a private plane or unsplices exactly
            # one subtree in place (the whole-slab CoW clone the
            # overlay exists to avoid no longer happens), so the edit
            # rides the main-slab splice path and the slab stays
            # structurally compressed
            return False
        ov = self._overlays.get(tid, {})
        ov_idents = {k.masked_identity(): k for k in ov}
        base_idents = set(upd._ident_to_t)
        for k in dels:
            if k.masked_identity() not in ov_idents:
                return False
        for k in ups:
            ident = k.masked_identity()
            if ident in base_idents and ident not in ov_idents:
                return False
        new_ov = dict(ov)
        for k in dels:
            new_ov.pop(ov_idents[k.masked_identity()], None)
        for k, r in ups.items():
            old_k = ov_idents.get(k.masked_identity())
            if old_k is not None and old_k != k:
                new_ov.pop(old_k, None)
            new_ov[k] = np.asarray(r)
        try:
            if new_ov:
                ct = compile_tables_from_content(
                    new_ov, rule_width=self._rule_width
                )
                self._clf.load_tenant_overlay(tid, ct)
            else:
                self._clf.load_tenant_overlay(tid, None)
        except Exception:
            # overlay slab bound exceeded (or the side-pool is full):
            # the caller folds everything into the main slab instead
            return False
        self._overlays[tid] = new_ov
        return True

    def _clear_overlay(self, tid: int) -> None:
        self._overlays.pop(tid, None)
        if getattr(self._clf, "overlay_allocator", None) is not None:
            try:
                self._clf.load_tenant_overlay(tid, None)
            except Exception:
                pass

    def apply_edit_transaction(self, name: str, ops) -> str:
        """Fold + apply a batched edit transaction for one tenant
        through the production fold (infw.txn.fold_ops) — N ops, one
        slab patch.  Overlay routing is disabled on the arena v1 (the
        per-tenant dense side-pool is driven explicitly), so every
        folded effect lands in the tenant's main slab."""
        from .txn import fold_ops, route_folded

        with self._op_lock:
            return self._apply_edit_transaction_locked(
                name, ops, fold_ops, route_folded
            )

    def _apply_edit_transaction_locked(self, name, ops, fold_ops,
                                       route_folded) -> str:
        tid = self.tenant_id(name)
        with self._lock:
            upd = self._updaters[tid]
        folded = fold_ops(ops, set(upd._ident_to_t))
        no_overlay: Dict[LpmKey, np.ndarray] = {}
        ups, dels, _dirty = route_folded(folded, no_overlay, False, 0)
        if not ups and not dels:
            return "noop"
        return self.update_tenant(name, ups, dels)

    def swap_tenant(self, name: str,
                    content: Dict[LpmKey, np.ndarray]) -> None:
        """Full ruleset replacement by page-table flip: bake the new
        slab into a free page (stage), then activate — O(1) on the
        serving path regardless of table size."""
        from .obs.events import TenantSwapRecord

        with self._op_lock:
            return self._swap_tenant_locked(name, content)

    def _swap_tenant_locked(self, name, content) -> None:
        from .obs.events import TenantSwapRecord

        tid = self.tenant_id(name)
        upd = IncrementalTables.from_content(
            dict(content), rule_width=self._rule_width
        )
        snap = upd.snapshot()
        # the overlay delta belongs to the ruleset being REPLACED:
        # clear it BEFORE the flip, so concurrent classifies see either
        # old-main+delta or (briefly) old-main alone — bounded
        # staleness of states that actually existed — never the
        # new-main+stale-delta hybrid that never did
        self._clear_overlay(tid)
        t0 = time.perf_counter()
        if hasattr(self._clf, "stage_tenant"):
            page = self._clf.stage_tenant(snap)
            t1 = time.perf_counter()
            self._clf.activate_tenant(tid, page, snap)
        else:
            page = -1
            t1 = time.perf_counter()
            self._clf.swap_tenant(tid, snap)
        t2 = time.perf_counter()
        upd.start_dirty_tracking()
        with self._lock:
            self._updaters[tid] = upd
        self._emit(TenantSwapRecord(
            tenant=name, tenant_id=tid,
            page=self._clf.allocator.page_of(tid) if page < 0 else page,
            entries=snap.num_entries, kind="swap",
            stage_us=(t1 - t0) * 1e6, flip_us=(t2 - t1) * 1e6,
        ))

    def destroy_tenant(self, name: str) -> None:
        from .obs.events import TenantSwapRecord

        with self._op_lock:
            tid = self.tenant_id(name)
            self._clf.destroy_tenant(tid)
            self._destroy_finish(name, tid)

    def _destroy_finish(self, name: str, tid: int) -> None:
        from .obs.events import TenantSwapRecord
        self._overlays.pop(tid, None)  # clf.destroy_tenant freed the slab
        with self._lock:
            self._names.pop(name, None)
            self._updaters.pop(tid, None)
        self._emit(TenantSwapRecord(
            tenant=name, tenant_id=tid, page=-1, entries=0, kind="destroy",
        ))

    # -- dataplane passthrough ----------------------------------------------

    def classify_mixed(self, batch, tenant_names_or_ids,
                       apply_stats: bool = True):
        """Mixed-tenant classify: per-packet tenant tags by name (str)
        or id (int) — one batch, one dispatch."""
        tags = np.asarray([
            self._names.get(t, -1) if isinstance(t, str) else int(t)
            for t in tenant_names_or_ids
        ], np.int32)
        return self._clf.classify_tenants(
            batch, tags, apply_stats=apply_stats
        )
