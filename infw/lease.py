"""Single-writer lease — the manager's leader election.

The reference manager runs with controller-runtime leader election
(/root/reference/main.go:76-85, LeaderElectionID
"e9b59492.ingress-nodefw.openshift.io"): one manager process holds a
renewable lease object; a second instance blocks in standby until the
lease expires, then takes over.  Two active managers against one store
would fight (duplicate NodeState writes, conflicting status rollups),
exactly like two un-elected controller-runtime managers against one API
server.

Two implementations of one contract:

- ``InMemoryLease`` — same-process instances sharing an
  ``InMemoryStore`` (the envtest role);
- ``FileLease`` — cross-process single-writer over a shared state dir
  (the compose deployment, where the dir IS the cluster API), using
  atomic create (O_EXCL) for first acquisition and write-then-verify
  for steal/renew.

Takeover semantics (matching the leader-election contract):

- ``try_acquire`` succeeds when the lease is free, expired (steal), or
  already held by this holder (re-entrant refresh);
- ``renew`` succeeds ONLY while this holder still owns the lease; a
  renewal failure means another instance stole an expired lease and the
  caller must stop acting as leader (controller-runtime treats this as
  fatal and exits the process; Manager.stop()s itself);
- holders never block each other's clocks: a crashed leader is taken
  over after at most ``duration_s`` without any cleanup.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional, Tuple

log = logging.getLogger("infw.lease")


class InMemoryLease:
    """Thread-safe lease for same-process manager instances."""

    def __init__(self, duration_s: float = 15.0) -> None:
        self.duration_s = float(duration_s)
        self._lock = threading.Lock()
        self._holder: Optional[str] = None
        self._expires_at = 0.0

    def holder(self) -> Optional[Tuple[str, float]]:
        with self._lock:
            if self._holder is None or time.time() >= self._expires_at:
                return None
            return self._holder, self._expires_at

    def try_acquire(self, holder: str) -> bool:
        now = time.time()
        with self._lock:
            if (
                self._holder is None
                or self._holder == holder
                or now >= self._expires_at
            ):
                stolen = (
                    self._holder is not None
                    and self._holder != holder
                    and now >= self._expires_at
                )
                if stolen:
                    log.info(
                        "lease: %s taking over expired lease from %s",
                        holder, self._holder,
                    )
                self._holder = holder
                self._expires_at = now + self.duration_s
                return True
            return False

    def renew(self, holder: str) -> bool:
        now = time.time()
        with self._lock:
            if self._holder == holder and now < self._expires_at:
                self._expires_at = now + self.duration_s
                return True
            return False

    def release(self, holder: str) -> None:
        with self._lock:
            if self._holder == holder:
                self._holder = None
                self._expires_at = 0.0


class FileLease:
    """Cross-process lease over a shared file.

    First acquisition uses O_CREAT|O_EXCL (atomic on one filesystem).
    Steal and renew write a temp file, os.replace() it over the lease,
    then RE-READ to verify this holder won — two concurrent stealers
    both replace, but only the last writer survives the verify, and the
    loser backs off.  The verify read happens after a short settle so a
    racing replace lands before we conclude."""

    def __init__(self, path: str, duration_s: float = 15.0,
                 settle_s: float = 0.05) -> None:
        self.path = path
        self.duration_s = float(duration_s)
        self.settle_s = float(settle_s)

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError):
            # torn write from a crashed holder: treat as expired garbage
            return {}

    def _write(self, holder: str) -> dict:
        rec = {
            "holder": holder,
            "acquired_at": time.time(),
            "expires_at": time.time() + self.duration_s,
            "pid": os.getpid(),
        }
        tmp = f"{self.path}.{holder}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return rec

    def holder(self) -> Optional[Tuple[str, float]]:
        rec = self._read()
        if not rec or not rec.get("holder"):
            return None
        if time.time() >= float(rec.get("expires_at", 0)):
            return None
        return rec["holder"], float(rec["expires_at"])

    def try_acquire(self, holder: str) -> bool:
        rec = self._read()
        if rec is None:
            # free: atomic exclusive create wins or loses cleanly
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return False
            with os.fdopen(fd, "w") as f:
                json.dump({
                    "holder": holder,
                    "acquired_at": time.time(),
                    "expires_at": time.time() + self.duration_s,
                    "pid": os.getpid(),
                }, f)
                f.flush()
                os.fsync(f.fileno())
            return True
        if (
            rec.get("holder") == holder
            and time.time() < float(rec.get("expires_at", 0))
        ):
            self._write(holder)  # re-entrant refresh while still held
            return True
        if time.time() < float(rec.get("expires_at", 0)):
            return False
        # Expired — even when the stale record names THIS holder: a
        # concurrent stealer may be mid write-then-verify, so an expired
        # own record must go through the same verified steal, not a bare
        # refresh (two leaders otherwise).
        prev = rec.get("holder")
        self._write(holder)
        time.sleep(self.settle_s)
        cur = self._read() or {}
        won = cur.get("holder") == holder
        if won and prev:
            log.info("lease: %s took over expired lease from %s (file %s)",
                     holder, prev, self.path)
        return won

    def renew(self, holder: str) -> bool:
        rec = self._read()
        if (
            not rec
            or rec.get("holder") != holder
            or time.time() >= float(rec.get("expires_at", 0))
        ):
            return False
        self._write(holder)
        time.sleep(self.settle_s)
        cur = self._read() or {}
        return cur.get("holder") == holder

    def release(self, holder: str) -> None:
        rec = self._read()
        if rec and rec.get("holder") == holder:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass
