"""Virtual network-interface registry.

The reference resolves interface names via netlink
(/root/reference/pkg/interfaces/interfaces.go): validity = up and not
loopback (:24-35), name -> index (:53-60), and bond interfaces expand to
their member indices (:85-116).  On a TPU host the dataplane is fed packet
batches rather than NIC queues, so interfaces become a declarative registry
that the daemon configures; the resolution semantics (including bond
expansion and the "invalid interfaces are skipped, not errors" behavior)
are preserved exactly.

Like the reference's test seam (the package-level ``netInterfaces`` var,
interfaces.go:11-13 / ebpfsyncer.go:26), the registry lookup is a module
function that tests can monkeypatch.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional


class InterfaceError(RuntimeError):
    pass


@dataclass
class Interface:
    name: str
    index: int
    up: bool = True
    loopback: bool = False
    type: str = "device"          # "device" | "bond"
    master: Optional[str] = None  # bond master name for member links
    xdp_attached: bool = False    # mirrors netlink's Xdp.Attached flag


class InterfaceRegistry:
    """In-memory mirror of the host link table."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ifaces: Dict[str, Interface] = {}

    def add(self, iface: Interface) -> None:
        with self._lock:
            self._ifaces[iface.name] = iface

    def remove(self, name: str) -> None:
        with self._lock:
            self._ifaces.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._ifaces.clear()

    def get(self, name: str) -> Optional[Interface]:
        with self._lock:
            return self._ifaces.get(name)

    def list(self) -> List[Interface]:
        with self._lock:
            return list(self._ifaces.values())

    def is_valid_interface_name_and_state(self, name: str) -> bool:
        """IsValidInterfaceNameAndState (interfaces.go:24-35)."""
        iface = self.get(name)
        return iface is not None and iface.up and not iface.loopback

    def get_interface_index(self, name: str) -> int:
        """GetInterfaceIndex (interfaces.go:53-60)."""
        iface = self.get(name)
        if iface is None:
            raise InterfaceError(f"looking up network interface name {name!r}: not found")
        return iface.index

    def get_interface_indices(self, name: str) -> List[int]:
        """GetInterfaceIndices (interfaces.go:85-116): non-bond interfaces
        resolve to their own index; bonds resolve to all member indices."""
        iface = self.get(name)
        if iface is None:
            raise InterfaceError(f"link {name!r} not found")
        if iface.type != "bond":
            return [self.get_interface_index(name)]
        return [l.index for l in self.list() if l.master == name]

    def get_interfaces_with_xdp_attached(self) -> List[str]:
        """GetInterfacesWithXDPAttached (interfaces.go:38-50)."""
        return [l.name for l in self.list() if l.xdp_attached]

    def detach_xdp_from_all_interfaces(self) -> None:
        """DetachXDPFromAllInterfaces (interfaces.go:63-81)."""
        with self._lock:
            for iface in self._ifaces.values():
                iface.xdp_attached = False

    def set_xdp(self, name: str, attached: bool) -> None:
        iface = self.get(name)
        if iface is None:
            raise InterfaceError(f"link {name!r} not found")
        iface.xdp_attached = attached


# Process-global default registry, preloaded with a typical node NIC so the
# out-of-the-box experience matches a single-NIC node.
default_registry = InterfaceRegistry()
default_registry.add(Interface(name="eth0", index=2))


def is_valid_interface_name_and_state(name: str) -> bool:
    return default_registry.is_valid_interface_name_and_state(name)


def get_interface_index(name: str) -> int:
    return default_registry.get_interface_index(name)


def get_interface_indices(name: str) -> List[int]:
    return default_registry.get_interface_indices(name)
