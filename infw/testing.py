"""Test fixtures: adversarial ruleset and packet generators.

Replaces the reference's veth+netcat traffic harness
(/root/reference/pkg/ebpfsyncer/ebpfsyncer_test.go:1236-1318) with synthetic
rule tables and packet tensors; the reachability tables of that suite become
golden verdict vectors checked against the NumPy oracle.
"""
from __future__ import annotations

import ipaddress
from typing import Dict, List, Optional, Tuple

import numpy as np

from .compiler import LpmKey, compile_tables_from_content, CompiledTables
from .constants import (
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    MAX_RULES_PER_TARGET,
)
from .packets import PacketBatch

_PROTOS = [IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP, IPPROTO_ICMP, IPPROTO_ICMPV6, 0]


def random_rules(
    rng: np.random.Generator, width: int, max_rules: Optional[int] = None
) -> np.ndarray:
    """Random packed rule rows (width, 7) with the loader's invariants:
    index == order == ruleId, index 0 empty."""
    rows = np.zeros((width, 7), np.int32)
    n = rng.integers(0, max_rules if max_rules is not None else width - 1, endpoint=True)
    orders = rng.choice(np.arange(1, width), size=min(n, width - 1), replace=False)
    for order in orders:
        proto = _PROTOS[rng.integers(0, len(_PROTOS))]
        rows[order, 0] = order
        rows[order, 1] = proto
        if proto in (IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP):
            if rng.random() < 0.5:
                start = int(rng.integers(1, 65000))
                rows[order, 2] = start
                rows[order, 3] = int(rng.integers(start + 1, 65536))
            else:
                rows[order, 2] = int(rng.integers(1, 65536))
                rows[order, 3] = 0
        elif proto in (IPPROTO_ICMP, IPPROTO_ICMPV6):
            rows[order, 4] = int(rng.integers(0, 256))
            rows[order, 5] = int(rng.integers(0, 3))
        rows[order, 6] = int(rng.integers(1, 3))  # DENY or ALLOW
    return rows


def random_tables(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 16,
    v6_fraction: float = 0.3,
    overlap_fraction: float = 0.3,
) -> CompiledTables:
    """Random LPM content with deliberately overlapping prefixes (nested
    CIDRs of different lengths over shared bases) to stress longest-match
    tie-breaks."""
    content: Dict[LpmKey, np.ndarray] = {}
    bases: List[Tuple[bytes, bool]] = []
    while len(content) < n_entries:
        is_v6 = rng.random() < v6_fraction
        if bases and rng.random() < overlap_fraction:
            base, is_v6 = bases[rng.integers(0, len(bases))]
            data = bytearray(base)
            # perturb tail bytes to create nested/sibling prefixes
            pos = rng.integers(1, 16)
            data[pos] = rng.integers(0, 256)
            data = bytes(data)
        else:
            if is_v6:
                data = bytes(rng.integers(0, 256, 16, dtype=np.uint8))
            else:
                data = bytes(rng.integers(0, 256, 4, dtype=np.uint8)) + bytes(12)
            bases.append((data, is_v6))
        if is_v6:
            mask_len = int(rng.choice([0, 8, 13, 24, 32, 48, 64, 96, 128]))
        else:
            mask_len = int(rng.choice([0, 1, 8, 13, 16, 24, 30, 31, 32]))
            data = data[:4] + bytes(12)
        ifindex = int(ifindexes[rng.integers(0, len(ifindexes))])
        key = LpmKey(prefix_len=mask_len + 32, ingress_ifindex=ifindex, ip_data=data)
        content[key] = random_rules(rng, width)
    return compile_tables_from_content(content, rule_width=width)


def random_batch(
    rng: np.random.Generator,
    tables: CompiledTables,
    n_packets: int,
    ifindexes: Tuple[int, ...] = (2, 3, 9),
    hit_fraction: float = 0.7,
) -> PacketBatch:
    """Random packets biased toward table hits and match boundaries."""
    keys = list(tables.content.keys())
    b = n_packets
    kind = rng.choice([0, 1, 2, 3], size=b, p=[0.02, 0.55, 0.4, 0.03]).astype(np.int32)
    l4_ok = (rng.random(b) > 0.05).astype(np.int32)
    ifindex = np.array([ifindexes[i] for i in rng.integers(0, len(ifindexes), b)], np.int32)
    ip = np.zeros((b, 16), np.uint8)
    proto = np.zeros(b, np.int32)
    dst_port = np.zeros(b, np.int32)
    icmp_type = np.zeros(b, np.int32)
    icmp_code = np.zeros(b, np.int32)

    for i in range(b):
        if keys and rng.random() < hit_fraction:
            key = keys[rng.integers(0, len(keys))]
            data = bytearray(key.ip_data)
            if rng.random() < 0.5:
                # flip bits beyond the mask: should still match
                m = key.mask_len
                if m < 128:
                    bit = rng.integers(m, 128)
                    data[bit // 8] ^= 0x80 >> (bit % 8)
            else:
                # sometimes flip a bit inside the mask: should not match
                if key.mask_len > 0 and rng.random() < 0.3:
                    bit = rng.integers(0, key.mask_len)
                    data[bit // 8] ^= 0x80 >> (bit % 8)
            ip[i] = np.frombuffer(bytes(data), np.uint8)
            ifindex[i] = key.ingress_ifindex if rng.random() < 0.9 else ifindex[i]
            is_v4_key = all(d == 0 for d in data[4:]) and key.mask_len <= 32
            kind[i] = 1 if (is_v4_key and rng.random() < 0.8) else (2 if rng.random() < 0.8 else kind[i])
            # bias protocol/port toward a rule in that entry
            rows = tables.content[key]
            nz = np.nonzero(rows[:, 0])[0]
            if len(nz) and rng.random() < 0.8:
                r = rows[nz[rng.integers(0, len(nz))]]
                proto[i] = r[1] if r[1] != 0 else rng.integers(0, 255)
                if r[1] in (IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP):
                    if r[3] == 0:
                        dst_port[i] = r[2] + rng.integers(-1, 2)
                    else:
                        dst_port[i] = int(
                            rng.choice([r[2] - 1, r[2], r[3] - 1, r[3], r[3] + 1])
                        )
                    dst_port[i] = int(np.clip(dst_port[i], 0, 65535))
                elif r[1] in (IPPROTO_ICMP, IPPROTO_ICMPV6):
                    icmp_type[i] = r[4] + rng.integers(0, 2)
                    icmp_code[i] = r[5]
                continue
        # fully random packet
        ip[i] = rng.integers(0, 256, 16, dtype=np.uint8)
        if kind[i] == 1:
            ip[i, 4:] = 0
        proto[i] = int(rng.choice([6, 17, 132, 1, 58, 47, 0]))
        dst_port[i] = int(rng.integers(0, 65536))
        icmp_type[i] = int(rng.integers(0, 256))
        icmp_code[i] = int(rng.integers(0, 3))

    words = np.zeros((b, 4), np.uint32)
    for w in range(4):
        words[:, w] = (
            ip[:, 4 * w].astype(np.uint32) << 24
            | ip[:, 4 * w + 1].astype(np.uint32) << 16
            | ip[:, 4 * w + 2].astype(np.uint32) << 8
            | ip[:, 4 * w + 3].astype(np.uint32)
        )
    # v4 packets must have zero high words (host parser guarantees this)
    words[kind == 1, 1:] = 0
    return PacketBatch(
        kind=kind,
        l4_ok=l4_ok,
        ifindex=ifindex,
        ip_words=words,
        proto=proto,
        dst_port=dst_port,
        icmp_type=icmp_type,
        icmp_code=icmp_code,
        pkt_len=rng.integers(60, 1500, b).astype(np.int32),
    )


def stats_dict_from_array(stats4: np.ndarray) -> Dict[int, List[int]]:
    """(MAX_TARGETS, 4) int64 -> {ruleId: [ap, ab, dp, db]} with zero rows
    dropped, for comparison against the oracle's dict."""
    out: Dict[int, List[int]] = {}
    for rid in np.nonzero(stats4.any(axis=1))[0]:
        out[int(rid)] = [int(x) for x in stats4[rid]]
    return out
