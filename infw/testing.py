"""Test fixtures: adversarial ruleset and packet generators.

Replaces the reference's veth+netcat traffic harness
(/root/reference/pkg/ebpfsyncer/ebpfsyncer_test.go:1236-1318) with synthetic
rule tables and packet tensors; the reachability tables of that suite become
golden verdict vectors checked against the NumPy oracle.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .compiler import (
    CompiledTables,
    LpmKey,
    TableColumns,
    compile_tables_from_columns,
    compile_tables_from_content,
)
from .constants import (
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
)
from .packets import PacketBatch

_PROTOS = [IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP, IPPROTO_ICMP, IPPROTO_ICMPV6, 0]


def random_rules(
    rng: np.random.Generator, width: int, max_rules: Optional[int] = None
) -> np.ndarray:
    """Random packed rule rows (width, 7) with the loader's invariants:
    index == order == ruleId, index 0 empty."""
    rows = np.zeros((width, 7), np.int32)
    n = rng.integers(0, max_rules if max_rules is not None else width - 1, endpoint=True)
    orders = rng.choice(np.arange(1, width), size=min(n, width - 1), replace=False)
    for order in orders:
        proto = _PROTOS[rng.integers(0, len(_PROTOS))]
        rows[order, 0] = order
        rows[order, 1] = proto
        if proto in (IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP):
            if rng.random() < 0.5:
                start = int(rng.integers(1, 65000))
                rows[order, 2] = start
                rows[order, 3] = int(rng.integers(start + 1, 65536))
            else:
                rows[order, 2] = int(rng.integers(1, 65536))
                rows[order, 3] = 0
        elif proto in (IPPROTO_ICMP, IPPROTO_ICMPV6):
            rows[order, 4] = int(rng.integers(0, 256))
            rows[order, 5] = int(rng.integers(0, 3))
        rows[order, 6] = int(rng.integers(1, 3))  # DENY or ALLOW
    return rows


def random_tables(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 16,
    v6_fraction: float = 0.3,
    overlap_fraction: float = 0.3,
) -> CompiledTables:
    """Random LPM content with deliberately overlapping prefixes (nested
    CIDRs of different lengths over shared bases) to stress longest-match
    tie-breaks."""
    content: Dict[LpmKey, np.ndarray] = {}
    bases: List[Tuple[bytes, bool]] = []
    while len(content) < n_entries:
        is_v6 = rng.random() < v6_fraction
        if bases and rng.random() < overlap_fraction:
            base, is_v6 = bases[rng.integers(0, len(bases))]
            data = bytearray(base)
            # perturb tail bytes to create nested/sibling prefixes
            pos = rng.integers(1, 16)
            data[pos] = rng.integers(0, 256)
            data = bytes(data)
        else:
            if is_v6:
                data = bytes(rng.integers(0, 256, 16, dtype=np.uint8))
            else:
                data = bytes(rng.integers(0, 256, 4, dtype=np.uint8)) + bytes(12)
            bases.append((data, is_v6))
        if is_v6:
            mask_len = int(rng.choice([0, 8, 13, 24, 32, 48, 64, 96, 128]))
        else:
            mask_len = int(rng.choice([0, 1, 8, 13, 16, 24, 30, 31, 32]))
            data = data[:4] + bytes(12)
        ifindex = int(ifindexes[rng.integers(0, len(ifindexes))])
        key = LpmKey(prefix_len=mask_len + 32, ingress_ifindex=ifindex, ip_data=data)
        content[key] = random_rules(rng, width)
    return compile_tables_from_content(content, rule_width=width)


def random_batch(
    rng: np.random.Generator,
    tables: CompiledTables,
    n_packets: int,
    ifindexes: Tuple[int, ...] = (2, 3, 9),
    hit_fraction: float = 0.7,
) -> PacketBatch:
    """Random packets biased toward table hits and match boundaries."""
    keys = list(tables.content.keys())
    b = n_packets
    kind = rng.choice([0, 1, 2, 3], size=b, p=[0.02, 0.55, 0.4, 0.03]).astype(np.int32)
    l4_ok = (rng.random(b) > 0.05).astype(np.int32)
    ifindex = np.array([ifindexes[i] for i in rng.integers(0, len(ifindexes), b)], np.int32)
    ip = np.zeros((b, 16), np.uint8)
    proto = np.zeros(b, np.int32)
    dst_port = np.zeros(b, np.int32)
    icmp_type = np.zeros(b, np.int32)
    icmp_code = np.zeros(b, np.int32)

    for i in range(b):
        if keys and rng.random() < hit_fraction:
            key = keys[rng.integers(0, len(keys))]
            data = bytearray(key.ip_data)
            if rng.random() < 0.5:
                # flip bits beyond the mask: should still match
                m = key.mask_len
                if m < 128:
                    bit = rng.integers(m, 128)
                    data[bit // 8] ^= 0x80 >> (bit % 8)
            else:
                # sometimes flip a bit inside the mask: should not match
                if key.mask_len > 0 and rng.random() < 0.3:
                    bit = rng.integers(0, key.mask_len)
                    data[bit // 8] ^= 0x80 >> (bit % 8)
            ip[i] = np.frombuffer(bytes(data), np.uint8)
            ifindex[i] = key.ingress_ifindex if rng.random() < 0.9 else ifindex[i]
            is_v4_key = all(d == 0 for d in data[4:]) and key.mask_len <= 32
            kind[i] = 1 if (is_v4_key and rng.random() < 0.8) else (2 if rng.random() < 0.8 else kind[i])
            # bias protocol/port toward a rule in that entry
            rows = tables.content[key]
            nz = np.nonzero(rows[:, 0])[0]
            if len(nz) and rng.random() < 0.8:
                r = rows[nz[rng.integers(0, len(nz))]]
                proto[i] = r[1] if r[1] != 0 else rng.integers(0, 255)
                if r[1] in (IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP):
                    if r[3] == 0:
                        dst_port[i] = r[2] + rng.integers(-1, 2)
                    else:
                        dst_port[i] = int(
                            rng.choice([r[2] - 1, r[2], r[3] - 1, r[3], r[3] + 1])
                        )
                    dst_port[i] = int(np.clip(dst_port[i], 0, 65535))
                elif r[1] in (IPPROTO_ICMP, IPPROTO_ICMPV6):
                    icmp_type[i] = r[4] + rng.integers(0, 2)
                    icmp_code[i] = r[5]
                continue
        # fully random packet
        ip[i] = rng.integers(0, 256, 16, dtype=np.uint8)
        if kind[i] == 1:
            ip[i, 4:] = 0
        proto[i] = int(rng.choice([6, 17, 132, 1, 58, 47, 0]))
        dst_port[i] = int(rng.integers(0, 65536))
        icmp_type[i] = int(rng.integers(0, 256))
        icmp_code[i] = int(rng.integers(0, 3))

    words = np.zeros((b, 4), np.uint32)
    for w in range(4):
        words[:, w] = (
            ip[:, 4 * w].astype(np.uint32) << 24
            | ip[:, 4 * w + 1].astype(np.uint32) << 16
            | ip[:, 4 * w + 2].astype(np.uint32) << 8
            | ip[:, 4 * w + 3].astype(np.uint32)
        )
    # v4 packets must have zero high words (host parser guarantees this)
    words[kind == 1, 1:] = 0
    return PacketBatch(
        kind=kind,
        l4_ok=l4_ok,
        ifindex=ifindex,
        ip_words=words,
        proto=proto,
        dst_port=dst_port,
        icmp_type=icmp_type,
        icmp_code=icmp_code,
        pkt_len=rng.integers(60, 1500, b).astype(np.int32),
    )


def random_tables_fast(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 16,
    v6_fraction: float = 0.3,
    group_size: int = 8,
) -> CompiledTables:
    """Vectorized large-table generator: like random_tables but NumPy-
    vectorized end to end so 100K-1M-entry tables build in seconds (the
    scale tier of BASELINE config 3/5).  Entries cluster into groups
    sharing a base address with realistic prefix-length mixes (v4 peaked
    at /24, v6 at /48), so nested/sibling prefixes stress longest-match
    tie-breaks exactly like the per-entry generator."""
    content: Dict[LpmKey, np.ndarray] = {}
    seen = set()
    while len(content) < n_entries:
        n = int((n_entries - len(content)) * 1.4) + 64
        is_v6 = rng.random(n) < v6_fraction
        n_groups = max(1, n // group_size)
        bases = rng.integers(0, 256, (n_groups, 16), dtype=np.uint8)
        gid = rng.integers(0, n_groups, n)
        ip = bases[gid].copy()
        # sibling prefixes: perturb one tail byte on half the entries
        perturb = rng.random(n) < 0.5
        pos = rng.integers(1, 16, n)
        val = rng.integers(0, 256, n, dtype=np.uint8)
        rows_i = np.arange(n)[perturb]
        ip[rows_i, pos[perturb]] = val[perturb]

        v4_lens = np.array([0, 8, 12, 16, 20, 24, 24, 24, 28, 32])
        v6_lens = np.array([0, 32, 40, 48, 48, 48, 56, 64, 96, 128])
        mask_len = np.where(
            is_v6,
            v6_lens[rng.integers(0, len(v6_lens), n)],
            v4_lens[rng.integers(0, len(v4_lens), n)],
        ).astype(np.int64)
        ip[~is_v6, 4:] = 0
        ifindex = np.asarray(ifindexes)[rng.integers(0, len(ifindexes), n)]

        rules = random_rules_bulk(rng, n, width)

        ip_bytes = [bytes(row) for row in ip]
        for i in range(n):
            # exact masked-identity dedupe so the final entry count is
            # exactly n_entries (from_content would collapse aliases)
            m = int(mask_len[i])
            nb, rem = m // 8, m % 8
            data = ip_bytes[i][:nb]
            if rem:
                data += bytes([ip_bytes[i][nb] & ((0xFF << (8 - rem)) & 0xFF)])
            ident = (int(ifindex[i]), m, data)
            if ident in seen:
                continue
            seen.add(ident)
            key = LpmKey(
                prefix_len=int(mask_len[i]) + 32,
                ingress_ifindex=int(ifindex[i]),
                ip_data=ip_bytes[i],
            )
            content[key] = rules[i]
            if len(content) >= n_entries:
                break
    return compile_tables_from_content(content, rule_width=width)


def clean_tables_fast(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 4,
    v6_fraction: float = 0.3,
) -> CompiledTables:
    """Semantically CLEAN large-table generator: the scale of
    random_tables_fast with none of its (deliberate) semantic hazards —
    non-nested prefixes (distinct v4 /24s and v6 /48s, so no entry can
    be LPM-dead or conflict with an ancestor) carrying one distinct
    Allow rule each (no shadowing, no redundancy, no failsafe Deny).
    The static analyzer (infw.analysis.rules) must report ZERO findings
    on these tables at any size — the negative control of its property
    suite, and a clean substrate for future adversarial injections."""
    n_v6 = int(n_entries * v6_fraction)
    n_v4 = n_entries - n_v6
    if n_v4 > 1 << 24 or n_v6 > 1 << 40:
        raise ValueError("n_entries exceeds the disjoint-prefix space")
    content: Dict[LpmKey, np.ndarray] = {}
    v4_vals = rng.choice(1 << 24, size=n_v4, replace=False).astype(np.int64)
    # distinct 40-bit v6 prefixes without materializing the space:
    # random 64-bit draws deduped, topped up on collision
    v6_vals = np.unique(rng.integers(0, 1 << 40, n_v6 + 64, dtype=np.int64))
    while len(v6_vals) < n_v6:
        v6_vals = np.unique(np.concatenate([
            v6_vals, rng.integers(0, 1 << 40, n_v6, dtype=np.int64)
        ]))
    v6_vals = v6_vals[:n_v6]
    ifx = np.asarray(ifindexes)[rng.integers(0, len(ifindexes), n_entries)]
    ports = 70 + (np.arange(n_entries) % 60000)
    i = 0
    for v in v4_vals:
        data = int(v << 8).to_bytes(4, "big") + bytes(12)
        rows = np.zeros((width, 7), np.int32)
        rows[1] = [1, IPPROTO_TCP, ports[i], 0, 0, 0, 2]  # ALLOW
        content[LpmKey(24 + 32, int(ifx[i]), data)] = rows
        i += 1
    for v in v6_vals:
        data = (0x20 << 120 | int(v) << 80).to_bytes(16, "big")
        rows = np.zeros((width, 7), np.int32)
        rows[1] = [1, IPPROTO_TCP, ports[i], 0, 0, 0, 2]  # ALLOW
        content[LpmKey(48 + 32, int(ifx[i]), data)] = rows
        i += 1
    return compile_tables_from_content(content, rule_width=width)


def clean_columns_fast(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 4,
    v6_fraction: float = 0.3,
) -> TableColumns:
    """clean_tables_fast as COLUMNS: the same disjoint /24+/48 Allow-only
    distribution with zero per-key Python — the generator of the 10M
    bench/test tier, where even a C-level dict build costs real seconds.
    ``compile_tables_from_columns(clean_columns_fast(...))`` is the
    whole cold-build path."""
    n_v6 = int(n_entries * v6_fraction)
    n_v4 = n_entries - n_v6
    if n_v4 > 1 << 24 or n_v6 > 1 << 40:
        raise ValueError("n_entries exceeds the disjoint-prefix space")
    v4_vals = rng.choice(1 << 24, size=n_v4, replace=False).astype(np.int64)
    v6_vals = np.unique(rng.integers(0, 1 << 40, n_v6 + 64, dtype=np.int64))
    while len(v6_vals) < n_v6:
        v6_vals = np.unique(np.concatenate([
            v6_vals, rng.integers(0, 1 << 40, n_v6, dtype=np.int64)
        ]))
    v6_vals = v6_vals[:n_v6]
    ifx = np.asarray(ifindexes, np.int64)[
        rng.integers(0, len(ifindexes), n_entries)
    ]
    ip = np.zeros((n_entries, 16), np.uint8)
    # v4 /24: value << 8 as the first 4 big-endian bytes
    v4_words = (v4_vals << 8).astype(">u4")
    ip[:n_v4, :4] = v4_words.view(np.uint8).reshape(n_v4, 4)
    # v6 /48: 0x20 byte + 40-bit value in bytes 1..5
    v6_hi = (np.int64(0x20) << 40) | v6_vals
    v6_bytes = v6_hi.astype(">u8").view(np.uint8).reshape(n_v6, 8)
    ip[n_v4:, :6] = v6_bytes[:, 2:]
    plen = np.empty(n_entries, np.int32)
    plen[:n_v4] = 24 + 32
    plen[n_v4:] = 48 + 32
    ports = 70 + (np.arange(n_entries) % 60000)
    rules = np.zeros((n_entries, width, 7), np.int32)
    rules[:, 1, 0] = 1
    rules[:, 1, 1] = IPPROTO_TCP
    rules[:, 1, 2] = ports
    rules[:, 1, 6] = 2  # ALLOW
    return TableColumns(prefix_len=plen, ifindex=ifx, ip=ip, rules=rules)


def clean_tables_scale(
    rng: np.random.Generator,
    n_entries: int,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 4,
    v6_fraction: float = 0.3,
) -> CompiledTables:
    """clean_columns_fast through the vectorized compiler — the 10M-tier
    analogue of clean_tables_fast (same distribution family; the
    per-entry port sequence differs only in assignment order)."""
    return compile_tables_from_columns(
        clean_columns_fast(rng, n_entries, ifindexes, width, v6_fraction),
        rule_width=width,
    )


def gate_tripped_tables(
    rng: np.random.Generator,
    n_entries: int = 48,
    ifindexes: Tuple[int, ...] = (2, 3),
    width: int = 4,
) -> CompiledTables:
    """Tables whose joined-targets layout trips the duplication gate
    (jaxpath.JOINED_DUP_LIMIT / the 4096-row floor), so the device state
    keeps the INACTIVE ``(1, 1)`` joined placeholder on the trie path.

    Mid-stride prefixes (/17 under distinct /16 bases) leaf-push into
    2^(24-17) = 128 slots each, so ~40 entries already duplicate to
    >4096 joined positions — the exact layout regime of the PR-4
    placeholder bucket-padding bug, and the substrate of the state
    checker's injected-defect acceptance gate."""
    content: Dict[LpmKey, np.ndarray] = {}
    for i in range(n_entries):
        mask = 17 if i % 4 != 3 else 24  # mostly /17, some /24 siblings
        data = bytes([10, i % 256, (i // 256) % 2 * 128, 0]) + bytes(12)
        rows = np.zeros((width, 7), np.int32)
        rows[1] = [1, IPPROTO_TCP, 70 + (i % 60000), 0, 0, 0, 1 + i % 2]
        ifx = int(ifindexes[i % len(ifindexes)])
        content[LpmKey(mask + 32, ifx, data)] = rows
    return compile_tables_from_content(content, rule_width=width)


def random_rules_bulk(
    rng: np.random.Generator, n: int, width: int
) -> np.ndarray:
    """(n, width, 7) packed rule rows, vectorized version of random_rules:
    index == order == ruleId, index 0 empty, mixed protocols, half port
    ranges / half single ports, DENY or ALLOW actions."""
    rows = np.zeros((n, width, 7), np.int32)
    if width < 2:
        return rows
    # per-entry fill probability in [0.3, 1.0] so table density varies
    fill_p = rng.uniform(0.3, 1.0, (n, 1))
    populated = rng.random((n, width)) < fill_p
    populated[:, 0] = False  # order 0 reserved (catch-all slot semantics)
    order = np.broadcast_to(np.arange(width, dtype=np.int32), (n, width))
    proto = np.asarray(_PROTOS)[rng.integers(0, len(_PROTOS), (n, width))]
    is_transport = (
        (proto == IPPROTO_TCP) | (proto == IPPROTO_UDP) | (proto == IPPROTO_SCTP)
    )
    is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
    start = rng.integers(1, 65000, (n, width))
    use_range = rng.random((n, width)) < 0.5
    span = rng.integers(1, 500, (n, width))
    end = np.where(use_range, np.minimum(start + span, 65535), 0)
    rows[..., 0] = np.where(populated, order, 0)
    rows[..., 1] = np.where(populated, proto, 0)
    rows[..., 2] = np.where(populated & is_transport, start, 0)
    rows[..., 3] = np.where(populated & is_transport, end, 0)
    rows[..., 4] = np.where(populated & is_icmp, rng.integers(0, 256, (n, width)), 0)
    rows[..., 5] = np.where(populated & is_icmp, rng.integers(0, 3, (n, width)), 0)
    rows[..., 6] = np.where(populated, rng.integers(1, 3, (n, width)), 0)
    return rows


def random_batch_fast(
    rng: np.random.Generator,
    tables: CompiledTables,
    n_packets: int,
    extra_ifindexes: Tuple[int, ...] = (9,),
    hit_fraction: float = 0.7,
) -> PacketBatch:
    """Vectorized version of random_batch: packets biased toward table
    hits (address sampled from a random entry, bits flipped beyond — or
    occasionally inside — the mask) and toward rule-match boundaries
    (protocol/port copied from a random populated rule of that entry).
    Generates 10M-packet batches in seconds for the replay tier."""
    b = n_packets
    T = int(tables.num_entries)
    kind = rng.choice([0, 1, 2, 3], size=b, p=[0.02, 0.55, 0.4, 0.03]).astype(np.int32)
    l4_ok = (rng.random(b) > 0.05).astype(np.int32)
    all_if = np.unique(
        np.concatenate([tables.key_words[:T, 0].astype(np.int64),
                        np.asarray(extra_ifindexes, np.int64)])
    )
    ifindex = all_if[rng.integers(0, len(all_if), b)].astype(np.int32)
    # random baseline
    ip = rng.integers(0, 256, (b, 16), dtype=np.uint8)
    proto = np.asarray([6, 17, 132, 1, 58, 47, 0])[rng.integers(0, 7, b)].astype(np.int32)
    dst_port = rng.integers(0, 65536, b).astype(np.int32)
    icmp_type = rng.integers(0, 256, b).astype(np.int32)
    icmp_code = rng.integers(0, 3, b).astype(np.int32)

    hit = rng.random(b) < (hit_fraction if T else 0.0)
    if T:
        e = rng.integers(0, T, b)
        # entry address bytes from the dense key words (big-endian words)
        ent_ip = (
            tables.key_words[:T, 1:5].astype(">u4").copy().view(np.uint8).reshape(T, 16)
        )
        ent_mask = tables.mask_len[:T].astype(np.int64)
        ent_if = tables.key_words[:T, 0].astype(np.int32)
        m = ent_mask[e]
        hip = ent_ip[e].copy()
        # flip a bit beyond the mask (still matches) or, 30% of the time
        # when flippable, inside the mask (usually breaks the match)
        beyond_ok = m < 128
        bit_beyond = (m + (rng.integers(0, 1 << 16, b) % np.maximum(128 - m, 1)))
        inside = (rng.random(b) < 0.3) & (m > 0)
        bit_inside = rng.integers(0, 1 << 16, b) % np.maximum(m, 1)
        bit = np.where(inside, bit_inside, np.where(beyond_ok, bit_beyond, 0))
        do_flip = beyond_ok | inside
        byte_i, mask_v = (bit // 8).astype(np.int64), (0x80 >> (bit % 8)).astype(np.uint8)
        sel = np.where(hit & do_flip)[0]
        hip[sel, byte_i[sel]] ^= mask_v[sel]
        ip[hit] = hip[hit]
        ifindex = np.where(hit & (rng.random(b) < 0.9), ent_if[e], ifindex)
        is_v4_key = (ent_mask[e] <= 32) & ~np.any(hip[:, 4:] != 0, axis=1)
        kind = np.where(
            hit & is_v4_key & (rng.random(b) < 0.8), 1,
            np.where(hit & ~is_v4_key & (rng.random(b) < 0.8), 2, kind),
        ).astype(np.int32)
        # bias protocol/port toward a random populated rule of the entry
        R = tables.rules.shape[1]
        ridx = rng.integers(0, R, b)
        rule = tables.rules[np.clip(e, 0, T - 1), ridx]  # (b, 7)
        has_rule = rule[:, 0] != 0
        use_rule = hit & has_rule & (rng.random(b) < 0.8)
        rproto = rule[:, 1]
        proto = np.where(use_rule & (rproto != 0), rproto, proto)
        is_tr = (rproto == IPPROTO_TCP) | (rproto == IPPROTO_UDP) | (rproto == IPPROTO_SCTP)
        jitter = rng.integers(-1, 2, b)
        port_single = np.clip(rule[:, 2] + jitter, 0, 65535)
        edge = np.stack([
            rule[:, 2] - 1, rule[:, 2], rule[:, 3] - 1, rule[:, 3], rule[:, 3] + 1
        ], 1)[np.arange(b), rng.integers(0, 5, b)]
        port_range = np.clip(edge, 0, 65535)
        dst_port = np.where(
            use_rule & is_tr,
            np.where(rule[:, 3] == 0, port_single, port_range),
            dst_port,
        ).astype(np.int32)
        is_ic = (rproto == IPPROTO_ICMP) | (rproto == IPPROTO_ICMPV6)
        icmp_type = np.where(
            use_rule & is_ic, rule[:, 4] + rng.integers(0, 2, b), icmp_type
        ).astype(np.int32)
        icmp_code = np.where(use_rule & is_ic, rule[:, 5], icmp_code).astype(np.int32)

    ip[kind == 1, 4:] = 0
    words = np.ascontiguousarray(ip).view(">u4").astype(np.uint32).reshape(b, 4)
    return PacketBatch(
        kind=kind,
        l4_ok=l4_ok,
        ifindex=ifindex,
        ip_words=words,
        proto=proto,
        dst_port=dst_port.astype(np.int32),
        icmp_type=icmp_type,
        icmp_code=icmp_code,
        pkt_len=rng.integers(60, 1500, b).astype(np.int32),
    )


# --- arrival processes (open-loop load generation) --------------------------
#
# The SLO serving tier (infw.scheduler, bench_slo, tools/loadgen.py)
# measures tail latency OPEN-LOOP: packet i is declared to arrive at a
# scheduled offset regardless of how the system is keeping up, and its
# latency is measured from that schedule — the coordinated-omission-safe
# methodology (a closed-loop generator that waits for completions before
# sending more silently excludes exactly the queueing it caused).


def poisson_arrivals(
    rng: np.random.Generator, rate_pps: float, n: int
) -> np.ndarray:
    """(n,) float64 cumulative arrival offsets (seconds) of a Poisson
    process at ``rate_pps`` — exponential inter-arrivals, deterministic
    per (seeded rng, rate, n)."""
    if rate_pps <= 0:
        raise ValueError(f"rate must be positive, got {rate_pps}")
    gaps = rng.exponential(1.0 / float(rate_pps), int(n))
    return np.cumsum(gaps)


def burst_arrivals(
    rng: np.random.Generator, rate_pps: float, n: int, burst: int = 64
) -> np.ndarray:
    """(n,) float64 arrival offsets of a bursty process at the SAME mean
    rate as the Poisson generator: packets arrive in back-to-back groups
    of ``burst`` with exponentially distributed gaps BETWEEN bursts
    (mean burst/rate) — the adversarial arrival shape for a coalescing
    scheduler (a whole burst lands on one admission decision)."""
    if rate_pps <= 0:
        raise ValueError(f"rate must be positive, got {rate_pps}")
    burst = max(1, int(burst))
    n = int(n)
    n_bursts = -(-n // burst)
    gaps = rng.exponential(burst / float(rate_pps), n_bursts)
    starts = np.cumsum(gaps)
    return np.repeat(starts, burst)[:n]


def stats_dict_from_array(stats4: np.ndarray) -> Dict[int, List[int]]:
    """(MAX_TARGETS, 4) int64 -> {ruleId: [ap, ab, dp, db]} with zero rows
    dropped, for comparison against the oracle's dict."""
    out: Dict[int, List[int]] = {}
    for rid in np.nonzero(stats4.any(axis=1))[0]:
        out[int(rid)] = [int(x) for x in stats4[rid]]
    return out


# --- flow-locality traffic (the stateful flow tier's workload) ---------------


def flow_locality_fids(
    rng: np.random.Generator, n: int, established_fraction: float,
    chunk_packets: int = 1024,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """The chunk-aware flow-id assignment under flow_trace_batch (and
    tools/loadgen.py's established-fraction mode): returns (fid, fresh,
    n_flows) where ``fresh`` marks first occurrences and repeats only
    reference flows born in EARLIER chunks — so a verdict cache that
    inserts at chunk boundaries sees exactly ~established_fraction hits
    per steady-state chunk (chunk 0 is the all-fresh warmup)."""
    n = int(n)
    e = float(established_fraction)
    if not 0.0 <= e < 1.0:
        raise ValueError(
            f"established_fraction must be in [0, 1), got {e}"
        )
    cp = max(int(chunk_packets), 1)
    chunk = np.arange(n) // cp
    chunk_starts = np.arange(0, n, cp)
    fresh = (rng.random(n) >= e) | (chunk == 0)
    seen = np.cumsum(fresh)            # flows born through packet i
    # flows born BEFORE each packet's chunk (the repeat-eligible pool)
    born_before = np.concatenate(
        [[0], seen[chunk_starts[1:] - 1]]
    )[chunk]
    fresh = fresh | (born_before == 0)
    seen = np.cumsum(fresh)
    born_before = np.concatenate(
        [[0], seen[chunk_starts[1:] - 1]]
    )[chunk]
    pick = rng.random(n)
    fid = np.where(
        fresh, seen - 1,
        (pick * np.maximum(born_before, 1)).astype(np.int64),
    ).astype(np.int64)
    return fid, fresh, int(seen[-1])


def flow_trace_batch(
    rng: np.random.Generator,
    tables: CompiledTables,
    n_packets: int,
    established_fraction: float,
    chunk_packets: int = 1024,
    fin_fraction: float = 0.05,
) -> Tuple[PacketBatch, Dict[str, int]]:
    """Seeded packet stream with controlled flow locality — the workload
    of the flow tier's hit-rate ladder (bench_flow, tools/loadgen.py).

    ``established_fraction`` (e) is the per-chunk fraction of packets
    that repeat a flow born in an EARLIER chunk of ``chunk_packets``
    packets — chunk-aware on purpose: a verdict cache inserts a chunk's
    fresh flows only after that chunk's dispatch, so intra-chunk repeats
    of newborn flows can never hit and would silently dilute the ladder.
    Chunk 0 is the all-fresh warmup; every later chunk carries exactly
    ~e established traffic (TCP flows whose first packet is a pure SYN
    pay one extra miss each — the NEW -> EST handshake gate; the bench
    reports measured hit rates next to the nominal rungs).

    Flow definitions draw from random_batch_fast over ``tables`` (hit-
    biased addresses/rules), repaired to classification-eligible lanes
    (real IP kinds, l4_ok=1) so the locality knob is exact.  TCP flags:
    SYN on a TCP flow's first packet, ACK mid-stream, FIN|ACK on its
    last packet for ``fin_fraction`` of flows.  pkt_len varies per
    packet (it feeds statistics, never the flow key).  Byte-
    deterministic per (seeded rng, arguments).

    Returns (batch, meta) with meta = {"n_flows", "repeats"}."""
    n = int(n_packets)
    fid, fresh, n_flows = flow_locality_fids(
        rng, n, established_fraction, chunk_packets
    )
    pool = random_batch_fast(rng, tables, n_flows)
    # repair to eligible lanes: the locality knob must be exact
    kind = np.asarray(pool.kind)
    kind = np.where((kind == 1) | (kind == 2), kind, 1).astype(np.int32)
    v4 = kind == 1
    ipw = np.asarray(pool.ip_words).copy()
    ipw[v4, 1:] = 0
    batch = PacketBatch(
        kind=kind[fid],
        l4_ok=np.ones(n, np.int32),
        ifindex=np.asarray(pool.ifindex)[fid],
        ip_words=ipw[fid],
        proto=np.asarray(pool.proto)[fid],
        dst_port=np.asarray(pool.dst_port)[fid],
        icmp_type=np.asarray(pool.icmp_type)[fid],
        icmp_code=np.asarray(pool.icmp_code)[fid],
        pkt_len=rng.integers(60, 1500, n).astype(np.int32),
    )
    # TCP state arcs: SYN opens, ACK carries, FIN|ACK closes (sampled)
    from .kernels.jaxpath import TCP_ACK, TCP_FIN, TCP_SYN

    is_tcp = batch.proto == 6
    flags = np.where(is_tcp, TCP_ACK, 0).astype(np.int32)
    flags[fresh & is_tcp] = TCP_SYN
    last = np.zeros(n_flows, np.int64)
    np.maximum.at(last, fid, np.arange(n, dtype=np.int64))
    closing = last[rng.random(n_flows) < fin_fraction]
    closing = closing[is_tcp[closing]]
    flags[closing] = TCP_FIN | TCP_ACK
    batch.tcp_flags = flags
    return batch, {"n_flows": n_flows, "repeats": int(n - n_flows)}


# --- adversarial attack traces (the telemetry tier's workload) ---------------

ATTACK_MODES = ("synflood", "portscan", "denystorm")


def attack_trace_batch(
    rng: np.random.Generator,
    tables: CompiledTables,
    n_packets: int,
    mode: str = "synflood",
    attack_fraction: float = 0.4,
    attack_start: float = 0.25,
    chunk_packets: int = 1024,
    n_attackers: int = 2,
) -> Tuple[PacketBatch, Dict[str, object]]:
    """Seeded adversarial traffic mix for the telemetry tier
    (bench_telemetry, tools/loadgen.py --attack): background traffic
    with flow locality (flow_trace_batch at 50% established — the
    flow_locality_fids arcs) carrying an injected attack that begins at
    ``attack_start`` of the stream (rounded down to a chunk boundary)
    and claims ``attack_fraction`` of the lanes from then on.

    Modes:
    - ``synflood``: ``n_attackers`` v4 sources blast pure-SYN TCP at one
      port — the SYN-rate summary's surface (and the flow tier's NEW
      gate: these never enter the fast path);
    - ``portscan``: ONE v4 source sweeps dst ports sequentially — a
      top-talker with maximal key dispersion below the src;
    - ``denystorm``: attackers replay packets the ORACLE says this
      ruleset denies (sampled from a table-biased pool), driving the
      per-tenant deny fraction over the storm threshold.

    Byte-deterministic per (seeded rng, arguments).  Returns (batch,
    meta) with meta = {"mode", "start", "n_attack", "attackers":
    [(ip_words row, kind)], "attack_mask"}."""
    if mode not in ATTACK_MODES:
        raise ValueError(
            f"unknown attack mode {mode!r} (expected one of {ATTACK_MODES})"
        )
    n = int(n_packets)
    batch, meta = flow_trace_batch(
        rng, tables, n, 0.5, chunk_packets=chunk_packets
    )
    from .kernels.jaxpath import TCP_ACK, TCP_SYN

    cp = max(int(chunk_packets), 1)
    start = (int(n * float(attack_start)) // cp) * cp
    mask = (np.arange(n) >= start) & (rng.random(n) < float(attack_fraction))
    k = int(mask.sum())
    flags = np.asarray(batch.tcp_flags, np.int32)
    attackers: List[Tuple[np.ndarray, int]] = []
    if mode in ("synflood", "portscan"):
        n_src = 1 if mode == "portscan" else max(1, int(n_attackers))
        srcs = np.zeros((n_src, 4), np.uint32)
        srcs[:, 0] = rng.integers(1, 1 << 32, n_src, dtype=np.uint64)
        lane_src = np.arange(k) % n_src
        batch.kind[mask] = 1
        batch.l4_ok[mask] = 1
        batch.ip_words[mask] = srcs[lane_src]
        batch.proto[mask] = IPPROTO_TCP
        batch.icmp_type[mask] = 0
        batch.icmp_code[mask] = 0
        if mode == "synflood":
            batch.dst_port[mask] = 443
            flags[mask] = TCP_SYN  # pure SYN, never promotes
        else:
            batch.dst_port[mask] = np.arange(k) % 65536
            flags[mask] = TCP_ACK
        attackers = [(srcs[i].copy(), 1) for i in range(n_src)]
    else:  # denystorm: oracle-confirmed deny lanes, replayed verbatim
        from . import oracle

        pool = random_batch_fast(rng, tables, max(4 * n_attackers, 256))
        ref = oracle.classify(tables, pool)
        deny = np.nonzero((ref.results & 0xFF) == 1)[0]
        if len(deny) == 0:
            raise ValueError(
                "denystorm needs at least one oracle-DENY lane in the "
                "table-biased pool; got none (all-allow ruleset?)"
            )
        picks = deny[: max(1, int(n_attackers))]
        lane_src = np.arange(k) % len(picks)
        for field in ("kind", "l4_ok", "ifindex", "ip_words", "proto",
                      "dst_port", "icmp_type", "icmp_code"):
            getattr(batch, field)[mask] = np.asarray(
                getattr(pool, field)
            )[picks][lane_src]
        flags[mask] = np.where(
            np.asarray(pool.proto)[picks][lane_src] == IPPROTO_TCP,
            TCP_ACK, 0,
        )
        attackers = [
            (np.asarray(pool.ip_words)[i].copy(),
             int(np.asarray(pool.kind)[i])) for i in picks
        ]
    batch.tcp_flags = flags
    return batch, {
        "mode": mode, "start": int(start), "n_attack": k,
        "attackers": attackers, "attack_mask": mask,
        "n_flows": meta["n_flows"],
    }
