"""Create-or-update apply helper.

Equivalent of the reference's pkg/apply
(/root/reference/pkg/apply/apply.go:36-58): create the object if absent,
otherwise update it when semantically different, preserving the stored
status subresource.
"""
from __future__ import annotations

from .spec import semantic_equal
from .store import InMemoryStore, NotFoundError


def apply_object(store: InMemoryStore, obj) -> object:
    """ApplyObject (apply.go:36)."""
    try:
        existing = store.get(obj.KIND, obj.metadata.name, obj.metadata.namespace)
    except NotFoundError:
        return store.create(obj)
    same = (
        semantic_equal(existing.spec, obj.spec)
        and existing.metadata.labels == obj.metadata.labels
        and [o.to_dict() for o in existing.metadata.owner_references]
        == [o.to_dict() for o in obj.metadata.owner_references]
    )
    return existing if same else store.update(obj)
