"""Anomaly-scoring policy tier: shadow/enforce mitigation over the MXU
inference kernels (ISSUE-14).

The control-plane half of kernels.mxu_score: ``AnomalyTier`` owns the
donated device ScoreState, the model value operands (hot-swapped whole,
never recompiled) and the per-tenant [threshold, mode] policy rows, and
drives scoring on BOTH serving paths — the donated exchange the
resident fused step chains through (jaxpath.jitted_resident_step(score=
spec)) and the one-follow-on-launch-per-admission form on the
multi-dispatch wire path (the telemetry wiring shape, ISSUE-13).

Policy semantics:

- **shadow** (default): scores and per-tenant counters only — verdicts
  are never touched; ``anomaly-verdict`` summary records ride the obs
  event ring at the decimated drain cadence.
- **enforce**: a lane over its tenant's threshold is rewritten to Deny
  (ruleId 0) — but NEVER a failsafe cell (kernels.mxu_score.failsafe_
  lane_mask_np, the same infw.failsaferules port list the
  analysis/rules.py coverage proof checks) and never an existing rule
  Deny.  On the flow paths the ENFORCED verdict is what batch-inserts
  into the flow table, so mitigation sticks to the flow — and a model
  swap bumps the flow generation exactly like a rule patch
  (TpuClassifier.set_score_model), so stale enforced verdicts are
  invalidated by the same stamps every table edit uses.

Models are versioned artifacts: ``save_model``/``load_model`` write an
npz of the value arrays plus a JSON manifest (format tag, version, the
geometry, a sha256 of the npz bytes) — the daemon's ``<state-dir>/
models/`` hot-swap dir consumes exactly these pairs.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from .kernels.mxu_score import (
    DEFAULT_THRESHOLD,
    HostScoreModel,
    ScoreModel,
    ScoreSpec,
    ScoreState,
    default_model,
    model_device,
    validate_model,
    zero_state_host,
    zero_tparams,
)

#: manifest format tag (bump on any incompatible artifact change)
MODEL_FORMAT = "infw-mlscore-v1"


# --- versioned model artifacts (npz + JSON manifest) -------------------------


def save_model(model: ScoreModel, path: str,
               version: Optional[str] = None) -> str:
    """Write ``path`` (.npz of the value arrays) plus ``path + '.json'``
    (the manifest: format, version, geometry, sha256 of the npz bytes).
    Returns the manifest path.  Writes are tmp+rename, so a hot-swap
    dir scanner can never observe a torn artifact."""
    validate_model(model)
    if not path.endswith(".npz"):
        path = path + ".npz"
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **model.arrays())
    os.replace(tmp, path)
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    manifest = {
        "format": MODEL_FORMAT,
        "version": str(version or model.version),
        "spec": dict(model.spec._asdict()),
        "sha256": digest,
    }
    mpath = path + ".json"
    with open(mpath + ".tmp", "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(mpath + ".tmp", mpath)
    return mpath


def load_model(path: str) -> ScoreModel:
    """Load a versioned model artifact.  The manifest is REQUIRED and
    its checksum must match the npz bytes — a silently corrupted or
    hand-edited artifact must fail at the control plane, never produce
    wrong scores on the serving path."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    mpath = path + ".json"
    if not os.path.exists(mpath):
        raise ValueError(f"score model manifest missing: {mpath}")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("format") != MODEL_FORMAT:
        raise ValueError(
            f"score model format {manifest.get('format')!r} != "
            f"{MODEL_FORMAT!r}"
        )
    with open(path, "rb") as f:
        raw = f.read()
    digest = hashlib.sha256(raw).hexdigest()
    if digest != manifest.get("sha256"):
        raise ValueError(
            f"score model checksum mismatch for {path} (manifest "
            f"{manifest.get('sha256', '')[:12]}.., npz {digest[:12]}..)"
        )
    spec = ScoreSpec.make(**manifest["spec"])
    import io

    with np.load(io.BytesIO(raw)) as z:
        model = ScoreModel(
            spec=spec, version=str(manifest.get("version", "unversioned")),
            **{k: z[k] for k in
               ("fidx", "fthr", "leaf", "w1", "b1", "w2", "b2", "qshift")},
        )
    validate_model(model)
    return model


# --- ring records ------------------------------------------------------------


@dataclass
class AnomalyVerdictRecord:
    """One decimated drain window of the anomaly tier, exactly once:
    per-tenant scored/anomalous/enforced counts with the window's max
    score and the tenant's policy row, plus the window's most-anomalous
    sources decoded from the device feature table.  ``seq`` is the
    gap-free drain generation (the telemetry-summary discipline)."""

    seq: int
    admissions: int
    tenants: List[dict] = field(default_factory=list)
    top: List[dict] = field(default_factory=list)

    def lines(self) -> List[str]:
        out = [
            f"anomaly-verdict seq={self.seq} "
            f"admissions={self.admissions} tenants={len(self.tenants)}"
        ]
        for t in self.tenants:
            mode = "ENFORCE" if t.get("enforce") else "shadow"
            out.append(
                f"\ttenant {t['tenant']}: {t['scored']} scored, "
                f"{t['anom']} anomalous, {t['enforced']} enforced, "
                f"max {t['max_score']} (thr {t['threshold']}, {mode})"
            )
        for h in self.top:
            out.append(
                f"\tanomalous-src tenant {h['tenant']} {h['src']}: "
                f"{h['anom_hits']} hit(s), ~{h['pkts']} pkts"
            )
        return out


def _format_src(keys_row: np.ndarray) -> str:
    kind = int(keys_row[5]) & 3
    if kind == 1:
        return ".".join(str(b) for b in int(keys_row[1]).to_bytes(4, "big"))
    import ipaddress

    return str(ipaddress.IPv6Address(
        keys_row[1:5].astype(">u4").tobytes()
    ))


class ScoreSnapshot(NamedTuple):
    """One drained window's host copies (summary inputs)."""

    seq: int
    admissions: int
    skeys: np.ndarray
    scols: np.ndarray
    tstat: np.ndarray
    tparams: np.ndarray


def summarize_snapshot(snap: ScoreSnapshot,
                       top_n: int = 8) -> AnomalyVerdictRecord:
    """Derive the drain-window record from one snapshot: exact tstat
    rows per tenant; the feature table's anomaly-hit column (stable
    sort on (-hits, slot): deterministic ties) becomes the anomalous-
    source list."""
    rec = AnomalyVerdictRecord(seq=snap.seq, admissions=snap.admissions)
    for t in np.nonzero(snap.tstat[:, 0] > 0)[0]:
        scored, anom, enforced, mx = (int(x) for x in snap.tstat[t])
        rec.tenants.append({
            "tenant": int(t), "scored": scored, "anom": anom,
            "enforced": enforced, "max_score": mx,
            "threshold": int(snap.tparams[t, 0]),
            "enforce": bool(snap.tparams[t, 1]),
        })
    hits = snap.scols[:, 6]
    occ = np.nonzero(hits > 0)[0]
    order = occ[np.argsort(-hits[occ], kind="stable")][:top_n]
    for slot in order:
        row = snap.skeys[slot]
        rec.top.append({
            "tenant": int(row[0]),
            "src": _format_src(row),
            "anom_hits": int(hits[slot]),
            "pkts": int(snap.scols[slot, 0]),
            "slot": int(slot),
        })
    return rec


# --- the device tier ---------------------------------------------------------


class AnomalyTier:
    """Host-side owner of the device scoring plane.

    Thread-safety / ordering: every device mutation (classic update
    launch, resident donated exchange, drain snapshot+reset, model
    swap) runs under ONE lock, so score updates land in a total device
    order; the optional HostScoreModel mirror replays the SAME order
    through a pending queue (resident admissions' verdicts are
    host-resident only at materialize — the TelemetryTier discipline).
    Lock nesting: the flow tier's dispatch lock and the telemetry
    tier's lock may be held when this lock is taken, never the reverse
    (flow -> telemetry -> mlscore).

    ``track_model`` is a SHADOW-mode facility (statecheck / tests): the
    mirror replays from the served verdicts, which under enforcement no
    longer carry the pre-policy rule verdicts — constructing a tracked
    tier with enforcement on (or enabling it later) raises.
    """

    def __init__(self, spec: ScoreSpec, model: Optional[ScoreModel] = None,
                 device=None, mode: str = "shadow",
                 threshold: int = DEFAULT_THRESHOLD,
                 track_model: bool = False, drain_every: int = 256,
                 ring=None, keep_masks: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        if mode not in ("shadow", "enforce"):
            raise ValueError(
                f"mlscore mode must be shadow|enforce, got {mode!r}"
            )
        if track_model and mode == "enforce":
            raise ValueError(
                "mlscore track_model is shadow-only (the mirror replays "
                "from served verdicts, which enforcement rewrites)"
            )
        self.spec = spec
        self._device = device
        self._lock = threading.Lock()
        host = zero_state_host(spec)
        put = lambda a: jax.device_put(jnp.asarray(a), device)
        self._state = ScoreState(*(put(a) for a in host))
        host_model = model or default_model(spec)
        validate_model(host_model)
        if host_model.spec != spec:
            raise ValueError("mlscore model geometry != tier spec")
        self._host_model = host_model
        self._model_dev = model_device(host_model, device)
        self._tparams_np = zero_tparams(
            spec, threshold=threshold, enforce=(mode == "enforce")
        )
        self._tparams_dev = put(self._tparams_np)
        self.model = (
            HostScoreModel(spec, host_model, self._tparams_np)
            if track_model else None
        )
        #: pending model mirrors in device-dispatch order (the
        #: TelemetryTier queue shape): resident entries hold the fused
        #: buffer and replay once the admission materializes
        self._mirror_q: list = []
        self.drain_every = int(drain_every)
        self._admissions = 0
        self._window_admissions = 0
        self._drain_seq = 0
        self._ring = ring
        self._zeros_cache: Dict[int, tuple] = {}
        #: test/bench facility: retain the last ``keep_masks``
        #: admissions' (epoch, anom mask, scores) triples — how the
        #: precision/recall legs read device decisions without a
        #: per-admission readback in production (0 = off)
        self._keep_masks = int(keep_masks)
        self._masks: list = []
        self.counters = {
            "updates": 0, "drains": 0, "records": 0,
            "anomalies": 0, "enforced": 0, "model_swaps": 0,
        }
        self.model_version = host_model.version
        #: control-plane hook run after a successful model swap (the
        #: classifier wires flow-generation invalidation here, so a
        #: swap behaves like a rule patch)
        self.on_swap = None
        self.top_n = 8

    # -- plumbing ------------------------------------------------------------

    def attach_ring(self, ring) -> None:
        with self._lock:
            self._ring = ring

    def _put(self, a):
        import jax

        return jax.device_put(a, self._device)

    def _zeros(self, b: int):
        z = self._zeros_cache.get(b)
        if z is None:
            z = (
                self._put(np.zeros(b, np.int32)),
                self._put(np.zeros(b, np.int32)),
            )
            self._zeros_cache[b] = z
        return z

    def _note(self, key: str, n: int = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + n

    def reset_state(self) -> None:
        """Zero the device score state (and the tracking mirror) without
        touching model/policy/counters — the bench's per-pass reset so
        interleaved A/B reps start from identical state.  One small H2D
        of zeros; shapes are spec-fixed, so nothing recompiles."""
        with self._lock:
            host = zero_state_host(self.spec)
            self._state = ScoreState(*(self._put(a) for a in host))
            if self.model is not None:
                self.model.reset_state()
            self._mirror_q.clear()
            self._masks.clear()

    # -- policy --------------------------------------------------------------

    def set_mode(self, mode: str, tenant: Optional[int] = None) -> None:
        """Flip shadow/enforce for one tenant (or all): one tiny
        tparams re-upload, no recompile — mode is a runtime operand."""
        if mode not in ("shadow", "enforce"):
            raise ValueError(
                f"mlscore mode must be shadow|enforce, got {mode!r}"
            )
        with self._lock:
            if mode == "enforce" and self.model is not None:
                raise ValueError(
                    "mlscore track_model is shadow-only; detach the "
                    "mirror before enforcing"
                )
            rows = (
                slice(None) if tenant is None
                else int(tenant)
            )
            self._tparams_np[rows, 1] = 1 if mode == "enforce" else 0
            self._tparams_dev = self._put(self._tparams_np)
            hook = self.on_swap
        # a policy flip changes what the tier would decide NOW — flow
        # entries caching verdicts enforced under the old policy must go
        # stale exactly like after a model swap (same generation stamps)
        if hook is not None:
            hook()

    def set_threshold(self, threshold: int,
                      tenant: Optional[int] = None) -> None:
        with self._lock:
            rows = slice(None) if tenant is None else int(tenant)
            self._tparams_np[rows, 0] = int(threshold)
            self._tparams_dev = self._put(self._tparams_np)
            hook = self.on_swap
        if hook is not None:
            hook()

    def tparams(self) -> np.ndarray:
        with self._lock:
            return self._tparams_np.copy()

    def swap_model(self, model: ScoreModel,
                   version: Optional[str] = None) -> None:
        """Hot-swap the model values: validate, upload the new operand
        arrays whole (spec-fixed shapes — zero recompiles), replace the
        mirror's model, then fire ``on_swap`` (the classifier's flow-
        generation bump: a model swap behaves like a rule patch)."""
        validate_model(model)
        if model.spec != self.spec:
            raise ValueError(
                f"score model geometry {model.spec} != tier spec "
                f"{self.spec} (geometry changes are a tier rebuild, "
                "not a hot swap)"
            )
        with self._lock:
            self._host_model = model
            self._model_dev = model_device(model, self._device)
            self.model_version = str(version or model.version)
            if self.model is not None:
                self.model.swap(model)
            self._note("model_swaps")
            hook = self.on_swap
        if hook is not None:
            hook()

    def host_model(self) -> ScoreModel:
        with self._lock:
            return self._host_model

    # -- updates -------------------------------------------------------------

    def update(self, wire_np: np.ndarray, res: np.ndarray,
               tenant_np: Optional[np.ndarray] = None,
               tflags_np: Optional[np.ndarray] = None):
        """The multi-dispatch path's scoring launch: ONE device program
        per admission over (wire, merged rule verdicts), donated state.
        Returns host copies (res16' uint16, anom bool, scores int32) —
        the caller (backend/tpu) swaps its verdicts for res16' so
        enforcement lands before the flow insert and the stats
        derivation, bit-identically to the fused path."""
        from .kernels import mxu_score

        b = wire_np.shape[0]
        wire = self._put(np.ascontiguousarray(wire_np, np.uint32))
        res_dev = self._put(np.asarray(res, np.uint32))
        zt, zf = None, None
        if tenant_np is None or tflags_np is None:
            zt, zf = self._zeros(b)
        tenant = (zt if tenant_np is None
                  else self._put(np.ascontiguousarray(tenant_np, np.int32)))
        tflags = (zf if tflags_np is None
                  else self._put(np.ascontiguousarray(tflags_np, np.int32)))
        fn = mxu_score.jitted_score_update(self.spec)
        with self._lock:
            sc2, score, anom, res_out = fn(
                self._state, self._model_dev, self._tparams_dev, wire,
                tenant, tflags, res_dev,
            )
            self._state = sc2
            self._admissions += 1
            self._window_admissions += 1
            epoch = self._admissions
            self._note("updates")
            if self.model is not None:
                self._mirror_q.append(
                    (np.asarray(wire_np, np.uint32).copy(),
                     None if tenant_np is None
                     else np.asarray(tenant_np, np.int32).copy(),
                     None if tflags_np is None
                     else np.asarray(tflags_np, np.int32).copy(),
                     np.asarray(res, np.uint32).copy(), None)
                )
                self._replay_ready_locked()
        # reported scores are int16-saturated on BOTH paths: the fused
        # resident readback packs them into an int16 lane, so the
        # classic path clips identically (the anom decision was made
        # in-kernel on the raw int32 — only the report saturates)
        score_np = np.clip(np.asarray(score), -32768, 32767).astype(np.int32)
        anom_np = np.asarray(anom)
        res16 = (np.asarray(res_out) & 0xFFFF).astype(np.uint16)
        self._note_result(epoch, anom_np, score_np)
        self.maybe_drain()
        return res16, anom_np, score_np

    def resident_exchange(self, launch, epoch: int,
                          wire_np, tenant_np, tflags_np):
        """The resident fused step's donated score chain: ``launch(sc,
        model, tparams) -> (sc', rest)`` runs under this tier's lock so
        score updates land in device-dispatch order; the model mirror
        (track_model only) queues with the fused buffer and replays
        once the admission materializes."""
        with self._lock:
            sc2, rest = launch(
                self._state, self._model_dev, self._tparams_dev
            )
            self._state = sc2
            self._admissions += 1
            self._window_admissions += 1
            self._note("updates")
            if self.model is not None:
                fused = rest[-1]
                self._mirror_q.append(
                    (np.asarray(wire_np, np.uint32).copy(),
                     None if tenant_np is None
                     else np.asarray(tenant_np, np.int32).copy(),
                     None if tflags_np is None
                     else np.asarray(tflags_np, np.int32).copy(),
                     None, fused)
                )
        return rest

    def resident_exchange_super(self, launch, epoch0: int, k: int,
                                wire_np, tenant_np, tflags_np):
        """The superbatch variant of ``resident_exchange`` (ISSUE-16):
        one launch carries ``k`` stacked admissions with the donated
        score state chained through the device-side scan carry; the
        model mirror queues ``k`` entries, one per admission, each
        holding its row of the stacked (k, L) fused readback."""
        with self._lock:
            sc2, rest = launch(
                self._state, self._model_dev, self._tparams_dev
            )
            self._state = sc2
            self._admissions += k
            self._window_admissions += k
            self._note("updates", k)
            if self.model is not None:
                fused = rest[-1]
                wire_stack = np.asarray(wire_np, np.uint32)
                for j in range(k):
                    self._mirror_q.append(
                        (wire_stack[j].copy(),
                         None if tenant_np is None
                         else np.asarray(tenant_np[j], np.int32).copy(),
                         None if tflags_np is None
                         else np.asarray(tflags_np[j], np.int32).copy(),
                         None, (fused, j))
                    )
        return rest

    def _replay_ready_locked(self) -> None:
        """Drain the head of the mirror queue in device order (the
        TelemetryTier shape): a resident entry's verdicts live in its
        fused buffer (or its row of a superbatch's stacked readback) —
        resident_fused_host blocks until the dispatch lands, which
        keeps classic entries behind it in order.  Shadow-only: the
        fused res16 IS the pre-policy rule verdict vector."""
        from .kernels import jaxpath

        while self._mirror_q:
            wire, tenant, tflags, res, fused = self._mirror_q[0]
            if res is None:
                res16, _hit, _h, _s, _c, _an, _sc = (
                    jaxpath.split_resident_score_outputs(
                        jaxpath.resident_fused_host(fused), wire.shape[0]
                    )
                )
                res = res16.astype(np.uint32)
            self.model.update(wire, res, tenant, tflags)
            self._mirror_q.pop(0)

    def _note_result(self, epoch: int, anom_np: np.ndarray,
                     score_np: Optional[np.ndarray]) -> None:
        n_anom = int(anom_np.sum()) if anom_np is not None else 0
        with self._lock:
            if n_anom:
                self._note("anomalies", n_anom)
            if self._keep_masks and anom_np is not None:
                self._masks.append((epoch, anom_np.copy(),
                                    None if score_np is None
                                    else score_np.copy()))
                del self._masks[:-self._keep_masks]

    def resident_note_materialized(self, epoch: int,
                                   anom_np: Optional[np.ndarray] = None,
                                   score_np: Optional[np.ndarray] = None,
                                   enforced: int = 0) -> None:
        """Materialize hook for resident admissions: replay pending
        model mirrors, note the admission's anomaly outcome (the fused
        buffer's bitmap, parsed by the caller) and run the decimated
        drain cadence."""
        if self.model is not None:
            with self._lock:
                self._replay_ready_locked()
        if anom_np is not None:
            self._note_result(epoch, anom_np, score_np)
        if enforced:
            with self._lock:
                self._note("enforced", enforced)
        self.maybe_drain()

    def recent_masks(self) -> list:
        """The retained (epoch, anom mask, scores) triples (keep_masks
        test/bench facility), oldest first."""
        with self._lock:
            return list(self._masks)

    def set_keep_masks(self, n: int) -> None:
        """Enable/resize the retained-decision window (test/bench
        only; 0 disables and drops the backlog)."""
        with self._lock:
            self._keep_masks = int(n)
            if not self._keep_masks:
                self._masks.clear()
            else:
                del self._masks[:-self._keep_masks]

    # -- the decimated drain -------------------------------------------------

    def maybe_drain(self) -> List[AnomalyVerdictRecord]:
        with self._lock:
            due = self._window_admissions >= self.drain_every
        return self.drain() if due else []

    def drain(self, force: bool = True) -> List[AnomalyVerdictRecord]:
        """Snapshot + window-reset the device tensors and emit the
        window's anomaly-verdict record on the attached ring.  Exactly-
        once: snapshot and reset run under the tier lock atomically
        with the admission counters, so every admission lands in
        exactly one window and ``seq`` stamps are gap-free (the
        telemetry drain contract).  Only the WINDOW state resets (tstat
        + per-row anomaly hits); rates persist."""
        from .kernels import mxu_score

        with self._lock:
            if not force and self._window_admissions < self.drain_every:
                return []
            if self.model is not None:
                self._replay_ready_locked()
            snap = ScoreSnapshot(
                seq=self._drain_seq + 1,
                admissions=self._window_admissions,
                skeys=np.asarray(self._state.skeys),
                scols=np.asarray(self._state.scols),
                tstat=np.asarray(self._state.tstat),
                tparams=self._tparams_np.copy(),
            )
            self._state = mxu_score.jitted_score_drain()(self._state)
            if self.model is not None:
                self.model.drain()
            self._drain_seq += 1
            self._window_admissions = 0
            self._note("drains")
            enforced = int(snap.tstat[:, 2].sum())
            if enforced:
                self._note("enforced", enforced)
            rec = summarize_snapshot(snap, top_n=self.top_n)
            self._note("records")
            if self._ring is not None:
                self._ring.push(rec)
        return [rec]

    # -- introspection -------------------------------------------------------

    def columns(self) -> Dict[str, np.ndarray]:
        """Host copies of the device tensors (the model-compare side),
        materialized INSIDE the lock — the state is donated per
        admission, so an off-lock snapshot could be consumed
        mid-read."""
        with self._lock:
            s = self._state
            return {k: np.asarray(getattr(s, k)) for k in s._fields}

    @property
    def admissions(self) -> int:
        with self._lock:
            return self._admissions

    @property
    def drain_seq(self) -> int:
        with self._lock:
            return self._drain_seq

    def counter_values(self) -> Dict[str, int]:
        """mlscore_* counters for /metrics."""
        with self._lock:
            out = {
                f"mlscore_{k}_total": int(v)
                for k, v in self.counters.items()
            }
            out["mlscore_admissions_total"] = self._admissions
            out["mlscore_drain_seq"] = self._drain_seq
            out["mlscore_window_admissions"] = self._window_admissions
            out["mlscore_enforce_tenants"] = int(
                (self._tparams_np[:, 1] != 0).sum()
            )
        return out

    def warm(self, ladder) -> int:
        """Pre-compile the classic score-update executable for every
        wire shape in ``ladder`` (inert KIND_OTHER rows: every lane
        ineligible, only the epoch advances — mirrored into the tracked
        model via tick()).  Prewarm launches must NOT count as
        admissions (counters, drain window and the mirror all see
        served traffic only)."""
        from .kernels import mxu_score

        fn = mxu_score.jitted_score_update(self.spec)
        n = 0
        for b in sorted(set(int(x) for x in ladder)):
            for width in (4, 7):
                wire_np = np.zeros((b, width), np.uint32)
                wire_np[:, 0] = 3  # KIND_OTHER
                wire = self._put(wire_np)
                zt, zf = self._zeros(b)
                res = self._put(np.zeros(b, np.uint32))
                with self._lock:
                    sc2, _score, _anom, _res = fn(
                        self._state, self._model_dev, self._tparams_dev,
                        wire, zt, zf, res,
                    )
                    self._state = sc2
                    if self.model is not None:
                        self.model.tick()
                n += 1
        return n
