"""Declarative e2e reachability harness.

Port of the reference functional suite's table engine
(/root/reference/test/e2e/functional/tests/e2e.go:59-176,856+): test cases
describe virtual client/server pods, generate IngressNodeFirewall CRs from
the pods' IPs (sourceCIDRs = pod IP masked to a prefix, orders generated
unique per CIDR), drive the FULL stack (admission -> fan-out -> NodeState
-> syncer -> classifier), then assert a ``Reachable`` table.  Where the
reference probes with real netcat/ping pods, this harness synthesizes the
equivalent raw frames (obs.pcap.build_frame) and asserts the classifier
verdict — PASS == reachable, DROP == unreachable (SURVEY.md §4 carry-over).
"""
from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from .backend.cpu_ref import CpuRefClassifier
from .constants import (
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    XDP_PASS,
)
from .interfaces import Interface, InterfaceRegistry
from .manager import Manager
from .obs.pcap import build_frame, parse_frames
from .spec import (
    IngressNodeFirewall,
    IngressNodeFirewallICMPRule,
    IngressNodeFirewallNodeState,
    IngressNodeFirewallProtoRule,
    IngressNodeFirewallProtocolRule,
    IngressNodeFirewallRules,
    IngressNodeFirewallSpec,
    IngressNodeProtocolConfig,
    ObjectMeta,
    PROTOCOL_TYPE_ICMP,
    PROTOCOL_TYPE_ICMP6,
    PROTOCOL_TYPE_SCTP,
    PROTOCOL_TYPE_TCP,
    PROTOCOL_TYPE_UDP,
)
from .store import Node
from .syncer import DataplaneSyncer

_PROTO_NUM = {
    PROTOCOL_TYPE_TCP: IPPROTO_TCP,
    PROTOCOL_TYPE_UDP: IPPROTO_UDP,
    PROTOCOL_TYPE_SCTP: IPPROTO_SCTP,
    PROTOCOL_TYPE_ICMP: IPPROTO_ICMP,
    PROTOCOL_TYPE_ICMP6: IPPROTO_ICMPV6,
}

RuleTemplate = Callable[[str, int], IngressNodeFirewallProtocolRule]


@dataclass
class Pod:
    """A virtual client/server endpoint (the reference's netcat pods)."""

    name: str
    ipv4: str = ""
    ipv6: str = ""

    def ip(self, family: int) -> str:
        return self.ipv4 if family == 4 else self.ipv6


@dataclass
class SourceCIDRsEntry:
    """sourceCIDRsEntry (e2e.go:76-84): a pod whose IP, masked to the
    given prefixes, becomes the generated sourceCIDR(s)."""

    pod_name: str
    v4_prefix: int = 24
    v6_prefix: int = 64


@dataclass
class TestRule:
    """testRule (e2e.go:63-73): CIDR sources + protocol rule templates;
    the harness generates unique orders per CIDR."""

    __test__ = False  # keep pytest from collecting this dataclass

    source_cidrs_entries: List[SourceCIDRsEntry]
    proto_rules: List[RuleTemplate]


@dataclass
class Reachable:
    """reachable (e2e.go:86-97)."""

    source: str
    destination: str
    port: int = 0
    connectivity: bool = True
    protocol: str = PROTOCOL_TYPE_TCP
    icmp_type: int = 8
    icmp_code: int = 0


def cidr_of(ip: str, v4_prefix: int, v6_prefix: int) -> str:
    addr = ipaddress.ip_address(ip)
    prefix = v4_prefix if addr.version == 4 else v6_prefix
    net = ipaddress.ip_network(f"{ip}/{prefix}", strict=False)
    return str(net)


class Harness:
    """Builds the stack once per scenario: manager store + fan-out +
    in-process syncer fed by the generated NodeState."""

    def __init__(
        self,
        pods: Sequence[Pod],
        node_name: str = "e2e-node",
        iface: str = "eth0",
        ifindex: int = 2,
        node_labels: Optional[Dict[str, str]] = None,
        classifier_factory: Optional[Callable] = None,
    ) -> None:
        """``classifier_factory`` selects the dataplane under test —
        CpuRefClassifier by default (CI), backend.tpu.TpuClassifier to
        drive the same reachability tables against the device path (the
        reference runs its one table engine against the real XDP
        dataplane, e2e.go:856+; ours must run against the real TPU one
        too, not only the C++ oracle)."""
        self.pods = {p.name: p for p in pods}
        self.node_name = node_name
        self.iface = iface
        self.ifindex = ifindex
        self.node_labels = node_labels or {"do-node-ingress-firewall": "true"}
        self.manager = Manager(namespace="e2e-ns")
        self.manager.store.create(
            Node(metadata=ObjectMeta(name=node_name, labels=dict(self.node_labels)))
        )
        self.registry = InterfaceRegistry()
        self.registry.add(Interface(name=iface, index=ifindex))
        self.syncer = DataplaneSyncer(
            classifier_factory=classifier_factory or CpuRefClassifier,
            registry=self.registry,
        )

    def apply_rules(
        self,
        test_rules: List[TestRule],
        interfaces: Optional[List[str]] = None,
        families: Sequence[int] = (4, 6),
        inf_name: str = "e2e-inf",
        protocols: Optional[Dict[RuleTemplate, List[str]]] = None,
    ) -> None:
        """Generate the INF from the rule templates (order generated
        unique per sourceCIDR, e2e.go:71-72) and run it through
        admission + fan-out + sync."""
        ingress: List[IngressNodeFirewallRules] = []
        for tr in test_rules:
            cidrs: List[str] = []
            for entry in tr.source_cidrs_entries:
                pod = self.pods[entry.pod_name]
                for family in families:
                    ip = pod.ip(family)
                    if ip:
                        cidrs.append(cidr_of(ip, entry.v4_prefix, entry.v6_prefix))
            rules: List[IngressNodeFirewallProtocolRule] = []
            order = 1
            for template in tr.proto_rules:
                # A template carries its natural protocol list (set by the
                # factory); the protocols dict overrides per test case.
                default = getattr(template, "default_protocols", [PROTOCOL_TYPE_TCP])
                protos = (protocols or {}).get(template, default)
                for proto in protos:
                    rules.append(template(proto, order))
                    order += 1
            ingress.append(
                IngressNodeFirewallRules(source_cidrs=cidrs, rules=rules)
            )
        inf = IngressNodeFirewall(
            metadata=ObjectMeta(name=inf_name),
            spec=IngressNodeFirewallSpec(
                node_selector=dict(self.node_labels),
                ingress=ingress,
                interfaces=list(interfaces or [self.iface]),
            ),
        )
        self.manager.store.create(inf)  # admission webhook runs here
        self.resync()

    def resync(self) -> None:
        """Drain the manager queue and program the dataplane from the
        resulting NodeState (also used after out-of-band spec updates)."""
        self.manager.drain()
        ns_obj = self.manager.store.get(
            IngressNodeFirewallNodeState.KIND, self.node_name, "e2e-ns"
        )
        assert ns_obj.status.sync_status != "Error", ns_obj.status.sync_error_message
        self.syncer.sync_interface_ingress_rules(
            ns_obj.spec.interface_ingress_rules, False
        )

    def probe(self, r: Reachable, family: int = 4) -> bool:
        """One connectivity probe: synthesize the frame the reference's
        netcat/ping client would emit, classify, and report PASS."""
        src = self.pods[r.source].ip(family)
        dst = self.pods[r.destination].ip(family)
        if not src or not dst:
            raise ValueError(f"pod without family-{family} address")
        proto = _PROTO_NUM[r.protocol]
        icmp_type = r.icmp_type
        if family == 6 and r.protocol == PROTOCOL_TYPE_ICMP:
            # A generic "ICMP" probe means the family's native ICMP: switch
            # the protocol number AND translate the well-known echo types
            # (request 8->128, reply 0->129) — what ping does per family.
            proto = IPPROTO_ICMPV6
            icmp_type = {8: 128, 0: 129}.get(icmp_type, icmp_type)
        frame = build_frame(
            src, dst, proto,
            src_port=40001, dst_port=r.port,
            icmp_type=icmp_type, icmp_code=r.icmp_code,
        )
        batch = parse_frames([frame], ifindex=self.ifindex)
        out = self.syncer.classifier.classify(batch)
        return int(out.xdp[0]) == XDP_PASS

    def check_reachability(
        self, table: List[Reachable], families: Sequence[int] = (4,)
    ) -> List[str]:
        """Assert the whole table; returns a list of human-readable
        failures (empty == all expectations met)."""
        failures = []
        for r in table:
            for family in families:
                got = self.probe(r, family)
                if got != r.connectivity:
                    failures.append(
                        f"{r.source}->{r.destination} proto={r.protocol} "
                        f"port={r.port} family={family}: "
                        f"expected connectivity={r.connectivity}, got {got}"
                    )
        return failures

    def close(self) -> None:
        self.manager.stop()
        self.syncer.shutdown()


# --- rule templates (the funcs the reference table passes, e2e.go:177+) ------
# Each factory tags its template with default_protocols so forgetting the
# protocols dict still instantiates a valid rule shape.

def deny_port(port) -> RuleTemplate:
    def template(proto: str, order: int) -> IngressNodeFirewallProtocolRule:
        return _transport_rule(proto, order, port, "Deny")

    template.default_protocols = [PROTOCOL_TYPE_TCP]
    return template


def allow_port(port) -> RuleTemplate:
    def template(proto: str, order: int) -> IngressNodeFirewallProtocolRule:
        return _transport_rule(proto, order, port, "Allow")

    template.default_protocols = [PROTOCOL_TYPE_TCP]
    return template


def deny_icmp(icmp_type: int = 8, icmp_code: int = 0) -> RuleTemplate:
    def template(proto: str, order: int) -> IngressNodeFirewallProtocolRule:
        return _icmp_rule(proto, order, icmp_type, icmp_code, "Deny")

    template.default_protocols = [
        PROTOCOL_TYPE_ICMP if icmp_type < 128 else PROTOCOL_TYPE_ICMP6
    ]
    return template


def deny_all() -> RuleTemplate:
    def template(proto: str, order: int) -> IngressNodeFirewallProtocolRule:
        return IngressNodeFirewallProtocolRule(
            order=order,
            protocol_config=IngressNodeProtocolConfig(protocol=""),
            action="Deny",
        )

    template.default_protocols = [PROTOCOL_TYPE_TCP]  # instantiated once
    return template


def _transport_rule(proto, order, port, action):
    pr = IngressNodeFirewallProtoRule(ports=port)
    kw = {
        PROTOCOL_TYPE_TCP: "tcp",
        PROTOCOL_TYPE_UDP: "udp",
        PROTOCOL_TYPE_SCTP: "sctp",
    }[proto]
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(protocol=proto, **{kw: pr}),
        action=action,
    )


def _icmp_rule(proto, order, icmp_type, icmp_code, action):
    icmp = IngressNodeFirewallICMPRule(icmp_type=icmp_type, icmp_code=icmp_code)
    kw = "icmp" if proto == PROTOCOL_TYPE_ICMP else "icmpv6"
    return IngressNodeFirewallProtocolRule(
        order=order,
        protocol_config=IngressNodeProtocolConfig(protocol=proto, **{kw: icmp}),
        action=action,
    )
