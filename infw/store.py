"""Pluggable resource store — the control plane's "cluster API".

The reference's manager and daemon communicate exclusively through the
Kubernetes API (SURVEY.md §1): controllers List/Get/Create/Update/Delete
typed objects and react to watch events.  This module provides that
surface as an in-memory, thread-safe store with watch callbacks — the
default backend for tests and single-host deployments (the role envtest
plays for the reference's controller suite,
/root/reference/controllers/suite_test.go:66-137).  A networked adapter
can implement the same Store protocol later without touching the
controllers.

Semantics preserved from the k8s client:
- objects are copied on write and on read (no aliasing mutations);
- ``update_status`` writes only the status subresource
  (r.Status().Update, ingressnodefirewall_controller.go:141-147);
- deletes of finalized objects set ``deletion_timestamp`` and wait for
  finalizer removal (the NodeState finalizer dance,
  ingressnodefirewallnodestate_controller.go:77-99);
- every write bumps ``resource_version`` and fans out a watch event.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .spec import (
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
    IngressNodeFirewallNodeState,
    ObjectMeta,
    deep_copy,
)

log = logging.getLogger("infw.store")


class StoreError(RuntimeError):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class AdmissionError(StoreError):
    """Rejection by an admission validator (the webhook's deny response,
    pkg/webhook/webhook.go:50-66)."""

    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


@dataclass
class Node:
    """Minimal cluster Node: metadata only (the fan-out controller matches
    on labels, ingressnodefirewall_controller.go:269-275)."""

    KIND = "Node"
    API_VERSION = "v1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    def to_dict(self) -> dict:
        return {"kind": self.KIND, "metadata": self.metadata.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata", {}) or {}))


@dataclass
class DaemonSetStatus:
    """The readiness fields the availability probe consumes
    (pkg/status/status.go:101-111)."""

    desired_number_scheduled: int = 0
    number_ready: int = 0

    def to_dict(self) -> dict:
        return {
            "desiredNumberScheduled": self.desired_number_scheduled,
            "numberReady": self.number_ready,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DaemonSetStatus":
        return cls(
            desired_number_scheduled=int(d.get("desiredNumberScheduled", 0)),
            number_ready=int(d.get("numberReady", 0)),
        )


@dataclass
class DaemonSet:
    """The rendered per-node daemon deployment descriptor — what the
    reference's DaemonSet manifest is to kubelet
    (bindata/manifests/daemon/daemonset.yaml), reduced to the fields that
    drive TPU daemon processes: selector, image, env contract."""

    KIND = "DaemonSet"
    API_VERSION = "apps/v1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict = field(default_factory=dict)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec,
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DaemonSet":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata", {}) or {}),
            spec=dict(d.get("spec", {}) or {}),
            status=DaemonSetStatus.from_dict(d.get("status", {}) or {}),
        )


_KINDS = {
    IngressNodeFirewall.KIND: IngressNodeFirewall,
    IngressNodeFirewallConfig.KIND: IngressNodeFirewallConfig,
    IngressNodeFirewallNodeState.KIND: IngressNodeFirewallNodeState,
    Node.KIND: Node,
    DaemonSet.KIND: DaemonSet,
}

# watch event types
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchCallback = Callable[[str, object], None]


def _copy(obj):
    return obj.__class__.from_dict(obj.to_dict())


class InMemoryStore:
    """Thread-safe object store with watches."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._watchers: Dict[str, List[WatchCallback]] = {}
        self._admission: Dict[str, Callable] = {}
        self._rv = 0

    # -- admission (the validating-webhook seam) -----------------------------

    def set_admission(self, kind: str, validator: Callable) -> None:
        """Register an admission validator for a kind: called on create and
        update (not status/finalizer writes, matching the reference
        webhook's Create/Update hooks) with (obj, store); a non-empty error
        list rejects the write with AdmissionError."""
        with self._lock:
            self._admission[kind] = validator

    def _admit(self, obj) -> None:
        with self._lock:
            validator = self._admission.get(obj.KIND)
        if validator is None:
            return
        errors = validator(obj, self)
        if errors:
            raise AdmissionError(list(errors))

    # -- keys ----------------------------------------------------------------

    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
        return (kind, namespace or "", name)

    def _key_of(self, obj) -> Tuple[str, str, str]:
        return self._key(obj.KIND, obj.metadata.namespace, obj.metadata.name)

    # -- reads ---------------------------------------------------------------

    def get(self, kind: str, name: str, namespace: str = ""):
        with self._lock:
            obj = self._objects.get(self._key(kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return _copy(obj)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> List[object]:
        """List with optional namespace scoping and MatchingLabels
        selection (client.MatchingLabels semantics: empty selector matches
        everything)."""
        with self._lock:
            out = []
            for (k, ns, _), obj in sorted(self._objects.items()):
                if k != kind:
                    continue
                if namespace is not None and ns != (namespace or ""):
                    continue
                if labels:
                    meta = obj.metadata
                    if any(meta.labels.get(lk) != lv for lk, lv in labels.items()):
                        continue
                out.append(_copy(obj))
            return out

    # -- writes --------------------------------------------------------------

    def create(self, obj) -> object:
        with self._lock:
            # Admission inside the lock: cross-object invariants (e.g. the
            # cross-INF order-overlap check) must validate against the same
            # state the write commits into; the RLock makes the validator's
            # own store reads re-entrant.
            self._admit(obj)
            key = self._key_of(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            stored = _copy(obj)
            # The API server ignores status on create (status is a
            # subresource); writers must follow with update_status.
            if hasattr(stored, "status"):
                stored.status = stored.status.__class__()
            self._rv += 1
            stored.metadata.resource_version = self._rv
            if not stored.metadata.uid:
                stored.metadata.uid = f"uid-{self._rv}"
            self._objects[key] = stored
            out = _copy(stored)
        self._notify(ADDED, stored)
        return out

    def update(self, obj) -> object:
        """Full-object update (spec + metadata); the status subresource is
        carried over from the stored object, mirroring the API server's
        split."""
        with self._lock:
            self._admit(obj)
            key = self._key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            stored = _copy(obj)
            if hasattr(cur, "status"):
                stored.status = deep_copy(cur.status) if hasattr(cur.status, "to_dict") else cur.status
            stored.metadata.uid = cur.metadata.uid
            stored.metadata.deletion_timestamp = cur.metadata.deletion_timestamp
            # No-op updates don't bump the version or fire watches (API-server
            # semantics — this is what lets level-based reconciles that write
            # back unchanged state converge instead of livelocking).
            stored.metadata.resource_version = cur.metadata.resource_version
            if stored.to_dict() == cur.to_dict():
                return _copy(cur)
            self._rv += 1
            stored.metadata.resource_version = self._rv
            self._objects[key] = stored
            out = _copy(stored)
        self._notify(MODIFIED, stored)
        return out

    def update_status(self, obj) -> object:
        with self._lock:
            key = self._key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            new_status = (
                deep_copy(obj.status) if hasattr(obj.status, "to_dict") else obj.status
            )
            same = (
                new_status.to_dict() == cur.status.to_dict()
                if hasattr(new_status, "to_dict")
                else new_status == cur.status
            )
            if same:  # no-op status write (see update())
                return _copy(cur)
            stored = _copy(cur)
            stored.status = new_status
            self._rv += 1
            stored.metadata.resource_version = self._rv
            self._objects[key] = stored
            out = _copy(stored)
        self._notify(MODIFIED, stored)
        return out

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        """Finalizer-aware delete: objects with finalizers get a deletion
        timestamp and remain until the finalizers are removed via
        update_finalizers."""
        with self._lock:
            key = self._key(kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur.metadata.deletion_timestamp = time.time()
                self._rv += 1
                cur.metadata.resource_version = self._rv
                event, obj = MODIFIED, cur
            else:
                del self._objects[key]
                event, obj = DELETED, cur
        # Re-notify even when deletion was already in progress: watchers
        # whose finalizer teardown failed transiently get a retry signal on
        # the next delete attempt (the role controller-runtime's requeue
        # plays for the reference).
        self._notify(event, obj)

    def update_finalizers(self, obj, finalizers: List[str]) -> object:
        """Set the finalizer list; an object past its deletion timestamp
        with no finalizers left is removed (API-server GC behavior the
        NodeState controller's finalizer dance relies on)."""
        with self._lock:
            key = self._key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            cur.metadata.finalizers = list(finalizers)
            self._rv += 1
            cur.metadata.resource_version = self._rv
            if cur.metadata.deletion_timestamp is not None and not cur.metadata.finalizers:
                del self._objects[key]
                event = DELETED
            else:
                event = MODIFIED
            out = _copy(cur)
        self._notify(event, cur)
        return out

    # -- watches -------------------------------------------------------------

    def watch(self, kind: str, callback: WatchCallback) -> Callable[[], None]:
        """Subscribe to events for a kind; returns an unsubscribe thunk."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(callback)

        def cancel() -> None:
            with self._lock:
                try:
                    self._watchers.get(kind, []).remove(callback)
                except ValueError:
                    pass

        return cancel

    def _notify(self, event: str, obj) -> None:
        """Fan out an event.  Callers invoke this OUTSIDE the store lock so
        slow watchers (a full dataplane sync can sleep through attach
        retries) never block other threads' store access."""
        with self._lock:
            callbacks = list(self._watchers.get(obj.KIND, []))
        for cb in callbacks:
            # A raising watcher must not propagate into the writer's
            # create/update call or skip the remaining watchers (mirrors
            # controller-runtime's per-handler workqueue isolation).
            try:
                cb(event, _copy(obj))
            except Exception:
                log.exception("watch callback failed for %s %s", event, obj.KIND)
