"""Deadline-aware continuous microbatching: the serving-tier scheduler.

BENCH_r05 put on-device verdict latency at 7-26 us across batch 32-4096
while the wire path sits on a ~108-114 ms tunnel sync floor — at serving
scale, tail latency is decided by how arrivals are coalesced into
device-sized work units, not by the kernel (hXDP makes the same argument
for offloaded packet processing).  This module turns that into policy:

- **admit-by-deadline, not by fixed chunk size** (``DeadlinePolicy``):
  while the device pipeline is busy, arrivals queue; each admission
  takes the LARGEST batch whose oldest packet still meets its per-packet
  deadline budget given the measured service time of that batch size.
  When the pipeline has a free slot the policy is work-conserving — the
  queued packets ship immediately, whatever their count — so the device
  never idles while packets wait (continuous batching, the vLLM-style
  serving loop applied to packet verdicts).
- **service-time model** (``ServiceModel``): an EWMA of observed
  dispatch->materialize latency per batch-size bucket, so the admission
  decision reasons about THIS deployment's measured service curve (a
  tunneled chip and an on-node PCIe chip differ by 4 orders of
  magnitude) instead of a constant.
- **batch-size ladder** (``batch_ladder``): admitted batches pad to
  power-of-two buckets from ``MIN_LADDER_BATCH`` (32 — the BENCH_r05
  small-batch anomaly shape) up to the admission cap, and
  ``prewarm_ladder`` runs every ladder shape through the production
  dispatch once at startup so shape-driven jit recompiles never land on
  the serving path.
- **mesh spillover** (``ContinuousScheduler(spill_clf=...)``): a
  coalesced batch larger than the per-chip budget dispatches through the
  MeshTpuClassifier, which shards it over the ``"data"`` axis; on a
  single-chip pool (no spill target) the oversized admission is split
  into per-chip-budget jobs instead — degrade, never refuse.
- **update-storm interleaving** (``ContinuousScheduler(txn_batcher=...,
  txn_flush=...)``): queued rule edits (infw.txn) flush under their
  bounded-staleness policy WHILE serving — a tripped flush runs on its
  own thread occupying ONE pipeline slot instead of stalling
  admissions, and in-flight classifies finish on the table generation
  they were dispatched against (the double-buffer swap contract).

Observability: ``SchedulerStats`` exports queue depth, the achieved
batch-size histogram, deadline-miss and spill counters through the
metrics registry's counter-provider protocol, and every deadline miss
emits a ``DeadlineMissRecord`` on the obs event ring.

Latency accounting is coordinated-omission-safe: a packet's verdict
latency is measured from its SCHEDULED arrival time (the open-loop load
generator's timestamp), never from when the scheduler got around to
dequeuing it — a backlogged scheduler therefore reports the queueing it
caused instead of hiding it (the classic closed-loop p99 underreport).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ._threads import spawn
from .constants import KIND_IPV6, KIND_OTHER
from .packets import PacketBatch

log = logging.getLogger("infw.scheduler")

#: smallest admitted batch bucket — the BENCH_r05 anomaly shape (batch=32
#: read 11.77 ms p50-above-floor while 64/128 read ~0, a first-dispatch
#: jit specialization landing inside the timed path); the ladder starts
#: here precisely so the pre-warm covers it
MIN_LADDER_BATCH = 32


def batch_ladder(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two admission buckets MIN_LADDER_BATCH..max_batch (the
    cap itself is always the last step, pow2 or not) — every batch shape
    the scheduler can emit, and therefore every shape prewarm_ladder
    must cover."""
    max_batch = max(int(max_batch), MIN_LADDER_BATCH)
    steps: List[int] = []
    b = MIN_LADDER_BATCH
    while b < max_batch:
        steps.append(b)
        b <<= 1
    steps.append(max_batch)
    return tuple(steps)


def ladder_bucket(n: int, max_batch: int) -> int:
    """Smallest ladder step >= n (capped at max_batch): the padded batch
    size an n-packet admission dispatches as."""
    if n >= max_batch:
        return int(max_batch)
    return min(1 << max(MIN_LADDER_BATCH.bit_length() - 1,
                        (n - 1).bit_length()), int(max_batch))


def ladder_floor(n: int, max_batch: int) -> int:
    """Largest ladder step <= n (never below the smallest step): the
    admission-cap quantizer — a cap that is itself a ladder member can
    only ever produce pre-warmed dispatch shapes, whatever batch sizes
    the service model's evolving estimates suggest."""
    best = MIN_LADDER_BATCH
    for b in batch_ladder(max_batch):
        if b <= n:
            best = b
        else:
            break
    return best


class ServiceModel:
    """EWMA service-time estimate per batch-size bucket.

    Unobserved buckets fall back to the nearest observed bucket (the
    service curve is RPC-floor-flat for small batches and near-linear
    for large ones, so nearest-bucket is conservative in both regimes);
    a fully cold model uses ``base + per_packet * n`` seeds."""

    def __init__(self, default_base_s: float = 1e-3,
                 default_per_packet_s: float = 1e-6,
                 alpha: float = 0.3) -> None:
        self._base = float(default_base_s)
        self._per_packet = float(default_per_packet_s)
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._est: Dict[int, float] = {}

    def observe(self, bucket: int, dt_s: float) -> None:
        if dt_s <= 0:
            return
        b = int(bucket)
        with self._lock:
            prev = self._est.get(b)
            self._est[b] = (
                dt_s if prev is None
                else prev + self._alpha * (dt_s - prev)
            )

    def estimate(self, bucket: int) -> float:
        b = int(bucket)
        with self._lock:
            if not self._est:
                return self._base + self._per_packet * b
            got = self._est.get(b)
            if got is not None:
                return got
            nearest = min(self._est, key=lambda k: abs(k.bit_length()
                                                       - b.bit_length()))
            return self._est[nearest]

    def snapshot(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._est)


class SchedulerStats:
    """Thread-safe scheduler observability, exported through the metrics
    registry's counter-provider protocol (Registry.register_counters):
    admitted packets, dispatched batches, the achieved batch-size
    histogram (per ladder bucket), deadline misses, mesh spills, and the
    instantaneous queue depth."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.admitted_total = 0
        self.batches_total = 0
        self.miss_total = 0
        self.completed_total = 0
        self.spilled_batches_total = 0
        self.queue_depth = 0
        self.batch_hist: Dict[int, int] = {}

    def set_queue_depth(self, n: int) -> None:
        with self._lock:
            self.queue_depth = int(n)

    def note_admit(self, n: int, bucket: int, spilled: bool = False) -> None:
        with self._lock:
            self.admitted_total += int(n)
            self.batches_total += 1
            self.batch_hist[int(bucket)] = (
                self.batch_hist.get(int(bucket), 0) + 1
            )
            if spilled:
                self.spilled_batches_total += 1

    def note_complete(self, n: int, misses: int) -> None:
        with self._lock:
            self.completed_total += int(n)
            self.miss_total += int(misses)

    def counter_values(self) -> Dict[str, int]:
        """Prometheus counter sources, rendered by the metrics registry
        as ingressnodefirewall_node_scheduler_* (queue depth is an
        instantaneous gauge riding the same channel)."""
        with self._lock:
            out = {
                "scheduler_admitted_packets_total": self.admitted_total,
                "scheduler_batches_total": self.batches_total,
                "scheduler_deadline_miss_total": self.miss_total,
                "scheduler_completed_packets_total": self.completed_total,
                "scheduler_spilled_batches_total": self.spilled_batches_total,
                "scheduler_queue_depth": self.queue_depth,
            }
            for b, c in sorted(self.batch_hist.items()):
                out[f"scheduler_batch_size_{b}_total"] = c
            return out

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "admitted": self.admitted_total,
                "batches": self.batches_total,
                "misses": self.miss_total,
                "completed": self.completed_total,
                "spilled_batches": self.spilled_batches_total,
                "queue_depth": self.queue_depth,
                "batch_hist": dict(self.batch_hist),
            }


class WireStatsCounters:
    """Adapter exposing a classifier's per-format H2D accounting
    (``TpuClassifier.wire_stats()``) as metrics-registry counters:
    ingressnodefirewall_node_wire_<fmt>_{packets,bytes}_total.  Takes a
    zero-arg getter (not the classifier) so the provider survives table
    reloads and backend swaps; classifiers without wire_stats (the CPU
    reference) render nothing."""

    def __init__(self, clf_getter: Callable[[], object]) -> None:
        self._get = clf_getter

    def counter_values(self) -> Dict[str, int]:
        clf = self._get()
        ws = getattr(clf, "wire_stats", None)
        if clf is None or ws is None:
            return {}
        out: Dict[str, int] = {}
        for fmt, (pkts, nbytes) in sorted(ws().items()):
            out[f"wire_{fmt}_packets_total"] = int(pkts)
            out[f"wire_{fmt}_bytes_total"] = int(nbytes)
        return out


class AdmitDecision(NamedTuple):
    n: int                  # packets to admit now (0 = keep waiting)
    wait_s: Optional[float]  # max time to wait before re-deciding


class DeadlinePolicy:
    """Admit-by-deadline batch coalescing.

    ``admit`` is called with the queue state and the number of batches
    currently in the dispatch pipeline; it returns how many packets to
    admit NOW (0 = wait up to ``wait_s`` for the batch to grow).  Rules,
    in order:

    1. queue >= max_admit: ship a full admission (overload — coalescing
       can only help, the deadline is already the queue's problem).
    2. pipeline has a free slot (in_flight < busy_depth): ship whatever
       is queued immediately — work-conserving, the device must never
       idle while packets wait (the "continuous" in continuous
       batching).
    3. otherwise the oldest packet's remaining slack is
       deadline - wait - est_service(bucket) - margin: positive slack
       means waiting grows the batch for free (largest-batch-that-meets-
       deadline); exhausted slack ships the queue as-is.
    """

    def __init__(self, deadline_s: float, max_admit: int,
                 service: Optional[ServiceModel] = None,
                 margin_frac: float = 0.1, busy_depth: int = 2) -> None:
        if deadline_s <= 0:
            raise ValueError(f"deadline must be positive, got {deadline_s}")
        if max_admit < 1:
            raise ValueError(f"max_admit must be >= 1, got {max_admit}")
        self.deadline_s = float(deadline_s)
        self.max_admit = int(max_admit)
        self.service = service if service is not None else ServiceModel()
        self.margin_s = float(margin_frac) * self.deadline_s
        self.busy_depth = max(1, int(busy_depth))

    def admit(self, now: float, queue_len: int, oldest_ts: float,
              in_flight: int, eof: bool = False) -> AdmitDecision:
        if queue_len <= 0:
            return AdmitDecision(0, None)
        if queue_len >= self.max_admit:
            return AdmitDecision(self.max_admit, 0.0)
        if in_flight < self.busy_depth or eof:
            return AdmitDecision(queue_len, 0.0)
        bucket = ladder_bucket(queue_len, self.max_admit)
        slack = (
            self.deadline_s - (now - oldest_ts)
            - self.service.estimate(bucket) - self.margin_s
        )
        if slack <= 0:
            return AdmitDecision(queue_len, 0.0)
        return AdmitDecision(0, slack)

    def service_cap(self) -> int:
        """Largest ladder batch whose estimated service time still fits
        inside the deadline budget — the replay-tick analogue of the
        admit rule, where every queued packet shares one arrival burst
        and batch size is the only latency lever.  Never below the
        smallest ladder step: a deadline tighter than one minimal
        dispatch cannot be met by starving the queue."""
        cap = MIN_LADDER_BATCH
        for b in batch_ladder(self.max_admit):
            if self.service.estimate(b) + self.margin_s <= self.deadline_s:
                cap = b
            else:
                break
        return cap


class FixedChunkPolicy:
    """The pre-scheduler baseline as a policy: dispatch only when the
    queue holds a full ``chunk`` (the daemon's historical fixed
    ``ingest_chunk`` behavior), flushing the remainder at end of stream.
    Exists so bench_slo can A/B the deadline scheduler against the exact
    semantics it replaced, in the same record."""

    def __init__(self, chunk: int) -> None:
        self.max_admit = int(chunk)
        self.deadline_s = float("inf")
        self.service = ServiceModel()

    def admit(self, now: float, queue_len: int, oldest_ts: float,
              in_flight: int, eof: bool = False) -> AdmitDecision:
        if queue_len >= self.max_admit:
            return AdmitDecision(self.max_admit, 0.0)
        if eof and queue_len > 0:
            return AdmitDecision(queue_len, 0.0)
        return AdmitDecision(0, None)

    def service_cap(self) -> int:
        return self.max_admit


# -- ladder pre-warm ---------------------------------------------------------


def _inert_wire(n: int, width: int) -> np.ndarray:
    """(n, width) KIND_OTHER wire rows: always PASS, no stats — the
    shape-only payload of the pre-warm dispatches."""
    w = np.zeros((n, width), np.uint32)
    w[:, 0] = KIND_OTHER
    return w


def prewarm_ladder(clf, ladder, include_depth_classes: bool = True,
                   service: Optional[ServiceModel] = None) -> int:
    """Run every wire shape the scheduler can emit through the
    production dispatch once, so jit specialization (and a tunneled
    deployment's per-executable first-dispatch cost) happens at startup
    instead of inside a serving-path latency budget.

    Covers, per ladder size: the v4-compact 4-word wire (the v4_only
    specialization) and the 7-word mixed-family wire, the latter across
    every depth-steering class of the current table generation
    including the declared full-depth class.  Classifiers
    without the packed wire contract (the CPU reference) are a no-op.
    Returns the number of dispatches; failures degrade to fewer warmed
    shapes, never to an exception — a cold shape costs one compile at
    serve time, exactly what this makes rare."""
    supports = getattr(clf, "supports_packed", None)
    if supports is None or not supports():
        return 0
    depth_keys: List[Optional[tuple]] = [None]
    if include_depth_classes:
        # every steering class of the CURRENT generation plus the
        # declared full-depth class (the fused-walk shape)
        shape_classes = getattr(clf, "serving_shape_classes", None)
        if shape_classes is not None:
            depth_keys += list(shape_classes())
    n_done = 0
    t0 = time.perf_counter()
    for bs in ladder:
        # the two wire shapes the pack path can emit: the v4-compact
        # 4-word wire (v4_only jobs) and the 7-word mixed/v6 wire; the
        # depth-steering jit variants specialize the 7-word v6 walk
        for width, v4_only in ((4, True), (7, False)):
            wire = _inert_wire(int(bs), width)
            for depth in (depth_keys if width == 7 else [None]):
                try:
                    if hasattr(clf, "prepare_packed"):
                        pending = clf.classify_prepared(
                            clf.prepare_packed(wire, v4_only, depth=depth),
                            apply_stats=False,
                        )
                    else:
                        pending = clf.classify_async_packed(
                            wire, v4_only, apply_stats=False, depth=depth,
                        )
                    pending.result()
                    n_done += 1
                except Exception as e:  # degrade, never refuse
                    log.debug("prewarm skip @%d w%d v4=%s depth=%s: %s",
                              bs, width, v4_only, depth, e)
    warm_flow = getattr(clf, "warm_flow_ladder", None)
    if warm_flow is not None:
        # stateful flow tier: pre-compile the probe/insert executables
        # for every ladder shape too, so the warm flow lifecycle (probe,
        # miss fall-through, batch insert, age) is compile-free on the
        # serving path — the same zero-recompile contract as the
        # classify ladder above (also covers the pow2 miss buckets,
        # which are a subset of the ladder shapes).
        try:
            n_done += int(warm_flow([int(b) for b in ladder]) or 0)
        except Exception as e:  # degrade, never refuse
            log.debug("flow prewarm skipped: %s", e)
    warm_tel = getattr(clf, "warm_telemetry_ladder", None)
    if warm_tel is not None:
        # telemetry plane (ISSUE-13): the ladder loop above warmed the
        # resident fused sketch variants through the production
        # dispatch; this compiles the classic follow-on sketch-update
        # launch for every ladder shape too, so telemetry never costs a
        # serving-path compile in either dispatch mode
        try:
            n_done += int(warm_tel([int(b) for b in ladder]) or 0)
        except Exception as e:  # degrade, never refuse
            log.debug("telemetry prewarm skipped: %s", e)
    warm_ml = getattr(clf, "warm_mlscore_ladder", None)
    if warm_ml is not None:
        # anomaly scoring (ISSUE-14): the ladder loop above warmed the
        # resident fused score variants through the production
        # dispatch; this compiles the classic follow-on score-update
        # launch for every ladder shape too, so scoring never costs a
        # serving-path compile in either dispatch mode
        try:
            n_done += int(warm_ml([int(b) for b in ladder]) or 0)
        except Exception as e:  # degrade, never refuse
            log.debug("mlscore prewarm skipped: %s", e)
    mark_resident = getattr(clf, "mark_resident_warm", None)
    if mark_resident is not None:
        # resident-pool-aware prewarm (ISSUE-12): the ladder loop above
        # already compiled every resident fused program and allocated
        # the per-rung pool state (zero columns, epoch seed, table
        # context) through the production dispatch; freeze the pool's
        # allocation baseline HERE so any later pool allocation is, by
        # definition, a serving-path allocation — the zero-alloc
        # steady-state gate bench_resident asserts
        try:
            mark_resident()
        except Exception as e:  # degrade, never refuse
            log.debug("resident warm mark skipped: %s", e)
    if service is not None:
        # seed the admission policy's service model with a COMPILE-FREE
        # timing sample per ladder step (the shapes are warm now), so
        # the first real admissions size against measured service times
        # instead of the cold-model default
        for bs in ladder:
            wire = _inert_wire(int(bs), 4)
            try:
                t1 = time.perf_counter()
                if hasattr(clf, "prepare_packed"):
                    clf.classify_prepared(
                        clf.prepare_packed(wire, True), apply_stats=False
                    ).result()
                else:
                    clf.classify_async_packed(
                        wire, True, apply_stats=False
                    ).result()
                service.observe(int(bs), time.perf_counter() - t1)
            except Exception:
                pass
    log.info("ladder prewarm: %d dispatches over %d shapes in %.1fs",
             n_done, len(ladder), time.perf_counter() - t0)
    return n_done


def data_parallel_width(clf) -> int:
    """How many ways a classifier spreads one wire batch along the
    "data" axis (``Classifier.data_shards``: MeshTpuClassifier's shard
    count, 1 single-chip/CPU) — the multiplier on the scheduler's
    per-chip admission budget."""
    return max(1, int(getattr(clf, "data_shards", 1) or 1))


# -- the continuous serving loop ---------------------------------------------


class ServeResult(NamedTuple):
    results: np.ndarray         # (n,) uint32 packed verdicts, input order
    xdp: np.ndarray             # (n,) int32 XDP actions
    latency_s: np.ndarray       # (n,) float64 completion - scheduled arrival
    batch_sizes: np.ndarray     # admitted (unpadded) size per dispatch
    stats: SchedulerStats


class ContinuousScheduler:
    """Open-loop serving harness: drive a packet stream with scheduled
    arrival times through a classifier under a coalescing policy, with
    double-buffered staging (prepare_packed ping-pong) and optional mesh
    spillover.  The daemon's ingest tick embeds the same policy; this
    class is the standalone loop the SLO bench and the tests drive."""

    def __init__(
        self,
        clf,
        policy,
        chip_budget: Optional[int] = None,
        spill_clf=None,
        pipeline_depth: int = 4,
        stage_depth: int = 2,
        ring=None,
        stats: Optional[SchedulerStats] = None,
        clock: Callable[[], float] = time.monotonic,
        txn_batcher=None,
        txn_flush: Optional[Callable] = None,
        tracer=None,
        stripe=None,
    ) -> None:
        self.clf = clf
        #: per-device admission striping (ISSUE-16,
        #: backend.mesh.DeviceStripe): when given, every PRIMARY job
        #: (not spill, not oversized-split) round-robins across the
        #: stripe's pinned classifiers — k chips run k independent
        #: overlapped pipelines; spill and tenant jobs keep their
        #: explicit targets
        self.stripe = stripe
        self.policy = policy
        self.spill_clf = spill_clf
        #: update-storm interleaving (infw.txn): when a TxnBatcher and a
        #: flush callable ``txn_flush(items, reason)`` (items =
        #: TxnBatcher.drain()'s (op, enqueue_ts) pairs) are given, the
        #: serve loop checks the batcher's bounded-staleness policy each
        #: iteration and runs a tripped flush on its own thread while it
        #: OCCUPIES A PIPELINE SLOT — admissions keep flowing (classify
        #: dispatches continue against the old generation until the
        #: swap), but the pipeline never overcommits device work while
        #: a table patch is in flight.
        self.txn_batcher = txn_batcher
        self.txn_flush = txn_flush
        #: per-chip admission budget: a coalesced batch beyond it spills
        #: to the mesh target (sharded over "data") or, with no spill
        #: target, splits into per-budget jobs on the primary
        self.chip_budget = int(
            chip_budget if chip_budget is not None else policy.max_admit
        )
        self.pipeline_depth = max(1, int(pipeline_depth))
        #: how many admissions may be host-packed with their H2D copy
        #: started ahead of the launch window (the PR-2 double-buffer
        #: bound, 2 = classic ping-pong)
        self.stage_depth = max(1, int(stage_depth))
        self.ring = ring
        self.stats = stats if stats is not None else SchedulerStats()
        self._clock = clock
        #: serving-path span tracer (obs.telemetry.SpanTracer): when
        #: given, every admitted job charges pack / dispatch /
        #: materialize / drain spans to the shared histograms (the
        #: daemon's ingest tick charges ingest/pack the same way)
        self.tracer = tracer

    # -- dispatch plumbing ---------------------------------------------------

    def _dispatch(self, clf, batch: PacketBatch, idx: np.ndarray,
                  bucket: int, tenant_of: Optional[np.ndarray] = None):
        """One admitted job through the production path: fused subset
        pack + ladder padding + (prepare_packed | classify_async_packed |
        classify_async), matching the daemon's prepare/launch halves.

        ``tenant_of`` (tenant-tagged admissions, the multi-tenant arena
        path): per-packet tenant ids aligned with ``batch`` — when the
        classifier serves the arena contract, ONE admitted job carries
        mixed-tenant traffic and the tenant column steers each packet's
        slab in-kernel; padding lanes get tenant -1 (UNDEF)."""
        if tenant_of is not None and hasattr(
            clf, "classify_async_packed_tenant"
        ):
            sub = np.ascontiguousarray(idx, np.int64)
            wire, _v4 = batch.pack_wire_subset(sub)
            tags = np.ascontiguousarray(tenant_of[sub], np.int32)
            pad = bucket - wire.shape[0]
            if pad > 0:
                padrows = np.zeros((pad, wire.shape[1]), np.uint32)
                padrows[:, 0] = KIND_OTHER
                wire = np.concatenate([wire, padrows])
                tags = np.concatenate([tags, np.full(pad, -1, np.int32)])
            return lambda: clf.classify_async_packed_tenant(
                wire, tags, apply_stats=False
            )
        supports = getattr(clf, "supports_packed", None)
        if supports is not None and supports():
            wire, v4_only = batch.pack_wire_subset(
                np.ascontiguousarray(idx, np.int64)
            )
            pad = bucket - wire.shape[0]
            if pad > 0:
                padrows = np.zeros((pad, wire.shape[1]), np.uint32)
                padrows[:, 0] = KIND_OTHER
                wire = np.concatenate([wire, padrows])
            if hasattr(clf, "prepare_packed"):
                plan = clf.prepare_packed(wire, v4_only)
                return lambda: clf.classify_prepared(plan, apply_stats=False)
            return lambda: clf.classify_async_packed(
                wire, v4_only, apply_stats=False
            )
        merged = batch.take(
            np.ascontiguousarray(idx, np.int64)
        ).pad_to(bucket)
        return lambda: clf.classify_async(merged, apply_stats=False)

    def _emit_miss(self, n_miss: int, n: int, worst_s: float,
                   deadline_s: float) -> None:
        self.stats.note_complete(0, n_miss)
        if self.ring is not None and n_miss:
            from .obs.events import DeadlineMissRecord

            self.ring.push(DeadlineMissRecord(
                n_miss=int(n_miss), batch=int(n),
                worst_us=float(worst_s * 1e6),
                deadline_us=float(deadline_s * 1e6),
            ))

    # -- the loop ------------------------------------------------------------

    def serve(self, batch: PacketBatch, arrival_offsets_s: np.ndarray,
              anchor: Optional[float] = None,
              tenant_of: Optional[np.ndarray] = None) -> ServeResult:
        """Classify ``batch`` as an open-loop arrival stream: packet i
        becomes eligible at ``anchor + arrival_offsets_s[i]`` (anchor
        defaults to now).  Blocks until every packet's verdict is
        host-resident; per-packet latency is completion minus SCHEDULED
        arrival (coordinated-omission-safe).  ``tenant_of`` tags each
        packet with its tenant id for arena-backed classifiers — one
        admission then dispatches ONE mixed-tenant batch instead of a
        per-tenant job fan-out."""
        n = len(batch)
        if tenant_of is not None:
            tenant_of = np.ascontiguousarray(tenant_of, np.int32)
            if tenant_of.shape != (n,):
                raise ValueError(
                    f"tenant_of shape {tenant_of.shape} != ({n},)"
                )
            # refusing beats silently classifying every tenant against
            # one table: a non-arena backend would drop the tags on the
            # floor and break cross-tenant isolation with no signal
            for target, label in ((self.clf, "classifier"),
                                  (self.spill_clf, "spill classifier")):
                if target is not None and not hasattr(
                    target, "classify_async_packed_tenant"
                ):
                    raise ValueError(
                        f"tenant_of given but the {label} does not serve "
                        "the tenant contract (classify_async_packed_tenant)"
                    )
        offs = np.asarray(arrival_offsets_s, np.float64)
        if offs.shape != (n,):
            raise ValueError(
                f"arrival offsets shape {offs.shape} != ({n},)"
            )
        order = np.argsort(offs, kind="stable")
        t0 = self._clock() if anchor is None else float(anchor)
        arrive = t0 + offs
        results = np.zeros(n, np.uint32)
        xdp = np.full(n, 2, np.int32)
        done_ts = np.zeros(n, np.float64)
        batch_sizes: List[int] = []

        queue: deque = deque()   # (packet position, arrival ts)
        staged: deque = deque()  # admitted jobs not yet launched
        pos = 0
        deadline_s = getattr(self.policy, "deadline_s", float("inf"))
        spill_width = (
            data_parallel_width(self.spill_clf)
            if self.spill_clf is not None else 1
        )
        # one coalescing DECISION may exceed the per-chip budget either
        # way: with a spill target it ships as one mesh dispatch sharded
        # over "data" (so the cap scales by the width); without one the
        # admission is split into per-budget jobs below — the policy's
        # own max_admit is the only decision-level cap
        max_admit_now = (
            self.chip_budget * max(spill_width, 1)
            if self.spill_clf is not None else self.policy.max_admit
        )

        # Completion runs on its own thread POOL (one drainer per
        # pipeline slot): a launched job's result is materialized (and
        # its packets' completion stamped) the moment the device
        # finishes — a single FIFO drainer would stamp a fast job queued
        # behind a slow one (e.g. a primary-chip job behind a spilled
        # mesh job) at the slow job's finish time, manufacturing false
        # deadline misses and poisoning the service model; lazy draining
        # in the admission loop would be worse still.
        cv = threading.Condition()
        pending_q: deque = deque()
        outstanding = [0]
        stop_flag = [False]
        errs: List[BaseException] = []

        def drain_loop() -> None:
            while True:
                with cv:
                    while not pending_q and not stop_flag[0]:
                        cv.wait()
                    if not pending_q:
                        return
                    job, pending = pending_q.popleft()
                try:
                    tr = job.get("trace")
                    t_mat0 = time.perf_counter()
                    out = pending.result()
                    if tr is not None:
                        tr.add("materialize", time.perf_counter() - t_mat0)
                    t_done = self._clock()
                    idx = job["idx"]
                    k = len(idx)
                    results[idx] = np.asarray(out.results)[:k]
                    xdp[idx] = np.asarray(out.xdp)[:k]
                    done_ts[idx] = t_done
                    self.policy.service.observe(
                        job["bucket"], t_done - job["t_launch"]
                    )
                    lat = t_done - arrive[idx]
                    n_miss = int((lat > deadline_s).sum())
                    self.stats.note_complete(k, 0)
                    self._emit_miss(n_miss, k, float(lat.max()), deadline_s)
                    if tr is not None:
                        tr.mark("drain")
                        self.tracer.finish(tr)
                except BaseException as e:  # surfaced by serve() at exit
                    errs.append(e)
                with cv:
                    outstanding[0] -= 1
                    cv.notify_all()

        kinds_all = np.asarray(batch.kind)

        def admit_job(count: int) -> None:
            take = [queue.popleft() for _ in range(count)]
            idx = np.asarray([t[0] for t in take], np.int64)
            # family-homogeneous jobs, like the daemon's ingest tick: the
            # v4 share ships compact and walks the truncated trie instead
            # of riding the v6 sub-batch's full-depth walk
            k = kinds_all[idx]
            for g in (idx[k != KIND_IPV6], idx[k == KIND_IPV6]):
                if len(g) == 0:
                    continue
                if len(g) > self.chip_budget:
                    if self.spill_clf is not None:
                        # overflow path: one mesh dispatch, sharded over
                        # the "data" axis
                        _push_job(self.spill_clf, g, True)
                        continue
                    # single-chip pool: split the oversized admission
                    # into per-budget jobs (degrade, never refuse)
                    for s in range(0, len(g), self.chip_budget):
                        _push_job(self.clf, g[s: s + self.chip_budget],
                                  False)
                    continue
                _push_job(self.clf, g, False)

        def _push_job(target, idx, spilled: bool) -> None:
            if target is self.clf and self.stripe is not None:
                # device round-robin: each admission lands whole on one
                # chip of the stripe (its own flow state and donated
                # epoch chain) — striping scales admissions/s, the mesh
                # spill target scales one admission
                target = self.stripe.next_classifier()
            cap = self.policy.max_admit * max(spill_width, 1)
            bucket = ladder_bucket(len(idx), max(cap, len(idx)))
            self.stats.note_admit(len(idx), bucket, spilled=spilled)
            batch_sizes.append(len(idx))
            trace = (
                self.tracer.begin(len(idx))
                if self.tracer is not None else None
            )
            thunk = self._dispatch(target, batch, idx, bucket,
                                   tenant_of=tenant_of)
            if trace is not None:
                # subset pack + ladder pad + prepare_packed (H2D start)
                trace.mark("pack")
            # the bucket travels with the job: the drain thread must
            # feed the service observation to the bucket the job was
            # DISPATCHED at, not a recomputation that forgets spill
            # scaling
            staged.append((
                {"idx": idx, "bucket": bucket, "trace": trace}, thunk,
            ))

        def launch_ready() -> None:
            while staged:
                with cv:
                    if outstanding[0] >= self.pipeline_depth:
                        return
                job, thunk = staged.popleft()
                job["t_launch"] = self._clock()
                t_disp0 = time.perf_counter()
                pending = thunk()
                tr = job.get("trace")
                if tr is not None:
                    tr.add("dispatch", time.perf_counter() - t_disp0)
                with cv:
                    pending_q.append((job, pending))
                    outstanding[0] += 1
                    cv.notify_all()

        flush_busy = [False]

        def maybe_flush_txn(now: float) -> None:
            """Bounded-staleness edit flush, interleaved with serving:
            when the batcher's deadline/batch threshold trips, the flush
            runs on its own thread while holding ONE pipeline slot — the
            admission loop keeps coalescing and dispatching (in-flight
            classifies finish on the old generation; the swap is a
            reference assignment), but device work never overcommits
            while the patch is in flight."""
            if (
                self.txn_batcher is None or self.txn_flush is None
                or flush_busy[0]
            ):
                return
            reason = self.txn_batcher.should_flush(now)
            if reason is None:
                return
            items = self.txn_batcher.drain()
            if not items:
                return
            flush_busy[0] = True
            with cv:
                outstanding[0] += 1  # the flush occupies a pipeline slot

            def run_flush() -> None:
                try:
                    self.txn_flush(items, reason)
                except BaseException as e:  # surfaced by serve() at exit
                    errs.append(e)
                finally:
                    with cv:
                        outstanding[0] -= 1
                        cv.notify_all()
                    flush_busy[0] = False

            spawn(run_flush, name="infw-txn-flush")

        drainers = [
            spawn(drain_loop, name=f"infw-sched-drain-{i}", start=False)
            for i in range(self.pipeline_depth)
        ]
        for t in drainers:
            t.start()
        try:
            while True:
                now = self._clock()
                maybe_flush_txn(now)
                while pos < n and arrive[order[pos]] <= now:
                    p = int(order[pos])
                    queue.append((p, arrive[p]))
                    pos += 1
                with cv:
                    infl = outstanding[0]
                self.stats.set_queue_depth(len(queue))
                eof = pos >= n
                if eof and not queue and not staged and infl == 0:
                    break
                dec = self.policy.admit(
                    now, len(queue), queue[0][1] if queue else now,
                    infl + len(staged), eof=eof,
                )
                if dec.n > 0 and len(staged) < self.stage_depth:
                    # ping-pong staging bound: at most stage_depth
                    # admissions have their host pack + H2D copy started
                    # ahead of the launch window — overload coalesces in
                    # the arrival queue, not in prepared device buffers
                    admit_job(min(dec.n, len(queue), max_admit_now))
                    launch_ready()
                    continue
                launch_ready()
                # wait for the next event: an arrival, the policy's
                # re-decision point, the edit batcher's staleness
                # deadline, or a completion (cv notify)
                now2 = self._clock()
                next_arrival = (
                    arrive[order[pos]] - now2 if pos < n else float("inf")
                )
                wait = min(
                    next_arrival,
                    dec.wait_s if dec.wait_s is not None else float("inf"),
                )
                if (
                    self.txn_batcher is not None and not flush_busy[0]
                    and len(self.txn_batcher)
                ):
                    # the staleness budget bounds the sleep too — a 2 ms
                    # deadline must not ride the 50 ms poll cap through
                    # an arrival gap
                    wait = min(wait, max(
                        self.txn_batcher.staleness_s
                        - self.txn_batcher.oldest_age(now2), 0.0,
                    ))
                with cv:
                    cv.wait(min(wait, 0.05) if wait > 0 else 0.001)
        finally:
            with cv:
                stop_flag[0] = True
                cv.notify_all()
            for t in drainers:
                t.join()
        if errs:
            raise errs[0]
        self.stats.set_queue_depth(0)
        return ServeResult(
            results=results, xdp=xdp, latency_s=done_ts - arrive,
            batch_sizes=np.asarray(batch_sizes, np.int64),
            stats=self.stats,
        )
