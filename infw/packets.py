"""Batched packet representation.

The reference's per-packet inputs are the XDP context fields consumed by
ingress_node_firewall_main and ip_extract_l4info
(/root/reference/bpf/ingress_node_firewall_kernel.c:95-174,412-439):
ethertype, source IP, L4 protocol, destination port or ICMP type/code,
ingress ifindex and packet length.  The TPU dataplane consumes those same
fields as a struct-of-arrays batch; header parsing from raw bytes happens
host-side (infw.obs.pcap) or packets are generated synthetically.

Field conventions:
- ``kind``: KIND_* code for the ethertype switch outcome (constants.py);
- ``l4_ok``: 0 if ip_extract_l4info would have failed (unsupported L4
  protocol or truncated header) -> lookup returns SET_ACTION(UNDEF);
- ``ip_words``: (B, 4) uint32 big-endian words of the 16-byte source-IP key
  data (IPv4 packets occupy word 0, rest zero — kernel.c:206-212);
- ``dst_port`` is host byte order (the kernel compares bpf_ntohs(dstPort));
- ``pkt_len`` is the full frame length (bpf_xdp_get_buff_len).
"""
from __future__ import annotations

import os as _os
import subprocess as _subprocess
import zlib as _zlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .constants import IPPROTO_ICMP, IPPROTO_ICMPV6, KIND_IPV4, KIND_IPV6
from .netutil import ip_str_to_words

_native_pack_unavailable = False


@dataclass
class PacketBatch:
    kind: np.ndarray       # (B,) int32
    l4_ok: np.ndarray      # (B,) int32 (0/1)
    ifindex: np.ndarray    # (B,) int32
    ip_words: np.ndarray   # (B, 4) uint32
    proto: np.ndarray      # (B,) int32
    dst_port: np.ndarray   # (B,) int32
    icmp_type: np.ndarray  # (B,) int32
    icmp_code: np.ndarray  # (B,) int32
    pkt_len: np.ndarray    # (B,) int32
    #: optional (B,) int32 TCP flag bits (jaxpath.TCP_*) consumed by the
    #: stateful flow tier's SYN/EST/FIN/RST state machine; None (sources
    #: that carry no flags) degrades the TCP model to established-on-
    #: first-packet.  Never crosses the classify wire formats — the
    #: verdict does not depend on it.
    tcp_flags: Optional[np.ndarray] = None
    #: optional (B, L) uint8 payload-prefix column (first 64/128 bytes,
    #: ISSUE-19) consumed by the payload-matching tier, plus its (B,)
    #: int32 valid-byte counts (bytes past ``payload_len[i]`` are
    #: padding the matcher masks off).  Rides BESIDE the packed wire —
    #: header classification never reads it, so header-only sources
    #: (None) skip the tier without a shape change.
    payload: Optional[np.ndarray] = None
    payload_len: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return int(self.kind.shape[0])

    @property
    def size(self) -> int:
        return len(self)

    def slice(self, start: int, stop: int) -> "PacketBatch":
        return PacketBatch(
            **{
                f: getattr(self, f)[start:stop]
                for f in (
                    "kind l4_ok ifindex ip_words proto dst_port "
                    "icmp_type icmp_code pkt_len".split()
                )
            },
            tcp_flags=(
                None if self.tcp_flags is None
                else self.tcp_flags[start:stop]
            ),
            payload=(
                None if self.payload is None else self.payload[start:stop]
            ),
            payload_len=(
                None if self.payload_len is None
                else self.payload_len[start:stop]
            ),
        )

    def take(self, idx: np.ndarray) -> "PacketBatch":
        """Arbitrary-index subset (used to regroup packets by family so
        each device chunk is depth-homogeneous)."""
        return PacketBatch(
            **{
                f: getattr(self, f)[idx]
                for f in (
                    "kind l4_ok ifindex ip_words proto dst_port "
                    "icmp_type icmp_code pkt_len".split()
                )
            },
            tcp_flags=(
                None if self.tcp_flags is None else self.tcp_flags[idx]
            ),
            payload=(
                None if self.payload is None else self.payload[idx]
            ),
            payload_len=(
                None if self.payload_len is None else self.payload_len[idx]
            ),
        )

    def pack_wire(self) -> np.ndarray:
        """Pack into the (B, 7) uint32 device wire format — 28B/packet
        instead of 9 separate int32 arrays (48B/packet).  The host→device
        link (PCIe in production, the tunnel here) is the streaming
        bottleneck, so the descriptor is packed like a NIC ring entry:

          w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | icmpType(8)<<11
              | icmpCode(8)<<19 | pktLenHi(5)<<27
          w1: dstPort(16) | pktLenLo(16)<<16
          w2: ifindex (full u32)
          w3..w6: ip_words

        pktLen carries 21 bits (clamp at 2 MiB - 1): jumbo frames are
        < 10K and even BIG-TCP GRO/TSO aggregates cap at 512 KiB, so no
        real capture frame clips and byte statistics stay exact.

        Device-side inverse: kernels.jaxpath.unpack_wire (fused into the
        classify jit, so unpacking costs no extra HBM round trip)."""
        out = np.empty((len(self), 7), np.uint32)
        self._pack_wire_header(out)
        out[:, 3:7] = self.ip_words.astype(np.uint32)
        return out

    def _pack_wire_header(self, out: np.ndarray) -> None:
        """w0..w2 of the wire layout (shared by the 7- and 4-word formats)."""
        plen = np.clip(self.pkt_len, 0, 0x1FFFFF).astype(np.uint32)
        out[:, 0] = (
            (self.kind.astype(np.uint32) & 3)
            | ((self.l4_ok.astype(np.uint32) & 1) << 2)
            | ((self.proto.astype(np.uint32) & 0xFF) << 3)
            | ((self.icmp_type.astype(np.uint32) & 0xFF) << 11)
            | ((self.icmp_code.astype(np.uint32) & 0xFF) << 19)
            | ((plen >> 16) << 27)
        )
        out[:, 1] = (self.dst_port.astype(np.uint32) & 0xFFFF) | (
            (plen & 0xFFFF) << 16
        )
        out[:, 2] = self.ifindex.astype(np.uint32)

    def is_v4_compactable(self) -> bool:
        """True when the batch can take the 4-word wire format: no IPv6
        packets and no nonzero high IP words (the host parser guarantees
        zeros for v4/malformed/other frames; synthetic batches may not)."""
        return not bool(
            (np.asarray(self.kind) == KIND_IPV6).any()
        ) and not bool(np.asarray(self.ip_words)[:, 1:].any())

    def pack_wire_v4(self) -> np.ndarray:
        """The family-compact (B, 4) uint32 wire format — 16B/packet for
        v4-only chunks (the daemon's ingest regroups by family, so the
        majority family of real traffic takes this path): w0..w2 as
        pack_wire, w3 = IP word 0.  Caller contract: is_v4_compactable().
        Device-side inverse: unpack_wire (width-discriminated)."""
        out = np.empty((len(self), 4), np.uint32)
        self._pack_wire_header(out)
        out[:, 3] = self.ip_words[:, 0].astype(np.uint32)
        return out

    def pack_wire_subset(self, idx: np.ndarray) -> Tuple[np.ndarray, bool]:
        """take(idx) + pack_wire[_v4] fused into one pass -> (wire,
        v4_only).  Dispatches to the native C++ kernel when available
        (the daemon's per-chunk hot path: copying 9 SoA arrays per chunk
        just to re-pack them doubles the host cost); NumPy fallback is
        the composed slow path, differentially tested against it."""
        global _native_pack_unavailable
        idx = np.ascontiguousarray(idx, np.int64)
        if not _native_pack_unavailable:
            try:
                return self._pack_wire_subset_native(idx)
            except (OSError, ImportError, AttributeError, AssertionError,
                    _subprocess.SubprocessError):
                _native_pack_unavailable = True
        sub = self.take(idx)
        compact = sub.is_v4_compactable()
        wire = sub.pack_wire_v4() if compact else sub.pack_wire()
        v4_only = not bool((np.asarray(sub.kind) == KIND_IPV6).any())
        return wire, v4_only

    def _pack_wire_subset_native(self, idx: np.ndarray) -> Tuple[np.ndarray, bool]:
        import ctypes

        from .backend.cpu_ref import load_library

        lib = load_library()
        n = len(idx)
        flat = np.empty(n * 7, np.uint32)
        c = lambda a, dt: np.ascontiguousarray(a, dt)
        p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
        kind = c(self.kind, np.int32)
        l4_ok = c(self.l4_ok, np.int32)
        ifindex = c(self.ifindex, np.int32)
        words = c(self.ip_words, np.uint32)
        proto = c(self.proto, np.int32)
        dst_port = c(self.dst_port, np.int32)
        icmp_type = c(self.icmp_type, np.int32)
        icmp_code = c(self.icmp_code, np.int32)
        pkt_len = c(self.pkt_len, np.int32)
        flags = lib.infw_pack_wire_subset(
            n, p(idx, ctypes.c_int64),
            p(kind, ctypes.c_int32), p(l4_ok, ctypes.c_int32),
            p(ifindex, ctypes.c_int32), p(words, ctypes.c_uint32),
            p(proto, ctypes.c_int32), p(dst_port, ctypes.c_int32),
            p(icmp_type, ctypes.c_int32), p(icmp_code, ctypes.c_int32),
            p(pkt_len, ctypes.c_int32),
            p(flat, ctypes.c_uint32), min(8, _os.cpu_count() or 1),
        )
        compact = bool(flags & 1)
        w = 4 if compact else 7
        return flat[: n * w].reshape(n, w), bool(flags & 2)

    def pad_to(self, n: int) -> "PacketBatch":
        """Pad with KIND_OTHER packets (always XDP_PASS, no stats) so batch
        shapes stay static under jit."""
        b = len(self)
        if b >= n:
            return self
        pad = n - b

        def _pad(a):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, widths, constant_values=3)  # KIND_OTHER / junk

        return PacketBatch(
            kind=_pad(self.kind),
            l4_ok=np.pad(self.l4_ok, (0, pad)),
            ifindex=np.pad(self.ifindex, (0, pad)),
            ip_words=np.pad(self.ip_words, ((0, pad), (0, 0))),
            proto=np.pad(self.proto, (0, pad)),
            dst_port=np.pad(self.dst_port, (0, pad)),
            icmp_type=np.pad(self.icmp_type, (0, pad)),
            icmp_code=np.pad(self.icmp_code, (0, pad)),
            pkt_len=np.pad(self.pkt_len, (0, pad)),
            tcp_flags=(
                None if self.tcp_flags is None
                else np.pad(self.tcp_flags, (0, pad))
            ),
            payload=(
                None if self.payload is None
                else np.pad(self.payload, ((0, pad), (0, 0)))
            ),
            payload_len=(
                None if self.payload_len is None
                else np.pad(self.payload_len, (0, pad))
            ),
        )


def make_batch(
    *,
    src: Sequence[str],
    proto: Sequence[int],
    ifindex: Sequence[int],
    dst_port: Optional[Sequence[int]] = None,
    icmp_type: Optional[Sequence[int]] = None,
    icmp_code: Optional[Sequence[int]] = None,
    pkt_len: Optional[Sequence[int]] = None,
    l4_ok: Optional[Sequence[int]] = None,
    kind: Optional[Sequence[int]] = None,
) -> PacketBatch:
    """Convenience constructor from parallel per-packet field lists; ``src``
    is a list of IP address strings and determines v4/v6 kind."""
    b = len(src)
    words = np.zeros((b, 4), np.uint32)
    kinds = np.zeros(b, np.int32)
    for i, addr in enumerate(src):
        w, is_v4 = ip_str_to_words(addr)
        words[i] = w
        kinds[i] = KIND_IPV4 if is_v4 else KIND_IPV6
    if kind is not None:
        kinds = np.asarray(kind, np.int32)

    def arr(x, default=0):
        if x is None:
            return np.full(b, default, np.int32)
        return np.asarray(x, np.int32)

    return PacketBatch(
        kind=kinds,
        l4_ok=arr(l4_ok, 1),
        ifindex=arr(ifindex),
        ip_words=words,
        proto=arr(proto),
        dst_port=arr(dst_port),
        icmp_type=arr(icmp_type),
        icmp_code=arr(icmp_code),
        pkt_len=arr(pkt_len, 64),
    )


def concat(batches: List[PacketBatch]) -> PacketBatch:
    flags = None
    if any(b.tcp_flags is not None for b in batches):
        flags = np.concatenate([
            b.tcp_flags if b.tcp_flags is not None
            else np.zeros(len(b), np.int32)
            for b in batches
        ])
    return PacketBatch(
        **{
            f: np.concatenate([getattr(b, f) for b in batches])
            for f in (
                "kind l4_ok ifindex ip_words proto dst_port "
                "icmp_type icmp_code pkt_len".split()
            )
        },
        tcp_flags=flags,
    )


def expand_wire_v4(w: np.ndarray) -> np.ndarray:
    """(n, 4) compact wire rows -> (n, 7): zero high IP words (the compact
    format's eligibility guarantee).  Lives next to pack_wire/pack_wire_v4
    so the 4-word/7-word correspondence has one owner; used when a merged
    ingest job mixes compact and full segments and must ship one width."""
    out = np.zeros((w.shape[0], 7), np.uint32)
    out[:, :4] = w
    return out


def _l4_word(w0: np.ndarray, w1: np.ndarray) -> np.ndarray:
    """The 16-bit l4 overlay shared by narrow_wire and wire8: dst_port
    for transport rows, type<<8|code for the family ICMPs — lossless for
    classification because the ordered scan never reads both
    (kernel.c:222-258)."""
    proto = (w0 >> 3) & 0xFF
    is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
    return np.where(
        is_icmp,
        ((w0 >> 11) & 0xFF) << 8 | ((w0 >> 19) & 0xFF),
        w1 & 0xFFFF,
    ).astype(np.uint32)


def _ifindex_dict(ifx: np.ndarray):
    """The ifindex dictionary shared by wire8 and the delta codec — ONE
    implementation of the device contract (<= 15 distinct interfaces per
    chunk, 16-slot ifmap padded with -1, 4-bit indexes) so the formats'
    eligibility can never desynchronize.  Returns (ifmap, ifdict) or
    None when the chunk exceeds the cap."""
    uniq = np.unique(ifx)
    if len(uniq) > 15:
        return None
    ifmap = np.full(16, -1, np.int32)
    ifmap[: len(uniq)] = uniq.astype(np.int64)
    return ifmap, np.searchsorted(uniq, ifx).astype(np.uint32)


def narrow_wire(w: np.ndarray):
    """(n, 4|7) wire -> the NARROW (n, 3|6) format, or None when the rows
    don't qualify.  Saves one word per packet (v4 16B -> 12B, v6 28B ->
    24B) on the H2D link — the replay bottleneck — by (a) folding the
    ifindex into w0 when every ifindex fits 16 bits, and (b) overlaying
    dst_port with the ICMP type/code in one 16-bit "l4 word", which is
    LOSSLESS for classification: the ordered scan reads dst_port only for
    transport protocols and the ICMP fields only for the family's ICMP
    protocol (kernel.c:222-258), never both, and the kernels' parse sets
    l4_ok=0 for any other protocol.  pkt_len must fit 16 bits (w0's
    high-bit stash must be clear) so byte statistics stay exact.

    Narrow layout:
      w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | ifindex(16)<<11
      w1: l4word(16) | pktLen(16)<<16
      w2..: ip word 0 (v4) / words 0..3 (v6)

    Device-side inverse: kernels.jaxpath.unpack_wire (width 3/6)."""
    w0 = w[:, 0]
    ifx = w[:, 2]
    if int(w0.size) == 0:
        return np.zeros((0, w.shape[1] - 1), np.uint32)
    if (w0 >> 27).any() or (ifx >> 16).any():
        return None  # pkt_len >= 64KiB or wide ifindex: keep the full form
    l4w = _l4_word(w0, w[:, 1])
    out = np.empty((w.shape[0], w.shape[1] - 1), np.uint32)
    out[:, 0] = (w0 & 0x7FF) | (ifx << 11)
    out[:, 1] = l4w | (w[:, 1] & 0xFFFF0000)  # pktLen low 16 stays in place
    out[:, 2:] = w[:, 3:]
    return out


def wire8(w: np.ndarray):
    """(n, 4) v4-compact wire -> the 8-BYTE format, or None when the rows
    don't qualify: (n, 2) uint32 rows plus the (16,) int32 ifindex
    dictionary the device decodes through.

    The byte diet beyond the 12B narrow form comes from two observations:
    (a) classification itself never reads pkt_len — it exists only for
    byte statistics, which the host can compute EXACTLY from the returned
    verdicts and its own pkt_len column (stats_from_results), so the
    length never needs to cross the link; (b) a chunk rarely spans more
    than a handful of interfaces, so a 4-bit dictionary index replaces
    the 16-bit ifindex (the bond-expansion world of interfaces.go:85-116
    still fits: 15 member links per chunk).

    Layout:  w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | ifdict(4)<<11 |
                 l4word(16)<<15          (l4word as in narrow_wire)
             w1: ip word 0
    Device-side inverse: kernels.jaxpath.unpack_wire8 (needs the dict).
    Qualifies only v4-compact chunks (ip words 1..3 zero — the caller's
    pack_wire_v4 contract)."""
    if w.shape[1] != 4:
        return None
    if w.shape[0] == 0:
        return np.zeros((0, 2), np.uint32), np.full(16, -1, np.int32)
    w0 = w[:, 0]
    d = _ifindex_dict(w[:, 2])
    if d is None:
        return None
    ifmap, ifdict = d
    l4w = _l4_word(w0, w[:, 1])
    out = np.empty((w.shape[0], 2), np.uint32)
    out[:, 0] = (w0 & 0x7FF) | (ifdict << 11) | (l4w << 15)
    out[:, 1] = w[:, 3]
    return out, ifmap


# --- delta+varint compressed wire (the sub-8B format) -----------------------
#
# The replay tier is host->device LINK bound (VERDICT round-5 weak #1: the
# end-to-end record sits at ~0.5 M pkts/s against a ~49 M/s
# device-attributable rate), so bytes-per-packet is the lever.  wire8
# reached 8 B by shedding pkt_len and dictionary-coding the ifindex; the
# delta format goes below it by exploiting the same locality that
# cache-aware forwarding tables exploit (PAPERS: cache-aware FIB
# structures): a chunk's IP words cluster under the table's prefixes, so
# SORTING the chunk by IP and shipping varint-coded deltas averages 2-3
# bytes where the raw word costs 4.  The sort permutation never crosses
# the link — the device classifies in sorted order and the HOST applies
# the inverse permutation to the returned verdicts (order is host-side
# bookkeeping, exactly like pkt_len).
#
# Layout (three sections, offsets fully determined by (n, dict_mode,
# fixed_w) — the "fixed-stride plan" the device decoder specializes on):
#   A: meta15 dictionary indexes — meta15 = kind(2) | l4_ok(1)<<2 |
#      proto(8)<<3 | ifdict(4)<<11, the sub-l4 bits of wire8's w0.  A
#      chunk rarely holds more than a handful of distinct (kind, proto,
#      iface) combinations, so: dict_mode 0 = single value, no section;
#      1 = <=16 values, two 4-bit indexes per byte; 2 = <=256 values,
#      one byte each.
#   B: l4 word (narrow_wire's port/ICMP overlay), 2 bytes LE per packet
#      (ports are uniform in practice — varint would usually cost 3).
#   C: sorted-IP deltas — LEB128 varints (7 bits per byte, bit 7 =
#      continuation), or a fixed 1/2/4-byte little-endian stride when
#      that costs no more (fixed_w > 0; enables the Pallas decode plan).
#      The first "delta" is the absolute first sorted IP word.
#
# Device-side inverse: kernels.wire_decode (XLA parallel varint decode /
# fixed-stride expand + cumsum).  Host-side inverse + fail-closed
# validation: decode_delta_host below (crc over the shipped bytes, strict
# varint structure checks) — the codec never guesses on corrupt input.

#: varint width thresholds: value v needs 1 + sum(v >= 2^(7k)) bytes
_VARINT_STEPS = tuple(np.uint64(1) << np.uint64(7 * k) for k in range(1, 5))


@dataclass
class DeltaWire:
    """One encoded chunk.  ``payload`` is what crosses the link (plus the
    tiny ``dict_vals``/``ifmap`` headers); ``perm`` stays host-side."""

    payload: np.ndarray    # (P,) uint8 — sections A | B | C
    dict_vals: np.ndarray  # (D,) uint32 meta15 dictionary, D >= 1
    ifmap: np.ndarray      # (16,) int32 wire8-style ifindex dictionary
    perm: np.ndarray       # (n,) int64 sort permutation (host-only)
    n: int
    dict_mode: int         # 0 = constant, 1 = 4-bit packed, 2 = u8
    fixed_w: int           # 0 = varint section C, else 1/2/4-byte stride
    crc: int               # crc32 over payload+dict_vals+ifmap

    @property
    def wire_bytes(self) -> int:
        return int(self.payload.nbytes)


def delta_section_offsets(n: int, dict_mode: int) -> Tuple[int, int]:
    """(offset of section B, offset of section C's start) — the static
    layout contract shared with the device decoder."""
    n_a = 0 if dict_mode == 0 else ((n + 1) // 2 if dict_mode == 1 else n)
    return n_a, n_a + 2 * n


def varint_encode(vals: np.ndarray) -> np.ndarray:
    """Vectorized LEB128 encode of uint64 values (< 2^35 — deltas are
    32-bit so at most 5 bytes each)."""
    v = np.ascontiguousarray(vals, np.uint64)
    nb = np.ones(len(v), np.int64)
    for step in _VARINT_STEPS:
        nb += v >= step
    ends = np.cumsum(nb)
    starts = ends - nb
    out = np.zeros(int(ends[-1]) if len(v) else 0, np.uint8)
    for k in range(5):
        m = nb > k
        if not m.any():
            break
        chunk = (v[m] >> np.uint64(7 * k)) & np.uint64(0x7F)
        cont = (nb[m] - 1 > k).astype(np.uint8) << 7
        out[starts[m] + k] = chunk.astype(np.uint8) | cont
    return out


def _delta_crc(payload: np.ndarray, dict_vals: np.ndarray,
               ifmap: np.ndarray) -> int:
    crc = _zlib.crc32(np.ascontiguousarray(payload, np.uint8).tobytes())
    crc = _zlib.crc32(np.ascontiguousarray(dict_vals, "<u4").tobytes(), crc)
    return _zlib.crc32(np.ascontiguousarray(ifmap, "<i4").tobytes(), crc)


#: sticky gate: once the native library fails to load/bind, stop
#: retrying per chunk (the pack-subset pattern)
_native_delta_unavailable = False


def _encode_delta_native(
    w: np.ndarray, max_bytes_per_pkt: Optional[float]
) -> Optional[DeltaWire]:
    """Native (C++) single-pass delta encode — byte-identical to the
    NumPy reference below (differentially tested); raises on library
    unavailability so the caller can fall back, returns None on the
    same non-qualification conditions."""
    import ctypes

    from .backend.cpu_ref import load_library

    lib = load_library()
    n = w.shape[0]
    wc = np.ascontiguousarray(w, np.uint32)
    payload = np.empty(8 * n, np.uint8)
    dict_vals = np.empty(256, np.uint32)
    ifmap = np.empty(16, np.int32)
    perm = np.empty(n, np.int64)
    meta = np.zeros(3, np.int32)
    p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
    total = lib.infw_encode_delta(
        n, p(wc, ctypes.c_uint32), p(payload, ctypes.c_uint8),
        p(dict_vals, ctypes.c_uint32), p(ifmap, ctypes.c_int32),
        p(perm, ctypes.c_int64), p(meta, ctypes.c_int32),
    )
    if total < 0:
        return None
    payload = payload[:total].copy()
    if max_bytes_per_pkt is not None and len(payload) >= max_bytes_per_pkt * n:
        return None
    dict_vals = dict_vals[: int(meta[0])].copy()
    return DeltaWire(
        payload=payload, dict_vals=dict_vals, ifmap=ifmap, perm=perm,
        n=n, dict_mode=int(meta[1]), fixed_w=int(meta[2]),
        crc=_delta_crc(payload, dict_vals, ifmap),
    )


def encode_delta_wire(
    w: np.ndarray, max_bytes_per_pkt: Optional[float] = None
) -> Optional[DeltaWire]:
    """(n, 4) v4-compact wire -> DeltaWire, or None when the chunk does
    not qualify (not v4-compact, >15 interfaces, >256 distinct meta15
    values, n == 0) or — with ``max_bytes_per_pkt`` set (the auto-codec
    gate) — when the compressed payload would not beat that budget.
    Qualification mirrors wire8: pkt_len never ships (host statistics),
    ifindex travels as a 4-bit dictionary.

    Dispatches to the native C++ single-pass encoder when available
    (ISSUE-12 part 4: host packing is the residual cost of the
    non-resident delta path once dispatch is one fused program — the
    sort + five vectorized sweeps below collapse into one pass); the
    NumPy body is the differentially-tested reference fallback."""
    if w.shape[1] != 4 or w.shape[0] == 0:
        return None
    global _native_delta_unavailable
    if not _native_delta_unavailable:
        try:
            return _encode_delta_native(w, max_bytes_per_pkt)
        except (OSError, ImportError, AttributeError, AssertionError,
                _subprocess.SubprocessError):
            _native_delta_unavailable = True
    n = w.shape[0]
    w0 = w[:, 0]
    d = _ifindex_dict(w[:, 2])
    if d is None:
        return None
    ifmap, ifdict = d
    meta15 = (w0 & 0x7FF) | (ifdict << 11)
    dict_vals, dict_idx = np.unique(meta15, return_inverse=True)
    if len(dict_vals) > 256:
        return None
    dict_mode = 0 if len(dict_vals) == 1 else (1 if len(dict_vals) <= 16 else 2)

    perm = np.argsort(w[:, 3], kind="stable").astype(np.int64)
    ip_sorted = w[perm, 3].astype(np.uint64)
    deltas = np.empty(n, np.uint64)
    deltas[0] = ip_sorted[0]
    np.subtract(ip_sorted[1:], ip_sorted[:-1], out=deltas[1:])
    var_c = varint_encode(deltas)
    # fixed-stride plan: when every delta fits w bytes and the fixed
    # section costs no more than the varints, take the fixed layout (the
    # decode is a pure reshape — the Pallas-friendly plan)
    fixed_w = 0
    dmax = int(deltas.max())
    for cand in (1, 2, 4):
        if dmax < (1 << (8 * cand)) and n * cand <= len(var_c):
            fixed_w = cand
            break

    l4 = _l4_word(w0, w[:, 1])[perm]
    midx = dict_idx[perm].astype(np.uint8)
    off_b, off_c = delta_section_offsets(n, dict_mode)
    c_len = n * fixed_w if fixed_w else len(var_c)
    payload = np.zeros(off_c + c_len, np.uint8)
    if dict_mode == 1:
        half = np.zeros(2 * ((n + 1) // 2), np.uint8)
        half[:n] = midx
        payload[:off_b] = half[0::2] | (half[1::2] << 4)
    elif dict_mode == 2:
        payload[:off_b] = midx
    payload[off_b:off_c] = (
        l4.astype("<u2").view(np.uint8).reshape(n, 2).reshape(-1)
    )
    if fixed_w:
        payload[off_c:] = (
            deltas.astype("<u8").view(np.uint8).reshape(n, 8)[:, :fixed_w]
            .reshape(-1)
        )
    else:
        payload[off_c:] = var_c
    if max_bytes_per_pkt is not None and len(payload) >= max_bytes_per_pkt * n:
        return None
    return DeltaWire(
        payload=payload, dict_vals=dict_vals.astype(np.uint32), ifmap=ifmap,
        perm=perm, n=n, dict_mode=dict_mode, fixed_w=fixed_w,
        crc=_delta_crc(payload, dict_vals, ifmap),
    )


class DeltaDecodeError(ValueError):
    """Fail-closed decode failure: the stream is truncated, corrupt or
    structurally invalid.  Callers must drop/deny the whole chunk — the
    codec never yields a best-effort partial decode."""


def _varint_decode_host(buf: np.ndarray, n: int) -> np.ndarray:
    """Strict LEB128 decode of exactly ``n`` values consuming EXACTLY the
    whole buffer; raises DeltaDecodeError on any structural violation
    (dangling continuation, >5-byte runs, overlong count, trailing
    bytes)."""
    b = np.asarray(buf, np.uint8)
    if n == 0:
        if len(b):
            raise DeltaDecodeError("trailing bytes after 0-value stream")
        return np.zeros(0, np.uint64)
    if len(b) == 0:
        raise DeltaDecodeError("empty varint section")
    term = (b & 0x80) == 0
    n_vals = int(term.sum())
    if n_vals != n:
        raise DeltaDecodeError(f"varint stream holds {n_vals} values, "
                               f"expected {n}")
    if not term[-1]:
        raise DeltaDecodeError("dangling continuation byte at stream end")
    ends = np.nonzero(term)[0]
    starts = np.concatenate([[-1], ends[:-1]]) + 1
    lens = ends - starts + 1
    if int(lens.max()) > 5:
        raise DeltaDecodeError("varint run exceeds 5 bytes (32-bit domain)")
    vals = np.zeros(n, np.uint64)
    for k in range(5):
        m = lens > k
        if not m.any():
            break
        vals[m] |= (b[starts[m] + k].astype(np.uint64) & 0x7F) << np.uint64(
            7 * k
        )
    if int(vals.max()) > 0xFFFFFFFF:
        raise DeltaDecodeError("varint value exceeds 32 bits")
    return vals


def decode_delta_host(dw: DeltaWire) -> Tuple[np.ndarray, ...]:
    """CPU inverse + validation oracle of encode_delta_wire: returns the
    classification fields in SORTED (stream) order — (kind, l4_ok,
    ifindex, proto, dst_port, icmp_type, icmp_code, ip_word0), the
    unpack_wire8 field contract (pkt_len never ships).  Raises
    DeltaDecodeError on ANY integrity violation: crc mismatch, bad
    section lengths, malformed varints, out-of-range dictionary indexes,
    delta overflow past 2^32.  This is the fail-closed boundary — a
    corrupt stream denies the chunk, it never misclassifies."""
    n = int(dw.n)
    if n < 0:
        raise DeltaDecodeError("negative packet count")
    if dw.crc != _delta_crc(dw.payload, dw.dict_vals, dw.ifmap):
        raise DeltaDecodeError("payload crc mismatch")
    if dw.dict_mode not in (0, 1, 2) or dw.fixed_w not in (0, 1, 2, 4):
        raise DeltaDecodeError("invalid layout flags")
    if len(dw.dict_vals) < 1 or len(dw.dict_vals) > 256:
        raise DeltaDecodeError("invalid dictionary size")
    off_b, off_c = delta_section_offsets(n, dw.dict_mode)
    p = np.asarray(dw.payload, np.uint8)
    if len(p) < off_c:
        raise DeltaDecodeError("payload shorter than fixed sections")
    if dw.fixed_w and len(p) != off_c + n * dw.fixed_w:
        raise DeltaDecodeError("fixed-stride section length mismatch")
    if dw.dict_mode == 0:
        dict_idx = np.zeros(n, np.int64)
    elif dw.dict_mode == 1:
        half = p[:off_b]
        dict_idx = np.empty(2 * len(half), np.int64)
        dict_idx[0::2] = half & 0xF
        dict_idx[1::2] = half >> 4
        if n % 2 and dict_idx[n] != 0:
            raise DeltaDecodeError("nonzero padding nibble")
        dict_idx = dict_idx[:n]
    else:
        dict_idx = p[:n].astype(np.int64)
    if n and int(dict_idx.max()) >= len(dw.dict_vals):
        raise DeltaDecodeError("dictionary index out of range")
    l4 = p[off_b:off_c].view("<u2").astype(np.int64)
    if dw.fixed_w:
        raw = np.zeros((n, 8), np.uint8)
        raw[:, : dw.fixed_w] = p[off_c:].reshape(n, dw.fixed_w)
        deltas = raw.reshape(-1).view("<u8").astype(np.uint64)
    else:
        deltas = _varint_decode_host(p[off_c:], n)
    ip = np.cumsum(deltas, dtype=np.uint64)
    if n and int(ip[-1]) > 0xFFFFFFFF:
        raise DeltaDecodeError("delta sum overflows 32-bit IP word")
    meta = dw.dict_vals[dict_idx].astype(np.int64)
    kind = (meta & 3).astype(np.int32)
    l4_ok = ((meta >> 2) & 1).astype(np.int32)
    proto = ((meta >> 3) & 0xFF).astype(np.int32)
    ifd = ((meta >> 11) & 0xF).astype(np.int64)
    ifindex = np.asarray(dw.ifmap, np.int32)[ifd]
    is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
    dst_port = np.where(is_icmp, 0, l4).astype(np.int32)
    icmp_type = np.where(is_icmp, l4 >> 8, 0).astype(np.int32)
    icmp_code = np.where(is_icmp, l4 & 0xFF, 0).astype(np.int32)
    return (kind, l4_ok, ifindex, proto, dst_port, icmp_type, icmp_code,
            ip.astype(np.uint32))
