"""Batched packet representation.

The reference's per-packet inputs are the XDP context fields consumed by
ingress_node_firewall_main and ip_extract_l4info
(/root/reference/bpf/ingress_node_firewall_kernel.c:95-174,412-439):
ethertype, source IP, L4 protocol, destination port or ICMP type/code,
ingress ifindex and packet length.  The TPU dataplane consumes those same
fields as a struct-of-arrays batch; header parsing from raw bytes happens
host-side (infw.obs.pcap) or packets are generated synthetically.

Field conventions:
- ``kind``: KIND_* code for the ethertype switch outcome (constants.py);
- ``l4_ok``: 0 if ip_extract_l4info would have failed (unsupported L4
  protocol or truncated header) -> lookup returns SET_ACTION(UNDEF);
- ``ip_words``: (B, 4) uint32 big-endian words of the 16-byte source-IP key
  data (IPv4 packets occupy word 0, rest zero — kernel.c:206-212);
- ``dst_port`` is host byte order (the kernel compares bpf_ntohs(dstPort));
- ``pkt_len`` is the full frame length (bpf_xdp_get_buff_len).
"""
from __future__ import annotations

import os as _os
import subprocess as _subprocess
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .constants import IPPROTO_ICMP, IPPROTO_ICMPV6, KIND_IPV4, KIND_IPV6
from .netutil import ip_str_to_words

_native_pack_unavailable = False


@dataclass
class PacketBatch:
    kind: np.ndarray       # (B,) int32
    l4_ok: np.ndarray      # (B,) int32 (0/1)
    ifindex: np.ndarray    # (B,) int32
    ip_words: np.ndarray   # (B, 4) uint32
    proto: np.ndarray      # (B,) int32
    dst_port: np.ndarray   # (B,) int32
    icmp_type: np.ndarray  # (B,) int32
    icmp_code: np.ndarray  # (B,) int32
    pkt_len: np.ndarray    # (B,) int32

    def __len__(self) -> int:
        return int(self.kind.shape[0])

    @property
    def size(self) -> int:
        return len(self)

    def slice(self, start: int, stop: int) -> "PacketBatch":
        return PacketBatch(
            **{
                f: getattr(self, f)[start:stop]
                for f in (
                    "kind l4_ok ifindex ip_words proto dst_port "
                    "icmp_type icmp_code pkt_len".split()
                )
            }
        )

    def take(self, idx: np.ndarray) -> "PacketBatch":
        """Arbitrary-index subset (used to regroup packets by family so
        each device chunk is depth-homogeneous)."""
        return PacketBatch(
            **{
                f: getattr(self, f)[idx]
                for f in (
                    "kind l4_ok ifindex ip_words proto dst_port "
                    "icmp_type icmp_code pkt_len".split()
                )
            }
        )

    def pack_wire(self) -> np.ndarray:
        """Pack into the (B, 7) uint32 device wire format — 28B/packet
        instead of 9 separate int32 arrays (48B/packet).  The host→device
        link (PCIe in production, the tunnel here) is the streaming
        bottleneck, so the descriptor is packed like a NIC ring entry:

          w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | icmpType(8)<<11
              | icmpCode(8)<<19 | pktLenHi(5)<<27
          w1: dstPort(16) | pktLenLo(16)<<16
          w2: ifindex (full u32)
          w3..w6: ip_words

        pktLen carries 21 bits (clamp at 2 MiB - 1): jumbo frames are
        < 10K and even BIG-TCP GRO/TSO aggregates cap at 512 KiB, so no
        real capture frame clips and byte statistics stay exact.

        Device-side inverse: kernels.jaxpath.unpack_wire (fused into the
        classify jit, so unpacking costs no extra HBM round trip)."""
        out = np.empty((len(self), 7), np.uint32)
        self._pack_wire_header(out)
        out[:, 3:7] = self.ip_words.astype(np.uint32)
        return out

    def _pack_wire_header(self, out: np.ndarray) -> None:
        """w0..w2 of the wire layout (shared by the 7- and 4-word formats)."""
        plen = np.clip(self.pkt_len, 0, 0x1FFFFF).astype(np.uint32)
        out[:, 0] = (
            (self.kind.astype(np.uint32) & 3)
            | ((self.l4_ok.astype(np.uint32) & 1) << 2)
            | ((self.proto.astype(np.uint32) & 0xFF) << 3)
            | ((self.icmp_type.astype(np.uint32) & 0xFF) << 11)
            | ((self.icmp_code.astype(np.uint32) & 0xFF) << 19)
            | ((plen >> 16) << 27)
        )
        out[:, 1] = (self.dst_port.astype(np.uint32) & 0xFFFF) | (
            (plen & 0xFFFF) << 16
        )
        out[:, 2] = self.ifindex.astype(np.uint32)

    def is_v4_compactable(self) -> bool:
        """True when the batch can take the 4-word wire format: no IPv6
        packets and no nonzero high IP words (the host parser guarantees
        zeros for v4/malformed/other frames; synthetic batches may not)."""
        return not bool(
            (np.asarray(self.kind) == KIND_IPV6).any()
        ) and not bool(np.asarray(self.ip_words)[:, 1:].any())

    def pack_wire_v4(self) -> np.ndarray:
        """The family-compact (B, 4) uint32 wire format — 16B/packet for
        v4-only chunks (the daemon's ingest regroups by family, so the
        majority family of real traffic takes this path): w0..w2 as
        pack_wire, w3 = IP word 0.  Caller contract: is_v4_compactable().
        Device-side inverse: unpack_wire (width-discriminated)."""
        out = np.empty((len(self), 4), np.uint32)
        self._pack_wire_header(out)
        out[:, 3] = self.ip_words[:, 0].astype(np.uint32)
        return out

    def pack_wire_subset(self, idx: np.ndarray) -> Tuple[np.ndarray, bool]:
        """take(idx) + pack_wire[_v4] fused into one pass -> (wire,
        v4_only).  Dispatches to the native C++ kernel when available
        (the daemon's per-chunk hot path: copying 9 SoA arrays per chunk
        just to re-pack them doubles the host cost); NumPy fallback is
        the composed slow path, differentially tested against it."""
        global _native_pack_unavailable
        idx = np.ascontiguousarray(idx, np.int64)
        if not _native_pack_unavailable:
            try:
                return self._pack_wire_subset_native(idx)
            except (OSError, ImportError, AttributeError, AssertionError,
                    _subprocess.SubprocessError):
                _native_pack_unavailable = True
        sub = self.take(idx)
        compact = sub.is_v4_compactable()
        wire = sub.pack_wire_v4() if compact else sub.pack_wire()
        v4_only = not bool((np.asarray(sub.kind) == KIND_IPV6).any())
        return wire, v4_only

    def _pack_wire_subset_native(self, idx: np.ndarray) -> Tuple[np.ndarray, bool]:
        import ctypes

        from .backend.cpu_ref import load_library

        lib = load_library()
        n = len(idx)
        flat = np.empty(n * 7, np.uint32)
        c = lambda a, dt: np.ascontiguousarray(a, dt)
        p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
        kind = c(self.kind, np.int32)
        l4_ok = c(self.l4_ok, np.int32)
        ifindex = c(self.ifindex, np.int32)
        words = c(self.ip_words, np.uint32)
        proto = c(self.proto, np.int32)
        dst_port = c(self.dst_port, np.int32)
        icmp_type = c(self.icmp_type, np.int32)
        icmp_code = c(self.icmp_code, np.int32)
        pkt_len = c(self.pkt_len, np.int32)
        flags = lib.infw_pack_wire_subset(
            n, p(idx, ctypes.c_int64),
            p(kind, ctypes.c_int32), p(l4_ok, ctypes.c_int32),
            p(ifindex, ctypes.c_int32), p(words, ctypes.c_uint32),
            p(proto, ctypes.c_int32), p(dst_port, ctypes.c_int32),
            p(icmp_type, ctypes.c_int32), p(icmp_code, ctypes.c_int32),
            p(pkt_len, ctypes.c_int32),
            p(flat, ctypes.c_uint32), min(8, _os.cpu_count() or 1),
        )
        compact = bool(flags & 1)
        w = 4 if compact else 7
        return flat[: n * w].reshape(n, w), bool(flags & 2)

    def pad_to(self, n: int) -> "PacketBatch":
        """Pad with KIND_OTHER packets (always XDP_PASS, no stats) so batch
        shapes stay static under jit."""
        b = len(self)
        if b >= n:
            return self
        pad = n - b

        def _pad(a):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, widths, constant_values=3)  # KIND_OTHER / junk

        return PacketBatch(
            kind=_pad(self.kind),
            l4_ok=np.pad(self.l4_ok, (0, pad)),
            ifindex=np.pad(self.ifindex, (0, pad)),
            ip_words=np.pad(self.ip_words, ((0, pad), (0, 0))),
            proto=np.pad(self.proto, (0, pad)),
            dst_port=np.pad(self.dst_port, (0, pad)),
            icmp_type=np.pad(self.icmp_type, (0, pad)),
            icmp_code=np.pad(self.icmp_code, (0, pad)),
            pkt_len=np.pad(self.pkt_len, (0, pad)),
        )


def make_batch(
    *,
    src: Sequence[str],
    proto: Sequence[int],
    ifindex: Sequence[int],
    dst_port: Optional[Sequence[int]] = None,
    icmp_type: Optional[Sequence[int]] = None,
    icmp_code: Optional[Sequence[int]] = None,
    pkt_len: Optional[Sequence[int]] = None,
    l4_ok: Optional[Sequence[int]] = None,
    kind: Optional[Sequence[int]] = None,
) -> PacketBatch:
    """Convenience constructor from parallel per-packet field lists; ``src``
    is a list of IP address strings and determines v4/v6 kind."""
    b = len(src)
    words = np.zeros((b, 4), np.uint32)
    kinds = np.zeros(b, np.int32)
    for i, addr in enumerate(src):
        w, is_v4 = ip_str_to_words(addr)
        words[i] = w
        kinds[i] = KIND_IPV4 if is_v4 else KIND_IPV6
    if kind is not None:
        kinds = np.asarray(kind, np.int32)

    def arr(x, default=0):
        if x is None:
            return np.full(b, default, np.int32)
        return np.asarray(x, np.int32)

    return PacketBatch(
        kind=kinds,
        l4_ok=arr(l4_ok, 1),
        ifindex=arr(ifindex),
        ip_words=words,
        proto=arr(proto),
        dst_port=arr(dst_port),
        icmp_type=arr(icmp_type),
        icmp_code=arr(icmp_code),
        pkt_len=arr(pkt_len, 64),
    )


def concat(batches: List[PacketBatch]) -> PacketBatch:
    return PacketBatch(
        **{
            f: np.concatenate([getattr(b, f) for b in batches])
            for f in (
                "kind l4_ok ifindex ip_words proto dst_port "
                "icmp_type icmp_code pkt_len".split()
            )
        }
    )


def expand_wire_v4(w: np.ndarray) -> np.ndarray:
    """(n, 4) compact wire rows -> (n, 7): zero high IP words (the compact
    format's eligibility guarantee).  Lives next to pack_wire/pack_wire_v4
    so the 4-word/7-word correspondence has one owner; used when a merged
    ingest job mixes compact and full segments and must ship one width."""
    out = np.zeros((w.shape[0], 7), np.uint32)
    out[:, :4] = w
    return out


def _l4_word(w0: np.ndarray, w1: np.ndarray) -> np.ndarray:
    """The 16-bit l4 overlay shared by narrow_wire and wire8: dst_port
    for transport rows, type<<8|code for the family ICMPs — lossless for
    classification because the ordered scan never reads both
    (kernel.c:222-258)."""
    proto = (w0 >> 3) & 0xFF
    is_icmp = (proto == IPPROTO_ICMP) | (proto == IPPROTO_ICMPV6)
    return np.where(
        is_icmp,
        ((w0 >> 11) & 0xFF) << 8 | ((w0 >> 19) & 0xFF),
        w1 & 0xFFFF,
    ).astype(np.uint32)


def narrow_wire(w: np.ndarray):
    """(n, 4|7) wire -> the NARROW (n, 3|6) format, or None when the rows
    don't qualify.  Saves one word per packet (v4 16B -> 12B, v6 28B ->
    24B) on the H2D link — the replay bottleneck — by (a) folding the
    ifindex into w0 when every ifindex fits 16 bits, and (b) overlaying
    dst_port with the ICMP type/code in one 16-bit "l4 word", which is
    LOSSLESS for classification: the ordered scan reads dst_port only for
    transport protocols and the ICMP fields only for the family's ICMP
    protocol (kernel.c:222-258), never both, and the kernels' parse sets
    l4_ok=0 for any other protocol.  pkt_len must fit 16 bits (w0's
    high-bit stash must be clear) so byte statistics stay exact.

    Narrow layout:
      w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | ifindex(16)<<11
      w1: l4word(16) | pktLen(16)<<16
      w2..: ip word 0 (v4) / words 0..3 (v6)

    Device-side inverse: kernels.jaxpath.unpack_wire (width 3/6)."""
    w0 = w[:, 0]
    ifx = w[:, 2]
    if int(w0.size) == 0:
        return np.zeros((0, w.shape[1] - 1), np.uint32)
    if (w0 >> 27).any() or (ifx >> 16).any():
        return None  # pkt_len >= 64KiB or wide ifindex: keep the full form
    l4w = _l4_word(w0, w[:, 1])
    out = np.empty((w.shape[0], w.shape[1] - 1), np.uint32)
    out[:, 0] = (w0 & 0x7FF) | (ifx << 11)
    out[:, 1] = l4w | (w[:, 1] & 0xFFFF0000)  # pktLen low 16 stays in place
    out[:, 2:] = w[:, 3:]
    return out


def wire8(w: np.ndarray):
    """(n, 4) v4-compact wire -> the 8-BYTE format, or None when the rows
    don't qualify: (n, 2) uint32 rows plus the (16,) int32 ifindex
    dictionary the device decodes through.

    The byte diet beyond the 12B narrow form comes from two observations:
    (a) classification itself never reads pkt_len — it exists only for
    byte statistics, which the host can compute EXACTLY from the returned
    verdicts and its own pkt_len column (stats_from_results), so the
    length never needs to cross the link; (b) a chunk rarely spans more
    than a handful of interfaces, so a 4-bit dictionary index replaces
    the 16-bit ifindex (the bond-expansion world of interfaces.go:85-116
    still fits: 15 member links per chunk).

    Layout:  w0: kind(2) | l4_ok(1)<<2 | proto(8)<<3 | ifdict(4)<<11 |
                 l4word(16)<<15          (l4word as in narrow_wire)
             w1: ip word 0
    Device-side inverse: kernels.jaxpath.unpack_wire8 (needs the dict).
    Qualifies only v4-compact chunks (ip words 1..3 zero — the caller's
    pack_wire_v4 contract)."""
    if w.shape[1] != 4:
        return None
    if w.shape[0] == 0:
        return np.zeros((0, 2), np.uint32), np.full(16, -1, np.int32)
    w0 = w[:, 0]
    ifx = w[:, 2]
    uniq = np.unique(ifx)
    if len(uniq) > 15:
        return None
    ifmap = np.full(16, -1, np.int32)
    ifmap[: len(uniq)] = uniq.astype(np.int64)
    ifdict = np.searchsorted(uniq, ifx).astype(np.uint32)
    l4w = _l4_word(w0, w[:, 1])
    out = np.empty((w.shape[0], 2), np.uint32)
    out[:, 0] = (w0 & 0x7FF) | (ifdict << 11) | (l4w << 15)
    out[:, 1] = w[:, 3]
    return out, ifmap
