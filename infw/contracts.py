"""Declared concurrency contracts — the machine-checked half of the
comment disciplines PRs 10/13/15/17 introduced.

Two kinds of declaration live here, both consumed statically by
``infw.analysis.lockcheck`` (the decorators are runtime no-ops):

``@must_precede("first", "then")`` — inside the decorated function,
every call to ``then`` must come after a call to ``first`` (checked by
source position; the decorated body is expected to be the linear landing
sequence, not a dispatch table).  ``then``/``first`` name either a
callee (``self.first(...)`` / ``first(...)``) or, with a ``store:``
prefix, a store to an instance attribute (``store:_names`` matches
``self._names[...] = ...`` and ``self._names = ...``) — so
publish-after-load disciplines are expressible too.

``LOCK_ORDER`` — the global lock-nesting order: ``(outer, inner)`` pairs
meaning ``outer`` may be held while acquiring ``inner``, NEVER the
reverse.  lockcheck flags any measured acquisition edge that contradicts
a declared pair (directly or through the declared order's transitive
closure).  Lock names are ``ClassName._attr`` as inventoried by
lockcheck.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Tuple

#: Declared lock-nesting order (PR 13's discipline, extended by PR 14):
#: the fused resident dispatch holds the flow tier's lock while
#: exchanging donated buffers under the telemetry tier's lock, which in
#: turn wraps the anomaly tier's exchange — flow -> telemetry ->
#: mlscore, never any reverse edge.
LOCK_ORDER: List[Tuple[str, str]] = [
    ("FlowTier._lock", "TelemetryTier._lock"),
    ("TelemetryTier._lock", "AnomalyTier._lock"),
    ("FlowTier._lock", "AnomalyTier._lock"),
]

#: must_precede registry: qualname -> list of (first, then) pairs.
#: Filled at import time by the decorators below; lockcheck reads the
#: decorators from source, so this registry is for runtime
#: introspection/tests only.
MUST_PRECEDE: Dict[str, List[Tuple[str, str]]] = {}


def must_precede(first: str, then: str) -> Callable:
    """Declare an intra-function ordering contract (see module
    docstring).  Identity decorator at runtime."""

    def deco(fn: Callable) -> Callable:
        key = getattr(fn, "__qualname__", getattr(fn, "__name__", str(fn)))
        MUST_PRECEDE.setdefault(key, []).append((first, then))
        return fn

    return deco
