"""Declared contracts — the machine-checked half of the comment
disciplines PRs 10/13/15/17 introduced, plus the device-table value
bounds (TENSOR_BOUNDS) shared by the runtime invariant checkers and
the static bounds verifier.

Three kinds of declaration live here (the decorators are runtime
no-ops; the first two are consumed statically by
``infw.analysis.lockcheck``, the third by both
``infw.analysis.statecheck`` at runtime and
``infw.analysis.boundscheck`` at trace time):

``@must_precede("first", "then")`` — inside the decorated function,
every call to ``then`` must come after a call to ``first`` (checked by
source position; the decorated body is expected to be the linear landing
sequence, not a dispatch table).  ``then``/``first`` name either a
callee (``self.first(...)`` / ``first(...)``) or, with a ``store:``
prefix, a store to an instance attribute (``store:_names`` matches
``self._names[...] = ...`` and ``self._names = ...``) — so
publish-after-load disciplines are expressible too.

``LOCK_ORDER`` — the global lock-nesting order: ``(outer, inner)`` pairs
meaning ``outer`` may be held while acquiring ``inner``, NEVER the
reverse.  lockcheck flags any measured acquisition edge that contradicts
a declared pair (directly or through the declared order's transitive
closure).  Lock names are ``ClassName._attr`` as inventoried by
lockcheck.

``TENSOR_BOUNDS`` — per-role device-table value bounds: role name ->
resolver mapping a concrete table container to per-field
``TensorBound(lo, hi, bits)`` declarations.  The SAME resolver output
feeds two consumers: ``check_declared_bounds`` (called from
statecheck's ``check_device_tables``/``check_ctrie_tables``/
``check_arena``) verifies a concrete state obeys the declaration, and
``boundscheck`` seeds its abstract interpretation of every kernel
jaxpr from it — so a bound the static pass relies on to prove a
gather in-range is by construction one the runtime invariant sweep
enforces on every install.  ``bits`` is an optional maybe-bits mask
constraining the NON-NEGATIVE values of the field (negative sentinel
values like ``-1`` page rows are bounded by ``lo`` alone); it is what
lets the verifier reason through ``value & mask`` decodes such as the
spliced page table's ``page | bank << 30`` rows.
"""
from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

#: Declared lock-nesting order (PR 13's discipline, extended by PR 14):
#: the fused resident dispatch holds the flow tier's lock while
#: exchanging donated buffers under the telemetry tier's lock, which in
#: turn wraps the anomaly tier's exchange — flow -> telemetry ->
#: mlscore, never any reverse edge.
LOCK_ORDER: List[Tuple[str, str]] = [
    ("FlowTier._lock", "TelemetryTier._lock"),
    ("TelemetryTier._lock", "AnomalyTier._lock"),
    ("FlowTier._lock", "AnomalyTier._lock"),
]

#: must_precede registry: qualname -> list of (first, then) pairs.
#: Filled at import time by the decorators below; lockcheck reads the
#: decorators from source, so this registry is for runtime
#: introspection/tests only.
MUST_PRECEDE: Dict[str, List[Tuple[str, str]]] = {}


def must_precede(first: str, then: str) -> Callable:
    """Declare an intra-function ordering contract (see module
    docstring).  Identity decorator at runtime."""

    def deco(fn: Callable) -> Callable:
        key = getattr(fn, "__qualname__", getattr(fn, "__name__", str(fn)))
        MUST_PRECEDE.setdefault(key, []).append((first, then))
        return fn

    return deco


# -- declared tensor value bounds (PR 20) ------------------------------------


class TensorBound(NamedTuple):
    """Declared value bound for one device-table field: every element
    is in ``[lo, hi]``, and every NON-NEGATIVE element has set bits
    only inside ``bits`` (None = no bit declaration)."""

    lo: int
    hi: int
    bits: Optional[int] = None


def _pow2_mask(n: int) -> int:
    """Smallest all-ones mask covering ``n`` (0 -> 0)."""
    m = 0
    while m < n:
        m = (m << 1) | 1
    return m


def _ctrie_tables_bounds(cdev, spec=None) -> Dict[str, TensorBound]:
    """Standalone compressed-poptrie tables (jaxpath.CTrieTables):
    l0 col 0 holds node_id+1 (<= nodes rows), col 1 / targets hold
    tidx+1 joined positions (< joined rows), root_lut holds DIR-16
    root ids (< l0_rows / 65536).  These are exactly the ranges
    statecheck's check_ctrie_tables sweeps on every install."""
    n0 = cdev.l0.shape[0] // 65536
    jrows = cdev.joined.shape[0]
    return {
        "l0": TensorBound(0, max(cdev.nodes.shape[0], jrows - 1)),
        "targets": TensorBound(0, jrows - 1),
        "root_lut": TensorBound(0, max(n0 - 1, 0)),
    }


def _device_tables_bounds(tdev, spec=None) -> Dict[str, TensorBound]:
    """Uncompressed DeviceTables: mask_len carries the -1 padding
    sentinel and caps at 128 bits; root_lut / trie_targets index the
    DIR-16 root level / joined rows."""
    out = {}
    mask_len = getattr(tdev, "mask_len", None)
    if mask_len is not None:
        out["mask_len"] = TensorBound(-1, 128)
    if getattr(tdev, "trie_levels", None):
        l0 = tdev.trie_levels[0]
        # sharded layouts carry a leading shard dim: (S, n0*65536, 2)
        rows0 = l0.shape[1] if l0.ndim == 3 else l0.shape[0]
        n0 = rows0 // 65536
        out["root_lut"] = TensorBound(0, max(n0 - 1, 0))
    joined = getattr(tdev, "joined", None)
    if joined is not None and joined.shape[0] > 1:
        out["trie_targets"] = TensorBound(0, joined.shape[0] - 1)
    elif joined is None and mask_len is not None and \
            getattr(tdev, "trie_targets", None) is not None:
        # joined-less shards: targets hold mask_len positions +1 (0 =
        # no match), bounded by the per-rule column count
        out["trie_targets"] = TensorBound(0, mask_len.shape[-1])
    return out


def _ctrie_arena_bounds(ca, spec=None) -> Dict[str, TensorBound]:
    """Paged ctrie arena (jaxpath.CtrieArena).  The page table is the
    interesting row: ``-1`` absent-tenant sentinel, else ``page`` or
    ``page | bank << 30`` on spliced geometries — declared as an
    interval PLUS a maybe-bits mask, because only the bit view
    survives the kernel's ``& _SPLICE_PAGE_MASK`` decode.  l0 col 0
    additionally carries SPLICE_TAG-tagged slot ids on spliced
    geometries."""
    from .kernels import jaxpath

    n0 = ca.l0.shape[0] // 65536
    jrows = ca.joined.shape[0]
    l0_hi = max(ca.nodes.shape[0], jrows - 1)
    out = {
        "targets": TensorBound(0, jrows - 1),
        "root_lut": TensorBound(0, max(n0 - 1, 0)),
    }
    if spec is not None and getattr(spec, "spliced", False):
        tag = int(jaxpath.SPLICE_TAG)
        l0_hi = max(l0_hi, tag + spec.splice_slots - 1)
        out["page_table"] = TensorBound(
            -1, (1 << jaxpath._SPLICE_BANK_SHIFT) + spec.pages - 1,
            bits=_pow2_mask(spec.pages - 1)
            | (1 << jaxpath._SPLICE_BANK_SHIFT))
        out["splice"] = TensorBound(-1, spec.plane_slots - 1)
    elif spec is not None:
        out["page_table"] = TensorBound(-1, spec.pages - 1)
    out["l0"] = TensorBound(0, l0_hi)
    return out


def _dense_arena_bounds(da, spec=None) -> Dict[str, TensorBound]:
    out = {"mask_len": TensorBound(-1, 128)}
    if spec is not None:
        out["page_table"] = TensorBound(-1, spec.pages - 1)
    return out


def _ac_delta_bounds(trans, spec=None) -> Dict[str, TensorBound]:
    """Aho-Corasick transition tensor: every entry is a DFA state id
    in [0, states-1] (the dense delta) — the bound that makes a
    narrowed restage of the carried walk state a provable wrap."""
    return {"": TensorBound(0, trans.shape[0] - 1)}


def _flow_page_table_bounds(pt, spec=None) -> Dict[str, TensorBound]:
    """Flow-tier tenant -> slab page map: ``-1`` unmapped sentinel,
    else a slab id below the tier's slab count (``spec``; the
    single-slab fixtures pass 1).  The bound is what lets the verifier
    prove ``clip(page, 0) * slab_entries + local`` lands inside the
    flow columns."""
    n = int(spec) if spec is not None else 1
    return {"": TensorBound(-1, max(n - 1, 0))}


def _ac_dflat_bounds(dflat, spec=None) -> Dict[str, TensorBound]:
    """Flattened one-hot transition block of the matmul regime: every
    entry is a 0/1 indicator.  (Row one-hotness itself is beyond an
    elementwise bound — the verifier cannot derive it, which is why
    the int8 restage of the matmul walk carries a justified
    suppression rather than a proof.)"""
    return {"": TensorBound(0, 1)}


#: role -> resolver(concrete_value, spec=None) -> {field: TensorBound}.
#: ``""`` keys a bare-array argument; other keys name NamedTuple
#: fields.  Fields without a declaration default to dtype-top (no
#: promise beyond the dtype).
TENSOR_BOUNDS: Dict[str, Callable] = {
    "ctrie-tables": _ctrie_tables_bounds,
    "device-tables": _device_tables_bounds,
    "ctrie-arena": _ctrie_arena_bounds,
    "dense-arena": _dense_arena_bounds,
    "ac-delta": _ac_delta_bounds,
    "ac-dflat": _ac_dflat_bounds,
    "flow-page-table": _flow_page_table_bounds,
}


def resolve_bounds(role: str, value, spec=None) -> Dict[str, TensorBound]:
    """The declared per-field bounds of ``value`` under ``role``
    (empty dict for unknown roles — callers treat that as dtype-top)."""
    fn = TENSOR_BOUNDS.get(role)
    return fn(value, spec=spec) if fn else {}


def check_declared_bounds(role: str, value, spec=None) -> List[str]:
    """Runtime half of TENSOR_BOUNDS: verify a concrete table
    container obeys every declared field bound.  Returns violation
    strings (empty = clean); consumed by statecheck's invariant
    sweeps so the static verifier's seed assumptions are enforced on
    every install."""
    import numpy as np

    viols: List[str] = []
    for field, b in resolve_bounds(role, value, spec=spec).items():
        arr = np.asarray(value if field == "" else getattr(value, field))
        if arr.size == 0:
            continue
        a = arr.astype(np.int64)
        lo, hi = int(a.min()), int(a.max())
        name = field or role
        if lo < b.lo or hi > b.hi:
            viols.append(
                f"bounds[{role}].{name}: values [{lo}, {hi}] escape "
                f"declared [{b.lo}, {b.hi}]")
        if b.bits is not None:
            nn = a[a >= 0]
            if nn.size and int(np.bitwise_or.reduce(
                    nn.reshape(-1)) & ~np.int64(b.bits)):
                viols.append(
                    f"bounds[{role}].{name}: non-negative values set "
                    f"bits outside declared mask {b.bits:#x}")
    return viols
