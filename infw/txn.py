"""Update-storm dataplane: batched multi-edit patch transactions.

Single incremental edits are solved (a 1-key rules edit diff-scatter
patches in ~100 ms at 1M entries), but every edit pays a full
snapshot + H2D staging + scatter dispatch, so BGP-style churn —
thousands of adds/deletes per second concurrent with classification —
serializes into seconds of control-plane lag.  This module turns N
queued edits into ONE fused transaction per device generation:

- **net-effect folding** (:func:`fold_ops`): later ops on the same
  masked LPM identity supersede earlier ones; an add of a NEW identity
  followed by its delete annihilates to nothing; delete-then-readd of a
  live identity folds to an in-place rules upsert (content-identical to
  the sequential application, so the statecheck oracle holds).  The
  fold output is one (upserts, deletes, new-keys) triple the
  incremental compiler absorbs in a single ``IncrementalTables.apply``.
- **bounded-staleness batching** (:class:`TxnBatcher`): edits
  accumulate while classify batches are in flight and flush when (a)
  the oldest queued edit exceeds the staleness deadline
  (``--patch-staleness-us`` / ``INFW_PATCH_STALENESS_US``) or (b) the
  batch-size threshold trips — so verdict staleness is bounded while
  per-edit device cost amortizes toward O(dirty rows), not O(ops).
- **one device generation per flush** (:class:`TxnApplier` /
  ``DataplaneSyncer.apply_edit_transaction``): the folded transaction
  routes exactly like the syncer's per-sync diff (overlay side-table
  for structurally-new CIDR adds, merged dirty-row hint for the
  diff-scatter patch, columnar-rebuild escalation when the trie must
  renumber or the capped-scatter budget is exceeded) and lands as ONE
  ``load_tables`` call — one snapshot, one H2D staging pass, one
  pre-warmed fused scatter launch (``jaxpath.txn_scatter``), with the
  old generation serving until the swap.
- **observability** (:class:`TxnStats` + ``obs.events.PatchTxnRecord``):
  ops folded, dirty rows, flush reason, escalations, and a per-op
  staleness histogram, exported through the daemon's /metrics registry
  and the obs event ring.

The statecheck model checker (infw.analysis.statecheck) drives this
fold through its ``txn``/``txn-ctrie`` configurations: every flushed
transaction must be bit-identical to a cold rebuild from a
cache-stripped snapshot AND oracle-equivalent to the per-op ground
truth through production dispatch — ``tools/infw_lint.py state
--inject-defect fold`` proves a fold bug (delete-then-readd
resurrecting stale rules) is caught with a shrunk <= 2-op reproducer.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .compiler import CompileError, IncrementalTables, LpmKey

log = logging.getLogger("infw.txn")

#: single-key edit kinds a transaction folds (the statecheck alphabet
#: minus the driver-level overlay_spill / full_replace / txn_flush)
TXN_EDIT_KINDS = (
    "key_add", "cidr_add", "key_delete", "rules_edit", "order_change",
)

#: bounded-staleness defaults (daemon knobs override)
DEFAULT_STALENESS_US = 2000.0
DEFAULT_MAX_OPS = 1024

#: injected-defect switch for the statecheck acceptance gate
#: (tools/infw_lint.py state --inject-defect fold): delete-then-readd of
#: a live identity folds to a NO-OP instead of an upsert, so the device
#: keeps the stale pre-delete rules while the op semantics say the
#: re-add's rules are live.  Never set in production.
_INJECT_FOLD_BUG = False


@dataclass
class EditOp:
    """One declarative single-key edit of the running dataplane — the
    production twin of the statecheck alphabet (any object with
    ``kind``/``key``/``rules`` attributes folds, so statecheck's own
    EditOps feed :func:`fold_ops` directly)."""

    kind: str
    key: LpmKey
    rules: Optional[np.ndarray] = None

    def __post_init__(self) -> None:
        if self.kind not in TXN_EDIT_KINDS:
            raise ValueError(
                f"unknown edit kind {self.kind!r} "
                f"(expected one of {TXN_EDIT_KINDS})"
            )
        if self.kind != "key_delete" and self.rules is None:
            raise ValueError(f"{self.kind} requires a rules matrix")


@dataclass
class FoldedTxn:
    """Net effect of one op sequence: what actually ships.

    ``upserts`` hit identities already live in the main table or the
    overlay (routing decides which); ``new_keys`` are identities the
    dataplane has never seen, each carrying the kind of its FINAL add op
    (``cidr_add`` keys are overlay-eligible); ``deletes`` remove live
    identities.  ``n_ops`` - (ops that survived) = ops folded away."""

    upserts: Dict[LpmKey, np.ndarray] = field(default_factory=dict)
    new_keys: Dict[LpmKey, Tuple[np.ndarray, str]] = field(
        default_factory=dict
    )
    deletes: List[LpmKey] = field(default_factory=list)
    n_ops: int = 0

    @property
    def n_effects(self) -> int:
        return len(self.upserts) + len(self.new_keys) + len(self.deletes)

    @property
    def n_folded(self) -> int:
        return self.n_ops - self.n_effects


def fold_ops(ops: Sequence, existing_idents) -> FoldedTxn:
    """Host-side net-effect fold: one pass over ``ops`` keeping only the
    LAST effect per masked LPM identity.

    Semantics (per identity, in op order — exactly what applying the ops
    one generation at a time would leave behind):

    - a later add/edit supersedes any earlier add/edit or delete
      (delete-then-readd folds to an upsert of the re-add's rules);
    - a delete supersedes earlier adds/edits; if the identity was NOT
      live before the transaction (``existing_idents``), the add+delete
      pair annihilates to nothing;
    - identities live before the transaction whose final effect is an
      add/edit land in ``upserts``; never-seen identities land in
      ``new_keys`` with their final add kind (``cidr_add`` = overlay
      eligible).
    """
    # per-ident running state: ("set", key, rules, kind) | ("del", key)
    state: Dict[tuple, tuple] = {}
    n = 0
    for op in ops:
        kind = op.kind
        if kind not in TXN_EDIT_KINDS:
            raise ValueError(f"cannot fold op kind {kind!r}")
        n += 1
        ident = op.key.masked_identity()
        if kind == "key_delete":
            state[ident] = ("del", op.key)
            continue
        if _INJECT_FOLD_BUG and state.get(ident, ("",))[0] == "del":
            # the injected defect: the re-add after a delete is dropped
            # and the pair treated as a pure no-op — a live identity
            # keeps its STALE pre-delete rules on device while the op
            # semantics say the re-add's rules are in force
            del state[ident]
            continue
        state[ident] = ("set", op.key, np.asarray(op.rules), kind)
    out = FoldedTxn(n_ops=n)
    for ident, st in state.items():
        if st[0] == "del":
            if ident in existing_idents:
                out.deletes.append(st[1])
            # else: identity born and killed inside the transaction —
            # annihilated, nothing ships
            continue
        _tag, key, rules, kind = st
        if ident in existing_idents:
            out.upserts[key] = rules
        else:
            out.new_keys[key] = (rules, kind)
    return out


def route_folded(folded: FoldedTxn, overlay: Dict[LpmKey, np.ndarray],
                 overlay_ok: bool, overlay_cap: int):
    """Route a folded transaction against the live overlay dict (which
    is MUTATED in place) — THE routing shared by the syncer, the
    TxnApplier and the statecheck driver, so the model checker exercises
    the exact production logic:

    - overlay-resident identities edit/delete inside the overlay;
    - main-table upserts/deletes pass through;
    - structurally-new ``cidr_add`` keys go to the overlay while
      ``overlay_ok`` holds and it has room; a capacity overflow
      mid-transaction spills the WHOLE overlay into the returned
      upserts (one structural merge) and stops overlay routing for the
      rest of the transaction.

    Returns ``(upserts, deletes, overlay_dirty)`` — deletes/upserts for
    the main table, and whether the overlay changed (caller invalidates
    its compiled-overlay memo)."""
    ov_by_ident = {k.masked_identity(): k for k in overlay}
    ups: Dict[LpmKey, np.ndarray] = {}
    dels: List[LpmKey] = []
    ov_dirty = False
    for key in folded.deletes:
        ov_key = ov_by_ident.get(key.masked_identity())
        if ov_key is not None:
            overlay.pop(ov_key, None)
            ov_dirty = True
        else:
            dels.append(key)
    for key, rules in folded.upserts.items():
        ov_key = ov_by_ident.get(key.masked_identity())
        if ov_key is not None:
            overlay.pop(ov_key, None)
            overlay[key] = rules
            ov_dirty = True
        else:
            ups[key] = rules
    for key, (rules, kind) in folded.new_keys.items():
        if kind == "cidr_add" and overlay_ok:
            if len(overlay) < overlay_cap:
                overlay[key] = rules
                ov_dirty = True
                continue
            ups.update(overlay)
            overlay.clear()
            ov_dirty = True
            overlay_ok = False
        ups[key] = rules
    return ups, dels, ov_dirty


# --- bounded-staleness batching ---------------------------------------------


class TxnBatcher:
    """Thread-safe edit queue with the flush policy: edits accumulate
    while classify batches are in flight; :meth:`should_flush` trips on
    (a) the oldest edit's age exceeding the staleness deadline or (b)
    the batch-size threshold.  ``drain()`` hands back (op, enqueue_ts)
    pairs so the flusher can account per-op staleness."""

    def __init__(self, staleness_s: float = DEFAULT_STALENESS_US * 1e-6,
                 max_ops: int = DEFAULT_MAX_OPS,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if staleness_s <= 0:
            raise ValueError(f"staleness must be positive, got {staleness_s}")
        if max_ops < 1:
            raise ValueError(f"max_ops must be >= 1, got {max_ops}")
        self.staleness_s = float(staleness_s)
        self.max_ops = int(max_ops)
        self._clock = clock
        self._lock = threading.Lock()
        self._q: List[Tuple[object, float]] = []

    def queue(self, op, now: Optional[float] = None) -> None:
        ts = self._clock() if now is None else float(now)
        with self._lock:
            self._q.append((op, ts))

    def queue_many(self, ops: Sequence, now: Optional[float] = None) -> None:
        ts = self._clock() if now is None else float(now)
        with self._lock:
            self._q.extend((op, ts) for op in ops)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def oldest_age(self, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else float(now)
        with self._lock:
            return now - self._q[0][1] if self._q else 0.0

    def should_flush(self, now: Optional[float] = None) -> Optional[str]:
        """Flush reason ("batch" | "deadline") or None (keep coalescing).
        The batch threshold is checked first: an overfull queue should
        ship regardless of age."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if not self._q:
                return None
            if len(self._q) >= self.max_ops:
                return "batch"
            if now - self._q[0][1] >= self.staleness_s:
                return "deadline"
            return None

    def drain(self) -> List[Tuple[object, float]]:
        with self._lock:
            q, self._q = self._q, []
            return q


# --- observability -----------------------------------------------------------

#: per-op staleness histogram bucket bounds, microseconds (<= bound)
STALENESS_BUCKETS_US = (100, 1_000, 10_000, 100_000, 1_000_000)


class TxnStats:
    """Thread-safe transaction counters for the /metrics registry
    (counter-provider protocol): transactions, ops in/folded, device
    dirty rows, escalations, per-reason flush counts, and the per-op
    staleness histogram (enqueue -> flush-start age)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.txns_total = 0
        self.ops_total = 0
        self.folded_total = 0
        self.dirty_rows_total = 0
        self.escalations_total = 0
        self.reasons: Dict[str, int] = {}
        self.staleness_hist = [0] * (len(STALENESS_BUCKETS_US) + 1)

    def note_flush(self, n_ops: int, n_folded: int, dirty_rows: int,
                   reason: str, escalated: bool,
                   staleness_s: Sequence[float] = ()) -> None:
        with self._lock:
            self.txns_total += 1
            self.ops_total += int(n_ops)
            self.folded_total += int(n_folded)
            self.dirty_rows_total += int(dirty_rows)
            if escalated:
                self.escalations_total += 1
            self.reasons[reason] = self.reasons.get(reason, 0) + 1
            for s in staleness_s:
                us = s * 1e6
                for i, bound in enumerate(STALENESS_BUCKETS_US):
                    if us <= bound:
                        self.staleness_hist[i] += 1
                        break
                else:
                    self.staleness_hist[-1] += 1

    def counter_values(self) -> Dict[str, int]:
        """Prometheus counter sources, rendered by the metrics registry
        as ingressnodefirewall_node_patch_txn_*."""
        with self._lock:
            out = {
                "patch_txn_total": self.txns_total,
                "patch_txn_ops_total": self.ops_total,
                "patch_txn_ops_folded_total": self.folded_total,
                "patch_txn_dirty_rows_total": self.dirty_rows_total,
                "patch_txn_escalations_total": self.escalations_total,
            }
            for reason, c in sorted(self.reasons.items()):
                out[f"patch_txn_flush_{reason}_total"] = c
            for i, bound in enumerate(STALENESS_BUCKETS_US):
                out[f"patch_txn_staleness_us_bucket_le_{bound}"] = (
                    self.staleness_hist[i]
                )
            out["patch_txn_staleness_us_bucket_inf"] = self.staleness_hist[-1]
            return out

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "txns": self.txns_total, "ops": self.ops_total,
                "folded": self.folded_total,
                "dirty_rows": self.dirty_rows_total,
                "escalations": self.escalations_total,
                "reasons": dict(self.reasons),
                "staleness_hist": list(self.staleness_hist),
            }


@dataclass
class TxnReport:
    """What one flushed transaction did (also the PatchTxnRecord
    payload)."""

    n_ops: int
    n_folded: int
    dirty_rows: int
    mode: str          # "patch" | "full"
    reason: str
    escalated: bool
    apply_s: float = 0.0
    worst_staleness_s: float = 0.0


# --- the apply half ----------------------------------------------------------


class TxnApplier:
    """Owns the incremental compile state + a classifier and applies
    folded edit transactions as ONE device patch generation — the
    update-storm apply half the churn bench and the scheduler harness
    drive (the daemon's checkpointed path is
    ``DataplaneSyncer.apply_edit_transaction``, same fold + routing).

    Routing per flush, mirroring the syncer's per-sync diff:

    - overlay-resident identities edit/delete inside the overlay dict
      (a tiny dense side-table re-upload, the main trie untouched);
    - main-table upserts/deletes ship as ONE ``IncrementalTables.apply``
      and ONE ``load_tables`` with the merged dirty-row hint — the
      diff-scatter patch covers every dirty row of the transaction in a
      single fused scatter launch;
    - structurally-new ``cidr_add`` keys route to the overlay while it
      has room (capacity overflow mid-transaction spills the WHOLE
      overlay into the main table — one structural merge);
    - a transaction the updater cannot absorb (trie depth exceeded)
      escalates to the columnar rebuild path, the old generation
      serving until the swap.
    """

    def __init__(self, clf, updater: IncrementalTables,
                 overlay_cap: int = 1024, overlay_min_main: int = 4096,
                 stats: Optional[TxnStats] = None, ring=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.clf = clf
        self.updater = updater
        self.overlay: Dict[LpmKey, np.ndarray] = {}
        self.overlay_cap = int(overlay_cap)
        self.overlay_min_main = int(overlay_min_main)
        self.stats = stats
        self.ring = ring
        self._clock = clock
        self._ov_memo = None
        self._lock = threading.Lock()

    # -- overlay helpers -----------------------------------------------------

    def _compiled_overlay(self):
        from .compiler import compile_tables_from_content

        if not self.overlay:
            self._ov_memo = None
            return None
        if self._ov_memo is None:
            self._ov_memo = compile_tables_from_content(
                dict(self.overlay), rule_width=self.updater.rule_width
            )
        return self._ov_memo

    # -- the flush -----------------------------------------------------------

    def apply(self, ops: Sequence, reason: str = "manual",
              enqueue_ts: Optional[Sequence[float]] = None) -> TxnReport:
        """Fold + route + apply one transaction; returns the report
        (emitted to the stats sink / event ring when configured)."""
        with self._lock:
            t0 = self._clock()
            existing = set(self.updater._ident_to_t) | {
                k.masked_identity() for k in self.overlay
            }
            folded = fold_ops(ops, existing)
            # same post-delete size gate as the syncer: a shrunken main
            # table may land on the dense path, which cannot honor an
            # overlay (folded.deletes over-counts by the overlay's own
            # deletes — conservative toward merging, never wrong)
            overlay_ok = (
                getattr(self.clf, "supports_overlay", False)
                and len(self.updater._ident_to_t) - len(folded.deletes)
                > self.overlay_min_main
            )
            ups, dels, ov_dirty = route_folded(
                folded, self.overlay, overlay_ok, self.overlay_cap
            )
            if ov_dirty:
                self._ov_memo = None
            escalated = self._apply_main(ups, dels)
            mode, dirty_rows = getattr(
                self.clf, "_last_load", ("full", 0)
            )
            worst = 0.0
            staleness: List[float] = []
            if enqueue_ts:
                staleness = [max(0.0, t0 - ts) for ts in enqueue_ts]
                worst = max(staleness, default=0.0)
            report = TxnReport(
                n_ops=folded.n_ops, n_folded=folded.n_folded,
                dirty_rows=int(dirty_rows), mode=mode, reason=reason,
                escalated=escalated, apply_s=self._clock() - t0,
                worst_staleness_s=worst,
            )
            if self.stats is not None:
                self.stats.note_flush(
                    report.n_ops, report.n_folded, report.dirty_rows,
                    reason, escalated, staleness_s=staleness,
                )
            if self.ring is not None:
                from .obs.events import PatchTxnRecord

                self.ring.push(PatchTxnRecord(
                    ops=report.n_ops, folded=report.n_folded,
                    dirty_rows=report.dirty_rows, reason=reason,
                    escalated=escalated,
                    staleness_us=worst * 1e6,
                ))
            return report

    def _apply_main(self, ups, dels) -> bool:
        """One batched updater apply + one device load; returns True
        when the transaction escalated to the columnar rebuild path
        (the old generation keeps serving until load_tables swaps)."""
        escalated = False
        try:
            if ups and not self.updater.fits(ups):
                raise CompileError("trie depth exceeded; rebuild")
            self.updater.apply(ups, dels)
            if self.updater.maybe_compact():
                escalated = True
        except CompileError:
            content = dict(self.updater.content)
            del_idents = {k.masked_identity() for k in dels}
            content = {
                k: v for k, v in content.items()
                if k.masked_identity() not in del_idents
            }
            content.update(ups)
            content.update(self.overlay)
            self.overlay = {}
            self._ov_memo = None
            self.updater = IncrementalTables.from_content(
                content, rule_width=self.updater.rule_width
            )
            escalated = True
        snap = self.updater.snapshot()
        hint = self.updater.peek_dirty()
        if getattr(self.clf, "supports_overlay", False):
            self.clf.load_tables(
                snap, dirty_hint=hint, overlay=self._compiled_overlay()
            )
        else:
            if self.overlay:
                raise RuntimeError("overlay routed to a non-overlay backend")
            self.clf.load_tables(snap, dirty_hint=hint)
        self.updater.clear_dirty()
        return escalated


# --- edit-file protocol (daemon <- churngen) --------------------------------
#
# One JSON document per file: {"ops": [{"kind", "prefix_len", "ifindex",
# "ip" (32 hex chars), "rules" ((R, 7) int rows, absent for deletes)}]}.
# tmp + rename discipline like every other file in the state-dir
# protocol; the daemon consumes files in sorted order.


def op_to_json(op) -> dict:
    doc = {
        "kind": op.kind,
        "prefix_len": int(op.key.prefix_len),
        "ifindex": int(op.key.ingress_ifindex),
        "ip": op.key.ip_data.hex(),
    }
    if op.rules is not None:
        doc["rules"] = np.asarray(op.rules, np.int32).tolist()
    return doc


def op_from_json(doc: dict) -> EditOp:
    key = LpmKey(
        int(doc["prefix_len"]), int(doc["ifindex"]),
        bytes.fromhex(doc["ip"]),
    )
    rules = doc.get("rules")
    return EditOp(
        kind=str(doc["kind"]), key=key,
        rules=None if rules is None else np.asarray(rules, np.int32),
    )


def write_edit_file(path: str, ops: Sequence) -> None:
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"ops": [op_to_json(op) for op in ops]}, f)
    os.replace(tmp, path)


def read_edit_file(path: str) -> List[EditOp]:
    with open(path) as f:
        doc = json.load(f)
    return [op_from_json(d) for d in doc["ops"]]
