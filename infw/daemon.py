"""The per-node daemon: watch desired state, program the dataplane,
classify ingest traffic, serve metrics, stream deny events.

Equivalent of the reference daemon binary
(/root/reference/cmd/daemon/daemon.go): env contract NODE_NAME /
NAMESPACE / POLL_PERIOD_SECONDS / ENABLE_LPM_LOOKUP_DBG (:69-84),
loopback-bound metrics + health endpoints (:57-58, ports 39301/39300),
namespace-scoped state watching (:91-95), wiring of the NodeState
controller and the statistics poller (:96-130).

TPU-native deltas:
- ``--backend tpu|cpu`` selects the classifier (Pallas/XLA device path vs
  the native C++ reference) behind the same syncer boundary.
- Desired state arrives either through an in-process Store watch or a
  **state directory** (``<state-dir>/nodestates/<node>.json``) so external
  controllers/tools can drive a running daemon exactly like applying a CR;
  file deletion = CR deletion.
- Packet ingest is file-based replay: drop a frames file (see
  ``write_frames_file``) into ``<state-dir>/ingest/``; verdict summaries
  land in ``<state-dir>/out/``; deny events stream to the event log (the
  role of the syslog sidecar, cmd/syslog/syslog.go).
- ``ENABLE_LPM_LOOKUP_DBG`` fills a bounded in-memory key buffer served at
  ``/debug/lookup-keys`` — the analogue of the 16384-entry debug hash map
  (bpf/ingress_node_firewall_kernel.c:59-64,214-216) inspectable with
  bpftool.
"""
from __future__ import annotations

import argparse
import json
import logging
import mmap
import os
import signal
import struct
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ._threads import spawn
from . import platform as platform_mod
from .compiler import CompileError
from .constants import DENY, KIND_IPV6, KIND_OTHER, MAX_TARGETS
from .interfaces import InterfaceError, InterfaceRegistry, default_registry
from .nodestate_controller import NodeStateReconciler
from .obs.events import EventRing, EventsLogger, emit_deny_events
from .obs.pcap import FramesBuf, parse_frames_buf
from .obs.statistics import Registry as MetricsRegistry, Statistics
from . import packets as packets_mod
from .packets import PacketBatch, expand_wire_v4
from .schema import validate_nodestate_schema
from .spec import IngressNodeFirewallNodeState
from .store import InMemoryStore
from .syncer import DataplaneSyncer, SyncError

log = logging.getLogger("infw.daemon")

DEFAULT_METRICS_PORT = 39301   # cmd/daemon/daemon.go:57
DEFAULT_HEALTH_PORT = 39300    # cmd/daemon/daemon.go:58
DEBUG_MAP_ENTRIES = 16384      # kernel.c:63 debug map max_entries
DEFAULT_INGEST_CHUNK = 1 << 16     # packets per in-flight sub-batch
# In-flight async classify jobs.  Deeper pipelining lets more jobs
# enqueue before the first drain blocks, overlapping device transfers
# with the link's round-trip latency: measured 1.9x sustained ingest vs
# depth 4 on a ~100ms-RTT link (bench config: 1M-row jobs, where one
# job's wire buffer is ~16-28MB; at this default chunk of 64K rows a job
# is <=1.8MB, so memory is trivial either way).
DEFAULT_PIPELINE_DEPTH = 16
DEFAULT_MAX_TICK_PACKETS = 4 << 20   # parse-ahead bound for one ingest tick
# Double-buffered ingestion: how many UPCOMING jobs are host-packed +
# codec-encoded with their H2D copy already started while earlier jobs'
# classifies run (prepare_packed).  2 keeps one transfer in flight ahead
# of the compute at all times (classic double buffering) without holding
# more than ~2 chunks of extra pinned wire memory.
DEFAULT_H2D_STAGE_DEPTH = 2

_FRAMES_MAGIC = b"INFW1\n"
_FRAMES_MAGIC2 = b"INFW2\n"


# --- frames-file replay format ----------------------------------------------

def write_frames_file(path: str, frames: Sequence[bytes], ifindex) -> None:
    """v1 length-prefixed raw-frame container for ingest replay: per
    record a u32 ingress ifindex + u32 length + frame bytes."""
    if np.isscalar(ifindex):
        ifindex = [int(ifindex)] * len(frames)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_FRAMES_MAGIC)
        f.write(struct.pack("<I", len(frames)))
        for idx, frame in zip(ifindex, frames):
            f.write(struct.pack("<II", int(idx), len(frame)))
            f.write(frame)
    os.replace(tmp, path)


def write_frames_file_v2(path: str, fb: FramesBuf) -> None:
    """v2 columnar container: u32 count, then the ifindex and length
    arrays, then all frame bytes concatenated.  Written and read with
    three bulk I/O calls — the replay-scale format (10M frames = two
    40MB arrays + one buffer, no per-record Python)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_FRAMES_MAGIC2)
        f.write(struct.pack("<I", len(fb)))
        f.write(np.ascontiguousarray(fb.ifindex, "<u4").tobytes())
        f.write(np.ascontiguousarray(fb.lengths, "<u4").tobytes())
        f.write(np.ascontiguousarray(fb.buf).tobytes())
    os.replace(tmp, path)


def read_frames_file(path: str) -> Tuple[List[bytes], List[int]]:
    with open(path, "rb") as f:
        magic = f.read(len(_FRAMES_MAGIC))
        if magic != _FRAMES_MAGIC:
            raise ValueError(f"{path}: not an infw frames file")
        (count,) = struct.unpack("<I", f.read(4))
        frames, ifindexes = [], []
        for _ in range(count):
            idx, length = struct.unpack("<II", f.read(8))
            frames.append(f.read(length))
            ifindexes.append(idx)
    return frames, ifindexes


def read_frames_any(path: str) -> FramesBuf:
    """Read either frames-file version into a FramesBuf.

    The v2 frame buffer is memory-mapped, not read: the parser (native,
    one linear pass) faults pages straight from the page cache with no
    intermediate copy of the (potentially multi-GB) payload, and the map
    lives only as long as the FramesBuf referencing it."""
    with open(path, "rb") as f:
        magic = f.read(len(_FRAMES_MAGIC2))
        if magic == _FRAMES_MAGIC2:
            (count,) = struct.unpack("<I", f.read(4))
            # Bound the declared count against the file size BEFORE
            # allocating: a corrupt header with count near 2^32 would
            # otherwise attempt multi-GB reads ahead of the truncation
            # check below.
            st_size = os.fstat(f.fileno()).st_size
            if 8 * count + f.tell() > st_size:
                raise ValueError(
                    f"{path}: v2 header count {count} exceeds file size"
                )
            ifindex = np.frombuffer(f.read(4 * count), "<u4")
            lengths = np.frombuffer(f.read(4 * count), "<u4")
            payload_off = f.tell()
            total = os.fstat(f.fileno()).st_size - payload_off
            if len(lengths) != count or total != int(
                lengths.astype(np.int64).sum()
            ):
                raise ValueError(f"{path}: truncated v2 frames file")
            if total:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                buf = np.frombuffer(mm, np.uint8, count=total, offset=payload_off)
            else:
                buf = np.zeros(0, np.uint8)
            return FramesBuf.from_lengths(buf, lengths, ifindex)
    if magic != _FRAMES_MAGIC:
        raise ValueError(f"{path}: not an infw frames file")
    frames, ifindexes = read_frames_file(path)
    return FramesBuf.from_frames(frames, ifindexes)


# --- debug lookup buffer (ENABLE_LPM_LOOKUP_DBG) -----------------------------

class DebugLookupBuffer:
    """Bounded record of the LPM lookup keys the dataplane constructed —
    the debug hash map (kernel.c:59-64) re-expressed host-side.  Keys are
    (ifindex, ip_words) per classified packet; capacity-bounded with
    overwrite of the oldest (the kernel map just stops inserting; a ring
    is strictly more useful and still bounded)."""

    def __init__(self, capacity: int = DEBUG_MAP_ENTRIES) -> None:
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)

    def record_batch(self, batch: PacketBatch) -> None:
        ifx = np.asarray(batch.ifindex)
        if len(ifx) == 0:
            return
        words = np.asarray(batch.ip_words)
        # Build all row tuples in C (one column_stack + tolist) rather than
        # 5 int() conversions per packet in a Python loop.
        rows = np.column_stack([ifx.reshape(-1, 1), words.reshape(len(ifx), -1)])
        items = [(r[0], tuple(r[1:])) for r in rows.tolist()]
        with self._lock:
            self._buf.extend(items)

    def snapshot(self) -> List[Tuple[int, Tuple[int, int, int, int]]]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


# --- classifier factories ----------------------------------------------------

def stats_from_results(results: np.ndarray, pkt_len: np.ndarray) -> np.ndarray:
    """Per-file statistics from host-resident verdicts — (MAX_TARGETS, 4)
    int64 [allow_pkts, allow_bytes, deny_pkts, deny_bytes], mirroring the
    device accumulation semantics (kernel.c:361-399: allow/deny only,
    ruleId < MAX_TARGETS).  Computed host-side so a device job that spans
    files never entangles one file's counters with another's exactly-once
    lifecycle."""
    action = results & 0xFF
    rid = (results >> 8).astype(np.int64)
    pl = np.asarray(pkt_len, np.int64)
    out = np.zeros((MAX_TARGETS, 4), np.int64)
    for col, act in ((0, 2), (2, 1)):  # ALLOW=2, DENY=1
        m = (action == act) & (rid < MAX_TARGETS)
        if m.any():
            r = rid[m]
            out[:, col] = np.bincount(r, minlength=MAX_TARGETS)[:MAX_TARGETS]
            out[:, col + 1] = np.bincount(
                r, weights=pl[m], minlength=MAX_TARGETS
            )[:MAX_TARGETS].astype(np.int64)
    return out


def make_classifier_factory(backend: str, fused_deep: Optional[bool] = None,
                            wire_codec: Optional[str] = None,
                            mesh: Optional[str] = None,
                            compressed: Optional[bool] = None,
                            flow_table=None,
                            resident: Optional[bool] = None,
                            telemetry=None,
                            mlscore=None,
                            mlscore_mode: Optional[str] = None,
                            payload=None,
                            payload_mode: Optional[str] = None,
                            payload_plen: Optional[int] = None):
    """``fused_deep`` steers the TPU backend's fused Pallas deep-walk
    dispatch (kernels.pallas_walk) for full-depth v6 chunks; None keeps
    the backend default (on for real TPU hardware, off in interpret
    mode).  ``wire_codec`` selects the H2D wire format (auto | wire8 |
    delta — the --wire-codec knob); None keeps the backend default (the
    INFW_WIRE_CODEC env, else auto).  ``mesh`` is the multi-chip serving
    spec ("DATAxRULES", the --mesh / INFW_MESH knob): when it resolves
    against the visible device pool the factory produces the
    MeshTpuClassifier; when it does not (single-chip node, 1x1 spec) the
    daemon falls back to the single-chip classifier and keeps serving.
    The CPU reference backend ignores all three."""
    from .backend import classifier_class

    if backend == "cpu":
        if flow_table is not None:
            log.warning(
                "--flow-table is a device-backend feature; the cpu "
                "reference classifier serves stateless"
            )
        if resident:
            log.warning(
                "--resident is a device-backend feature; the cpu "
                "reference classifier serves the multi-dispatch path"
            )
        if telemetry is not None:
            log.warning(
                "--telemetry is a device-backend feature; the cpu "
                "reference classifier exports no sketch plane"
            )
        if mlscore is not None:
            log.warning(
                "--mlscore is a device-backend feature; the cpu "
                "reference classifier serves unscored"
            )
        if payload is not None:
            log.warning(
                "--payload is a device-backend feature; the cpu "
                "reference classifier serves headers-only"
            )
        return classifier_class("cpu")
    if backend == "tpu":
        import functools

        kw = {}
        if fused_deep is not None:
            kw["fused_deep"] = fused_deep
        if wire_codec is not None:
            kw["wire_codec"] = wire_codec
        if compressed is not None:
            kw["compressed"] = compressed
        if resident:
            # zero-copy resident serving loop (ISSUE-12): one fused
            # donated-buffer device program per admission; implies a
            # flow tier (the classifier synthesizes a default table
            # when none was configured)
            kw["resident"] = True
        if flow_table is not None:
            # the stateful flow tier (infw.flow): a FlowConfig built at
            # launch (validated there) rides into every classifier
            # generation the syncer constructs
            kw["flow_table"] = flow_table
        if telemetry is not None:
            # device-resident telemetry plane (infw.obs.telemetry): a
            # SketchSpec built at launch rides into every classifier
            # generation; the daemon attaches its obs ring + drain
            # cadence on the idle loop (_telemetry_maintenance)
            kw["telemetry"] = telemetry
        if mlscore is not None:
            # MXU anomaly scoring (infw.mlscore): the launch-validated
            # (ScoreSpec, ScoreModel) bundle rides into every classifier
            # generation; the daemon attaches the obs ring, the drain
            # cadence and the <state-dir>/models/ hot-swap scan on the
            # idle loop (_mlscore_maintenance)
            spec, model = mlscore
            kw["mlscore"] = spec
            kw["mlscore_model"] = model
            kw["mlscore_mode"] = mlscore_mode or "shadow"
        if payload is not None:
            # payload matching tier (infw.payload): the launch-validated
            # pattern set (an AcModel / PayloadTier) rides into every
            # classifier generation; the daemon runs the
            # <state-dir>/patterns/ hot-swap scan on the idle loop
            # (_payload_maintenance).  The automaton tensors replicate
            # onto the mesh via the classifier's device sharding, so the
            # tier serves on multi-chip nodes too.
            kw["payload"] = payload
            kw["payload_mode"] = payload_mode or "shadow"
            if payload_plen is not None:
                kw["payload_plen"] = payload_plen
        if mesh:
            from .backend.mesh import resolve_mesh_spec

            m = resolve_mesh_spec(mesh)  # None -> single-chip fallback
            if m is not None:
                log.info(
                    "serving on a %dx%d (data x rules) device mesh",
                    m.shape["data"], m.shape["rules"],
                )
                if kw.pop("compressed", None):
                    # the compressed layout is single-chip for now: the
                    # mesh shard programs walk the per-level form
                    log.warning(
                        "--compressed is single-chip only; the mesh "
                        "backend serves the per-level trie layout"
                    )
                if kw.pop("mlscore", None) is not None:
                    # the scoring tensors are not mesh-placed yet (the
                    # telemetry-plane posture, ISSUE-13/14)
                    kw.pop("mlscore_model", None)
                    kw.pop("mlscore_mode", None)
                    log.warning(
                        "--mlscore is single-chip only; the mesh "
                        "backend serves unscored"
                    )
                return functools.partial(
                    classifier_class("mesh"), mesh=m, **kw
                )
        if not kw:
            return classifier_class("tpu")
        return functools.partial(classifier_class("tpu"), **kw)
    raise ValueError(f"unknown backend {backend!r} (expected tpu|cpu)")


class _FlowCounters:
    """flow_* counters + occupancy gauge as a /metrics provider: the
    getter indirection survives classifier reloads (the WireStatsCounters
    pattern); a classifier without a flow tier renders nothing.
    ``prefix`` disambiguates independent tiers — the registry SUMS
    same-named counters, so the tenant arena's flow tier must not share
    the single-tenant tier's series."""

    def __init__(self, clf_getter, prefix: str = "") -> None:
        self._get = clf_getter
        self._prefix = prefix

    def counter_values(self):
        clf = self._get()
        fc = getattr(clf, "flow_counters", None)
        if clf is None or fc is None:
            return {}
        try:
            vals = fc()
        except Exception:
            return {}
        if not self._prefix:
            return vals
        return {f"{self._prefix}{k}": v for k, v in vals.items()}


def _batch_from_wire(wire: np.ndarray, tcp_flags=None) -> PacketBatch:
    """Rebuild a PacketBatch from a packed 4/7-word wire record (the
    ring ingest's fallback for backends without the packed-wire
    contract) — the host twin of kernels' unpack_wire."""
    from .flow import host_unpack_wire

    f = host_unpack_wire(np.asarray(wire, np.uint32))
    return PacketBatch(
        kind=f["kind"], l4_ok=f["l4_ok"], ifindex=f["ifindex"],
        ip_words=f["ip_words"], proto=f["proto"],
        dst_port=f["dst_port"], icmp_type=f["icmp_type"],
        icmp_code=f["icmp_code"], pkt_len=f["pkt_len"],
        tcp_flags=(
            None if tcp_flags is None
            else np.asarray(tcp_flags, np.int32).copy()
        ),
    )


class _ResidentCounters:
    """resident_* pool gauges as a /metrics provider (the
    _FlowCounters getter-indirection pattern: survives classifier
    reloads; a classifier without a resident pool renders nothing)."""

    def __init__(self, clf_getter) -> None:
        self._get = clf_getter

    def counter_values(self):
        clf = self._get()
        rc = getattr(clf, "resident_counters", None)
        if clf is None or rc is None:
            return {}
        try:
            return rc()
        except Exception:
            return {}


class _TelemetryCounters:
    """telemetry_* counters as a /metrics provider (same getter
    indirection: survives classifier reloads; no telemetry tier renders
    nothing)."""

    def __init__(self, clf_getter) -> None:
        self._get = clf_getter

    def counter_values(self):
        clf = self._get()
        tc = getattr(clf, "telemetry_counters", None)
        if clf is None or tc is None:
            return {}
        try:
            return tc()
        except Exception:
            return {}


class _MlScoreCounters:
    """mlscore_* counters as a /metrics provider (same getter
    indirection: survives classifier reloads; no scoring tier renders
    nothing)."""

    def __init__(self, clf_getter) -> None:
        self._get = clf_getter

    def counter_values(self):
        clf = self._get()
        mc = getattr(clf, "mlscore_counters", None)
        if clf is None or mc is None:
            return {}
        try:
            return mc()
        except Exception:
            return {}


class _PayloadCounters:
    """payload_* counters + pattern-set version gauge as a /metrics
    provider (same getter indirection: survives classifier reloads; no
    payload tier renders nothing)."""

    def __init__(self, clf_getter) -> None:
        self._get = clf_getter

    def counter_values(self):
        clf = self._get()
        pc = getattr(clf, "payload_counters", None)
        if clf is None or pc is None:
            return {}
        try:
            return pc()
        except Exception:
            return {}


# --- daemon ------------------------------------------------------------------

class Daemon:
    def __init__(
        self,
        state_dir: str,
        node_name: str,
        namespace: str = "ingress-node-firewall-system",
        backend: str = "cpu",
        poll_period_s: float = 30.0,
        debug_lookup: bool = False,
        registry: Optional[InterfaceRegistry] = None,
        store: Optional[InMemoryStore] = None,
        metrics_port: int = DEFAULT_METRICS_PORT,
        health_port: int = DEFAULT_HEALTH_PORT,
        file_poll_interval_s: float = 0.2,
        event_sink=None,
        events_socket: Optional[str] = None,
        ingest_chunk: int = DEFAULT_INGEST_CHUNK,
        pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
        max_tick_packets: int = DEFAULT_MAX_TICK_PACKETS,
        event_ring_size: int = 1 << 21,
        fused_deep: Optional[bool] = None,
        wire_codec: Optional[str] = None,
        compressed: Optional[bool] = None,
        h2d_overlap: bool = True,
        h2d_stage_depth: int = DEFAULT_H2D_STAGE_DEPTH,
        mesh: Optional[str] = None,
        deadline_us: Optional[float] = None,
        max_batch: Optional[int] = None,
        patch_staleness_us: Optional[float] = None,
        patch_max_ops: Optional[int] = None,
        tenants: Optional[int] = None,
        flow_table=None,
        resident: bool = False,
        ring: Optional[str] = None,
        telemetry=None,
        telemetry_drain: int = 256,
        trace: bool = False,
        trace_slow_us: float = 50_000.0,
        mlscore=None,
        mlscore_mode: Optional[str] = None,
        payload=None,
        payload_mode: Optional[str] = None,
        payload_plen: Optional[int] = None,
        superbatch_k: Optional[int] = None,
    ) -> None:
        self.state_dir = state_dir
        self.node_name = node_name
        self.namespace = namespace
        self.backend = backend
        self.debug_lookup = debug_lookup
        self.file_poll_interval_s = file_poll_interval_s
        self.ingest_chunk = max(1, int(ingest_chunk))
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.max_tick_packets = max(1, int(max_tick_packets))
        self.h2d_overlap = bool(h2d_overlap)
        self.h2d_stage_depth = max(1, int(h2d_stage_depth))
        # Stateful flow tier (--flow-table / INFW_FLOW_TABLE): an exact-
        # match verdict cache in front of the LPM+scan (infw.flow); the
        # daemon owns its observability (flow_* counters on /metrics,
        # FlowEvictRecords on the event ring) and the idle-loop age
        # sweep.  flow_table is a validated FlowConfig or None.
        self.flow_table = flow_table
        self._flow_attached: set = set()
        self._flow_age_last = 0.0
        # CoW arena upkeep (ISSUE-15): the idle-loop dedup sweep
        # re-hashes patched/cloned tenant slabs and re-merges pages
        # whose content re-converged (flips only — compile-free)
        self._tenant_dedup_last = 0.0
        # Zero-copy resident serving (--resident / INFW_RESIDENT,
        # ISSUE-12): the syncer's classifiers run the donated-buffer
        # fused serving loop; resident_* pool gauges export on /metrics.
        self.resident = bool(resident)
        # Device-resident telemetry plane (--telemetry / INFW_TELEMETRY,
        # ISSUE-13): count-min + top-K heavy-hitter tensors updated
        # inside the serving dispatch; the daemon owns the decimated
        # summarizer cadence (one small D2H per --telemetry-drain
        # admissions), the summary records on the obs event ring, the
        # telemetry_* counters on /metrics and the per-tenant
        # token-bucket sampling of raw deny-event export.
        self.telemetry = telemetry  # validated SketchSpec or None
        self.telemetry_drain = max(1, int(telemetry_drain))
        self._telemetry_attached: set = set()
        self._telemetry_drain_last = 0.0
        # MXU anomaly scoring (--mlscore [MODEL] / INFW_MLSCORE,
        # ISSUE-14): per-flow quantized inference fused into the
        # serving dispatch with shadow/enforce mitigation; the daemon
        # owns the anomaly-verdict records on the obs event ring, the
        # mlscore_* counters on /metrics and the <state-dir>/models/
        # hot-swap dir (versioned npz+manifest artifacts, consumed on
        # the idle loop — a swap behaves like a rule patch).
        self.mlscore = mlscore  # validated (ScoreSpec, ScoreModel) or None
        self.mlscore_mode = mlscore_mode or "shadow"
        self._mlscore_attached: set = set()
        self._mlscore_drain_last = 0.0
        # last models-dir hot-swapped artifact (consumed from disk) —
        # re-applied to rebuilt classifier generations so an escalation
        # rebuild can't silently revert to the launch-time model
        self._mlscore_swapped_model = None
        self.models_dir = os.path.join(state_dir, "models")
        # Payload matching tier (--payload [PATTERNS] / INFW_PAYLOAD,
        # ISSUE-19): batched Aho-Corasick multi-pattern matching over
        # the ring-sliced payload-prefix column, fused into the serving
        # dispatch with shadow/enforce mitigation; the daemon owns the
        # payload_* counters on /metrics and the <state-dir>/patterns/
        # hot-swap dir (versioned npz+manifest artifacts, consumed on
        # the idle loop — an in-bucket swap recompiles nothing).
        self.payload = payload  # patterns / AcModel / PayloadTier or None
        self.payload_mode = payload_mode or "shadow"
        self.payload_plen = payload_plen
        self._payload_attached: set = set()
        # last patterns-dir hot-swapped set (consumed from disk) —
        # re-applied to rebuilt classifier generations so an escalation
        # rebuild can't silently revert to the launch-time pattern set
        self._payload_swapped = None
        self.patterns_dir = os.path.join(state_dir, "patterns")
        # Serving-path tracing (--trace): per-stage span clocks through
        # the ingest/serving pipeline, exported as Prometheus histograms
        # on /metrics + sampled TraceSpanRecords for slow admissions.
        self.tracer = None
        if trace:
            from .obs.telemetry import SpanTracer

            self.tracer = SpanTracer(slow_us=float(trace_slow_us))
        # Persistent pinned host ingest ring (--ring / INFW_RING): a
        # preallocated shared-memory SPSC ring producers write packed
        # wire records into IN PLACE — the ingest loop admits by ring
        # cursor (no per-chunk file syscalls, no per-chunk numpy
        # reallocation on the hot path); the popped slot views double
        # as the H2D staging buffers and are released only after the
        # dispatch that read them materialized.
        # Device-side epoch loop (ISSUE-16): when the ring holds >= K
        # committed chunks of one shape class, the ingest loop stacks
        # them into ONE superbatch dispatch
        # (jaxpath.jitted_resident_superbatch) — K admissions chew
        # entirely on-device, one stacked fused readback.  K=1 disables
        # gathering (every chunk rides the single fused path).
        if superbatch_k is None:
            superbatch_k = int(os.environ.get("INFW_SUPERBATCH_K", "1") or 1)
        self.superbatch_k = max(1, int(superbatch_k))
        self.ingest_ring = None
        self._ring_inflight: deque = deque()
        if ring:
            from .ring import IngestRing

            # a payload tier grows each slot by the prefix column
            # (n * (L + 4) bytes) so producers can ship payload bytes
            # through the same zero-copy cursor discipline
            ring_pw = 0
            if payload is not None and backend != "cpu":
                from .kernels.wire_decode import PAYLOAD_PREFIX_WIDTHS

                ring_pw = int(payload_plen or PAYLOAD_PREFIX_WIDTHS[0])
            self.ingest_ring = IngestRing.create(
                ring, slots=max(8, 2 * self.pipeline_depth + 4),
                slot_packets=max(self.max_tick_packets, 4096),
                payload_width=ring_pw,
            )
        # Deadline-aware continuous microbatching (infw.scheduler): with
        # --deadline-us set, ingest jobs are sized by the LARGEST ladder
        # batch whose measured service time still fits the per-packet
        # deadline budget (admit-by-deadline) instead of the fixed
        # ingest_chunk; the batch-size ladder is pre-warmed at table
        # load so shape-driven jit recompiles never land on the serving
        # path, and scheduler observability (queue depth, batch-size
        # histogram, deadline misses, per-format wire bytes) exports on
        # /metrics with misses also emitted on the obs event ring.
        self.max_batch = max(1, int(max_batch)) if max_batch else self.ingest_chunk
        self.sched_stats = None
        self._sched_policy = None
        self._prewarmed_gen = None
        if deadline_us is not None:
            from .scheduler import DeadlinePolicy, SchedulerStats

            self.sched_stats = SchedulerStats()
            self._sched_policy = DeadlinePolicy(
                deadline_s=float(deadline_us) * 1e-6,
                max_admit=self.max_batch,
            )
            # ping-pong staging is the point of the serving loop: keep at
            # least one prepared batch ahead of the in-flight one
            self.h2d_stage_depth = max(2, self.h2d_stage_depth)
        self.registry = registry if registry is not None else default_registry
        # Update-storm edit batching (infw.txn): rule edits dropped into
        # <state-dir>/edits/ queue in a TxnBatcher and flush as ONE
        # folded patch transaction when (a) the oldest edit exceeds the
        # staleness deadline (--patch-staleness-us/INFW_PATCH_STALENESS_US)
        # or (b) the batch threshold (--patch-max-ops) trips — checked
        # between classify admissions inside the ingest tick AND on the
        # idle file loop, so edits never stall classification and
        # verdict staleness stays bounded.  Counters + the staleness
        # histogram export on /metrics; each flush emits a
        # PatchTxnRecord on the obs event ring.
        from .txn import (
            DEFAULT_MAX_OPS,
            DEFAULT_STALENESS_US,
            TxnBatcher,
            TxnStats,
        )

        self.patch_staleness_us = float(
            patch_staleness_us if patch_staleness_us is not None
            else DEFAULT_STALENESS_US
        )
        self.patch_max_ops = int(patch_max_ops or DEFAULT_MAX_OPS)
        self.txn_stats = TxnStats()
        self.txn_batcher = TxnBatcher(
            staleness_s=self.patch_staleness_us * 1e-6,
            max_ops=self.patch_max_ops,
        )
        # at most one flush in flight, on its own thread (see
        # _maybe_flush_edits); only the file loop mutates this
        self._edit_flush_thread = None

        self.nodestates_dir = os.path.join(state_dir, "nodestates")
        self.ingest_dir = os.path.join(state_dir, "ingest")
        self.edits_dir = os.path.join(state_dir, "edits")
        self.out_dir = os.path.join(state_dir, "out")
        self.events_path = os.path.join(state_dir, "events.log")
        # Multi-tenant paged arena mode (--tenants/INFW_TENANTS): each
        # tenant is a slab in one preallocated pool, created lazily when
        # <state-dir>/tenants/<name>/edits/ first appears; per-tenant
        # edit files apply through the SAME folded-transaction codec as
        # the single-tenant edits dir, landing as per-slab row scatters.
        self.tenants_max = max(0, int(tenants or 0))
        self.tenants_dir = os.path.join(state_dir, "tenants")
        self.tenant_registry = None
        dirs = [self.nodestates_dir, self.ingest_dir, self.edits_dir,
                self.out_dir]
        if self.tenants_max:
            dirs.append(self.tenants_dir)
        if self.mlscore is not None:
            dirs.append(self.models_dir)
        if self.payload is not None:
            dirs.append(self.patterns_dir)
        for d in dirs:
            os.makedirs(d, exist_ok=True)

        if backend == "tpu":
            platform_mod.enable_jax_compile_cache(
                os.path.join(state_dir, "jax-cache")
            )

        # Per-daemon metrics registry (controller-runtime gives each
        # manager its own, statistics.go:79-86): /metrics serves whatever
        # collectors are registered here — the daemon's own Statistics
        # plus any additional pollers a composition adds.
        self.metrics_registry = MetricsRegistry()
        self.stats = Statistics(poll_period_s=poll_period_s)
        self.stats.register(self.metrics_registry)
        self.syncer = DataplaneSyncer(
            classifier_factory=make_classifier_factory(
                backend, fused_deep=fused_deep, wire_codec=wire_codec,
                mesh=mesh, compressed=compressed,
                flow_table=flow_table if backend != "cpu" else None,
                resident=self.resident if backend != "cpu" else None,
                telemetry=self.telemetry if backend != "cpu" else None,
                mlscore=self.mlscore if backend != "cpu" else None,
                mlscore_mode=self.mlscore_mode,
                payload=self.payload if backend != "cpu" else None,
                payload_mode=self.payload_mode,
                payload_plen=self.payload_plen,
            ),
            registry=self.registry,
            stats_poller=self.stats,
            checkpoint_dir=os.path.join(state_dir, "checkpoint"),
        )
        self.store = store if store is not None else InMemoryStore()
        self.reconciler = NodeStateReconciler(
            self.store, self.syncer, node_name=node_name, namespace=namespace
        )
        self.store.watch(IngressNodeFirewallNodeState.KIND, self._on_store_event)

        # perf-ring analogue (kernel.c perf event array): once full,
        # incoming records are dropped and counted as LostSamples (the
        # oldest events survive a deny storm), so capacity trades event
        # completeness for memory
        self.ring = EventRing(capacity=max(64, int(event_ring_size)))
        self._event_file = open(self.events_path, "a", buffering=1)
        # Sidecar composition (daemonset.yaml:54-67): events always land in
        # events.log (the in-process record) and, when --events-socket is
        # given, are ALSO shipped as unixgram datagrams to the follower
        # process (cmd/syslog/syslog.go:16) — fire-and-forget, a dead
        # sidecar never blocks the dataplane.
        self._events_socket_sink = None
        if events_socket:
            from .obs.sidecar import UnixDatagramSink

            self._events_socket_sink = UnixDatagramSink(events_socket)
        base_sink = event_sink if event_sink is not None else self._write_event_line
        if self._events_socket_sink is not None:
            def sink(line, _base=base_sink, _sock=self._events_socket_sink):
                _base(line)
                _sock(line)
        else:
            sink = base_sink
        self.events_logger = EventsLogger(
            self.ring,
            sink,
            iface_names={i.index: i.name for i in self.registry.list()},
            # replay-scale batches drain as vectorized binary rows next
            # to events.log; the line sink gets one summary line each
            spill_path=os.path.join(
                os.path.dirname(self.events_path), "deny-events.bin"
            ),
        )
        # deny-event loss/queue totals on /metrics (events.go:79-82's
        # LostSamples, exported instead of only logged)
        self.metrics_registry.register_counters(self.ring)
        # background-thread crash accounting (infw._threads.spawn): zero
        # in a healthy control plane, so any nonzero reading is a page
        from ._threads import CRASH_COUNTERS

        self.metrics_registry.register_counters(CRASH_COUNTERS)
        # per-format H2D wire accounting (TpuClassifier.wire_stats) as
        # counters; the getter indirection survives table reloads and the
        # CPU backend (no wire_stats) renders nothing.  Registry holds
        # providers weakly, so keep the strong ref here.
        from .scheduler import WireStatsCounters

        self._wire_counters = WireStatsCounters(
            lambda: self.syncer.classifier
        )
        self.metrics_registry.register_counters(self._wire_counters)
        if self.sched_stats is not None:
            self.metrics_registry.register_counters(self.sched_stats)
        # patch-transaction counters + staleness histogram
        # (ingressnodefirewall_node_patch_txn_*)
        self.metrics_registry.register_counters(self.txn_stats)
        if (self.flow_table is not None or self.resident) and backend != "cpu":
            # flow_* counters + occupancy gauge; the getter indirection
            # survives table reloads exactly like the wire counters
            # (resident mode implies a flow tier, so its counters export
            # here too)
            self._flow_counters = _FlowCounters(
                lambda: self.syncer.classifier
            )
            self.metrics_registry.register_counters(self._flow_counters)
        if self.resident and backend != "cpu":
            # resident_* pool gauges (dispatches, context reuses,
            # fallbacks, steady-state allocation counter) — the
            # observability half of the zero-alloc contract
            self._resident_counters = _ResidentCounters(
                lambda: self.syncer.classifier
            )
            self.metrics_registry.register_counters(self._resident_counters)
        if self.telemetry is not None and backend != "cpu":
            # telemetry_* counters (updates, drains, summaries, sampled/
            # suppressed raw events, drain seq) — the decimation's
            # accounting half
            self._telemetry_counters = _TelemetryCounters(
                lambda: self.syncer.classifier
            )
            self.metrics_registry.register_counters(self._telemetry_counters)
        if self.mlscore is not None and backend != "cpu":
            # mlscore_* counters (updates, anomalies, enforced denies,
            # model swaps, drain seq) — the policy tier's accounting
            self._mlscore_counters = _MlScoreCounters(
                lambda: self.syncer.classifier
            )
            self.metrics_registry.register_counters(self._mlscore_counters)
        if self.payload is not None and backend != "cpu":
            # payload_* counters (admissions, scanned lanes, matches,
            # enforced rewrites, pattern swaps) + the pattern-set
            # version gauge — the matching tier's accounting
            self._payload_counters = _PayloadCounters(
                lambda: self.syncer.classifier
            )
            self.metrics_registry.register_counters(self._payload_counters)
        if self.tracer is not None:
            # span histograms (ingressnodefirewall_node_span_us) +
            # trace_* sample counters; slow-admission TraceSpanRecords
            # land on the obs event ring next to deny events
            self.tracer.attach_ring(self.ring)
            self.metrics_registry.register_histograms(
                self.tracer.histograms
            )
            self.metrics_registry.register_counters(self.tracer)
        if self.ingest_ring is not None:
            # ring_* cursor/backpressure gauges
            self.metrics_registry.register_counters(self.ingest_ring)
        if self.tenants_max:
            self.tenant_registry = self._build_tenant_registry()
            # tenant_* counters (active/free slabs, swaps, flips,
            # compactions, per-tenant packets/verdicts) on /metrics
            self.metrics_registry.register_counters(self.tenant_registry)
        # tenant names whose create failed deterministically (e.g. the
        # pool is smaller than the dirs an operator made): logged once,
        # then skipped — not retried every idle-loop pass forever
        self._tenant_create_failed: set = set()
        self.debug_buffer = DebugLookupBuffer()

        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._servers: List[ThreadingHTTPServer] = []
        self._known_state_files: Dict[str, float] = {}
        # Files rejected deterministically (schema/compile): remembered by
        # mtime so they are logged once, not every tick — but kept separate
        # from _known_state_files so deleting a rejected file never counts
        # as a CR deletion (which would reset the dataplane).
        self._rejected_state_files: Dict[str, float] = {}
        self.metrics_port = metrics_port
        self.health_port = health_port

    # -- event sink ----------------------------------------------------------

    def _write_event_line(self, line: str) -> None:
        self._event_file.write(line + "\n")

    # -- store-driven reconcile ----------------------------------------------

    def _on_store_event(self, event: str, obj) -> None:
        try:
            if event == "DELETED":
                if (
                    obj.metadata.name == self.node_name
                    and obj.metadata.namespace == self.namespace
                ):
                    # finalizer path already synced the delete; nothing to do
                    return
            self.reconciler.reconcile(obj.metadata.name, obj.metadata.namespace)
        except (SyncError, CompileError, InterfaceError) as e:
            log.error("reconcile failed: %s", e)

    # -- file-driven desired state -------------------------------------------

    def scan_nodestates_once(self) -> None:
        """State-dir protocol: <nodestates>/<node-name>.json holds the
        NodeState CR dict; file deletion is CR deletion."""
        seen = {}
        for fn in os.listdir(self.nodestates_dir):
            if not fn.endswith(".json"):
                continue
            path = os.path.join(self.nodestates_dir, fn)
            try:
                mtime = os.path.getmtime(path)
            except FileNotFoundError:
                continue
            seen[fn] = mtime
            if self._known_state_files.get(fn) == mtime:
                continue
            if self._rejected_state_files.get(fn) == mtime:
                continue
            try:
                with open(path) as f:
                    doc = json.load(f)
                ns_obj = IngressNodeFirewallNodeState.from_dict(doc)
            except OSError as e:
                # I/O errors can be transient; retry next tick.
                log.error("bad nodestate file %s: %s", fn, e)
                continue
            except (json.JSONDecodeError, TypeError, AttributeError, ValueError, KeyError) as e:
                # Deterministically unparseable bytes: reject once by mtime
                # like the schema tier, not every tick.
                log.error("bad nodestate file %s: %s", fn, e)
                self._rejected_state_files[fn] = mtime
                continue
            if not ns_obj.metadata.name:
                ns_obj.metadata.name = fn[: -len(".json")]
            if not ns_obj.metadata.namespace:
                ns_obj.metadata.namespace = self.namespace
            if ns_obj.metadata.name != self.node_name:
                continue
            schema_errs = validate_nodestate_schema(ns_obj)
            if schema_errs:
                # The file protocol has no API server in front of it; apply
                # the schema tier here so a misspelled protocol or order=0
                # is rejected with CRD-style messages, not a compile error.
                log.error("schema-invalid nodestate %s: %s", fn, "; ".join(schema_errs))
                self._rejected_state_files[fn] = mtime
                continue
            try:
                self.syncer.sync_interface_ingress_rules(
                    ns_obj.spec.interface_ingress_rules, False
                )
                self._known_state_files[fn] = mtime
            except CompileError as e:
                # Deterministic input error: re-reading the same bytes can
                # never succeed, so record the mtime and reject once.
                log.error("sync failed for %s: %s", fn, e)
                self._rejected_state_files[fn] = mtime
            except (SyncError, InterfaceError) as e:
                # Possibly transient (interface not up yet, attach EBUSY):
                # leave unrecorded so the next tick retries, but never
                # abort the rest of the scan.
                log.error("sync failed for %s: %s", fn, e)
        for fn in list(self._rejected_state_files):
            if fn not in seen:
                del self._rejected_state_files[fn]
        for fn in list(self._known_state_files):
            if fn not in seen:
                del self._known_state_files[fn]
                try:
                    self.syncer.sync_interface_ingress_rules({}, True)
                except (SyncError, CompileError, InterfaceError) as e:
                    log.error("delete sync failed for %s: %s", fn, e)

    # -- rule-edit files (the update-storm control plane) --------------------

    def scan_edits_once(self) -> int:
        """Queue every edit file in <state-dir>/edits/ into the
        transaction batcher (infw.txn edit-file protocol: one JSON doc
        of single-key ops per file, written tmp+rename by
        tools/churngen.py or any control plane).  Files are consumed in
        sorted order; a deterministically bad file is removed and
        logged, never wedging the scan.  Returns ops queued."""
        from .txn import read_edit_file

        n = 0
        for fn in sorted(os.listdir(self.edits_dir)):
            path = os.path.join(self.edits_dir, fn)
            if fn.endswith(".tmp") or not os.path.isfile(path):
                continue
            if fn.endswith("-manifest.json"):
                continue  # churngen's schedule sidecar, not an edit file
            try:
                ops = read_edit_file(path)
            except (OSError, ValueError, KeyError, TypeError) as e:
                log.error("bad edit file %s: %s", fn, e)
                try:
                    os.remove(path)
                except OSError as re:
                    log.error("could not remove bad edit file %s: %s",
                              fn, re)
                continue
            self.txn_batcher.queue_many(ops)
            n += len(ops)
            try:
                os.remove(path)
            except OSError as e:
                log.error("could not remove edit file %s: %s", fn, e)
        return n

    def _build_tenant_registry(self):
        """The multi-tenant arena control plane: one preallocated pool
        sized from --tenants and the INFW_TENANT_SLAB_ENTRIES /
        INFW_TENANT_RULE_SLOTS geometry knobs, with a staging page so
        every full-ruleset tenant update is a hot swap (page-table row
        flip), never a serving-path re-upload."""
        from .backend.tpu import ArenaClassifier
        from .kernels import jaxpath
        from .syncer import TenantRegistry

        entries = int(os.environ.get("INFW_TENANT_SLAB_ENTRIES") or 1024)
        slots = int(os.environ.get("INFW_TENANT_RULE_SLOTS") or 16)
        spec = jaxpath.make_arena_spec(
            "ctrie",
            pages=max(self.tenants_max + 2, 4),
            max_tenants=self.tenants_max,
            entries=entries,
            rule_slots=slots,
            lut_rows=64,
            root_nodes=4,
            node_rows=4 * entries,
            target_rows=8 * entries,
            d_max=18,
        )
        clf = ArenaClassifier(spec, flow_table=self.flow_table)
        if self.flow_table is not None:
            self._attach_flow_events(clf)
            # registry holds providers weakly — keep the strong ref;
            # prefixed so the arena tier's series never sums into the
            # single-tenant flow_* series
            self._tenant_flow_counters = _FlowCounters(
                lambda: clf, prefix="tenant_"
            )
            self.metrics_registry.register_counters(
                self._tenant_flow_counters
            )
        return TenantRegistry(clf, rule_width=slots, event_ring=self.ring)

    def scan_tenant_edits_once(self) -> int:
        """Apply every per-tenant edit file under
        <state-dir>/tenants/<name>/edits/ (same JSON edit-file codec as
        the single-tenant dir) as ONE folded transaction per file
        through the tenant registry.  A tenant is created (empty) the
        first time its directory appears; bad files are consumed and
        logged like the single-tenant scan.  Returns ops applied."""
        if self.tenant_registry is None:
            return 0
        from .txn import read_edit_file

        n = 0
        try:
            names = sorted(os.listdir(self.tenants_dir))
        except OSError:
            return 0
        for name in names:
            edits = os.path.join(self.tenants_dir, name, "edits")
            if not os.path.isdir(edits):
                continue
            if name not in self.tenant_registry.tenant_ids_by_name():
                if name in self._tenant_create_failed:
                    continue
                try:
                    self.tenant_registry.create_tenant(name, {})
                except Exception as e:
                    log.error(
                        "could not create tenant %r (will not retry; "
                        "its edit files are left in place): %s", name, e,
                    )
                    self._tenant_create_failed.add(name)
                    continue
            for fn in sorted(os.listdir(edits)):
                path = os.path.join(edits, fn)
                if fn.endswith(".tmp") or not os.path.isfile(path):
                    continue
                try:
                    ops = read_edit_file(path)
                    self.tenant_registry.apply_edit_transaction(name, ops)
                    n += len(ops)
                except Exception as e:
                    log.error("bad tenant edit file %s/%s: %s", name, fn, e)
                try:
                    os.remove(path)
                except OSError as e:
                    log.error("could not remove tenant edit file %s: %s",
                              fn, e)
        return n

    def _maybe_flush_edits(self, force: bool = False) -> bool:
        """Start a flush of the queued edit transaction when the
        bounded-staleness policy trips (or ``force``): ONE folded patch
        generation through the syncer, counters + staleness histogram
        into TxnStats, a PatchTxnRecord on the obs ring.  The flush runs
        on its OWN thread (at most one in flight — later edits keep
        coalescing toward the next transaction), so neither the ingest
        tick's admissions nor the idle loop ever wait on it; in
        particular an escalated columnar rebuild, which can take
        seconds at the 1M tier, overlaps classification instead of
        starving it (the scheduler-path slot contract, daemon half).
        Returns True when a flush was started."""
        batcher = getattr(self, "txn_batcher", None)
        if batcher is None or len(batcher) == 0:
            return False
        t = getattr(self, "_edit_flush_thread", None)
        if t is not None and t.is_alive():
            return False  # one flush in flight; edits keep coalescing
        reason = "manual" if force else batcher.should_flush()
        if reason is None:
            return False
        if self.syncer.classifier is None or self.syncer.classifier.tables is None:
            # no dataplane yet: keep queuing — the staleness clock keeps
            # running, so the first sync is followed by a flush
            return False
        items = batcher.drain()
        if not items:
            return False

        def work() -> None:
            try:
                self.syncer.apply_edit_transaction(
                    [op for op, _ts in items], reason=reason,
                    enqueue_ts=[ts for _op, ts in items],
                    stats=self.txn_stats, ring=self.ring,
                )
            except Exception as e:
                # a deterministically bad transaction must not re-queue
                # forever; drop it with a loud log (the model checker
                # and edit-file validation make this the rare path)
                log.error(
                    "edit transaction flush failed (%d ops dropped): %s",
                    len(items), e,
                )

        th = spawn(work, name="infw-edit-flush")
        self._edit_flush_thread = th
        return True

    # -- ingest --------------------------------------------------------------
    #
    # (helpers below are module-level: _expand_wire, _concat_batches,
    # stats_from_results)

    def process_ingest_once(self) -> int:
        """Classify every frames file in the ingest dir; write verdict
        summaries to out/; emit deny events; consume the file.

        Cross-file batching: all pending files (bounded by
        ``max_tick_packets``) are parsed up front, their packets regrouped
        into family-homogeneous device jobs of ``ingest_chunk`` rows that
        SPAN file boundaries — on a high-latency link each dispatch/
        readback round trip is the dominant cost, so ten small files share
        a handful of round trips instead of paying two each.  Jobs are
        kept ``pipeline_depth`` deep in flight so H2D, kernel and D2H of
        consecutive jobs overlap (the inline per-packet role of
        bpf/ingress_node_firewall_kernel.c:412-457).

        Failure isolation: a failed MERGED job is re-dispatched as
        per-file jobs, so a fault attributable to one file's packets
        poisons only that file (left on disk for retry) while its
        job-mates complete; statistics are computed host-side per file
        from the verdicts and applied only after the file is consumed —
        exactly once across any retry."""
        clf = self.syncer.classifier
        if clf is None or clf.tables is None:
            return 0
        processed = 0
        # getattr: tolerate the bare ingest-only harness (bench.py and
        # the ingest tests build Daemon.__new__ without __init__, the
        # h2d_stage_depth pattern below)
        tracer = getattr(self, "tracer", None)

        # Deadline scheduling (infw.scheduler, --deadline-us): job sizes
        # come from the policy's service-time model — the largest ladder
        # batch that still meets the per-packet deadline budget — scaled
        # by the classifier's data-parallel width (a mesh spreads one
        # admission over its "data" shards, so the per-chip budget
        # multiplies; a single-chip pool serves it unchanged).  Without
        # the knob the historical fixed ingest_chunk applies.
        # (getattr: the bench's Daemon.__new__ ingest harness constructs
        # no scheduler state.)
        policy = getattr(self, "_sched_policy", None)
        sched_stats = getattr(self, "sched_stats", None)
        if policy is not None:
            from .scheduler import data_parallel_width, ladder_floor

            self._maybe_prewarm_ladder(clf)
            width = data_parallel_width(clf)
            # quantize the cap to a pre-warmed ladder step: with a
            # non-pow2 mesh width, service_cap * width can land between
            # steps, and a chunk-capped pad would otherwise emit a shape
            # the prewarm never compiled
            chunk = ladder_floor(
                policy.service_cap() * width, self.max_batch * width
            )
            min_bucket_exp = 5  # the ladder starts at 32 (pre-warmed)
        else:
            chunk = self.ingest_chunk
            min_bucket_exp = 6

        def finalize(fctx) -> None:
            """Write verdicts, consume the file, then apply stats and
            emit events — strictly AFTER the source file is removed: a
            failure anywhere earlier leaves the file for a clean retry
            with zero double-counted statistics and no duplicate deny
            events."""
            nonlocal processed
            batch, fb, fn = fctx["batch"], fctx["frames"], fctx["fn"]
            results, xdp = fctx["results"], fctx["xdp"]
            if self.debug_lookup:
                self.debug_buffer.record_batch(batch)
            # Per-packet verdicts go to a binary sidecar (little-endian u32
            # per packet, file order), NOT into the JSON — a replay-scale
            # (10M-packet) file would otherwise produce a ~100MB JSON doc
            # built in memory.  The JSON stays a bounded summary.
            results.astype("<u4").tofile(
                os.path.join(self.out_dir, fn + ".verdicts.bin")
            )
            summary = {
                "file": fn,
                "packets": len(batch),
                "pass": int((xdp == 2).sum()),
                "drop": int((xdp == 1).sum()),
                "results_file": fn + ".verdicts.bin",
            }
            # tmp + rename like every other file in the protocol: readers
            # poll for the path and must never see a half-written doc
            jpath = os.path.join(self.out_dir, fn + ".verdicts.json")
            with open(jpath + ".tmp", "w") as f:
                json.dump(summary, f)
            os.replace(jpath + ".tmp", jpath)
            os.remove(fctx["path"])
            clf.stats.add(stats_from_results(results, np.asarray(batch.pkt_len)))
            self._emit_deny_sampled(clf, results, batch.ifindex,
                                    batch.pkt_len, fb, batch)
            processed += 1

        def seg_done(fctx) -> None:
            fctx["remaining"] -= 1
            if fctx["remaining"] == 0 and not fctx["failed"]:
                try:
                    finalize(fctx)
                except Exception as e:
                    log.error("ingest finalize failed for %s: %s", fctx["fn"], e)

        # ---- phase 1: read + parse pending files (bounded per tick) ----
        files = []
        total = 0
        for fn in sorted(os.listdir(self.ingest_dir)):
            path = os.path.join(self.ingest_dir, fn)
            if fn.endswith(".tmp") or not os.path.isfile(path):
                continue
            if files and total >= self.max_tick_packets:
                break  # the rest belongs to the next tick
            try:
                t_read0 = time.perf_counter()
                fb = read_frames_any(path)
                t_read1 = time.perf_counter()
                batch = parse_frames_buf(fb)
                if tracer is not None:
                    # file-drop taxonomy: ingest = file read, pack =
                    # frame parse (the wire pack itself is charged per
                    # job in prepare below)
                    h = tracer.histograms
                    h.observe("ingest", (t_read1 - t_read0) * 1e6)
                    h.observe("pack",
                              (time.perf_counter() - t_read1) * 1e6)
            except (OSError, ValueError, struct.error, IndexError) as e:
                # A parse crash must consume the file like a bad header
                # does — leaving it would wedge the tick at this file
                # every poll and starve later-sorted files.
                log.error("bad ingest file %s: %s", fn, e)
                try:
                    os.remove(path)
                except OSError as re:
                    # An unremovable file (EACCES/EROFS, racing unlink)
                    # must not abort the tick — that would starve every
                    # later-sorted file behind it.
                    log.error("could not remove bad ingest file %s: %s",
                              fn, re)
                continue
            # Arrival timestamp for the deadline accounting: the file's
            # DROP time (mtime — write_frames_file's os.replace stamps
            # it), mapped into the monotonic domain by age, so time the
            # file spent queued in the ingest dir behind a busy tick (or
            # the ladder prewarm) counts against the deadline — never
            # the parse or dispatch time (the coordinated-omission rule).
            try:
                age = max(0.0, time.time() - os.path.getmtime(path))
            except OSError:
                age = 0.0
            n = len(batch)
            fctx = {
                "fn": fn, "path": path, "frames": fb, "batch": batch,
                "results": np.zeros(n, np.uint32),
                "xdp": np.full(n, 2, np.int32),
                "remaining": 0, "failed": False,
                "t_arrival": time.monotonic() - age,
            }
            if n == 0:
                try:
                    finalize(fctx)  # no device dispatch for an empty file
                except Exception as e:
                    log.error("ingest finalize failed for %s: %s", fn, e)
                continue
            files.append(fctx)
            total += n
        if not files:
            return processed

        # ---- phase 2: family- and depth-homogeneous jobs spanning files --
        # v4-only jobs take the truncated trie walk (3 gathers, not 15);
        # v6 jobs additionally split by the classifier's depth classes
        # (v6_depth_groups): most v6 packets' root slots need only a few
        # deep levels, and walk cost is linear in levels.
        jobs: deque = deque()
        depth_groups_of = getattr(clf, "v6_depth_groups", None)
        group_keys = [(False, None)]
        if depth_groups_of is None:
            group_keys.append((True, None))
        else:
            # discover this generation's classes from the first v6 split
            seen_depths = set()
            per_file_v6 = {}
            for fctx in files:
                kinds = np.asarray(fctx["batch"].kind)
                g = np.nonzero(kinds == KIND_IPV6)[0]
                b = fctx["batch"]
                groups = depth_groups_of(b.ifindex, b.ip_words, g)
                per_file_v6[id(fctx)] = dict(
                    (d, idx) for d, idx in groups
                )
                seen_depths.update(d for d, _ in groups)
            # d is the (class, generation) pair from v6_depth_groups;
            # shallow classes first, full depth (class None) last
            group_keys += [(True, d) for d in sorted(
                seen_depths,
                key=lambda d: (d[0] is None, -1 if d[0] is None else d[0]),
            )]
        for want_v6, depth in group_keys:
            cur = []
            cur_n = 0
            for fctx in files:
                if want_v6 and depth_groups_of is not None:
                    g = per_file_v6[id(fctx)].get(depth)
                    if g is None:
                        continue
                else:
                    kinds = np.asarray(fctx["batch"].kind)
                    g = np.nonzero((kinds == KIND_IPV6) == want_v6)[0]
                pos = 0
                while pos < len(g):
                    take = g[pos : pos + (chunk - cur_n)]
                    cur.append((fctx, take))
                    fctx["remaining"] += 1
                    cur_n += len(take)
                    pos += len(take)
                    if cur_n >= chunk:
                        jobs.append({"segments": cur, "retry": False,
                                     "depth": depth})
                        cur, cur_n = [], 0
            if cur:
                jobs.append({"segments": cur, "retry": False,
                             "depth": depth})

        packed_ok = (
            getattr(clf, "supports_packed", None) is not None
            and clf.supports_packed()
        )

        def _bucket(n: int) -> int:
            """Pad jobs to power-of-two row counts (capped at the chunk
            size) so tail jobs reuse compiled executables instead of
            jit-compiling a fresh shape mid-tick.  Padding rows are
            KIND_OTHER (always PASS, no stats — and per-file statistics
            come from the host-side verdicts anyway, so inert padding is
            free).  Scheduler mode starts the ladder at 32 (every step
            pre-warmed); the legacy floor stays 64."""
            if n >= chunk:
                return n
            return min(1 << max(min_bucket_exp, (n - 1).bit_length()), chunk)

        # Double-buffered ingestion: ``prepare`` does the HOST half of a
        # dispatch (segment gather + wire pack + codec encode) and — on
        # backends exposing prepare_packed — STARTS the H2D copy of the
        # payload; ``launch`` invokes the classify on the staged plan.
        # The drain loop below keeps up to ``h2d_stage_depth`` prepared
        # jobs ahead of the in-flight window, so while one chunk's
        # classify runs on device, the next chunk's transfer is already
        # in flight and the one after that is being packed/encoded on
        # the host — the pipeline never stalls on a cold H2D copy.
        # (getattr defaults keep the bench/tests' Daemon.__new__ ingest
        # harnesses working without listing every knob.)
        h2d_overlap = bool(getattr(self, "h2d_overlap", True))
        can_stage = packed_ok and hasattr(clf, "prepare_packed")

        def prepare(job):
            """Host pack (+ staged H2D start).  Returns the launch
            payload, or None when every segment already failed; raises
            like the old dispatch did (the caller maps it to
            job_failed)."""
            nonlocal packed_ok, can_stage
            t_prep0 = time.perf_counter()
            segs = [(f, idx) for f, idx in job["segments"] if not f["failed"]]
            job["segments"] = segs
            if not segs:
                return None
            n = sum(len(idx) for _f, idx in segs)
            if tracer is not None:
                job["trace"] = tracer.begin(n)
            if packed_ok:
                parts = [
                    f["batch"].pack_wire_subset(np.ascontiguousarray(idx, np.int64))
                    for f, idx in segs
                ]
                width = max(w.shape[1] for w, _v4 in parts)
                wire = np.concatenate(
                    [w if w.shape[1] == width else expand_wire_v4(w)
                     for w, _v4 in parts]
                )
                pad = _bucket(n) - n
                if pad:
                    padrows = np.zeros((pad, width), np.uint32)
                    padrows[:, 0] = KIND_OTHER
                    wire = np.concatenate([wire, padrows])
                v4_only = all(v4 for _w, v4 in parts)
                if can_stage and h2d_overlap:
                    try:
                        t_h2d0 = time.perf_counter()
                        plan = clf.prepare_packed(
                            wire, v4_only, depth=job.get("depth")
                        )
                        tr = job.get("trace")
                        if tr is not None:
                            tr.add("pack", t_h2d0 - t_prep0)
                            tr.add("h2d", time.perf_counter() - t_h2d0)
                        return ("plan", plan)
                    except RuntimeError:
                        if clf.supports_packed() or clf.active_path is None:
                            raise
                        packed_ok = can_stage = False
                        log.warning(
                            "table flipped to wide-ruleId mid-tick; "
                            "falling back to unpacked classify"
                        )
                else:
                    return ("wire", wire, v4_only, job.get("depth"))
            merged = packets_mod.concat(
                [f["batch"].take(idx) for f, idx in segs]
            ).pad_to(_bucket(n))
            return ("batch", merged)

        def launch(job, prep):
            """Dispatch the prepared job.  Returns a PendingClassify, or
            raises (eager backends raise HERE, async ones at .result())."""
            nonlocal packed_ok, can_stage
            if prep[0] == "plan":
                return clf.classify_prepared(prep[1], apply_stats=False)
            if prep[0] == "wire":
                _tag, wire, v4_only, depth = prep
                try:
                    return clf.classify_async_packed(
                        wire, v4_only, apply_stats=False, depth=depth,
                    )
                except RuntimeError:
                    # A concurrent load_tables can flip the table to
                    # wide-ruleId mid-tick; re-check and fall through to
                    # the unpacked path instead of poisoning every
                    # in-flight file (the retry jobs would raise again,
                    # still packed).  A CLOSED classifier also fails
                    # supports_packed — that is not a format flip and the
                    # unpacked path would raise identically, so re-raise.
                    if clf.supports_packed() or clf.active_path is None:
                        raise
                    packed_ok = can_stage = False  # sticky for this tick
                    log.warning(
                        "table flipped to wide-ruleId mid-tick; "
                        "falling back to unpacked classify"
                    )
                    # job["segments"] as filtered at PREPARE time — the
                    # drain's offset walk is aligned to that list, so a
                    # file failing between prepare and launch must not
                    # re-filter here (drain skips failed files on write)
                    segs = job["segments"]
                    n = sum(len(idx) for _f, idx in segs)
                    merged = packets_mod.concat(
                        [f["batch"].take(idx) for f, idx in segs]
                    ).pad_to(_bucket(n))
                    return clf.classify_async(merged, apply_stats=False)
            return clf.classify_async(prep[1], apply_stats=False)

        def job_failed(job, err) -> None:
            """A merged job's fault cannot be attributed to one file:
            re-dispatch each segment as its own single-file job.  A retry
            job's fault CAN be attributed — poison that file."""
            if not job["retry"]:
                log.warning("ingest job failed (%s); retrying per file", err)
                for f, idx in job["segments"]:
                    jobs.append({"segments": [(f, idx)], "retry": True,
                                 "depth": job.get("depth")})
                return
            for f, _idx in job["segments"]:
                if not f["failed"]:
                    f["failed"] = True
                    log.error("ingest classify failed for %s: %s", f["fn"], err)
                seg_done(f)

        def note_sched_drain(job, t_done: float) -> None:
            """Scheduler accounting at job completion: feed the observed
            launch->materialize latency into the service model (the
            admit-by-deadline sizing input for the NEXT jobs), count
            per-packet deadline misses from each file's ARRIVAL time,
            and emit a DeadlineMissRecord on the obs event ring."""
            n = sum(len(idx) for _f, idx in job["segments"])
            t_launch = job.get("t_launch")
            if n and t_launch is not None:
                from .scheduler import ladder_bucket

                # bucket by the tick's admission cap (chunk), not the
                # per-chip max_admit: mesh jobs dispatch at width-scaled
                # shapes and must feed the estimate for THAT bucket
                policy.service.observe(
                    ladder_bucket(n, chunk), t_done - t_launch
                )
            n_miss, worst = 0, 0.0
            for f, idx in job["segments"]:
                lat = t_done - f["t_arrival"]
                worst = max(worst, lat)
                if lat > policy.deadline_s:
                    n_miss += len(idx)
            if sched_stats is not None:
                sched_stats.note_complete(n, n_miss)
                sched_stats.set_queue_depth(
                    max(0, sched_stats.queue_depth - n)
                )
            if n_miss:
                from .obs.events import DeadlineMissRecord

                self.ring.push(DeadlineMissRecord(
                    n_miss=n_miss, batch=n, worst_us=worst * 1e6,
                    deadline_us=policy.deadline_s * 1e6,
                ))

        def drain_one() -> None:
            job, pending = inflight.popleft()
            tr = job.get("trace")
            try:
                t_mat0 = time.perf_counter()
                out = pending.result()
                if tr is not None:
                    tr.add("materialize", time.perf_counter() - t_mat0)
            except Exception as e:
                job_failed(job, e)
                return
            if policy is not None:
                try:
                    note_sched_drain(job, time.monotonic())
                except Exception as e:
                    log.error("scheduler accounting failed: %s", e)
            t_drain0 = time.perf_counter()
            off = 0
            for f, idx in job["segments"]:
                k = len(idx)
                if not f["failed"]:
                    f["results"][idx] = np.asarray(out.results)[off : off + k]
                    f["xdp"][idx] = np.asarray(out.xdp)[off : off + k]
                off += k
                seg_done(f)
            if tr is not None:
                tr.add("drain", time.perf_counter() - t_drain0)
                tracer.finish(tr)

        inflight: deque = deque()
        staged: deque = deque()
        stage_depth = (
            getattr(self, "h2d_stage_depth", DEFAULT_H2D_STAGE_DEPTH)
            if h2d_overlap else 1
        )
        def stage_more() -> None:
            # keep the staging window full: the NEXT jobs' host pack +
            # codec encode + H2D start run while earlier classifies are
            # still on device (and while drain_one blocks below)
            while jobs and len(staged) < stage_depth:
                job = jobs.popleft()
                try:
                    prep = prepare(job)
                except Exception as e:
                    job_failed(job, e)
                    continue
                if prep is not None:
                    staged.append((job, prep))

        if sched_stats is not None:
            sched_stats.set_queue_depth(total)
        edits_ok = hasattr(self, "txn_batcher")  # bench harness: __new__
        while jobs or staged or inflight:
            # apply/classify interleaving: a tripped edit-transaction
            # flush lands BETWEEN admissions — in-flight classifies keep
            # running on the generation they were dispatched against,
            # and the next launched job picks up the patched tables
            if edits_ok:
                try:
                    self._maybe_flush_edits()
                except Exception as e:
                    log.error("edit flush error: %s", e)
            stage_more()
            while staged and len(inflight) < self.pipeline_depth:
                job, prep = staged.popleft()
                job["t_launch"] = time.monotonic()
                try:
                    t_disp0 = time.perf_counter()
                    pending = launch(job, prep)
                    tr = job.get("trace")
                    if tr is not None:
                        tr.add("dispatch", time.perf_counter() - t_disp0)
                except Exception as e:
                    job_failed(job, e)
                    continue
                if pending is not None:
                    inflight.append((job, pending))
                    if sched_stats is not None:
                        n_job = sum(len(i) for _f, i in job["segments"])
                        from .scheduler import ladder_bucket

                        sched_stats.note_admit(
                            n_job, ladder_bucket(n_job, chunk)
                        )
                # top up staging as the window drains so the lookahead
                # never collapses mid-burst
                stage_more()
            if inflight:
                drain_one()
        return processed

    # -- ring ingest (persistent pinned host ring, ISSUE-12) -----------------

    def process_ring_once(self, budget: Optional[int] = None) -> int:
        """Drain committed ring records through the packed dispatch:
        admission by ring cursor — the popped slot views ARE the H2D
        staging buffers (zero-copy on the CPU backend), and each slot is
        released back to the producer only after the dispatch that read
        it materialized, so the producer can never overwrite a record
        mid-copy.  Up to ``pipeline_depth`` dispatches stay in flight
        (the same double-buffer discipline as the file ingest).  Returns
        packets processed."""
        ring = self.ingest_ring
        if ring is None:
            return 0
        clf = self.syncer.classifier
        if clf is None:
            return 0
        supports = getattr(clf, "supports_packed", None)
        packed = supports is not None and supports()
        if packed and getattr(clf, "active_path", None) is None:
            return 0
        if self._sched_policy is not None and packed:
            self._maybe_prewarm_ladder(clf)
        budget = self.max_tick_packets if budget is None else int(budget)
        processed = 0
        inflight = self._ring_inflight
        tracer = getattr(self, "tracer", None)
        super_k = self.superbatch_k
        can_super = (
            packed and super_k >= 2
            and getattr(clf, "prepare_packed_super", None) is not None
        )
        carry: list = []  # popped-but-undispatched (shape-class break)

        def dispatch_one(chunk, trace) -> bool:
            try:
                if packed:
                    plan = clf.prepare_packed(
                        chunk.wire, chunk.v4_only,
                        tcp_flags=chunk.tcp_flags,
                        payload=chunk.payload,
                        payload_len=chunk.payload_len,
                    )
                    if trace is not None:
                        trace.mark("h2d")
                    pending = clf.classify_prepared(plan, apply_stats=True)
                else:
                    # non-packed backend (the cpu reference): rebuild
                    # the batch from the wire record — slower, but the
                    # ring must drain on every backend
                    pending = clf.classify_async(
                        _batch_from_wire(chunk.wire, chunk.tcp_flags),
                        apply_stats=True,
                    )
                if trace is not None:
                    trace.mark("dispatch")
            except Exception as e:
                log.error("ring ingest dispatch failed: %s", e)
                chunk.release()
                return False
            inflight.append((chunk, pending, trace))
            return True

        while processed < budget:
            t0 = time.perf_counter()
            chunk = carry.pop(0) if carry else ring.pop(timeout=0.0)
            if chunk is None:
                break
            trace = None
            if tracer is not None:
                # span taxonomy on the ring path: ingest = cursor pop,
                # h2d = prepare_packed (staging device_put; the record
                # arrives pre-packed so pack is the producer's cost),
                # dispatch = program launch, materialize = readback,
                # drain = slot release + bookkeeping
                trace = tracer.begin(chunk.wire.shape[0])
                trace.add("ingest", time.perf_counter() - t0)
            group = [chunk]
            if can_super and not carry:
                # gather up to K committed records of ONE shape class —
                # same (n, width, v4_only, flags presence); the jit
                # cache keys on exactly those, a mixed stack would
                # recompile.  A mismatch carries to the next loop turn
                # (releases stay in pop order either way).
                while len(group) < super_k:
                    try:
                        nxt = ring.pop(timeout=0.0)
                    except ValueError as e:
                        log.error("ring ingest pop failed: %s", e)
                        break
                    if nxt is None:
                        break
                    if (nxt.wire.shape != chunk.wire.shape
                            or nxt.v4_only != chunk.v4_only
                            or (nxt.tcp_flags is None)
                            != (chunk.tcp_flags is None)
                            or (nxt.payload is None)
                            != (chunk.payload is None)
                            or (chunk.payload is not None
                                and nxt.payload.shape
                                != chunk.payload.shape)):
                        carry.append(nxt)
                        break
                    group.append(nxt)
            if len(group) >= 2:
                # one stacked H2D (the stack copy is the staging write —
                # slot views are not contiguous across slots) + ONE
                # device epoch-loop dispatch for the whole group
                wire_stack = np.stack([c.wire for c in group])
                flags_stack = (
                    None if chunk.tcp_flags is None
                    else np.stack([c.tcp_flags for c in group])
                )
                pay_stack = plen_stack = None
                if chunk.payload is not None:
                    pay_stack = np.stack([c.payload for c in group])
                    plen_stack = np.stack(
                        [c.payload_len for c in group]
                    )
                plan = None
                try:
                    plan = clf.prepare_packed_super(
                        wire_stack, chunk.v4_only,
                        tcp_flags_stack=flags_stack,
                        payload_stack=pay_stack,
                        payload_len_stack=plen_stack,
                    )
                    if plan is not None:
                        if trace is not None:
                            trace.mark("h2d")
                        pends = clf.classify_prepared_super(
                            plan, apply_stats=True
                        )
                        if trace is not None:
                            trace.mark("dispatch")
                except Exception as e:
                    log.error("ring superbatch dispatch failed: %s", e)
                    plan = None
                if plan is not None:
                    for j, (c, p) in enumerate(zip(group, pends)):
                        inflight.append((c, p, trace if j == 0 else None))
                        processed += c.wire.shape[0]
                    while len(inflight) > self.pipeline_depth:
                        self._ring_drain_one()
                    continue
                # superbatch declined (resident fallback): serve each
                # gathered record through the single-admission path
            for j, c in enumerate(group):
                if dispatch_one(c, trace if j == 0 else None):
                    processed += c.wire.shape[0]
            while len(inflight) > self.pipeline_depth:
                self._ring_drain_one()
        # a shape-class break popped one record past the budget: it must
        # still dispatch (releases are strictly in pop order)
        for c in carry:
            if dispatch_one(c, None):
                processed += c.wire.shape[0]
        while inflight:
            self._ring_drain_one()
        return processed

    def _ring_drain_one(self) -> None:
        chunk, pending, trace = self._ring_inflight.popleft()
        try:
            pending.result()
            if trace is not None:
                trace.mark("materialize")
        except Exception as e:
            log.error("ring ingest classify failed: %s", e)
        finally:
            chunk.release()
            if trace is not None:
                trace.mark("drain")
                self.tracer.finish(trace)  # trace only exists when tracer does

    def _maybe_prewarm_ladder(self, clf) -> None:
        """Pre-warm every batch-size ladder shape against the CURRENT
        table generation, once per generation: shape-driven jit
        specialization (and a tunneled deployment's per-executable
        first-dispatch cost) lands here, never inside a serving-path
        latency budget.  Covers batch=32 (the BENCH_r05 small-batch
        anomaly shape) and every depth-steering class."""
        gen = (id(clf), id(clf.tables), getattr(clf, "_depth_gen", 0))
        if gen == self._prewarmed_gen:
            return
        from .scheduler import (
            batch_ladder, data_parallel_width, prewarm_ladder,
        )

        try:
            # the ladder extends to max_batch * data shards: a mesh
            # classifier's tick jobs span the whole pool (chunk =
            # service_cap * width), so those shapes must be warm too —
            # the compile-free timing pass also seeds the admission
            # policy's service model, so the first tick's job sizing is
            # measured, not the cold-model default
            ladder = batch_ladder(self.max_batch * data_parallel_width(clf))
            prewarm_ladder(clf, ladder,
                           service=self._sched_policy.service)
        except Exception as e:
            log.error("ladder prewarm failed: %s", e)
        self._prewarmed_gen = gen

    # -- HTTP endpoints ------------------------------------------------------

    def _make_handler(daemon_self):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str, ctype="text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, daemon_self.metrics_registry.render_text())
                elif self.path in ("/healthz", "/readyz"):
                    self._send(200, "ok")
                elif self.path == "/debug/lookup-keys":
                    keys = daemon_self.debug_buffer.snapshot()
                    self._send(
                        200,
                        json.dumps(
                            [{"ifindex": k[0], "ip_words": list(k[1])} for k in keys]
                        ),
                        ctype="application/json",
                    )
                else:
                    self._send(404, "not found")

        return Handler

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        handler = self._make_handler()
        for port in {self.metrics_port, self.health_port}:
            srv = ThreadingHTTPServer(("127.0.0.1", port), handler)
            self._servers.append(srv)
            t = spawn(srv.serve_forever, name="infw-daemon-http")
            self._threads.append(t)
        self.events_logger.start()
        t = spawn(self._file_loop, name="infw-file-loop")
        self._threads.append(t)
        log.info(
            "daemon started node=%s backend=%s metrics=127.0.0.1:%d",
            self.node_name, self.backend, self.metrics_port,
        )

    def _file_loop(self) -> None:
        while not self._stop.wait(self.file_poll_interval_s):
            # Scan and ingest are isolated from each other: a persistently
            # bad nodestate file must not starve packet classification.
            try:
                self.scan_nodestates_once()
            except Exception as e:  # keep the loop alive
                log.error("nodestate scan error: %s", e)
            try:
                self.scan_edits_once()
                self._maybe_flush_edits()
            except Exception as e:
                log.error("edit scan error: %s", e)
            try:
                self.scan_tenant_edits_once()
            except Exception as e:
                log.error("tenant edit scan error: %s", e)
            try:
                self._tenant_dedup_maintenance()
            except Exception as e:
                log.error("tenant dedup sweep error: %s", e)
            try:
                self.process_ring_once()
            except Exception as e:
                log.error("ring ingest error: %s", e)
            try:
                self.process_ingest_once()
            except Exception as e:
                log.error("ingest error: %s", e)
            try:
                self._flow_maintenance()
            except Exception as e:
                log.error("flow maintenance error: %s", e)
            try:
                self._telemetry_maintenance()
            except Exception as e:
                log.error("telemetry maintenance error: %s", e)
            try:
                self._mlscore_maintenance()
            except Exception as e:
                log.error("mlscore maintenance error: %s", e)
            try:
                self._payload_maintenance()
            except Exception as e:
                log.error("payload maintenance error: %s", e)

    def _attach_flow_events(self, clf) -> None:
        """Wire a classifier's flow tier to the obs event ring (once
        per tier): eviction storms surface as FlowEvictRecords next to
        the deny events."""
        tier = getattr(clf, "flow", None)
        if tier is None or id(tier) in self._flow_attached:
            return
        from .obs.events import FlowEvictRecord

        tier.on_evict = lambda ev, ins, ep: self.ring.push(
            FlowEvictRecord(evicted=int(ev), inserted=int(ins),
                            epoch=int(ep))
        )
        self._flow_attached.add(id(tier))

    def _flow_maintenance(self) -> None:
        """Idle-loop flow upkeep: attach eviction events to any new
        classifier generation and run the epoch-based age sweep every
        few seconds (stale entries never serve regardless — the sweep
        just returns their slots ahead of LRU pressure)."""
        if self.flow_table is None:
            return
        now = time.monotonic()
        for clf in (self.syncer.classifier,
                    self.tenant_registry.classifier
                    if self.tenant_registry is not None else None):
            if clf is None:
                continue
            self._attach_flow_events(clf)
            if now - self._flow_age_last >= 5.0:
                age = getattr(clf, "flow_age_tick", None)
                if age is not None:
                    age()
        if now - self._flow_age_last >= 5.0:
            self._flow_age_last = now

    def _tenant_dedup_maintenance(self) -> None:
        """Idle-loop CoW arena upkeep: every few seconds, re-hash
        tenant slabs whose content hash went stale (in-place patches /
        CoW clones) and re-merge pages that re-converged onto one
        shared slab — page-table flips only, never a slab write, so
        the sweep is serving-path-safe at any cadence.  On spliced
        arenas the same pass re-merges subtree planes that unspliced
        apart and later re-converged (splice-row flips, ISSUE-17).
        Bounded per pass (``limit``) so one sweep never monopolizes
        the idle loop on a large pool."""
        if self.tenant_registry is None:
            return
        now = time.monotonic()
        if now - self._tenant_dedup_last < 5.0:
            return
        self._tenant_dedup_last = now
        sweep = getattr(self.tenant_registry.classifier, "dedup_sweep", None)
        if sweep is not None:
            rep = sweep(limit=64)
            if rep.get("merged") or rep.get("plane_merged"):
                log.info("tenant dedup sweep: %d page(s) re-hashed, "
                         "%d tenant row(s) re-merged, "
                         "%d subtree plane(s) re-merged",
                         rep["hashed"], rep["merged"],
                         rep.get("plane_merged", 0))

    def _telemetry_maintenance(self) -> None:
        """Idle-loop telemetry upkeep: attach the obs ring + drain
        cadence to any new classifier generation's tier, and force a
        time-based drain every few seconds so low-traffic windows still
        produce timely summaries (the admission-count decimation only
        fires under load)."""
        if self.telemetry is None:
            return
        clf = self.syncer.classifier
        tier = getattr(clf, "telemetry", None)
        if tier is None:
            return
        if id(tier) not in self._telemetry_attached:
            tier.attach_ring(self.ring)
            tier.drain_every = self.telemetry_drain
            self._telemetry_attached.add(id(tier))
        now = time.monotonic()
        if now - self._telemetry_drain_last >= 5.0:
            self._telemetry_drain_last = now
            with tier._lock:
                pending = tier._window_admissions > 0
            if pending:
                tier.drain(force=True)

    def _mlscore_maintenance(self) -> None:
        """Idle-loop scoring upkeep: attach the obs ring to any new
        classifier generation's tier, force a time-based drain so
        low-traffic windows still produce timely anomaly-verdict
        records, and consume dropped model artifacts from
        <state-dir>/models/ — each *.npz (+ required .json manifest)
        hot-swaps through set_score_model (a swap behaves like a rule
        patch: the flow generation bumps); bad or mismatched artifacts
        are consumed and logged, never retried forever (the edits-dir
        bad-file discipline)."""
        if self.mlscore is None:
            return
        clf = self.syncer.classifier
        tier = getattr(clf, "mlscore", None)
        if tier is None:
            return
        if id(tier) not in self._mlscore_attached:
            tier.attach_ring(self.ring)
            self._mlscore_attached.add(id(tier))
            # a classifier REBUILD (rules-edit escalation, re-place)
            # constructs its tier from the factory's launch-time model —
            # re-apply the last hot-swapped artifact (already consumed
            # from the models dir) so a rebuild can't silently revert
            swapped = getattr(self, "_mlscore_swapped_model", None)
            if (swapped is not None
                    and tier.model_version != swapped.version):
                try:
                    clf.set_score_model(swapped)
                    log.info("mlscore: re-applied hot-swapped model "
                             "%s to new classifier generation",
                             swapped.version)
                except Exception as e:
                    log.error("mlscore: re-apply of swapped model "
                              "failed: %s", e)
        now = time.monotonic()
        if now - self._mlscore_drain_last >= 5.0:
            self._mlscore_drain_last = now
            with tier._lock:
                pending = tier._window_admissions > 0
            if pending:
                tier.drain(force=True)
        # model hot-swap dir: consume complete npz+manifest pairs
        from .mlscore import load_model

        try:
            names = sorted(os.listdir(self.models_dir))
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".npz"):
                continue
            path = os.path.join(self.models_dir, fn)
            if not os.path.exists(path + ".json"):
                continue  # manifest not landed yet — next tick
            try:
                model = load_model(path)
                clf.set_score_model(model)
                self._mlscore_swapped_model = model
                log.info("mlscore: hot-swapped model %s (version %s)",
                         fn, tier.model_version)
            except Exception as e:
                log.error("mlscore: model artifact %s rejected: %s",
                          fn, e)
            for p in (path, path + ".json"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _payload_maintenance(self) -> None:
        """Idle-loop payload-tier upkeep: re-apply the last hot-swapped
        pattern set to any rebuilt classifier generation (a rules-edit
        escalation rebuild constructs its tier from the factory's
        launch-time set), then consume dropped pattern-set artifacts
        from <state-dir>/patterns/ — each *.npz (+ required .json
        manifest, infw.payload.save_patterns) hot-swaps through
        set_payload_patterns.  An in-bucket swap recompiles nothing
        (the zero-recompile discipline); a swap behaves like a rule
        patch — the flow generation bumps so cached payload verdicts
        can't serve stale.  Bad or mismatched artifacts are consumed
        and logged, never retried forever (the edits-dir bad-file
        discipline)."""
        if self.payload is None:
            return
        clf = self.syncer.classifier
        tier = getattr(clf, "payload", None)
        if tier is None:
            return
        if id(tier) not in self._payload_attached:
            self._payload_attached.add(id(tier))
            swapped = getattr(self, "_payload_swapped", None)
            if swapped is not None:
                pats, plen, label = swapped
                try:
                    clf.set_payload_patterns(pats, plen=plen)
                    log.info("payload: re-applied hot-swapped pattern "
                             "set %s to new classifier generation",
                             label)
                except Exception as e:
                    log.error("payload: re-apply of swapped pattern "
                              "set failed: %s", e)
        # pattern hot-swap dir: consume complete npz+manifest pairs
        from .payload import load_patterns

        try:
            names = sorted(os.listdir(self.patterns_dir))
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".npz"):
                continue
            path = os.path.join(self.patterns_dir, fn)
            if not os.path.exists(path + ".json"):
                continue  # manifest not landed yet — next tick
            try:
                pats, spec, label = load_patterns(path)
                clf.set_payload_patterns(pats, plen=spec.plen)
                self._payload_swapped = (pats, spec.plen, label)
                log.info("payload: hot-swapped pattern set %s "
                         "(version %s, %d patterns)", fn, label,
                         len(pats))
            except Exception as e:
                log.error("payload: pattern artifact %s rejected: %s",
                          fn, e)
            for p in (path, path + ".json"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _emit_deny_sampled(self, clf, results, ifindex, pkt_len, frames,
                           batch) -> None:
        """Deny-event export with the telemetry tier's per-tenant token
        bucket in front (ISSUE-13): the full firehose is replaced by
        bounded raw evidence — exact totals always travel in the sketch
        summaries; the bucket releases at most its budget of raw
        records, the rest counts as telemetry_suppressed_events (never
        as ring loss — suppression is policy, not overflow)."""
        tel = getattr(clf, "telemetry", None)
        if tel is None:
            emit_deny_events(self.ring, results, ifindex, pkt_len, frames,
                             batch=batch)
            return
        results = np.asarray(results)
        deny_idx = np.nonzero((results & 0xFF) == DENY)[0]
        if len(deny_idx) == 0:
            return
        grant = tel.sample_allow(0, len(deny_idx))
        if grant >= len(deny_idx):
            emit_deny_events(self.ring, results, ifindex, pkt_len, frames,
                             batch=batch)
            return
        if grant == 0:
            return
        keep = deny_idx[:grant]
        emit_deny_events(
            self.ring, results[keep], np.asarray(ifindex)[keep],
            np.asarray(pkt_len)[keep],
            None if frames is None else [frames[int(i)] for i in keep],
        )

    def stop(self) -> None:
        """SIGTERM path: stop polling/serving, detach the dataplane but
        keep the checkpoint (ebpfsyncer.go:90-97 — rules keep enforcing
        across daemon restarts via the pinned state)."""
        self._stop.set()
        for srv in self._servers:
            srv.shutdown()
        self.events_logger.stop()
        self.stats.stop_poll()
        self.stats.unregister()
        self.syncer.shutdown()
        self._event_file.close()
        if self._events_socket_sink is not None:
            self._events_socket_sink.close()
        if self.ingest_ring is not None:
            while self._ring_inflight:
                self._ring_drain_one()
            self.ingest_ring.close()

    @property
    def actual_metrics_port(self) -> int:
        return self._servers[0].server_address[1] if self._servers else self.metrics_port


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry with the reference env contract
    (cmd/daemon/daemon.go:69-84): flags override env, env overrides
    defaults."""
    p = argparse.ArgumentParser(prog="infw-daemon")
    p.add_argument("--state-dir", required=True)
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument(
        "--namespace",
        default=os.environ.get("NAMESPACE", "ingress-node-firewall-system"),
    )
    p.add_argument("--backend", default=os.environ.get("INFW_BACKEND", "cpu"),
                   choices=["tpu", "cpu"])
    p.add_argument(
        "--poll-period-seconds",
        type=float,
        default=float(os.environ.get("POLL_PERIOD_SECONDS", "30")),
    )
    p.add_argument("--metrics-port", type=int, default=DEFAULT_METRICS_PORT)
    p.add_argument("--health-port", type=int, default=DEFAULT_HEALTH_PORT)
    p.add_argument("--ingest-chunk", type=int, default=DEFAULT_INGEST_CHUNK)
    p.add_argument("--pipeline-depth", type=int, default=DEFAULT_PIPELINE_DEPTH)
    p.add_argument(
        "--superbatch-k", type=int, default=None,
        help="stack up to K same-shape ring records into one device-side "
             "epoch-loop dispatch (default INFW_SUPERBATCH_K or 1 = off)")
    p.add_argument("--max-tick-packets", type=int,
                   default=DEFAULT_MAX_TICK_PACKETS)
    p.add_argument("--event-ring-size", type=int, default=1 << 21,
                   help="deny-event ring capacity, minimum 64 (overflow "
                        "drops new records and counts them as lost "
                        "samples, like the kernel perf ring)")
    p.add_argument(
        "--no-fused-deep", action="store_true",
        default=os.environ.get("INFW_FUSED_DEEP", "") in ("0", "false", "no"),
        help="disable the fused Pallas deep-walk dispatch for full-depth "
             "v6 chunks (kernels.pallas_walk); the XLA per-level walk "
             "serves them instead",
    )
    p.add_argument(
        "--compressed", action="store_true",
        default=os.environ.get("INFW_COMPRESSED", "")
        not in ("", "0", "false", "no"),
        help="serve trie-sized tables from the path/level-compressed "
             "poptrie layout (jaxpath.build_cpoptrie): merged skip-node "
             "array + per-tidx joined rows — the 10M-tier working-set "
             "layout.  Ineligible tables (wide ruleIds) fall back to "
             "the level walk per load.  CLI beats INFW_COMPRESSED",
    )
    p.add_argument(
        "--no-compressed", action="store_true",
        help="force the per-level walk layout even when INFW_COMPRESSED "
             "is set (the off direction of --compressed, so the CLI can "
             "beat the env var both ways)",
    )
    p.add_argument(
        "--wire-codec", choices=["auto", "wire8", "delta"],
        default=os.environ.get("INFW_WIRE_CODEC") or None,
        help="H2D wire format for packed trie chunks (the --no-fused-deep "
             "precedence pattern: CLI beats INFW_WIRE_CODEC, env beats the "
             "default): auto = per-chunk choice by measured compressed "
             "size (delta when it beats wire8's 8 B/packet), wire8/delta "
             "= force, with eligibility fallbacks",
    )
    p.add_argument(
        "--mesh",
        default=os.environ.get("INFW_MESH") or None,
        help="multi-chip serving mesh as DATAxRULES (e.g. 8x1, 4x2) or a "
             "bare device count (rules=1); CLI beats INFW_MESH.  Packets "
             "shard over the data axis, the rule table over the rules "
             "axis (per-shard tries above the dense limit).  When the "
             "visible device pool is smaller than the spec the daemon "
             "logs a warning and serves single-chip",
    )
    p.add_argument(
        "--no-h2d-overlap", action="store_true",
        default=os.environ.get("INFW_H2D_OVERLAP", "") in ("0", "false", "no"),
        help="disable double-buffered ingestion (the next chunk's H2D "
             "copy overlapping the current chunk's classify); chunks then "
             "stage one at a time — the A/B control the bench's overlap "
             "margin line measures against",
    )
    p.add_argument(
        "--tenants", type=int,
        default=os.environ.get("INFW_TENANTS") or None,
        help="enable the multi-tenant paged table arena with this many "
             "tenant ids: one preallocated slab pool per layout family, "
             "tenants created lazily from <state-dir>/tenants/<name>/"
             "edits/ (same edit-file codec as the single-tenant dir), "
             "ruleset activation by page-table flip, tenant_* counters "
             "on /metrics.  Slab geometry via INFW_TENANT_SLAB_ENTRIES "
             "(default 1024) and INFW_TENANT_RULE_SLOTS (default 16).  "
             "CLI beats INFW_TENANTS",
    )
    p.add_argument(
        "--flow-table", type=int,
        default=os.environ.get("INFW_FLOW_TABLE") or None,
        help="enable the stateful flow tier with this many entries per "
             "flow slab (bucketed to a power of two): a device-resident "
             "exact-match verdict cache probed before the LPM + rule "
             "scan — established flows serve their cached verdict and "
             "only misses pay classification; rule patches / tenant "
             "swaps invalidate by generation bump.  Capacity knobs: "
             "INFW_FLOW_WAYS (set associativity, default 4) and "
             "INFW_FLOW_MAX_AGE (hit freshness horizon in probe epochs)."
             "  CLI beats INFW_FLOW_TABLE",
    )
    p.add_argument(
        "--deadline-us", type=float,
        default=os.environ.get("INFW_DEADLINE_US") or None,
        help="per-packet verdict deadline budget in microseconds: enables "
             "the deadline-aware continuous microbatching scheduler "
             "(infw.scheduler) — ingest jobs coalesce to the largest "
             "batch whose measured service time still meets the budget "
             "(admit-by-deadline, not the fixed --ingest-chunk), the "
             "batch-size ladder is pre-warmed at table load, and "
             "scheduler observability lands on /metrics and the event "
             "ring.  CLI beats INFW_DEADLINE_US",
    )
    p.add_argument(
        "--max-batch", type=int,
        default=os.environ.get("INFW_MAX_BATCH") or None,
        help="scheduler admission cap per chip (default: --ingest-chunk); "
             "on a --mesh pool one admission spreads over the data axis, "
             "so the effective cap multiplies by the data shards.  CLI "
             "beats INFW_MAX_BATCH",
    )
    p.add_argument(
        "--patch-staleness-us", type=float,
        default=os.environ.get("INFW_PATCH_STALENESS_US") or None,
        help="bounded verdict staleness for batched rule edits "
             "(infw.txn): edits dropped into <state-dir>/edits/ "
             "coalesce into ONE folded patch transaction and flush "
             "when the oldest queued edit exceeds this budget (or "
             "--patch-max-ops trips) — between classify admissions, "
             "never stalling them.  Default 2000us.  CLI beats "
             "INFW_PATCH_STALENESS_US",
    )
    p.add_argument(
        "--patch-max-ops", type=int,
        default=os.environ.get("INFW_PATCH_MAX_OPS") or None,
        help="batch-size flush threshold for queued rule edits "
             "(default 1024): a queue this deep flushes regardless of "
             "staleness.  CLI beats INFW_PATCH_MAX_OPS",
    )
    p.add_argument(
        "--resident", action="store_true",
        default=os.environ.get("INFW_RESIDENT", "")
        not in ("", "0", "false", "no"),
        help="zero-copy resident serving loop (tpu backend): one fused "
             "device program per admission (wire decode + flow probe + "
             "classify + stats + flow insert) over donated/aliased "
             "device buffers — zero steady-state pool allocations, "
             "resident_* gauges on /metrics.  Implies a flow table (a "
             "default one is synthesized when --flow-table is absent).  "
             "CLI beats INFW_RESIDENT",
    )
    p.add_argument(
        "--telemetry", nargs="?", const="2048",
        default=os.environ.get("INFW_TELEMETRY") or None,
        help="device-resident telemetry plane (tpu backend): count-min "
             "+ top-K heavy-hitter sketches updated inside the serving "
             "dispatch, per-tenant top-talker / deny-storm / SYN-rate "
             "summaries on the obs event ring at a decimated cadence, "
             "telemetry_* counters on /metrics, and per-tenant "
             "token-bucket sampling of raw deny-event export.  Optional "
             "value = count-min width (default 2048).  CLI beats "
             "INFW_TELEMETRY",
    )
    p.add_argument(
        "--telemetry-drain", type=int,
        default=os.environ.get("INFW_TELEMETRY_DRAIN") or 256,
        help="summarizer decimation: admissions per sketch drain (one "
             "small D2H each; default 256).  CLI beats "
             "INFW_TELEMETRY_DRAIN",
    )
    p.add_argument(
        "--trace", action="store_true",
        default=os.environ.get("INFW_TRACE", "")
        not in ("", "0", "false", "no"),
        help="serving-path tracing: per-stage span clocks (ingest -> "
             "pack -> H2D -> dispatch -> materialize -> drain) exported "
             "as Prometheus histograms on /metrics, with sampled "
             "TraceSpanRecords for slow admissions on the obs event "
             "ring.  CLI beats INFW_TRACE",
    )
    p.add_argument(
        "--trace-slow-us", type=float,
        default=os.environ.get("INFW_TRACE_SLOW_US") or 50_000.0,
        help="slow-admission threshold for sampled trace records "
             "(default 50000us)",
    )
    p.add_argument(
        "--mlscore", nargs="?", const="default",
        default=os.environ.get("INFW_MLSCORE") or None,
        help="MXU anomaly-scoring tier (tpu backend): per-flow "
             "quantized decision-forest (+optional int8 MLP) inference "
             "fused into the serving dispatch — SYN-flood / port-scan "
             "/ rate-anomaly verdicts the rule tables cannot express.  "
             "Optional value = path to a versioned model artifact "
             "(.npz + .json manifest, infw.mlscore.save_model); bare "
             "flag loads the built-in detection forest.  Anomaly-"
             "verdict records ride the obs event ring, mlscore_* "
             "counters /metrics, and <state-dir>/models/ hot-swaps "
             "artifacts live (a swap behaves like a rule patch).  CLI "
             "beats INFW_MLSCORE",
    )
    p.add_argument(
        "--mlscore-mode", choices=("shadow", "enforce"),
        default=os.environ.get("INFW_MLSCORE_MODE") or "shadow",
        help="anomaly mitigation policy: shadow (default) scores and "
             "records only; enforce rewrites over-threshold flows to "
             "Deny (ruleId 0) — NEVER failsafe-port cells "
             "(infw.failsaferules, the coverage-proof port list) and "
             "never existing rule Denies.  CLI beats INFW_MLSCORE_MODE",
    )
    p.add_argument(
        "--payload", nargs="?", const="default",
        default=os.environ.get("INFW_PAYLOAD") or None,
        help="payload matching tier (tpu backend): batched "
             "Aho-Corasick multi-pattern matching over ring-sliced "
             "payload prefixes, fused into the serving dispatch.  "
             "Optional value = path to a versioned pattern-set "
             "artifact (.npz + .json manifest, "
             "infw.payload.save_patterns) or a pattern count for the "
             "seeded built-in signature set; bare flag loads the "
             "built-in set.  payload_* counters + the pattern-set "
             "version gauge export on /metrics, and "
             "<state-dir>/patterns/ hot-swaps artifacts live (an "
             "in-bucket swap recompiles nothing; a swap behaves like "
             "a rule patch).  CLI beats INFW_PAYLOAD",
    )
    p.add_argument(
        "--payload-mode", choices=("shadow", "enforce"),
        default=os.environ.get("INFW_PAYLOAD_MODE") or "shadow",
        help="payload mitigation policy: shadow (default) matches and "
             "counts only; enforce rewrites matched packets to Deny "
             "(ruleId 0) — NEVER failsafe-port cells and never "
             "existing rule Denies.  CLI beats INFW_PAYLOAD_MODE",
    )
    p.add_argument(
        "--payload-plen", type=int,
        default=int(os.environ.get("INFW_PAYLOAD_PLEN") or 0) or None,
        help="payload prefix width in bytes (64 or 128): how much of "
             "each packet's payload the ring slices and the automaton "
             "scans (prefix-truncation semantics — patterns crossing "
             "the boundary cannot match).  Default 64, or the "
             "artifact's compiled width.  CLI beats INFW_PAYLOAD_PLEN",
    )
    p.add_argument(
        "--ring",
        default=os.environ.get("INFW_RING") or None,
        help="persistent pinned host ingest ring: path of a "
             "shared-memory ring file the daemon CREATES and consumes "
             "(producers attach with tools/loadgen.py --ring PATH).  "
             "Producers write packed wire records in place; the ingest "
             "loop admits by ring cursor — no per-chunk file syscalls.  "
             "CLI beats INFW_RING",
    )
    p.add_argument(
        "--events-socket",
        default=os.environ.get("INFW_EVENTS_SOCKET", ""),
        help="unixgram socket to ship deny-event lines to (the events "
        "sidecar composition, daemonset.yaml:54-67); run "
        "`python -m infw.obs.sidecar --socket PATH` as the follower",
    )
    args = p.parse_args(argv)

    if not args.node_name:
        p.error("environment variable NODE_NAME or --node-name is required")

    # argparse validates `choices` only for explicitly passed args, not
    # env-derived defaults — a bad INFW_WIRE_CODEC must fail the launch
    # here, not fail-open later (TpuClassifier raising inside the sync
    # loop leaves an empty dataplane that PASSes every packet)
    if args.wire_codec is not None and args.wire_codec not in (
        "auto", "wire8", "delta"
    ):
        p.error(
            f"invalid INFW_WIRE_CODEC {args.wire_codec!r} "
            "(expected auto|wire8|delta)"
        )

    # Scheduler knobs share the launch-time validation posture: a
    # non-positive deadline or batch cap (flag OR env-derived) must fail
    # the launch with a usage error, not raise inside the serving loop.
    if args.deadline_us is not None and not args.deadline_us > 0:
        p.error(f"--deadline-us must be positive, got {args.deadline_us}")
    if args.max_batch is not None and args.max_batch < 1:
        p.error(f"--max-batch must be >= 1, got {args.max_batch}")
    if args.patch_staleness_us is not None and not args.patch_staleness_us > 0:
        p.error(
            f"--patch-staleness-us must be positive, got "
            f"{args.patch_staleness_us}"
        )
    if args.patch_max_ops is not None and args.patch_max_ops < 1:
        p.error(f"--patch-max-ops must be >= 1, got {args.patch_max_ops}")
    if args.tenants is not None and int(args.tenants) < 1:
        p.error(f"--tenants must be >= 1, got {args.tenants}")
    # Flow-tier knobs share the launch-time validation posture: a bad
    # entry count / way count / age horizon (flag OR env-derived) must
    # fail the launch with a usage error, not raise inside the sync loop
    # and leave an empty PASS-everything dataplane.
    flow_cfg = None
    if args.flow_table is not None and str(args.flow_table) not in (
        "0", "", "false", "no"
    ):
        if int(args.flow_table) < 1:
            p.error(f"--flow-table must be >= 1, got {args.flow_table}")
        from .flow import FlowConfig

        try:
            flow_cfg = FlowConfig.make(
                entries=int(args.flow_table),
                ways=int(os.environ.get("INFW_FLOW_WAYS") or 4),
                max_age=int(os.environ.get("INFW_FLOW_MAX_AGE")
                            or FlowConfig().max_age),
            )
        except ValueError as e:
            p.error(str(e))

    # Resident/ring knobs share the launch-time validation posture.
    if args.resident and args.backend == "cpu":
        p.error("--resident requires the tpu backend (the cpu reference "
                "classifier has no device-resident serving loop)")
    # Telemetry knobs share it too: a bad sketch width / drain cadence
    # (flag OR env-derived) fails the launch, never the sync loop.
    telemetry_spec = None
    if args.telemetry is not None and str(args.telemetry) not in (
        "0", "", "false", "no"
    ):
        if args.backend == "cpu":
            p.error("--telemetry requires the tpu backend (the cpu "
                    "reference classifier has no device sketch plane)")
        from .kernels.sketch import SketchSpec

        raw = str(args.telemetry)
        if raw in ("1", "true", "yes"):
            raw = "2048"  # bare flag / truthy env: the default geometry
        try:
            if int(raw) < 8:
                raise ValueError(
                    f"--telemetry width must be >= 8, got {raw}"
                )
            telemetry_spec = SketchSpec.make(
                width=int(raw),
                depth=int(os.environ.get("INFW_TELEMETRY_DEPTH") or 4),
                topk=int(os.environ.get("INFW_TELEMETRY_TOPK") or 256),
            )
        except ValueError as e:
            p.error(str(e))
    if int(args.telemetry_drain) < 1:
        p.error(f"--telemetry-drain must be >= 1, got "
                f"{args.telemetry_drain}")
    # Scoring knobs share the launch-time validation posture: a bad
    # model artifact, an env-derived mode typo or a cpu backend must
    # fail the launch with a usage error, never raise inside the sync
    # loop and leave an empty PASS-everything dataplane.
    mlscore_bundle = None
    if args.mlscore is not None and str(args.mlscore) not in (
        "0", "", "false", "no"
    ):
        if args.backend == "cpu":
            p.error("--mlscore requires the tpu backend (the cpu "
                    "reference classifier has no scoring plane)")
        if args.mlscore_mode not in ("shadow", "enforce"):
            p.error(f"invalid INFW_MLSCORE_MODE {args.mlscore_mode!r} "
                    "(expected shadow|enforce)")
        from .kernels.mxu_score import ScoreSpec, default_model

        raw = str(args.mlscore)
        try:
            if raw in ("default", "1", "true", "yes"):
                spec = ScoreSpec.make()
                model = default_model(spec)
            else:
                from .mlscore import load_model

                model = load_model(raw)
                spec = model.spec
            mlscore_bundle = (spec, model)
        except (ValueError, OSError) as e:
            p.error(f"--mlscore: {e}")
    elif args.mlscore_mode == "enforce":
        # scoring resolved OFF (flag absent OR an explicit falsy env
        # value like INFW_MLSCORE=0): enforce mode with no scoring tier
        # would silently serve unmitigated — fail the launch either way
        p.error("--mlscore-mode enforce requires --mlscore")
    # Payload knobs: same launch-time validation posture — a bad
    # pattern artifact, a bad prefix width or a cpu backend must fail
    # the launch with a usage error, never inside the sync loop.
    payload_patterns = None
    payload_plen = None
    if args.payload is not None and str(args.payload) not in (
        "0", "", "false", "no"
    ):
        if args.backend == "cpu":
            p.error("--payload requires the tpu backend (the cpu "
                    "reference classifier has no payload plane)")
        if args.payload_mode not in ("shadow", "enforce"):
            p.error(f"invalid INFW_PAYLOAD_MODE {args.payload_mode!r} "
                    "(expected shadow|enforce)")
        from .kernels.wire_decode import PAYLOAD_PREFIX_WIDTHS

        if args.payload_plen is not None:
            if int(args.payload_plen) not in PAYLOAD_PREFIX_WIDTHS:
                p.error(f"--payload-plen must be one of "
                        f"{PAYLOAD_PREFIX_WIDTHS}, got "
                        f"{args.payload_plen}")
            payload_plen = int(args.payload_plen)
        raw = str(args.payload)
        try:
            if raw in ("default", "1", "true", "yes") or raw.isdigit():
                from .payload import signature_patterns

                count = int(raw) if raw.isdigit() else 32
                payload_patterns = signature_patterns(
                    np.random.default_rng(0), count,
                    plen=payload_plen or PAYLOAD_PREFIX_WIDTHS[0],
                )
            else:
                from .payload import load_patterns

                payload_patterns, pspec, _pver = load_patterns(raw)
                if payload_plen is None:
                    payload_plen = int(pspec.plen)
        except (ValueError, OSError) as e:
            p.error(f"--payload: {e}")
    elif args.payload_mode == "enforce":
        # matching resolved OFF: enforce mode with no payload tier
        # would silently serve unmitigated — fail the launch
        p.error("--payload-mode enforce requires --payload")
    if not float(args.trace_slow_us) > 0:
        p.error(f"--trace-slow-us must be positive, got "
                f"{args.trace_slow_us}")
    if args.ring:
        ring_dir = os.path.dirname(os.path.abspath(args.ring)) or "."
        if not os.path.isdir(ring_dir):
            p.error(f"--ring directory does not exist: {ring_dir}")

    # Same launch-time validation posture as the wire codec: a bad
    # INFW_MESH (or --mesh) must fail here with a usage error, not raise
    # inside the sync loop and leave an empty PASS-everything dataplane.
    # Gated on the tpu backend: the cpu backend ignores the knob, and
    # importing backend.mesh (which imports jax) would break the jax-free
    # CPU deployment path for a fleet-wide INFW_MESH setting.
    if args.mesh is not None and args.backend == "tpu":
        from .backend.mesh import parse_mesh_spec

        try:
            parse_mesh_spec(args.mesh)
        except ValueError as e:
            p.error(str(e))

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.backend == "tpu":
        # Join the multi-host process group when configured
        # (INFW_COORDINATOR / INFW_NUM_PROCESSES / INFW_PROCESS_ID) — the
        # DaemonSet-scale-out analogue; single-process is a no-op.
        from .parallel.multihost import init_distributed

        init_distributed()
    debug = os.environ.get("ENABLE_LPM_LOOKUP_DBG", "0") not in ("0", "", "false")
    daemon = Daemon(
        state_dir=args.state_dir,
        node_name=args.node_name,
        namespace=args.namespace,
        backend=args.backend,
        poll_period_s=args.poll_period_seconds,
        debug_lookup=debug,
        metrics_port=args.metrics_port,
        health_port=args.health_port,
        ingest_chunk=args.ingest_chunk,
        max_tick_packets=args.max_tick_packets,
        event_ring_size=args.event_ring_size,
        pipeline_depth=args.pipeline_depth,
        superbatch_k=args.superbatch_k,
        events_socket=args.events_socket or None,
        fused_deep=False if args.no_fused_deep else None,
        wire_codec=args.wire_codec,
        compressed=False if args.no_compressed
        else (True if args.compressed else None),
        h2d_overlap=not args.no_h2d_overlap,
        mesh=args.mesh,
        deadline_us=args.deadline_us,
        max_batch=args.max_batch,
        patch_staleness_us=args.patch_staleness_us,
        patch_max_ops=args.patch_max_ops,
        tenants=int(args.tenants) if args.tenants else None,
        flow_table=flow_cfg,
        resident=args.resident,
        telemetry=telemetry_spec,
        telemetry_drain=int(args.telemetry_drain),
        trace=args.trace,
        trace_slow_us=float(args.trace_slow_us),
        mlscore=mlscore_bundle,
        mlscore_mode=args.mlscore_mode,
        payload=payload_patterns,
        payload_mode=args.payload_mode,
        payload_plen=payload_plen,
        ring=args.ring,
    )
    stop = threading.Event()

    def on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    daemon.start()
    try:
        while not stop.wait(0.5):
            pass
    finally:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
