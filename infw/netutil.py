"""CIDR / address parsing helpers.

Reproduces the semantics of Go's net.ParseCIDR as used by the reference's
key builder (pkg/ebpf/ingress_node_firewall_loader.go:530-547) and webhook
(pkg/webhook/webhook.go:253-258):

- the "/len" part is mandatory;
- the *unmasked* address bytes go into the key data (Go copies ip.To4()/To16()
  of the address part, not the masked network);
- IPv4 and IPv4-mapped-IPv6 addresses store the 4-byte form at the front of
  the 16-byte key, everything else stores the 16-byte form;
- prefix_len is the CIDR mask length plus the 32 ifindex key bits.
"""
from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Optional

from .constants import IFINDEX_KEY_LENGTH


class CIDRParseError(ValueError):
    pass


@dataclass(frozen=True)
class ParsedCIDR:
    ip_data: bytes      # 16 bytes; v4 addresses occupy the first 4, rest zero
    mask_len: int       # CIDR prefix length as written
    is_v4_data: bool    # True if ip_data holds the 4-byte form


def parse_cidr(cidr: str) -> ParsedCIDR:
    if not isinstance(cidr, str) or "/" not in cidr:
        raise CIDRParseError(f"invalid CIDR address: {cidr!r}")
    try:
        iface = ipaddress.ip_interface(cidr)
    except ValueError as e:
        raise CIDRParseError(f"invalid CIDR address: {cidr!r}: {e}")

    ip = iface.ip
    mask_len = iface.network.prefixlen
    data = bytearray(16)
    if isinstance(ip, ipaddress.IPv4Address):
        data[0:4] = ip.packed
        is_v4 = True
    else:
        v4 = ip.ipv4_mapped
        if v4 is not None:
            # Go's ip.To4() returns the 4-byte form for v4-mapped addresses
            # (loader.go:537-538); the prefix length stays as written.
            data[0:4] = v4.packed
            is_v4 = True
        else:
            data[0:16] = ip.packed
            is_v4 = False
    return ParsedCIDR(ip_data=bytes(data), mask_len=mask_len, is_v4_data=is_v4)


def validate_source_cidr(cidr: str) -> Optional[str]:
    """webhook.go:253-258 — returns a reason string or None if valid."""
    try:
        parse_cidr(cidr)
    except CIDRParseError as e:
        return f"must define valid IPV4 or IPV6 CIDR: {e}"
    return None


def key_prefix_len(mask_len: int) -> int:
    """loader.go:543 — LPM prefixLen counts the 32 ifindex bits too."""
    return mask_len + IFINDEX_KEY_LENGTH


def ip_str_to_words(addr: str) -> tuple:
    """Parse a bare IP address into (word0..word3, is_v4) big-endian 32-bit
    words of the 16-byte key layout (v4 in the first word)."""
    ip = ipaddress.ip_address(addr)
    data = bytearray(16)
    if isinstance(ip, ipaddress.IPv4Address):
        data[0:4] = ip.packed
        is_v4 = True
    else:
        data[0:16] = ip.packed
        is_v4 = False
    words = tuple(int.from_bytes(data[i : i + 4], "big") for i in range(0, 16, 4))
    return words, is_v4
