"""Payload-matching policy tier: shadow/enforce mitigation over the
batched Aho-Corasick kernels (ISSUE-19).

The control-plane half of kernels.acmatch: ``PayloadTier`` owns the
compiled pattern automaton's device value operands (hot-swapped whole,
never recompiled — the geometry buckets in AcSpec are the only jit
key), the shadow/enforce mode scalar (a (1,) int32 DEVICE operand, so a
mode flip is a value swap too), and the match counters, and serves both
paths — the in-program fourth verdict-merge tier the resident fused
step carries (jaxpath.jitted_resident_step(payload=spec)) and the
one-follow-on-launch-per-admission form on the multi-dispatch wire
path.

Policy semantics mirror the scoring tier's enforce mode: a matched lane
is rewritten to Deny (ruleId 0) — but NEVER a failsafe cell
(kernels.mxu_score.failsafe_lane_mask_np, the same infw.failsaferules
port list) and never an existing rule Deny.  On the flow paths the
ENFORCED verdict is what batch-inserts into the flow table, so
mitigation sticks to the flow — and a pattern-set swap bumps the flow
generation exactly like a rule patch (TpuClassifier.set_payload_
patterns), invalidating stale cached verdicts through the same stamps
every table edit uses.

Pattern sets are versioned artifacts: ``save_patterns``/
``load_patterns`` write an npz (concatenated pattern bytes + lengths)
plus a JSON manifest (format tag, version, geometry, sha256 of the npz
bytes) — the daemon's ``<state-dir>/patterns/`` hot-swap dir consumes
exactly these pairs, the PR-14 models-dir discipline.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .kernels.acmatch import (
    AcModel,
    AcSpec,
    compile_patterns,
    host_payload_rewrite,
    jitted_acmatch,
    model_device,
    validate_patterns,
)

#: manifest format tag (bump on any incompatible artifact change)
PATTERN_FORMAT = "infw-acmatch-v1"


# --- versioned pattern-set artifacts (npz + JSON manifest) -------------------


def save_patterns(patterns: Sequence[bytes], path: str,
                  plen: int = 64, version: Optional[str] = None,
                  spec: Optional[AcSpec] = None) -> str:
    """Write ``path`` (.npz: concatenated pattern bytes + per-pattern
    lengths) plus ``path + '.json'`` (the manifest: format, version,
    geometry, sha256 of the npz bytes).  Returns the manifest path.
    Writes are tmp+rename, so a hot-swap dir scanner can never observe
    a torn artifact."""
    patterns = [bytes(p) for p in patterns]
    validate_patterns(patterns, plen)
    if spec is None:
        spec = compile_patterns(patterns, plen=plen).spec
    if not path.endswith(".npz"):
        path = path + ".npz"
    blob = np.frombuffer(b"".join(patterns), np.uint8)
    lens = np.asarray([len(p) for p in patterns], np.int32)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, blob=blob, lens=lens)
    os.replace(tmp, path)
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    manifest = {
        "format": PATTERN_FORMAT,
        "version": str(version or "0"),
        "spec": dict(spec._asdict()),
        "patterns": len(patterns),
        "sha256": digest,
    }
    mpath = path + ".json"
    with open(mpath + ".tmp", "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(mpath + ".tmp", mpath)
    return mpath


def load_patterns(path: str) -> Tuple[List[bytes], AcSpec, str]:
    """Load a versioned pattern-set artifact -> (patterns, spec,
    version).  The manifest is REQUIRED and its checksum must match the
    npz bytes — a corrupted artifact must fail at the control plane,
    never mis-match on the serving path."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    mpath = path + ".json"
    if not os.path.exists(mpath):
        raise ValueError(f"pattern-set manifest missing: {mpath}")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("format") != PATTERN_FORMAT:
        raise ValueError(
            f"pattern-set format {manifest.get('format')!r} != "
            f"{PATTERN_FORMAT!r}"
        )
    with open(path, "rb") as f:
        raw = f.read()
    digest = hashlib.sha256(raw).hexdigest()
    if digest != manifest.get("sha256"):
        raise ValueError(
            f"pattern-set checksum mismatch for {path} (manifest "
            f"{manifest.get('sha256', '')[:12]}.., npz {digest[:12]}..)"
        )
    import io

    with np.load(io.BytesIO(raw)) as z:
        blob = bytes(np.asarray(z["blob"], np.uint8).tobytes())
        lens = np.asarray(z["lens"], np.int64)
    pats, off = [], 0
    for n in lens:
        pats.append(blob[off:off + int(n)])
        off += int(n)
    spec = AcSpec(**manifest["spec"])
    return pats, spec, str(manifest.get("version", "0"))


# --- seeded traffic/pattern generators (bench, loadgen, statecheck) ---------

_HTTP_METHODS = (b"GET", b"POST", b"HEAD", b"PUT")
_HTTP_PATHS = (b"/", b"/index.html", b"/api/v1/items", b"/static/app.js",
               b"/health", b"/favicon.ico")


def signature_patterns(rng, count: int, plen: int = 64) -> List[bytes]:
    """A seeded signature set: a few text tokens (overlapping suffixes
    on purpose — the failure-link surface) plus random byte signatures
    of mixed length.  Deterministic per rng state."""
    base = [b"/etc/passwd", b"etc/passwd", b"passwd", b"<script>",
            b"script>", b"SELECT ", b"ELECT ", b"\x90\x90\x90\x90"]
    pats: List[bytes] = list(base[:min(count, len(base))])
    seen = set(pats)
    while len(pats) < count:
        n = int(rng.integers(2, min(17, plen + 1)))
        p = bytes(rng.integers(0, 256, size=n, dtype=np.uint8).tobytes())
        if p and p not in seen and len(p) <= plen:
            seen.add(p)
            pats.append(p)
    return pats[:count]


def benign_payloads(rng, n: int, plen: int = 64) -> Tuple[np.ndarray,
                                                          np.ndarray]:
    """(pay (n, plen) uint8, plen_col (n,) int32): HTTP-ish request
    prefixes of varying length — the benign traffic shape loadgen's
    ``--payload http`` emits."""
    pay = np.zeros((n, plen), np.uint8)
    lens = np.zeros(n, np.int32)
    for i in range(n):
        m = _HTTP_METHODS[int(rng.integers(0, len(_HTTP_METHODS)))]
        p = _HTTP_PATHS[int(rng.integers(0, len(_HTTP_PATHS)))]
        line = m + b" " + p + b" HTTP/1.1\r\nHost: example-" + \
            str(int(rng.integers(0, 100))).encode() + b".net\r\n\r\n"
        k = min(len(line), plen)
        pay[i, :k] = np.frombuffer(line[:k], np.uint8)
        lens[i] = k
    return pay, lens


def attack_payloads(rng, n: int, patterns: Sequence[bytes],
                    plen: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """Signature-bearing payload columns: benign base with one pattern
    planted per packet at a random offset — sometimes deliberately
    CROSSING the prefix-truncation boundary (those must NOT match, the
    truncation-semantics surface the statecheck config exercises)."""
    pay, lens = benign_payloads(rng, n, plen)
    pats = [bytes(p) for p in patterns]
    for i in range(n):
        p = pats[int(rng.integers(0, len(pats)))]
        lens[i] = plen
        if rng.random() < 0.15 and len(p) > 1:
            off = plen - int(rng.integers(1, len(p)))  # straddles the cut
        else:
            off = int(rng.integers(0, plen - len(p) + 1))
        end = min(off + len(p), plen)
        pay[i, off:end] = np.frombuffer(p[:end - off], np.uint8)
    return pay, lens


# --- the serving-tier facade -------------------------------------------------


class PayloadTier:
    """Owns the compiled automaton's device operands + policy mode +
    match counters.  STATELESS on device (unlike flow/telemetry/score —
    nothing donated): the fused step takes the operands alongside the
    tables, so swapping them can never disturb donation aliasing."""

    def __init__(self, model_or_patterns, plen: int = 64,
                 mode: str = "shadow", spec: Optional[AcSpec] = None,
                 keep_masks: int = 0, device=None) -> None:
        if isinstance(model_or_patterns, AcModel):
            model = model_or_patterns
        else:
            model = compile_patterns(
                model_or_patterns, plen=plen, spec=spec
            )
        if mode not in ("shadow", "enforce"):
            raise ValueError(f"payload mode {mode!r}")
        self._lock = threading.Lock()
        self.model = model
        self.spec = model.spec
        self.mode = mode
        self.version = 0
        #: Device or replicated NamedSharding (the mesh placement)
        self._device = device
        self._trans, self._mmap = model_device(model, device=device)
        self._pmode = self._put_mode(mode)
        self._counters: Dict[str, int] = {
            "admissions": 0, "lanes": 0, "matched": 0, "enforced": 0,
            "swaps": 0,
        }
        self._keep = int(keep_masks)
        self._masks: deque = deque(maxlen=max(1, self._keep))
        #: classifier hook: fired after a pattern swap (flow-generation
        #: bump — a swap behaves like a rule patch)
        self.on_swap = None

    # -- operands -----------------------------------------------------------

    def _put_mode(self, mode: str):
        import jax

        arr = np.asarray([1 if mode == "enforce" else 0], np.int32)
        return (jax.device_put(arr) if self._device is None
                else jax.device_put(arr, self._device))

    def device_ops(self) -> tuple:
        """(trans, matchmap, pmode) — the fused step's payload operand
        group.  Value operands only; geometry lives in ``self.spec``."""
        with self._lock:
            return (self._trans, self._mmap, self._pmode)

    @property
    def enforce(self) -> bool:
        return self.mode == "enforce"

    def set_mode(self, mode: str) -> None:
        if mode not in ("shadow", "enforce"):
            raise ValueError(f"payload mode {mode!r}")
        with self._lock:
            self.mode = mode
            self._pmode = self._put_mode(mode)

    def set_keep_masks(self, n: int) -> None:
        with self._lock:
            self._keep = int(n)
            self._masks = deque(self._masks, maxlen=max(1, self._keep))

    @property
    def tracking(self) -> bool:
        """True when retained-mask tracking is on (statecheck): the
        resident paths then re-derive the full match bitmap through one
        standalone launch per admission (the fused readback ships only
        the packed hit/rewrite bits)."""
        with self._lock:
            return self._keep > 0

    def recent_masks(self) -> list:
        """[(pay, plen, bitmap, hit)] retained admissions (statecheck's
        device-vs-oracle compare substrate; keep_masks > 0 only).
        ``bitmap`` is the (B, PW) device match bitmap, ``hit`` the
        SERVED matched-lane bits — on the fused paths they come from
        different programs over the same operands, so the cross-check
        bitmap.any(axis=1) == hit pins the fused merge to the
        standalone kernel."""
        with self._lock:
            return list(self._masks)

    # -- classic (follow-on launch) path ------------------------------------

    def match(self, pay_np: np.ndarray, plen_np: np.ndarray) -> np.ndarray:
        """One standalone device launch -> (B, PW) uint32 bitmaps."""
        import jax

        with self._lock:
            trans, mmap = self._trans, self._mmap
            spec = self.spec
        f = jitted_acmatch(spec)
        pay = np.ascontiguousarray(pay_np, np.uint8)
        plen = np.ascontiguousarray(plen_np, np.int32)
        if self._device is None:
            pay, plen = jax.device_put(pay), jax.device_put(plen)
        else:
            pay = jax.device_put(pay, self._device)
            plen = jax.device_put(plen, self._device)
        return np.asarray(f(trans, mmap, pay, plen))

    def apply_wire(self, res16: np.ndarray, pay_np: np.ndarray,
                   plen_np: np.ndarray, proto: np.ndarray,
                   dst_port: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """The multi-dispatch path's follow-on: match + (enforce-mode)
        host rewrite -> (res16_out, hit).  Counters accrue here."""
        bitmap = self.match(pay_np, plen_np)
        with self._lock:
            model, enforce = self.model, self.mode == "enforce"
        res_out = host_payload_rewrite(
            model, res16, bitmap, enforce, proto, dst_port
        )
        hit = (bitmap != 0).any(axis=1)
        self.note(bitmap, hit,
                  np.asarray(res_out, np.uint32)
                  != np.asarray(res16, np.uint32),
                  pay_np=pay_np, plen_np=plen_np)
        return res_out, hit

    # -- counters (both paths) ----------------------------------------------

    def note(self, bitmap: Optional[np.ndarray], hit: np.ndarray,
             rewrote: np.ndarray, pay_np: Optional[np.ndarray] = None,
             plen_np: Optional[np.ndarray] = None) -> None:
        """Fold one admission's outcome into the counters (and the
        retained-mask ring when tracking is on)."""
        with self._lock:
            self._counters["admissions"] += 1
            self._counters["lanes"] += int(np.asarray(hit).shape[0])
            self._counters["matched"] += int(np.count_nonzero(hit))
            self._counters["enforced"] += int(np.count_nonzero(rewrote))
            if self._keep and pay_np is not None and bitmap is not None:
                self._masks.append((
                    np.array(pay_np, np.uint8, copy=True),
                    np.array(plen_np, np.int32, copy=True),
                    np.array(bitmap, np.uint32, copy=True),
                    np.array(hit, bool, copy=True),
                ))

    def counter_values(self) -> Dict[str, int]:
        """payload_* counters/gauges for /metrics."""
        with self._lock:
            return {
                "payload_admissions_total": self._counters["admissions"],
                "payload_lanes_total": self._counters["lanes"],
                "payload_matched_total": self._counters["matched"],
                "payload_enforced_total": self._counters["enforced"],
                "payload_pattern_swaps_total": self._counters["swaps"],
                "payload_patterns": len(self.model.patterns),
                "payload_patternset_version": self.version,
            }

    # -- hot swap ------------------------------------------------------------

    def swap_patterns(self, patterns_or_model, plen: Optional[int] = None
                      ) -> None:
        """Replace the pattern set WITHOUT recompiling: the new set
        must land in the same AcSpec buckets (states/patterns/plen), so
        only the device value operands change.  Fires ``on_swap`` (the
        classifier's flow-generation bump) after the operands flip."""
        if isinstance(patterns_or_model, AcModel):
            model = patterns_or_model
        else:
            model = compile_patterns(
                patterns_or_model, plen=plen or self.spec.plen,
                spec=self.spec,
            )
        if model.spec != self.spec:
            raise ValueError(
                f"pattern swap changes geometry {self.spec} -> "
                f"{model.spec}; a swap must stay in-bucket"
            )
        trans, mmap = model_device(model, device=self._device)
        with self._lock:
            self.model = model
            self._trans, self._mmap = trans, mmap
            self.version += 1
            self._counters["swaps"] += 1
            # retained masks were matched by the OLD automaton — stale
            # against the new pattern set, drop them
            self._masks.clear()
            hook = self.on_swap
        if hook is not None:
            hook()

    def reset_counters(self) -> None:
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
