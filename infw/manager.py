"""The cluster manager: controller runtime wiring both reconcilers, the
admission webhook, and the NodeState export path.

Equivalent of the reference's manager binary (/root/reference/main.go):
env contract DAEMONSET_IMAGE / DAEMONSET_NAMESPACE (:87-99), webhook
registration behind a toggle (:142-147), platform probe (:149-154),
controller setup with watches (:132-140, 155-164), healthz endpoint and
blocking run loop (:101-126, 177).

The watch->workqueue->reconcile shape mirrors controller-runtime: events
coalesce in a debounced queue, reconciles run on a worker thread, and a
config reconcile returning requeue_after is rescheduled (the 5s
requeue-while-progressing, ingressnodefirewallconfig_controller.go:94-107).

NodeState export: when an ``export_dir`` is configured, every NodeState
write/delete is mirrored to ``<export_dir>/nodestates/<node>.json`` — the
file protocol the daemon watches — so manager and daemons compose across
process boundaries the way the reference's manager and DaemonSet compose
through the k8s API.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import logging
import os
import queue
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ._threads import spawn
from . import platform as platform_mod
from . import validate
from .controllers import (
    DEFAULT_CONFIG_NAME,
    IngressNodeFirewallConfigReconciler,
    IngressNodeFirewallReconciler,
)
from .spec import (
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
    IngressNodeFirewallNodeState,
    ObjectMeta,
)
from .apply import apply_object
from .store import (
    DELETED,
    AdmissionError,
    AlreadyExistsError,
    InMemoryStore,
    Node,
    NotFoundError,
    StoreError,
)

log = logging.getLogger("infw.manager")

DEFAULT_METRICS_PORT = 39201  # main.go:63
DEFAULT_HEALTH_PORT = 8081    # main.go:65


def inf_admission(obj: IngressNodeFirewall, store: InMemoryStore) -> List[str]:
    """The validating webhook hooked into the store's admission seam
    (webhook.go ValidateCreate/Update: validate against all *other*
    existing IngressNodeFirewalls)."""
    existing = [
        o
        for o in store.list(IngressNodeFirewall.KIND)
        if o.metadata.name != obj.metadata.name
    ]
    return validate.validate_ingress_node_firewall(obj, existing)


class Manager:
    def __init__(
        self,
        store: Optional[InMemoryStore] = None,
        namespace: str = "ingress-node-firewall-system",
        daemon_image: str = "infw-daemon:latest",
        enable_webhook: bool = True,
        export_dir: Optional[str] = None,
        apply_dir: Optional[str] = None,
        apply_poll_interval_s: float = 0.5,
        register_nodes: Optional[List[str]] = None,
        metrics_port: int = DEFAULT_METRICS_PORT,
        health_port: int = DEFAULT_HEALTH_PORT,
        lease=None,
        lease_holder: Optional[str] = None,
    ) -> None:
        self.store = store if store is not None else InMemoryStore()
        self.namespace = namespace
        self.platform = platform_mod.get_platform_info()
        backend = "tpu" if self.platform.is_tpu else "cpu"
        self.fanout = IngressNodeFirewallReconciler(self.store, namespace=namespace)
        self.config = IngressNodeFirewallConfigReconciler(
            self.store, namespace=namespace, daemon_image=daemon_image, backend=backend
        )
        self.metrics_port = metrics_port
        self.health_port = health_port
        self.reconcile_counts = {"fanout": 0, "config": 0}
        self.apply_counts = {"applied": 0, "rejected": 0, "deleted": 0}

        if enable_webhook:
            self.store.set_admission(IngressNodeFirewall.KIND, inf_admission)

        self.export_dir: Optional[str] = None
        if export_dir:
            self.export_dir = os.path.join(export_dir, "nodestates")
            os.makedirs(self.export_dir, exist_ok=True)

        # kubectl-apply analogue: a watched directory of IngressNodeFirewall
        # CR JSONs (see scan_apply_dir_once) — the file seam that lets an
        # operator drive a RUNNING manager process the way `kubectl apply`
        # drives the reference's API server.
        self.apply_dir: Optional[str] = None
        if apply_dir:
            self.apply_dir = apply_dir
            os.makedirs(self.apply_dir, exist_ok=True)
        self.apply_poll_interval_s = apply_poll_interval_s
        # filename -> ((kind, name, namespace) | None, content hash)
        self._applied: dict = {}

        # Self-registered Node inventory for API-server-less deployments
        # (the compose stack): the reference's fan-out matches CRs against
        # cluster Nodes; a single-node composition registers its own host
        # the way a kubelet joins the cluster.
        for node_name in register_nodes or []:
            try:
                self.store.create(Node(metadata=ObjectMeta(name=node_name)))
            except AlreadyExistsError:
                pass

        # Single-writer lease (leader election, main.go:76-85): start()
        # blocks in standby until the lease is acquired; a renewal
        # failure (another instance stole an expired lease) stops this
        # manager — the controller-runtime leader-loss-is-fatal contract.
        self.lease = lease
        self.lease_holder = lease_holder or f"mgr-{os.getpid()}-{id(self):x}"
        self.is_leader = lease is None  # leaderless single-writer default
        self.lease_lost = False

        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._servers: List[ThreadingHTTPServer] = []
        self._requeue_timers: dict = {}  # config name -> outstanding Timer
        self._watch_cancels: List = []

        # Watches (SetupWithManager): the fan-out controller reconciles on
        # INF + Node + owned NodeState events
        # (ingressnodefirewall_controller.go:239-249); the config controller
        # on Config events.
        for kind in (IngressNodeFirewall.KIND, Node.KIND):
            self._watch_cancels.append(
                self.store.watch(kind, lambda e, o: self.enqueue_fanout())
            )
        self._watch_cancels.append(
            self.store.watch(IngressNodeFirewallNodeState.KIND, self._on_nodestate_event)
        )
        self._watch_cancels.append(
            self.store.watch(
                IngressNodeFirewallConfig.KIND,
                lambda e, o: self.enqueue_config(o.metadata.name),
            )
        )

    # -- work queue ----------------------------------------------------------

    def enqueue_fanout(self) -> None:
        # Standby instances (lease not yet acquired) must not act OR
        # accumulate an unbounded queue; the post-acquisition full resync
        # in start() covers anything that happened while standing by.
        if not self.is_leader:
            return
        self._queue.put(("fanout", None))

    def enqueue_config(self, name: str) -> None:
        if not self.is_leader:
            return
        self._queue.put(("config", name))

    def _on_nodestate_event(self, event: str, obj) -> None:
        # Only the leader mirrors exports — a standby writing the same
        # export tmp files would race the leader's os.replace protocol.
        if not self.is_leader:
            return
        if self.export_dir is not None:
            path = os.path.join(self.export_dir, f"{obj.metadata.name}.json")
            if event == DELETED:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            else:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(obj.to_dict(), f)
                os.replace(tmp, path)
        # Owned-object watch: NodeState drift — including out-of-band
        # deletion — triggers the owner's reconcile (Owns(&NodeState),
        # :247); self-initiated deletes converge in one no-write pass since
        # the store suppresses no-op writes.
        self.enqueue_fanout()

    def process_one(self, block: bool = True, timeout: Optional[float] = None) -> bool:
        """Run one queued reconcile; returns False when the queue is empty
        (non-blocking mode) or the stop flag is set."""
        try:
            item = self._queue.get(block=block, timeout=timeout)
        except queue.Empty:
            return False
        kind, arg = item
        # Debounce: collapse consecutive duplicate requests.
        try:
            while True:
                nxt = self._queue.get_nowait()
                if nxt != item:
                    self._queue.put(nxt)
                    break
        except queue.Empty:
            pass
        try:
            if kind == "fanout":
                self.fanout.reconcile()
                self.reconcile_counts["fanout"] += 1
            elif kind == "config":
                result = self.config.reconcile(arg)
                self.reconcile_counts["config"] += 1
                if result.requeue_after is not None and not self._stop.is_set():
                    # One outstanding requeue per config: cancel-and-replace
                    # so a progressing deployment never accumulates timers.
                    old = self._requeue_timers.pop(arg, None)
                    if old is not None:
                        old.cancel()
                    t = threading.Timer(
                        result.requeue_after, lambda: self.enqueue_config(arg)
                    )
                    t.daemon = True
                    t.start()
                    self._requeue_timers[arg] = t
        except Exception as e:  # reconcile errors are logged, never fatal
            log.error("%s reconcile failed: %s", kind, e)
        return True

    def drain(self) -> None:
        """Process queued work until empty (test helper — the equivalent of
        envtest's Eventually())."""
        while self.process_one(block=False):
            pass

    # -- apply dir (kubectl-apply seam) --------------------------------------

    # CR kinds the apply seam accepts (NodeStates are manager-owned
    # output, never operator input — exactly the reference's RBAC shape)
    APPLY_KINDS = {
        IngressNodeFirewall.KIND: IngressNodeFirewall,
        IngressNodeFirewallConfig.KIND: IngressNodeFirewallConfig,
    }

    def scan_apply_dir_once(self) -> None:
        """Reconcile the apply directory against the store: each
        ``<name>.json`` is a CR (IngressNodeFirewall or
        IngressNodeFirewallConfig, discriminated by ``kind``) applied
        through the admission seam (create-or-update); file deletion
        deletes the CR.  The admission verdict lands in
        ``<name>.status.json`` — the file protocol's version of the
        webhook response the reference returns on the API call
        (webhook.go ValidateCreate/Update)."""
        if not self.apply_dir:
            return
        seen = set()
        for fn in sorted(os.listdir(self.apply_dir)):
            if (
                not fn.endswith(".json")
                or fn.endswith(".status.json")
                or fn.endswith(".tmp")
            ):
                continue
            path = os.path.join(self.apply_dir, fn)
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                continue
            seen.add(fn)
            # Content hash, not (mtime, size): same-length rewrites within
            # one mtime tick must not be silently skipped.
            sig = hashlib.sha1(raw).hexdigest()
            prev = self._applied.get(fn)  # (ident-or-None, sig)
            if prev is not None and prev[1] == sig:
                continue
            errors: List[str] = []
            obj = None
            try:
                doc = json.loads(raw)
            except ValueError as e:
                doc = None
                errors = [f"unparseable CR document: {e}"]
            if doc is not None:
                cls = self.APPLY_KINDS.get(
                    doc.get("kind") if isinstance(doc, dict) else None
                )
                if cls is None:
                    kind = doc.get("kind") if isinstance(doc, dict) else doc
                    errors = [
                        f"unsupported kind {kind!r} "
                        f"(expected one of {sorted(self.APPLY_KINDS)})"
                    ]
                else:
                    try:
                        obj = cls.from_dict(doc)
                    except Exception as e:
                        errors = [f"invalid {cls.KIND} document: {e}"]
                    if obj is not None and (
                        cls is IngressNodeFirewallConfig
                        and not obj.metadata.namespace
                    ):
                        # the config reconciler looks in the manager namespace
                        obj.metadata.namespace = self.namespace
            if obj is not None:
                ident = (obj.KIND, obj.metadata.name, obj.metadata.namespace)
                old_ident = (
                    prev[0]
                    if prev is not None and prev[0] not in (None, ident)
                    else None
                )
                errors = self._try_apply(obj)
                if old_ident is not None:
                    # The file renamed (or re-kinded) its CR.  The
                    # replacement is validated FIRST (above) so a bad edit
                    # never fails open: the webhook analogue rejects
                    # atomically, leaving the old object enforcing.  Only
                    # when the rejection is the successor conflicting with
                    # its own predecessor (cross-INF order overlap names
                    # the conflicting INF, validate.py:266-270) is the
                    # predecessor removed for ONE retry — any other
                    # rejection must not touch the enforcing object (a
                    # delete/recreate cycle would briefly fail open for
                    # watchers and can lose the CR if the restore races).
                    conflict_tag = (
                        f"conflicts with IngressNodeFirewall "
                        f"{old_ident[1]!r}"
                    )
                    # EVERY error must be a self-conflict: any other error
                    # survives the predecessor's removal, so the retry
                    # could not succeed and the churn would be pure risk.
                    self_conflict = (
                        old_ident[0] == obj.KIND
                        and bool(errors)
                        and all(conflict_tag in e for e in errors)
                    )
                    if not errors:
                        self._delete_cr(old_ident, fn + " (renamed)")
                    elif self_conflict:
                        old_obj = self._get_cr(old_ident)
                        self._delete_cr(old_ident, fn + " (renamed)")
                        errors = self._try_apply(obj)
                        if errors and old_obj is not None:
                            try:
                                self.store.create(old_obj)
                                log.warning(
                                    "apply %s: replacement rejected; "
                                    "restored %s/%s", fn, old_ident[0],
                                    old_ident[1],
                                )
                            except StoreError as e:
                                log.error(
                                    "apply %s: could not restore %s/%s "
                                    "after rejected replacement: %s",
                                    fn, old_ident[0], old_ident[1], e,
                                )
            self._write_apply_status(fn, errors)
            if errors:
                self.apply_counts["rejected"] += 1
                log.warning("apply %s rejected: %s", fn, "; ".join(errors))
                # Remember the rejected signature so an unchanged file is
                # not re-applied (and re-logged) every poll — but KEEP the
                # previously applied CR mapping: the live object must still
                # be deletable when the file goes away.
                old = prev if prev is not None else (None, None)
                self._applied[fn] = (old[0], sig)
            else:
                self.apply_counts["applied"] += 1
                log.info("applied %s -> %s/%s", fn, obj.KIND, obj.metadata.name)
                self._applied[fn] = (ident, sig)
        for fn in [f for f in self._applied if f not in seen]:
            ident, _sig = self._applied.pop(fn)
            try:
                os.remove(os.path.join(self.apply_dir, fn[:-5] + ".status.json"))
            except OSError:
                pass
            if ident is None:
                continue  # a rejected file never reached the store
            self._delete_cr(ident, fn + " removed")

    def _try_apply(self, obj) -> List[str]:
        """Apply through the admission seam; returns the rejection errors
        ([] on success)."""
        try:
            apply_object(self.store, obj)
        except AdmissionError as e:
            return list(e.errors)
        except StoreError as e:
            return [str(e)]
        return []

    def _get_cr(self, ident):
        kind, name, namespace = ident
        try:
            return self.store.get(kind, name, namespace or "")
        except NotFoundError:
            return None

    def _delete_cr(self, ident, why: str) -> None:
        kind, name, namespace = ident
        try:
            self.store.delete(kind, name, namespace or "")
            self.apply_counts["deleted"] += 1
            log.info("deleted %s/%s (%s)", kind, name, why)
        except NotFoundError:
            pass

    def _write_apply_status(self, fn: str, errors: List[str]) -> None:
        status_path = os.path.join(
            self.apply_dir, fn[:-5] + ".status.json"
        )
        tmp = status_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"applied": not errors, "errors": errors}, f)
        os.replace(tmp, status_path)

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scan_apply_dir_once()
            except Exception as e:  # never let a scan error kill the loop
                log.error("apply-dir scan failed: %s", e)
            self._stop.wait(self.apply_poll_interval_s)

    # -- lifecycle -----------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            self.process_one(block=True, timeout=0.2)

    def _make_handler(mgr):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path in ("/healthz", "/readyz"):
                    self._send(200, "ok")
                elif self.path == "/metrics":
                    lines = [
                        "# TYPE ingressnodefirewall_manager_reconcile_total counter"
                    ]
                    for k, v in mgr.reconcile_counts.items():
                        lines.append(
                            f'ingressnodefirewall_manager_reconcile_total{{controller="{k}"}} {v}'
                        )
                    lines.append(
                        "# TYPE ingressnodefirewall_manager_apply_total counter"
                    )
                    for k, v in mgr.apply_counts.items():
                        lines.append(
                            f'ingressnodefirewall_manager_apply_total{{outcome="{k}"}} {v}'
                        )
                    self._send(200, "\n".join(lines) + "\n")
                else:
                    self._send(404, "not found")

        return Handler

    def _await_lease(self, timeout: Optional[float]) -> bool:
        """Standby loop: poll try_acquire until leadership or timeout/stop.
        Returns True when this instance is the leader."""
        deadline = None if timeout is None else time.time() + timeout
        poll = max(0.05, self.lease.duration_s / 10.0)
        while not self._stop.is_set():
            if self.lease.try_acquire(self.lease_holder):
                self.is_leader = True
                log.info("lease acquired holder=%s", self.lease_holder)
                return True
            if deadline is not None and time.time() >= deadline:
                return False
            time.sleep(poll)
        return False

    def _renew_loop(self) -> None:
        interval = self.lease.duration_s / 3.0
        while not self._stop.wait(interval):
            if not self.lease.renew(self.lease_holder):
                # Another instance took over an expired lease: stop acting
                # as leader immediately (fatal, like controller-runtime's
                # leader-election loss).
                self.lease_lost = True
                self.is_leader = False
                log.error(
                    "lease lost holder=%s (stolen after expiry); stopping",
                    self.lease_holder,
                )
                spawn(self.stop, name="infw-mgr-stop")
                return

    def start(self, lease_timeout: Optional[float] = None) -> bool:
        """Bring the manager up.  With a lease configured this blocks in
        standby until leadership is acquired (pass ``lease_timeout`` to
        bound the wait; returns False if it expires un-acquired — the
        instance stays standby and can be start()ed again)."""
        if self.lease is not None and not self.is_leader:
            if not self._await_lease(lease_timeout):
                return False
            t = spawn(self._renew_loop, name="infw-lease-renew")
            self._threads.append(t)
        handler = self._make_handler()
        for port in {self.metrics_port, self.health_port}:
            srv = ThreadingHTTPServer(("127.0.0.1", port), handler)
            self._servers.append(srv)
            t = spawn(srv.serve_forever, name="infw-mgr-http")
            self._threads.append(t)
        t = spawn(self._worker, name="infw-mgr-worker")
        self._threads.append(t)
        if self.apply_dir:
            t = spawn(self._apply_loop, name="infw-mgr-apply")
            self._threads.append(t)
        # Initial full reconciles (the List-driven state resync on start).
        self.enqueue_fanout()
        self.enqueue_config(DEFAULT_CONFIG_NAME)
        log.info(
            "manager started namespace=%s platform=%s devices=%d leader=%s",
            self.namespace, self.platform.backend, self.platform.num_devices,
            self.is_leader,
        )
        return True

    def stop(self) -> None:
        self._stop.set()
        for cancel in self._watch_cancels:
            cancel()
        for t in self._requeue_timers.values():
            t.cancel()
        self._requeue_timers.clear()
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()
        if self.lease is not None and self.is_leader:
            self.is_leader = False
            self.lease.release(self.lease_holder)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry enforcing the env contract (main.go:87-99)."""
    p = argparse.ArgumentParser(prog="infw-manager")
    p.add_argument("--export-dir", default=None,
                   help="mirror NodeStates to <dir>/nodestates for file-driven daemons")
    p.add_argument("--apply-dir", default=None,
                   help="watch <dir> for CR JSONs (IngressNodeFirewall or "
                        "IngressNodeFirewallConfig, by kind) — the "
                        "kubectl-apply seam; <name>.status.json carries "
                        "the admission verdict)")
    p.add_argument("--register-node", action="append", default=None,
                   metavar="NAME",
                   help="register a Node in the manager's inventory "
                        "(repeatable; API-server-less compose runs where "
                        "no kubelet joins nodes)")
    p.add_argument("--namespace", default=os.environ.get(
        "DAEMONSET_NAMESPACE", ""))
    p.add_argument("--daemon-image", default=os.environ.get("DAEMONSET_IMAGE", ""))
    p.add_argument("--enable-webhook", action="store_true", default=True)
    p.add_argument("--disable-webhook", dest="enable_webhook", action="store_false")
    p.add_argument("--metrics-port", type=int, default=DEFAULT_METRICS_PORT)
    p.add_argument("--health-port", type=int, default=DEFAULT_HEALTH_PORT)
    p.add_argument("--lease-file", default=None,
                   help="single-writer lease file (leader election, "
                        "main.go:76-85); default <export-dir>/manager.lease "
                        "when --export-dir is set, 'none' disables")
    p.add_argument("--lease-duration", type=float, default=15.0,
                   help="lease duration in seconds; a crashed leader is "
                        "taken over after at most this long")
    args = p.parse_args(argv)

    # Mirrors the hard env guards at main.go:87-99.
    if not args.daemon_image:
        p.error("DAEMONSET_IMAGE environment variable or --daemon-image must be set")
    if not args.namespace:
        p.error("DAEMONSET_NAMESPACE environment variable or --namespace must be set")

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    lease = None
    lease_file = args.lease_file
    if lease_file is None and args.export_dir:
        lease_file = os.path.join(args.export_dir, "manager.lease")
    if lease_file and lease_file != "none":
        from .lease import FileLease

        os.makedirs(os.path.dirname(os.path.abspath(lease_file)),
                    exist_ok=True)
        lease = FileLease(lease_file, duration_s=args.lease_duration)
        log.info("single-writer lease at %s (duration %.0fs)",
                 lease_file, args.lease_duration)

    mgr = Manager(
        namespace=args.namespace,
        daemon_image=args.daemon_image,
        enable_webhook=args.enable_webhook,
        export_dir=args.export_dir,
        apply_dir=args.apply_dir,
        register_nodes=args.register_node,
        metrics_port=args.metrics_port,
        health_port=args.health_port,
        lease=lease,
    )
    stop = threading.Event()

    def on_signal(*_a):
        stop.set()
        mgr._stop.set()  # unblocks a standby _await_lease wait too

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    if not mgr.start():  # blocks in standby until the lease is acquired
        log.info("exiting before leadership (signalled in standby)")
        return 0
    try:
        # a lease loss stop()s the manager from its renew thread; exit
        # the process then (controller-runtime semantics) so a supervisor
        # can restart us into standby
        while not stop.wait(0.5):
            if mgr.lease_lost:
                log.error("exiting after lease loss")
                return 1
    finally:
        mgr.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
