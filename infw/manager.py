"""The cluster manager: controller runtime wiring both reconcilers, the
admission webhook, and the NodeState export path.

Equivalent of the reference's manager binary (/root/reference/main.go):
env contract DAEMONSET_IMAGE / DAEMONSET_NAMESPACE (:87-99), webhook
registration behind a toggle (:142-147), platform probe (:149-154),
controller setup with watches (:132-140, 155-164), healthz endpoint and
blocking run loop (:101-126, 177).

The watch->workqueue->reconcile shape mirrors controller-runtime: events
coalesce in a debounced queue, reconciles run on a worker thread, and a
config reconcile returning requeue_after is rescheduled (the 5s
requeue-while-progressing, ingressnodefirewallconfig_controller.go:94-107).

NodeState export: when an ``export_dir`` is configured, every NodeState
write/delete is mirrored to ``<export_dir>/nodestates/<node>.json`` — the
file protocol the daemon watches — so manager and daemons compose across
process boundaries the way the reference's manager and DaemonSet compose
through the k8s API.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from . import platform as platform_mod
from . import validate
from .controllers import (
    DEFAULT_CONFIG_NAME,
    IngressNodeFirewallConfigReconciler,
    IngressNodeFirewallReconciler,
)
from .spec import (
    IngressNodeFirewall,
    IngressNodeFirewallConfig,
    IngressNodeFirewallNodeState,
)
from .store import DELETED, InMemoryStore, Node

log = logging.getLogger("infw.manager")

DEFAULT_METRICS_PORT = 39201  # main.go:63
DEFAULT_HEALTH_PORT = 8081    # main.go:65


def inf_admission(obj: IngressNodeFirewall, store: InMemoryStore) -> List[str]:
    """The validating webhook hooked into the store's admission seam
    (webhook.go ValidateCreate/Update: validate against all *other*
    existing IngressNodeFirewalls)."""
    existing = [
        o
        for o in store.list(IngressNodeFirewall.KIND)
        if o.metadata.name != obj.metadata.name
    ]
    return validate.validate_ingress_node_firewall(obj, existing)


class Manager:
    def __init__(
        self,
        store: Optional[InMemoryStore] = None,
        namespace: str = "ingress-node-firewall-system",
        daemon_image: str = "infw-daemon:latest",
        enable_webhook: bool = True,
        export_dir: Optional[str] = None,
        metrics_port: int = DEFAULT_METRICS_PORT,
        health_port: int = DEFAULT_HEALTH_PORT,
    ) -> None:
        self.store = store if store is not None else InMemoryStore()
        self.namespace = namespace
        self.platform = platform_mod.get_platform_info()
        backend = "tpu" if self.platform.is_tpu else "cpu"
        self.fanout = IngressNodeFirewallReconciler(self.store, namespace=namespace)
        self.config = IngressNodeFirewallConfigReconciler(
            self.store, namespace=namespace, daemon_image=daemon_image, backend=backend
        )
        self.metrics_port = metrics_port
        self.health_port = health_port
        self.reconcile_counts = {"fanout": 0, "config": 0}

        if enable_webhook:
            self.store.set_admission(IngressNodeFirewall.KIND, inf_admission)

        self.export_dir: Optional[str] = None
        if export_dir:
            self.export_dir = os.path.join(export_dir, "nodestates")
            os.makedirs(self.export_dir, exist_ok=True)

        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._servers: List[ThreadingHTTPServer] = []
        self._requeue_timers: dict = {}  # config name -> outstanding Timer
        self._watch_cancels: List = []

        # Watches (SetupWithManager): the fan-out controller reconciles on
        # INF + Node + owned NodeState events
        # (ingressnodefirewall_controller.go:239-249); the config controller
        # on Config events.
        for kind in (IngressNodeFirewall.KIND, Node.KIND):
            self._watch_cancels.append(
                self.store.watch(kind, lambda e, o: self.enqueue_fanout())
            )
        self._watch_cancels.append(
            self.store.watch(IngressNodeFirewallNodeState.KIND, self._on_nodestate_event)
        )
        self._watch_cancels.append(
            self.store.watch(
                IngressNodeFirewallConfig.KIND,
                lambda e, o: self.enqueue_config(o.metadata.name),
            )
        )

    # -- work queue ----------------------------------------------------------

    def enqueue_fanout(self) -> None:
        self._queue.put(("fanout", None))

    def enqueue_config(self, name: str) -> None:
        self._queue.put(("config", name))

    def _on_nodestate_event(self, event: str, obj) -> None:
        if self.export_dir is not None:
            path = os.path.join(self.export_dir, f"{obj.metadata.name}.json")
            if event == DELETED:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            else:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(obj.to_dict(), f)
                os.replace(tmp, path)
        # Owned-object watch: NodeState drift — including out-of-band
        # deletion — triggers the owner's reconcile (Owns(&NodeState),
        # :247); self-initiated deletes converge in one no-write pass since
        # the store suppresses no-op writes.
        self.enqueue_fanout()

    def process_one(self, block: bool = True, timeout: Optional[float] = None) -> bool:
        """Run one queued reconcile; returns False when the queue is empty
        (non-blocking mode) or the stop flag is set."""
        try:
            item = self._queue.get(block=block, timeout=timeout)
        except queue.Empty:
            return False
        kind, arg = item
        # Debounce: collapse consecutive duplicate requests.
        try:
            while True:
                nxt = self._queue.get_nowait()
                if nxt != item:
                    self._queue.put(nxt)
                    break
        except queue.Empty:
            pass
        try:
            if kind == "fanout":
                self.fanout.reconcile()
                self.reconcile_counts["fanout"] += 1
            elif kind == "config":
                result = self.config.reconcile(arg)
                self.reconcile_counts["config"] += 1
                if result.requeue_after is not None and not self._stop.is_set():
                    # One outstanding requeue per config: cancel-and-replace
                    # so a progressing deployment never accumulates timers.
                    old = self._requeue_timers.pop(arg, None)
                    if old is not None:
                        old.cancel()
                    t = threading.Timer(
                        result.requeue_after, lambda: self.enqueue_config(arg)
                    )
                    t.daemon = True
                    t.start()
                    self._requeue_timers[arg] = t
        except Exception as e:  # reconcile errors are logged, never fatal
            log.error("%s reconcile failed: %s", kind, e)
        return True

    def drain(self) -> None:
        """Process queued work until empty (test helper — the equivalent of
        envtest's Eventually())."""
        while self.process_one(block=False):
            pass

    # -- lifecycle -----------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            self.process_one(block=True, timeout=0.2)

    def _make_handler(mgr):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path in ("/healthz", "/readyz"):
                    self._send(200, "ok")
                elif self.path == "/metrics":
                    lines = [
                        "# TYPE ingressnodefirewall_manager_reconcile_total counter"
                    ]
                    for k, v in mgr.reconcile_counts.items():
                        lines.append(
                            f'ingressnodefirewall_manager_reconcile_total{{controller="{k}"}} {v}'
                        )
                    self._send(200, "\n".join(lines) + "\n")
                else:
                    self._send(404, "not found")

        return Handler

    def start(self) -> None:
        handler = self._make_handler()
        for port in {self.metrics_port, self.health_port}:
            srv = ThreadingHTTPServer(("127.0.0.1", port), handler)
            self._servers.append(srv)
            t = threading.Thread(target=srv.serve_forever, daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()
        self._threads.append(t)
        # Initial full reconciles (the List-driven state resync on start).
        self.enqueue_fanout()
        self.enqueue_config(DEFAULT_CONFIG_NAME)
        log.info(
            "manager started namespace=%s platform=%s devices=%d",
            self.namespace, self.platform.backend, self.platform.num_devices,
        )

    def stop(self) -> None:
        self._stop.set()
        for cancel in self._watch_cancels:
            cancel()
        for t in self._requeue_timers.values():
            t.cancel()
        self._requeue_timers.clear()
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry enforcing the env contract (main.go:87-99)."""
    p = argparse.ArgumentParser(prog="infw-manager")
    p.add_argument("--export-dir", default=None,
                   help="mirror NodeStates to <dir>/nodestates for file-driven daemons")
    p.add_argument("--namespace", default=os.environ.get(
        "DAEMONSET_NAMESPACE", ""))
    p.add_argument("--daemon-image", default=os.environ.get("DAEMONSET_IMAGE", ""))
    p.add_argument("--enable-webhook", action="store_true", default=True)
    p.add_argument("--disable-webhook", dest="enable_webhook", action="store_false")
    p.add_argument("--metrics-port", type=int, default=DEFAULT_METRICS_PORT)
    p.add_argument("--health-port", type=int, default=DEFAULT_HEALTH_PORT)
    args = p.parse_args(argv)

    # Mirrors the hard env guards at main.go:87-99.
    if not args.daemon_image:
        p.error("DAEMONSET_IMAGE environment variable or --daemon-image must be set")
    if not args.namespace:
        p.error("DAEMONSET_NAMESPACE environment variable or --namespace must be set")

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    mgr = Manager(
        namespace=args.namespace,
        daemon_image=args.daemon_image,
        enable_webhook=args.enable_webhook,
        export_dir=args.export_dir,
        metrics_port=args.metrics_port,
        health_port=args.health_port,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    mgr.start()
    try:
        while not stop.wait(0.5):
            pass
    finally:
        mgr.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
