"""Classifier backend interface.

The runtime contract every dataplane backend implements.  Mirrors the role
of the loaded XDP program + its maps
(/root/reference/pkg/ebpf/ingress_node_firewall_loader.go:43-50): rules are
loaded idempotently, packets are classified, statistics accumulate until
reset.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional, Protocol

import numpy as np

from ..compiler import CompiledTables
from ..constants import MAX_TARGETS
from ..packets import PacketBatch


@dataclass
class ClassifyOutput:
    """Per-batch outputs: packed u32 results, XDP verdicts, and the batch's
    statistics increment (MAX_TARGETS, 4) int64 [allow_pkts, allow_bytes,
    deny_pkts, deny_bytes]."""

    results: np.ndarray
    xdp: np.ndarray
    stats_delta: np.ndarray


class PendingClassify:
    """Handle to an in-flight classification: the device work was dispatched
    but the results are not yet materialized on the host.

    The TPU analogue of the XDP program running inline on the NIC queue: a
    caller streaming batches keeps several in flight so H2D transfer, kernel
    and D2H readback of consecutive batches overlap.  `result()` blocks
    until this batch's outputs are host-resident and applies the stats
    increment exactly once."""

    def __init__(self, materialize) -> None:
        self._materialize = materialize
        self._out: Optional[ClassifyOutput] = None

    def result(self) -> ClassifyOutput:
        if self._out is None:
            self._out = self._materialize()
            self._materialize = None  # drop device refs
        return self._out


class StatsAccumulator:
    """Host-side equivalent of the per-CPU statistics map
    (bpf/ingress_node_firewall_kernel.c:36-41): accumulates per-ruleId
    counters until the dataplane is reset; read by the metrics poller."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats = np.zeros((MAX_TARGETS, 4), np.int64)

    def add(self, delta: np.ndarray) -> None:
        with self._lock:
            self._stats += delta

    def snapshot(self) -> np.ndarray:
        with self._lock:
            return self._stats.copy()

    def reset(self) -> None:
        with self._lock:
            self._stats[:] = 0


class Classifier(Protocol):
    """One-per-node dataplane program."""

    def load_tables(self, tables: CompiledTables, dirty_hint=None) -> None:
        """Swap in a newly compiled ruleset (idempotent, atomic).

        ``dirty_hint`` (IncrementalTables.peek_dirty() or None) is an
        optional superset of the rows changed since the previous load —
        device backends use it to patch in place instead of re-uploading;
        others ignore it."""
        ...

    def classify(self, batch: PacketBatch, apply_stats: bool = True) -> ClassifyOutput:
        ...

    def classify_async(
        self, batch: PacketBatch, apply_stats: bool = True
    ) -> PendingClassify:
        """Dispatch without blocking; materialize via .result().  Sync
        backends may run eagerly and return an already-resolved handle.
        With ``apply_stats=False`` the accumulator is left untouched and
        the caller applies ``stats_delta`` itself (exactly-once semantics
        across retries)."""
        ...

    @property
    def stats(self) -> StatsAccumulator:
        ...

    @property
    def tables(self) -> Optional[CompiledTables]:
        ...

    def close(self) -> None:
        ...
