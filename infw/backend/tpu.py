"""TPU classifier backend.

The device-resident dataplane: compiled rule tensors live in HBM/VMEM, the
classify step is the fused Pallas kernel (tables up to the dense limit) or
the XLA trie path (100K+ CIDRs).  Design points:

- **double-buffered table swap** (SURVEY.md §2: the TPU analogue of the
  reference's mutex-serialized map rewrite,
  /root/reference/pkg/ebpfsyncer/ebpfsyncer.go:56-63): the next rule
  tensors are built and device_put while classification continues on the
  current set; the swap is a single reference assignment under a lock, so
  in-flight batches finish on the old tables and new batches see the new
  ones — no torn reads, no pause.
- **async pipelining**: classify_async() dispatches the H2D transfer and
  kernel and returns a PendingClassify holding *unmaterialized* device
  arrays; nothing blocks until .result() is called, so a caller keeping
  several batches in flight overlaps H2D / kernel / D2H of consecutive
  batches (the daemon's streaming ingest does exactly this).  classify()
  is the synchronous convenience: dispatch + immediate materialize.
- statistics accumulate host-side in int64 from the device's per-batch
  (1024, 6) int32 sums, applied exactly once when a batch materializes.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

import jax
import numpy as np

from .._threads import spawn
from ..compiler import CompiledTables
from ..constants import ALLOW, DENY, KIND_IPV6
from ..kernels import jaxpath, pallas_dense, pallas_walk, wire_decode
from ..packets import PacketBatch, encode_delta_wire, narrow_wire, wire8
from .base import ClassifyOutput, PendingClassify, StatsAccumulator

#: H2D wire codec choices (the daemon's --wire-codec knob): "auto" picks
#: per chunk by measured compressed size (delta when it beats the wire8
#: 8 B/packet floor), "wire8"/"delta" force a format (with the usual
#: eligibility fallbacks — an ineligible chunk degrades down the
#: delta -> wire8 -> narrow -> full chain, never refuses)
WIRE_CODECS = ("auto", "wire8", "delta")


class TpuClassifier:
    """Single-chip device classifier."""

    #: the syncer may route structurally-new keys to a dense side-table
    #: (load_tables(..., overlay=...), jaxpath.classify_with_overlay)
    supports_overlay = True

    def __init__(
        self,
        device=None,
        dense_limit: int = pallas_dense.MAX_DENSE_TARGETS,
        force_path: Optional[str] = None,  # "dense"|"trie"|"ctrie"|None (auto)
        interpret: Optional[bool] = None,
        fused_deep: Optional[bool] = None,
        wire_codec: Optional[str] = None,
        decode_pallas: Optional[bool] = None,
        check_invariants: Optional[bool] = None,
        compressed: Optional[bool] = None,
        flow_table=None,
        flow_track_model: bool = False,
        resident: Optional[bool] = None,
        telemetry=None,
        telemetry_track_model: bool = False,
        mlscore=None,
        mlscore_model=None,
        mlscore_mode: Optional[str] = None,
        mlscore_track_model: bool = False,
        payload=None,
        payload_mode: Optional[str] = None,
        payload_plen: Optional[int] = None,
        payload_track: bool = False,
    ) -> None:
        self._device = device if device is not None else jax.devices()[0]
        self._dense_limit = dense_limit
        self._force_path = force_path
        self._interpret = (
            interpret if interpret is not None else pallas_dense.default_interpret()
        )
        # Fused deep-walk dispatch (kernels.pallas_walk): the
        # depth-steered FULL-DEPTH v6 class — the throughput floor every
        # adversarial mix converges to — runs as one Pallas grid pass
        # with the deep tail VMEM-resident instead of one XLA HBM gather
        # per level.  Defaults on for real TPU; interpret mode keeps the
        # (faster-on-CPU) XLA walk unless explicitly enabled — tests opt
        # in with fused_deep=True.  Precedence: explicit constructor arg
        # (e.g. the daemon's --no-fused-deep) > INFW_FUSED_DEEP env >
        # backend default.
        if fused_deep is None:
            env = os.environ.get("INFW_FUSED_DEEP", "")
            if env:
                fused_deep = env not in ("0", "false", "no")
        self._fused_deep = (
            fused_deep if fused_deep is not None else not self._interpret
        )
        # H2D wire codec (--wire-codec / INFW_WIRE_CODEC, CLI beats env,
        # same precedence shape as fused_deep): constructor arg > env >
        # "auto" (per-chunk choice by measured compressed size).
        if wire_codec is None:
            wire_codec = os.environ.get("INFW_WIRE_CODEC") or "auto"
        if wire_codec not in WIRE_CODECS:
            raise ValueError(
                f"unknown wire codec {wire_codec!r} (expected one of "
                f"{WIRE_CODECS})"
            )
        self._wire_codec = wire_codec
        # Pallas fixed-stride decode variant (kernels.wire_decode): off by
        # default everywhere until a recorded TPU run proves it over the
        # XLA decode; tests opt in explicitly.
        if decode_pallas is None:
            env = os.environ.get("INFW_DECODE_PALLAS", "")
            decode_pallas = env not in ("", "0", "false", "no")
        self._decode_pallas = bool(decode_pallas)
        # Opt-in deep invariant contracts at every patch boundary
        # (infw.analysis.statecheck.check_device_tables): shapes, dtypes,
        # pad-fill values, mask-word reconstruction, trie child/target
        # bounds, joined-plane consistency.  The cheap shape-only half
        # (jaxpath.assert_patched_tables) is ALWAYS on; this adds the
        # data-level pass — device reads, so opt in via the constructor
        # or INFW_CHECK_INVARIANTS=1.
        if check_invariants is None:
            env = os.environ.get("INFW_CHECK_INVARIANTS", "")
            check_invariants = env not in ("", "0", "false", "no")
        self._check_invariants = bool(check_invariants)
        # Path/level-compressed poptrie layout (jaxpath.build_cpoptrie):
        # trie-sized tables serve from the merged skip-node array — the
        # 10M-tier working-set layout — instead of the per-level walk.
        # Precedence mirrors fused_deep: constructor arg (the daemon's
        # --compressed) > INFW_COMPRESSED env > off.  force_path="ctrie"
        # is the explicit per-instance form.  Ineligible tables (wide
        # int32 ruleIds) fall back to the level walk at load time, never
        # refuse.
        if compressed is None:
            env = os.environ.get("INFW_COMPRESSED", "")
            if env:
                compressed = env not in ("0", "false", "no")
        self._compressed = bool(compressed) or force_path == "ctrie"
        self._lock = threading.Lock()
        # Stateful flow tier (infw.flow, the --flow-table knob): a
        # device-resident exact-match verdict cache probed before the
        # LPM + rule scan; hits serve the cached verdict in the probe
        # dispatch and only the (compacted) misses fall through to the
        # stateless classify below.  Precedence mirrors the other knobs:
        # constructor arg (FlowConfig or an entry count) > the
        # INFW_FLOW_TABLE env (entry count) > off.
        if flow_table is None:
            env = os.environ.get("INFW_FLOW_TABLE", "")
            if env and env not in ("0", "false", "no"):
                flow_table = int(env)
        # Zero-copy resident serving loop (--resident / INFW_RESIDENT,
        # ISSUE-12): one fused device program per admission (decode +
        # flow probe + stateless classify + merge + stats + miss
        # insert) over donated/aliased buffers, replacing the
        # probe-then-classify multi-dispatch plan.  The fused step IS
        # the flow tier's serving form, so resident implies a flow
        # table (a default one when none was configured).  Precedence
        # mirrors the other knobs: constructor arg > INFW_RESIDENT env
        # > off.
        if resident is None:
            env = os.environ.get("INFW_RESIDENT", "")
            if env:
                resident = env not in ("0", "false", "no")
        self._resident = None
        if resident:
            from ..resident import ResidentPool

            if flow_table is None or flow_table is False:
                from ..flow import FlowConfig

                flow_table = FlowConfig.make()
            self._resident = ResidentPool(device=self._device)
        self._flow = None
        if flow_table is not None and flow_table is not False:
            from ..flow import FlowConfig, FlowTier

            if not isinstance(flow_table, FlowConfig):
                flow_table = FlowConfig.make(entries=int(flow_table))
            self._flow = self._make_flow_tier(
                flow_table, track_model=flow_track_model
            )
        # Device-resident telemetry plane (ISSUE-13, --telemetry /
        # INFW_TELEMETRY): count-min + top-K heavy-hitter tensors
        # updated inside the resident fused step (donated, in-program)
        # or as one follow-on launch per admission on the multi-dispatch
        # wire path — observability as a batched tensor workload, the
        # host reads one snapshot per N admissions (the decimated
        # drain), never a per-packet event.  Precedence mirrors the
        # other knobs: constructor arg (SketchSpec or count-min width)
        # > INFW_TELEMETRY env (width) > off.
        if telemetry is None:
            env = os.environ.get("INFW_TELEMETRY", "")
            if env and env not in ("0", "false", "no"):
                telemetry = (
                    True if env in ("1", "true", "yes") else int(env)
                )
        self._telemetry = None
        if telemetry is not None and telemetry is not False:
            from ..kernels.sketch import SketchSpec
            from ..obs.telemetry import TelemetryTier

            if not isinstance(telemetry, SketchSpec):
                telemetry = (
                    SketchSpec.make() if telemetry is True
                    else SketchSpec.make(width=int(telemetry))
                )
            self._telemetry = TelemetryTier(
                telemetry, device=self._device,
                track_model=telemetry_track_model,
            )
        # MXU anomaly-scoring tier (ISSUE-14, --mlscore / INFW_MLSCORE):
        # quantized per-flow inference fused into the resident step or
        # launched once per admission on the multi-dispatch path; the
        # AnomalyTier applies per-tenant shadow/enforce policy, and a
        # model hot swap bumps the flow generation like a rule patch.
        # Precedence mirrors the other knobs: constructor arg
        # (ScoreSpec or truthy) > INFW_MLSCORE env > off; the mode knob
        # reads INFW_MLSCORE_MODE when unset (default shadow).
        if mlscore is None:
            env = os.environ.get("INFW_MLSCORE", "")
            if env and env not in ("0", "false", "no"):
                mlscore = True
        if mlscore_mode is None:
            mlscore_mode = os.environ.get("INFW_MLSCORE_MODE") or "shadow"
        self._mlscore = None
        if mlscore is not None and mlscore is not False:
            from ..kernels.mxu_score import ScoreSpec
            from ..mlscore import AnomalyTier

            if not isinstance(mlscore, ScoreSpec):
                mlscore = (
                    ScoreSpec.make() if mlscore is True
                    else ScoreSpec.make(slots=int(mlscore))
                )
            self._mlscore = AnomalyTier(
                mlscore, model=mlscore_model, device=self._device,
                mode=mlscore_mode, track_model=mlscore_track_model,
            )
            # a model swap behaves like a rule patch: resident flow
            # entries caching pre-swap (possibly enforced) verdicts go
            # stale through the same generation stamps
            self._mlscore.on_swap = self._on_score_model_swap
        # Payload-matching tier (ISSUE-19, --payload-patterns /
        # INFW_PAYLOAD): batched Aho-Corasick multi-pattern matching
        # over the optional ring-sliced payload-prefix column, fused
        # into the resident admission program as the fourth
        # verdict-merge tier or launched once per admission on the
        # multi-dispatch path.  The automaton is STATELESS on device
        # (value operands only, nothing donated), so engaging it never
        # disturbs the resident donation aliasing.  Precedence mirrors
        # the other knobs: constructor arg (PayloadTier / AcModel /
        # pattern list / artifact path / pattern count) > INFW_PAYLOAD
        # env (artifact path or seeded-set count) > off; the mode knob
        # reads INFW_PAYLOAD_MODE when unset (default shadow).
        if payload is None:
            env = os.environ.get("INFW_PAYLOAD", "")
            if env and env not in ("0", "false", "no"):
                payload = env
        if payload_mode is None:
            payload_mode = os.environ.get("INFW_PAYLOAD_MODE") or "shadow"
        self._payload = None
        if payload is not None and payload is not False:
            from ..kernels.acmatch import AcModel
            from ..payload import (
                PayloadTier, load_patterns, signature_patterns,
            )

            plen = int(payload_plen or 64)
            if isinstance(payload, PayloadTier):
                tier = payload
            elif isinstance(payload, AcModel):
                tier = PayloadTier(
                    payload, mode=payload_mode, device=self._device
                )
            elif isinstance(payload, (list, tuple)):
                tier = PayloadTier(
                    payload, plen=plen, mode=payload_mode,
                    device=self._device,
                )
            elif isinstance(payload, str) and payload not in (
                "1", "true", "yes"
            ) and not payload.isdigit():
                pats, spec, _ver = load_patterns(payload)
                tier = PayloadTier(
                    pats, plen=spec.plen, mode=payload_mode, spec=spec,
                    device=self._device,
                )
            else:
                count = (
                    64 if payload is True
                    or payload in ("1", "true", "yes")
                    else int(payload)
                )
                tier = PayloadTier(
                    signature_patterns(
                        np.random.default_rng(0), count, plen=plen
                    ),
                    plen=plen, mode=payload_mode, device=self._device,
                )
            self._payload = tier
            if payload_track:
                self._payload.set_keep_masks(256)
            # a pattern-set swap behaves like a rule patch: flow
            # entries caching pre-swap (possibly enforced) verdicts go
            # stale through the same generation stamps
            self._payload.on_swap = self._on_pattern_swap
        self._stats = StatsAccumulator()
        # per-format H2D accounting {fmt: [packets, payload bytes]} — the
        # bench reads this to put bytes/packet in the replay record
        self._wire_counts = {}
        self._tables: Optional[CompiledTables] = None
        # (path, dev tables, block_b|None, wide_rids, overlay dev|None,
        #  fused walk dev|None)
        self._active = None
        self._last_load = None  # ("patch"|"full", rows) — introspection/tests
        self._ov_cache = None   # (overlay CompiledTables, device copy)
        # host meta of the resident fused-walk tables: (tidx_sorted,
        # min_depth) — the rules-only-edit staleness check (see
        # load_tables); guarded by _lock alongside _active
        self._walk_meta = None
        # depth-class steering state (trie path): (root_lut np, depth
        # LUT np, class tuple, generation); None off the trie path.
        # The generation token guards callers that grouped against an
        # older table: a stale depth silently degrades to the full
        # walk (always correct) instead of under-walking.
        self._depth_steer = None
        self._depth_gen = 0
        self._closed = False

    # -- rule loading -------------------------------------------------------

    def load_tables(self, tables: CompiledTables, dirty_hint=None,
                    overlay: Optional[CompiledTables] = None) -> None:
        """Swap in a newly compiled ruleset.

        ``dirty_hint`` (IncrementalTables.peek_dirty()) accelerates the
        incremental device patch: with it, the patch scatters exactly the
        hinted rows with NO full-table host diff — a 1-key edit costs a
        couple of small transfers regardless of table size.  The hint is
        also how a FOLDED edit transaction (infw.txn) lands: N coalesced
        edits arrive as one merged dirty-row set, one H2D staging pass
        and one fused scatter launch (jaxpath.txn_scatter, pre-warmed
        across the dirty-row ladder at full-load time), so per-edit
        device cost amortizes toward O(dirty rows).  A transaction that
        exceeds the capped-scatter budget or forces trie renumbering
        past the row buckets escalates to the full rebuild below — the
        OLD generation keeps serving until the swap (the double-buffer
        contract), so classification never stalls on an oversized
        flush.

        ``overlay`` is a SMALL dense side-table of structurally-new keys
        (CIDR adds since the main table's last full build): it uploads in
        kilobytes and the classify combines both tables by longest
        prefix (jaxpath.classify_with_overlay), so a 1-key CIDR add
        never pays the main trie's re-transform.  Callers (the syncer)
        keep identities disjoint between main and overlay."""
        if self._closed:
            raise RuntimeError("classifier is closed")
        path = self._force_path or (
            "dense" if tables.num_entries <= self._dense_limit else "trie"
        )
        if path == "trie" and self._compressed and self._force_path is None:
            # the compressed upgrade applies to the AUTO-selected trie
            # path only: an explicit per-instance force_path="trie" must
            # beat the constructor/env knob (the documented precedence),
            # or every test/statecheck config pinning the per-level walk
            # silently flips under INFW_COMPRESSED=1
            path = "ctrie"
        # Build the next buffer off-lock (host packing + device_put can be
        # slow); swap under the lock.
        wide_rids = False
        if path == "ctrie":
            # Rules-only edit: carry the host caches forward BEFORE the
            # eligibility probes below — joined_by_tidx and
            # check_wire_ruleids memoize on first touch, so a fresh
            # snapshot would repack the full rules tensor right here.
            with self._lock:
                seed_prev = self._tables
            if seed_prev is not None and dirty_hint is not None:
                jaxpath.seed_ctrie_caches_forward(
                    seed_prev, tables, dirty_hint
                )
            # Compressed-layout eligibility: the per-tidx joined rows are
            # u16-packed and the wire result carries the ruleId — wide
            # tables serve from the level walk's u32 path instead (the
            # same fallback contract as the fused deep walk).
            try:
                jaxpath.check_wire_ruleids(tables)
            except ValueError:
                path = "trie"
            else:
                if jaxpath.joined_by_tidx(tables) is None:
                    path = "trie"
        if path == "dense":
            try:
                pt = pallas_dense.build_pallas_tables(tables)
            except ValueError as e:
                if "ruleId" not in str(e):
                    raise
                # Adversarial direct content whose ruleIds exceed the
                # Pallas packing: serve it from the trie path instead of
                # refusing the table at load time.
                path = "trie"
        if path == "dense":
            dev = jax.tree.map(lambda a: jax.device_put(a, self._device), pt)
            block_b = pallas_dense.choose_block_b(pt.mdt.shape[1])
            self._last_load = ("full", tables.num_entries)
        elif path == "ctrie":
            # Compressed-poptrie resident form: dev is (CTrieTables,
            # d_max) — d_max is the static walk-unroll bound and travels
            # beside the pytree, not inside it.  Same incremental
            # contract as the trie path: rules-only edits scatter the
            # per-tidx joined rows, structural edits diff the merged
            # node/target arrays row-wise; a layout shift past the row
            # buckets (or a d_max change) re-uploads.
            dev = None
            block_b = None
            with self._lock:
                prev_tables, prev_active = self._tables, self._active
            if (
                prev_tables is not None
                and prev_active is not None
                and prev_active[0] == "ctrie"
            ):
                patched = jaxpath.patch_ctrie(
                    prev_active[1][0], prev_tables, tables, self._device,
                    hint=dirty_hint,
                )
                if patched is None and jaxpath.hint_trie_unchanged(
                    dirty_hint
                ):
                    # only a rules-only hint takes a different path on
                    # retry (structural row-diff instead of the joined
                    # fast path); a structural hint already ran exactly
                    # the diff a no-hint attempt would repeat
                    patched = jaxpath.patch_ctrie(
                        prev_active[1][0], prev_tables, tables, self._device
                    )
                if patched is not None:
                    dev = (patched[0], prev_active[1][1])
                    self._last_load = ("patch", patched[1])
            if dev is None:
                dev = jaxpath.device_ctrie(tables, self._device, pad=True)
                self._last_load = ("full", tables.num_entries)
                # same first-edit contract as the level walk: the patch
                # scatters compile at load time, not on the first edit
                jaxpath.warm_ctrie_patch_scatters(dev[0], self._device)
        else:
            try:
                jaxpath.check_wire_ruleids(tables)
            except ValueError:
                # ruleIds > 255: the 2B wire result can't carry them —
                # fall back to the u32 (non-wire) classify path.
                wide_rids = True
            dev = None
            with self._lock:
                prev_tables, prev_active = self._tables, self._active
            if (
                prev_tables is not None
                and prev_active is not None
                and prev_active[0] == "trie"
            ):
                # Incremental device patch (the Map.Update analogue):
                # ship only the rows that changed since the resident
                # generation; falls back to a full upload on structural
                # change (trie growth, compaction, path flip).
                patched = jaxpath.patch_device_tables(
                    prev_active[1], prev_tables, tables, self._device,
                    hint=dirty_hint,
                )
                if patched is None and dirty_hint is not None:
                    # hint didn't apply (bucket growth / oversized delta):
                    # try the diff-based patch before a full re-upload
                    patched = jaxpath.patch_device_tables(
                        prev_active[1], prev_tables, tables, self._device
                    )
                if patched is not None:
                    dev, n_rows = patched
                    self._last_load = ("patch", n_rows)
            if dev is None:
                # pad=True buckets device row counts so later small edits
                # keep array shapes and can take the patch path
                dev = jaxpath.device_tables(tables, self._device, pad=True)
                self._last_load = ("full", tables.num_entries)
                # Pre-compile the patch scatters against the fresh layout:
                # the first post-load rule edit then ships in milliseconds
                # instead of paying the scatter-jit compile (the pinned-map
                # re-adoption contract is rules keep enforcing AND stay
                # editable immediately, loader.go:381-407).
                jaxpath.warm_patch_scatters(dev, self._device)
            block_b = None
        steer_parts = None
        walk_dev = None
        walk_meta = None
        defer_walk = False
        if path in ("trie", "ctrie"):
            # per-root-slot deep-level requirement (conservative across
            # rules-only patches via the cache carry-forward; recomputed
            # from the snapshot's slot arrays on structural loads);
            # thresholds are TUNED to this table's depth histogram
            # (jaxpath.tune_depth_classes) rather than the static set.
            # The LUT is in LEVEL terms — conservative for the
            # compressed walk, whose skip nodes only shrink step counts.
            lut = jaxpath.build_depth_lut(tables)
            classes = jaxpath.tune_depth_classes(tables)
            steer_parts = (
                np.asarray(tables.root_lut, np.int64),
                lut,
                classes,
            )
            if self._fused_deep and not wide_rids:
                structural_patch = dirty_hint is not None and any(
                    len(h) for h in dirty_hint.get("levels", ())
                )
                if structural_patch:
                    # A structural incremental edit (CIDR delete, overlay
                    # merge) must stay at diff-scatter-patch latency: the
                    # full walk rebuild (depth LUT + extraction + byte
                    # packing + upload) runs in the BACKGROUND and
                    # installs when ready; until then the full-depth
                    # class takes the XLA walk — the fallback contract,
                    # never a wrong verdict.
                    defer_walk = True
                else:
                    walk_dev, walk_meta = self._build_walk(
                        tables, classes, dirty_hint, path == "ctrie"
                    )
                    if walk_dev is not None:
                        # pre-compile the walk's joined-plane patch
                        # scatters (one per array shape, lru-deduped) so
                        # the first fused-path rules edit is compile-free
                        if path == "ctrie":
                            pallas_walk.warm_cwalk_patch_scatters(
                                walk_dev[0], self._device
                            )
                        else:
                            pallas_walk.warm_walk_patch_scatters(
                                walk_dev, self._device
                            )
        ov_dev = None
        if overlay is not None and overlay.num_entries > 0:
            if path not in ("trie", "ctrie") or wide_rids:
                # refusing beats silently dropping live rules: the caller
                # (syncer) must merge the overlay into the main table when
                # the classifier cannot honor it on this path
                raise ValueError(
                    f"overlay not supported on path={path} "
                    f"(wide_rids={wide_rids}); merge it into the main table"
                )
            with self._lock:
                cached = self._ov_cache
            if cached is not None and cached[0] is overlay:
                ov_dev = cached[1]  # unchanged overlay: keep device copy
            else:
                # bucket-padded like the main table so overlay growth
                # re-specializes jit only per pow2 bucket
                ov_dev = jaxpath.device_tables(
                    overlay, self._device, pad=True
                )
                with self._lock:
                    self._ov_cache = (overlay, ov_dev)
        if self._check_invariants:
            # deep contract pass BEFORE install: a violating generation
            # never serves (the patch boundary is the mutation site)
            self._run_invariant_check(dev, ov_dev)
        with self._lock:
            self._tables = tables
            self._active = (path, dev, block_b, wide_rids, ov_dev, walk_dev)
            self._walk_meta = walk_meta
            # the generation token is assigned INSIDE the install lock:
            # two concurrent loads must never install different tables
            # under one token, or a stale grouping would pass the
            # classify-time staleness check and under-walk
            self._depth_gen += 1
            self._depth_steer = (
                steer_parts + (self._depth_gen,)
                if steer_parts is not None else None
            )
        if self._flow is not None:
            # THE invalidation chokepoint: every table mutation — the
            # incremental patch, a folded txn flush, a full rebuild, an
            # overlay change — flows through load_tables, so one
            # generation bump here guarantees no resident flow entry
            # can serve a verdict computed against superseded tables.
            self._flow.bump_generation(0)
        if defer_walk:
            self._spawn_walk_rebuild(tables, steer_parts[2], path == "ctrie")

    def _make_flow_tier(self, cfg, track_model: bool = False):
        """Flow-tier factory (the mesh subclass overrides to place the
        flow columns by the declared partition rules)."""
        from ..flow import FlowTier

        return FlowTier(cfg, device=self._device, track_model=track_model)

    @property
    def flow(self):
        """The FlowTier when the stateful flow tier is enabled."""
        return self._flow

    def flow_counters(self):
        """flow_* counters + occupancy gauge for /metrics (empty when
        the tier is off)."""
        return {} if self._flow is None else self._flow.counter_values()

    def flow_age_tick(self, horizon=None) -> int:
        """Run one epoch-based age sweep (the daemon's idle-loop
        maintenance); returns entries reclaimed."""
        return 0 if self._flow is None else self._flow.age(horizon)

    def warm_flow_ladder(self, ladder) -> int:
        """Pre-compile the probe/insert executables across the batch
        ladder (called by scheduler.prewarm_ladder), so the warm flow
        lifecycle performs zero jit compiles on the serving path."""
        return 0 if self._flow is None else self._flow.warm(ladder)

    def _run_invariant_check(self, dev, ov_dev) -> None:
        """Opt-in deep invariant pass (INFW_CHECK_INVARIANTS=1 /
        check_invariants=True) over the about-to-install device tables;
        raises statecheck.InvariantViolation so the bad generation never
        installs.  Only DeviceTables layouts are checkable (the dense
        path's Pallas tables and the mesh shard structures have their own
        minimal checks)."""
        from ..analysis import statecheck  # lazy: no import cycle

        viols = []
        if isinstance(dev, jaxpath.DeviceTables):
            viols += statecheck.check_device_tables(dev)
        elif (
            isinstance(dev, tuple)
            and dev
            and isinstance(dev[0], jaxpath.CTrieTables)
        ):
            viols += statecheck.check_ctrie_tables(dev[0])
        if ov_dev is not None:
            viols += [
                f"overlay: {v}"
                for v in statecheck.check_device_tables(ov_dev)
            ]
        if viols:
            raise statecheck.InvariantViolation(
                "device-table invariant contract violated at the patch "
                "boundary:\n  " + "\n  ".join(viols)
            )

    def _build_walk(self, tables: CompiledTables, classes, dirty_hint,
                    compressed: bool = False):
        """Fused-walk tables for the full-depth steering class (level
        walk or the compressed skip-node walk, per ``compressed``).

        The joined byte planes bake RULE BYTES into the resident layout,
        so a rules-only edit whose dirty targets intersect the walk's
        kept tidx set must rebuild; a non-intersecting edit (the common
        1-key case at scale — the deep tail is a small extracted subset)
        carries the resident walk forward untouched.  Any build failure
        degrades to the XLA walk, never to a refusal.

        Compressed-path resident form: (CWalkTables, d_max) — the unroll
        bound travels beside the pytree into the jit-factory cache key."""
        want_path = "ctrie" if compressed else "trie"
        min_depth = classes[-2] if len(classes) >= 2 else None
        rules_only = jaxpath.hint_trie_unchanged(dirty_hint)
        with self._lock:
            prev_active, prev_meta = self._active, self._walk_meta
        if (
            rules_only
            and prev_meta is not None
            and prev_active is not None
            and prev_active[0] == want_path
            and len(prev_active) > 5
            and prev_active[5] is not None
            and prev_meta["min_depth"] == min_depth
        ):
            dirty = np.unique(np.asarray(dirty_hint.get("dense", ()), np.int64))
            if not compressed:
                # level walk: the extracted joined planes hold ONLY the
                # kept tidx rows — a non-intersecting edit carries the
                # resident walk forward untouched.  The compressed
                # walk's per-tidx matrix is FULL (root-level best0 hits
                # index it directly, outside the kept target set), so
                # every rules edit patches it.
                tidx_sorted = prev_meta["tidx_sorted"]
                if not bool(np.isin(dirty, tidx_sorted).any()):
                    return prev_active[5], prev_meta
            # dirty targets ARE resident: rewrite exactly their joined
            # rows on device (kilobytes) — the trie is untouched, so
            # levels/l0/nodes carry over
            try:
                if compressed:
                    p = pallas_walk.patch_cwalk_joined(
                        prev_active[5][0], prev_meta, tables, dirty,
                        self._device,
                    )
                    patched = None if p is None else (p, prev_active[5][1])
                else:
                    patched = pallas_walk.patch_walk_joined(
                        prev_active[5], prev_meta, tables, dirty,
                        self._device,
                    )
            except Exception:
                patched = None
            if patched is not None:
                return patched, prev_meta
        built = self._walk_build_fn(compressed)(tables, min_depth)
        if built is None:
            return None, None
        wt, meta = built
        return ((wt, meta["d_max"]) if compressed else wt), meta

    def _walk_build_fn(self, compressed: bool):
        """(tables, min_depth) -> (walk tables, meta) | None, exception-
        safe — the shared builder of the sync and background paths."""
        def build(tables, min_depth):
            try:
                if compressed:
                    return pallas_walk.build_cwalk_tables_meta(
                        tables, min_depth=min_depth, device=self._device
                    )
                return pallas_walk.build_walk_tables_meta(
                    tables, min_depth=min_depth, device=self._device
                )
            except Exception:
                return None

        return build

    def _spawn_walk_rebuild(self, tables: CompiledTables, classes,
                            compressed: bool = False) -> None:
        """Background fused-walk rebuild after a structural edit: build
        off-thread, install under the lock ONLY if this table generation
        is still resident (a newer load supersedes the result — its own
        walk build wins).  Classify dispatches read ``_active`` under the
        lock, so they pick the walk up at the next chunk."""
        want_path = "ctrie" if compressed else "trie"
        min_depth = classes[-2] if len(classes) >= 2 else None

        def work():
            built = self._walk_build_fn(compressed)(tables, min_depth)
            if built is None:
                return
            wt, meta = built
            if compressed:
                pallas_walk.warm_cwalk_patch_scatters(wt, self._device)
                resident = (wt, meta["d_max"])
            else:
                pallas_walk.warm_walk_patch_scatters(wt, self._device)
                resident = wt
            with self._lock:
                if (
                    self._tables is tables
                    and self._active is not None
                    and self._active[0] == want_path
                ):
                    self._active = self._active[:5] + (resident,)
                    self._walk_meta = meta

        spawn(work, name="infw-walk-rebuild")

    # -- classify -----------------------------------------------------------

    def classify_async(
        self, batch: PacketBatch, apply_stats: bool = True
    ) -> PendingClassify:
        """Dispatch H2D + kernel now; return a handle whose .result()
        materializes D2H and applies the stats increment exactly once.
        JAX's async dispatch means this returns as soon as the work is
        enqueued — in-flight batches finish on whatever table buffer they
        were dispatched against (the double-buffer swap contract).

        ``apply_stats=False`` defers the accumulator increment to the
        caller (who applies ``stats_delta`` itself) — used by the daemon's
        ingest so statistics land exactly once only after the source file
        is consumed, never on a batch that will be re-classified after a
        mid-pipeline failure."""
        with self._lock:
            if self._active is None:
                raise RuntimeError("no rule tables loaded")
            path, dev, block_b, wide_rids, ov_dev, _walk = self._active
        if wide_rids:
            return self._classify_async_wide(dev, batch, apply_stats)
        # Packed wire format: 24B/packet H2D (12B for v4-compactable
        # chunks, via the narrow transform in _dispatch_wire; 28B/16B
        # when wide ifindex/pkt_len disqualify narrowing), 2B/packet D2H
        # — the host<->device link is the streaming bottleneck, not the
        # kernel.  The daemon regroups ingest by family, so the majority
        # family of real traffic ships compact.
        kind = np.asarray(batch.kind)
        v4_only = not bool((kind == KIND_IPV6).any())
        compact = v4_only and not bool(np.asarray(batch.ip_words)[:, 1:].any())
        wire_np = batch.pack_wire_v4() if compact else batch.pack_wire()
        pay_np = plen_np = None
        if self._payload is not None and batch.payload is not None:
            pay_np = np.asarray(batch.payload)
            plen_np = (
                np.asarray(batch.payload_len, np.int32)
                if batch.payload_len is not None
                else np.full(pay_np.shape[0], pay_np.shape[1], np.int32)
            )
        if self._flow is not None:
            # flow tier first: the probe serves established flows and
            # only misses fall through to the stateless dispatch
            return self.classify_prepared(
                self.prepare_packed(
                    wire_np, v4_only,
                    tcp_flags=getattr(batch, "tcp_flags", None),
                    payload=pay_np, payload_len=plen_np,
                ),
                apply_stats=apply_stats,
            )
        pending = self._dispatch_wire(
            path, dev, block_b, wire_np, v4_only, kind, apply_stats,
            ov_dev=ov_dev,
        )
        if pay_np is None:
            return pending

        def materialize() -> ClassifyOutput:
            # one follow-on payload-match launch per admission (the
            # multi-dispatch form of the fused fourth tier)
            return self._apply_payload_wire(
                pending.result(), pay_np, plen_np, wire_np, apply_stats,
            )

        return PendingClassify(materialize)

    def supports_packed(self) -> bool:
        """True when classify_async_packed can take this table generation
        (the wide-ruleId fallback needs the full u32 batch path)."""
        with self._lock:
            return self._active is not None and not self._active[3]

    def v6_depth_groups(self, ifindex: np.ndarray, ip_words: np.ndarray,
                        idx: np.ndarray):
        """Split ``idx`` (positions of a v6 sub-batch) into depth-class
        groups [((class_or_None, generation), positions)] using the
        current generation's LUT — the v6 analogue of the family split:
        a group with class d is fully classified by trie_levels[:1+d]
        (52%% of bench v6 packets land at d<=3 while the full walk is 14
        deep levels); class None = full depth.  The generation token
        must travel with the class into classify_async_packed.  Returns
        [((None, 0), idx)] when steering is unavailable (gen 0 never
        matches a live generation, so the walk stays full-depth)."""
        with self._lock:
            steer = self._depth_steer
        if steer is None or len(idx) == 0:
            return [((None, 0), idx)]
        root_lut, lut, classes, gen = steer
        return [
            ((d, gen), sub)
            for d, sub in jaxpath.depth_group_indices(
                root_lut, lut, classes, ifindex, ip_words, idx
            )
        ]

    def serving_shape_classes(self):
        """The depth-steering classes of the CURRENT table generation as
        ``(class_or_None, generation)`` pairs (the ``depth`` argument of
        prepare_packed), full-depth class last — what the scheduler's
        ladder pre-warm must cover so no steering-specialized jit
        compiles on the serving path.  Empty when steering is off
        (dense / wide-ruleId paths)."""
        with self._lock:
            steer = self._depth_steer
        if steer is None:
            return []
        classes, gen = steer[2], steer[3]
        return [(int(d), gen) for d in classes] + [(None, gen)]

    #: data-axis width of one dispatched wire batch — 1 on a single
    #: chip; MeshTpuClassifier overrides with its "data" shard count.
    #: The scheduler multiplies its per-chip admission budget by this.
    data_shards = 1

    def classify_async_packed(
        self, wire_np: np.ndarray, v4_only: bool, apply_stats: bool = True,
        depth=None, tcp_flags: Optional[np.ndarray] = None,
        payload: Optional[np.ndarray] = None,
        payload_len: Optional[np.ndarray] = None,
    ) -> PendingClassify:
        # ``depth`` is the (class, generation) pair from v6_depth_groups;
        # a generation mismatch (table swapped since grouping) falls back
        # to the full walk — never under-walk against a newer table.
        """classify_async for a pre-packed (B, 4|7) uint32 wire array
        (PacketBatch.pack_wire_subset): the daemon's hot loop skips the
        9-array subset copy entirely.  Caller contract: supports_packed()
        is True for the current table generation; kind is recovered from
        wire w0 for the host-side XDP rebuild."""
        return self.classify_prepared(
            self.prepare_packed(wire_np, v4_only, depth=depth,
                                tcp_flags=tcp_flags, payload=payload,
                                payload_len=payload_len),
            apply_stats=apply_stats,
        )

    def prepare_packed(self, wire_np: np.ndarray, v4_only: bool, depth=None,
                       tcp_flags: Optional[np.ndarray] = None,
                       payload: Optional[np.ndarray] = None,
                       payload_len: Optional[np.ndarray] = None):
        """First half of classify_async_packed: choose the wire format
        (delta / wire8 / narrow / full per the codec knob and chunk
        eligibility) and START the H2D copy of the chosen payload,
        returning an opaque plan for classify_prepared.  The daemon's
        double-buffered ingest stages the NEXT chunk's plan while the
        current chunk's classify runs, so the transfer hides under
        compute; the plan snapshots the table generation at prepare
        time — in-flight plans finish on the tables they were staged
        against (the double-buffer swap contract)."""
        if self._resident is not None and self._flow is not None:
            plan = self._plan_resident(wire_np, v4_only, depth, tcp_flags,
                                       payload=payload,
                                       payload_len=payload_len)
            if plan is not None:
                return plan
        flow_probe = None
        if self._flow is not None and wire_np.shape[1] in (4, 7):
            # Flow tier engaged: dispatch the fused probe NOW (its H2D
            # + kernel overlap other in-flight work).  The probe MUST
            # run BEFORE the stateless snapshot below: it captures the
            # flow generation vector, and a concurrent load_tables
            # between the two capture points can then only make the
            # stamped generation OLDER than the tables that compute the
            # miss verdicts — those inserts are stale on arrival
            # (safe).  The reverse order would stamp old-table verdicts
            # with the NEW generation and serve them as live (the
            # flowstale bug, raced into existence).
            with self._lock:
                probe_ok = self._active is not None and not self._active[3]
            if probe_ok:
                fused, ctx = self._flow.probe(wire_np, tflags_np=tcp_flags)
                try:
                    fused.copy_to_host_async()
                except (AttributeError, RuntimeError):
                    pass
                flow_probe = (fused, ctx)
        with self._lock:
            if self._active is None:
                raise RuntimeError("no rule tables loaded")
            path, dev, block_b, wide_rids, ov_dev, walk_dev = self._active
        if wide_rids:
            raise RuntimeError(
                "wide-ruleId tables need the full-batch path (supports_packed)"
            )
        kind = (wire_np[:, 0] & 3).astype(np.int32)
        d = None
        use_walk = None
        if depth is not None:
            dclass, gen = depth
            with self._lock:
                cur_gen = self._depth_steer[3] if self._depth_steer else -1
            if dclass is not None and gen == cur_gen:
                d = int(dclass)
            elif dclass is None and gen == cur_gen:
                # the declared FULL-DEPTH class of the current
                # generation: eligible for the fused Pallas deep walk
                # (its extraction threshold came from the same class
                # list this grouping used — the gen token proves it)
                use_walk = walk_dev
        if flow_probe is not None:
            fused, ctx = flow_probe
            plan = {
                "flow": True, "fused": fused, "ctx": ctx,
                "wire_np": wire_np, "tcp_flags": tcp_flags,
                "path": path, "dev": dev, "block_b": block_b,
                "ov_dev": ov_dev, "depth": d, "walk_dev": use_walk,
                "v4_only": v4_only, "kind": kind, "n": wire_np.shape[0],
            }
        else:
            plan = self._plan_wire(
                path, dev, block_b, wire_np, v4_only, kind,
                ov_dev=ov_dev, depth=d, walk_dev=use_walk,
            )
        if self._telemetry is not None:
            # multi-dispatch telemetry (ISSUE-13): the sketch update
            # launches at materialize time with the admission's merged
            # verdicts (one extra async device program, no host
            # round-trip); the miss sub-dispatch inside _launch_flow
            # goes through _plan_wire/_launch_wire directly and so never
            # double-counts
            plan["telem_wire"] = wire_np
            plan["telem_flags"] = tcp_flags
        if self._mlscore is not None:
            # multi-dispatch anomaly scoring (ISSUE-14): one in-stream
            # follow-on launch per admission over (wire, merged RULE
            # verdicts) — on flow plans it runs INSIDE _launch_flow,
            # between the verdict merge and the miss insert, so the
            # flow table caches the ENFORCED verdicts exactly like the
            # fused path; the miss sub-dispatch never double-scores
            plan["ml_wire"] = wire_np
            plan["ml_flags"] = tcp_flags
        if self._payload is not None and payload is not None:
            # multi-dispatch payload matching (ISSUE-19): one follow-on
            # AC-match launch per admission — on flow plans it runs
            # INSIDE _launch_flow, between the verdict merge and the
            # miss insert, so the flow table caches the ENFORCED
            # verdicts exactly like the fused path
            plan["pay_np"] = np.asarray(payload)
            plan["plen_np"] = (
                np.asarray(payload_len, np.int32)
                if payload_len is not None
                else np.full(payload.shape[0], payload.shape[1], np.int32)
            )
            plan["pay_wire"] = wire_np
        return plan

    def classify_prepared(self, plan, apply_stats: bool = True) -> PendingClassify:
        """Second half: launch the classify on a prepare_packed plan."""
        if plan.get("resident"):
            # telemetry/scoring (when on) already rode the fused program
            return self._launch_resident(plan, apply_stats)
        if plan.get("flow"):
            # flow plans run the scoring launch INSIDE _launch_flow
            # (between merge and insert — the enforced verdicts must be
            # what the flow table caches)
            pending = self._launch_flow(plan, apply_stats)
            ml_done = True
        else:
            pending = self._launch_wire(plan, apply_stats)
            ml_done = False
        ml = self._mlscore
        tel = self._telemetry
        run_ml = ml is not None and not ml_done and "ml_wire" in plan
        run_pay = (
            self._payload is not None and "pay_np" in plan
            and not plan.get("flow")
        )
        run_tel = tel is not None and "telem_wire" in plan
        if not run_ml and not run_pay and not run_tel:
            return pending

        def materialize() -> ClassifyOutput:
            out = pending.result()
            if run_ml:
                # one follow-on scoring launch per admission over the
                # merged rule verdicts; in enforce mode the rewritten
                # res16 replaces the output (verdicts, XDP and stats
                # re-derive host-side — the wire8 contract)
                out = self._apply_mlscore_wire(
                    out, plan["ml_wire"], plan["ml_flags"], apply_stats,
                )
            if run_pay:
                # one follow-on AC-match launch over the (possibly
                # score-rewritten) verdicts — same ordering as the
                # fused step: score, then payload, then telemetry
                # counts what was served
                out = self._apply_payload_wire(
                    out, plan["pay_np"], plan["plen_np"],
                    plan["pay_wire"], apply_stats,
                )
            if run_tel:
                # one follow-on telemetry program per admission: wire +
                # SERVED verdicts in, nothing back (the decimated drain
                # is the only telemetry readback)
                tel.update(plan["telem_wire"], out.results,
                           tflags_np=plan["telem_flags"])
            return out

        return PendingClassify(materialize)

    def _apply_mlscore_wire(self, out: ClassifyOutput, wire_np, tcp_flags,
                            apply_stats: bool) -> ClassifyOutput:
        """Score one flow-less wire admission (the follow-on launch) and
        apply the policy rewrite host-side when it changed anything."""
        from ..daemon import stats_from_results  # lazy: no import cycle
        from ..flow import host_unpack_wire

        res16, _anom, _scores = self._mlscore.update(
            wire_np, out.results, tflags_np=tcp_flags,
        )
        if np.array_equal(res16, (out.results & 0xFFFF).astype(np.uint16)):
            return out
        f = host_unpack_wire(wire_np)
        results, xdp = jaxpath.host_finalize_wire(res16, f["kind"])
        stats_delta = stats_from_results(
            results, f["pkt_len"].astype(np.int64)
        )
        if apply_stats:
            # the device-side stats already applied inside the launch:
            # swap them for the post-policy derivation
            self._stats.add(stats_delta - out.stats_delta)
        return ClassifyOutput(
            results=results, xdp=xdp, stats_delta=stats_delta
        )

    def _apply_payload_wire(self, out: ClassifyOutput, pay_np, plen_np,
                            wire_np, apply_stats: bool) -> ClassifyOutput:
        """Payload-match one flow-less wire admission (the follow-on
        launch) and apply the enforce-mode rewrite host-side when it
        changed anything.  Counters accrue inside the tier."""
        from ..daemon import stats_from_results  # lazy: no import cycle
        from ..flow import host_unpack_wire

        f = host_unpack_wire(wire_np)
        res16 = (out.results & 0xFFFF).astype(np.uint16)
        new16, _hit = self._payload.apply_wire(
            res16, pay_np, plen_np, f["proto"], f["dst_port"],
        )
        new16 = np.asarray(new16, np.uint16)
        if np.array_equal(new16, res16):
            return out
        results, xdp = jaxpath.host_finalize_wire(new16, f["kind"])
        stats_delta = stats_from_results(
            results, f["pkt_len"].astype(np.int64)
        )
        if apply_stats:
            # the device-side stats already applied inside the launch:
            # swap them for the post-policy derivation
            self._stats.add(stats_delta - out.stats_delta)
        return ClassifyOutput(
            results=results, xdp=xdp, stats_delta=stats_delta
        )

    # -- resident serving loop (ISSUE-12) ------------------------------------

    @staticmethod
    def _clamp_payload(pay, plen, cap):
        """Fix a payload column to the tier's prefix cap: (…, L) uint8
        zero-padded/truncated to (…, cap), lengths clipped to cap (the
        prefix-truncation contract: only occurrences ending wholly
        within min(len, cap) count)."""
        pay = np.ascontiguousarray(pay, np.uint8)
        w = pay.shape[-1]
        if plen is None:
            plen = np.full(pay.shape[:-1], w, np.int32)
        if w != cap:
            fixed = np.zeros(pay.shape[:-1] + (cap,), np.uint8)
            k = min(cap, w)
            fixed[..., :k] = pay[..., :k]
            pay = fixed
        plen = np.minimum(
            np.ascontiguousarray(plen, np.int32), np.int32(cap)
        )
        return pay, plen

    def _plan_resident(self, wire_np, v4_only, depth, tcp_flags,
                       payload=None, payload_len=None):
        """Plan + DISPATCH one admission through the resident fused
        step (jaxpath.jitted_resident_step): unlike the multi-dispatch
        plan there is no separate launch half — the whole admission is
        one device program, already in flight when this returns; the
        plan only carries what the materialize needs.  Returns None
        when this admission cannot ride the resident path (wide
        ruleIds, unsupported wire width) — the caller falls back to the
        probe-then-classify plan, degrade never refuse."""
        if wire_np.shape[1] not in (4, 7):
            return None
        tier = self._flow
        pool = self._resident
        # generation-ordering contract: capture the flow-generation
        # snapshot BEFORE the table snapshot (see resident_gens_snapshot)
        gens_snap = tier.resident_gens_snapshot()
        ctx = pool.context(self)
        if ctx is None:
            pool.note("fallbacks")
            return None
        d = None
        if depth is not None and ctx.path == "trie":
            dclass, gen = depth
            with self._lock:
                cur_gen = self._depth_steer[3] if self._depth_steer else -1
            if dclass is not None and gen == cur_gen:
                d = int(dclass)
        n = wire_np.shape[0]
        kind = (wire_np[:, 0] & 3).astype(np.int32)
        tel = self._telemetry
        ml = self._mlscore
        pt = self._payload
        use_pay = pt is not None and payload is not None
        pay_np = plen_np = None
        if use_pay:
            pay_np, plen_np = self._clamp_payload(
                payload, payload_len, pt.spec.plen
            )
        fn = jaxpath.jitted_resident_step(
            tier.config.entries, tier.config.ways, ctx.path,
            bool(v4_only) and ctx.path == "trie", d, ctx.d_max,
            ctx.ov_dev is not None,
            sketch=tel.spec if tel is not None else None,
            score=ml.spec if ml is not None else None,
            payload=pt.spec if use_pay else None,
        )
        tables_args = (
            (ctx.tdev, ctx.ov_dev) if ctx.ov_dev is not None
            else (ctx.tdev,)
        )
        wire_dev = pool.stage_wire(self, wire_np)
        payload_ops = payload_dev = None
        if use_pay:
            # automaton value operands + this admission's payload
            # column: the pattern tensors are persistent device values
            # (swapped whole on a pattern hot-swap, never recompiled),
            # the pay/plen pair rides the wire tail
            payload_ops = pt.device_ops()
            payload_dev = (
                jax.device_put(pay_np, self._device),
                jax.device_put(plen_np, self._device),
            )
        fused, epoch = tier.resident_dispatch(
            fn, tables_args, wire_dev, n, wire_np=wire_np,
            tflags_np=tcp_flags, gens_snap=gens_snap,
            alloc_note=pool.note_alloc, telemetry=tel, mlscore=ml,
            payload_ops=payload_ops, payload_dev=payload_dev,
        )
        pool.note("dispatches")
        pool.note(f"slot{(epoch - 1) & 1}_dispatches")
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        self._note_wire(f"wire{wire_np.shape[1]}", n, wire_np.nbytes)
        if use_pay:
            self._note_wire(
                "payload", n, pay_np.nbytes + plen_np.nbytes
            )
        return {"resident": True, "fused": fused, "n": n, "kind": kind,
                "epoch": epoch, "mlscore": ml is not None,
                "payload": use_pay, "pay_np": pay_np, "plen_np": plen_np,
                "pkt_len": self._wire4_pkt_len(wire_np)}

    def prepare_packed_super(self, wire_stack: np.ndarray, v4_only: bool,
                             tcp_flags_stack: Optional[np.ndarray] = None,
                             payload_stack: Optional[np.ndarray] = None,
                             payload_len_stack: Optional[np.ndarray] = None):
        """Plan + DISPATCH ``k`` stacked same-shape admissions through
        the superbatch device epoch program (ISSUE-16,
        jaxpath.jitted_resident_superbatch): flow probe/insert, sketch
        updates and anomaly-score state chain through the device-side
        scan carry, with one stacked (k, L) fused readback instead of k
        host round-trips — bit-identical to k sequential fused
        dispatches by construction.  ``wire_stack`` is (k, b, w) with
        every row one admission of ONE shape class (same b/w/v4_only/
        flags presence — jit shape keying would recompile otherwise).
        Returns None when the resident path cannot serve (the caller
        falls back to k single-admission plans, degrade never
        refuse)."""
        if (
            self._resident is None or self._flow is None
            or wire_stack.ndim != 3 or wire_stack.shape[2] not in (4, 7)
        ):
            return None
        tier = self._flow
        pool = self._resident
        # generation-ordering contract: flow-generation snapshot BEFORE
        # the table snapshot (see resident_gens_snapshot)
        gens_snap = tier.resident_gens_snapshot()
        ctx = pool.context(self)
        if ctx is None:
            pool.note("fallbacks")
            return None
        k, n, w = wire_stack.shape
        tel = self._telemetry
        ml = self._mlscore
        pt = self._payload
        use_pay = pt is not None and payload_stack is not None
        pay_np = plen_np = None
        if use_pay:
            pay_np, plen_np = self._clamp_payload(
                payload_stack, payload_len_stack, pt.spec.plen
            )
        fn = jaxpath.jitted_resident_superbatch(
            tier.config.entries, tier.config.ways, ctx.path,
            bool(v4_only) and ctx.path == "trie", None, ctx.d_max,
            ctx.ov_dev is not None,
            sketch=tel.spec if tel is not None else None,
            score=ml.spec if ml is not None else None,
            payload=pt.spec if use_pay else None,
        )
        tables_args = (
            (ctx.tdev, ctx.ov_dev) if ctx.ov_dev is not None
            else (ctx.tdev,)
        )
        wire_dev = pool.stage_wire(self, wire_stack.reshape(k * n, w))
        wire_dev = wire_dev.reshape(k, n, w)
        payload_ops = payload_dev = None
        if use_pay:
            # the stacked (k, b, L) payload columns ride the scan xs
            # next to the wire; automaton operands stay loop-invariant
            payload_ops = pt.device_ops()
            payload_dev = (
                jax.device_put(pay_np, self._device),
                jax.device_put(plen_np, self._device),
            )
        fused, epoch = tier.resident_dispatch_super(
            fn, tables_args, wire_dev, k, n, wire_np=wire_stack,
            tflags_np=tcp_flags_stack, gens_snap=gens_snap,
            alloc_note=pool.note_alloc, telemetry=tel, mlscore=ml,
            payload_ops=payload_ops, payload_dev=payload_dev,
        )
        pool.note("dispatches")
        pool.note("superbatch_dispatches")
        pool.note("superbatch_admissions", k)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        self._note_wire(f"wire{w}", k * n, wire_stack.nbytes)
        if use_pay:
            self._note_wire(
                "payload", k * n, pay_np.nbytes + plen_np.nbytes
            )
        kinds = (wire_stack[:, :, 0] & 3).astype(np.int32)
        pkt_lens = [self._wire4_pkt_len(wire_stack[j]) for j in range(k)]
        return {"resident_super": True, "fused": fused, "k": k, "n": n,
                "kinds": kinds, "epoch0": epoch - k,
                "mlscore": ml is not None, "payload": use_pay,
                "pay_np": pay_np, "plen_np": plen_np,
                "pkt_lens": pkt_lens}

    def classify_prepared_super(self, plan, apply_stats: bool = True):
        """Materialize half of a superbatch plan: ONE pending per
        admission row, in dispatch order — the daemon pairs each with
        its ring chunk and releases slots independently; out-of-order
        result() calls stay correct because every tier's mirror queue
        drains in device-epoch order (resident_note_materialized)."""
        tier = self._flow
        k, n, epoch0 = plan["k"], plan["n"], plan["epoch0"]

        def make_row(j: int) -> PendingClassify:
            epoch = epoch0 + 1 + j
            kind = plan["kinds"][j]
            pkt_len = plan["pkt_lens"][j]

            def materialize() -> ClassifyOutput:
                from ..daemon import stats_from_results  # lazy: no cycle

                row = jaxpath.resident_fused_host((plan["fused"], j))
                anom = scores = pay_hit = pay_rw = None
                if plan.get("payload"):
                    parts = jaxpath.split_resident_payload_outputs(
                        row, n, score=bool(plan.get("mlscore"))
                    )
                    pay_hit, pay_rw = parts[-2], parts[-1]
                    parts = parts[:-2]
                    if plan.get("mlscore"):
                        (res16, _hit, hits, stale, counts, anom,
                         scores) = parts
                    else:
                        res16, _hit, hits, stale, counts = parts
                elif plan.get("mlscore"):
                    res16, _hit, hits, stale, counts, anom, scores = (
                        jaxpath.split_resident_score_outputs(row, n)
                    )
                else:
                    res16, _hit, hits, stale, counts = (
                        jaxpath.split_resident_outputs(row, n)
                    )
                inserts, evictions, promotes = counts
                tier.stats.add(
                    hits=hits, misses=n - hits, stale_rejects=stale,
                    inserts=inserts, evictions=evictions,
                    promotes=promotes,
                )
                tier.resident_note_materialized(epoch)
                if self._telemetry is not None:
                    self._telemetry.resident_note_materialized(epoch)
                if anom is not None and self._mlscore is not None:
                    self._mlscore.resident_note_materialized(
                        epoch, anom_np=anom, score_np=scores,
                    )
                if pay_hit is not None and self._payload is not None:
                    self._note_payload_resident(
                        plan, pay_hit, pay_rw, row=j
                    )
                if evictions and tier.on_evict is not None:
                    try:
                        tier.on_evict(evictions, inserts, epoch)
                    except Exception:
                        pass
                results, xdp = jaxpath.host_finalize_wire(res16, kind)
                stats_delta = stats_from_results(results, pkt_len)
                if apply_stats:
                    self._stats.add(stats_delta)
                return ClassifyOutput(
                    results=results, xdp=xdp, stats_delta=stats_delta
                )

            return PendingClassify(materialize)

        return [make_row(j) for j in range(k)]

    def _launch_resident(self, plan, apply_stats: bool) -> PendingClassify:
        """Materialize half of the resident plan: ONE ~100 B fused
        readback carries the merged verdicts, the hit bitmap and the
        flow counters; statistics derive host-side from the verdicts +
        the pkt_len column that never crossed the link (the wire8
        readback contract) — verdict- and stats-bit-identical to the
        multi-dispatch flow plan (the statecheck resident config and
        the bench_resident oracle gate pin this)."""
        tier = self._flow
        n, kind, epoch = plan["n"], plan["kind"], plan["epoch"]
        pkt_len = plan["pkt_len"]

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            arr = np.asarray(plan["fused"])
            anom = scores = pay_hit = pay_rw = None
            if plan.get("payload"):
                # payload extension of the fused readback: the last
                # 2*ceil(n/32) words are the matched-lane + rewritten-
                # lane bitmaps; res16 is the POLICY verdict vector
                # (payload-rewritten in enforce mode)
                parts = jaxpath.split_resident_payload_outputs(
                    arr, n, score=bool(plan.get("mlscore"))
                )
                pay_hit, pay_rw = parts[-2], parts[-1]
                parts = parts[:-2]
                if plan.get("mlscore"):
                    res16, _hit, hits, stale, counts, anom, scores = parts
                else:
                    res16, _hit, hits, stale, counts = parts
            elif plan.get("mlscore"):
                # scoring extension of the fused readback: res16 is
                # the POLICY verdict vector (rewritten in enforce
                # mode) — stats and XDP derive from what was served
                res16, _hit, hits, stale, counts, anom, scores = (
                    jaxpath.split_resident_score_outputs(arr, n)
                )
            else:
                res16, _hit, hits, stale, counts = (
                    jaxpath.split_resident_outputs(arr, n)
                )
            inserts, evictions, promotes = counts
            tier.stats.add(
                hits=hits, misses=n - hits, stale_rejects=stale,
                inserts=inserts, evictions=evictions, promotes=promotes,
            )
            tier.resident_note_materialized(epoch)
            if self._telemetry is not None:
                self._telemetry.resident_note_materialized(epoch)
            if anom is not None and self._mlscore is not None:
                self._mlscore.resident_note_materialized(
                    epoch, anom_np=anom, score_np=scores,
                )
            if pay_hit is not None and self._payload is not None:
                self._note_payload_resident(plan, pay_hit, pay_rw)
            if evictions and tier.on_evict is not None:
                try:
                    tier.on_evict(evictions, inserts, epoch)
                except Exception:
                    pass
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            stats_delta = stats_from_results(results, pkt_len)
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _note_payload_resident(self, plan, pay_hit, pay_rw,
                               row: Optional[int] = None) -> None:
        """Fold one resident admission's payload outcome into the tier
        counters.  The ~100 B fused readback carries only the packed
        hit/rewrite bitmaps — when mask tracking is on (statecheck),
        the full (B, PW) match bitmap re-derives through one standalone
        launch over the SAME automaton operands."""
        pt = self._payload
        pay_np = plan["pay_np"]
        plen_np = plan["plen_np"]
        if row is not None:
            pay_np, plen_np = pay_np[row], plen_np[row]
        bitmap = None
        if pt.tracking:
            bitmap = pt.match(pay_np, plen_np)
        pt.note(bitmap, pay_hit, pay_rw, pay_np=pay_np, plen_np=plen_np)

    @property
    def resident(self):
        """The ResidentPool when the resident serving loop is enabled."""
        return self._resident

    def resident_counters(self):
        """resident_* pool gauges for /metrics (empty when off)."""
        return {} if self._resident is None else (
            self._resident.counter_values()
        )

    @property
    def telemetry(self):
        """The TelemetryTier when the telemetry plane is enabled."""
        return self._telemetry

    def telemetry_counters(self):
        """telemetry_* counters for /metrics (empty when off)."""
        return {} if self._telemetry is None else (
            self._telemetry.counter_values()
        )

    def warm_telemetry_ladder(self, ladder) -> int:
        """Pre-compile the classic sketch-update executables across the
        batch ladder (scheduler prewarm hook; resident fused variants
        warm through the production dispatch like every other fused
        program)."""
        if self._telemetry is None:
            return 0
        return self._telemetry.warm(ladder)

    @property
    def mlscore(self):
        """The AnomalyTier when the scoring plane is enabled."""
        return self._mlscore

    def mlscore_counters(self):
        """mlscore_* counters for /metrics (empty when off)."""
        return {} if self._mlscore is None else (
            self._mlscore.counter_values()
        )

    def warm_mlscore_ladder(self, ladder) -> int:
        """Pre-compile the classic score-update executables across the
        batch ladder (scheduler prewarm hook; the resident fused score
        variants warm through the production dispatch)."""
        if self._mlscore is None:
            return 0
        return self._mlscore.warm(ladder)

    def set_score_model(self, model, version=None) -> None:
        """Hot-swap the anomaly model (validated artifact -> new value
        operands, zero recompiles).  The tier's on_swap hook then runs
        _on_score_model_swap: a model swap behaves like a rule patch."""
        if self._mlscore is None:
            raise RuntimeError("mlscore tier is not enabled")
        self._mlscore.swap_model(model, version=version)

    def _on_score_model_swap(self) -> None:
        """Invalidate flow-cached verdicts after a model swap through
        the SAME generation stamps every table edit uses — in enforce
        mode the flow table caches enforced verdicts, and a swapped
        model must not keep serving the old model's denies."""
        if self._flow is not None:
            self._flow.bump_generation()

    @property
    def payload(self):
        """The PayloadTier when the payload-matching tier is enabled."""
        return self._payload

    def payload_counters(self):
        """payload_* counters/gauges for /metrics (empty when off)."""
        return {} if self._payload is None else (
            self._payload.counter_values()
        )

    def set_payload_patterns(self, patterns_or_model,
                             plen: Optional[int] = None) -> None:
        """Hot-swap the pattern set (must stay in the same AcSpec
        geometry buckets -> new value operands, zero recompiles).  The
        tier's on_swap hook then runs _on_pattern_swap: a pattern swap
        behaves like a rule patch."""
        if self._payload is None:
            raise RuntimeError("payload tier is not enabled")
        self._payload.swap_patterns(patterns_or_model, plen=plen)

    def set_payload_mode(self, mode: str) -> None:
        """Flip shadow/enforce — a (1,) device value swap, never a
        recompile; flow-cached verdicts invalidate like a swap (a
        pre-flip cached Deny must not outlive enforce mode)."""
        if self._payload is None:
            raise RuntimeError("payload tier is not enabled")
        self._payload.set_mode(mode)
        self._on_pattern_swap()

    def _on_pattern_swap(self) -> None:
        """Invalidate flow-cached verdicts after a pattern-set swap
        through the SAME generation stamps every table edit uses — on
        the flow paths the table caches payload-ENFORCED verdicts, and
        a swapped set must not keep serving the old set's denies."""
        if self._flow is not None:
            self._flow.bump_generation()

    def mark_resident_warm(self) -> None:
        """Freeze the pool's prewarm allocation baseline (called by
        scheduler.prewarm_ladder after the ladder warm): any pool
        allocation after this is a serving-path allocation — the
        zero-alloc steady-state gate."""
        if self._resident is not None:
            if self._flow is not None:
                # the classic probe/insert warm bumped the host epoch
                # past the donated device chain; re-sync it now so the
                # first serving dispatch rides the chain, not a re-seed
                self._flow.resident_seed_epoch()
            self._resident.mark_warm()

    def _launch_flow(self, plan, apply_stats: bool) -> PendingClassify:
        """Complete a flow-tier plan: decode the probe's fused buffer,
        serve the hit lanes from the cache, fall the compacted misses
        through the stateless dispatch (same snapshot), merge, and
        batch-insert the fresh verdicts.  Verdict bit-identity with the
        stateless path is the invariant: the key covers every
        verdict-relevant field and a hit requires a live generation, so
        hit lanes return exactly what the LPM+scan would."""
        from .. import flow as flow_mod

        tier = self._flow
        n = plan["n"]
        wire_np = plan["wire_np"]
        tcp_flags = plan["tcp_flags"]
        kind = plan["kind"]

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            res16, hitmask, hits, stale = jaxpath.split_flow_probe_outputs(
                np.asarray(plan["fused"]), n
            )
            tier.stats.add(hits=hits, misses=n - hits,
                           stale_rejects=stale)
            res16 = res16.copy()
            # hit-lane statistics derive host-side from res16 + the
            # pkt_len column of the 4/7-word wire (the wire8 readback
            # contract) — the probe ships no stats tensor
            pl = (
                ((wire_np[:, 1] >> 16) & 0xFFFF)
                | ((wire_np[:, 0] >> 27) << 16)
            ).astype(np.int64)
            stats_delta = stats_from_results(res16.astype(np.uint32), pl)
            miss = np.nonzero(~hitmask)[0]
            miss_out = None
            if len(miss):
                m = len(miss)
                bucket = flow_mod.flow_miss_bucket(m)
                miss_wire = wire_np[miss]
                if bucket > m:
                    pad = np.zeros(
                        (bucket - m, miss_wire.shape[1]), np.uint32
                    )
                    pad[:, 0] = 3  # KIND_OTHER: PASS, no stats
                    miss_wire = np.concatenate([miss_wire, pad])
                sub_kind = (miss_wire[:, 0] & 3).astype(np.int32)
                miss_out = self._launch_wire(
                    self._plan_wire(
                        plan["path"], plan["dev"], plan["block_b"],
                        miss_wire, plan["v4_only"], sub_kind,
                        ov_dev=plan["ov_dev"], depth=plan["depth"],
                        walk_dev=plan["walk_dev"],
                    ),
                    apply_stats=False,
                ).result()
                res16[miss] = (
                    miss_out.results[:m] & 0xFFFF
                ).astype(np.uint16)
                stats_delta += miss_out.stats_delta
            if self._mlscore is not None:
                # the scoring launch rides between the verdict merge
                # and the miss insert: the flow table must cache the
                # ENFORCED verdicts (bit-identical to the fused path,
                # where _score_update_core runs before the in-program
                # insert), and stats re-derive from what was served
                # when the policy rewrote anything
                new16, _anom, _scores = self._mlscore.update(
                    wire_np, res16.astype(np.uint32), tflags_np=tcp_flags,
                )
                if not np.array_equal(new16, res16):
                    res16 = new16
                    stats_delta = stats_from_results(
                        res16.astype(np.uint32), pl
                    )
            if self._payload is not None and "pay_np" in plan:
                # the payload-match launch ALSO rides between merge and
                # insert (after scoring, same ordering as the fused
                # step): the flow table must cache the payload-enforced
                # verdicts — a matched flow stays denied from the cache
                f = flow_mod.host_unpack_wire(wire_np)
                new16, _pay_hit = self._payload.apply_wire(
                    res16.astype(np.uint16), plan["pay_np"],
                    plan["plen_np"], f["proto"], f["dst_port"],
                )
                new16 = np.asarray(new16, np.uint16)
                if not np.array_equal(new16, res16):
                    res16 = new16
                    stats_delta = stats_from_results(
                        res16.astype(np.uint32), pl
                    )
            if len(miss):
                verdicts = np.zeros(miss_wire.shape[0], np.uint32)
                verdicts[:m] = res16[miss].astype(np.uint32)
                mflags = None
                if tcp_flags is not None:
                    mflags = np.zeros(miss_wire.shape[0], np.int32)
                    mflags[:m] = np.asarray(tcp_flags, np.int32)[miss]
                tier.insert(plan["ctx"], miss_wire, verdicts,
                            tflags_np=mflags)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _note_wire(self, fmt: str, n: int, nbytes: int) -> None:
        with self._lock:
            c = self._wire_counts.setdefault(fmt, [0, 0])
            c[0] += n
            c[1] += nbytes

    def wire_stats(self):
        """{format: (packets, H2D payload bytes)} since construction."""
        with self._lock:
            return {k: tuple(v) for k, v in self._wire_counts.items()}

    @staticmethod
    def _wire4_pkt_len(wire4_np: np.ndarray) -> np.ndarray:
        """Full pkt_len reconstruction from the 4-word wire (pack_wire
        w1>>16 plus the w0>>27 high-bit stash) — stays host-side for the
        sub-12B formats, whose statistics derive from the verdicts."""
        return (
            ((wire4_np[:, 1] >> 16) & 0xFFFF)
            | ((wire4_np[:, 0] >> 27) << 16)
        ).astype(np.int64)

    def _dispatch_wire(
        self, path, dev, block_b, wire_np, v4_only, kind, apply_stats,
        ov_dev=None, depth=None, walk_dev=None,
    ) -> PendingClassify:
        return self._launch_wire(
            self._plan_wire(
                path, dev, block_b, wire_np, v4_only, kind,
                ov_dev=ov_dev, depth=depth, walk_dev=walk_dev,
            ),
            apply_stats,
        )

    def _plan_wire(
        self, path, dev, block_b, wire_np, v4_only, kind,
        ov_dev=None, depth=None, walk_dev=None,
    ):
        """Format choice + H2D staging.  Returns the plan consumed by
        _launch_wire; every jax.device_put here is async, so a staged
        plan's transfer overlaps whatever the device is running."""
        n = wire_np.shape[0]
        plan = {
            "path": path, "dev": dev, "block_b": block_b, "ov_dev": ov_dev,
            "depth": depth, "walk_dev": walk_dev, "v4_only": v4_only,
            "kind": kind, "n": n,
        }
        put = lambda a: jax.device_put(a, self._device)
        if path in ("trie", "ctrie") and wire_np.shape[1] == 4 and n:
            codec = self._wire_codec
            if codec in ("auto", "delta"):
                # delta+varint compressed wire (packets.encode_delta_wire):
                # sorted-chunk IP deltas + dictionary meta, ~4-6 B/packet;
                # "auto" takes it only when it beats the wire8 floor.
                enc = encode_delta_wire(
                    wire_np,
                    max_bytes_per_pkt=8.0 if codec == "auto" else None,
                )
                if enc is not None:
                    # what actually crosses the link: the BUCKET-padded
                    # payload plus the dict/ifmap headers — the auto gate
                    # and the byte counters reason about shipped bytes,
                    # not the unpadded stream (a payload just over its
                    # bucket step would otherwise "win" on paper while
                    # shipping wire8-sized buffers)
                    shipped = (
                        wire_decode.payload_bucket(len(enc.payload))
                        + 256 * 4 + enc.ifmap.nbytes
                    )
                    if codec == "delta" or shipped < 8 * n:
                        plan.update(
                            fmt="delta", enc=enc,
                            pkt_len=self._wire4_pkt_len(wire_np),
                            payload=put(wire_decode.pad_payload(enc.payload)),
                            dictv=put(wire_decode.pad_dict(enc.dict_vals)),
                            ifmap=put(enc.ifmap),
                        )
                        self._note_wire("delta", n, shipped)
                        return plan
            # 8B/packet transfer (packets.wire8): classification never
            # reads pkt_len, so the length stays host-side and byte
            # statistics are computed from the returned verdicts; the
            # ifindex travels as a 4-bit dictionary index.
            w8 = wire8(wire_np)
            if w8 is not None:
                wire8_np, ifmap = w8
                plan.update(
                    fmt="wire8", pkt_len=self._wire4_pkt_len(wire_np),
                    wire=put(wire8_np), ifmap=put(ifmap),
                )
                self._note_wire("wire8", n, wire8_np.nbytes + ifmap.nbytes)
                return plan
        if wire_np.shape[1] in (4, 7):
            # Narrow transfer (packets.narrow_wire): one word less per
            # packet on the H2D link when the chunk qualifies — the link
            # is the streaming bottleneck, not the kernel.
            nw = narrow_wire(wire_np)
            if nw is not None:
                wire_np = nw
        plan.update(fmt="wire", wire=put(wire_np))
        self._note_wire(f"wire{wire_np.shape[1]}", n, wire_np.nbytes)
        return plan

    def _launch_wire(self, plan, apply_stats: bool) -> PendingClassify:
        if plan["fmt"] == "delta":
            return self._launch_delta(plan, apply_stats)
        if plan["fmt"] == "wire8":
            return self._launch_wire8(plan, apply_stats)
        path, dev, block_b = plan["path"], plan["dev"], plan["block_b"]
        ov_dev, depth, walk_dev = plan["ov_dev"], plan["depth"], plan["walk_dev"]
        v4_only, kind, n = plan["v4_only"], plan["kind"], plan["n"]
        wire = plan["wire"]
        # Fused single-buffer output: results + stats come back in ONE
        # D2H materialization (jaxpath.fuse_wire_outputs) — each readback
        # RPC pays the link's sync floor, so two arrays per chunk would
        # double the per-chunk latency cost.
        if path == "dense":
            fused = pallas_dense.jitted_classify_pallas_wire_fused(
                self._interpret, block_b
            )(dev, wire)
        elif path == "ctrie":
            # Compressed skip-node walk: fused Pallas for the declared
            # full-depth class (the extraction threshold travels with
            # the gen token, same contract as the level walk); XLA
            # compressed walk otherwise.  Depth-class truncation does
            # not apply — d_max is already the path-compressed bound.
            cdev, d_max = dev
            if walk_dev is not None and ov_dev is None:
                wt, dw = walk_dev
                fused = pallas_walk.jitted_classify_cwalk_wire_fused(
                    dw, self._interpret
                )(wt, wire)
            elif ov_dev is not None:
                fused = jaxpath.jitted_classify_ctrie_wire_overlay_fused(
                    d_max
                )(cdev, ov_dev, wire)
            else:
                fused = jaxpath.jitted_classify_ctrie_wire_fused(d_max)(
                    cdev, wire
                )
        elif walk_dev is not None and ov_dev is None:
            # Fused deep walk: the whole v6 descent (level walk +
            # popcount-rank child step + joined rules tail) in one
            # Pallas grid pass with the extracted deep tail
            # VMEM-resident — no per-level HBM gather excursions.  The
            # overlay combine needs the XLA walk's score plumbing, so
            # overlay generations keep the XLA path for this class.
            fused = pallas_walk.jitted_classify_walk_wire_fused(
                self._interpret
            )(walk_dev, wire)
        elif ov_dev is not None:
            fused = jaxpath.jitted_classify_wire_overlay_fused(
                True, v4_only, depth
            )(dev, ov_dev, wire)
        else:
            # Depth specialization: a v4-only batch walks only the ≤/32
            # trie levels; a v6 depth-class chunk walks trie_levels[:1+d]
            # (v6_depth_groups) — the daemon steers homogeneous chunks.
            fused = jaxpath.jitted_classify_wire_fused(
                True, v4_only, depth
            )(dev, wire)
        # Start the D2H copy now so it overlaps the dispatch of subsequent
        # batches; .result() then finds the bytes already (or sooner) on
        # host.  Not all platforms expose it — best effort.
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            res16, stats = jaxpath.split_wire_outputs(np.asarray(fused), n)
            stats_delta = jaxpath.merge_stats_host(stats)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            return ClassifyOutput(results=results, xdp=xdp, stats_delta=stats_delta)

        return PendingClassify(materialize)

    def _launch_wire8(self, plan, apply_stats: bool) -> PendingClassify:
        """The 8B-wire launch: res16-only D2H; statistics (incl. exact
        byte counts) derive host-side from the verdicts + the pkt_len
        column that never crossed the link."""
        dev, ov_dev = plan["dev"], plan["ov_dev"]
        kind, n, pkt_len = plan["kind"], plan["n"], plan["pkt_len"]
        wire, ifm = plan["wire"], plan["ifmap"]
        if plan["path"] == "ctrie":
            cdev, d_max = dev
            fn = jaxpath.jitted_classify_ctrie_wire8_fused(
                d_max, ov_dev is not None
            )
            fused = (
                fn(cdev, ov_dev, wire, ifm)
                if ov_dev is not None else fn(cdev, wire, ifm)
            )
        elif ov_dev is not None:
            fused = jaxpath.jitted_classify_wire8_fused(True)(
                dev, ov_dev, wire, ifm
            )
        else:
            fused = jaxpath.jitted_classify_wire8_fused(False)(dev, wire, ifm)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            res16 = jaxpath.unpack_res16_host(np.asarray(fused), n)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            stats_delta = stats_from_results(results, pkt_len)
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _launch_delta(self, plan, apply_stats: bool) -> PendingClassify:
        """Compressed-wire launch (packets.encode_delta_wire +
        kernels.wire_decode): the device decodes the ~4-6 B/packet stream
        on-chip and classifies in SORTED order; the host inverse-permutes
        the returned verdicts back to chunk order (the permutation, like
        pkt_len, never crosses the link).  res16-only D2H, host-derived
        statistics — the wire8 readback contract."""
        dev, ov_dev = plan["dev"], plan["ov_dev"]
        kind, n, pkt_len = plan["kind"], plan["n"], plan["pkt_len"]
        enc = plan["enc"]
        use_pallas = self._decode_pallas and enc.fixed_w > 0
        if plan["path"] == "ctrie":
            cdev, d_max = dev
            fn = wire_decode.jitted_classify_delta_ctrie_fused(
                ov_dev is not None, d_max, n, enc.dict_mode, enc.fixed_w,
                use_pallas=use_pallas, interpret=self._interpret,
            )
            dev = cdev
        else:
            fn = wire_decode.jitted_classify_delta_fused(
                ov_dev is not None, n, enc.dict_mode, enc.fixed_w,
                use_pallas=use_pallas, interpret=self._interpret,
            )
        if ov_dev is not None:
            fused = fn(dev, ov_dev, plan["payload"], plan["dictv"],
                       plan["ifmap"])
        else:
            fused = fn(dev, plan["payload"], plan["dictv"], plan["ifmap"])
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            res16_sorted = jaxpath.unpack_res16_host(np.asarray(fused), n)
            res16 = np.empty(n, np.uint16)
            res16[enc.perm] = res16_sorted
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            stats_delta = stats_from_results(results, pkt_len)
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _classify_async_wide(
        self, dev, batch: PacketBatch, apply_stats: bool
    ) -> PendingClassify:
        """u32 results path for tables whose ruleIds exceed the wire
        format's 8 bits: full DeviceBatch H2D and 4B/packet results D2H —
        slower on the link, lossless on ruleIds."""
        db = jaxpath.device_batch(batch, self._device)
        res, xdp, stats = jaxpath.jitted_classify(True)(dev, db)
        for arr in (res, xdp, stats):
            try:
                arr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break

        def materialize() -> ClassifyOutput:
            stats_delta = jaxpath.merge_stats_host(np.asarray(stats))
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=np.asarray(res), xdp=np.asarray(xdp),
                stats_delta=stats_delta,
            )

        return PendingClassify(materialize)

    def classify(self, batch: PacketBatch, apply_stats: bool = True) -> ClassifyOutput:
        return self.classify_async(batch, apply_stats=apply_stats).result()

    # -- accessors / lifecycle ---------------------------------------------

    @property
    def stats(self) -> StatsAccumulator:
        return self._stats

    @property
    def tables(self) -> Optional[CompiledTables]:
        return self._tables

    @property
    def active_path(self) -> Optional[str]:
        return self._active[0] if self._active else None

    def close(self) -> None:
        """Release device references (the analogue of detaching the XDP
        program and closing the BPF objects, loader.go:306-333)."""
        with self._lock:
            self._active = None
            self._tables = None
            self._closed = True


class ArenaClassifier:
    """Multi-tenant paged-arena classifier (ISSUE-10): thousands of
    tenant rulesets resident in ONE pool per layout family, classify
    batches carrying MIXED-tenant traffic steered per packet by the
    device tenant -> page table, and tenant activation/hot-swap as a
    page-table row flip instead of a re-upload.

    Serves the packed-wire contract with a tenant column:
    ``classify_async_packed_tenant(wire_np, tenant_np)`` (and the
    ``classify_tenants`` batch convenience).  The wire rides the
    wire/narrow formats; the sub-8B codecs are per-chunk sequential
    transforms that would interleave tenants' sort orders, so the arena
    path keeps the 16-28B formats (same degrade-never-refuse posture as
    the mesh's delta fallback).

    Per-tenant edits reuse the incremental patch machinery PER SLAB
    (rules-only hints scatter exactly the dirty rows at the slab base);
    every executable is jit-cache-keyed on the POOL geometry only, so
    tenant create/swap/patch/destroy never recompiles on a warm arena
    (test-pinned)."""

    #: the syncer/registry may route structurally-new tenant keys into a
    #: per-tenant dense overlay side-pool (overlay_spec)
    supports_overlay = True
    data_shards = 1

    def __init__(
        self,
        spec: "jaxpath.ArenaSpec",
        device=None,
        overlay_spec: "Optional[jaxpath.ArenaSpec]" = None,
        interpret: Optional[bool] = None,
        fused_deep: Optional[bool] = None,
        check_invariants: Optional[bool] = None,
        flow_table=None,
        flow_track_model: bool = False,
    ) -> None:
        self._device = device if device is not None else jax.devices()[0]
        self._interpret = (
            interpret if interpret is not None
            else pallas_dense.default_interpret()
        )
        if fused_deep is None:
            env = os.environ.get("INFW_FUSED_DEEP", "")
            if env:
                fused_deep = env not in ("0", "false", "no")
        self._fused_deep = (
            bool(fused_deep) if fused_deep is not None
            else not self._interpret
        ) and spec.family == "ctrie"
        if check_invariants is None:
            env = os.environ.get("INFW_CHECK_INVARIANTS", "")
            check_invariants = env not in ("", "0", "false", "no")
        self._check_invariants = bool(check_invariants)
        self._alloc = jaxpath.ArenaAllocator(spec, self._device)
        if overlay_spec is not None and overlay_spec.family != "dense":
            raise ValueError("the overlay side-pool must be dense-family")
        self._ov_alloc = (
            jaxpath.ArenaAllocator(overlay_spec, self._device)
            if overlay_spec is not None else None
        )
        self._lock = threading.Lock()
        self._stats = StatsAccumulator()
        self._wire_counts = {}
        # per-tenant verdict accounting {tid: [packets, allow, deny]}
        self._tenant_counts = {}
        # paged Pallas walk planes, rebuilt when the node pool moves
        self._planes = None
        self._planes_gen = -1
        # Per-tenant flow slabs (infw.flow): one flow slab per ARENA
        # page, steered by the same tenant -> page mapping that steers
        # classification; the flow key embeds the tenant id, so slab
        # reuse across tenants can never serve a foreign verdict.
        if flow_table is None:
            env = os.environ.get("INFW_FLOW_TABLE", "")
            if env and env not in ("0", "false", "no"):
                flow_table = int(env)
        self._flow = None
        if flow_table is not None and flow_table is not False:
            from ..flow import FlowConfig, FlowTier

            if isinstance(flow_table, FlowConfig):
                fcfg = flow_table._replace(
                    pages=spec.pages, max_tenants=spec.max_tenants
                )
            else:
                fcfg = FlowConfig.make(
                    entries=int(flow_table), pages=spec.pages,
                    max_tenants=spec.max_tenants,
                )
            self._flow = FlowTier(fcfg, device=self._device,
                                  track_model=flow_track_model)
        self._closed = False
        if self._fused_deep:
            self._refresh_planes()

    # -- tenant lifecycle (allocator proxies + invariant hooks) -------------

    @property
    def allocator(self) -> "jaxpath.ArenaAllocator":
        return self._alloc

    @property
    def overlay_allocator(self):
        return self._ov_alloc

    @property
    def spec(self) -> "jaxpath.ArenaSpec":
        return self._alloc.spec

    def load_tenant(self, tenant: int, tables: CompiledTables,
                    hint=None) -> str:
        if self._closed:
            raise RuntimeError("classifier is closed")
        had_page = self._alloc.page_of(tenant) is not None
        rules_only = had_page and jaxpath.hint_trie_unchanged(hint)
        if not self._fused_deep or rules_only:
            # rules-only edits of a PRIVATE slab never touch the node
            # pool, so the planes need no refresh ordering; a rules-only
            # edit of a SHARED slab CoW-clones (a structural write of an
            # unreachable fresh page), which the allocator covers by
            # running pre_flip after the clone write and strictly before
            # the page-table flip — the same new-planes/old-table
            # pairing the swap path guarantees
            path = self._alloc.load_tenant(
                tenant, tables, hint=hint,
                pre_flip=self._refresh_planes if self._fused_deep else None,
            )
            self._after_mutation()
            self._flow_note(tenant)
            return path
        # fused planes live: a structural install must not let a
        # classify pair the NEW page table with stale planes — route
        # through stage (free page bake, or a content-hash HIT on an
        # already-resident page) -> plane refresh -> flip, the same
        # ordering the swap path guarantees
        try:
            page = self._alloc.stage(tables)
        except jaxpath.ArenaCapacityError:
            # no free page for staging: in-place rewrite with an
            # immediate refresh — a narrow stale window only on a full
            # pool (keep >= 1 free page when serving the fused walk)
            path = self._alloc.load_tenant(
                tenant, tables, hint=hint,
                pre_flip=self._refresh_planes,
            )
            self._after_mutation()
            self._flow_note(tenant)
            return path
        self._refresh_planes()
        self._alloc.activate(tenant, page, tables)
        self._after_mutation()
        self._flow_note(tenant)
        return "rewrite" if had_page else "assign"

    def load_tenant_overlay(self, tenant: int,
                            overlay: Optional[CompiledTables]) -> None:
        """Install/clear one tenant's dense overlay side-slab."""
        if self._ov_alloc is None:
            raise RuntimeError("arena built without an overlay side-pool")
        if overlay is None or overlay.num_entries == 0:
            if self._ov_alloc.page_of(tenant) is not None:
                self._ov_alloc.destroy_tenant(tenant)
        else:
            self._ov_alloc.load_tenant(tenant, overlay)

    def stage_tenant(self, tables: CompiledTables) -> int:
        page = self._alloc.stage(tables)
        # planes refresh at STAGE time, before any flip can land: a
        # classify that pairs new planes with the OLD page table is
        # safe (untouched pages' plane rows are unchanged; staged pages
        # are unreachable until their flip), while old-planes/new-table
        # would walk stale nodes — so the refresh must strictly precede
        # the activation
        if self._fused_deep:
            self._refresh_planes()
        return page

    def activate_tenant(self, tenant: int, page: int,
                        tables: Optional[CompiledTables] = None) -> None:
        if self._fused_deep:
            self._refresh_planes()  # cover externally-staged writes
        self._alloc.activate(tenant, page, tables)
        self._after_mutation()
        self._flow_note(tenant)

    def swap_tenant(self, tenant: int, tables: CompiledTables) -> None:
        page = self.stage_tenant(tables)
        self._alloc.activate(tenant, page, tables)
        self._after_mutation()
        self._flow_note(tenant)

    def destroy_tenant(self, tenant: int) -> None:
        self._alloc.destroy_tenant(tenant)
        if self._ov_alloc is not None and (
            self._ov_alloc.page_of(tenant) is not None
        ):
            self._ov_alloc.destroy_tenant(tenant)
        # destroy mutates the page table / free list too — the
        # invariant hook must cover it like every other boundary
        self._after_mutation()
        self._flow_note(tenant)

    def compact(self) -> int:
        if self._fused_deep:
            # slab moves flip pages one by one inside the allocator —
            # no safe plane pairing exists mid-compaction, so drop to
            # the (always-correct) XLA arena walk for its duration and
            # rebuild the planes after (compaction is rare)
            with self._lock:
                self._planes = None
        moved = self._alloc.compact()
        self._after_mutation()
        if moved and self._flow is not None:
            # slab moves re-steer every moved tenant's flow slab; the
            # pool-wide bump is the conservative invalidation
            for t in self._alloc.tenants():
                self._flow.set_page(t, self._alloc.page_of(t))
            self._flow.bump_all_generations()
        return moved

    def dedup_sweep(self, limit: Optional[int] = None) -> dict:
        """Background content re-merge (the lazy half of the CoW
        arena): re-hash stale pages and flip tenants whose slab content
        re-converged onto one shared page.  Flips only — no slab
        writes, so the fused planes need no refresh; moved tenants'
        flow slabs re-steer and invalidate like any other page move."""
        rep = self._alloc.dedup_sweep(limit)
        if rep["moved"]:
            for t in rep["moved"]:
                self._flow_note(t)
            self._after_mutation()
        return rep

    def _after_mutation(self) -> None:
        if self._fused_deep:
            self._refresh_planes()
        if self._check_invariants:
            from ..analysis import statecheck  # lazy: no import cycle

            viols = statecheck.check_arena(self._alloc)
            if viols:
                raise statecheck.InvariantViolation(
                    "arena invariant contract violated at the slab "
                    "boundary:\n  " + "\n  ".join(viols)
                )

    def _refresh_planes(self) -> None:
        """Bring the paged-walk byte planes up to the node pool: a full
        build only on first touch; afterwards ONLY the written slabs'
        plane rows re-derive and scatter (SN is 128-row aligned, so a
        slab maps 1:1 onto its plane rows) — O(slab) per mutation, not
        O(pool), keeping the hot-swap path flip-sized.  Subtree-plane
        writes (spliced arenas) patch the same way at their pool-row
        bases: O(touched subtrees), never a pool rebuild."""
        gen, pages, rows = self._alloc.consume_dirty_node_pages()
        pblocks = self._alloc.consume_dirty_plane_rows()[1] if hasattr(
            self._alloc, "consume_dirty_plane_rows") else []
        with self._lock:
            if gen == self._planes_gen and self._planes is not None:
                return
            planes = self._planes
            if planes is None or (not pages and not pblocks):
                nodes = self._alloc.host_nodes()
                planes = (
                    None if nodes is None
                    else pallas_walk.build_arena_cwalk_planes(
                        nodes, device=self._device
                    )
                )
            else:
                sn = self._alloc.spec.node_rows
                patches = [
                    (p * sn, rows[p][:sn]) for p in pages
                ] + [(b, blk) for b, blk in pblocks]
                for base, blk in patches:
                    nr = blk.shape[0]
                    slab_planes = pallas_walk._split_cnode_rows(blk)
                    patched = jaxpath._capped_scatter(
                        planes,
                        base + np.arange(nr, dtype=np.int64),
                        slab_planes[:nr],
                        self._device,
                    )
                    if patched is None:  # oversized delta: full rebuild
                        nodes = self._alloc.host_nodes()
                        patched = pallas_walk.build_arena_cwalk_planes(
                            nodes, device=self._device
                        )
                        planes = patched
                        break
                    planes = patched
            self._planes = planes
            self._planes_gen = gen

    def _flow_note(self, tenant: int) -> None:
        """Per-tenant flow bookkeeping after a lifecycle mutation:
        re-steer the tenant's flow slab to its (possibly new) arena
        page and invalidate its resident flow verdicts."""
        if self._flow is None:
            return
        page = self._alloc.page_of(tenant)
        self._flow.set_page(tenant, -1 if page is None else page)
        self._flow.bump_generation(tenant)

    @property
    def flow(self):
        return self._flow

    def flow_counters(self):
        return {} if self._flow is None else self._flow.counter_values()

    def flow_age_tick(self, horizon=None) -> int:
        return 0 if self._flow is None else self._flow.age(horizon)

    def warm_flow_ladder(self, ladder) -> int:
        return 0 if self._flow is None else self._flow.warm(ladder)

    # -- classify ------------------------------------------------------------

    def tenant_ids(self):
        return self._alloc.tenants()

    def classify_async_packed_tenant(
        self, wire_np: np.ndarray, tenant_np: np.ndarray,
        apply_stats: bool = True, tcp_flags: Optional[np.ndarray] = None,
    ) -> PendingClassify:
        """The mixed-tenant packed-wire dispatch: one batch, each
        packet steered to its tenant's slab in-kernel.  ``tenant_np``
        is (B,) int — ids outside the registry classify to UNDEF.
        With the flow tier enabled, established flows serve from their
        tenant's flow slab and only misses walk the arena."""
        if self._flow is not None and wire_np.shape[1] in (4, 7):
            return self._classify_flow_tenant(
                wire_np, tenant_np, apply_stats, tcp_flags
            )
        return self._classify_stateless_tenant(
            wire_np, tenant_np, apply_stats
        )

    def _classify_flow_tenant(
        self, wire_np, tenant_np, apply_stats, tcp_flags
    ) -> PendingClassify:
        from .. import flow as flow_mod

        if self._closed:
            raise RuntimeError("classifier is closed")
        tier = self._flow
        n = wire_np.shape[0]
        kind = (wire_np[:, 0] & 3).astype(np.int32)
        tenant_np = np.ascontiguousarray(tenant_np, np.int32)
        fused, ctx = tier.probe(wire_np, tenant_np=tenant_np,
                                tflags_np=tcp_flags)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            res16, hitmask, hits, stale = jaxpath.split_flow_probe_outputs(
                np.asarray(fused), n
            )
            tier.stats.add(hits=hits, misses=n - hits,
                           stale_rejects=stale)
            res16 = res16.copy()
            pl = (
                ((wire_np[:, 1] >> 16) & 0xFFFF)
                | ((wire_np[:, 0] >> 27) << 16)
            ).astype(np.int64)
            stats_delta = stats_from_results(res16.astype(np.uint32), pl)
            miss = np.nonzero(~hitmask)[0]
            if len(miss):
                m = len(miss)
                bucket = flow_mod.flow_miss_bucket(m)
                miss_wire = wire_np[miss]
                miss_tenant = tenant_np[miss]
                if bucket > m:
                    pad = np.zeros(
                        (bucket - m, miss_wire.shape[1]), np.uint32
                    )
                    pad[:, 0] = 3
                    miss_wire = np.concatenate([miss_wire, pad])
                    miss_tenant = np.concatenate(
                        [miss_tenant, np.full(bucket - m, -1, np.int32)]
                    )
                out = self._classify_stateless_tenant(
                    miss_wire, miss_tenant, apply_stats=False,
                    note_tenants=False,
                ).result()
                res16[miss] = (out.results[:m] & 0xFFFF).astype(np.uint16)
                stats_delta += out.stats_delta
                verdicts = np.zeros(miss_wire.shape[0], np.uint32)
                verdicts[:m] = out.results[:m] & 0xFFFF
                mflags = None
                if tcp_flags is not None:
                    mflags = np.zeros(miss_wire.shape[0], np.int32)
                    mflags[:m] = np.asarray(tcp_flags, np.int32)[miss]
                tier.insert(ctx, miss_wire, verdicts,
                            tenant_np=miss_tenant, tflags_np=mflags)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            self._note_tenants(tenant_np, results)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _classify_stateless_tenant(
        self, wire_np: np.ndarray, tenant_np: np.ndarray,
        apply_stats: bool = True, note_tenants: bool = True,
    ) -> PendingClassify:
        """The stateless arena dispatch (the pre-flow classify path and
        the flow tier's miss fall-through)."""
        if self._closed:
            raise RuntimeError("classifier is closed")
        spec = self._alloc.spec
        n = wire_np.shape[0]
        kind = (wire_np[:, 0] & 3).astype(np.int32)
        if wire_np.shape[1] in (4, 7):
            nw = narrow_wire(wire_np)
            if nw is not None:
                wire_np = nw
        put = lambda a: jax.device_put(a, self._device)
        wire = put(wire_np)
        tenant = put(np.ascontiguousarray(tenant_np, np.int32))
        self._note_wire(f"wire{wire_np.shape[1]}", n, wire_np.nbytes)
        # read order matters for the fused path: the ARENA snapshot
        # first, planes after — planes refresh strictly BEFORE flips
        # (stage_tenant), so planes are always at least as new as the
        # page table we pair them with (new-planes/old-table is safe;
        # the reverse would walk stale nodes)
        arena = self._alloc.arena
        ov = None if self._ov_alloc is None else self._ov_alloc.arena
        ov_busy = ov is not None and self._ov_alloc.tenants()
        d_max = spec.d_max if spec.family == "ctrie" else 0
        # spliced arenas key the (cached) factories on the spec so the
        # entry stage resolves splice rows; unspliced callers keep the
        # legacy cache identity by not passing the kwarg at all
        sp = {"spec": spec} if getattr(spec, "spliced", False) else {}
        if (
            self._fused_deep and self._planes is not None and not ov_busy
        ):
            fused = pallas_walk.jitted_classify_arena_cwalk_wire_fused(
                spec.pages, d_max, self._interpret, **sp
            )(arena, self._planes, wire, tenant)
        elif ov_busy:
            fused = jaxpath.jitted_classify_arena_wire_fused(
                spec.family, spec.pages, d_max, self._ov_alloc.spec.pages,
                **sp
            )(arena, ov, wire, tenant)
        else:
            fused = jaxpath.jitted_classify_arena_wire_fused(
                spec.family, spec.pages, d_max, **sp
            )(arena, wire, tenant)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            res16, stats = jaxpath.split_wire_outputs(np.asarray(fused), n)
            stats_delta = jaxpath.merge_stats_host(stats)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            if note_tenants:
                self._note_tenants(tenant_np, results)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def classify_tenants(
        self, batch: PacketBatch, tenant_np: np.ndarray,
        apply_stats: bool = True,
    ) -> ClassifyOutput:
        """Batch-object convenience over the packed-tenant dispatch."""
        return self.classify_async_packed_tenant(
            batch.pack_wire(), tenant_np, apply_stats=apply_stats
        ).result()

    def _note_wire(self, fmt: str, n: int, nbytes: int) -> None:
        with self._lock:
            c = self._wire_counts.setdefault(fmt, [0, 0])
            c[0] += n
            c[1] += nbytes

    def wire_stats(self):
        with self._lock:
            return {k: tuple(v) for k, v in self._wire_counts.items()}

    def _note_tenants(self, tenant_np, results) -> None:
        """Per-tenant packets/allow/deny accounting (the tenant_*
        observability satellite): three vectorized bincount passes over
        the batch — this runs on every classify materialize, so a
        per-tenant Python loop would serialize O(tenants x B) work
        under the lock at exactly the mixed-batch scale the arena
        serves."""
        t = np.asarray(tenant_np, np.int64)
        ok = (t >= 0) & (t < self._alloc.spec.max_tenants)
        t = t[ok]
        if len(t) == 0:
            return
        act = (np.asarray(results)[ok]) & 0xFF
        n = int(t.max()) + 1
        pkts = np.bincount(t, minlength=n)
        allow = np.bincount(t[act == ALLOW], minlength=n)
        deny = np.bincount(t[act == DENY], minlength=n)
        with self._lock:
            for tid in np.nonzero(pkts)[0]:
                c = self._tenant_counts.setdefault(int(tid), [0, 0, 0])
                c[0] += int(pkts[tid])
                c[1] += int(allow[tid])
                c[2] += int(deny[tid])

    def tenant_counters(self) -> dict:
        """tenant_* counters for /metrics: allocator slab/swap gauges
        plus per-tenant packet/verdict totals."""
        out = dict(self._alloc.counter_values())
        if self._ov_alloc is not None:
            for k, v in self._ov_alloc.counter_values().items():
                out[f"{k}_overlay"] = v
        with self._lock:
            for tid, (pk, al, dn) in sorted(self._tenant_counts.items()):
                out[f"tenant_{tid}_packets_total"] = pk
                out[f"tenant_{tid}_allow_total"] = al
                out[f"tenant_{tid}_deny_total"] = dn
        return out

    # -- accessors / lifecycle ----------------------------------------------

    @property
    def stats(self) -> StatsAccumulator:
        return self._stats

    def close(self) -> None:
        self._closed = True
