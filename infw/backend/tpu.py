"""TPU classifier backend.

The device-resident dataplane: compiled rule tensors live in HBM/VMEM, the
classify step is the fused Pallas kernel (tables up to the dense limit) or
the XLA trie path (100K+ CIDRs).  Design points:

- **double-buffered table swap** (SURVEY.md §2: the TPU analogue of the
  reference's mutex-serialized map rewrite,
  /root/reference/pkg/ebpfsyncer/ebpfsyncer.go:56-63): the next rule
  tensors are built and device_put while classification continues on the
  current set; the swap is a single reference assignment under a lock, so
  in-flight batches finish on the old tables and new batches see the new
  ones — no torn reads, no pause.
- **async pipelining**: classify() dispatches without blocking (JAX's
  async dispatch queues the work); results are materialized lazily, so a
  caller streaming batches overlaps host<->device transfer with compute.
- statistics accumulate host-side in int64 from the device's per-batch
  (1024, 6) int32 sums.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np

from ..compiler import CompiledTables
from ..kernels import jaxpath, pallas_dense
from ..packets import PacketBatch
from .base import ClassifyOutput, StatsAccumulator


class TpuClassifier:
    """Single-chip device classifier."""

    def __init__(
        self,
        device=None,
        dense_limit: int = pallas_dense.MAX_DENSE_TARGETS,
        force_path: Optional[str] = None,  # "dense" | "trie" | None (auto)
        interpret: Optional[bool] = None,
    ) -> None:
        self._device = device if device is not None else jax.devices()[0]
        self._dense_limit = dense_limit
        self._force_path = force_path
        self._interpret = (
            interpret if interpret is not None else pallas_dense.default_interpret()
        )
        self._lock = threading.Lock()
        self._stats = StatsAccumulator()
        self._tables: Optional[CompiledTables] = None
        self._active = None  # (path, device tables, block_b or None)
        self._closed = False

    # -- rule loading -------------------------------------------------------

    def load_tables(self, tables: CompiledTables) -> None:
        if self._closed:
            raise RuntimeError("classifier is closed")
        path = self._force_path or (
            "dense" if tables.num_entries <= self._dense_limit else "trie"
        )
        # Build the next buffer off-lock (host packing + device_put can be
        # slow); swap under the lock.
        if path == "dense":
            pt = pallas_dense.build_pallas_tables(tables)
            dev = jax.tree.map(lambda a: jax.device_put(a, self._device), pt)
            block_b = pallas_dense.choose_block_b(pt.mdt.shape[1])
        else:
            dev = jaxpath.device_tables(tables, self._device)
            block_b = None
        with self._lock:
            self._tables = tables
            self._active = (path, dev, block_b)

    # -- classify -----------------------------------------------------------

    def classify(self, batch: PacketBatch) -> ClassifyOutput:
        with self._lock:
            if self._active is None:
                raise RuntimeError("no rule tables loaded")
            path, dev, block_b = self._active
        db = jaxpath.device_batch(batch, self._device)
        if path == "dense":
            res, xdp, stats = pallas_dense.jitted_classify_pallas(
                self._interpret, block_b
            )(dev, db)
        else:
            res, xdp, stats = jaxpath.jitted_classify(True)(dev, db)
        stats_delta = jaxpath.merge_stats_host(np.asarray(stats))
        self._stats.add(stats_delta)
        return ClassifyOutput(
            results=np.asarray(res), xdp=np.asarray(xdp), stats_delta=stats_delta
        )

    # -- accessors / lifecycle ---------------------------------------------

    @property
    def stats(self) -> StatsAccumulator:
        return self._stats

    @property
    def tables(self) -> Optional[CompiledTables]:
        return self._tables

    @property
    def active_path(self) -> Optional[str]:
        return self._active[0] if self._active else None

    def close(self) -> None:
        """Release device references (the analogue of detaching the XDP
        program and closing the BPF objects, loader.go:306-333)."""
        with self._lock:
            self._active = None
            self._tables = None
            self._closed = True
