// Native CPU reference classifier.
//
// The parity component for the reference's single native-code piece — the
// XDP C program (/root/reference/bpf/ingress_node_firewall_kernel.c) — used
// as the --backend=cpu dataplane and as a second, independent differential
// oracle for the TPU kernels.  Implements the identical verdict semantics:
// LPM over (ifindex:32 || ip:128) with packet-side prefix caps, the ordered
// first-match rule scan (half-open ranges, end==0 single port, family-gated
// ICMP, protocol==0 catch-all), result packing action|ruleId<<8, and
// per-ruleId statistics.
//
// Built as a shared library; driven through ctypes (see cpu_ref.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxTargets = 1024;
constexpr int kUndef = 0;
constexpr int kDeny = 1;   // XDP_DROP
constexpr int kAllow = 2;  // XDP_PASS

constexpr int kKindMalformed = 0;
constexpr int kKindV4 = 1;
constexpr int kKindV6 = 2;

constexpr int kProtoIcmp = 1;
constexpr int kProtoTcp = 6;
constexpr int kProtoUdp = 17;
constexpr int kProtoIcmp6 = 58;
constexpr int kProtoSctp = 132;

struct Entry {
  uint32_t ifindex;
  int32_t mask_len;          // CIDR bits (without the 32 ifindex bits)
  uint8_t ip[16];            // masked prefix bytes, network order
};

inline bool prefix_matches(const Entry& e, const uint8_t* ip) {
  int full = e.mask_len / 8;
  if (full && std::memcmp(e.ip, ip, full) != 0) return false;
  int rem = e.mask_len % 8;
  if (rem) {
    uint8_t mask = static_cast<uint8_t>(0xFF00 >> rem);
    if ((e.ip[full] & mask) != (ip[full] & mask)) return false;
  }
  return true;
}

inline uint32_t scan_rules(const int32_t* rows, int width, int proto, int dport,
                           int itype, int icode, bool is_v4) {
  const int icmp_proto = is_v4 ? kProtoIcmp : kProtoIcmp6;
  for (int i = 0; i < width; ++i) {
    const int32_t* r = rows + i * 7;
    const int rid = r[0];
    if (rid == 0) continue;  // INVALID_RULE_ID slot
    const int rproto = r[1];
    if (rproto != 0 && rproto == proto) {
      if (rproto == kProtoTcp || rproto == kProtoUdp || rproto == kProtoSctp) {
        const int ps = r[2], pe = r[3];
        if (pe == 0) {
          if (ps == dport)
            return (static_cast<uint32_t>(rid & 0xFFFFFF) << 8) |
                   static_cast<uint32_t>(r[6] & 0xFF);
        } else if (dport >= ps && dport < pe) {
          return (static_cast<uint32_t>(rid & 0xFFFFFF) << 8) |
                 static_cast<uint32_t>(r[6] & 0xFF);
        }
      }
      if (rproto == icmp_proto && r[4] == itype && r[5] == icode) {
        return (static_cast<uint32_t>(rid & 0xFFFFFF) << 8) |
               static_cast<uint32_t>(r[6] & 0xFF);
      }
    }
    if (rproto == 0) {  // catch-all
      return (static_cast<uint32_t>(rid & 0xFFFFFF) << 8) |
             static_cast<uint32_t>(r[6] & 0xFF);
    }
  }
  return kUndef;
}

}  // namespace

extern "C" {

// Classify a batch.  All pointers are caller-owned contiguous arrays.
//   entries: ent_ifindex[T] u32, ent_masklen[T] i32, ent_ip[T*16] u8 (masked)
//   rules:   [T * width * 7] i32
//   packets: kind/l4_ok/proto/dport/itype/icode/pktlen [B] i32,
//            pkt_ifindex[B] u32, pkt_ip[B*16] u8
//   out:     results[B] u32, xdp[B] i32, stats[kMaxTargets*4] i64
//            (stats is ACCUMULATED into, not zeroed — per-CPU map behavior)
void infw_classify(int32_t T, int32_t width, const uint32_t* ent_ifindex,
                   const int32_t* ent_masklen, const uint8_t* ent_ip,
                   const int32_t* rules, int32_t B, const int32_t* kind,
                   const int32_t* l4_ok, const uint32_t* pkt_ifindex,
                   const uint8_t* pkt_ip, const int32_t* proto,
                   const int32_t* dport, const int32_t* itype,
                   const int32_t* icode, const int32_t* pktlen,
                   uint32_t* results, int32_t* xdp, int64_t* stats) {
  // Bucket entries per ifindex once per call to cut the LPM scan down.
  std::vector<Entry> entries(static_cast<size_t>(T));
  for (int32_t t = 0; t < T; ++t) {
    entries[t].ifindex = ent_ifindex[t];
    entries[t].mask_len = ent_masklen[t];
    std::memcpy(entries[t].ip, ent_ip + t * 16, 16);
  }

  for (int32_t p = 0; p < B; ++p) {
    const int k = kind[p];
    if (k == kKindMalformed) {
      results[p] = 0;
      xdp[p] = kDeny;  // XDP_DROP on malformed eth header
      continue;
    }
    if (k != kKindV4 && k != kKindV6) {
      results[p] = 0;
      xdp[p] = kAllow;  // unknown ethertype -> XDP_PASS
      continue;
    }
    const bool is_v4 = (k == kKindV4);
    uint32_t result = kUndef;
    if (l4_ok[p]) {
      const int cap = is_v4 ? 32 : 128;
      const uint8_t* ip = pkt_ip + p * 16;
      int best_len = -1;
      int best_t = -1;
      for (int32_t t = 0; t < T; ++t) {
        const Entry& e = entries[t];
        if (e.ifindex != pkt_ifindex[p]) continue;
        if (e.mask_len > cap || e.mask_len <= best_len) continue;
        if (!prefix_matches(e, ip)) continue;
        best_len = e.mask_len;
        best_t = t;
      }
      if (best_t >= 0) {
        result = scan_rules(rules + static_cast<size_t>(best_t) * width * 7,
                            width, proto[p], dport[p], itype[p], icode[p], is_v4);
      }
    }
    results[p] = result;
    const int action = static_cast<int>(result & 0xFF);
    const uint32_t rule_id = (result >> 8) & 0xFFFFFF;
    if (action == kDeny) {
      xdp[p] = kDeny;
      if (rule_id < kMaxTargets) {
        stats[rule_id * 4 + 2] += 1;
        stats[rule_id * 4 + 3] += pktlen[p];
      }
    } else if (action == kAllow) {
      xdp[p] = kAllow;
      if (rule_id < kMaxTargets) {
        stats[rule_id * 4 + 0] += 1;
        stats[rule_id * 4 + 1] += pktlen[p];
      }
    } else {
      xdp[p] = kAllow;  // UNDEF -> default pass, no stats
    }
  }
}

// Frame parser: the host-side replica of the XDP header parse
// (ingress_node_firewall_kernel.c:95-174,423-439) at ingest-replay scale.
// Bit-exact with the Python parse paths in infw/obs/pcap.py (fixed 20-byte
// iphdr — no IHL; unknown/truncated L4 => l4_ok=0; <14-byte frame =>
// KIND_MALFORMED), one linear pass per frame, parallelized over frame
// ranges — ~10x the vectorized-NumPy gather formulation at 1M frames.
void infw_parse_frames(
    int64_t n,
    const uint8_t* buf,
    const int64_t* offsets,
    const uint32_t* lengths,
    int32_t* kind,
    int32_t* l4_ok,
    uint32_t* words,   // (n, 4)
    int32_t* proto,
    int32_t* dst_port,
    int32_t* icmp_type,
    int32_t* icmp_code,
    int32_t* pkt_len,
    int32_t n_threads) {
  constexpr int kEthHlen = 14;
  constexpr int kV4Hlen = 20;  // fixed sizeof(struct iphdr), kernel.c:103
  constexpr int kV6Hlen = 40;
  constexpr int kKindOther = 3;
  int l4_hlen[256];
  for (int i = 0; i < 256; ++i) l4_hlen[i] = -1;
  l4_hlen[kProtoTcp] = 20;
  l4_hlen[kProtoUdp] = 8;
  l4_hlen[kProtoSctp] = 12;
  l4_hlen[kProtoIcmp] = 8;
  l4_hlen[kProtoIcmp6] = 8;

  auto be16 = [](const uint8_t* p) -> uint32_t {
    return (static_cast<uint32_t>(p[0]) << 8) | p[1];
  };
  auto be32 = [](const uint8_t* p) -> uint32_t {
    return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
           (static_cast<uint32_t>(p[2]) << 8) | p[3];
  };

  auto run = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* f = buf + offsets[i];
      const int32_t len = static_cast<int32_t>(lengths[i]);
      pkt_len[i] = len;
      l4_ok[i] = 0;
      proto[i] = 0;
      dst_port[i] = 0;
      icmp_type[i] = 0;
      icmp_code[i] = 0;
      words[i * 4 + 0] = words[i * 4 + 1] = words[i * 4 + 2] = words[i * 4 + 3] = 0;
      if (len < kEthHlen) {
        kind[i] = kKindMalformed;
        continue;
      }
      const uint32_t ethertype = be16(f + 12);
      int k, ip_hlen;
      if (ethertype == 0x0800) {
        k = kKindV4; ip_hlen = kV4Hlen;
      } else if (ethertype == 0x86DD) {
        k = kKindV6; ip_hlen = kV6Hlen;
      } else {
        kind[i] = kKindOther;
        continue;
      }
      kind[i] = k;
      if (len < kEthHlen + ip_hlen) continue;  // truncated IP: l4_ok=0
      int pr;
      if (k == kKindV4) {
        pr = f[kEthHlen + 9];
        words[i * 4 + 0] = be32(f + kEthHlen + 12);
      } else {
        pr = f[kEthHlen + 6];
        for (int w = 0; w < 4; ++w)
          words[i * 4 + w] = be32(f + kEthHlen + 8 + 4 * w);
      }
      proto[i] = pr;
      const int hl = l4_hlen[pr];
      if (hl < 0 || len < kEthHlen + ip_hlen + hl) continue;
      l4_ok[i] = 1;
      const uint8_t* l4 = f + kEthHlen + ip_hlen;
      if (pr == kProtoTcp || pr == kProtoUdp || pr == kProtoSctp) {
        dst_port[i] = static_cast<int32_t>(be16(l4 + 2));
      } else {
        icmp_type[i] = l4[0];
        icmp_code[i] = l4[1];
      }
    }
  };

  int nt = n_threads;
  if (nt <= 1 || n < (1 << 16)) {
    run(0, n);
  } else {
    std::vector<std::thread> threads;
    const int64_t step = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      const int64_t lo = t * step;
      const int64_t hi = lo + step < n ? lo + step : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
}

// Fused subset gather + wire pack (PacketBatch.take + pack_wire[_v4] in
// one pass): the daemon regroups ingest by family before dispatch, and
// copying 9 SoA arrays per chunk just to re-pack them into the 7- or
// 4-word device descriptor doubles the host cost of the hot loop.
// Returns flags: bit0 = packed compact (4 words/row, rows contiguous at
// the front of out), bit1 = subset is v4_only (no KIND_IPV6 rows).
int32_t infw_pack_wire_subset(
    int64_t n,
    const int64_t* idx,
    const int32_t* kind,
    const int32_t* l4_ok,
    const int32_t* ifindex,
    const uint32_t* words,  // (B, 4)
    const int32_t* proto,
    const int32_t* dst_port,
    const int32_t* icmp_type,
    const int32_t* icmp_code,
    const int32_t* pkt_len,
    uint32_t* out,          // room for n * 7 words
    int32_t n_threads) {
  bool any_v6 = false, any_hi = false;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = idx[i];
    any_v6 |= kind[r] == kKindV6;
    any_hi |= (words[r * 4 + 1] | words[r * 4 + 2] | words[r * 4 + 3]) != 0;
  }
  const bool compact = !any_v6 && !any_hi;
  const int w = compact ? 4 : 7;

  auto run = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t r = idx[i];
      uint32_t* o = out + i * w;
      int32_t pl = pkt_len[r];  // clip as signed: negative -> 0, not huge
      if (pl < 0) pl = 0;
      const uint32_t plen = pl > 0x1FFFFF ? 0x1FFFFF : static_cast<uint32_t>(pl);
      o[0] = (static_cast<uint32_t>(kind[r]) & 3) |
             ((static_cast<uint32_t>(l4_ok[r]) & 1) << 2) |
             ((static_cast<uint32_t>(proto[r]) & 0xFF) << 3) |
             ((static_cast<uint32_t>(icmp_type[r]) & 0xFF) << 11) |
             ((static_cast<uint32_t>(icmp_code[r]) & 0xFF) << 19) |
             ((plen >> 16) << 27);
      o[1] = (static_cast<uint32_t>(dst_port[r]) & 0xFFFF) | ((plen & 0xFFFF) << 16);
      o[2] = static_cast<uint32_t>(ifindex[r]);
      if (compact) {
        o[3] = words[r * 4 + 0];
      } else {
        o[3] = words[r * 4 + 0];
        o[4] = words[r * 4 + 1];
        o[5] = words[r * 4 + 2];
        o[6] = words[r * 4 + 3];
      }
    }
  };

  int nt = n_threads;
  if (nt <= 1 || n < (1 << 16)) {
    run(0, n);
  } else {
    std::vector<std::thread> threads;
    const int64_t step = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      const int64_t lo = t * step;
      const int64_t hi = lo + step < n ? lo + step : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  return (compact ? 1 : 0) | (any_v6 ? 0 : 2);
}

// Delta+varint wire encode (the packets.encode_delta_wire hot half):
// stable sort by IP word, ifindex/meta15 dictionaries, LEB128 or
// fixed-stride section C — BYTE-IDENTICAL to the NumPy reference
// (differentially tested), one pass in C++ instead of argsort + five
// vectorized sweeps.  The caller keeps the qualification gate
// (max_bytes_per_pkt) and the crc, which need the returned length.
//
// Returns the payload length, or -1 when the chunk does not qualify
// (>15 distinct ifindexes, >256 distinct meta15 values, n < 1).
// meta out: [dict_len, dict_mode, fixed_w].
int64_t infw_encode_delta(
    int64_t n,
    const uint32_t* w,    // (n, 4) row-major v4-compact wire
    uint8_t* payload,     // caller cap: n + 2n + 5n bytes
    uint32_t* dict_vals,  // cap 256
    int32_t* ifmap,       // 16, padded with -1
    int64_t* perm,        // n
    int32_t* meta) {      // [dict_len, dict_mode, fixed_w]
  if (n < 1) return -1;
  // ifindex dictionary: sorted unique (np.unique), <= 15 entries
  std::vector<uint32_t> ifs(n);
  for (int64_t i = 0; i < n; ++i) ifs[i] = w[i * 4 + 2];
  std::vector<uint32_t> if_uniq(ifs);
  std::sort(if_uniq.begin(), if_uniq.end());
  if_uniq.erase(std::unique(if_uniq.begin(), if_uniq.end()), if_uniq.end());
  if (if_uniq.size() > 15) return -1;
  for (int i = 0; i < 16; ++i)
    ifmap[i] = i < static_cast<int>(if_uniq.size())
                   ? static_cast<int32_t>(if_uniq[i])
                   : -1;
  // meta15 = (w0 & 0x7FF) | (ifdict << 11); dictionary sorted unique
  std::vector<uint32_t> meta15(n);
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t ifd = static_cast<uint32_t>(
        std::lower_bound(if_uniq.begin(), if_uniq.end(), ifs[i]) -
        if_uniq.begin());
    meta15[i] = (w[i * 4] & 0x7FFu) | (ifd << 11);
  }
  std::vector<uint32_t> dict(meta15);
  std::sort(dict.begin(), dict.end());
  dict.erase(std::unique(dict.begin(), dict.end()), dict.end());
  if (dict.size() > 256) return -1;
  const int dict_len = static_cast<int>(dict.size());
  const int dict_mode = dict_len == 1 ? 0 : (dict_len <= 16 ? 1 : 2);
  for (int i = 0; i < dict_len; ++i) dict_vals[i] = dict[i];
  // stable argsort by IP word (w3)
  for (int64_t i = 0; i < n; ++i) perm[i] = i;
  std::stable_sort(perm, perm + n, [&](int64_t a, int64_t b) {
    return w[a * 4 + 3] < w[b * 4 + 3];
  });
  // deltas in sorted order (non-negative by construction)
  std::vector<uint64_t> deltas(n);
  uint64_t prev = 0;
  uint64_t dmax = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t ip = w[perm[i] * 4 + 3];
    deltas[i] = i == 0 ? ip : ip - prev;
    prev = ip;
    if (deltas[i] > dmax) dmax = deltas[i];
  }
  // varint length first (the fixed-stride plan competes on total bytes)
  int64_t var_len = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = deltas[i];
    do {
      ++var_len;
      v >>= 7;
    } while (v);
  }
  int fixed_w = 0;
  for (int cand : {1, 2, 4}) {
    if (dmax < (1ull << (8 * cand)) && n * cand <= var_len) {
      fixed_w = cand;
      break;
    }
  }
  // sections: A (meta dictionary indexes), B (l4 words le16), C (ips)
  const int64_t n_a =
      dict_mode == 0 ? 0 : (dict_mode == 1 ? (n + 1) / 2 : n);
  const int64_t off_b = n_a;
  const int64_t off_c = n_a + 2 * n;
  const int64_t total = off_c + (fixed_w ? n * fixed_w : var_len);
  std::memset(payload, 0, static_cast<size_t>(off_c));
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = perm[i];
    const uint32_t midx = static_cast<uint32_t>(
        std::lower_bound(dict.begin(), dict.end(), meta15[r]) -
        dict.begin());
    if (dict_mode == 1) {
      payload[i / 2] |= static_cast<uint8_t>((i & 1) ? (midx << 4) : midx);
    } else if (dict_mode == 2) {
      payload[i] = static_cast<uint8_t>(midx);
    }
    const uint32_t w0 = w[r * 4], w1 = w[r * 4 + 1];
    const uint32_t proto = (w0 >> 3) & 0xFF;
    const bool is_icmp = proto == 1 || proto == 58;
    const uint32_t l4 = is_icmp
                            ? ((((w0 >> 11) & 0xFF) << 8) | ((w0 >> 19) & 0xFF))
                            : (w1 & 0xFFFF);
    payload[off_b + 2 * i] = static_cast<uint8_t>(l4 & 0xFF);
    payload[off_b + 2 * i + 1] = static_cast<uint8_t>((l4 >> 8) & 0xFF);
  }
  uint8_t* c = payload + off_c;
  if (fixed_w) {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t v = deltas[i];
      for (int k = 0; k < fixed_w; ++k) {
        *c++ = static_cast<uint8_t>(v & 0xFF);
        v >>= 8;
      }
    }
  } else {
    for (int64_t i = 0; i < n; ++i) {
      uint64_t v = deltas[i];
      do {
        const uint8_t b = static_cast<uint8_t>(v & 0x7F);
        v >>= 7;
        *c++ = v ? (b | 0x80) : b;
      } while (v);
    }
  }
  meta[0] = dict_len;
  meta[1] = dict_mode;
  meta[2] = fixed_w;
  return total;
}

int32_t infw_abi_version() { return 4; }

}  // extern "C"
