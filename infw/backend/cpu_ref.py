"""ctypes bindings + Classifier wrapper for the native C++ reference
classifier (native/classifier.cpp).

The shared library is built on demand with g++ (cached by source mtime) —
the framework's analogue of the reference's bpf2go build step
(/root/reference/pkg/ebpf/ingress_node_firewall_loader.go:53).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..compiler import CompiledTables
from ..constants import MAX_TARGETS
from ..packets import PacketBatch
from .base import ClassifyOutput, PendingClassify, StatsAccumulator

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "classifier.cpp")
_LIB = os.path.join(_NATIVE_DIR, "_build", "libinfwref.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build_library() -> str:
    os.makedirs(os.path.dirname(_LIB), exist_ok=True)
    if (not os.path.exists(_LIB)) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-Wall", "-pthread",
             "-shared", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
        )
    return _LIB


_ABI_VERSION = 4


def load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(_build_library())
            lib.infw_abi_version.restype = ctypes.c_int32
            if lib.infw_abi_version() != _ABI_VERSION:
                # Stale prebuilt .so whose mtime defeated the rebuild gate
                # (artifact cache, cp -p): force one rebuild from source
                # instead of binding symbols that may not exist.
                os.remove(_LIB)
                lib = ctypes.CDLL(_build_library())
                lib.infw_abi_version.restype = ctypes.c_int32
            i32p = ctypes.POINTER(ctypes.c_int32)
            u32p = ctypes.POINTER(ctypes.c_uint32)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.infw_classify.restype = None
            lib.infw_classify.argtypes = [
                ctypes.c_int32, ctypes.c_int32, u32p, i32p, u8p, i32p,
                ctypes.c_int32, i32p, i32p, u32p, u8p, i32p, i32p, i32p,
                i32p, i32p, u32p, i32p, i64p,
            ]
            lib.infw_parse_frames.restype = None
            lib.infw_parse_frames.argtypes = [
                ctypes.c_int64, u8p, i64p, u32p,
                i32p, i32p, u32p, i32p, i32p, i32p, i32p, i32p,
                ctypes.c_int32,
            ]
            lib.infw_pack_wire_subset.restype = ctypes.c_int32
            lib.infw_pack_wire_subset.argtypes = [
                ctypes.c_int64, i64p,
                i32p, i32p, i32p, u32p, i32p, i32p, i32p, i32p, i32p,
                u32p, ctypes.c_int32,
            ]
            lib.infw_encode_delta.restype = ctypes.c_int64
            lib.infw_encode_delta.argtypes = [
                ctypes.c_int64, u32p, u8p, u32p, i32p, i64p, i32p,
            ]
            assert lib.infw_abi_version() == _ABI_VERSION
            _lib = lib
        return _lib


def _words_to_bytes(words: np.ndarray) -> np.ndarray:
    """(N, 4) uint32 big-endian words -> (N, 16) uint8."""
    return words.astype(">u4").view(np.uint8).reshape(words.shape[0], 16)


class CpuRefClassifier:
    """Native CPU dataplane implementing the Classifier protocol."""

    def __init__(self) -> None:
        self._lib = load_library()
        self._lock = threading.Lock()
        self._stats = StatsAccumulator()
        self._tables: Optional[CompiledTables] = None
        self._packed = None
        self._closed = False

    def load_tables(self, tables: CompiledTables, dirty_hint=None) -> None:
        # dirty_hint is a device-patch acceleration; the CPU backend's
        # full repack is already cheap, so it is accepted and ignored.
        if self._closed:
            raise RuntimeError("classifier is closed")
        T = tables.num_entries
        ent_ifindex = np.ascontiguousarray(tables.key_words[:T, 0], np.uint32)
        ent_masklen = np.ascontiguousarray(tables.mask_len[:T], np.int32)
        ent_ip = np.ascontiguousarray(
            _words_to_bytes(tables.key_words[:T, 1:5].astype(np.uint32))
        )
        rules = np.ascontiguousarray(tables.rules[:T], np.int32)
        with self._lock:
            self._tables = tables
            self._packed = (T, tables.rule_width, ent_ifindex, ent_masklen, ent_ip, rules)

    def classify(self, batch: PacketBatch, apply_stats: bool = True) -> ClassifyOutput:
        with self._lock:
            if self._packed is None:
                raise RuntimeError("no rule tables loaded")
            T, width, ent_ifindex, ent_masklen, ent_ip, rules = self._packed

        B = len(batch)
        kind = np.ascontiguousarray(batch.kind, np.int32)
        l4_ok = np.ascontiguousarray(batch.l4_ok, np.int32)
        pkt_ifindex = np.ascontiguousarray(batch.ifindex, np.uint32)
        pkt_ip = np.ascontiguousarray(_words_to_bytes(batch.ip_words.astype(np.uint32)))
        proto = np.ascontiguousarray(batch.proto, np.int32)
        dport = np.ascontiguousarray(batch.dst_port, np.int32)
        itype = np.ascontiguousarray(batch.icmp_type, np.int32)
        icode = np.ascontiguousarray(batch.icmp_code, np.int32)
        pktlen = np.ascontiguousarray(batch.pkt_len, np.int32)

        results = np.zeros(B, np.uint32)
        xdp = np.zeros(B, np.int32)
        stats = np.zeros((MAX_TARGETS, 4), np.int64)

        c = ctypes
        p = lambda a, t: a.ctypes.data_as(c.POINTER(t))
        self._lib.infw_classify(
            c.c_int32(T), c.c_int32(width),
            p(ent_ifindex, c.c_uint32), p(ent_masklen, c.c_int32),
            p(ent_ip, c.c_uint8), p(rules, c.c_int32),
            c.c_int32(B), p(kind, c.c_int32), p(l4_ok, c.c_int32),
            p(pkt_ifindex, c.c_uint32), p(pkt_ip, c.c_uint8),
            p(proto, c.c_int32), p(dport, c.c_int32), p(itype, c.c_int32),
            p(icode, c.c_int32), p(pktlen, c.c_int32),
            p(results, c.c_uint32), p(xdp, c.c_int32), p(stats, c.c_int64),
        )
        if apply_stats:
            self._stats.add(stats)
        return ClassifyOutput(results=results, xdp=xdp, stats_delta=stats)

    def classify_async(
        self, batch: PacketBatch, apply_stats: bool = True
    ) -> PendingClassify:
        """Eager: the native call is synchronous, so the handle resolves
        immediately (protocol parity with TpuClassifier)."""
        out = self.classify(batch, apply_stats=apply_stats)
        return PendingClassify(lambda: out)

    @property
    def stats(self) -> StatsAccumulator:
        return self._stats

    @property
    def tables(self) -> Optional[CompiledTables]:
        return self._tables

    def close(self) -> None:
        with self._lock:
            self._packed = None
            self._tables = None
            self._closed = True


# -- payload-tier host oracle (ISSUE-19) -------------------------------------
#
# The Aho-Corasick reference the statecheck `payload` config compares
# against.  Deliberately CONSTRUCTION-INDEPENDENT: a naive
# find-every-occurrence substring scan over each truncated prefix, so
# an automaton-construction bug (the aclink injected defect drops one
# failure-link fold) cannot be shared by both sides of the compare.


def payload_match_ref(patterns, pay, plen, prefix_len, pwords):
    """Naive multi-pattern reference -> (B, pwords) uint32 bitmaps.

    ``patterns`` is a sequence of byte strings (pattern j -> bit j),
    ``pay`` (B, L) uint8 payload-prefix columns, ``plen`` (B,) valid
    byte counts, ``prefix_len`` the matched prefix length.  Truncation
    semantics: pattern j is claimed for packet i iff an occurrence ends
    wholly within the first ``min(plen[i], prefix_len)`` bytes —
    occurrences crossing the truncation boundary claim nothing.
    """
    pay = np.asarray(pay, np.uint8)
    plen = np.asarray(plen).astype(np.int64)
    b = pay.shape[0]
    out = np.zeros((b, int(pwords)), np.uint32)
    pats = [bytes(p) for p in patterns]
    for i in range(b):
        n = int(min(plen[i], prefix_len, pay.shape[1]))
        hay = pay[i, :n].tobytes()
        for j, p in enumerate(pats):
            if p in hay:
                out[i, j // 32] |= np.uint32(1 << (j % 32))
    return out


class HostAcAutomaton:
    """A tiny, independent host Aho-Corasick (goto + failure links
    walked AT MATCH TIME, no folding) — the second reference
    implementation tests use to pin the naive scan and the compiled
    DFA against each other from a third angle."""

    def __init__(self, patterns):
        self.patterns = [bytes(p) for p in patterns]
        self.goto = [{}]
        self.out = [set()]
        for j, p in enumerate(self.patterns):
            s = 0
            for ch in p:
                if ch not in self.goto[s]:
                    self.goto.append({})
                    self.out.append(set())
                    self.goto[s][ch] = len(self.goto) - 1
                s = self.goto[s][ch]
            self.out[s].add(j)
        from collections import deque

        self.fail = [0] * len(self.goto)
        q = deque(self.goto[0].values())
        while q:
            s = q.popleft()
            for ch, t in self.goto[s].items():
                f = self.fail[s]
                while f and ch not in self.goto[f]:
                    f = self.fail[f]
                cand = self.goto[f].get(ch, 0)
                self.fail[t] = cand if cand != t else 0
                q.append(t)

    def matches(self, data: bytes):
        """Set of pattern indices with an occurrence ending in data."""
        found = set()
        s = 0
        for ch in data:
            while s and ch not in self.goto[s]:
                s = self.fail[s]
            s = self.goto[s].get(ch, 0)
            f = s
            while f:
                found |= self.out[f]
                f = self.fail[f]
            found |= self.out[s]
        return found
