"""Multi-chip TPU classifier backend: the mesh serving path.

``MeshTpuClassifier`` serves the same ``classify_async`` /
``prepare_packed`` / ``classify_prepared`` contract as the single-chip
``TpuClassifier``, so the daemon's double-buffered delta-wire ingest and
depth-class steering work unchanged — but the dataplane spans a
``("data", "rules")`` device mesh (parallel.mesh):

- **data axis** (the per-CPU XDP lanes of the reference, PAPER.md §2):
  the packed wire is sharded over "data" at prepare time, so the H2D
  staging of the next chunk runs per chip while the current chunk's
  classify executes; per-shard statistics are combined on device with
  ONE psum and the host reads a single merged ``stats_delta`` instead of
  N per-chip copies.
- **rules axis** (tensor parallelism over targets, the hXDP
  parallel-lane analogue): with ``rules_shards > 1`` the rule table is
  partitioned across chips — dense tables target-sharded, trie tables as
  per-shard tries — and the global longest-prefix winner is selected
  with pmax over match scores.

Kernel parity with the single chip: the replicated configurations
(``rules_shards == 1``) run the SAME kernels under shard_map — the int8
Pallas dense kernel, the XLA trie walk with v4/depth truncation, the
fused Pallas deep walk for the full-depth steering class, and the
replicated overlay combine.  Rule loading on those configurations keeps
the single-chip incremental contract: a 1-key rules edit diff-scatter
patches the mesh-resident arrays (the small patch rows broadcast to
every chip — kilobytes), and a structural CIDR add ships as the
broadcast overlay side-table, the main trie untouched.  A folded edit
TRANSACTION (infw.txn) rides the same machinery: the replicated
NamedSharding stands in for the device in the fused transaction scatter
(jaxpath.txn_scatter), so one flush broadcasts its merged dirty-row
payload to every chip in one staging pass + one launch — the
update-storm path needs no mesh-specific code.  The rules-sharded
configurations re-place per load as always, so a transaction flush
against them costs one re-place, not a broadcast patch.

The rules-sharded configurations rebuild their per-shard partition on
every load (the round-robin entry split renumbers shard membership on
any structural edit) and refuse overlays — the syncer merges into the
main table instead, exactly as it does for the single-chip paths that
cannot honor one.

Wire formats on the mesh: wire / narrow / wire8.  The delta+varint codec
is per-chunk sequential (one varint stream + one inverse permutation per
encode) and does not shard along the data axis, so ``--wire-codec
delta``/``auto`` degrades per chunk down the familiar
delta -> wire8 -> narrow -> full chain starting at wire8 — never
refuses, same contract as an ineligible chunk on one chip.
"""
from __future__ import annotations

import logging
import re
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compiler import CompiledTables
from ..constants import KIND_OTHER
from ..kernels import jaxpath
from ..packets import PacketBatch, narrow_wire, wire8
from ..parallel import mesh as meshmod
from .base import ClassifyOutput, PendingClassify, StatsAccumulator
from .tpu import TpuClassifier

log = logging.getLogger("infw.backend.mesh")


def parse_mesh_spec(spec: str) -> Tuple[int, int]:
    """"DATAxRULES" (e.g. "4x2") or a bare device count "8" (rules=1)
    -> (data_shards, rules_shards).  Raises ValueError on junk — the
    daemon CLI surfaces this at launch, not inside the sync loop."""
    m = re.fullmatch(r"\s*(\d+)\s*(?:[xX]\s*(\d+))?\s*", spec or "")
    if not m:
        raise ValueError(
            f"bad mesh spec {spec!r} (expected DATAxRULES, e.g. 4x2, or a "
            "device count)"
        )
    data = int(m.group(1))
    rules = int(m.group(2)) if m.group(2) else 1
    if data < 1 or rules < 1:
        raise ValueError(f"mesh axes must be positive, got {spec!r}")
    return data, rules


def resolve_mesh_spec(spec: str) -> Optional[Mesh]:
    """Build the serving mesh for a --mesh/INFW_MESH spec, or None when
    the daemon should FALL BACK to the single-chip classifier: a 1x1
    spec, or a device pool too small for the requested shape (logged —
    a daemon scheduled onto a single-chip node with a fleet-wide mesh
    setting must come up serving, not crash-loop)."""
    data, rules = parse_mesh_spec(spec)
    if data * rules <= 1:
        return None
    n_avail = len(jax.devices())
    if data * rules > n_avail:
        log.warning(
            "mesh %dx%d needs %d devices but only %d visible; "
            "falling back to the single-chip classifier",
            data, rules, data * rules, n_avail,
        )
        return None
    return meshmod.make_mesh(data * rules, rules_shards=rules)


class MeshTpuClassifier(TpuClassifier):
    """Multi-chip device classifier on a ("data", "rules") mesh.

    With ``rules_shards == 1`` (the default, pure data parallelism) all
    table state is REPLICATED on the mesh — placement, incremental
    patching, overlay broadcast and the fused-walk build all reuse the
    single-chip machinery verbatim, with the replicated NamedSharding
    standing in for the single device — and only the dispatch differs:
    the wire shards over "data" and runs under shard_map with a device-
    side stats psum.  With ``rules_shards > 1`` the table itself is
    partitioned (see module docstring)."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        data_shards: Optional[int] = None,
        rules_shards: int = 1,
        **kw,
    ) -> None:
        if mesh is None:
            n_avail = len(jax.devices())
            data = data_shards or max(n_avail // max(rules_shards, 1), 1)
            mesh = meshmod.make_mesh(
                data * rules_shards, rules_shards=rules_shards
            )
        self._mesh = mesh
        self._data_shards = mesh.shape["data"]
        self._rules_shards = mesh.shape["rules"]
        self._replicated = NamedSharding(mesh, P())
        self._data_sharding = NamedSharding(mesh, P("data", None))
        # The replicated sharding IS the placement: every device_put /
        # scatter-patch / walk-build in the single-chip machinery takes a
        # jax.device_put target, and a NamedSharding broadcasts where a
        # Device pins.
        super().__init__(device=self._replicated, **kw)
        #: the overlay side-table broadcasts in kilobytes on the
        #: replicated configs; the rules-sharded partition cannot honor
        #: one (the syncer merges instead)
        self.supports_overlay = self._rules_shards == 1

    def _make_flow_tier(self, cfg, track_model: bool = False):
        """Place the flow-tier columns by the declared partition rules
        (parallel.mesh.FLOW_PARTITION_RULES): flow rows shard over
        "rules" when the capacity divides the axis, the steering state
        replicates, and the probe/insert dispatches run under the same
        jitted factories as the single chip — GSPMD, no mesh-specific
        flow kernel."""
        from ..flow import FlowTier

        return FlowTier(
            cfg, device=self._replicated,
            shardings=meshmod.flow_shardings(self._mesh, cfg.capacity),
            track_model=track_model,
        )

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def data_shards(self) -> int:
        """Width of the "data" axis one dispatched batch spreads over —
        the scheduler's per-chip admission budget multiplies by this
        (a spilled batch costs each chip only batch/data_shards rows)."""
        return self._data_shards

    # -- rule loading -------------------------------------------------------

    def load_tables(self, tables: CompiledTables, dirty_hint=None,
                    overlay: Optional[CompiledTables] = None) -> None:
        if self._rules_shards == 1:
            # Replicated tables: the whole single-chip load path — dense
            # Pallas build, diff-scatter patch, overlay cache, fused-walk
            # build/patch, depth steering — runs against the mesh via the
            # replicated placement.  A 1-key edit ships the same
            # kilobytes as on one chip, broadcast.
            return super().load_tables(
                tables, dirty_hint=dirty_hint, overlay=overlay
            )
        if self._closed:
            raise RuntimeError("classifier is closed")
        if overlay is not None and overlay.num_entries > 0:
            raise ValueError(
                f"overlay not supported on the rules-sharded mesh "
                f"(rules_shards={self._rules_shards}); merge it into the "
                "main table"
            )
        path = self._force_path or (
            "dense" if tables.num_entries <= self._dense_limit else "trie"
        )
        wide_rids = False
        try:
            jaxpath.check_wire_ruleids(tables)
        except ValueError:
            wide_rids = True
        steer_parts = None
        if path == "dense":
            dev = meshmod.shard_tables(tables, self._mesh)
        else:
            # Per-shard tries are a PARTITION of the entry set: any
            # structural change renumbers the round-robin split, so the
            # sharded configuration re-places on every load (the
            # incremental patch story belongs to the replicated config).
            dev = meshmod.shard_tables_trie(tables, self._mesh)
            lut = jaxpath.build_depth_lut(tables)
            classes = jaxpath.tune_depth_classes(tables)
            steer_parts = (
                np.asarray(tables.root_lut, np.int64), lut, classes,
            )
        if self._check_invariants:
            # The sharded partitions are NOT the bucket-padded patch
            # layout (they re-place on every load), so the deep
            # DeviceTables contract doesn't apply; run the minimal
            # sharded consistency pass instead.  The replicated config
            # inherits the full check via super().load_tables.
            from ..analysis import statecheck  # lazy: no import cycle

            viols = statecheck.check_sharded_tables(dev)
            if viols:
                raise statecheck.InvariantViolation(
                    "sharded-table invariant contract violated:\n  "
                    + "\n  ".join(viols)
                )
        with self._lock:
            self._tables = tables
            self._active = (path, dev, None, wide_rids, None, None)
            self._walk_meta = None
            self._last_load = ("full", tables.num_entries)
            self._depth_gen += 1
            self._depth_steer = (
                steer_parts + (self._depth_gen,)
                if steer_parts is not None else None
            )
        if self._flow is not None:
            # the sharded load path bypasses super().load_tables — the
            # flow invalidation chokepoint must still fire here
            self._flow.bump_generation(0)

    # -- dispatch -----------------------------------------------------------

    def _mesh_pad_rows(self, arr: np.ndarray) -> np.ndarray:
        """Pad wire rows to a multiple of 2*data_shards: equal shard
        sizes for the "data" split, EVEN rows per shard so the u16 pair
        packing of the fused output never straddles a shard boundary.
        Pad rows are KIND_OTHER — always PASS, never counted — and the
        host slices them off at materialize."""
        n = arr.shape[0]
        m = 2 * self._data_shards
        npad = (-n) % m
        if npad == 0 and n > 0:
            return arr
        rows = np.zeros((max(npad, m if n == 0 else npad), arr.shape[1]),
                        arr.dtype)
        rows[:, 0] = KIND_OTHER
        return np.concatenate([arr, rows])

    def _plan_wire(
        self, path, dev, block_b, wire_np, v4_only, kind,
        ov_dev=None, depth=None, walk_dev=None,
    ):
        """Mesh format choice + per-shard H2D staging: the chosen payload
        is placed with the "data" sharding, which starts one async copy
        per chip — the staged-plan transfer overlaps whatever every chip
        is running (the double-buffer contract, now per shard)."""
        n = wire_np.shape[0]
        plan = {
            "path": path, "dev": dev, "block_b": block_b, "ov_dev": ov_dev,
            "depth": depth, "walk_dev": walk_dev, "v4_only": v4_only,
            "kind": kind, "n": n,
        }
        put_data = lambda a: jax.device_put(a, self._data_sharding)
        replicated_trie = path == "trie" and self._rules_shards == 1
        if (
            replicated_trie and wire_np.shape[1] == 4 and n
            and self._wire_codec in ("auto", "wire8", "delta")
        ):
            # wire8 is the mesh's compressed format: 8 B/packet, a fixed
            # per-row layout that shards cleanly over "data" (the delta
            # stream is sequential per chunk and does not — see module
            # docstring), with the ifindex dictionary replicated.
            w8 = wire8(wire_np)
            if w8 is not None:
                wire8_np, ifmap = w8
                wire8_np = self._mesh_pad_rows(wire8_np)
                plan.update(
                    fmt="wire8", pkt_len=self._wire4_pkt_len(wire_np),
                    wire=put_data(wire8_np),
                    ifmap=jax.device_put(ifmap, self._replicated),
                )
                self._note_wire("wire8", n, wire8_np.nbytes + ifmap.nbytes)
                return plan
        if wire_np.shape[1] in (4, 7):
            nw = narrow_wire(wire_np)
            if nw is not None:
                wire_np = nw
        wire_np = self._mesh_pad_rows(wire_np)
        plan.update(fmt="wire", wire=put_data(wire_np))
        self._note_wire(f"wire{wire_np.shape[1]}", n, wire_np.nbytes)
        return plan

    def _launch_wire(self, plan, apply_stats: bool) -> PendingClassify:
        if plan["fmt"] == "wire8":
            return self._launch_wire8(plan, apply_stats)
        path, dev, block_b = plan["path"], plan["dev"], plan["block_b"]
        ov_dev, depth, walk_dev = (
            plan["ov_dev"], plan["depth"], plan["walk_dev"]
        )
        v4_only, kind, n = plan["v4_only"], plan["kind"], plan["n"]
        wire = plan["wire"]
        mesh = self._mesh
        if path == "dense":
            if self._rules_shards > 1:
                fn = meshmod.jitted_mesh_wire(mesh, "dense-sharded", dev)
            else:
                fn = meshmod.jitted_mesh_wire(
                    mesh, "pallas-dense", dev,
                    interpret=self._interpret, block_b=block_b,
                )
            fused = fn(dev, wire)
        elif walk_dev is not None and ov_dev is None:
            # Fused Pallas deep walk per shard — same kernel, same
            # overlay exclusion, as the single-chip dispatch.
            fn = meshmod.jitted_mesh_wire(
                mesh, "walk", walk_dev, interpret=self._interpret
            )
            fused = fn(walk_dev, wire)
        elif ov_dev is not None:
            fn = meshmod.jitted_mesh_wire(
                mesh, "trie-overlay", dev, v4_only=v4_only, depth=depth,
                overlay=ov_dev,
            )
            fused = fn(dev, ov_dev, wire)
        elif self._rules_shards > 1:
            fn = meshmod.jitted_mesh_wire(
                mesh, "trie-sharded", dev, v4_only=v4_only, depth=depth
            )
            fused = fn(dev, wire)
        else:
            fn = meshmod.jitted_mesh_wire(
                mesh, "trie", dev, v4_only=v4_only, depth=depth
            )
            fused = fn(dev, wire)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        data_shards = self._data_shards

        def materialize() -> ClassifyOutput:
            res16, stats = meshmod.split_fused_wire_outputs(
                np.asarray(fused), n, data_shards
            )
            stats_delta = jaxpath.merge_stats_host(stats)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _launch_wire8(self, plan, apply_stats: bool) -> PendingClassify:
        dev, ov_dev = plan["dev"], plan["ov_dev"]
        kind, n, pkt_len = plan["kind"], plan["n"], plan["pkt_len"]
        fn = meshmod.jitted_mesh_wire8(self._mesh, dev, overlay=ov_dev)
        if ov_dev is not None:
            fused = fn(dev, ov_dev, plan["wire"], plan["ifmap"])
        else:
            fused = fn(dev, plan["wire"], plan["ifmap"])
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        data_shards = self._data_shards

        def materialize() -> ClassifyOutput:
            from ..daemon import stats_from_results  # lazy: no import cycle

            res16, _ = meshmod.split_fused_wire_outputs(
                np.asarray(fused), n, data_shards, with_stats=False
            )
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            stats_delta = stats_from_results(results, pkt_len)
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def _classify_async_wide(
        self, dev, batch: PacketBatch, apply_stats: bool
    ) -> PendingClassify:
        """u32 results path for wide-ruleId tables, on the mesh: the
        DeviceBatch shards over "data", results come back 4B/packet."""
        n = len(batch)
        bp = -(-max(n, 1) // self._data_shards) * self._data_shards
        db = meshmod.shard_batch(batch.pad_to(bp), self._mesh)
        if self._rules_shards > 1:
            # dev is ShardedTrieTables (trie) or mesh DeviceTables (dense)
            if isinstance(dev, meshmod.ShardedTrieTables):
                fn = meshmod.make_sharded_trie_classifier(
                    self._mesh, len(dev.trie_levels)
                )
            else:
                fn = meshmod.make_sharded_classifier(
                    self._mesh, len(dev.trie_levels)
                )
        else:
            fn = meshmod.jitted_mesh_classify(self._mesh, "trie", dev)
        res, xdp, stats = fn(dev, db)
        for arr in (res, xdp, stats):
            try:
                arr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break

        def materialize() -> ClassifyOutput:
            stats_delta = jaxpath.merge_stats_host(np.asarray(stats))
            if apply_stats:
                self._stats.add(stats_delta)
            return ClassifyOutput(
                results=np.asarray(res)[:n], xdp=np.asarray(xdp)[:n],
                stats_delta=stats_delta,
            )

        return PendingClassify(materialize)


class MeshArenaClassifier:
    """Multi-tenant paged arena spanning a ("data", "rules") mesh: the
    slab pools are placed ONCE with the per-family partition rules
    (parallel.mesh.ARENA_PARTITION_RULES — pages in whole-slab blocks
    over "rules", page table replicated), tenant lifecycle mutations
    broadcast through the replicated scatter path, and mixed-tenant
    wire batches shard over "data".  Dispatch reuses the SAME jitted
    arena classify factories as the single chip — the pool placement
    engages GSPMD, so there is no mesh-specific kernel to keep in
    parity."""

    supports_overlay = False  # per-tenant overlays: single-chip only v1
    data_shards = 1

    def __init__(self, spec, mesh=None, data_shards=None,
                 rules_shards: int = 1, interpret: bool = True) -> None:
        from ..kernels import jaxpath as _jp

        if mesh is None:
            n = (data_shards or 2) * rules_shards
            mesh = meshmod.make_mesh(n, rules_shards=rules_shards)
        self._mesh = mesh
        self.data_shards = mesh.shape["data"]
        self._interpret = interpret
        self._alloc = _jp.ArenaAllocator(
            spec,
            device=meshmod.arena_replicated(mesh),
            shardings=meshmod.arena_shardings(
                mesh, spec.family, spec.pages,
                spliced=getattr(spec, "spliced", False),
            ),
        )
        self._stats = StatsAccumulator()
        self._closed = False

    @property
    def allocator(self):
        return self._alloc

    @property
    def spec(self):
        return self._alloc.spec

    def load_tenant(self, tenant: int, tables: CompiledTables,
                    hint=None) -> str:
        return self._alloc.load_tenant(tenant, tables, hint=hint)

    def stage_tenant(self, tables: CompiledTables) -> int:
        """Content-addressed staging (hash hit = an already-resident
        shared page, no bake); lifecycle scatters broadcast replicated
        exactly like the single-chip path."""
        return self._alloc.stage(tables)

    def activate_tenant(self, tenant: int, page: int,
                        tables=None) -> None:
        self._alloc.activate(tenant, page, tables)

    def swap_tenant(self, tenant: int, tables: CompiledTables) -> None:
        self._alloc.swap_tenant(tenant, tables)

    def destroy_tenant(self, tenant: int) -> None:
        self._alloc.destroy_tenant(tenant)

    def compact(self) -> int:
        return self._alloc.compact()

    def dedup_sweep(self, limit=None) -> dict:
        """Background content re-merge: page-table row flips broadcast
        through the replicated scatter path — shared pages stay placed
        by the SAME whole-slab partition rules as private ones (a
        refcount is host bookkeeping; GSPMD never sees it)."""
        return self._alloc.dedup_sweep(limit)

    def tenant_counters(self) -> dict:
        return self._alloc.counter_values()

    def classify_async_packed_tenant(
        self, wire_np: np.ndarray, tenant_np: np.ndarray,
        apply_stats: bool = True,
    ) -> PendingClassify:
        """Mixed-tenant mesh dispatch: wire + tenant column sharded
        over "data" (padded to a whole number of shard rows with
        dead lanes), pools as placed — one fused output buffer."""
        if self._closed:
            raise RuntimeError("classifier is closed")
        spec = self._alloc.spec
        n = wire_np.shape[0]
        kind = (wire_np[:, 0] & 3).astype(np.int32)
        data = self.data_shards
        # pad to 2*data rows so the u16 result-pair packing never
        # straddles shards (the MeshTpuClassifier contract)
        pad = (-n) % (2 * data)
        if pad:
            wire_np = np.concatenate(
                [wire_np,
                 np.full((pad, wire_np.shape[1]), KIND_OTHER, np.uint32)],
                axis=0,
            )
            tenant_np = np.concatenate(
                [tenant_np, np.full(pad, -1, tenant_np.dtype)]
            )
        ds = meshmod.arena_data_sharding(self._mesh)
        wire = jax.device_put(wire_np, ds)
        tenant = jax.device_put(
            np.ascontiguousarray(tenant_np, np.int32),
            NamedSharding(self._mesh, P("data")),
        )
        d_max = spec.d_max if spec.family == "ctrie" else 0
        sp = {"spec": spec} if getattr(spec, "spliced", False) else {}
        fused = jaxpath.jitted_classify_arena_wire_fused(
            spec.family, spec.pages, d_max, **sp
        )(self._alloc.arena, wire, tenant)
        try:
            fused.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def materialize() -> ClassifyOutput:
            res16, stats = jaxpath.split_wire_outputs(
                np.asarray(fused), n + pad
            )
            res16 = res16[:n]
            stats_delta = jaxpath.merge_stats_host(stats)
            if apply_stats:
                self._stats.add(stats_delta)
            results, xdp = jaxpath.host_finalize_wire(res16, kind)
            return ClassifyOutput(
                results=results, xdp=xdp, stats_delta=stats_delta
            )

        return PendingClassify(materialize)

    def classify_tenants(self, batch: PacketBatch, tenant_np: np.ndarray,
                         apply_stats: bool = True) -> ClassifyOutput:
        return self.classify_async_packed_tenant(
            batch.pack_wire(), tenant_np, apply_stats=apply_stats
        ).result()

    @property
    def stats(self) -> StatsAccumulator:
        return self._stats

    def close(self) -> None:
        self._closed = True


class DeviceStripe:
    """Per-device pipeline striping (ISSUE-16): ``width`` single-chip
    ``TpuClassifier`` instances, each PINNED to one device of the pool,
    each running its own donated resident pipeline — and optionally each
    fed by its own shared-memory ingest ring.  Where
    ``MeshTpuClassifier`` shards ONE dispatch over the ("data","rules")
    mesh (scale a single admission), a stripe scales ADMISSION
    THROUGHPUT: the scheduler round-robins whole admissions across the
    stripe (``ContinuousScheduler(stripe=...)``), so k chips run k
    independent overlapped epoch chains — per-device flow state, no
    cross-chip synchronization on the serving path.

    The two compose with the deployment: stripe across chips when flows
    hash-partition cleanly at the NIC edge (per-device flow tables are
    disjoint by construction), mesh-shard when one admission must span
    the pool.
    """

    def __init__(self, devices=None, width: Optional[int] = None,
                 ring_dir: Optional[str] = None,
                 ring_slots: int = 16, ring_slot_packets: int = 4096,
                 **clf_kw) -> None:
        devices = list(jax.devices() if devices is None else devices)
        if width is not None:
            if width > len(devices):
                raise ValueError(
                    f"stripe width {width} exceeds the {len(devices)}-"
                    "device pool"
                )
            devices = devices[:width]
        if not devices:
            raise ValueError("empty device stripe")
        self.classifiers = [
            TpuClassifier(device=d, **clf_kw) for d in devices
        ]
        #: per-device ingest rings (ring_dir/stripe<i>.ring) — one SPSC
        #: ring per chip, so producers hash-partition flows at the edge
        #: and each chip's pipeline drains its own ring cursor
        self.rings = []
        if ring_dir is not None:
            import os as _os

            from ..ring import IngestRing

            for i in range(len(self.classifiers)):
                self.rings.append(IngestRing.create(
                    _os.path.join(ring_dir, f"stripe{i}.ring"),
                    slots=ring_slots, slot_packets=ring_slot_packets,
                ))
        self._inflight = [[] for _ in self.classifiers]
        self._rr = 0

    @property
    def width(self) -> int:
        return len(self.classifiers)

    def next_classifier(self):
        """Round-robin admission target (the scheduler's stripe hook)."""
        clf = self.classifiers[self._rr % len(self.classifiers)]
        self._rr += 1
        return clf

    def load_tables(self, tables, **kw) -> None:
        for clf in self.classifiers:
            clf.load_tables(tables, **kw)

    def mark_resident_warm(self) -> None:
        for clf in self.classifiers:
            if getattr(clf, "resident", None) is not None:
                clf.mark_resident_warm()

    def drain_rings_once(self, budget_per_device: int = 1 << 30) -> int:
        """Pop committed records from every device's ring and dispatch
        each on its OWN classifier, holding up to PIPELINE_SLOTS
        admissions in flight per device before materializing (the same
        overlap discipline as the daemon's single-ring ingest); slots
        release in pop order.  Returns packets processed."""
        from ..resident import ResidentPool

        processed = 0
        for i, (clf, ring) in enumerate(zip(self.classifiers, self.rings)):
            infl = self._inflight[i]
            done = 0
            while done < budget_per_device:
                chunk = ring.pop(timeout=0.0)
                if chunk is None:
                    break
                plan = clf.prepare_packed(
                    chunk.wire, chunk.v4_only, tcp_flags=chunk.tcp_flags,
                )
                pending = clf.classify_prepared(plan, apply_stats=True)
                infl.append((chunk, pending))
                done += chunk.wire.shape[0]
                while len(infl) > ResidentPool.PIPELINE_SLOTS:
                    c, p = infl.pop(0)
                    p.result()
                    c.release()
            processed += done
        for infl in self._inflight:
            while infl:
                c, p = infl.pop(0)
                p.result()
                c.release()
        return processed

    def counter_values(self) -> dict:
        """Aggregated stripe gauges: per-device resident/ring counters
        summed, plus the stripe width."""
        out: dict = {"stripe_width": len(self.classifiers)}
        for clf in self.classifiers:
            for k, v in clf.resident_counters().items():
                out[k] = out.get(k, 0) + v
        for ring in self.rings:
            for k, v in ring.counter_values().items():
                out[k] = out.get(k, 0) + v
        return out

    def close(self) -> None:
        for ring in self.rings:
            ring.close()
        for clf in self.classifiers:
            clf.close()
