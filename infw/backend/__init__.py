"""Classifier backends.

- tpu: JAX/Pallas single-chip device classifier (dense MXU kernel or XLA
  trie path).
- mesh: multi-chip serving classifier — the same contract as tpu on a
  ("data", "rules") device mesh (data-sharded wire, optional
  rules-sharded tables, one device-side stats psum).  Selected by the
  daemon's --mesh / INFW_MESH knob; falls back to tpu when the device
  pool is too small.
- cpu_ref: native C++ reference classifier (ctypes), the differential
  oracle and CPU fallback — the parity component for the reference's one
  native-code piece (the XDP C program).

The heavy backends import jax at module load, so they are NOT imported
here eagerly; use :func:`classifier_class` (or import the module
directly) to resolve one by name.
"""
from .base import Classifier, ClassifyOutput  # noqa: F401


def classifier_class(name: str):
    """Resolve a backend name to its classifier class: "tpu", "mesh",
    or "cpu"."""
    if name == "tpu":
        from .tpu import TpuClassifier

        return TpuClassifier
    if name == "mesh":
        from .mesh import MeshTpuClassifier

        return MeshTpuClassifier
    if name == "cpu":
        from .cpu_ref import CpuRefClassifier

        return CpuRefClassifier
    raise ValueError(f"unknown backend {name!r} (expected tpu|mesh|cpu)")
