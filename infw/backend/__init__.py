"""Classifier backends.

- tpu: JAX/Pallas device classifier (dense MXU kernel or XLA trie path).
- cpu_ref: native C++ reference classifier (ctypes), the differential
  oracle and CPU fallback — the parity component for the reference's one
  native-code piece (the XDP C program).
"""
from .base import Classifier, ClassifyOutput  # noqa: F401
