"""Daemon-side NodeState controller.

Equivalent of the reference's
/root/reference/controllers/ingressnodefirewallnodestate_controller.go:
filters reconcile requests to this node's own name + namespace (:62-64),
maintains the finalizer so in-flight deletions detach the dataplane before
the object disappears (:77-99), and delegates the actual work to the
one-method syncer boundary (:112-123).  The module-level ``mock`` variable
is the same test-injection seam the reference uses (:112-113).
"""
from __future__ import annotations

import logging
from typing import Optional

from .spec import IngressNodeFirewallNodeState
from .store import InMemoryStore, NotFoundError
from .syncer import Syncer, SyncError

log = logging.getLogger("infw.nodestate")

# ingressNodeFirewallFinalizer (ingressnodefirewallnodestate_controller.go:42)
INGRESS_NODE_FIREWALL_FINALIZER = "ingressnodefirewall.tpu/finalizer"

# mock shall be None for production but can be overwritten for mock tests
# (ingressnodefirewallnodestate_controller.go:112-113).
mock: Optional[Syncer] = None


class NodeStateReconciler:
    def __init__(
        self,
        store: InMemoryStore,
        syncer: Syncer,
        node_name: str,
        namespace: str = "ingress-node-firewall-system",
    ) -> None:
        self.store = store
        self.syncer = syncer
        self.node_name = node_name
        self.namespace = namespace

    def reconcile(self, name: str, namespace: str) -> None:
        """Reconcile (:58-104)."""
        if name != self.node_name or namespace != self.namespace:
            return
        try:
            node_state = self.store.get(
                IngressNodeFirewallNodeState.KIND, name, namespace
            )
        except NotFoundError:
            return  # deletion already handled (:68-75)

        if node_state.metadata.deletion_timestamp is not None:
            if INGRESS_NODE_FIREWALL_FINALIZER in node_state.metadata.finalizers:
                self.reconcile_resource(node_state, is_delete=True)
                finalizers = [
                    f
                    for f in node_state.metadata.finalizers
                    if f != INGRESS_NODE_FIREWALL_FINALIZER
                ]
                self.store.update_finalizers(node_state, finalizers)
            return

        if INGRESS_NODE_FIREWALL_FINALIZER not in node_state.metadata.finalizers:
            self.store.update_finalizers(
                node_state,
                node_state.metadata.finalizers + [INGRESS_NODE_FIREWALL_FINALIZER],
            )

        log.info(
            "Reconciling resource and programming dataplane name=%s namespace=%s",
            name, namespace,
        )
        self.reconcile_resource(node_state, is_delete=False)

    def reconcile_resource(
        self, node_state: IngressNodeFirewallNodeState, is_delete: bool
    ) -> None:
        """reconcileResource (:115-123)."""
        syncer = mock if mock is not None else self.syncer
        try:
            syncer.sync_interface_ingress_rules(
                node_state.spec.interface_ingress_rules, is_delete
            )
        except SyncError as e:
            raise SyncError(f"FailedToSyncIngressNodeFirewallResources: {e}") from e
