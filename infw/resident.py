"""Zero-copy resident serving pool (ISSUE-12).

The host floor the SLO tier measured — Python -> pack -> encode -> H2D ->
launch(probe) -> launch(classify) -> launch(insert) -> D2H with fresh
buffer construction at every hop — collapses to: write the wire into a
preallocated slot, start ONE async H2D, launch ONE fused device program
(kernels.jaxpath.jitted_resident_step: decode + flow probe + stateless
classify + merge + stats + miss insert), read ONE fused buffer back.
The mutable flow columns and the epoch scalar are donated, so XLA
rewrites them in place across dispatches (input-output aliasing, checked
by the jaxcheck donation lint) and the steady-state loop performs zero
pool allocations — the residual host work is pointer bumps on
preallocated memory (the Gallium offload split, PAPERS.md), the device
work is one program per admission (the hXDP move, applied to the
serving loop).

``ResidentPool`` owns the per-table-generation program context (the
classify operands the fused step closes over) and the allocation
counters the bench gate asserts:

- ``allocs``: fresh persistent device buffers the resident path created
  (per-generation table snapshots, per-rung zero columns, epoch
  re-seeds).  Flat across a warmed steady-state run — the
  "zero device allocations" gate of bench_resident.
- ``dispatches`` / ``reuses``: fused launches and context cache hits.
- ``fallbacks``: admissions that declined the resident path (wide
  ruleIds, unsupported width) and fell back to the multi-dispatch plan.

The table-generation check is THE staleness guard: every
``load_tables`` bumps the classifier's generation token, and the pool
rebuilds its captured classify operands when the token moves.
``_INJECT_RESIDENT_STALE_BUG`` (tools/infw_lint.py state
--inject-defect residentstale) drops exactly that check — the donated
serving loop keeps classifying against the pre-patch tables — and the
statecheck ``resident`` config must catch it by oracle divergence with
a shrunk reproducer.
"""
from __future__ import annotations

import os
import threading
from typing import NamedTuple, Optional

import numpy as np

#: TEST-ONLY defect injection: when truthy (module flag or the
#: INFW_INJECT_RESIDENT_STALE_BUG env var), the pool's table-generation
#: staleness check is dropped — after a rule patch the resident fused
#: program keeps serving from the stale captured table operands.  Never
#: set in production.
_INJECT_RESIDENT_STALE_BUG = False


def _inject_resident_stale_bug() -> bool:
    if _INJECT_RESIDENT_STALE_BUG:
        return True
    env = os.environ.get("INFW_INJECT_RESIDENT_STALE_BUG", "")
    return env not in ("", "0", "false", "no")


class ResidentContext(NamedTuple):
    """The fused step's per-table-generation classify operands."""

    gen: int
    path: str           # "dense" | "trie" | "ctrie"
    tdev: object        # DeviceTables | CTrieTables
    ov_dev: object      # DeviceTables | None
    d_max: int          # ctrie static unroll bound (0 otherwise)


class ResidentPool:
    """Donated-buffer pool + program-context cache for one classifier.

    Thread-safety: context() may race load_tables — the generation token
    is read under the CLASSIFIER's lock together with the active tables,
    so a context can never pair a token with another generation's
    operands; the pool's own lock guards only its cache and counters.
    """

    #: two in-flight admissions (the ISSUE-16 pipeline): slot N+1's
    #: pack/encode/H2D and slot N-1's fused readback overlap slot N's
    #: compute — the bound the daemon's ring ingest and the scheduler's
    #: stage_depth both honor on the resident path
    PIPELINE_SLOTS = 2

    def __init__(self, device=None) -> None:
        self._lock = threading.Lock()
        self._ctx: Optional[ResidentContext] = None
        self._device = device
        self.counters = {
            "allocs": 0, "reuses": 0, "dispatches": 0, "fallbacks": 0,
            # superbatch (device-side epoch loop, ISSUE-16): one
            # dispatch chews k stacked admissions entirely on-device
            "superbatch_dispatches": 0, "superbatch_admissions": 0,
            # per-pipeline-slot dispatch parity (observability: a stuck
            # slot shows as one counter flatlining)
            "slot0_dispatches": 0, "slot1_dispatches": 0,
        }
        #: allocation count at warm-completion (mark_warm): the serving-
        #: path gate is allocs - warm_allocs == 0
        self.warm_allocs: Optional[int] = None

    # -- counters ------------------------------------------------------------

    def note(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def note_alloc(self, _what: str = "") -> None:
        self.note("allocs")

    def mark_warm(self) -> None:
        """Freeze the prewarm allocation baseline: every pool
        allocation after this point happened on the serving path (the
        bench_resident zero-alloc gate reads steady_allocs())."""
        with self._lock:
            self.warm_allocs = self.counters["allocs"]

    def steady_allocs(self) -> int:
        with self._lock:
            if self.warm_allocs is None:
                return self.counters["allocs"]
            return self.counters["allocs"] - self.warm_allocs

    def counter_values(self) -> dict:
        """resident_* gauges for /metrics."""
        with self._lock:
            out = {f"resident_{k}_total": v for k, v in self.counters.items()}
            out["resident_pool_warm"] = int(self.warm_allocs is not None)
            out["resident_steady_allocs"] = (
                self.counters["allocs"] - self.warm_allocs
                if self.warm_allocs is not None else 0
            )
        return out

    # -- program context -----------------------------------------------------

    def context(self, clf) -> Optional[ResidentContext]:
        """The classify operands of the CURRENT table generation, or
        None when the resident path cannot serve this generation (no
        tables, wide ruleIds) — the caller falls back to the
        multi-dispatch plan.

        Cache discipline: one context per generation token; a stale hit
        is impossible because the token is assigned inside the
        classifier's install lock (load_tables) and read here together
        with the active tuple.  The injected residentstale defect
        returns the cached context WITHOUT the token check — the stale
        donated serving loop the statecheck acceptance must catch."""
        from .kernels import jaxpath

        with self._lock:
            ctx = self._ctx
        if ctx is not None and _inject_resident_stale_bug():
            self.note("reuses")
            return ctx
        with clf._lock:
            active = clf._active
            tables = clf._tables
            gen = clf._depth_gen
        if active is None:
            return None
        path, dev, _block_b, wide_rids, ov_dev, _walk = active
        if wide_rids:
            return None
        if ctx is not None and ctx.gen == gen:
            self.note("reuses")
            return ctx
        if path == "ctrie":
            if not (isinstance(dev, tuple) and len(dev) == 2):
                return None
            tdev, d_max = dev[0], dev[1]
        elif path == "trie":
            if not isinstance(dev, jaxpath.DeviceTables):
                # mesh rules-sharded partitions re-place per load and
                # are not the resident program's operand shape — the
                # multi-dispatch plan keeps serving them
                return None
            tdev, d_max = dev, 0
        else:
            # dense path: the resident program is pure XLA (the Pallas
            # dense kernel cannot compose into the fused step), so keep
            # a DeviceTables twin of the small dense table — built once
            # per generation, bit-identical verdicts either way
            try:
                jaxpath.check_wire_ruleids(tables)
            except ValueError:
                return None
            tdev = jaxpath.device_tables(tables, clf._device, pad=True)
            d_max = 0
            self.note_alloc("dense-twin")
        ctx = ResidentContext(
            gen=gen, path=path, tdev=tdev, ov_dev=ov_dev, d_max=d_max,
        )
        with self._lock:
            self._ctx = ctx
        self.note_alloc("context")
        return ctx

    def stage_wire(self, clf, wire_np: np.ndarray):
        """Start the async H2D of one wire chunk (the per-admission
        staging copy: on the CPU backend this aliases aligned host
        memory — e.g. a pinned ring slot — and on device backends it
        rides XLA's stream arena, not the pool)."""
        import jax

        return jax.device_put(
            np.ascontiguousarray(wire_np, np.uint32), clf._device
        )
