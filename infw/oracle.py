"""Scalar NumPy oracle classifier.

A direct, per-packet transliteration of the XDP program's semantics
(/root/reference/bpf/ingress_node_firewall_kernel.c:189-457) operating on
the compiled table *content* (the LPM key -> rule-rows map), independent of
the dense/trie tensor encodings.  Used as the differential-testing ground
truth for every accelerated backend.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from .compiler import CompiledTables
from .constants import (
    ALLOW,
    DENY,
    IPPROTO_ICMP,
    IPPROTO_ICMPV6,
    IPPROTO_SCTP,
    IPPROTO_TCP,
    IPPROTO_UDP,
    KIND_IPV4,
    KIND_MALFORMED,
    KIND_OTHER,
    MAX_TARGETS,
    UNDEF,
    V4_KEY_PREFIX_LEN,
    V6_KEY_PREFIX_LEN,
    XDP_DROP,
    XDP_PASS,
    set_actionrule_response,
)
from .packets import PacketBatch

_TRANSPORT = (IPPROTO_TCP, IPPROTO_UDP, IPPROTO_SCTP)


@dataclass
class ClassifyResult:
    """Per-batch outputs: the packed u32 results (action | ruleId<<8), the
    final XDP verdicts, and aggregated statistics keyed by ruleId with
    [allow_packets, allow_bytes, deny_packets, deny_bytes] values —
    mirroring ruleStatistics_st (bpf/ingress_node_firewall.h:45-54)."""

    results: np.ndarray  # (B,) uint32
    xdp: np.ndarray      # (B,) int32
    stats: Dict[int, List[int]] = field(default_factory=dict)


def _lpm_lookup(
    entries: List[Tuple[int, int, int, int]],  # (ifindex, mask_len, masked_ip_int, target)
    ifindex: int,
    ip_int: int,
    cap_prefix_len: int,
) -> int:
    """Longest-prefix match over the (ifindex || ip) key space.  Entries
    with prefixLen (mask_len + 32) greater than the packet key's prefix
    length cannot match (BPF LPM trie lookup semantics with the packet key
    built at kernel.c:206-212 / 292-295)."""
    best_target = -1
    best_len = -1
    for e_ifindex, e_mask_len, e_masked_ip, target in entries:
        if e_ifindex != ifindex:
            continue
        if e_mask_len + 32 > cap_prefix_len:
            continue
        if e_mask_len > 0 and (ip_int >> (128 - e_mask_len)) != (
            e_masked_ip >> (128 - e_mask_len)
        ):
            continue
        # Strictly greater: equal-length duplicates cannot both exist after
        # masked-identity dedup.
        if e_mask_len > best_len:
            best_len = e_mask_len
            best_target = target
    return best_target


def _scan_rules(
    rows: np.ndarray, proto: int, dport: int, icmp_type: int, icmp_code: int, is_v4: bool
) -> int:
    """The ordered rule scan (kernel.c:222-258 / 305-340)."""
    icmp_proto = IPPROTO_ICMP if is_v4 else IPPROTO_ICMPV6
    for i in range(rows.shape[0]):
        rid, rproto, ps, pe, it, ic, act = (int(x) for x in rows[i])
        if rid == 0:  # INVALID_RULE_ID -> empty slot
            continue
        if rproto != 0 and rproto == proto:
            if rproto in _TRANSPORT:
                if pe == 0:
                    if ps == dport:
                        return set_actionrule_response(act, rid)
                else:
                    if ps <= dport < pe:
                        return set_actionrule_response(act, rid)
            if rproto == icmp_proto:
                if it == icmp_type and ic == icmp_code:
                    return set_actionrule_response(act, rid)
        if rproto == 0:
            # Protocol not set: catch-all (kernel.c:254-257).
            return set_actionrule_response(act, rid)
    return UNDEF  # SET_ACTION(UNDEF) == 0


def _dedup_entries(tables: CompiledTables):
    """Masked-identity dedup of the table content (the map layer collapses
    aliased keys; loader.go writes one map value per key).  Returns
    (entries, rules_by_target) where entries are
    (ifindex, mask_len, masked_ip_int, target)."""
    dedup: Dict[Tuple[int, int, bytes], int] = {}
    ordered: List[Tuple[Tuple[int, int, int, int], np.ndarray]] = []
    for key, rows in tables.content.items():
        ident = key.masked_identity()
        e = (
            key.ingress_ifindex,
            key.mask_len,
            int.from_bytes(ident[2], "big"),
        )
        if ident in dedup:
            ordered[dedup[ident]] = ((*e, dedup[ident]), rows)
        else:
            dedup[ident] = len(ordered)
            ordered.append(((*e, len(ordered)), rows))
    entries = [e for e, _ in ordered]
    rules_by_target = [rows for _, rows in ordered]
    return entries, rules_by_target


def classify(tables: CompiledTables, batch: PacketBatch) -> ClassifyResult:
    """Reference classification of a whole batch, including the ethertype
    dispatch, stats accumulation and final XDP verdict of
    ingress_node_firewall_main (kernel.c:412-457)."""
    entries, rules_by_target = _dedup_entries(tables)

    def lookup(ifindex: int, ip_int: int, cap: int) -> int:
        return _lpm_lookup(entries, ifindex, ip_int, cap)

    return _classify_with_lookup(lookup, rules_by_target, batch)


def _classify_with_lookup(
    lookup, rules_by_target: List[np.ndarray], batch: PacketBatch
) -> ClassifyResult:
    b = len(batch)
    results = np.zeros(b, np.uint32)
    xdp = np.zeros(b, np.int32)
    stats: Dict[int, List[int]] = {}

    for i in range(b):
        kind = int(batch.kind[i])
        if kind == KIND_MALFORMED:
            xdp[i] = XDP_DROP  # kernel.c:423-426
            continue
        if kind == KIND_OTHER:
            xdp[i] = XDP_PASS  # kernel.c:436-438
            continue
        is_v4 = kind == KIND_IPV4
        if not int(batch.l4_ok[i]):
            result = UNDEF  # extract failure -> SET_ACTION(UNDEF), kernel.c:199-202
        else:
            ip_int = 0
            for w in range(4):
                ip_int = (ip_int << 32) | int(batch.ip_words[i, w])
            cap = V4_KEY_PREFIX_LEN if is_v4 else V6_KEY_PREFIX_LEN
            target = lookup(int(batch.ifindex[i]), ip_int, cap)
            if target < 0:
                result = UNDEF
            else:
                result = _scan_rules(
                    rules_by_target[target],
                    int(batch.proto[i]),
                    int(batch.dst_port[i]),
                    int(batch.icmp_type[i]),
                    int(batch.icmp_code[i]),
                    is_v4,
                )
        results[i] = result
        action = result & 0xFF
        rule_id = (result >> 8) & 0xFFFFFF
        if action == DENY:
            xdp[i] = XDP_DROP
            _bump(stats, rule_id, deny=True, length=int(batch.pkt_len[i]))
        elif action == ALLOW:
            xdp[i] = XDP_PASS
            _bump(stats, rule_id, deny=False, length=int(batch.pkt_len[i]))
        else:
            xdp[i] = XDP_PASS  # UNDEF -> default pass, no stats (kernel.c:453-455)
    return ClassifyResult(results=results, xdp=xdp, stats=stats)


class HashLpmOracle:
    """LPM-by-hash oracle for large-table spot checks.

    The scalar ``classify`` walks every entry per packet (O(entries) — the
    direct transliteration of the BPF trie's longest-match semantics), so
    differential checks at the 100K-1M-entry tiers could only afford a
    few thousand packets.  This variant buckets the deduped entries by
    mask length into hash maps keyed by (ifindex, masked-ip); lookup
    probes mask lengths longest-first — O(distinct mask lens) per packet.
    It shares the entry preprocessing, rule scan and per-packet dispatch
    with the scalar oracle, but its lookup structure is independent of
    both the scalar linear scan AND the tensor trie/dense encodings, so
    it remains a meaningful differential ground truth (cross-validated
    against the scalar oracle in tests and in bench spot checks)."""

    def __init__(self, tables: CompiledTables) -> None:
        entries, self._rules_by_target = _dedup_entries(tables)
        buckets: Dict[int, Dict[Tuple[int, int], int]] = {}
        for ifindex, mask_len, masked_ip, target in entries:
            b = buckets.setdefault(mask_len, {})
            b[(ifindex, masked_ip >> (128 - mask_len) if mask_len else 0)] = target
        # longest-first probe order (strictly-greater tie-break of
        # _lpm_lookup: equal lengths cannot coexist after dedup)
        self._probe = sorted(buckets.items(), key=lambda kv: -kv[0])

    def _lookup(self, ifindex: int, ip_int: int, cap: int) -> int:
        for mask_len, bucket in self._probe:
            if mask_len + 32 > cap:
                continue  # entry longer than the packet-side key cap
            t = bucket.get(
                (ifindex, ip_int >> (128 - mask_len) if mask_len else 0)
            )
            if t is not None:
                return t
        return -1

    def classify(self, batch: PacketBatch) -> ClassifyResult:
        return _classify_with_lookup(self._lookup, self._rules_by_target, batch)


def _bump(stats: Dict[int, List[int]], rule_id: int, deny: bool, length: int) -> None:
    # The stats map has MAX_TARGETS entries; lookups for larger ruleIds fail
    # and record nothing (kernel.c:376-390).
    if rule_id >= MAX_TARGETS:
        return
    entry = stats.setdefault(rule_id, [0, 0, 0, 0])
    if deny:
        entry[2] += 1
        entry[3] += length
    else:
        entry[0] += 1
        entry[1] += length
