#!/usr/bin/env python
"""Headline benchmark: packet classifications/sec/chip at 100K rule entries.

Config 2/3 of BASELINE.json: 1000 sourceCIDR targets x 100 ordered rules
(= 100K rule entries, the reference's full MAX_TARGETS x MAX_RULES_PER_TARGET
capacity, bpf/ingress_node_firewall.h:13-14), mixed IPv4/IPv6 + TCP/UDP/ICMP,
classified by the fused int8-MXU Pallas kernel on one chip.  Verdicts are
spot-checked against the scalar oracle before timing.

Timing methodology (the device is reached through a tunnel whose dispatch
layer memoizes repeated identical executions and whose block_until_ready is
unreliable): K classify iterations are CHAINED on-device inside one jitted
fori_loop — iteration i+1's ports depend on iteration i's verdicts, so no
caching or reordering is possible — and only a scalar checksum is read
back.  Throughput is the two-point slope (K=23 minus K=3) / 20, which
cancels the fixed RPC/dispatch overhead exactly.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is throughput / 10M (the BASELINE.json north-star target);
diagnostics go to stderr.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from infw import oracle, testing  # noqa: E402
from infw.kernels import jaxpath, pallas_dense  # noqa: E402

TARGET = 10_000_000.0  # classifications/sec (BASELINE.json north star)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def fail(reason):
    log(f"FATAL: {reason}")
    print(json.dumps({
        "metric": "packet classifications/sec/chip @100K rules",
        "value": 0.0, "unit": "packets/s", "vs_baseline": 0.0,
    }))
    return 1


def main():
    on_tpu = jax.default_backend() == "tpu"
    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.default_rng(2024)
    tables = testing.random_tables(
        rng, n_entries=1000, width=100, ifindexes=(2, 3, 4)
    )
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch(rng, tables, n_packets=n_packets)

    pt = jax.tree.map(jax.device_put, pallas_dense.build_pallas_tables(tables))
    db = jaxpath.device_batch(batch)
    interpret = not on_tpu
    block_b = pallas_dense.choose_block_b(pt.mdt.shape[1])
    fn = pallas_dense.jitted_classify_pallas(interpret, block_b)

    t0 = time.perf_counter()
    out = fn(pt, db)
    np.asarray(out[0])
    log(f"compile+first run: {time.perf_counter()-t0:.2f}s "
        f"(dtype={pt.mdt.dtype}, block_b={block_b})")

    # Correctness gate: subsample vs the scalar oracle (real readback).
    sub = batch.slice(0, 2000)
    ref = oracle.classify(tables, sub)
    got = np.asarray(fn(pt, jaxpath.device_batch(sub))[0])
    if not (got == ref.results).all():
        return fail("verdict mismatch vs oracle")
    log("verdict spot-check vs oracle: OK (2000 packets)")

    # Chained-loop throughput (see module docstring).
    def step(i, carry):
        dport, acc = carry
        b = db._replace(dst_port=dport)
        res, xdp, stats = pallas_dense.classify_pallas(
            pt, b, interpret=interpret, block_b=block_b
        )
        dport = (dport + (res & 1).astype(jnp.int32)) % 65536
        return dport, acc + jnp.sum(res.astype(jnp.uint32))

    @jax.jit
    def loop(k):
        return jax.lax.fori_loop(0, k, step, (db.dst_port, jnp.uint32(0)))[1]

    k1, k2 = (3, 23) if on_tpu else (1, 3)
    t0 = time.perf_counter()
    int(loop(1))  # compile the loop
    log(f"loop compile: {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter(); int(loop(k1)); t1 = time.perf_counter()
    t2 = time.perf_counter(); int(loop(k2)); t3 = time.perf_counter()
    dt = ((t3 - t2) - (t1 - t0)) / (k2 - k1)
    if dt <= 0:
        return fail(f"non-monotonic timing: k={k1}:{t1-t0:.3f}s k={k2}:{t3-t2:.3f}s")
    throughput = n_packets / dt
    log(f"throughput: {throughput/1e6:.2f} M classifications/s "
        f"({dt*1e3:.2f} ms / {n_packets} packets, slope of k={k1}->k={k2})")

    # p50 verdict latency: full round-trip of a small batch (dispatch ->
    # verdict bytes on host) — includes the host<->device link, the honest
    # analogue of the per-packet verdict path.  Fresh input each iteration
    # so the tunnel cannot memoize.
    lats = []
    for i in range(10 if on_tpu else 3):
        small = batch.slice(0, 4096)
        small.dst_port = ((small.dst_port.astype(np.int64) + i) % 65536).astype(np.int32)
        sdb = jaxpath.device_batch(small)
        t0 = time.perf_counter()
        r = fn(pt, sdb)
        np.asarray(r[0])
        lats.append(time.perf_counter() - t0)
    p50 = sorted(lats)[len(lats) // 2]
    log(f"p50 verdict latency (4096-packet round-trip incl. link): {p50*1e3:.3f} ms")

    print(json.dumps({
        "metric": "packet classifications/sec/chip @100K rules (1000 CIDRs x 100 rules, Pallas int8 dense)",
        "value": round(throughput, 1),
        "unit": "packets/s",
        "vs_baseline": round(throughput / TARGET, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
