#!/usr/bin/env python
"""Headline benchmark: packet classifications/sec/chip at 100K rule entries.

Config 2/3 of BASELINE.json: 1000 sourceCIDR targets x 100 ordered rules
(= 100K rule entries, the reference's full MAX_TARGETS x MAX_RULES_PER_TARGET
capacity, bpf/ingress_node_firewall.h:13-14), mixed IPv4/IPv6 + TCP/UDP/ICMP,
classified by the fused Pallas kernel on one chip.  Verdicts are
spot-checked against the scalar oracle before timing.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is throughput / 10M (the BASELINE.json north-star target);
diagnostics go to stderr.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from infw import oracle, testing  # noqa: E402
from infw.kernels import jaxpath, pallas_dense  # noqa: E402

TARGET = 10_000_000.0  # classifications/sec (BASELINE.json north star)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    on_tpu = jax.default_backend() == "tpu"
    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.default_rng(2024)
    tables = testing.random_tables(
        rng, n_entries=1000, width=100, stride=4, ifindexes=(2, 3, 4)
    )
    n_packets = 2**20 if on_tpu else 2**14
    batch = testing.random_batch(rng, tables, n_packets=n_packets)

    pt = jax.tree.map(jax.device_put, pallas_dense.build_pallas_tables(tables))
    db = jaxpath.device_batch(batch)
    fn = pallas_dense.jitted_classify_pallas(not on_tpu)

    t0 = time.perf_counter()
    out = fn(pt, db)
    out[0].block_until_ready()
    log(f"compile+first run: {time.perf_counter()-t0:.2f}s")

    # Correctness gate: subsample vs the scalar oracle.
    sub = batch.slice(0, 2000)
    ref = oracle.classify(tables, sub)
    got = np.asarray(fn(pt, jaxpath.device_batch(sub))[0])
    if not (got == ref.results).all():
        log("FATAL: verdict mismatch vs oracle")
        print(json.dumps({
            "metric": "packet classifications/sec/chip @100K rules",
            "value": 0.0, "unit": "packets/s", "vs_baseline": 0.0,
        }))
        return 1
    log("verdict spot-check vs oracle: OK (2000 packets)")

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(pt, db)
    out[0].block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    throughput = n_packets / dt
    log(f"throughput: {throughput/1e6:.2f} M classifications/s "
        f"({dt*1e3:.2f} ms / {n_packets} packets)")

    # p50 verdict latency: round-trip of a small batch (dispatch -> verdicts
    # on host), the analogue of the per-packet verdict path.
    small = jaxpath.device_batch(batch.slice(0, 4096))
    lats = []
    for _ in range(30 if on_tpu else 5):
        t0 = time.perf_counter()
        r = fn(pt, small)
        np.asarray(r[0])
        lats.append(time.perf_counter() - t0)
    p50 = sorted(lats)[len(lats) // 2]
    log(f"p50 verdict latency (4096-packet batch round-trip): {p50*1e3:.3f} ms")

    print(json.dumps({
        "metric": "packet classifications/sec/chip @100K rules (1000 CIDRs x 100 rules, Pallas dense)",
        "value": round(throughput, 1),
        "unit": "packets/s",
        "vs_baseline": round(throughput / TARGET, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
